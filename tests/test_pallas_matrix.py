"""Pallas transfer-matrix kernels (ops/pallas_matrix.py).

CPU tier: every kernel variant (f32 / int8-MXU / bit-packed uint32) and
every L-build mode (in-kernel dots / VMEM pretile / HBM-streamed
pretile), plus the fused streaming combine, run in pallas interpret
mode and are differentially pinned against (a) an independent numpy
oracle of the factored math and (b) the XLA scan path through the
PRODUCTION matrix_check dispatch. Probe sidecar caching and the
demote-not-fail variant ladder are unit-tested with fake probes.
Real-chip verdict parity lives in tests/test_tpu_parity.py (-m tpu).
"""
from __future__ import annotations

import numpy as np
import pytest

VARIANTS = ("f32", "int8", "packed")
MODES = ("none", "vmem", "hbm")


def _oracle(S, V, pend, ids, mtT, slots, valid):
    """The shared numpy replay (also the enabled() probe's reference)."""
    from jepsen_tpu.ops.pallas_matrix import _oracle_product

    return _oracle_product(S, V, pend, ids, mtT, slots, valid)


def _inputs(S, V, T, U, G, seed=0):
    rng = np.random.default_rng(seed)
    return ((rng.random((T, G, S)) < 0.5).astype(np.float32),
            rng.integers(0, U, (T, G, S)).astype(np.int32),
            (rng.random((U, V, V)) < 0.3).astype(np.float32),
            rng.integers(0, S, (T, G)).astype(np.int32),
            (rng.random((T, G)) < 0.8).astype(np.float32))


def test_static_tables_express_kron_and_kill():
    """Rexp * tile(X) == R (kron) X^T, and Kexp @ B == the row
    gather+mask the XLA path performs — the two identities the
    factored kernel rests on."""
    from jepsen_tpu.ops.pallas_matrix import _static_tables

    S, V = 3, 4
    M = 1 << S
    MV = M * V
    Rexp, Kexp, U1, U2 = _static_tables(S, V)
    rng = np.random.default_rng(7)
    X = (rng.random((V, V)) < 0.4).astype(np.float32)
    rows = np.arange(MV)
    a, w = rows // V, rows % V
    for s in range(S):
        R = np.zeros((M, M), np.float32)
        src = np.arange(M)[((np.arange(M) >> s) & 1) == 0]
        R[src | (1 << s), src] = 1.0
        kron = R[a][:, a] * X.T[w][:, w]  # [(a,w),(b,v)] = R[a,b] X[v,w]
        got = Rexp[s] * (U1 @ X.T @ U2)
        assert np.array_equal(kron, got), s

    B = (rng.random((MV, MV)) < 0.3).astype(np.float32)
    for s in range(S):
        ok = ((a >> s) & 1) == 0
        kill_idx = np.where(ok, ((a | (1 << s)) * V + w), 0)
        ref = B[kill_idx] * ok[:, None]
        assert np.array_equal((Kexp[s] @ B > 0) * 1.0, (ref > 0) * 1.0), s


@pytest.mark.parametrize("variant", VARIANTS)
def test_kernel_matches_numpy_oracle_interpret(variant):
    """Every representation variant is bit-identical to the numpy
    oracle on a random run — the identity the auto-probe re-verifies
    per (S, V, variant) before a production dispatch."""
    from jepsen_tpu.ops.pallas_matrix import _build

    S, V, T, U, G = 3, 8, 5, 16, 4        # MV=64: packed word-aligned
    pend, ids, mtT, slots, valid = _inputs(S, V, T, U, G)
    ref = _oracle(S, V, pend, ids, mtT, slots, valid)
    fn = _build(S, V, T, U, interpret=True, variant=variant)
    got = np.asarray(fn(pend, ids, mtT, slots, valid)).astype(np.float32)
    assert np.array_equal(ref, got), variant


@pytest.mark.parametrize("variant", VARIANTS)
@pytest.mark.parametrize("mode", MODES)
def test_lbuild_modes_match_oracle_interpret(variant, mode):
    """All three L-build data paths — in-kernel tiling dots, the VMEM
    pre-tiled table, and the HBM-streamed double-buffered table — are
    bit-identical to the oracle for every variant (the hbm mode is what
    lets value domains past PALLAS_PRETILE_BYTES keep the fast
    L-build)."""
    from jepsen_tpu.ops.pallas_matrix import _build

    S, V, T, U, G = 3, 8, 5, 16, 4
    pend, ids, mtT, slots, valid = _inputs(S, V, T, U, G, seed=3)
    ref = _oracle(S, V, pend, ids, mtT, slots, valid)
    fn = _build(S, V, T, U, interpret=True, pretile=mode, variant=variant)
    got = np.asarray(fn(pend, ids, mtT, slots, valid)).astype(np.float32)
    assert np.array_equal(ref, got), (variant, mode)


def test_pretile_mode_selection(monkeypatch):
    """Mode thresholds: VMEM under the budget, HBM streaming past it,
    in-kernel dots past the HBM cap; integer variants' 1-byte tables
    extend the VMEM budget 4x over f32."""
    import jepsen_tpu.ops.pallas_matrix as pm

    S, V = 3, 8            # MV=64 -> one f32 tile = 16 KiB
    assert pm._pretile_mode(S, V, 16, "f32") == "vmem"
    monkeypatch.setattr(pm, "PALLAS_PRETILE_BYTES", 16 * 64 * 64)
    # f32 tables now blow the VMEM budget at U=16; the int8 table is
    # 4x smaller and still fits
    assert pm._pretile_mode(S, V, 16, "f32") == "hbm"
    assert pm._pretile_mode(S, V, 16, "int8") == "vmem"
    monkeypatch.setattr(pm, "PALLAS_PRETILE_HBM_BYTES", 16 * 64 * 64)
    assert pm._pretile_mode(S, V, 64, "f32") == "none"


def test_fused_combine_matches_tree_and_oracle():
    """The fused streaming combine == the sequential numpy chain == the
    jitlin tree combine, bit for bit (boolean products are exact under
    any association — the identity that makes the fusion safe)."""
    import jax.numpy as jnp
    from jepsen_tpu.ops.jitlin import _kernel_math
    from jepsen_tpu.ops.pallas_matrix import _build_combine, _combine_oracle

    B, C, MV = 2, 7, 32
    S, V = 2, 8            # MV = (1<<2)*8 = 32
    rng = np.random.default_rng(4)
    P = (rng.random((B, C, MV, MV)) < 0.15).astype(np.float32)
    tot0 = np.broadcast_to(np.eye(MV, dtype=np.float32),
                           (B, MV, MV)).copy()
    ref = _combine_oracle(P, tot0)
    fn = _build_combine(B, C, MV, interpret=True)
    got = np.asarray(fn(jnp.asarray(P, jnp.bfloat16),
                        jnp.asarray(tot0, jnp.bfloat16))
                     ).astype(np.float32)
    assert np.array_equal(got, ref)

    def step_ids(st, f, a, b):   # unused by the combine; shape only
        return st, jnp.ones_like(st, dtype=bool)

    math = _kernel_math(S, V, step_ids, B * C)
    tree = math.make_combine(B, C, init_state=0)
    alive, _, total = tree(
        jnp.asarray(P.reshape(B * C, MV, MV), jnp.bfloat16),
        jnp.zeros((B * C,), bool), jnp.asarray(tot0, jnp.bfloat16))
    assert np.array_equal(np.asarray(total, dtype=np.float32), ref)
    assert np.array_equal(np.asarray(alive),
                          (ref[:, :, 0] > 0).any(axis=1))


def test_production_dispatch_variant_parity(monkeypatch):
    """matrix_check through every pallas variant (interpret mode,
    forced) agrees with the XLA scan path on valid AND corrupted
    histories, and the fused combine rides the same dispatches — the
    same cross-checks the chip parity tier runs for real. Quick-lane
    shapes: 60-op small-domain histories."""
    from __graft_entry__ import _register_history  # conftest adds the root
    import jepsen_tpu.ops.pallas_matrix as pm
    from jepsen_tpu.checker.linear_encode import encode_register_ops
    from jepsen_tpu.ops.jitlin import last_dispatch_info, matrix_check

    def verdicts(h, variant):
        monkeypatch.setattr(pm, "FORCE_INTERPRET", False)
        scan = matrix_check(encode_register_ops(h), force=True,
                            combine_fused=False)
        assert last_dispatch_info()["variant"] == "scan"
        monkeypatch.setattr(pm, "FORCE_INTERPRET", True)
        try:
            pallas = matrix_check(encode_register_ops(h), force=True,
                                  variant=variant)
            info = last_dispatch_info()
        finally:
            monkeypatch.setattr(pm, "FORCE_INTERPRET", False)
        assert info["variant"] == variant, info
        assert info["combine"] == "fused", info
        return scan, pallas

    h_ok = _register_history(60, n_procs=3, seed=5, n_values=4)
    h_bad = _register_history(60, n_procs=3, seed=6, n_values=4)
    import random
    reads = [op for op in h_bad
             if op.get("f") == "read" and op.get("type") == "ok"]
    for op in random.Random(0).sample(reads, min(2, len(reads))):
        op["value"] = 999

    for variant in VARIANTS:
        scan, pallas = verdicts(h_ok, variant)
        assert scan is not None and pallas is not None
        assert pallas[0] == scan[0] is True, variant
        scan, pallas = verdicts(h_bad, variant)
        assert pallas[0] == scan[0] is False, variant


@pytest.mark.explain
@pytest.mark.parametrize("variant", ["packed", "int8", "f32"])
def test_variant_verdict_localizes_to_frontier(variant, monkeypatch):
    """ISSUE 12 (explain tier): an INVALID verdict from each pallas
    kernel variant (interpret mode) localizes to the same
    first-return/event as the exact CPU frontier — the representation
    changes how the boolean products are computed, never which return
    first kills the frontier. (Lives here rather than test_explain.py
    so its interpret-mode compiles don't land right before the
    timing-sensitive live-daemon tests in tier-1 file order.)"""
    from __graft_entry__ import _register_history
    import jepsen_tpu.ops.pallas_matrix as pm
    from jepsen_tpu.checker.linear_cpu import check_stream
    from jepsen_tpu.checker.linear_encode import encode_register_ops
    from jepsen_tpu.ops.jitlin import matrix_check, matrix_localize

    h = _register_history(160, n_procs=3, seed=6, n_values=4)
    import random
    reads = [op for op in h
             if op.get("f") == "read" and op.get("type") == "ok"]
    for op in random.Random(1).sample(reads, 2):
        op["value"] = 999
    s = encode_register_ops(h)
    cpu = check_stream(s)
    assert cpu.valid is False
    monkeypatch.setattr(pm, "FORCE_INTERPRET", True)
    try:
        m = matrix_check(s, force=True, variant=variant)
    finally:
        monkeypatch.setattr(pm, "FORCE_INTERPRET", False)
    assert m is not None and m[0] is False and not m[2], variant
    loc = matrix_localize(s)
    assert loc is not None
    assert loc.failed_event == cpu.failed_event, variant
    assert loc.failed_op_index == cpu.failed_op_index, variant


def test_checker_knobs_route_variant(monkeypatch):
    """The test-map knobs reach the ladder's matrix rung: a pinned
    matrix_variant/combine_fused routes the dispatch (visible in the
    re-published phase split's routing labels), and the verdict settles
    at the matrix rung as before."""
    from __graft_entry__ import _register_history
    import jepsen_tpu.ops.pallas_matrix as pm
    from jepsen_tpu.checker.linearizable import LinearizableChecker
    from jepsen_tpu.ops import jitlin

    monkeypatch.setattr(jitlin, "MATRIX_MIN_RETURNS", 10)
    monkeypatch.setattr(pm, "FORCE_INTERPRET", True)
    chk = LinearizableChecker(accelerator="tpu")
    out = chk.check({"matrix_variant": "int8", "combine_fused": True,
                     "checker_sharded": False},
                    _register_history(240, n_procs=3, seed=3, n_values=5),
                    {})
    assert out["valid?"] is True
    assert out["algorithm"] == "jitlin-tpu-matrix"
    split = jitlin.last_phase_seconds()
    assert split.get("variant") == "int8", split
    assert split.get("combine") == "fused", split


def test_variant_runtime_failure_demotes(monkeypatch):
    """A variant that blows up at dispatch time is disabled and the
    dispatch demotes to the next representation — same verdict, no
    error (PR-3 ladder semantics inside the rung)."""
    from __graft_entry__ import _register_history
    import jepsen_tpu.ops.pallas_matrix as pm
    from jepsen_tpu.checker.linear_encode import encode_register_ops
    from jepsen_tpu.ops.jitlin import last_dispatch_info, matrix_check

    monkeypatch.setattr(pm, "FORCE_INTERPRET", True)
    monkeypatch.setattr(pm, "_DISABLED", set())
    real_build = pm._build.__wrapped__

    def bomb(S, V, T, U, interpret=False, pretile="none", variant="f32"):
        if variant == "packed":
            raise RuntimeError("synthetic packed lowering failure")
        return real_build(S, V, T, U, interpret, pretile, variant)

    bomb.__wrapped__ = bomb
    import functools
    monkeypatch.setattr(pm, "_build", functools.lru_cache(maxsize=32)(bomb))
    h = _register_history(60, n_procs=3, seed=5, n_values=4)
    m = matrix_check(encode_register_ops(h), force=True, variant="packed")
    assert m is not None and m[0] is True
    info = last_dispatch_info()
    assert info["variant"] == "int8", info     # demoted one rung down
    assert (3, 8, "packed") in pm._DISABLED


def test_gates(monkeypatch):
    import jepsen_tpu.ops.pallas_matrix as pm

    # VMEM caps: decline huge operator dimensions
    assert pm.chunk_product(9, 8, 4, 16) is None        # S over cap
    assert pm.chunk_product(8, 16, 4, 16) is None       # MV = 4096 over cap
    # packed caps: word alignment and the AND-intermediate MV bound
    assert pm.variant_ok("packed", 1, 8) is False       # MV=16 not /32
    assert pm.variant_ok("packed", 5, 16) is False      # MV=512 > cap
    assert pm.variant_ok("packed", 3, 8) is True        # MV=64
    assert pm.variant_ok("int8", 5, 16) is True
    assert pm.variant_ok("bf16", 3, 8) is False         # unknown name
    # env kill-switch (monkeypatch restores any externally-set value)
    monkeypatch.setenv("JEPSEN_TPU_NO_PALLAS", "1")
    assert not pm.available()
    assert not pm.enabled(3, 8)
    assert not pm.combine_enabled(64)
    assert pm.best_variant(3, 8) is None
    assert pm.chunk_product(3, 8, 4, 16) is None
    monkeypatch.delenv("JEPSEN_TPU_NO_PALLAS")
    assert pm.available()


def test_env_and_knob_coercion(monkeypatch):
    """Tolerant routing knobs: garbage warns and reads as unset/auto,
    never raises (the sweep-variable discipline every env knob here
    follows)."""
    import jepsen_tpu.ops.pallas_matrix as pm

    monkeypatch.setenv("JEPSEN_TPU_MATRIX_VARIANT", "Packed")
    assert pm.matrix_variant() == "packed"
    monkeypatch.setenv("JEPSEN_TPU_MATRIX_VARIANT", "bf16")
    assert pm.matrix_variant() == "auto"
    monkeypatch.setenv("JEPSEN_TPU_PALLAS_PROBE", "FORCE")
    assert pm.probe_mode() == "force"
    monkeypatch.setenv("JEPSEN_TPU_PALLAS_PROBE", "never")
    assert pm.probe_mode() == "auto"
    monkeypatch.setenv("JEPSEN_TPU_FUSE_COMBINE", "no")
    assert pm.fuse_combine_mode() is False
    monkeypatch.setenv("JEPSEN_TPU_FUSE_COMBINE", "1")
    assert pm.fuse_combine_mode() is True
    monkeypatch.delenv("JEPSEN_TPU_FUSE_COMBINE")
    assert pm.fuse_combine_mode() is None
    assert pm.coerce_variant("int8") == "int8"
    assert pm.coerce_variant("auto") is None
    assert pm.coerce_variant("") is None
    assert pm.coerce_variant(7) is None


def test_probe_sidecar_cache(monkeypatch, tmp_path):
    """Probe verdicts persist per (backend, jax version, S, V, variant)
    in the fs_cache sidecar: a fresh process (fresh _PROBED) reuses the
    stored verdict instead of re-probing; JEPSEN_TPU_PALLAS_PROBE=force
    re-probes and refreshes; =skip trusts the gates without probing.
    probe_seconds() accumulates only for real probe runs."""
    import jepsen_tpu.ops.pallas_matrix as pm

    monkeypatch.setenv("JEPSEN_CACHE_DIR", str(tmp_path))
    monkeypatch.setattr(pm, "FORCE_INTERPRET", False)
    monkeypatch.setattr(pm, "_PROBED", {})
    monkeypatch.setattr(pm, "_DISABLED", set())
    calls = []
    monkeypatch.setattr(pm, "_run_probe",
                        lambda S, V, variant, mode: calls.append(variant)
                        or True)
    t0 = pm.probe_seconds()
    assert pm.enabled(3, 8, "int8") is True
    assert calls == ["int8"]
    assert pm.probe_seconds() >= t0

    # fresh process: in-memory memo cleared, sidecar answers
    monkeypatch.setattr(pm, "_PROBED", {})
    assert pm.enabled(3, 8, "int8") is True
    assert calls == ["int8"]                   # no second probe

    # force: re-probe and refresh the sidecar
    monkeypatch.setenv("JEPSEN_TPU_PALLAS_PROBE", "force")
    monkeypatch.setattr(pm, "_PROBED", {})
    assert pm.enabled(3, 8, "int8") is True
    assert calls == ["int8", "int8"]

    # skip: gates only, no probe, nothing persisted for this key
    monkeypatch.setenv("JEPSEN_TPU_PALLAS_PROBE", "skip")
    monkeypatch.setattr(pm, "_PROBED", {})
    assert pm.enabled(3, 8, "packed") is True
    assert "packed" not in calls

    # a persisted MISS also sticks across processes
    monkeypatch.setenv("JEPSEN_TPU_PALLAS_PROBE", "auto")
    monkeypatch.setattr(pm, "_PROBED", {})
    monkeypatch.setattr(pm, "_run_probe",
                        lambda S, V, variant, mode: False)
    assert pm.enabled(4, 8, "f32") is False
    monkeypatch.setattr(pm, "_PROBED", {})
    monkeypatch.setattr(pm, "_run_probe",
                        lambda S, V, variant, mode: True)
    assert pm.enabled(4, 8, "f32") is False    # sidecar's verdict wins


def test_transient_probe_failure_not_persisted(monkeypatch, tmp_path):
    """A transient probe failure (device busy, co-tenant OOM) must not
    write a permanent ok=false verdict into the cross-process sidecar —
    the next process re-probes and self-heals. Deterministic failures
    (lowering errors, mismatches) do persist."""
    import jepsen_tpu.ops.pallas_matrix as pm

    monkeypatch.setenv("JEPSEN_CACHE_DIR", str(tmp_path))
    monkeypatch.setattr(pm, "FORCE_INTERPRET", False)
    monkeypatch.setattr(pm, "_PROBED", {})
    monkeypatch.setattr(pm, "_DISABLED", set())

    def busy(S, V, variant, mode):
        raise RuntimeError("RESOURCE_EXHAUSTED: co-tenant ate the HBM")

    monkeypatch.setattr(pm, "_run_probe", busy)
    assert pm.enabled(3, 8, "int8") is False       # this process: off
    monkeypatch.setattr(pm, "_PROBED", {})         # "next process"
    monkeypatch.setattr(pm, "_run_probe",
                        lambda S, V, variant, mode: True)
    assert pm.enabled(3, 8, "int8") is True        # re-probed, healed

    def lower_fail(S, V, variant, mode):
        raise RuntimeError("Only interpret mode is supported on CPU")

    monkeypatch.setattr(pm, "_PROBED", {})
    monkeypatch.setattr(pm, "_run_probe", lower_fail)
    assert pm.enabled(4, 8, "int8") is False
    monkeypatch.setattr(pm, "_PROBED", {})
    monkeypatch.setattr(pm, "_run_probe",
                        lambda S, V, variant, mode: True)
    assert pm.enabled(4, 8, "int8") is False       # persisted verdict wins


def test_best_variant_order_and_demotion(monkeypatch):
    """Auto order prefers the densest probed-good representation; a
    pinned variant that fails its probe demotes down the order instead
    of erroring; runtime disable() beats every probe."""
    import jepsen_tpu.ops.pallas_matrix as pm

    monkeypatch.setattr(pm, "FORCE_INTERPRET", False)
    monkeypatch.setattr(pm, "_PROBED", {})
    monkeypatch.setattr(pm, "_DISABLED", set())
    monkeypatch.delenv("JEPSEN_TPU_MATRIX_VARIANT", raising=False)
    verdicts = {"packed": False, "int8": True, "f32": True}
    monkeypatch.setattr(
        pm, "enabled",
        lambda S, V, variant="f32": verdicts.get(variant, False))
    assert pm.best_variant(3, 8) == "int8"
    assert pm.best_variant(3, 8, force="packed") == "int8"  # demoted
    assert pm.best_variant(3, 8, force="f32") == "f32"
    verdicts.update({"packed": True})
    assert pm.best_variant(3, 8) == "packed"
    monkeypatch.setenv("JEPSEN_TPU_MATRIX_VARIANT", "f32")
    assert pm.best_variant(3, 8) == "f32"
