"""Dispatch-pipeline unit tests: deterministic overlap/coalescing on
CPU (fake two-batch overlap, the auto-routing threshold, donation
safety) plus the differential check that pipelined multikey results
match the serial path bit-for-bit."""
import numpy as np
import pytest

from jepsen_tpu import telemetry
from jepsen_tpu.parallel import pipeline
from jepsen_tpu.parallel.pipeline import CostModel, DispatchPipeline


class FakeHandle:
    """A dispatch handle recording when it was blocked on."""

    def __init__(self, name, log):
        self.name = name
        self.log = log

    def block_until_ready(self):
        self.log.append(("block", self.name))


def test_two_batch_overlap_order():
    """With depth 2, batch 2's host prep runs BEFORE anything blocks on
    batch 0 — the overlap the pipeline exists for — and the delayed
    blocking hits the OLDEST handle exactly when depth is exceeded."""
    log = []
    pipe = DispatchPipeline(depth=2, name="t")

    def prep(i):
        def f():
            log.append(("prep", i))
            return (i,)
        return f

    def dispatch(i):
        log.append(("dispatch", i))
        return FakeHandle(i, log)

    for i in range(3):
        pipe.submit(prep(i), dispatch)
    out = pipe.results()
    assert [h.name for h in out] == [0, 1, 2]  # submission order
    # batch 0 and 1 dispatched with no blocking; block on 0 happens only
    # when batch 2 exceeds the depth, and AFTER batch 2's prep
    assert log.index(("prep", 2)) < log.index(("block", 0))
    assert ("block", 1) not in log  # depth never exceeded again
    stats = pipe.stats()
    assert stats["batches"] == 3
    assert stats["inflight_peak"] == 2
    # prep of batches 1 and 2 ran while >= 1 dispatch was in flight
    assert stats["overlap_frac"] > 0


def test_pipeline_depth_one_serializes():
    log = []
    pipe = DispatchPipeline(depth=1, name="t1")
    for i in range(2):
        pipe.submit(lambda i=i: (i,),
                    lambda i: FakeHandle(i, log))
    pipe.results()
    assert ("block", 0) in log
    assert pipe.stats()["inflight_peak"] == 1


def test_pipeline_metrics_registry():
    """Occupancy instruments land in a live registry."""
    reg = telemetry.Registry()
    with telemetry.use(reg):
        pipe = DispatchPipeline(depth=2, name="m")
        for i in range(3):
            pipe.submit(lambda i=i: (i,), lambda i: FakeHandle(i, []))
        pipe.results()
    names = {r["name"] for r in reg.snapshot()}
    assert "dispatch_batches_total" in names
    assert "dispatch_inflight_peak" in names
    assert "dispatch_overlap_frac" in names
    assert reg.counter("dispatch_batches_total",
                       labels=("queue",)).value(queue="m") == 3
    prom = reg.render_prom()
    assert 'dispatch_overlap_frac{queue="m"}' in prom


def test_cost_model_threshold():
    """Routing boundary: CPU wins exactly when its predicted time beats
    the 2x round-trip device floor."""
    m = CostModel(roundtrip_s=0.1, cpu_events_per_sec_=100_000.0)
    # floor = 0.2 s -> 20_000 events is the break-even point
    assert m.route(1_000) == "cpu"
    assert m.route(19_999) == "cpu"
    assert m.route(20_001) == "device"
    assert m.route(10_000_000) == "device"
    # zero RTT (no backend measured): never routes off the device
    z = CostModel(roundtrip_s=0.0, cpu_events_per_sec_=100_000.0)
    assert z.route(1) == "device"


def test_cost_model_ewma_feedback():
    pipeline._CPU_RATE.clear()
    try:
        assert pipeline.cpu_events_per_sec() == \
            pipeline.DEFAULT_CPU_EVENTS_PER_SEC
        pipeline.observe_cpu_rate(100_000, 1.0)
        assert pipeline.cpu_events_per_sec() == pytest.approx(100_000.0)
        pipeline.observe_cpu_rate(200_000, 1.0)
        r = pipeline.cpu_events_per_sec()
        assert 100_000 < r < 200_000  # EWMA, not last-sample
        pipeline.observe_cpu_rate(0, 0.0)  # degenerate sample ignored
        assert pipeline.cpu_events_per_sec() == r
    finally:
        pipeline._CPU_RATE.clear()


def test_rtt_env_override(monkeypatch):
    monkeypatch.setenv("JEPSEN_TPU_RTT_S", "0.25")
    assert pipeline.measured_roundtrip_s() == 0.25


def test_donation_gate_on_cpu():
    """The donation gate must be off on the CPU backend (it would warn
    per call and can't be honored) — and the donating/non-donating
    wrappers must collapse to one object there so nothing double
    compiles."""
    assert pipeline.donate_ok() is False


def _streams(n_keys, n_ops=120, n_values=5):
    # n_procs=3 keeps the matrix kernels small (MV = 2^3 * 8 = 64): the
    # differential guarantees don't depend on kernel size, and the
    # quick lane shouldn't pay S=5 compile times
    from __graft_entry__ import _register_history
    from jepsen_tpu.checker.linear_encode import encode_register_ops
    return [encode_register_ops(_register_history(
        n_ops, n_procs=3, seed=1000 + k, n_values=n_values))
        for k in range(n_keys)]


def test_pipelined_multikey_bit_identical(monkeypatch):
    """The differential guarantee: the pipelined sub-batch path returns
    exactly what one serial dispatch returns, key for key — and both
    agree with the exact CPU lane's verdicts."""
    from jepsen_tpu.ops import jitlin

    streams = _streams(24)
    serial = jitlin.matrix_check_batch(streams)
    # force the pipelined path: tiny sub-batches -> 4 dispatches
    monkeypatch.setattr(jitlin, "MATRIX_PIPELINE_KEYS", 6)
    monkeypatch.setattr(jitlin, "MATRIX_SUB_KEYS", 6)
    pipelined = jitlin.matrix_check_batch(streams)
    assert pipelined == serial
    assert pipeline.last_stats().get("queue") == "matrix"
    assert pipeline.last_stats()["batches"] == 4
    # CPU-lane agreement on the verdicts
    from jepsen_tpu.parallel import batch_check
    cpu = batch_check(streams, mesh=False, accelerator="cpu")
    assert [r[0] for r in cpu] == [r[0] for r in serial]


def test_pipelined_multikey_invalid_key(monkeypatch):
    """A corrupted key stays False through the pipelined path, in the
    right position."""
    from jepsen_tpu.ops import jitlin

    # same key count and sub-batch size as the valid differential above,
    # so both tests share the already-compiled kernel shapes
    streams = _streams(24, n_ops=120)
    bad = streams[7]
    a = np.asarray(bad.a).copy()
    # find a read invoke (kind 0, f == READ(0)) and corrupt its value
    ks, fs = np.asarray(bad.kind), np.asarray(bad.f)
    idx = np.nonzero((ks == 0) & (fs == 0) & (np.asarray(bad.a) != 0))[0]
    a[idx[len(idx) // 2]] = (a[idx[len(idx) // 2]] % 5) + 1
    object.__setattr__(bad, "a", a)
    monkeypatch.setattr(jitlin, "MATRIX_PIPELINE_KEYS", 6)
    monkeypatch.setattr(jitlin, "MATRIX_SUB_KEYS", 6)
    piped = jitlin.matrix_check_batch(streams)
    serial_alive = [r[0] for r in jitlin.matrix_check_batch(streams)]
    assert [r[0] for r in piped] == serial_alive
    # the CPU oracle agrees on every key (including the corrupted one,
    # whatever its verdict is)
    from jepsen_tpu.checker.linear_cpu import check_stream
    oracle = [check_stream(s).valid is True for s in streams]
    assert [r[0] for r in piped] == oracle


def test_batch_check_auto_routes_small_to_cpu(monkeypatch):
    """accelerator=auto + a dominating RTT routes a small batch to the
    CPU lane (last_route() records it); verdicts match the device lane."""
    import jepsen_tpu.parallel as par
    from jepsen_tpu.parallel import batch_check

    streams = _streams(4, n_ops=60)
    monkeypatch.setenv("JEPSEN_TPU_RTT_S", "1000.0")
    out_auto = batch_check(streams, mesh=False, accelerator="auto")
    assert par.last_route() == "cpu"
    out_dev = batch_check(streams, mesh=False)
    assert par.last_route() == "device"
    assert [r[0] for r in out_auto] == [r[0] for r in out_dev]


def test_batch_check_auto_keeps_big_on_device(monkeypatch):
    import jepsen_tpu.parallel as par
    from jepsen_tpu.parallel import batch_check

    streams = _streams(4, n_ops=60)
    monkeypatch.setenv("JEPSEN_TPU_RTT_S", "0.0")
    batch_check(streams, mesh=False, accelerator="auto")
    assert par.last_route() == "device"


def test_resume_chain_after_donation_gate():
    """Segmented resume chaining stays correct under the donation
    machinery (on CPU the gate collapses both wrappers; the chain's
    verdicts must hold either way)."""
    from bench import _block_stream
    from jepsen_tpu.ops.jitlin import matrix_check_resume

    s0 = _block_stream(300, n_procs=3, n_values=4)
    s1 = _block_stream(300, n_procs=3, n_values=4, start_block=300)
    a0, ix0, tot = matrix_check_resume(s0, None, n_slots=3, num_states=5)
    a1, ix1, tot2 = matrix_check_resume(s1, tot, n_slots=3, num_states=5)
    assert bool(np.asarray(a1).all()) and not bool(np.asarray(ix1).any())


def test_phase_attribution_recorded():
    from jepsen_tpu.ops import jitlin

    streams = _streams(2, n_ops=80)
    jitlin.matrix_check_batch(streams)
    ph = jitlin.last_phase_seconds()
    for k in ("prepass", "grids", "dispatch", "fetch"):
        assert k in ph and ph[k] >= 0


def test_checker_exports_phase_gauges(monkeypatch):
    """The linearizable checker's telemetry carries the per-phase
    attribution gauges for matrix-path checks."""
    from __graft_entry__ import _register_history
    from jepsen_tpu.checker.linearizable import LinearizableChecker
    from jepsen_tpu.ops import jitlin

    monkeypatch.setattr(jitlin, "MATRIX_MIN_RETURNS", 10)
    reg = telemetry.Registry()
    with telemetry.use(reg):
        chk = LinearizableChecker(accelerator="tpu")
        out = chk.check({}, _register_history(600, n_procs=3, seed=3,
                                              n_values=5), {})
    assert out["algorithm"] == "jitlin-tpu-matrix"
    phases = {r["labels"]["phase"] for r in reg.snapshot()
              if r["name"] == "checker_matrix_phase_seconds"}
    assert {"prepass", "grids", "dispatch", "fetch"} <= phases


def test_matrix_phase_model_shares():
    m = telemetry.matrix_phase_model(64_000, 5, 8, 256, 1)
    assert m["modeled_matmul_frac"] > 0.99
    assert m["modeled_lbuild_frac"] < 0.01
    total = (m["modeled_matmul_frac"] + m["modeled_lbuild_frac"]
             + m["modeled_combine_frac"])
    assert total == pytest.approx(1.0, abs=0.01)
