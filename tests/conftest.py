"""Test config: force an 8-device virtual CPU mesh before JAX is imported.

Mirrors the reference's tier-1/tier-2 test strategy (SURVEY.md §4): pure unit
tests plus fake-cluster integration, no real TPU required. Multi-chip sharding
is exercised on a virtual 8-device CPU mesh, the same mechanism the driver's
``dryrun_multichip`` uses.
"""
import os
import sys

# Must run before any backend init anywhere in the test session. Force —
# the image's profile exports JAX_PLATFORMS=axon (a tunneled TPU), and unit
# tests must not depend on (or block on) that tunnel.
#
# Exception: JEPSEN_TPU_TESTS=1 opts a session INTO the real chip for the
# ``-m tpu`` parity tier (tests/test_tpu_parity.py) — the platform list is
# left alone so the axon TPU stays the default device.
#
# The ``-m mesh`` lane (multi-device sharding differentials,
# tests/test_mesh.py) overrides even that: its tests NEED the 8-device
# virtual CPU mesh, and a single tunneled chip can't provide one — so a
# mesh-lane session is always forced onto the virtual mesh.
TPU_SESSION = bool(os.environ.get("JEPSEN_TPU_TESTS"))


def _wants_mesh_lane() -> bool:
    """True when this session's -m expression selects the mesh marker
    (parsed from argv — this must run before pytest parses options,
    because the XLA flag only works before any jax import)."""
    def selects(expr: str) -> bool:
        return "mesh" in expr and "not mesh" not in expr

    argv = sys.argv
    for i, a in enumerate(argv):
        if a in ("-m", "--markexpr") and i + 1 < len(argv) \
                and selects(argv[i + 1]):
            return True
        if (a.startswith("-m") or a.startswith("--markexpr=")) \
                and selects(a):
            return True
    return False


MESH_LANE = _wants_mesh_lane()
if not TPU_SESSION or MESH_LANE:
    os.environ["JAX_PLATFORMS"] = "cpu"
    _flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The image's sitecustomize registers an out-of-process TPU PJRT plugin
# ("axon") in every interpreter and sets jax_platforms="axon,cpu" via
# jax.config — which overrides the env var. Initializing that backend dials
# a relay and can block indefinitely if the tunnel is down. Tests are
# CPU-only by design (outside the opted-in tpu tier), so force the
# platform list back to cpu before any backend init (conftest imports
# before any test touches jax).
if not TPU_SESSION or MESH_LANE:
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass


# --- tier-1 wall-clock guard -------------------------------------------
#
# The quick lane (-m 'not slow') must stay inside the driver's 870 s
# timeout; PR 2 split the slow tests out to get it there. This guard
# fails the SESSION when the quick lane exceeds its budget, so a slow
# test creeping into the quick lane is a red build, not a silent drift
# back toward the timeout. Tune/disable with JEPSEN_TPU_TIER1_BUDGET_S
# (0 disables).

import time as _time_mod  # noqa: E402

TIER1_BUDGET_S = float(os.environ.get("JEPSEN_TPU_TIER1_BUDGET_S", "870"))


def _is_quick_lane(config) -> bool:
    expr = config.getoption("markexpr", default="") or ""
    return "not slow" in expr


def pytest_configure(config):
    config._jepsen_session_t0 = _time_mod.monotonic()
    if TIER1_BUDGET_S > 0 and _is_quick_lane(config):
        # A WEDGED session never reaches sessionfinish — the driver's
        # outer `timeout` kills it with no diagnostics. Arm faulthandler
        # to dump every thread's stack at the budget mark, so CI logs
        # show where the wedge is instead of nothing (doc/robustness.md).
        import faulthandler
        try:
            faulthandler.dump_traceback_later(
                TIER1_BUDGET_S, file=sys.__stderr__)
            config._jepsen_dump_armed = True
        except Exception:  # noqa: BLE001 — diagnostics never break a run
            pass


_TEST_DURATIONS: dict = {}


def pytest_runtest_logreport(report):
    # accumulate per-test wall time (setup+call+teardown) so an
    # over-budget session can NAME the creep instead of only dumping
    # thread stacks — a slow-but-finished trip used to leave no trail
    _TEST_DURATIONS[report.nodeid] = (
        _TEST_DURATIONS.get(report.nodeid, 0.0)
        + getattr(report, "duration", 0.0))


def _dump_slowest(file, n: int = 10) -> None:
    worst = sorted(_TEST_DURATIONS.items(), key=lambda kv: -kv[1])[:n]
    if not worst:
        return
    print(f"\n==== slowest {len(worst)} tests this session ====",
          file=file)
    for nodeid, secs in worst:
        print(f"{secs:8.2f}s  {nodeid}", file=file)


def pytest_sessionfinish(session, exitstatus):
    if getattr(session.config, "_jepsen_dump_armed", False):
        import faulthandler
        faulthandler.cancel_dump_traceback_later()
    if TIER1_BUDGET_S <= 0 or not _is_quick_lane(session.config):
        return
    elapsed = _time_mod.monotonic() - session.config._jepsen_session_t0
    if elapsed > TIER1_BUDGET_S:
        import pytest
        # over budget but not wedged: name the slowest tests (the usual
        # culprits) and dump what is still running (a lingering thread
        # is the other cause of creep), then fail the session
        _dump_slowest(sys.__stderr__)
        from jepsen_tpu.telemetry import dump_thread_stacks
        dump_thread_stacks(sys.__stderr__)
        # pytest.exit from sessionfinish is the supported way to force
        # the exit status (wrap_session catches exit.Exception here)
        pytest.exit(
            f"quick lane took {elapsed:.0f}s, over its "
            f"{TIER1_BUDGET_S:.0f}s tier-1 budget — move the slow "
            "test(s) above to the slow lane (pytest.mark.slow); see "
            "doc/robustness.md", returncode=1)


import pytest  # noqa: E402


@pytest.fixture(autouse=True, scope="session")
def _native_ingest_build_guard():
    """Tier-1 guard for the host ingest spine: when a compiler is
    present, the C extension must BUILD and pass its differential
    probe — a silent fallback to the Python twins would let native
    regressions (or a probe divergence) ship unnoticed behind green
    tests. No compiler (g++ genuinely absent) still degrades softly;
    every other failure is loud."""
    import shutil

    if shutil.which("g++") is None:
        yield
        return
    from jepsen_tpu.history_ir import ingest
    from jepsen_tpu.native import columnar_c
    try:
        so = columnar_c.build()
    except Exception as e:  # noqa: BLE001 — rethrown as the loud signal
        pytest.exit("native ingest guard: columnar_ext.c failed to "
                    f"compile with g++ present: {e!r}", returncode=1)
    m = columnar_c.mod()
    if m is None or not hasattr(m, "ingest_chunk"):
        pytest.exit(f"native ingest guard: built {so} but the module "
                    "did not load or lacks the spine entry points",
                    returncode=1)
    if ingest.native_mod() is None:
        pytest.exit("native ingest guard: extension built but the "
                    "differential probe condemned it (see "
                    "jepsen.history_ir log) — tier-1 must not run on "
                    "a silently-diverged native path", returncode=1)
    yield


@pytest.fixture(autouse=True, scope="session")
def _hermetic_fs_cache(tmp_path_factory):
    """fs_cache writes (the pallas probe-verdict sidecar above all —
    ops/pallas_matrix persists per-backend probe results there) land in
    a session temp dir, never the user's real ~/.jepsen-tpu/cache:
    tests must neither pollute nor depend on developer-machine state.
    Per-test JEPSEN_CACHE_DIR monkeypatches still override."""
    prev = os.environ.get("JEPSEN_CACHE_DIR")
    os.environ["JEPSEN_CACHE_DIR"] = str(tmp_path_factory.mktemp("fs-cache"))
    yield
    if prev is None:
        os.environ.pop("JEPSEN_CACHE_DIR", None)
    else:
        os.environ["JEPSEN_CACHE_DIR"] = prev


def run_fake(suite_test_fn, **opts):
    """Shared fake-mode lifecycle harness for suite tests: builds the
    suite's test map in --fake mode (in-memory doubles over the dummy
    remote) and runs the full core.run lifecycle into a throwaway store."""
    import tempfile

    with tempfile.TemporaryDirectory() as tmp:
        t = suite_test_fn({"fake": True, "time_limit": 1.0,
                           "store_dir": tmp, "no_perf": True,
                           "accelerator": "cpu", **opts})
        from jepsen_tpu import core
        return core.run(t)
