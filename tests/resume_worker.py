"""Chaos-test worker: a deliberately slowed, every-segment-checkpointed
segmented matrix check for the parent test to SIGKILL mid-check
(tests/test_resume.py).

Runs the PRODUCTION dispatch — ``LinearizableChecker.check`` with a
run-dir-backed test map — over a deterministic valid register history,
with ``matrix_check_resume`` wrapped in a per-segment sleep so the
parent can aim a SIGKILL between two durable ``check.ckpt`` persists.
The parent resumes the same check in-process afterwards and asserts a
bit-identical verdict that re-ran only the segments after the last
checkpoint.

Usage:

    JEPSEN_TPU_MATRIX_SEGMENT_EVENTS=2048 \
        python resume_worker.py <store-dir> <name> <timestamp> [sleep_s]
"""
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_PROCS, N_VALUES = 3, 5


def block_history(n_blocks: int, seed: int = 11,
                  plant_anomaly_at: int | None = None) -> list[dict]:
    """Deterministic valid register history of write-then-read blocks
    (quiescent between every pair, so every segment boundary is a
    quiescent cut). Shared by the worker and the parent test — both
    sides MUST check the identical history for the bit-identity
    assertions to mean anything. ``plant_anomaly_at`` makes block b's
    read observe a never-written value (non-linearizable)."""
    import numpy as np
    rng = np.random.default_rng(seed)
    ops: list[dict] = []
    for b in range(n_blocks):
        p = int(rng.integers(N_PROCS))
        v = int(rng.integers(N_VALUES))
        ops.append({"process": p, "type": "invoke", "f": "write",
                    "value": v})
        ops.append({"process": p, "type": "ok", "f": "write", "value": v})
        p2 = int(rng.integers(N_PROCS))
        rv = (v + 1) % N_VALUES if b == plant_anomaly_at else v
        ops.append({"process": p2, "type": "invoke", "f": "read",
                    "value": None})
        ops.append({"process": p2, "type": "ok", "f": "read", "value": rv})
    return ops


def main() -> int:
    store_dir, name, ts = sys.argv[1:4]
    sleep_s = float(sys.argv[4]) if len(sys.argv) > 4 else 0.25

    from jepsen_tpu.ops import jitlin

    real = jitlin.matrix_check_resume

    def slow_resume(*args, **kw):
        out = real(*args, **kw)
        time.sleep(sleep_s)
        return out

    jitlin.matrix_check_resume = slow_resume

    from jepsen_tpu.checker.linearizable import LinearizableChecker

    test = {"name": name, "start_time": ts, "store_dir": store_dir,
            # write a durable checkpoint at every opportunity: the
            # parent kills between two persists
            "check_ckpt_interval": 0.001,
            "checker_sharded": False}
    history = block_history(4096)
    out = LinearizableChecker(accelerator="tpu").check(test, history, {})
    print(json.dumps({"valid": out["valid?"],
                      "algorithm": out["algorithm"]}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
