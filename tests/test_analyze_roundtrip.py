"""Checkpoint/resume soundness for the round-4 workloads: a stored
history re-checked through the analyze path (store.jsonl round-trip)
must reach the SAME verdict as the live run. JSON stringifies dict
keys, so any checker comparing against int-keyed state is at risk —
the mongodb transfer checker was falsely convicting stored histories
until its key normalization landed."""
import pytest

from jepsen_tpu import core, store
from jepsen_tpu.suites import (crate, dgraph, faunadb, galera, mongodb,
                               rabbitmq, rethinkdb, stolon, tidb, yugabyte)


def _run_and_reanalyze(suite_test_fn, tmp_path, **opts):
    t = suite_test_fn({"fake": True, "time_limit": 1.0,
                       "store_dir": str(tmp_path), "no_perf": True,
                       "accelerator": "cpu", **opts})
    live = core.run(t)
    name = t["name"]
    ts = sorted(store.tests(name, str(tmp_path))[name])[-1]
    hist = store.load_history(name, ts, str(tmp_path))
    # a fresh test map, the way the analyze CLI rebuilds it
    t2 = suite_test_fn({"fake": True, "time_limit": 1.0,
                        "store_dir": str(tmp_path), "no_perf": True,
                        "accelerator": "cpu", **opts})
    re = t2["checker"].check(t2, hist, {})
    return live["results"], re


# (suite_fn, opts, may_be_unknown) — the last flag marks the one
# workload whose short fake run can legitimately end "unknown" (a
# straggler key claimed near the time limit never gets its final read);
# every other case must deterministically verify True
CASES = [
    (faunadb.faunadb_test, {"workload": "bank"}, False),
    (mongodb.mongodb_test, {"workload": "transfer"}, False),
    (faunadb.faunadb_test, {"workload": "monotonic"}, False),
    (faunadb.faunadb_test, {"workload": "multimonotonic"}, False),
    (faunadb.faunadb_test, {"workload": "internal"}, False),
    (tidb.tidb_test, {"workload": "monotonic"}, False),
    (dgraph.dgraph_test, {"workload": "delete"}, False),
    (dgraph.dgraph_test, {"workload": "sequential"}, False),
    (stolon.stolon_test, {"workload": "ledger"}, False),
    # broad sweep over value shapes (lists, tuples, txn mops, queues):
    # the whole JSON-round-trip bug class, not just dict keys
    (galera.galera_test, {"workload": "dirty-reads"}, False),
    (yugabyte.yugabyte_test, {"workload": "multi-key-acid"}, False),
    (tidb.tidb_test, {"workload": "set-cas"}, False),
    (tidb.tidb_test, {"workload": "append"}, False),
    (crate.crate_test, {"workload": "lost-updates"}, True),
    (rethinkdb.rethinkdb_test, {"workload": "counter"}, False),
    (rabbitmq.rabbitmq_test, {"workload": "queue"}, False),
    (faunadb.faunadb_test, {"workload": "pages"}, False),
    # round-4 additions: the crate visibility probe (strong-read sets)
    # and the per-key-table Elle variant
    (crate.crate_test, {"workload": "dirty-read",
                        "dirty_read_quiesce": 0.2}, False),
    (yugabyte.yugabyte_test, {"workload": "append-table"}, False),
]


@pytest.mark.parametrize("suite_fn,opts,may_be_unknown", CASES,
                         ids=[f"{fn.__name__}-{o['workload']}"
                              for fn, o, _ in CASES])
@pytest.mark.slow
def test_analyze_verdict_matches_live(tmp_path, suite_fn, opts,
                                      may_be_unknown):
    live, re = _run_and_reanalyze(suite_fn, tmp_path, **opts)
    if may_be_unknown:
        assert live["valid?"] in (True, "unknown"), live
    else:
        assert live["valid?"] is True, live
    assert re["valid?"] == live["valid?"], (
        "stored-history re-check diverged from the live verdict",
        live["valid?"], re)
