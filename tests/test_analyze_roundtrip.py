"""Checkpoint/resume soundness for the round-4 workloads: a stored
history re-checked through the analyze path (store.jsonl round-trip)
must reach the SAME verdict as the live run. JSON stringifies dict
keys, so any checker comparing against int-keyed state is at risk —
the mongodb transfer checker was falsely convicting stored histories
until its key normalization landed."""
import pytest

from jepsen_tpu import core, store
from jepsen_tpu.suites import dgraph, faunadb, mongodb, stolon, tidb


def _run_and_reanalyze(suite_test_fn, tmp_path, **opts):
    t = suite_test_fn({"fake": True, "time_limit": 1.0,
                       "store_dir": str(tmp_path), "no_perf": True,
                       "accelerator": "cpu", **opts})
    live = core.run(t)
    name = t["name"]
    ts = sorted(store.tests(name, str(tmp_path))[name])[-1]
    hist = store.load_history(name, ts, str(tmp_path))
    # a fresh test map, the way the analyze CLI rebuilds it
    t2 = suite_test_fn({"fake": True, "time_limit": 1.0,
                        "store_dir": str(tmp_path), "no_perf": True,
                        "accelerator": "cpu", **opts})
    re = t2["checker"].check(t2, hist, {})
    return live["results"], re


CASES = [
    (faunadb.faunadb_test, {"workload": "bank"}),
    (mongodb.mongodb_test, {"workload": "transfer"}),
    (faunadb.faunadb_test, {"workload": "monotonic"}),
    (faunadb.faunadb_test, {"workload": "multimonotonic"}),
    (faunadb.faunadb_test, {"workload": "internal"}),
    (tidb.tidb_test, {"workload": "monotonic"}),
    (dgraph.dgraph_test, {"workload": "delete"}),
    (dgraph.dgraph_test, {"workload": "sequential"}),
    (stolon.stolon_test, {"workload": "ledger"}),
]


@pytest.mark.parametrize("suite_fn,opts", CASES,
                         ids=[f"{fn.__name__}-{o['workload']}"
                              for fn, o in CASES])
def test_analyze_verdict_matches_live(tmp_path, suite_fn, opts):
    live, re = _run_and_reanalyze(suite_fn, tmp_path, **opts)
    assert live["valid?"] is True, live
    assert re["valid?"] is True, (
        "stored-history re-check diverged from the live verdict", re)
