"""Multi-device checker sharding: mesh-vs-single-device differentials.

The tier-1 conftest forces an 8-virtual-CPU-device mesh, so every test
here exercises the REAL shard_map kernels, collectives, and padding —
the same mechanism production uses across real chips
(doc/performance.md "Multi-device sharding"). Everything asserts
bit-identity against the single-device path: sharding is a data-plane
optimization and must never change a verdict.

Run just this lane with ``-m mesh`` (conftest forces the virtual mesh
even in a ``JEPSEN_TPU_TESTS`` session).
"""
import numpy as np
import pytest

from jepsen_tpu import telemetry

pytestmark = pytest.mark.mesh

N_PROCS, N_VALUES = 3, 5


@pytest.fixture
def metrics_registry():
    """A live telemetry registry installed for the test's duration."""
    reg = telemetry.Registry()
    prev = telemetry.install(reg)
    try:
        yield reg
    finally:
        telemetry.install(prev)


def _mesh(n=8):
    import jax

    from jepsen_tpu.parallel import get_mesh
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} devices (forced by conftest; a "
                    f"non-conftest runner must set "
                    f"--xla_force_host_platform_device_count)")
    return get_mesh(n)


def _history(n_blocks, seed=0, plant_anomaly_at=None):
    """A register history of write/read blocks; planting an anomaly
    makes one read observe a value never written (non-linearizable)."""
    rng = np.random.default_rng(seed)
    ops = []
    for b in range(n_blocks):
        p = int(rng.integers(N_PROCS))
        v = int(rng.integers(N_VALUES))
        ops.append({"process": p, "type": "invoke", "f": "write",
                    "value": v})
        ops.append({"process": p, "type": "ok", "f": "write", "value": v})
        p2 = int(rng.integers(N_PROCS))
        rv = (v + 1) % N_VALUES if b == plant_anomaly_at else v
        ops.append({"process": p2, "type": "invoke", "f": "read",
                    "value": None})
        ops.append({"process": p2, "type": "ok", "f": "read", "value": rv})
    return ops


def _stream(n_blocks, seed=0, plant_anomaly_at=None, intern=None):
    from jepsen_tpu.checker.linear_encode import encode_register_ops
    return encode_register_ops(
        _history(n_blocks, seed=seed, plant_anomaly_at=plant_anomaly_at),
        **({"intern": intern} if intern is not None else {}))


# ---------------------------------------------------------------------------
# Segmented path: chunk-axis sharding
# ---------------------------------------------------------------------------

def test_segmented_mesh_differential_bit_identical():
    """matrix_check_resume chains compose the same verdicts AND the same
    carry operator bits on the mesh as on one device — valid chain and a
    chain with a planted anomaly mid-segment."""
    from jepsen_tpu.history import Intern
    from jepsen_tpu.ops import jitlin

    mesh = _mesh()
    for name, anomaly in (("valid", None), ("anomalous", 250)):
        intern = Intern()
        segs = [
            _stream(500, seed=s,
                    plant_anomaly_at=(anomaly if s == 1 else None),
                    intern=intern)
            for s in range(3)
        ]
        outs = {}
        for label, m in (("single", None), ("mesh", mesh)):
            tot, alive, ix = None, None, None
            for seg in segs:
                alive, ix, tot = jitlin.matrix_check_resume(
                    seg, tot, n_slots=N_PROCS, num_states=len(intern),
                    mesh=m)
            outs[label] = (np.asarray(alive).copy(), np.asarray(ix).copy(),
                           np.asarray(tot).copy())
        a1, i1, t1 = outs["single"]
        a2, i2, t2 = outs["mesh"]
        assert np.array_equal(a1, a2), name
        assert np.array_equal(i1, i2), name
        assert np.array_equal(t1, t2), f"{name}: carry operators diverge"
        assert bool(a1[0]) is (anomaly is None), name


def test_segmented_variant_chain_matches_mesh_twin(monkeypatch):
    """ISSUE 12 mesh-twin bit-identity: a segmented chain through the
    pallas kernel variants + fused streaming combine (single device,
    interpret mode) composes the SAME verdicts and the SAME carry
    operator bits as the sharded mesh twin (XLA scan + device-side tree
    combine) — the variants change the operand representation, never
    one bit of the composed operator."""
    import jepsen_tpu.ops.pallas_matrix as pm
    from jepsen_tpu.history import Intern
    from jepsen_tpu.ops import jitlin

    mesh = _mesh()
    for variant in ("packed", "int8"):
        intern = Intern()
        segs = [_stream(120, seed=10 + s, intern=intern) for s in range(2)]
        outs = {}
        monkeypatch.setattr(pm, "FORCE_INTERPRET", True)
        try:
            tot, alive = None, None
            for seg in segs:
                alive, _, tot = jitlin.matrix_check_resume(
                    seg, tot, n_slots=N_PROCS, num_states=len(intern),
                    variant=variant, combine_fused=True)
            info = jitlin.last_dispatch_info()
            assert info == {"variant": variant, "combine": "fused"}, info
            outs["pallas"] = (np.asarray(alive).copy(),
                              np.asarray(tot).copy())
        finally:
            monkeypatch.setattr(pm, "FORCE_INTERPRET", False)
        tot, alive = None, None
        for seg in segs:
            alive, _, tot = jitlin.matrix_check_resume(
                seg, tot, n_slots=N_PROCS, num_states=len(intern),
                mesh=mesh)
        outs["mesh"] = (np.asarray(alive).copy(), np.asarray(tot).copy())
        a1, t1 = outs["pallas"]
        a2, t2 = outs["mesh"]
        assert np.array_equal(a1, a2), variant
        assert np.array_equal(t1, t2), (
            f"{variant}: carry operators diverge from the mesh twin")
        assert bool(a1[0])


def test_segmented_mixed_chain_sharded_then_single():
    """A chain may mix sharded and single-device segments (the ladder's
    sharded→device demotion mid-chain): the carry is the same replicated
    product either way."""
    from jepsen_tpu.history import Intern
    from jepsen_tpu.ops import jitlin

    mesh = _mesh()
    intern = Intern()
    segs = [_stream(500, seed=s, intern=intern) for s in range(2)]

    tot, alive, ix = None, None, None
    for seg, m in zip(segs, (mesh, None)):
        alive, ix, tot = jitlin.matrix_check_resume(
            seg, tot, n_slots=N_PROCS, num_states=len(intern), mesh=m)
    mixed = np.asarray(tot).copy()

    tot2 = None
    for seg in segs:
        _, _, tot2 = jitlin.matrix_check_resume(
            seg, tot2, n_slots=N_PROCS, num_states=len(intern), mesh=None)
    assert bool(np.asarray(alive)[0])
    assert np.array_equal(mixed, np.asarray(tot2))


# ---------------------------------------------------------------------------
# Key batch: key-axis sharding + non-divisible padding
# ---------------------------------------------------------------------------

def test_batch_mesh_differential_nondivisible_keys(metrics_registry):
    """11 keys over 8 devices: the key axis pads to 16 (never silently
    drops sharding), verdicts — including a planted per-key anomaly —
    are identical to single-device, and the padding cost is published."""
    from jepsen_tpu.ops import jitlin

    mesh = _mesh()
    streams = [
        _stream(150, seed=100 + k,
                plant_anomaly_at=(75 if k == 7 else None))
        for k in range(11)
    ]
    r1 = jitlin.matrix_check_batch(streams)
    r2 = jitlin.matrix_check_batch(streams, mesh=mesh)
    assert r1 == r2
    assert [r[0] for r in r1] == [k != 7 for k in range(11)]
    frac = metrics_registry.gauge("checker_mesh_padding_frac").value()
    assert 0.0 < frac < 1.0  # 11 keys padded to 16: visible, not free


def test_scan_batch_mesh_differential():
    """The vmapped event-scan path (below the matrix regime) with the
    leading key axis sharded: pad_to_multiple + per-device staging give
    the same verdicts as single-device."""
    from jepsen_tpu.parallel import batch_check

    mesh = _mesh()
    streams = [
        _stream(12, seed=200 + k, plant_anomaly_at=(6 if k == 2 else None))
        for k in range(5)
    ]
    r1 = batch_check(streams, mesh=False)
    r2 = batch_check(streams, mesh=mesh)
    assert r1 == r2
    assert [r[0] for r in r1] == [k != 2 for k in range(5)]


# ---------------------------------------------------------------------------
# Ladder: the sharded rung wins, and demotes instead of failing
# ---------------------------------------------------------------------------

def _matrix_regime_history():
    # ≥ MATRIX_MIN_RETURNS returns so the matrix rungs are eligible
    from jepsen_tpu.ops.jitlin import MATRIX_MIN_RETURNS
    return _history(MATRIX_MIN_RETURNS // 2 + 50, seed=7)


def test_ladder_sharded_rung_wins(metrics_registry):
    """checker_sharded=True routes the check through the mesh rung."""
    from jepsen_tpu.checker.linearizable import LinearizableChecker

    _mesh()
    chk = LinearizableChecker(accelerator="tpu")
    out = chk.check({}, _matrix_regime_history(),
                    {"checker_sharded": True})
    assert out["valid?"] is True
    assert out["algorithm"] == "jitlin-tpu-matrix-sharded"


def test_ladder_sharded_demotes_to_single_device(metrics_registry,
                                                 monkeypatch):
    """An injected collective failure demotes sharded → single-device
    (counted in checker_backend_demotions_total) instead of failing the
    check — the acceptance contract for backends without mesh support."""
    from jepsen_tpu.checker.linearizable import LinearizableChecker
    from jepsen_tpu.ops import jitlin

    _mesh()

    def no_collectives(*a, **kw):
        raise RuntimeError("collectives are not implemented on this "
                           "backend")

    # a fresh compile cache so the poisoned builder is actually invoked
    # (a warm mesh kernel from an earlier test would dodge the injection)
    monkeypatch.setattr(jitlin, "_MATRIX_CACHE", {})
    monkeypatch.setattr(jitlin, "_build_matrix_kernel_mesh",
                        no_collectives)
    chk = LinearizableChecker(accelerator="tpu")
    out = chk.check({}, _matrix_regime_history(),
                    {"checker_sharded": True})
    assert out["valid?"] is True
    assert out["algorithm"] == "jitlin-tpu-matrix"  # single-device won
    reg = metrics_registry
    demoted = reg.counter("checker_backend_demotions_total",
                          labels=("backend", "reason")).value(
                              backend="sharded-matrix", reason="error")
    assert demoted == 1


def test_ladder_sharded_disabled_by_knob(metrics_registry):
    """checker_sharded=False never attempts the mesh rung."""
    from jepsen_tpu.checker.linearizable import LinearizableChecker

    chk = LinearizableChecker(accelerator="tpu")
    out = chk.check({}, _matrix_regime_history(),
                    {"checker_sharded": False})
    assert out["valid?"] is True
    assert out["algorithm"] == "jitlin-tpu-matrix"


# ---------------------------------------------------------------------------
# Knobs, cost model, preflight
# ---------------------------------------------------------------------------

def test_knob_coercion_tolerant():
    from jepsen_tpu import parallel

    assert parallel.coerce_flag(None) is None
    assert parallel.coerce_flag(True) is True
    assert parallel.coerce_flag(0) is False
    assert parallel.coerce_flag(" Yes ") is True
    assert parallel.coerce_flag("off") is False
    assert parallel.coerce_flag("garbage") is None  # warns, reads unset
    assert parallel.coerce_devices(None) is None
    assert parallel.coerce_devices("4") == 4
    assert parallel.coerce_devices(2.0) == 2
    assert parallel.coerce_devices(-3) == 0
    assert parallel.coerce_devices("many") is None
    assert parallel.coerce_devices(True) is None  # bool is not a count


def test_mesh_env_knobs(monkeypatch):
    from jepsen_tpu import parallel

    monkeypatch.setenv("JEPSEN_TPU_MESH_DEVICES", "nonsense")
    assert parallel.mesh_devices_limit() is None  # warns, no raise
    monkeypatch.setenv("JEPSEN_TPU_MESH_DEVICES", "4")
    assert parallel.mesh_devices_limit() == 4
    mesh = parallel.auto_mesh()
    if mesh is not None:
        assert int(mesh.devices.size) <= 4
    monkeypatch.setenv("JEPSEN_TPU_MESH_DEVICES", "1")
    assert parallel.auto_mesh() is None  # <2 devices: no mesh
    monkeypatch.delenv("JEPSEN_TPU_MESH_DEVICES")
    monkeypatch.setenv("JEPSEN_TPU_SHARDED", "0")
    assert parallel.sharded_mesh_for(1 << 30) is None


def test_cost_model_mesh_route(monkeypatch):
    """Small batches never pay mesh overhead on faith; measured rates
    flip the route once the mesh is actually faster."""
    from jepsen_tpu.parallel import pipeline

    monkeypatch.setattr(pipeline, "_DEVICE_RATE", {})
    assert not pipeline.mesh_route(100, 8)  # below MESH_MIN_EVENTS
    assert not pipeline.mesh_route(1 << 30, 1)  # one device is no mesh
    assert pipeline.mesh_route(pipeline.MESH_MIN_EVENTS, 8)
    # measured: mesh 4x faster -> route big batches to it
    pipeline.observe_device_rate(1, 100_000, 1.0)
    pipeline.observe_device_rate(8, 400_000, 1.0)
    assert pipeline.mesh_route(1_000_000, 8)
    # measured: mesh slower (collective overhead) -> stay single-device
    monkeypatch.setattr(pipeline, "_DEVICE_RATE",
                        {1: 100_000.0, 8: 50_000.0})
    assert not pipeline.mesh_route(1_000_000, 8)


@pytest.mark.lint
def test_preflight_mesh_knobs():
    from jepsen_tpu.analysis.preflight import _check_knobs

    assert _check_knobs({"mesh_devices": 4, "checker_sharded": True}) == []
    diags = _check_knobs({"mesh_devices": "many"})
    assert any(d.code == "KNB001" and d.path == "mesh_devices"
               for d in diags)
    diags = _check_knobs({"mesh_devices": -1})
    assert any(d.code == "KNB002" for d in diags)
    diags = _check_knobs({"checker_sharded": "true"})
    assert any(d.code == "KNB006" and d.path == "checker_sharded"
               for d in diags)
    diags = _check_knobs({"checker_sharded": "sideways"})
    assert any(d.code == "KNB001" and d.path == "checker_sharded"
               for d in diags)


# ---------------------------------------------------------------------------
# Multi-process seam (single-process execution of the local-mesh gate)
# ---------------------------------------------------------------------------

def test_distributed_local_mesh_gate():
    """batch_check_distributed's local-mesh gate: small batches stay
    single-device (mesh=False floor), and results match batch_check.
    The true two-process run is tests/test_distributed.py (slow lane);
    this covers the new gate logic on one process."""
    from jepsen_tpu.parallel import batch_check
    from jepsen_tpu.parallel.distributed import batch_check_distributed

    streams = [_stream(12, seed=300 + k) for k in range(3)]
    assert batch_check_distributed(streams) == batch_check(streams,
                                                           mesh=False)


def test_distributed_skip_matcher_signatures():
    """The test_distributed skip-reason matcher still recognizes the
    backend's no-multiprocess-collectives signatures (it must keep
    triggering under the forced-device-count flag, not fail the lane)."""
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "_td", os.path.join(os.path.dirname(__file__),
                            "test_distributed.py"))
    td = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(td)
    hit = td._missing_collective_support(
        ["jaxlib.xla_extension.XlaRuntimeError: UNIMPLEMENTED: "
         "Multiprocess computations aren't implemented on the CPU "
         "backend."])
    assert hit is not None
    assert td._missing_collective_support(
        ["AssertionError: verdicts diverged"]) is None
