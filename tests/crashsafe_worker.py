"""Chaos-test worker: a deliberately slow fake-mode run for the parent
test to SIGKILL mid-case (tests/test_crashsafe.py).

Opens a partition (recorded, never healed — the kill lands before the
stop op), then grinds through register ops at ~100/s per worker so the
write-ahead journal accumulates lines the parent can poll for. Usage:

    python crashsafe_worker.py <store-dir>
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from jepsen_tpu import core  # noqa: E402
from jepsen_tpu import generator as gen
from jepsen_tpu import nemesis as nem
from jepsen_tpu.fakes import AtomClient, AtomDB, noop_test


class SlowAtomClient(AtomClient):
    """AtomClient with a per-op delay, so the run is killable mid-case
    instead of finishing before the parent can aim."""

    def invoke(self, test, op):
        time.sleep(0.01)
        return super().invoke(test, op)


def main() -> int:
    store_dir = sys.argv[1]
    db = AtomDB()
    ops = [{"type": "invoke", "f": "write", "value": 1},
           {"type": "invoke", "f": "read", "value": None},
           {"type": "invoke", "f": "cas", "value": [1, 2]},
           {"type": "invoke", "f": "write", "value": 3}]
    g = gen.Seq([
        gen.nemesis_gen(gen.Seq([{"type": "info", "f": "start-partition",
                                  "value": None}])),
        gen.clients(gen.limit(50_000, gen.cycle(gen.Seq(ops)))),
        gen.nemesis_gen(gen.Seq([{"type": "info", "f": "stop-partition",
                                  "value": None}])),
    ])
    t = noop_test(db=db, client=SlowAtomClient(db),
                  nemesis=nem.partitioner(),
                  generator=g, store_dir=store_dir,
                  time_limit=600.0,
                  # fsync every append: the WAL the parent inspects
                  # after SIGKILL must be fully durable
                  wal_fsync_interval=0,
                  metrics_interval=0)
    core.run(t)
    return 0


if __name__ == "__main__":
    sys.exit(main())
