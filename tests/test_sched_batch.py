"""Chunked interpreter scheduler + native scheduler lane
(doc/performance.md "Host ingest spine").

Pins the Tentpole-B contracts: the ``_SchedBus`` drains whole chunks
without reordering a single completion, the coalesced WAL lands the
same record sequence (and the same bytes) a per-op journal would, the
``sched_batch_ops`` knob and its env twin coerce tolerantly, and the
native ``sim_lane`` is bit-identical to the pure-Python simulated
scheduler — including the mid-op bail path.
"""
import json
import os
import random
import threading

import pytest

from jepsen_tpu import generator as gen
from jepsen_tpu.generator.interpreter import (
    DEFAULT_SCHED_BATCH_OPS, _SchedBus, run, ClientWorker,
)
from jepsen_tpu.generator.simulate import quick
from jepsen_tpu.journal import Journal


# -- _SchedBus ----------------------------------------------------------

def test_sched_bus_preserves_arrival_order():
    bus = _SchedBus(max_chunk=8)
    for i in range(20):
        bus.put(i)
    out = []
    while True:
        chunk = bus.drain_nowait()
        if not chunk:
            break
        assert len(chunk) <= 8
        out.extend(chunk)
    assert out == list(range(20))


def test_sched_bus_max_chunk_caps_and_remainder_stays():
    bus = _SchedBus(max_chunk=3)
    for i in range(5):
        bus.put(i)
    assert bus.drain_nowait() == [0, 1, 2]
    assert bus.qsize() == 2
    assert bus.drain(0.0) == [3, 4]


def test_sched_bus_drain_timeout_is_empty_list():
    bus = _SchedBus(max_chunk=4)
    assert bus.drain(0.01) == []  # the queue.Empty analog
    assert bus.drain_nowait() == []


def test_sched_bus_wakes_blocked_drain():
    bus = _SchedBus(max_chunk=4)
    got = []

    def producer():
        bus.put("x")

    t = threading.Thread(target=producer)
    t.start()
    got = bus.drain(5.0)
    t.join()
    assert got == ["x"]


def test_sched_bus_concurrent_producers_lose_nothing():
    bus = _SchedBus(max_chunk=7)
    n_workers, per = 8, 200

    def worker(wid):
        for i in range(per):
            bus.put((wid, i))

    ts = [threading.Thread(target=worker, args=(w,))
          for w in range(n_workers)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    seen = []
    while bus.qsize():
        seen.extend(bus.drain_nowait())
    assert len(seen) == n_workers * per
    # per-producer order is preserved even when interleaved
    for w in range(n_workers):
        assert [i for wid, i in seen if wid == w] == list(range(per))


# -- knob + env twin ----------------------------------------------------

class _EchoClient:
    def open(self, test, node):
        return self

    def setup(self, test):
        pass

    def invoke(self, test, op):
        return {**op, "type": "ok"}

    def teardown(self, test):
        pass

    def close(self, test):
        pass


def _threaded_run(n=300, conc=3, journal=None, **knobs):
    test = {"concurrency": conc, "client": _EchoClient(),
            "nodes": ["n1"], "name": "sched-batch",
            "generator": gen.clients(gen.limit(
                n, gen.Fn(lambda: {"f": "write", "value": 1}))),
            **({"_journal": journal} if journal is not None else {}),
            **knobs}
    return run(test)


@pytest.mark.parametrize("knob", [None, 0, 1, 64, "257", "bogus"])
def test_sched_batch_knob_accepts_all_forms(knob):
    kw = {} if knob is None else {"sched_batch_ops": knob}
    h = _threaded_run(n=120, **kw)
    ok = [o for o in h if o["type"] == "ok"]
    assert len(ok) == 120


def test_sched_batch_env_twin(monkeypatch):
    monkeypatch.setenv("JEPSEN_TPU_SCHED_BATCH", "16")
    h = _threaded_run(n=90)
    assert len([o for o in h if o["type"] == "ok"]) == 90


# -- WAL coalescing -----------------------------------------------------

def test_append_many_bytes_identical_to_per_op(tmp_path):
    """Journal.append_many is the coalesced landing the scheduler's
    wal_flush uses: for the same records it must write the exact bytes
    a per-op append loop would."""
    rng = random.Random(11)
    ops = [{"type": "ok", "f": "write", "value": rng.randint(-5, 5),
            "process": i % 4, "time": i,
            "u": "café \U0001f600", "big": 2**70 + i}
           for i in range(500)]
    p1, p2 = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    j1 = Journal(p1)
    for o in ops:
        j1.append(o)
    j1.close()
    j2 = Journal(p2)
    j2.append_many(ops)
    j2.close()
    assert p1.read_bytes() == p2.read_bytes()


def test_batched_wal_order_matches_history(tmp_path):
    """Under chunked scheduling the journal must still receive every
    history-bound record in exact history order — coalescing batches
    the WRITES, never reorders the records."""
    wal = tmp_path / "history.wal.jsonl"
    j = Journal(wal)
    h = _threaded_run(n=400, conc=4, journal=j, sched_batch_ops=32)
    j.close()
    recs = [json.loads(ln) for ln in wal.read_bytes().splitlines()]
    want = [o for o in h if o.get("type") in
            ("invoke", "ok", "fail", "info")]
    assert recs == want


def test_per_op_fallback_wal_order_matches_history(tmp_path):
    wal = tmp_path / "history.wal.jsonl"
    j = Journal(wal)
    h = _threaded_run(n=200, conc=4, journal=j, sched_batch_ops=0)
    j.close()
    recs = [json.loads(ln) for ln in wal.read_bytes().splitlines()]
    assert recs == [o for o in h if o.get("type") in
                    ("invoke", "ok", "fail", "info")]


# -- native scheduler lane ---------------------------------------------

def _lane_available():
    from jepsen_tpu.history_ir import ingest
    if ingest.sim_lane() is None:
        pytest.skip("native scheduler lane unavailable")


def _fingerprint(h):
    # key ORDER is part of the contract (json/repr observability)
    return [list(op.items()) for op in h]


def _mk_plain(n):
    return gen.limit(n, gen.Fn(lambda: {"f": "write", "value": 1}))


def _mk_bail(n):
    cnt = {"i": 0}

    def f():
        cnt["i"] += 1
        if cnt["i"] % 7 == 0:
            # explicit process: the lane can't simulate it → mid-op bail
            return {"f": "write", "value": cnt["i"], "process": 0}
        if cnt["i"] > n:
            return None
        return {"f": "write", "value": cnt["i"]}

    return gen.limit(n, gen.Fn(f))


@pytest.mark.parametrize("mk", [_mk_plain, _mk_bail])
@pytest.mark.parametrize("seed", [0, 7, 42])
@pytest.mark.parametrize("conc", [1, 2, 5])
def test_sim_lane_bit_identical_to_python(mk, seed, conc):
    """simulate() with the native lane vs the forced pure-Python loop:
    identical history (values, key order), identical end-of-run rng
    state — including generators that force the mid-op bail path."""
    _lane_available()
    from jepsen_tpu.history_ir import ingest

    def one(env):
        os.environ["JEPSEN_TPU_INGEST_NATIVE"] = env
        ingest.reset()
        try:
            test = {"concurrency": conc, "name": "lane-diff"}
            stats = {}
            h = quick(test, mk(60), seed=seed, stats=stats)
            return _fingerprint(h), stats
        finally:
            os.environ.pop("JEPSEN_TPU_INGEST_NATIVE", None)
            ingest.reset()

    assert one("1") == one("0")


def test_sim_lane_exception_folds_back_and_propagates():
    """An f() that raises mid-lane must surface the exception AND leave
    steps/rng folded back exactly like the pure loop."""
    _lane_available()
    from jepsen_tpu.history_ir import ingest

    def one(env):
        os.environ["JEPSEN_TPU_INGEST_NATIVE"] = env
        ingest.reset()
        try:
            cnt = {"i": 0}

            def f():
                cnt["i"] += 1
                if cnt["i"] == 13:
                    raise RuntimeError("boom")
                return {"f": "write", "value": cnt["i"]}

            try:
                quick({"concurrency": 3, "name": "lane-raise"},
                      gen.limit(50, gen.Fn(f)), seed=3)
            except RuntimeError as e:
                return cnt["i"], str(e)
            pytest.fail("exception did not propagate")
        finally:
            os.environ.pop("JEPSEN_TPU_INGEST_NATIVE", None)
            ingest.reset()

    assert one("1") == one("0")
