"""Causal-trace tier: run-wide spans, Perfetto export, flight recorder
(doc/observability.md "Causal trace").

Covers the tentpole's load-bearing claims: concurrent emission never
tears the streamed JSON, the flight ring's wraparound is exact, a
SIGKILL'd ``--trace`` run leaves a loadable trace prefix AND a
flight-recorder dump (the stall watchdog's), the offline
``jepsen-tpu trace`` derivation mints the SAME per-op trace ids as the
live stream, and an invalid run's explain instant links back to the
anomalous op's dispatch slice by trace id. Satellite regressions:
``tracing.Tracer``'s per-tracer seeded RNG and ``TracedClient``'s
symmetric open peeling (the two-open pin).
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
from pathlib import Path

import pytest

import jepsen_tpu.generator as gen
from jepsen_tpu import core, nemesis as nemesis_mod, store, tracing
from jepsen_tpu import trace as trace_mod
from jepsen_tpu.checker.linearizable import linearizable
from jepsen_tpu.fakes import AtomClient, AtomDB, noop_test
from jepsen_tpu.trace.flight import FlightRecorder
from jepsen_tpu.trace.perfetto import PerfettoSink, read_trace_events

pytestmark = pytest.mark.trace


def _strict_load(path) -> list:
    """A cleanly-closed trace.json must be STRICT JSON."""
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    assert isinstance(data, list)
    return data


def _track_names(events) -> dict:
    return {ev["tid"]: ev["args"]["name"] for ev in events
            if ev.get("ph") == "M" and ev.get("name") == "thread_name"}


def _tracks_used(events) -> set:
    names = _track_names(events)
    return {names[ev["tid"]] for ev in events
            if ev.get("ph") != "M" and ev.get("tid") in names}


def _op_ids(events) -> set:
    """{(f, trace_id)} of the op slices (X live/derived, B in-flight)."""
    return {(ev["args"]["f"], ev["args"]["trace_id"]) for ev in events
            if ev.get("ph") in ("B", "X")
            and "trace_id" in (ev.get("args") or {})}


# ---------------------------------------------------------------------------
# Model basics
# ---------------------------------------------------------------------------

class TestTraceIds:
    def test_pure_function_of_process_and_time(self):
        assert trace_mod.trace_id_for(3, 12345) == \
            trace_mod.trace_id_for(3, 12345)
        assert trace_mod.trace_id_for(3, 12345) != \
            trace_mod.trace_id_for(4, 12345)
        assert trace_mod.trace_id_for(3, 12345) != \
            trace_mod.trace_id_for(3, 12346)

    def test_null_tracer_is_inert(self):
        t = trace_mod.NULL_TRACER
        assert not t.enabled and t.op_sink() is None
        t.instant("scheduler", "stall")  # no-ops, never raises
        t.window_begin("nemesis", "net", wid="w")
        with t.span("checker-ladder", "rung"):
            pass
        assert t.dump_flight("/nonexistent/x", reason="test") is False


class TestKnobs:
    def test_trace_enabled_coercion(self, monkeypatch):
        monkeypatch.delenv("JEPSEN_TPU_TRACE", raising=False)
        assert trace_mod.trace_enabled({"trace": True}) is True
        assert trace_mod.trace_enabled({"trace": "yes"}) is True
        assert trace_mod.trace_enabled({"trace": 0}) is False
        assert trace_mod.trace_enabled({}) is False
        # garbage reads as unset, then the env twin decides
        monkeypatch.setenv("JEPSEN_TPU_TRACE", "1")
        assert trace_mod.trace_enabled({"trace": "banana"}) is True
        assert trace_mod.trace_enabled({}) is True
        monkeypatch.setenv("JEPSEN_TPU_TRACE", "off")
        assert trace_mod.trace_enabled({}) is False

    def test_flight_capacity_coercion(self, monkeypatch):
        monkeypatch.delenv("JEPSEN_TPU_FLIGHT_RECORDER_EVENTS",
                           raising=False)
        assert trace_mod.flight_recorder_events({}) == \
            trace_mod.DEFAULT_FLIGHT_EVENTS
        assert trace_mod.flight_recorder_events(
            {"flight_recorder_events": 16}) == 16
        assert trace_mod.flight_recorder_events(
            {"flight_recorder_events": "64"}) == 64
        assert trace_mod.flight_recorder_events(
            {"flight_recorder_events": 0}) == 0
        assert trace_mod.flight_recorder_events(
            {"flight_recorder_events": "garbage"}) == \
            trace_mod.DEFAULT_FLIGHT_EVENTS
        monkeypatch.setenv("JEPSEN_TPU_FLIGHT_RECORDER_EVENTS", "8")
        assert trace_mod.flight_recorder_events({}) == 8

    def test_preflight_rows(self):
        from jepsen_tpu.analysis import preflight as preflight_mod
        t = core.prepare_test(noop_test(
            flight_recorder_events="garbage", trace="banana"))
        codes = {d.code for d in preflight_mod.preflight(t)}
        assert "KNB001" in codes

    def test_zero_capacity_disables_recorder(self):
        t = trace_mod.for_test({"flight_recorder_events": 0})
        assert t is trace_mod.NULL_TRACER


# ---------------------------------------------------------------------------
# Perfetto sink
# ---------------------------------------------------------------------------

class TestPerfettoSink:
    def test_strict_json_on_close_and_prefix_without(self, tmp_path):
        p = tmp_path / "t.json"
        t = trace_mod.RunTracer(perfetto=PerfettoSink(p))
        t.instant("scheduler", "stall", args={"idle_s": 1})
        with t.span("checker-ladder", "rung", args={"backend": "cpu"}):
            pass
        t.close()
        evs = _strict_load(p)
        assert {e.get("ph") for e in evs} >= {"M", "i", "X"}
        # a torn file (simulated kill: drop the terminator and half a
        # line) still yields every complete line
        torn = tmp_path / "torn.json"
        body = p.read_text().splitlines()
        torn.write_text("\n".join(body[:-2]) + '\n{"ph":"i","na')
        assert len(read_trace_events(torn)) == len(evs) - 1

    def test_concurrent_emission_never_tears(self, tmp_path):
        """Scheduler-style op sink + nemesis windows + checker instants
        from concurrent threads: every line parses, nothing interleaves
        mid-line."""
        p = tmp_path / "t.json"
        tracer = trace_mod.RunTracer(perfetto=PerfettoSink(p),
                                     flight=FlightRecorder(4096))
        tracer.set_op_origin(0)
        sink = tracer.op_sink()
        n_ops, n_aux = 500, 200

        def scheduler():
            for i in range(n_ops):
                op = {"process": i % 5, "f": "write", "time": i * 1000,
                      "type": "invoke"}
                sink((trace_mod.OP_BEGIN, i % 5, op))
                comp = {**op, "type": "ok", "time": i * 1000 + 500}
                sink((trace_mod.OP_COMPLETE, i % 5, comp, i * 1000))

        def nemesis():
            for i in range(n_aux):
                tracer.window_begin("nemesis", "net", wid=f"fault-{i}")
                tracer.window_end("nemesis", "net", wid=f"fault-{i}")

        def checker():
            for i in range(n_aux):
                tracer.instant("checker-ladder", "demote",
                               args={"backend": "b", "reason": "r"})

        threads = [threading.Thread(target=f)
                   for f in (scheduler, nemesis, checker)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        tracer.close()
        evs = _strict_load(p)
        by_ph: dict = {}
        for ev in evs:
            by_ph[ev["ph"]] = by_ph.get(ev["ph"], 0) + 1
        assert by_ph["X"] == n_ops          # one slice per completed op
        assert by_ph["b"] == by_ph["e"] == n_aux
        assert by_ph["i"] == n_aux

    def test_op_slice_carries_dispatch_trace_id(self, tmp_path):
        p = tmp_path / "t.json"
        tracer = trace_mod.RunTracer(perfetto=PerfettoSink(p))
        tracer.set_op_origin(1_000_000)
        op = {"process": 2, "f": "read", "time": 5_000_000,
              "type": "invoke"}
        comp = {**op, "type": "ok", "time": 7_000_000}
        tracer.op_sink()((trace_mod.OP_COMPLETE, 2, comp, 5_000_000))
        tracer.close()
        (x,) = [e for e in _strict_load(p) if e["ph"] == "X"]
        assert x["args"]["trace_id"] == trace_mod.trace_id_for(2, 5_000_000)
        assert x["ts"] == 1_000_000 + 5_000
        assert x["dur"] == 2_000
        assert x["name"] == "read"


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------

class TestFlightRecorder:
    def test_wraparound_exactness(self, tmp_path):
        fr = FlightRecorder(16)
        for i in range(40):
            fr.record({"ph": "i", "track": "scheduler", "name": "stall",
                       "ts": i, "args": {"i": i}})
        snap = fr.snapshot()
        assert [e["args"]["i"] for e in snap] == list(range(24, 40))
        assert fr.recorded == 16
        out = tmp_path / "fr.jsonl"
        assert fr.dump(out, reason="test")
        lines = [json.loads(x) for x in out.read_text().splitlines()]
        header, rows = lines[0], lines[1:]
        assert header["flight_recorder"] and header["reason"] == "test"
        assert header["capacity"] == 16 and header["retained"] == 16
        assert [r["args"]["i"] for r in rows] == list(range(24, 40))

    def test_dump_expands_tuples_and_subsumes_completed(self, tmp_path):
        fr = FlightRecorder(32)
        fr.op_origin_us = 10_000_000
        done = {"process": 0, "f": "write", "time": 1_000_000,
                "type": "invoke"}
        fr.record((trace_mod.OP_BEGIN, 0, done))
        fr.record((trace_mod.OP_COMPLETE, 0,
                   {**done, "type": "ok", "time": 2_000_000}, 1_000_000))
        hung = {"process": 1, "f": "read", "time": 1_500_000,
                "type": "invoke"}
        fr.record((trace_mod.OP_BEGIN, 1, hung))  # still in flight
        out = tmp_path / "fr.jsonl"
        assert fr.dump(out, reason="stall")
        rows = [json.loads(x) for x in out.read_text().splitlines()][1:]
        phs = [(r["ph"], r.get("name")) for r in rows]
        # the completed op is ONE X slice; the hung op stays an open B
        assert phs == [("X", "write"), ("B", "read")]
        assert rows[0]["args"]["trace_id"] == \
            trace_mod.trace_id_for(0, 1_000_000)
        assert rows[1]["args"]["trace_id"] == \
            trace_mod.trace_id_for(1, 1_500_000)
        assert rows[1]["ts"] == 10_000_000 + 1_500

    def test_appender_is_ring_append(self):
        fr = FlightRecorder(4)
        app = fr.appender()
        for i in range(6):
            app(("B", 0, {"time": i}))
        assert fr.recorded == 4
        assert [ev[2]["time"] for ev in fr.snapshot()] == [2, 3, 4, 5]


# ---------------------------------------------------------------------------
# E2E: traced fake runs
# ---------------------------------------------------------------------------

def _register_test(tmp, n_ops=60, client=None, checker=None, **overrides):
    db = AtomDB()
    return noop_test(
        name="traced", db=db,
        client=client if client is not None else AtomClient(db),
        concurrency=5, store_dir=str(tmp), trace=True,
        generator=gen.clients(gen.limit(n_ops, gen.mix([
            gen.repeat({"f": "read"}),
            lambda test, ctx: {"f": "write",
                               "value": ctx.rng.randrange(5)},
        ]))),
        checker=checker if checker is not None
        else linearizable(accelerator="cpu"),
        **overrides)


class TestTracedRun:
    def test_clean_run_trace_and_no_flight_dump(self, tmp_path):
        result = core.run(_register_test(tmp_path))
        assert result["results"]["valid?"] is True
        d = store.test_dir(result)
        evs = _strict_load(d / "trace.json")
        tracks = _tracks_used(evs)
        assert {"worker-0", "scheduler", "checker-ladder"} <= tracks
        assert len(_op_ids(evs)) == 60
        # clean run: the flight recorder never dumps
        assert not (d / "flight-recorder.jsonl").exists()
        # the legacy client span log carries the run-trace id attribute
        spans = [json.loads(x) for x in
                 (d / "trace.jsonl").read_text().splitlines()]
        traced = [s for s in spans if "trace-id" in s["attributes"]]
        assert traced, "client spans must carry the causal trace id"
        live_ids = {tid for _f, tid in _op_ids(evs)}
        assert {s["attributes"]["trace-id"] for s in traced} <= live_ids

    def test_offline_derive_matches_live_ids(self, tmp_path):
        result = core.run(_register_test(tmp_path))
        d = store.test_dir(result)
        live = _op_ids(_strict_load(d / "trace.json"))
        from jepsen_tpu.trace.derive import derive_run_trace
        out = derive_run_trace(d)
        # a live trace.json exists, so the derived one must not clobber
        assert out.name == "trace-derived.json"
        assert _op_ids(_strict_load(out)) == live

    def test_derive_concurrency_fallback_survives_renumbering(self):
        """No test.json: concurrency falls back to peak-in-flight,
        which crash renumbering cannot inflate (review pin)."""
        from jepsen_tpu.trace.derive import _concurrency
        ops = []
        for p in (0, 1, 2):
            ops.append({"type": "invoke", "process": p, "f": "r",
                        "time": p * 10})
        for p in (0, 1, 2):
            ops.append({"type": "info" if p == 0 else "ok",
                        "process": p, "f": "r", "time": 100 + p})
        # worker 0 renumbers 0 -> 3 -> 6 across two crashes
        for p in (3, 6):
            ops.append({"type": "invoke", "process": p, "f": "r",
                        "time": 200 + p})
            ops.append({"type": "info", "process": p, "f": "r",
                        "time": 300 + p})
        assert _concurrency({}, ops) == 3

    def test_derive_late_rows_join_on_invoke_time(self, tmp_path):
        """late.jsonl rows re-stamp "time" at quarantine; the derived
        instant must mint its id from the preserved invoke_time so it
        joins the dispatch slice (review pin)."""
        (tmp_path / "test.json").write_text(json.dumps(
            {"concurrency": 2, "start_time": "20260804T000000.000"}))
        (tmp_path / "history.jsonl").write_text(
            json.dumps({"type": "invoke", "process": 0, "f": "read",
                        "time": 1_000_000}) + "\n"
            + json.dumps({"type": "ok", "process": 0, "f": "read",
                          "time": 2_000_000}) + "\n")
        (tmp_path / "late.jsonl").write_text(json.dumps(
            {"type": "ok", "process": 7, "f": "read", "late": True,
             "worker": 1, "invoke_time": 123_000,
             "time": 999_000}) + "\n")
        from jepsen_tpu.trace.derive import derive_run_trace
        evs = _strict_load(derive_run_trace(tmp_path))
        (late,) = [e for e in evs if e.get("ph") == "i"
                   and e.get("name") == "late-completion"]
        assert late["args"]["trace_id"] == \
            trace_mod.trace_id_for(7, 123_000)

    def test_trace_cli_on_untraced_run(self, tmp_path):
        t = _register_test(tmp_path)
        t["trace"] = False
        result = core.run(t)
        d = store.test_dir(result)
        assert not (d / "trace.json").exists()
        from jepsen_tpu.cli import noop_main
        rc = noop_main(["trace", str(d)])
        assert rc == 0
        evs = _strict_load(d / "trace.json")  # retroactively traceable
        assert len(_op_ids(evs)) == 60

    def test_explain_instant_links_to_dispatch_slice(self, tmp_path):
        class StaleReadClient(AtomClient):
            """Reads return a value nobody ever wrote: the planted
            linearizability anomaly."""

            def invoke(self, test, op):
                if op.get("f") == "read":
                    return {**op, "type": "ok", "value": 4}
                return super().invoke(test, op)

        db = AtomDB()
        t = _register_test(tmp_path, client=StaleReadClient(db))
        result = core.run(t)
        assert result["results"]["valid?"] is False
        d = store.test_dir(result)
        evs = _strict_load(d / "trace.json")
        explains = [e for e in evs if e.get("ph") == "i"
                    and e.get("name") == "explain"]
        assert explains, "invalid run must emit the explain instant"
        link = explains[0]["args"]["trace_id"]
        dispatch = {tid: f for f, tid in _op_ids(evs)}
        assert link in dispatch, \
            "explain must link to a dispatched op's trace id"
        assert dispatch[link] == explains[0]["args"]["f"]
        assert "checker" in _tracks_used(evs)

    def test_six_tracks_with_nemesis_and_live_daemon(self, tmp_path):
        """The acceptance e2e: one --trace run with a nemesis and a
        concurrently-polling live daemon leaves >= 6 distinct tracks
        spanning workers, scheduler, nemesis, checker ladder, and
        live (the checkpoint track is pinned separately at unit level
        — a quick-lane history never spans a frontier chunk)."""

        class PacedClient(AtomClient):
            def invoke(self, test, op):
                time.sleep(0.004)
                return super().invoke(test, op)

        db = AtomDB()
        g = gen.Seq([
            gen.nemesis_gen(gen.Seq([{"type": "info",
                                      "f": "start-partition",
                                      "value": None}])),
            gen.clients(gen.limit(150, gen.mix([
                gen.repeat({"f": "read"}),
                lambda test, ctx: {"f": "write",
                                   "value": ctx.rng.randrange(5)},
            ]))),
            gen.nemesis_gen(gen.Seq([{"type": "info",
                                      "f": "stop-partition",
                                      "value": None}])),
        ])
        t = noop_test(name="traced", db=db, client=PacedClient(db),
                      concurrency=5, store_dir=str(tmp_path), trace=True,
                      nemesis=nemesis_mod.partitioner(),
                      generator=g,
                      checker=linearizable(accelerator="cpu"),
                      time_limit=120.0)
        from jepsen_tpu.live.daemon import LiveDaemon
        daemon = LiveDaemon(store_root=str(tmp_path), poll_s=0.05)
        daemon.start()
        try:
            result = core.run(t)
        finally:
            daemon.stop()
        assert result["results"]["valid?"] is True
        d = store.test_dir(result)
        evs = _strict_load(d / "trace.json")
        tracks = _tracks_used(evs)
        assert {"scheduler", "nemesis", "checker-ladder",
                "live"} <= tracks, tracks
        assert {n for n in tracks if n.startswith("worker-")}, tracks
        assert len(tracks) >= 6, tracks
        # the durable fault registry's window slices ride the nemesis
        # track: one begin at record, one end at the stop's heal-mark
        assert any(e.get("ph") == "b" for e in evs)
        assert any(e.get("ph") == "e" for e in evs)


class TestCheckpointTrack:
    def test_frontier_ckpt_write_and_resume_instants(self, tmp_path,
                                                     monkeypatch):
        from jepsen_tpu.checker import checkpoint as ckpt_mod
        from jepsen_tpu.checker.linear_cpu import cas_register_step_py
        from jepsen_tpu.checker.linear_encode import encode_register_ops
        history = []
        for i in range(200):
            history.append({"type": "invoke", "process": 0, "f": "write",
                            "value": i % 5, "time": i * 1000})
            history.append({"type": "ok", "process": 0, "f": "write",
                            "value": i % 5, "time": i * 1000 + 500})
        stream = encode_register_ops(history)
        monkeypatch.setattr(ckpt_mod, "FRONTIER_CHUNK_EVENTS", 64)
        p = tmp_path / "t.json"
        tracer = trace_mod.RunTracer(perfetto=PerfettoSink(p))
        with trace_mod.use(tracer):
            cs = ckpt_mod.CheckpointStore(tmp_path / "check.ckpt",
                                          interval_s=0.0)
            res = ckpt_mod.checkpointed_check_stream(
                stream, cas_register_step_py, 0, cs)
            assert res.valid is True and cs.writes >= 1
            # the surviving (uncleared) ckpt resumes -> resume instant
            cs2 = ckpt_mod.CheckpointStore(tmp_path / "check.ckpt",
                                           interval_s=None)
            res2 = ckpt_mod.checkpointed_check_stream(
                stream, cas_register_step_py, 0, cs2)
            assert res2.valid is True
        tracer.close()
        evs = _strict_load(p)
        names = [e.get("name") for e in evs if e.get("ph") == "i"]
        assert "ckpt-write" in names and "ckpt-resume" in names
        assert _tracks_used(evs) == {"checkpoint"}


# ---------------------------------------------------------------------------
# SIGKILL chaos: loadable prefix + stall flight dump
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_sigkill_leaves_loadable_trace_and_flight_dump(tmp_path):
    """A hung --trace run trips the stall watchdog (flight dump) and is
    then SIGKILLed: trace.json's complete-line prefix must stay
    Perfetto-loadable and flight-recorder.jsonl must hold the last ~N
    events of causal context."""
    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "trace_worker.py")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen([sys.executable, worker, str(tmp_path)],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True, env=env)
    flight = None
    deadline = time.monotonic() + 120
    try:
        while time.monotonic() < deadline:
            dumps = list(tmp_path.glob("noop/*/flight-recorder.jsonl"))
            if dumps:
                flight = dumps[0]
                break
            if proc.poll() is not None:
                out = proc.stdout.read()
                pytest.fail(f"worker exited early ({proc.returncode}):\n"
                            f"{out[-4000:]}")
            time.sleep(0.05)
        assert flight is not None, "stall watchdog never dumped"
        os.kill(proc.pid, signal.SIGKILL)
    finally:
        if proc.poll() is None:
            proc.kill()
        proc.wait(timeout=30)

    run_dir = flight.parent
    # the streamed trace: no terminator (the run was killed), but every
    # complete line parses and the op slices are there
    raw = (run_dir / "trace.json").read_text()
    assert not raw.rstrip().endswith("]")
    evs = read_trace_events(run_dir / "trace.json")
    assert evs, "trace prefix must hold events"
    assert any(ev.get("ph") == "X" for ev in evs)
    assert any(ev.get("ph") == "M" for ev in evs)
    # the flight dump: header + expanded events, hung op still open
    rows = [json.loads(x) for x in flight.read_text().splitlines()]
    assert rows[0]["flight_recorder"] is True
    assert rows[0]["reason"] == "stall"
    assert any(r.get("ph") == "X" for r in rows[1:])
    assert any(r.get("ph") == "B" for r in rows[1:]), \
        "the hung op must appear as an open dispatch slice"
    # the stall watchdog's own instant rides the scheduler track
    assert any(r.get("ph") == "i" and r.get("name") == "stall"
               for r in rows[1:])


# ---------------------------------------------------------------------------
# Fatal-path dump
# ---------------------------------------------------------------------------

def test_fatal_run_dumps_flight_recorder(tmp_path):
    class ExplodingDB(AtomDB):
        def setup(self, test, node):
            raise RuntimeError("db refused to start (as designed)")

    db = ExplodingDB()
    t = noop_test(name="fatal", db=db, client=AtomClient(db),
                  concurrency=2, store_dir=str(tmp_path),
                  generator=gen.clients(gen.limit(
                      5, gen.repeat({"f": "read"}))))
    with pytest.raises(Exception):
        core.run(t)
    dumps = list(tmp_path.glob("fatal/*/flight-recorder.jsonl"))
    assert dumps, "a fatal run must leave a flight dump"
    rows = [json.loads(x) for x in dumps[0].read_text().splitlines()]
    assert rows[0]["reason"] == "fatal"


def test_preflight_failure_is_dump_exempt(tmp_path):
    from jepsen_tpu.analysis.preflight import PreflightFailed
    t = noop_test(name="rejected", store_dir=str(tmp_path),
                  op_timeout_s="banana",
                  generator=gen.clients(gen.limit(
                      5, gen.repeat({"f": "read"}))))
    with pytest.raises(PreflightFailed):
        core.run(t)
    assert not list(tmp_path.glob("rejected/*/flight-recorder.jsonl"))


# ---------------------------------------------------------------------------
# Satellites: the legacy client-span tracer
# ---------------------------------------------------------------------------

class TestLegacyTracerSatellites:
    def test_seeded_rng_is_per_tracer_and_deterministic(self, tmp_path):
        a = tracing.Tracer(str(tmp_path / "a.jsonl"), seed=42)
        b = tracing.Tracer(str(tmp_path / "b.jsonl"), seed=42)
        ids_a = [a._new_id() for _ in range(5)]
        ids_b = [b._new_id() for _ in range(5)]
        assert ids_a == ids_b
        # consuming the GLOBAL random module must not perturb a tracer
        import random
        c = tracing.Tracer(str(tmp_path / "c.jsonl"), seed=42)
        random.random()
        assert [c._new_id() for _ in range(5)] == ids_a
        for t in (a, b, c):
            t.close()

    def test_two_open_keeps_tracing_without_double_wrap(self, tmp_path):
        class SelfWrappingClient(AtomClient):
            """A suite shape that hands back an ALREADY-traced client
            from open() — the re-open path that used to drop/stack
            tracers."""

            def open(self, test, node):
                fresh = super().open(test, node)
                return tracing.TracedClient(
                    fresh, tracing.Tracer(None), node)

        db = AtomDB()
        tracer = tracing.Tracer(str(tmp_path / "t.jsonl"), seed=7)
        c0 = tracing.TracedClient(SelfWrappingClient(db), tracer)
        c1 = c0.open({}, "n1")
        c2 = c1.open({}, "n1")
        for c in (c1, c2):
            assert isinstance(c, tracing.TracedClient)
            # exactly ONE wrapper layer, and it is OUR tracer
            assert not isinstance(c.inner, tracing.TracedClient)
            assert c.tracer is tracer
        c2.invoke({}, {"f": "read", "process": 0, "time": 1})
        tracer.close()
        spans = [json.loads(x) for x in
                 (tmp_path / "t.jsonl").read_text().splitlines()]
        assert [s["name"] for s in spans] == ["invoke/read"]


# ---------------------------------------------------------------------------
# Web summary
# ---------------------------------------------------------------------------

def test_web_trace_section_renders_summary(tmp_path):
    p = tmp_path / "trace.json"
    tracer = trace_mod.RunTracer(perfetto=PerfettoSink(p))
    tracer.set_op_origin(0)
    sink = tracer.op_sink()
    op = {"process": 0, "f": "write", "time": 1_000_000, "type": "invoke"}
    sink((trace_mod.OP_COMPLETE, 0, {**op, "type": "ok",
                                     "time": 3_000_000}, 1_000_000))
    tracer.instant("checker-ladder", "demote",
                   args={"backend": "pallas-matrix",
                         "reason": "watchdog-timeout"})
    tracer.close()
    from jepsen_tpu.web import _trace_section
    html = _trace_section("traced/20260101T000000.000", tmp_path)
    assert "causal trace" in html and "trace.json" in html
    assert "worker-0" in html
    assert "pallas-matrix (watchdog-timeout)" in html
