"""Postgres-protocol suite family tests: cockroachdb, stolon, yugabyte
plus the widened postgres suite — test-map shapes, DB-automation
command shapes over the dummy remote, fake-mode runs for the new
monotonic/sequential workloads, and the shared PG client's workload
bodies against a stub connection."""
import pytest

from jepsen_tpu import control
from jepsen_tpu.suites import cockroachdb, postgres, stolon, yugabyte
from jepsen_tpu.suites._pg_client import PGSuiteClient, seq_table
from jepsen_tpu.workloads import monotonic, sequential

from conftest import run_fake  # noqa: E402

NODES = ["n1", "n2", "n3", "n4", "n5"]


# ---------------------------------------------------------------------------
# cluster strings / command shapes
# ---------------------------------------------------------------------------

def test_cockroach_join_spec():
    assert cockroachdb.join_spec({"nodes": NODES}).startswith("n1:26257,")


def test_cockroach_db_commands():
    t = {"nodes": NODES, "ssh": {"dummy": True}}
    remote = control.default_remote(t)
    db = cockroachdb.CockroachDB()
    try:
        control.on("n2", t, lambda: db.start(t, "n2"))
        joined = " ".join(str(x) for x in remote.log)
        assert "--insecure" in joined
        assert "--join=n1:26257,n2:26257,n3:26257,n4:26257,n5:26257" in joined
        assert "--advertise-addr=n2:26257" in joined
    finally:
        control.disconnect_all(t)


def test_stolon_topology():
    t = {"nodes": NODES}
    assert stolon.pg_id(t, "n3") == "pg3"
    assert stolon.store_endpoints(t).startswith("http://n1:2379,")
    spec = stolon.initial_cluster_spec(t)
    assert spec["synchronousReplication"] is True
    assert spec["maxStandbysPerSender"] == 4


def test_stolon_daemon_commands():
    t = {"nodes": NODES, "ssh": {"dummy": True}}
    remote = control.default_remote(t)
    db = stolon.StolonDB()
    try:
        control.on("n2", t, lambda: db.start_keeper(t, "n2"))
        control.on("n2", t, lambda: db.start_proxy(t, "n2"))
        joined = " ".join(str(x) for x in remote.log)
        assert "--uid pg2" in joined
        assert "--store-backend etcdv3" in joined
        assert "--pg-port 5433" in joined
        assert "stolon-proxy" in joined
    finally:
        control.disconnect_all(t)


def test_yugabyte_masters():
    t = {"nodes": NODES}
    assert yugabyte.master_nodes(t) == ["n1", "n2", "n3"]
    assert yugabyte.master_addresses(t) == "n1:7100,n2:7100,n3:7100"
    assert set(yugabyte.workloads_expected_to_pass()) == \
        set(yugabyte.YSQL_WORKLOADS)


def test_yugabyte_ycql_workloads_resolve():
    """The YCQL api split resolves every YCQL workload to a kit
    (yugabyte/core.clj:74-85); unknown names are rejected."""
    import pytest
    base = {"nodes": ["n1", "n2", "n3"], "concurrency": 3}
    for name in yugabyte.YCQL_WORKLOADS:
        w = yugabyte.ycql_workload(name, base)
        assert "generator" in w and "checker" in w, name
    assert yugabyte.ycql_workload("set-index", base).get("set-index") is True
    with pytest.raises(ValueError):
        yugabyte.ycql_workload("monotonic", base)


# ---------------------------------------------------------------------------
# fake-mode lifecycle: monotonic & sequential
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_cockroach_fake_monotonic_run():
    result = run_fake(cockroachdb.cockroachdb_test, workload="monotonic")
    assert result["results"]["valid?"] is True, result["results"]
    finals = [op for op in result["history"]
              if op.get("f") == "read-all" and op.get("type") == "ok"]
    assert finals and finals[-1]["value"], "final read must return rows"


@pytest.mark.slow
def test_cockroach_fake_sequential_run():
    result = run_fake(cockroachdb.cockroachdb_test, workload="sequential")
    assert result["results"]["valid?"] is True, result["results"]


@pytest.mark.slow
def test_stolon_fake_append_run():
    result = run_fake(stolon.stolon_test, workload="append")
    assert result["results"]["valid?"] is True, result["results"]


@pytest.mark.slow
def test_yugabyte_fake_bank_run():
    result = run_fake(yugabyte.yugabyte_test, workload="bank")
    assert result["results"]["valid?"] is True, result["results"]


@pytest.mark.slow
def test_postgres_fake_monotonic_run():
    result = run_fake(postgres.postgres_test, workload="monotonic")
    assert result["results"]["valid?"] is True, result["results"]


# ---------------------------------------------------------------------------
# monotonic checker semantics
# ---------------------------------------------------------------------------

def _final_read(rows):
    return [{"type": "ok", "f": "read-all", "value": rows}]


def test_monotonic_checker_accepts_increasing():
    out = monotonic.checker().check(
        {}, _final_read([[0, "1.0"], [1, "2.0"], [2, "10.0"]]), {})
    assert out["valid?"] is True


def test_monotonic_checker_flags_off_order():
    # value 2 committed at an earlier timestamp than value 1
    out = monotonic.checker().check(
        {}, _final_read([[0, "1.0"], [2, "2.0"], [1, "3.0"]]), {})
    assert out["valid?"] is False
    assert out["off-order-count"] >= 1


def test_monotonic_checker_numeric_ts_comparison():
    # "10.0" must sort after "2.0" (Decimal, not lexicographic)
    out = monotonic.checker().check(
        {}, _final_read([[0, "2.0"], [1, "10.0"]]), {})
    assert out["valid?"] is True


def test_monotonic_checker_flags_lost_inserts():
    history = [{"type": "ok", "f": "inc", "value": 5}] + \
        _final_read([[0, "1.0"]])
    out = monotonic.checker().check({}, history, {})
    assert out["valid?"] is False
    assert out["lost"] == [5]


def test_monotonic_unparseable_ts_is_unknown():
    # a parsing problem must not masquerade as a serializability verdict
    out = monotonic.checker().check(
        {}, _final_read([[0, "1.0"], [1, "garbage"], [2, "2.0"]]), {})
    assert out["valid?"] == "unknown"
    assert out["unparseable-count"] == 1
    assert out["unparseable-ts"] == [[1, "garbage"]]


def test_monotonic_equal_ts_is_ambiguous_not_off_order():
    out = monotonic.checker().check(
        {}, _final_read([[0, "1.0"], [2, "2.0"], [1, "2.0"]]), {})
    assert out["valid?"] is True
    assert out["off-order-count"] == 0
    assert out["ambiguous-count"] == 1


def test_monotonic_wallclock_plus_clock_nemesis_is_unknown():
    class _C:
        logical_ts = False

    nemesis_op = {"type": "info", "process": "nemesis", "f": "bump",
                  "value": {"n1": 1000}}
    h = [nemesis_op] + _final_read([[0, "1.0"], [2, "2.0"], [1, "3.0"]])
    out = monotonic.checker().check({"client": _C()}, h, {})
    assert out["valid?"] == "unknown"
    assert out["off-order-count"] >= 1  # still reported, just not convicted
    # a logical/HLC timestamp keeps full conviction power
    class _L:
        logical_ts = True
    out = monotonic.checker().check({"client": _L()}, h, {})
    assert out["valid?"] is False


# ---------------------------------------------------------------------------
# sequential checker semantics
# ---------------------------------------------------------------------------

def test_sequential_trailing_nil():
    assert sequential.trailing_nil(["5_4", None]) is True
    assert not sequential.trailing_nil([None, "5_3"])
    assert not sequential.trailing_nil([None, None])
    assert not sequential.trailing_nil(["5_4", "5_3"])


def test_sequential_checker():
    chk = sequential.checker()
    good = {"type": "ok", "f": "read",
            "value": [5, [None, None, "5_2", "5_1", "5_0"]]}
    bad = {"type": "ok", "f": "read",
           "value": [5, ["5_4", None, "5_2", "5_1", "5_0"]]}
    assert chk.check({}, [good], {})["valid?"] is True
    out = chk.check({}, [good, bad], {})
    assert out["valid?"] is False and out["bad-read-count"] == 1


# ---------------------------------------------------------------------------
# the shared PG client against a stub connection
# ---------------------------------------------------------------------------

class StubConn:
    """Collects queries; PGConnection.query returns (rows, tag)."""

    def __init__(self, replies=()):
        self.queries: list[str] = []
        self.replies = dict(replies)

    def query(self, sql):
        self.queries.append(sql)
        for prefix, rows in self.replies.items():
            if sql.startswith(prefix):
                return rows, "SELECT"
        return [], "OK 0"

    def rowcount(self, tag):
        return 0

    def close(self):
        pass


def test_pg_client_mono_inc_uses_ts_expr():
    c = PGSuiteClient(ts_expr="cluster_logical_timestamp()")
    c.conn = StubConn({"SELECT MAX": [["4"]]})
    out = c.invoke({}, {"f": "inc", "type": "invoke", "value": None,
                        "process": 3})
    assert out["type"] == "ok" and out["value"] == 5
    insert = [q for q in c.conn.queries if q.startswith("INSERT INTO mono")]
    assert insert and "cluster_logical_timestamp()" in insert[0]
    assert c.conn.queries[-1] == "COMMIT"


def test_pg_client_read_all_keeps_ts_strings():
    c = PGSuiteClient()
    big = "1712000000000000000000000000.0000000001"
    c.conn = StubConn({"SELECT val, sts": [["0", "1.5"], ["1", big]]})
    out = c.invoke({}, {"f": "read-all", "type": "invoke", "value": None})
    assert out["value"] == [[0, "1.5"], [1, big]]  # precision preserved


def test_pg_client_sequential_ops():
    c = PGSuiteClient()
    c.conn = StubConn()
    out = c.invoke({"key-count": 3},
                   {"f": "write", "type": "invoke", "value": 7})
    assert out["type"] == "ok"
    inserts = [q for q in c.conn.queries if q.startswith("INSERT INTO seq_")]
    assert len(inserts) == 3
    assert "'7_0'" in inserts[0] and "'7_2'" in inserts[2]  # client order

    c.conn = StubConn()
    out = c.invoke({"key-count": 3},
                   {"f": "read", "type": "invoke", "value": 7})
    assert out["type"] == "ok"
    k, elements = out["value"]
    assert k == 7 and len(elements) == 3
    selects = [q for q in c.conn.queries if q.startswith("SELECT k FROM")]
    assert "'7_2'" in selects[0] and "'7_0'" in selects[2]  # reversed


def test_seq_table_stable():
    assert seq_table("5_0") == seq_table("5_0")
    assert seq_table("5_0").startswith("seq_")


@pytest.mark.slow
def test_cockroach_fake_adya_run():
    result = run_fake(cockroachdb.cockroachdb_test, workload="adya")
    assert result["results"]["valid?"] is True, result["results"]
    # the G2 test is only meaningful if inserts actually executed
    assert any(op.get("f") == "insert" and op.get("type") == "ok"
               for op in result["history"])


def test_pg_client_adya_insert():
    c = PGSuiteClient()
    c.conn = StubConn()  # empty pair → insert proceeds
    out = c.invoke({}, {"f": "insert", "type": "invoke",
                        "value": [3, 17, "a"]})
    assert out["type"] == "ok"
    assert any(q.startswith("INSERT INTO adya") for q in c.conn.queries)
    assert c.conn.queries[-1] == "COMMIT"

    c.conn = StubConn({"SELECT uid FROM adya": [["9"]]})  # occupied
    out = c.invoke({}, {"f": "insert", "type": "invoke",
                        "value": [3, 18, "b"]})
    assert out["type"] == "fail"
    assert any(q == "ROLLBACK" for q in c.conn.queries)


def test_pg_client_counter_add_checks_rowcount():
    class CounterStub(StubConn):
        def __init__(self, rc):
            super().__init__()
            self.rc = rc

        def query(self, sql):
            self.queries.append(sql)
            return [], "UPDATE"

        def rowcount(self, tag):
            return self.rc

    c = PGSuiteClient()
    c.conn = CounterStub(1)
    out = c.invoke({"counter": True},
                   {"f": "add", "type": "invoke", "value": 3})
    assert out["type"] == "ok"
    c.conn = CounterStub(0)  # row missing → the add did not apply
    out = c.invoke({"counter": True},
                   {"f": "add", "type": "invoke", "value": 3})
    assert out["type"] == "fail"


@pytest.mark.slow
def test_yugabyte_test_all_sweep_fake():
    """The test-all runner sweeps every workload expected to pass
    (yugabyte/core.clj:110-123 + cli.clj:429-515) in fake mode.

    This validates the sweep MECHANICS (per-workload test maps, store
    layout, exit codes), not real-time behavior — but the phases are
    wall-clock-limited, so on a heavily loaded machine a starved run
    can degenerate. Every workload is empty-phase-safe (verified by a
    0.02 s-limit sweep), yet one full-suite flake was observed under
    load; the sweep therefore gets a 2 s limit and one retry with the
    failing exit code surfaced, so a deterministic regression still
    fails twice and loudly."""
    import tempfile

    from jepsen_tpu.suites.yugabyte import main_all

    codes = []
    for _ in range(2):
        with tempfile.TemporaryDirectory() as tmp:
            code = main_all(["--no-ssh", "--time-limit", "2",
                             "--accelerator", "cpu", "--store-dir", tmp])
        codes.append(code)
        if code == 0:
            break
    assert codes[-1] == 0, f"sweep exit codes across attempts: {codes}"


def test_monotonic_unhashable_values_do_not_crash():
    h = _final_read([[[1, 2], "garbage"], [0, "1.0"], [1, "2.0"]])
    out = monotonic.checker().check({}, h, {})
    assert out["valid?"] == "unknown"   # unparseable row present
    assert out["unparseable-count"] == 1


def test_monotonic_scrambler_counts_as_clock_nemesis():
    class _C:
        logical_ts = False

    nem = {"type": "info", "process": "nemesis", "f": "scramble-clock"}
    h = [nem] + _final_read([[0, "1.0"], [2, "2.0"], [1, "3.0"]])
    out = monotonic.checker().check({"client": _C()}, h, {})
    assert out["valid?"] == "unknown"


def test_pg_client_comments_dispatch():
    """comments ops route to the sharded comment_N tables
    (cockroach/comments.clj:30-84): writes insert by id-table, reads
    union every table inside one txn."""
    from jepsen_tpu.suites._pg_client import COMMENT_TABLE_COUNT

    c = PGSuiteClient()
    c.conn = StubConn()
    out = c.invoke({"comments": True},
                   {"f": "write", "type": "invoke", "value": [3, 17]})
    assert out["type"] == "ok"
    assert any(q.startswith(f"INSERT INTO comment_{17 % COMMENT_TABLE_COUNT}")
               for q in c.conn.queries)

    c = PGSuiteClient()
    c.conn = StubConn({"SELECT id FROM comment_2": [["17"]],
                       "SELECT id FROM comment_5": [["5"]]})
    out = c.invoke({"comments": True},
                   {"f": "read", "type": "invoke", "value": [3, None]})
    assert out["type"] == "ok"
    assert out["value"] == [3, [5, 17]]
    selects = [q for q in c.conn.queries if q.startswith("SELECT id FROM")]
    assert len(selects) == COMMENT_TABLE_COUNT
    assert c.conn.queries[-1] == "COMMIT"


def test_pg_append_table_txn():
    """append-table txns route micro-ops to one table per key, create
    missing tables on demand, and retry the whole txn
    (yugabyte/ysql/append_table.clj:28-129 with-table)."""
    import re

    from jepsen_tpu.suites._pg_client import PGSuiteClient
    from jepsen_tpu.suites._postgres import PgError

    class ScriptedPG:
        def __init__(self):
            self.tables = {}
            self.sql = []
            self._snap = None

        def query(self, sql):
            self.sql.append(sql)
            if sql.startswith("BEGIN"):
                self._snap = {t: list(v) for t, v in self.tables.items()}
                return [], b""
            if sql.startswith("COMMIT"):
                self._snap = None
                return [], b""
            if sql.startswith("ROLLBACK"):
                if self._snap is not None:  # undo in-txn inserts
                    self.tables = self._snap
                    self._snap = None
                return [], b""
            m = re.search(r"CREATE TABLE IF NOT EXISTS (\w+)", sql)
            if m:
                self.tables.setdefault(m.group(1), [])
                return [], b""
            m = re.search(r"SELECT v FROM (\w+) ORDER BY k", sql)
            if m:
                t = m.group(1)
                if t not in self.tables:
                    raise PgError({"C": "42P01",
                                   "M": f'relation "{t}" does not exist'})
                return [[v] for v in self.tables[t]], b""
            m = re.search(r"INSERT INTO (\w+) \(v\) VALUES \((\d+)\)", sql)
            if m:
                t = m.group(1)
                if t not in self.tables:
                    raise PgError({"C": "42P01",
                                   "M": f'relation "{t}" does not exist'})
                self.tables[t].append(int(m.group(2)))
                return [], b""
            return [], b""

    c = PGSuiteClient.__new__(PGSuiteClient)
    c.isolation = "serializable"
    c.txn_style = "append-table"
    c._broken = False
    c.conn = ScriptedPG()
    op = {"f": "txn", "type": "invoke",
          "value": [["append", 1, 10], ["r", 1, None], ["append", 2, 20]]}
    out = c._txn(op)
    assert out["type"] == "ok"
    assert out["value"] == [["append", 1, 10], ["r", 1, [10]],
                            ["append", 2, 20]]
    assert set(c.conn.tables) == {"append_1", "append_2"}
    creates = [s for s in c.conn.sql if s.startswith("CREATE TABLE")]
    assert len(creates) == 2  # one per missing table, then retried

    # a non-42P01 error still maps through the standard sql-error path
    class AlwaysFails(ScriptedPG):
        def query(self, sql):
            if sql.startswith(("BEGIN", "ROLLBACK")):
                return [], b""
            raise PgError({"C": "40001", "M": "restart transaction"})

    c.conn = AlwaysFails()
    out = c._txn(op)
    assert out["type"] == "fail" and out["error"][0] == \
        "serialization-failure"


@pytest.mark.slow
def test_yugabyte_fake_append_table_run():
    from conftest import run_fake

    result = run_fake(yugabyte.yugabyte_test, workload="append-table")
    assert result["results"]["workload"]["valid?"] is True, (
        result["results"])
