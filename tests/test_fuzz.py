"""Schedule-fuzzer tier (doc/robustness.md "Schedule fuzzing").

Covers the ISSUE-18 acceptance surface:

* trial determinism: same schedule ⇒ byte-identical history, with the
  simulator's wall cap riding a :class:`StepClock` so machine load
  can't skew truncation (the same-seed/different-load differential);
* the satellite seams: ``FakeClusterState.mutate_knobs`` seeded knob
  mutation + the rate-aware settle window, tolerant ``fuzz_knob``
  coercion with ``JEPSEN_TPU_FUZZ_*`` env twins;
* schedule canonicalization/round-trip, corpus mutation determinism,
  fault×op interleaving edge extraction, checker-state
  ``coverage_probe()`` on :class:`FrontierSession` and the ladder,
  near-miss margin promotion;
* the generic PR-8 ddmin over fault windows, a planted-bug
  positive/negative pair, artifact landing + bit-identical replay,
  and whole-hunt determinism through the fleet verdict path;
* slow lane: the guided-vs-blind e2e — a seeded guided hunt finds and
  minimizes the interleaving-gated demo anomaly at a budget where
  blind random finds nothing.
"""
from __future__ import annotations

import json
import random

import pytest

pytestmark = pytest.mark.fuzz

OVERLAP_FAULTS = [{"kind": k, "start": 0.1, "dur": 0.4}
                  for k in ("net", "clock-rate", "pause", "membership")]


def _schedule(seed=3, n_ops=120, faults=None):
    from jepsen_tpu.fuzz.schedule import Schedule
    return Schedule(seed=seed, n_ops=n_ops, concurrency=3,
                    faults=[dict(w) for w in (faults or [])])


# -- satellite 1: injectable clock / trial determinism -----------------


def test_step_clock_is_pure_step_count():
    from jepsen_tpu.generator.simulate import StepClock
    c = StepClock(step_s=0.5)
    assert [c() for _ in range(4)] == [0.0, 0.5, 1.0, 1.5]
    assert c.reads == 4


def test_simulate_wall_cap_ignores_real_load():
    """Same seed, different machine load ⇒ identical truncation: the
    StepClock makes ``max_wall_s`` a pure step-count cap, so a
    complete_fn that stalls (load) changes nothing."""
    import time

    from jepsen_tpu import generator as gen_mod
    from jepsen_tpu.generator.simulate import StepClock, simulate

    def run(stall_s):
        def gen():
            n = {"i": 0}

            def f():
                n["i"] += 1
                return {"f": "write", "value": n["i"]}
            return gen_mod.clients(gen_mod.limit(200, gen_mod.Fn(f)))

        def complete(ctx, op):
            if stall_s:
                time.sleep(stall_s)
            out = dict(op)
            out["type"] = "ok"
            out["time"] = op["time"] + 1
            return out

        return simulate({"concurrency": 3}, gen(), complete, seed=11,
                        limit=1600, max_wall_s=40.0,
                        clock=StepClock(step_s=1.0), _lane=None)

    fast, loaded = run(0.0), run(0.002)
    assert fast == loaded
    assert len(fast) < 400  # the cap actually truncated


def test_trial_same_schedule_byte_identical():
    from jepsen_tpu.fuzz.trial import run_trial
    s = _schedule(seed=7, faults=OVERLAP_FAULTS)
    a = "".join(json.dumps(op) + "\n" for op in run_trial(s))
    b = "".join(json.dumps(op) + "\n" for op in run_trial(s.copy()))
    assert a == b and a


def test_trial_histories_are_client_clean():
    """Client ops never land on the nemesis thread (they would mutate
    the register invisibly), and indeterminate completions stay under
    the frontier-explosion cap."""
    from jepsen_tpu.fuzz.trial import MAX_CRASHES, run_trial
    h = run_trial(_schedule(seed=2, faults=OVERLAP_FAULTS))
    client = [op for op in h if isinstance(op.get("process"), int)]
    assert client and all(isinstance(op.get("process"), int)
                          for op in h if op.get("type") != "info"
                          or op.get("process") != "nemesis")
    infos = [op for op in client if op.get("type") == "info"]
    assert len(infos) <= MAX_CRASHES


# -- satellite 2: FakeClusterState fuzz seams --------------------------


def test_fake_cluster_mutate_knobs_deterministic(tmp_path):
    from jepsen_tpu.fakes import FakeClusterState

    def knobs(seed):
        c = FakeClusterState(tmp_path / f"m{seed}.json",
                             nodes=["n1", "n2", "n3"], time_fn=lambda: 0.0)
        return [c.mutate_knobs(random.Random(seed)) for _ in range(5)]

    assert knobs(42) == knobs(42)
    assert knobs(42) != knobs(43)
    for k in knobs(42):
        assert k["settle_s"] >= 0.0 and 1 <= k["min_members"] <= 2


def test_fake_cluster_rate_aware_settle(tmp_path):
    """The settle window is measured on the CLUSTER clock: a 2× rate
    factor converges in half the wall time, and garbage rates read as
    1.0 (the nemesis must never wedge the cluster)."""
    from jepsen_tpu.fakes import FakeClusterState
    vclock = {"t": 0.0}
    c = FakeClusterState(tmp_path / "members.json",
                         nodes=["n1", "n2", "n3"], settle_s=1.0,
                         time_fn=lambda: vclock["t"])
    op = c.op({})
    pend = (op, c.invoke({}, op))
    c.set_clock_rate(2.0)
    vclock["t"] = 0.4  # 0.4 wall × 2.0 = 0.8 cluster < 1.0: in flight
    assert c.resolve_op({}, pend) is None
    vclock["t"] = 0.6  # 1.2 cluster ≥ 1.0: settled
    assert c.resolve_op({}, pend) is c
    c.set_clock_rate("garbage")
    assert c.clock_rate == 1.0
    c.set_clock_rate(-3)
    assert c.clock_rate == 1.0


# -- knobs: tolerant coercion + env twins ------------------------------


def test_fuzz_knob_env_twin_and_coercion(monkeypatch):
    from jepsen_tpu.fuzz.hunt import fuzz_knob
    assert fuzz_knob("fuzz_trials", None, 400, 1.0) == 400
    assert fuzz_knob("fuzz_trials", 12, 400, 1.0) == 12
    monkeypatch.setenv("JEPSEN_TPU_FUZZ_TRIALS", "77")
    assert fuzz_knob("fuzz_trials", None, 400, 1.0) == 77
    assert fuzz_knob("fuzz_trials", 12, 400, 1.0) == 12  # explicit wins
    monkeypatch.setenv("JEPSEN_TPU_FUZZ_TRIALS", "banana")
    assert fuzz_knob("fuzz_trials", None, 400, 1.0) == 400
    assert fuzz_knob("fuzz_trials", True, 400, 1.0) == 400  # bool ≠ number
    assert fuzz_knob("fuzz_trials", -5, 400, 1.0) == 1.0  # clamps to min
    assert fuzz_knob("fuzz_seed", -5, 0, None) == -5  # no floor on seed


def test_preflight_has_fuzz_knob_rows():
    from jepsen_tpu.analysis.preflight import (_ENV_NUMERIC_KNOBS,
                                               _NUMERIC_KNOBS)
    from jepsen_tpu.fuzz.hunt import FUZZ_KNOBS
    rows = {r[0] for r in _NUMERIC_KNOBS}
    envs = {r[0] for r in _ENV_NUMERIC_KNOBS}
    for key, _default, _lo in FUZZ_KNOBS:
        assert key in rows, f"preflight KNB row missing for {key}"
        assert "JEPSEN_TPU_" + key.upper() in envs, \
            f"preflight env twin missing for {key}"


# -- schedule + corpus -------------------------------------------------


def test_schedule_round_trip_and_key():
    s = _schedule(seed=9, faults=OVERLAP_FAULTS)
    s.knobs = {"clock_rate": 2.0, "settle_s": 0.01}
    from jepsen_tpu.fuzz.schedule import Schedule
    t = Schedule.from_json(s.to_json())
    assert t.canonical() == s.canonical()
    assert t.key() == s.key() and len(s.key()) == 12
    t.faults[0]["start"] = 0.5
    assert t.key() != s.key()


def test_schedule_windows_ops_bounds():
    s = _schedule(n_ops=100, faults=[
        {"kind": "net", "start": 0.99, "dur": 0.5},
        {"kind": "pause", "start": 0.0, "dur": 0.0001}])
    wins = s.windows_ops()
    for start, end, _kind in wins:
        assert 0 <= start < 100 and start < end <= 100
    assert wins[1][1] - wins[1][0] == 1  # every window ≥ one op wide


def test_corpus_mutation_deterministic():
    from jepsen_tpu.fuzz.corpus import mutate, random_schedule
    base = _schedule(seed=1, faults=OVERLAP_FAULTS)

    def walk(seed):
        rng = random.Random(seed)
        s, out = base, []
        for _ in range(20):
            s = mutate(s, rng, splice_from=random_schedule(rng))
            out.append(s.key())
        return out

    assert walk(5) == walk(5)
    assert walk(5) != walk(6)


def test_corpus_dedup_and_pick():
    from jepsen_tpu.fuzz.corpus import Corpus
    c = Corpus(base=_schedule(seed=1))
    assert len(c) == 1
    assert not c.add(_schedule(seed=1))  # same key: dedup
    assert c.add(_schedule(seed=2), reason="new-edge")
    assert len(c) == 2
    picked = {c.pick(random.Random(i)).seed for i in range(20)}
    assert picked <= {1, 2} and picked


# -- coverage signals --------------------------------------------------


def test_history_edges_fault_op_interleaving():
    from jepsen_tpu.fuzz.coverage import history_edges
    h = [
        {"type": "invoke", "process": 0, "f": "write", "value": 1},
        {"type": "ok", "process": 0, "f": "write", "value": 1},
        {"type": "info", "process": "nemesis", "f": "start-partition"},
        {"type": "info", "process": "nemesis", "f": "start-clock-rate"},
        {"type": "invoke", "process": 1, "f": "read", "value": None},
        {"type": "ok", "process": 1, "f": "read", "value": 1},
        {"type": "info", "process": "nemesis", "f": "stop-partition"},
        {"type": "invoke", "process": 0, "f": "cas", "value": [1, 2]},
        {"type": "fail", "process": 0, "f": "cas", "value": [1, 2]},
    ]
    edges = history_edges(h)
    assert "op:none:write:ok" in edges
    assert "op:clock-rate+net:read:ok" in edges
    assert "op:clock-rate:cas:fail" in edges


def test_history_edges_membership_horizon():
    from jepsen_tpu.fuzz.coverage import (MEMBERSHIP_HORIZON_OPS,
                                          history_edges)
    h = [{"type": "info", "process": "nemesis", "f": "grow"}]
    for i in range(MEMBERSHIP_HORIZON_OPS + 2):
        h.append({"type": "invoke", "process": 0, "f": "read"})
        h.append({"type": "ok", "process": 0, "f": "read", "value": None})
    edges = history_edges(h)
    assert "op:membership:read:ok" in edges
    assert "op:none:read:ok" in edges  # past the horizon


def test_coverage_map_new_edges_and_near_miss():
    from jepsen_tpu.fuzz.coverage import CoverageMap
    m = CoverageMap()
    assert m.observe(["a", "b"]) == 2
    assert m.observe(["b", "c"]) == 1
    assert len(m) == 3
    assert not m.observe_margin(None)
    assert m.observe_margin(5) and m.best_margin == 5
    assert not m.observe_margin(7)  # only a SHRINKING margin promotes
    assert m.observe_margin(1) and m.best_margin == 1


def test_frontier_session_coverage_probe():
    from jepsen_tpu.checker.linear_cpu import FrontierSession
    from jepsen_tpu.checker.linear_encode import encode_register_ops
    fs = FrontierSession()
    probe = fs.coverage_probe()
    assert probe["margin"] is None and probe["died"] is False
    h = [
        {"type": "invoke", "process": 0, "f": "write", "value": 1},
        {"type": "ok", "process": 0, "f": "write", "value": 1},
        {"type": "invoke", "process": 1, "f": "read", "value": None},
        {"type": "ok", "process": 1, "f": "read", "value": 1},
    ]
    res = fs.absorb(encode_register_ops(h))
    assert res.valid is True
    probe = fs.coverage_probe()
    assert any(e.startswith("frontier:peak:b") for e in probe["edges"])
    assert isinstance(probe["margin"], int) and probe["margin"] >= 1
    assert probe["died"] is False


def test_ladder_coverage_probe_rung_regimes():
    from jepsen_tpu.checker.ladder import Backend, BackendLadder
    ladder = BackendLadder([
        Backend("flaky", lambda ctx: None),  # declines every dispatch
        Backend("steady", lambda ctx: {"ok": True}),
    ])
    assert ladder.coverage_probe()["edges"] == []
    out, name = ladder.run({})
    assert name == "steady" and out == {"ok": True}
    edges = ladder.coverage_probe()["edges"]
    assert "rung:steady:settled" in edges
    assert any(e.startswith("rung:flaky:") for e in edges)


# -- ddmin + planted bug ----------------------------------------------


def test_ddmin_generic_minimization():
    from jepsen_tpu.checker.explain import ddmin
    items = list("abcdefgh")
    kept, info = ddmin(items, lambda ws: {"a", "e"} <= set(ws))
    assert kept == ["a", "e"]
    assert info["minimal"] is True


def test_planted_bug_positive_and_negative():
    """The demo bug is interleaving-gated: a four-way-overlap schedule
    trips it (and ONLY the bug — the same schedule is valid on the
    honest register); a no-overlap schedule never arms the final
    stage."""
    from jepsen_tpu.checker.linearizable import LinearizableChecker
    from jepsen_tpu.fuzz.hunt import DEMO_BUG_SPEC
    from jepsen_tpu.fuzz.trial import PlantedBug, run_trial
    ck = LinearizableChecker(accelerator="cpu")
    overlap = _schedule(seed=3, faults=OVERLAP_FAULTS)
    h = run_trial(overlap, bug=PlantedBug.from_spec(DEMO_BUG_SPEC))
    assert ck.check(None, h, {"explain": False})["valid?"] is False
    assert ck.check(None, run_trial(overlap),
                    {"explain": False})["valid?"] is True
    apart = _schedule(seed=3, faults=[
        {"kind": "net", "start": 0.0, "dur": 0.12},
        {"kind": "clock-rate", "start": 0.2, "dur": 0.12},
        {"kind": "pause", "start": 0.45, "dur": 0.12},
        {"kind": "membership", "start": 0.7, "dur": 0.12}])
    h = run_trial(apart, bug=PlantedBug.from_spec(DEMO_BUG_SPEC))
    assert ck.check(None, h, {"explain": False})["valid?"] is True


def test_planted_bug_spec_round_trip():
    from jepsen_tpu.fuzz.trial import PlantedBug
    from jepsen_tpu.fuzz.hunt import DEMO_BUG_SPEC
    bug = PlantedBug.from_spec(DEMO_BUG_SPEC)
    assert PlantedBug.from_spec(bug.spec()).spec() == bug.spec()
    assert PlantedBug.from_spec(None) is None
    assert PlantedBug.from_spec([]) is None


# -- artifacts + replay ------------------------------------------------


def test_minimize_land_and_replay(tmp_path):
    """The quick-lane artifact contract: a known-tripping anomaly
    minimizes through ddmin (still invalid at every probe), lands as a
    hunt/<id>/ bundle, and --replay reproduces it bit-identically."""
    from jepsen_tpu.fuzz import hunt as hunt_mod
    h = hunt_mod.Hunter(tmp_path, trials=1, pool_workers=0,
                        seed=0, bug_spec=hunt_mod.DEMO_BUG_SPEC)
    schedule = _schedule(seed=3, faults=OVERLAP_FAULTS)
    assert h._trial_invalid(schedule) is not None
    minimized, info = h.minimize(schedule)
    assert len(minimized.faults) <= len(schedule.faults)
    assert minimized.n_ops <= schedule.n_ops
    assert h._trial_invalid(minimized) is not None
    hunt_id = h.land({"schedule": schedule,
                      "verdict": {"valid_so_far": False}})
    d = tmp_path / "hunt" / hunt_id
    for name in ("schedule.json", "minimized.json", "history.jsonl",
                 "verdict.json", "hunt.json"):
        assert (d / name).exists(), name
    meta = json.loads((d / "hunt.json").read_text())
    assert meta["bug_spec"] == hunt_mod.DEMO_BUG_SPEC
    assert meta["seed_tuple"]["n_ops"] == minimized.n_ops
    rep = hunt_mod.replay(tmp_path, hunt_id)
    assert rep["identical"] is True and rep["reproduced"] is True
    hunts = hunt_mod.list_hunts(tmp_path)
    assert [r["id"] for r in hunts] == [hunt_id]
    assert hunt_mod.list_hunts(tmp_path / "nope") == []


def test_hunt_deterministic_through_fleet_path(tmp_path):
    """Whole-hunt determinism through the LiveDaemon verdict path: two
    hunts with the same seed tuple discover identical coverage and
    retain identical corpora."""
    from jepsen_tpu.fuzz.hunt import Hunter

    def go(tag):
        h = Hunter(tmp_path / tag, trials=8, pool_workers=0,
                   trial_ops=60, seed=4, batch_size=4,
                   stop_on_first=False)
        summary = h.run()
        keys = [e["key"] for e in h.corpus.entries]
        return summary, sorted(h.covmap.edges), keys

    (sum_a, edges_a, keys_a), (sum_b, edges_b, keys_b) = go("a"), go("b")
    assert sum_a["outcomes"] == sum_b["outcomes"]
    assert sum_a["outcomes"]["error"] == 0
    assert sum_a["trials"] == 8
    assert edges_a == edges_b and edges_a
    assert keys_a == keys_b
    assert sum_a["coverage_edges"] == len(edges_a)
    # scratch trial dirs are reaped; only the corpus/coverage remain
    assert not (tmp_path / "a" / "work").exists() or \
        not any((tmp_path / "a" / "work").iterdir())


def test_hunt_telemetry_metrics(tmp_path):
    from jepsen_tpu import telemetry
    from jepsen_tpu.fuzz.hunt import Hunter
    reg = telemetry.Registry()
    h = Hunter(tmp_path, trials=4, pool_workers=0, trial_ops=60,
               seed=4, batch_size=4, stop_on_first=False, registry=reg)
    summary = h.run()
    rows = {r["name"]: r for r in reg.snapshot()
            if r["type"] in ("counter", "gauge")}
    assert sum(r["value"] for r in reg.snapshot()
               if r["name"] == "fuzz_trials_total") == summary["trials"]
    assert rows["fuzz_coverage_edges"]["value"] == \
        float(summary["coverage_edges"])
    assert rows["fuzz_corpus_size"]["value"] == \
        float(summary["corpus_size"])


def test_web_home_lists_hunt_artifacts(tmp_path):
    """The web home page surfaces landed hunts with replay hints."""
    import urllib.request

    from jepsen_tpu.web import make_server
    d = tmp_path / "hunt" / "cafe00112233"
    d.mkdir(parents=True)
    (d / "hunt.json").write_text(json.dumps({
        "id": "cafe00112233",
        "seed_tuple": {"seed": 9, "n_ops": 64,
                       "faults": [{"kind": "net", "start": 0.1,
                                   "dur": 0.2}]}}))
    srv = make_server(str(tmp_path), "127.0.0.1", 0)
    import threading
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        home = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.server_address[1]}/",
            timeout=10).read().decode()
    finally:
        srv.shutdown()
        t.join(timeout=10)
    assert "cafe00112233" in home
    assert "hunt --replay cafe00112233" in home


# -- the e2e: guided finds what blind cannot (slow lane) ---------------


@pytest.mark.slow
def test_guided_hunt_finds_planted_anomaly_blind_does_not(tmp_path):
    """ISSUE-18 acceptance: at an equal 400-trial budget against the
    interleaving-gated demo bug, the seeded guided hunt finds AND
    minimizes the anomaly; blind random finds nothing; the landed
    artifact replays bit-identically."""
    from jepsen_tpu.fuzz import hunt as hunt_mod

    guided = hunt_mod.Hunter(tmp_path / "guided", trials=400,
                             pool_workers=0, trial_ops=120, seed=1,
                             guided=True,
                             bug_spec=hunt_mod.DEMO_BUG_SPEC)
    g = guided.run()
    assert g["anomalies"] >= 1, g
    assert g["trials"] <= 400
    hunt_id = g["hunt_ids"][0]
    d = tmp_path / "guided" / "hunt" / hunt_id
    meta = json.loads((d / "hunt.json").read_text())
    minimized = meta["seed_tuple"]
    original = json.loads((d / "schedule.json").read_text())
    assert len(minimized["faults"]) <= len(original["faults"])
    assert minimized["n_ops"] <= original["n_ops"]
    rep = hunt_mod.replay(tmp_path / "guided", hunt_id)
    assert rep["identical"] is True and rep["reproduced"] is True

    blind = hunt_mod.Hunter(tmp_path / "blind", trials=400,
                            pool_workers=0, trial_ops=120, seed=1,
                            guided=False,
                            bug_spec=hunt_mod.DEMO_BUG_SPEC)
    b = blind.run()
    assert b["anomalies"] == 0, b
    assert b["trials"] == 400
    assert b["outcomes"]["error"] == 0
