"""Orchestrator: the full test lifecycle (reference: jepsen/src/jepsen/core.clj).

``run(test)``: prepare -> logging -> node sessions -> OS setup -> DB cycle ->
client+nemesis setup -> generator interpreter -> log snarfing -> teardown ->
save-1 -> analyze -> save-2 -> results (core.clj:326-397). A *test is a map*
(core.clj:326-352): plain dict keys name/nodes/concurrency/ssh/os/db/client/
nemesis/generator/checker/... merged over fakes.noop_test defaults.
"""
from __future__ import annotations

import contextlib
import logging
import threading
from typing import Any

from jepsen_tpu import client as client_mod
from jepsen_tpu import control, db as db_mod, history as history_mod, store
from jepsen_tpu import journal as journal_mod
from jepsen_tpu import telemetry
from jepsen_tpu.checker import check_safe
from jepsen_tpu.generator import interpreter
from jepsen_tpu.nemesis import faults as faults_mod
from jepsen_tpu.utils import (
    join_noisy, real_pmap, retry_with_backoff, with_relative_time,
    with_thread_name,
)

logger = logging.getLogger("jepsen.core")


def synchronize(test: dict, timeout_s: float = 60.0) -> None:
    """A barrier across all db nodes' setup threads (core.clj:44-57).
    DB implementations call this between setup phases. A broken barrier
    (another node failed or timed out) surfaces as SetupFailed so
    db.cycle retries the whole cycle."""
    barrier = test.get("barrier")
    if barrier is not None:
        try:
            barrier.wait(timeout=timeout_s)
        except threading.BrokenBarrierError as e:
            raise db_mod.SetupFailed("setup barrier broken") from e


def prepare_test(test: dict) -> dict:
    """Fills start-time, concurrency, and the setup barrier
    (core.clj:310-324)."""
    test = dict(test)
    test.setdefault("start_time", store.start_time())
    n_nodes = len(test.get("nodes") or [])
    from jepsen_tpu.utils import parse_concurrency
    test["concurrency"] = parse_concurrency(test.get("concurrency", 1), n_nodes)
    if n_nodes:
        test.setdefault("barrier", threading.Barrier(n_nodes))
    if test.get("net") is None and not (test.get("ssh") or {}).get("dummy"):
        from jepsen_tpu.net import IPTables
        test["net"] = IPTables()
    elif test.get("net") is None:
        from jepsen_tpu.net import NoopNet
        test["net"] = NoopNet()
    return test


def log_test_start(test: dict) -> None:
    """Records run provenance (core.clj:253-272)."""
    import subprocess
    import sys
    try:
        sha = subprocess.run(["git", "rev-parse", "HEAD"], capture_output=True,
                             text=True, timeout=5).stdout.strip()
    except Exception:  # noqa: BLE001
        sha = "unknown"
    logger.info("Test %s starting; argv=%r git=%s", test.get("name"),
                sys.argv, sha)


@contextlib.contextmanager
def with_os(test: dict):  # owner: scheduler
    """OS setup on all nodes; teardown after (core.clj:93-100)."""
    os_ = test.get("os")
    if os_ is not None:
        control.on_nodes(test, lambda n: os_.setup(test, n))
    try:
        yield
    finally:
        if os_ is not None and not test.get("leave_db_running"):
            try:
                control.on_nodes(test, lambda n: os_.teardown(test, n))
            except Exception:  # noqa: BLE001
                logger.exception("OS teardown failed")


@contextlib.contextmanager
def with_db(test: dict):  # owner: scheduler
    """DB cycle (teardown->setup, retried), teardown after unless
    leave_db_running (core.clj:172-181, db.clj:121-158)."""
    db = test.get("db")
    if db is not None:
        db_mod.cycle(test, db)
    try:
        yield
    finally:
        if db is not None and not test.get("leave_db_running"):
            try:
                # teardown is idempotent by contract (db.clj:121-158);
                # capped-exponential-jitter retries ride out transport
                # flakes a chaotic run leaves behind
                retry_with_backoff(lambda: db_mod.teardown_all(test, db),
                                   tries=3, desc="db teardown")
            except Exception:  # noqa: BLE001
                logger.exception("DB teardown failed")


def snarf_logs(test: dict) -> None:  # owner: scheduler
    """Downloads db log files from each node into the store dir
    (core.clj:102-136)."""
    db = test.get("db")
    if not isinstance(db, db_mod.LogFiles):
        return

    def snarf(node):
        files = db.log_files(test, node)
        if not files:
            return
        dest = store.path_mk(test, node, "x").parent
        dest.mkdir(parents=True, exist_ok=True)
        for f in files:
            try:
                control.on(node, test, lambda: control.download(f, str(dest)))
            except Exception:  # noqa: BLE001
                logger.warning("couldn't download %s from %s", f, node)

    try:
        real_pmap(snarf, list(test.get("nodes") or []))
    except Exception:  # noqa: BLE001
        logger.exception("log snarfing failed")


@contextlib.contextmanager
def with_client_and_nemesis(test: dict):  # owner: scheduler
    """Nemesis setup (concurrently) + one client open+setup per node;
    teardown both after (core.clj:183-212). Rebinds test['client'] /
    test['nemesis'] to the set-up instances."""
    proto_client = test.get("client")
    proto_nemesis = test.get("nemesis")
    setup_clients: list = []
    clients_lock = threading.Lock()

    nemesis_box: list = [None]
    nemesis_err: list = []

    def setup_nemesis():
        try:
            if proto_nemesis is not None:
                nemesis_box[0] = proto_nemesis.setup(test)
        except Exception as e:  # noqa: BLE001
            nemesis_err.append(e)

    nt = threading.Thread(target=setup_nemesis, daemon=True)
    nt.start()
    try:
        if proto_client is not None:
            def open_and_setup(node):
                c = proto_client.open(test, node)
                # record immediately so a failure on another node still
                # tears this one down
                with clients_lock:
                    setup_clients.append(c)
                c.setup(test)
            real_pmap(open_and_setup, list(test.get("nodes") or []))
        join_noisy(nt, "nemesis setup")
        if nemesis_err:
            raise nemesis_err[0]
        if nemesis_box[0] is not None:
            test["nemesis"] = nemesis_box[0]
        yield
    finally:
        # never tear down a nemesis that's still setting up
        join_noisy(nt, "nemesis setup (teardown wait)")
        for c in setup_clients:
            try:
                c.teardown(test)
                c.close(test)
            except Exception:  # noqa: BLE001
                logger.exception("client teardown failed")
        try:
            if nemesis_box[0] is not None:
                # idempotent by contract (heal/reset/restart); retried
                # with capped-exponential full-jitter backoff because
                # this teardown IS the cluster's heal path
                retry_with_backoff(lambda: nemesis_box[0].teardown(test),
                                   tries=4, desc="nemesis teardown")
                faults = test.get("_faults")
                if faults is not None:
                    # teardown restores normal operation (nemesis.clj
                    # contract) — except file damage, which nothing can
                    # undo: those entries stay on the books
                    healed = faults.mark_healed(
                        kinds=faults_mod.TEARDOWN_HEALS, via="teardown")
                    if healed:
                        logger.info("nemesis teardown healed fault(s) %s",
                                    healed)
        except Exception:  # noqa: BLE001
            logger.exception("nemesis teardown failed")
        test["nemesis"] = proto_nemesis


def run_case(test: dict) -> list[dict]:  # owner: scheduler
    """Client+nemesis setup then the interpreter (core.clj:214-219)."""
    with with_client_and_nemesis(test):
        return interpreter.run(test)


@contextlib.contextmanager
def _maybe_profile(test: dict):
    """--profile: a jax.profiler device trace of the checker phase into
    the store dir's profile/ (telemetry.profiler_trace degrades to a
    no-op when the profiler is unavailable)."""
    if not test.get("profile"):
        yield
        return
    with telemetry.profiler_trace(store.path(test, "profile")):
        yield


def _live_final_results(test: dict, checker) -> dict | None:
    """The live daemon's final incremental verdict for this run, when it
    is *fresh* (final state covering exactly this history) and the
    checker is one whose live session computes the same result shape —
    a bare LinearizableChecker or elle AppendChecker. Anything else
    (composed checkers, stats/timeline bundles, recovered histories)
    re-checks from scratch; reuse must never lose a sub-result."""
    if not test.get("live_reuse") or test.get("wal_recovered"):
        return None
    try:
        from jepsen_tpu.checker.linearizable import LinearizableChecker
        from jepsen_tpu.live.daemon import load_live_status
        from jepsen_tpu.workloads.append import AppendChecker
        if not isinstance(checker, (LinearizableChecker, AppendChecker)):
            return None
        status = load_live_status(store.test_dir(test))
        if not status or status.get("state") != "final":
            return None
        results = status.get("results")
        if not isinstance(results, dict) or "valid?" not in results:
            return None
        if status.get("ops_absorbed") != len(test.get("history") or []):
            return None  # stale: the history grew/shrank since finalize
        workload = status.get("workload")
        # NOT register-independent: a bare LinearizableChecker on a
        # key-lifted history computes something else entirely (the
        # supported lifted config is IndependentChecker, which fails
        # the isinstance gate above) — reuse must not diverge from
        # what --no-live-reuse would compute
        if isinstance(checker, LinearizableChecker) and \
                workload != "register":
            return None
        if isinstance(checker, AppendChecker) and workload != "list-append":
            return None
        logger.info("reusing live daemon's final incremental verdict "
                    "(live-status.json, %d ops); --no-live-reuse "
                    "re-checks from scratch", status.get("ops_absorbed"))
        return {**results, "live-reused": True}
    except Exception:  # noqa: BLE001 — reuse is an optimization, never a risk
        logger.exception("live-verdict reuse probe failed; re-checking")
        return None


def analyze(test: dict) -> dict:
    """Indexes the history, runs the checker, persists results
    (core.clj:221-236), and exports the telemetry snapshot
    (metrics.prom + metrics.json + metrics-summary.txt) into the store
    dir. Standalone re-analysis (cli analyze) gets its own registry so
    checker metrics are captured there too. A run the live daemon
    tracked to completion can skip the re-check entirely:
    ``live_reuse`` (cli analyze's default) adopts the daemon's final
    incremental verdict when it exactly covers this history."""
    logger.info("Analyzing...")
    history = history_mod.index(test.get("history") or [])
    test["history"] = history
    checker = test.get("checker")
    reg = telemetry.get_registry()
    prev = None
    if not reg.enabled and test.get("metrics", True) is not False:
        reg = telemetry.Registry()
        prev = telemetry.install(reg)
    try:
        reused = _live_final_results(test, checker)
        if reused is not None:
            test["results"] = reused
        elif checker is not None:
            with _maybe_profile(test):
                test["results"] = check_safe(checker, test, history, {})
        else:
            test["results"] = {"valid?": True}
        if test.get("wal_recovered"):
            # verdict over a crash-recovered partial history: sound for
            # the ops that were journaled, but the run never finished —
            # badge it so nobody mistakes it for a complete run
            # (cli analyze --recover, doc/robustness.md)
            test["results"]["incomplete"] = True
        if reg.enabled:
            reg.gauge("run_history_ops",
                      "ops in the final history").set(len(history))
            # standalone re-analysis (prev installed here) exports under
            # metrics-analyze.* — the live run's interpreter/control/
            # nemesis measurements are unreproducible and must survive
            # any number of re-checks
            prefix = "metrics" if prev is None else "metrics-analyze"
            try:
                reg.export(store.test_dir(test), prefix=prefix)
                from jepsen_tpu import report
                report.write_metrics_summary(test, reg,
                                             filename=f"{prefix}-summary.txt")
            except Exception:  # noqa: BLE001 — export never masks a verdict
                logger.exception("telemetry export failed")
        store.save_2(test)
    finally:
        if prev is not None:
            telemetry.install(prev)
    logger.info("Analysis complete")
    return test


def log_results(test: dict) -> None:
    """(core.clj:238-251)"""
    results = test.get("results") or {}
    valid = results.get("valid?")
    if valid is True:
        logger.info("Everything looks good! ヽ('ー`)ノ")
    elif valid == "unknown":
        logger.info("Errors occurred during analysis, but no anomalies found. ಠ~ಠ")
    else:
        logger.info("Analysis invalid! (ﾉಥ益ಥ）ﾉ ┻━┻")


def _telemetry_setup(test: dict):
    """Installs a live metrics registry (unless ``metrics: False``) with
    a periodic background flusher into the store dir, the run's causal
    tracer (Perfetto ``trace.json`` sink at ``--trace`` verbosity, the
    always-on flight recorder unless ``flight_recorder_events`` is 0 —
    doc/observability.md "Causal trace"), and — for ``trace`` runs — a
    span tracer wrapped around the client. Returns a teardown closure;
    the tracer in ``test['tracer']`` is closed by the teardown whether
    core created it or a suite did (tracing.py leaves shared-tracer
    teardown to us)."""
    from jepsen_tpu import trace as trace_mod
    prev_reg = None
    flusher = None
    if test.get("metrics", True) is not False:
        reg = telemetry.Registry()
        prev_reg = telemetry.install(reg)
        interval = test.get("metrics_interval", 10.0)
        flusher = telemetry.Flusher(reg, store.test_dir(test),
                                    interval_s=interval or 0).start()
    run_tracer = trace_mod.for_test(test)
    prev_tracer = trace_mod.install(run_tracer)
    if run_tracer.flight is not None:
        try:
            run_tracer.arm_crash_dump(
                store.path(test, trace_mod.FLIGHT_NAME))
        except Exception:  # noqa: BLE001 — bare test map, no store coords
            logger.debug("no store dir for crash-dump hook", exc_info=True)
    if trace_mod.trace_enabled(test) and test.get("tracer") is None:
        from jepsen_tpu import tracing
        test["tracer"] = tracing.Tracer(str(store.path_mk(test,
                                                          "trace.jsonl")))
        if test.get("client") is not None and not isinstance(
                test["client"], tracing.TracedClient):
            test["client"] = tracing.TracedClient(test["client"],
                                                  test["tracer"])

    def teardown():
        tracer = test.get("tracer")
        if tracer is not None:
            try:
                tracer.close()
            except Exception:  # noqa: BLE001
                logger.exception("tracer close failed")
        try:
            run_tracer.close()
        except Exception:  # noqa: BLE001
            logger.exception("run tracer close failed")
        trace_mod.install(prev_tracer)
        if flusher is not None:
            flusher.stop(final_export=True)
        if prev_reg is not None:
            telemetry.install(prev_reg)

    return teardown


def _crash_safety_setup(test: dict):
    """Installs the write-ahead history journal and the durable fault
    registry into the store dir (doc/robustness.md). ``wal: False``
    turns the journal off; ``fault_registry: False`` the registry.
    Either failing to open degrades to the pre-crash-safe behavior
    rather than failing the run.

    Also writes an early ``test.json`` snapshot: ``analyze --recover``
    and ``cli heal`` need the test map (nodes, ssh opts) even when the
    run never reached save_1 — it is rewritten with the final state at
    save time."""
    journal = faults = late = None
    try:
        store.write_test(test)
    except Exception:  # noqa: BLE001
        logger.exception("early test.json write failed")
    # host ingest spine: honor the test map's ingest_native knob for
    # every consumer that never sees the test map (tailers, sessions),
    # and pre-register the fallback counter so run metrics export it
    # even when the native path never falls back (absence must mean
    # "zero fallbacks", not "counter unknown")
    try:
        from jepsen_tpu.history_ir import ingest as ingest_mod
        ingest_mod.configure_from_test(test)
        telemetry.get_registry().counter(
            "native_ingest_fallback_total",
            "ingest work that fell back to the Python path",
            labels=("reason",))
    except Exception:  # noqa: BLE001 — knob plumbing never blocks a run
        logger.exception("ingest knob configuration failed")
    if test.get("wal", True) is not False:
        try:
            journal = journal_mod.Journal(
                store.path_mk(test, journal_mod.WAL_NAME),
                fsync_interval_s=test.get(
                    "wal_fsync_interval",
                    journal_mod.DEFAULT_FSYNC_INTERVAL_S))
            test["_journal"] = journal
            # ir_stream_from_wal: tail our own WAL into an incremental
            # history-IR builder on a background thread, so the encode
            # the checkers need at analyze time hides under the run
            # itself (doc/performance.md "History IR")
            from jepsen_tpu import history_ir
            history_ir.maybe_start_wal_streamer(test, journal.path)
        except OSError:
            logger.exception("couldn't open history WAL; journaling off")
    if test.get("fault_registry", True) is not False:
        try:
            faults = faults_mod.FaultRegistry(
                store.path_mk(test, faults_mod.FAULTS_NAME))
            test["_faults"] = faults
        except OSError:
            logger.exception("couldn't open fault registry")
    # the quarantine log for late completions from reaped zombie workers
    # (doc/robustness.md); lazily opened, so clean runs leave no file
    try:
        late = journal_mod.ForensicLog(
            store.path(test, journal_mod.LATE_NAME))
        test["_late"] = late
    except Exception:  # noqa: BLE001
        logger.exception("couldn't set up late-completion log")
    return journal, faults, late


def _preflight_gate(test: dict) -> None:
    """Static validation BEFORE any node/db contact (doc/static-analysis.md).
    ``preflight: False`` (``--no-preflight``) skips it — restoring the
    pre-preflight behavior bit-identically, with only a skip counter to
    show for it. Error diagnostics raise
    :class:`jepsen_tpu.analysis.preflight.PreflightFailed`."""
    if test.get("preflight", True) is False:
        reg = telemetry.get_registry()
        if reg.enabled:
            reg.counter("preflight_skipped_total",
                        "runs that opted out of preflight validation").inc()
        return
    from jepsen_tpu.analysis import preflight as preflight_mod
    preflight_mod.check(test)


def _fatal_flight_dump(test: dict, exc: BaseException) -> None:
    """The fatal-path flight-recorder dump (doc/observability.md
    "Causal trace"): a run dying on an exception leaves its last ~N
    trace events next to the store artifacts. ``PreflightFailed`` is
    exempt — a rejected test map never ran, there is nothing to
    record."""
    from jepsen_tpu.analysis.preflight import PreflightFailed
    if isinstance(exc, PreflightFailed):
        return
    from jepsen_tpu import trace as trace_mod
    try:
        trace_mod.get_tracer().dump_flight(
            store.path(test, trace_mod.FLIGHT_NAME), reason="fatal")
    except Exception:  # noqa: BLE001 — a crash dump must never mask the crash
        logger.exception("fatal-path flight dump failed")


def run(test: dict) -> dict:  # owner: scheduler
    """The whole enchilada (core.clj:326-397)."""
    test = prepare_test(test)
    store.start_logging(test)
    telemetry_teardown = _telemetry_setup(test)
    journal = faults = late = None
    try:
        # a mis-specified test dies HERE, in milliseconds, before node
        # sessions / DB cycling / device compilation spend real time
        _preflight_gate(test)
        journal, faults, late = _crash_safety_setup(test)
        with with_thread_name(f"jepsen-{test.get('name')}"):
            log_test_start(test)
            with control.with_test_nodes(test):
                with with_os(test):
                    with with_db(test):
                        with with_relative_time():
                            history = run_case(test)
                        test["history"] = history
                        snarf_logs(test)
                        streamer = test.get("_ir_streamer")
                        if streamer is not None:
                            # absorb the WAL's final tail while it still
                            # exists; history_ir.of adopts the streamed
                            # IR at analyze time (or batch-builds if the
                            # stream diverged)
                            streamer.drain_final()
                        store.save_1(test)
                        if journal is not None:
                            # history.jsonl is authoritative now; a
                            # surviving WAL marks a crashed run
                            journal.close(discard=True)
            test = analyze(test)
            log_results(test)
            return test
    except BaseException as e:
        _fatal_flight_dump(test, e)
        raise
    finally:
        streamer = test.pop("_ir_streamer", None)
        if streamer is not None:
            streamer.drain_final()  # no-op when already drained
        test.pop("_journal", None)
        if journal is not None:
            journal.close()  # no-op when already discarded
        test.pop("_late", None)
        if late is not None:
            late.close()
        test.pop("_faults", None)
        if faults is not None:
            # crash-path heal replay: a run that died mid-fault (or
            # whose nemesis teardown failed, or whose fault-closing op
            # outlived its deadline) still restores the cluster
            try:
                actionable, evidence = faults_mod.actionable_unhealed(faults)
                if actionable:
                    logger.warning("run left %d unhealed fault(s); "
                                   "replaying heals", len(actionable))
                    summary = faults_mod.replay_unhealed(test, faults)
                    logger.info("crash-path heal replay: %s", summary)
                elif evidence:
                    # file damage: evidence, not a heal target
                    logger.info("%d unhealable fault record(s) (file "
                                "damage) remain on the books",
                                len(evidence))
            except Exception:  # noqa: BLE001
                logger.exception("crash-path fault heal replay failed")
            faults.close()
        telemetry_teardown()
        store.stop_logging()
