"""Database automation protocols (reference: jepsen/src/jepsen/db.clj).

A DB sets up and tears down the system under test on each node. Optional
capability mixins mirror the reference's protocols: Process (db.clj:18-24),
Pause (:26-29), Primary (:31-38), LogFiles (:40-41). ``cycle`` runs
teardown -> setup across nodes with retries (db.clj:117-158).
"""
from __future__ import annotations

import logging
from typing import Iterable

from jepsen_tpu.utils import real_pmap

logger = logging.getLogger("jepsen.db")

CYCLE_TRIES = 3  # db.clj:117-119


class SetupFailed(Exception):
    """DB setup failed; the whole cycle should be retried."""


class DB:
    def setup(self, test: dict, node: str) -> None:
        """Installs and starts the DB on node."""

    def teardown(self, test: dict, node: str) -> None:
        """Removes the DB from node, including logs and data."""


class Process:
    """Start/kill the DB process abruptly (db.clj:18-24)."""

    def start(self, test: dict, node: str):
        raise NotImplementedError

    def kill(self, test: dict, node: str):
        raise NotImplementedError


class Pause:
    """SIGSTOP/SIGCONT-style pause (db.clj:26-29)."""

    def pause(self, test: dict, node: str):
        raise NotImplementedError

    def resume(self, test: dict, node: str):
        raise NotImplementedError


class Primary:
    """Single-primary systems (db.clj:31-38)."""

    def primaries(self, test: dict) -> list[str]:
        raise NotImplementedError

    def setup_primary(self, test: dict, node: str) -> None:
        """Called on (first nodes) after every node's setup."""


class LogFiles:
    """Paths of log files to download from nodes (db.clj:40-41)."""

    def log_files(self, test: dict, node: str) -> list[str]:
        return []


class NoopDB(DB, LogFiles):
    """A database that does nothing (jepsen.db/noop)."""


def cycle(test: dict, db: DB) -> None:
    """teardown! then setup! across all nodes in parallel, retried up to
    CYCLE_TRIES times on SetupFailed (db.clj:121-158). Suites synchronize
    between phases via core.synchronize."""
    nodes: Iterable[str] = test.get("nodes") or []
    for attempt in range(1, CYCLE_TRIES + 1):
        # a failed attempt may leave the setup barrier broken (Python
        # breaks a Barrier permanently on timeout/abort) — reset it so the
        # retry can actually synchronize
        barrier = test.get("barrier")
        if barrier is not None:
            barrier.reset()
        try:
            real_pmap(lambda n: db.teardown(test, n), list(nodes))
            real_pmap(lambda n: db.setup(test, n), list(nodes))
            if isinstance(db, Primary) and nodes:
                db.setup_primary(test, list(nodes)[0])
            return
        except SetupFailed as e:
            if attempt == CYCLE_TRIES:
                raise
            logger.warning("DB setup failed (%r); retrying cycle (%d/%d)",
                           e, attempt, CYCLE_TRIES)


def teardown_all(test: dict, db: DB) -> None:
    real_pmap(lambda n: db.teardown(test, n), list(test.get("nodes") or []))
