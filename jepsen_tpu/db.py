"""Database automation protocols (reference: jepsen/src/jepsen/db.clj).

A DB sets up and tears down the system under test on each node. Optional
capability mixins mirror the reference's protocols: Process (db.clj:18-24),
Pause (:26-29), Primary (:31-38), LogFiles (:40-41). ``cycle`` runs
teardown -> setup across nodes with retries (db.clj:117-158).
"""
from __future__ import annotations

import logging
from typing import Iterable

logger = logging.getLogger("jepsen.db")

CYCLE_TRIES = 3  # db.clj:117-119


class SetupFailed(Exception):
    """DB setup failed; the whole cycle should be retried."""


class DB:
    def setup(self, test: dict, node: str) -> None:
        """Installs and starts the DB on node."""

    def teardown(self, test: dict, node: str) -> None:
        """Removes the DB from node, including logs and data."""


class Process:
    """Start/kill the DB process abruptly (db.clj:18-24)."""

    def start(self, test: dict, node: str):
        raise NotImplementedError

    def kill(self, test: dict, node: str):
        raise NotImplementedError


class Pause:
    """SIGSTOP/SIGCONT-style pause (db.clj:26-29)."""

    def pause(self, test: dict, node: str):
        raise NotImplementedError

    def resume(self, test: dict, node: str):
        raise NotImplementedError


class Primary:
    """Single-primary systems (db.clj:31-38)."""

    def primaries(self, test: dict) -> list[str]:
        raise NotImplementedError

    def setup_primary(self, test: dict, node: str) -> None:
        """Called on (first nodes) after every node's setup."""


class LogFiles:
    """Paths of log files to download from nodes (db.clj:40-41)."""

    def log_files(self, test: dict, node: str) -> list[str]:
        return []


class NoopDB(DB, LogFiles):
    """A database that does nothing (jepsen.db/noop)."""


class TcpdumpDB(DB, LogFiles):
    """Runs a tcpdump capture on each node from setup to teardown, yielding
    the pcap + daemon log as log files (reference: db.clj:49-115 tcpdump).

    Options: ``ports`` (capture only these ports), ``clients_only`` (only
    traffic to/from the control node — filters out inter-DB-node chatter),
    ``filter`` (extra pcap filter string, ANDed in).
    """

    DIR = "/tmp/jepsen/tcpdump"

    def __init__(self, ports: Iterable[int] = (), clients_only: bool = False,
                 filter: str | None = None):
        self.ports = list(ports)
        self.clients_only = clients_only
        self.filter = filter
        self.log_file = f"{self.DIR}/log"
        self.cap_file = f"{self.DIR}/tcpdump"
        self.pid_file = f"{self.DIR}/pid"

    def _filter_str(self, node: str) -> str:
        from jepsen_tpu.control.util import control_ip
        parts = []
        if self.ports:
            # any-of the ports; parenthesized so the 'or' doesn't swallow
            # the ANDed host/custom clauses below
            parts.append("(" + " or ".join(f"port {p}"
                                           for p in self.ports) + ")")
        if self.clients_only:
            parts.append(f"host {control_ip(node)}")
        if self.filter:
            parts.append(self.filter)
        return " and ".join(parts)

    def setup(self, test, node):
        from jepsen_tpu import control
        from jepsen_tpu.control import util as cu
        with control.su():
            control.exec_("mkdir", "-p", self.DIR)
            # -U: unbuffered — tcpdump doesn't reliably flush on signals,
            # so don't buffer at all (db.clj:88-93)
            cu.start_daemon(
                {"logfile": self.log_file, "pidfile": self.pid_file,
                 "chdir": self.DIR},
                "tcpdump", "-w", self.cap_file, "-s", "65535",
                "-B", "16384", "-U", self._filter_str(node))

    def teardown(self, test, node):
        import time as _time
        from jepsen_tpu import control
        from jepsen_tpu.control import RemoteError, util as cu
        with control.su():
            # SIGINT for a clean flush, then wait for exit (db.clj:96-109)
            try:
                pid = control.exec_("cat", self.pid_file).strip()
            except RemoteError:
                pid = None
            if pid:
                try:
                    control.exec_("kill", "-s", "INT", pid)
                except RemoteError:
                    pass
                deadline = _time.monotonic() + 10
                while _time.monotonic() < deadline:
                    try:
                        control.exec_("ps", "-p", pid)
                        _time.sleep(0.05)
                    except RemoteError:
                        break
            cu.stop_daemon("tcpdump", self.pid_file)
            control.exec_("rm", "-rf", self.DIR)

    def log_files(self, test, node):
        return [self.log_file, self.cap_file]


def cycle(test: dict, db: DB) -> None:
    """teardown! then setup! across all nodes in parallel (with control
    sessions bound, as the reference's on-nodes does), retried up to
    CYCLE_TRIES times on SetupFailed (db.clj:121-158, core.clj with-db).
    Suites synchronize between phases via core.synchronize."""
    from jepsen_tpu import control
    nodes: Iterable[str] = test.get("nodes") or []
    for attempt in range(1, CYCLE_TRIES + 1):
        # a failed attempt may leave the setup barrier broken (Python
        # breaks a Barrier permanently on timeout/abort) — reset it so the
        # retry can actually synchronize
        barrier = test.get("barrier")
        if barrier is not None:
            barrier.reset()
        try:
            control.on_nodes(test, lambda n: db.teardown(test, n))
            control.on_nodes(test, lambda n: db.setup(test, n))
            if isinstance(db, Primary) and nodes:
                first = list(nodes)[0]
                control.on(first, test,
                           lambda: db.setup_primary(test, first))
            return
        except SetupFailed as e:
            if attempt == CYCLE_TRIES:
                raise
            logger.warning("DB setup failed (%r); retrying cycle (%d/%d)",
                           e, attempt, CYCLE_TRIES)


def teardown_all(test: dict, db: DB) -> None:
    from jepsen_tpu import control
    control.on_nodes(test, lambda n: db.teardown(test, n))
