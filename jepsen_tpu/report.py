"""Report helpers (reference: jepsen/src/jepsen/report.clj — a
stdout-capturing macro writing a store file), plus the human-readable
telemetry summary folded into each run's store dir."""
from __future__ import annotations

import contextlib
import io

from jepsen_tpu import store


@contextlib.contextmanager
def to(test: dict, filename: str):
    """Captures stdout within the block and writes it to the test's store
    dir (report.clj:7)."""
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        yield buf
    store.path_mk(test, filename).write_text(buf.getvalue())


def _fmt_labels(labels: dict) -> str:
    if not labels:
        return ""
    return "{" + ",".join(f"{k}={v}" for k, v in labels.items()) + "}"


def metrics_summary(snapshot: list[dict]) -> str:
    """Formats a registry snapshot (telemetry.Registry.snapshot rows)
    as the aligned text block written to metrics-summary.txt — counters
    and gauges one per line, histograms with count/mean/p50/p95/max."""
    counters, gauges, hists, events = [], [], [], []
    for row in snapshot:
        kind = row.get("type")
        if kind == "counter":
            counters.append(row)
        elif kind == "gauge":
            gauges.append(row)
        elif kind == "histogram":
            hists.append(row)
        elif kind == "event":
            events.append(row)
    lines: list[str] = []

    def section(title, rows, fmt):
        if not rows:
            return
        lines.append(title)
        for r in rows:
            lines.append("  " + fmt(r))
        lines.append("")

    section("counters", counters, lambda r: (
        f"{r['name']}{_fmt_labels(r['labels'])} = {r['value']:g}"))
    section("gauges", gauges, lambda r: (
        f"{r['name']}{_fmt_labels(r['labels'])} = {r['value']:g}"))

    def hist_line(r):
        mean = r["sum"] / r["count"] if r["count"] else 0.0
        qs = "".join(f" {q}={r[q]:.6g}" for q in ("p50", "p95")
                     if r.get(q) is not None)
        mx = f" max={r['max']:.6g}" if r.get("max") is not None else ""
        return (f"{r['name']}{_fmt_labels(r['labels'])} "
                f"count={r['count']} mean={mean:.6g}{qs}{mx}")

    section("histograms", hists, hist_line)
    section("events", events, lambda r: (
        f"t={r['time']:.3f} {r['name']} "
        + " ".join(f"{k}={v}" for k, v in (r.get("fields") or {}).items())))
    return "\n".join(lines)


def write_metrics_summary(test: dict, registry,
                          filename: str = "metrics-summary.txt") -> None:
    """metrics-summary.txt: the at-a-glance companion to metrics.prom /
    metrics.json (core.analyze calls this at export time)."""
    snapshot = registry.snapshot()
    if not snapshot:
        return
    with to(test, filename):
        print(f"telemetry summary — {test.get('name')} "
              f"{test.get('start_time')}\n")
        print(metrics_summary(snapshot))
