"""Report helpers (reference: jepsen/src/jepsen/report.clj — a
stdout-capturing macro writing a store file)."""
from __future__ import annotations

import contextlib
import io

from jepsen_tpu import store


@contextlib.contextmanager
def to(test: dict, filename: str):
    """Captures stdout within the block and writes it to the test's store
    dir (report.clj:7)."""
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        yield buf
    store.path_mk(test, filename).write_text(buf.getvalue())
