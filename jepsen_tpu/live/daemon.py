"""The online checker daemon: discovery, polling, admission, status.

``jepsen-tpu live [store-root|run-dir ...]`` runs a single poller
thread that:

1. **discovers** active runs — run directories holding a
   ``history.wal.jsonl`` with no final live verdict yet;
2. **tails** each run's WAL via :class:`jepsen_tpu.journal.WalTailer`
   (offset-tracking, torn-line tolerant);
3. **checks** each run incrementally through its
   :mod:`~jepsen_tpu.live.sessions` session, under **cost-model-driven
   admission**: one poll's verdict work is budgeted by the measured CPU
   checking rate (:class:`jepsen_tpu.parallel.pipeline.CostModel`), the
   most-lagged runs are served first, and a hot run consumes at most
   its fair share — the rest defer with a counted metric instead of
   starving;
4. **publishes** per-run ``live-status.json`` (atomic) plus
   ``live_*`` gauges/histograms into its metrics registry, exported as
   ``live-metrics.prom`` / ``live-metrics.json`` under the store root;
5. **finalizes** a run when its authoritative ``history.jsonl``
   appears: any tail the discarded WAL didn't deliver is absorbed from
   the history file, the session settles its exact final verdict, and
   the final state is left in ``live-status.json`` for ``cli analyze``
   to reuse when fresh.

Shutdown is wedge-proof: ``stop()`` signals the poller and joins it
with :func:`jepsen_tpu.utils.join_noisy` (bounded waits + heartbeat
logging; the thread itself is a daemon thread, so a hung check can
never hold the process hostage). Per-run circuit breakers (mirroring
the checker ladder's policy) stop re-dispatching a session that failed
``LIVE_BREAKER_THRESHOLD`` consecutive polls.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from pathlib import Path

from jepsen_tpu import telemetry
from jepsen_tpu.journal import WAL_NAME, WalTailer
from jepsen_tpu.live import sessions as sessions_mod
from jepsen_tpu.utils import join_noisy

logger = logging.getLogger("jepsen.live")

LIVE_STATUS_NAME = "live-status.json"
# per-run restart snapshot: session carry + WAL byte offset, so a daemon
# restart resumes tailing where it left off instead of re-ingesting the
# whole WAL (doc/robustness.md "Resumable checks and the elastic mesh")
LIVE_CKPT_NAME = "live-session.ckpt"
# at most one snapshot write per tracked run per this many seconds
SNAPSHOT_MIN_INTERVAL_S = 5.0

DEFAULT_POLL_S = 1.0
DEFAULT_LAG_BUDGET_OPS = 50_000
DEFAULT_MAX_RUNS = 16
DEFAULT_CHECK_BUDGET_S = 0.5
LIVE_BREAKER_THRESHOLD = 3

# Cap on distinct {run} label values in the per-run metric export: at
# fleet scale (100+ concurrent runs) one series per run per gauge is a
# cardinality explosion every scrape pays for. The top-K runs by lag
# keep their own series; the rest fold into one run="other" aggregate
# (doc/observability.md "Fleet plane"). Env-tunable for big hosts.
DEFAULT_RUN_SERIES_TOPK = 8

# live knob spec shared with preflight's KNB validation
# (analysis/preflight._NUMERIC_KNOBS): (key, default, min)
LIVE_KNOBS = (
    ("live_poll_s", DEFAULT_POLL_S, 0.0),
    ("live_lag_budget_ops", DEFAULT_LAG_BUDGET_OPS, 0.0),
    ("live_max_runs", DEFAULT_MAX_RUNS, 1.0),
    ("live_check_budget_s", DEFAULT_CHECK_BUDGET_S, 0.0),
)


def coerce_knob(name: str, value, default: float, lo: float) -> float:
    """Tolerant numeric-knob coercion: strings parse, garbage logs a
    warning and falls back to the default — the daemon must come up on
    a half-garbled config, and preflight (KNB001/KNB002) is where the
    strictness lives."""
    if value is None:
        return default
    try:
        if isinstance(value, bool):
            raise ValueError("bool is not a number")
        v = float(value)
    except (TypeError, ValueError):
        logger.warning("live knob %s=%r is not numeric; using default "
                       "%r", name, value, default)
        return default
    if v < lo:
        logger.warning("live knob %s=%r below minimum %r; clamping",
                       name, value, lo)
        return lo
    return v


def load_live_status(run_dir) -> dict | None:
    """The run's live-status.json as a dict, or None."""
    try:
        with open(Path(run_dir) / LIVE_STATUS_NAME) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


class RunTracker:  # durability: fsync
    """One tracked run: tailer + session + status/metric publication.
    Durable artifacts (the restart snapshot, live-status.json) go
    through atomic tmp+fsync+rename writers only — the
    ``durability-protocol`` lint rule holds this class to it."""

    def __init__(self, run_dir, accelerator: str = "auto",
                 fence=None, lease: dict | None = None):
        self.run_dir = Path(run_dir)
        self.name = self.run_dir.parent.name
        self.timestamp = self.run_dir.name
        self.accelerator = accelerator
        # fence() -> bool: re-checks the caller's run lease immediately
        # before every durable write (doc/robustness.md "Fleet HA"). A
        # False verdict drops the write and marks the tracker fenced —
        # a deposed checker's stale state must never overwrite its
        # adopter's progress. None (single-host live mode) never fences.
        self.fence = fence
        self.fenced = False
        # {"host", "epoch"} when leased: stamped into every status this
        # tracker publishes, so artifacts record which holder wrote them
        self.lease = lease
        self.tailer = WalTailer(self.run_dir / WAL_NAME)
        self.session = None
        self._sniff_buf: list[dict] = []
        self.unsupported = False
        self.final = False
        self.broken: str | None = None
        self._consecutive_failures = 0
        self.ops_absorbed = 0
        self.polls = 0
        self._caught_up_t = time.monotonic()
        # valid_so_far stays None (-> live_verdict -1, "unknown") until
        # a session actually verdicts: an untracked workload or a run
        # the breaker broke before its first check must never read as
        # "valid" (doc/observability.md's live_verdict semantics)
        self.last_verdict: dict = {"valid_so_far": None,
                                   "first_anomaly_op": None,
                                   "backend": None, "checked_ops": 0}
        # restart adoption: True resumed from a snapshot, False rejected
        # one (divergence / unrestorable), None = no snapshot found
        self.resumed: bool | None = None
        self._last_snapshot = 0.0
        self._snapshot_ops = 0
        self._adopt_snapshot()

    # -- restart snapshots ----------------------------------------------

    @property
    def _ckpt_path(self) -> Path:
        return self.run_dir / LIVE_CKPT_NAME

    def _adopt_snapshot(self) -> None:
        """Divergence-checked adoption of a previous daemon's snapshot
        (mirroring the WAL streamer's field-by-field verification): the
        tailer only seeks to the saved offset when the WAL's first
        ``offset`` bytes hash to what the writer consumed, and the
        session payload must restore whole. Anything else discards the
        snapshot and re-ingests from zero — a restart may cost a
        re-read, never a diverged verdict."""
        try:
            with open(self._ckpt_path, encoding="utf-8") as f:
                snap = json.load(f)
        except (OSError, ValueError):
            return
        if snap.get("version") != 1:
            self.resumed = False
            return
        session = None
        if snap.get("session") is not None:
            session = sessions_mod.restore_session(
                snap["session"], accelerator=self.accelerator)
            if session is None:
                logger.warning("live: %s snapshot's session payload "
                               "didn't restore; re-ingesting", self.label)
                self.resumed = False
                return
        elif not snap.get("unsupported"):
            # a sessionless, not-unsupported snapshot would drop the
            # sniff buffer's ops — re-ingest instead
            self.resumed = False
            return
        if not self.tailer.seek(snap.get("offset", 0),
                                lines_read=snap.get("lines_read", 0),
                                torn_skipped=snap.get("torn_skipped", 0),
                                prefix_sha=snap.get("prefix_sha")):
            logger.warning("live: %s WAL diverged from its restart "
                           "snapshot (hash mismatch); re-ingesting",
                           self.label)
            self.resumed = False
            return
        self.session = session
        self.unsupported = bool(snap.get("unsupported"))
        self.ops_absorbed = int(snap.get("ops_absorbed", 0))
        last = snap.get("last_verdict")
        if isinstance(last, dict):
            self.last_verdict = last
        self.resumed = True
        logger.info("live: %s resumed from snapshot at WAL offset %d "
                    "(%d ops absorbed)", self.label, self.tailer.offset,
                    self.ops_absorbed)

    def maybe_snapshot(self) -> bool:
        """Persists the restart snapshot when the interval elapsed and
        something new was absorbed. Unsnapshotable sessions (Elle's
        retained-history state) skip — their restart path is the
        re-ingest."""
        if self.final or self.broken or self.fenced:
            return False
        if self.session is None and not self.unsupported:
            return False  # still sniffing: the buffer isn't durable
        now = time.monotonic()
        if now - self._last_snapshot < SNAPSHOT_MIN_INTERVAL_S:
            return False
        if self.ops_absorbed == self._snapshot_ops:
            return False
        sess_snap = None
        if self.session is not None:
            sess_snap = self.session.snapshot()
            if sess_snap is None:
                return False
        payload = {
            "version": 1,
            "offset": self.tailer.offset,
            "lines_read": self.tailer.lines_read,
            "torn_skipped": self.tailer.torn_skipped,
            "prefix_sha": self.tailer.prefix_sha(),
            "ops_absorbed": self.ops_absorbed,
            "unsupported": self.unsupported,
            "session": sess_snap,
            "last_verdict": dict(self.last_verdict),
            "wrote_at": time.time(),
        }
        if self.fence is not None and not self.fence():
            # deposed mid-check: the adopter owns this snapshot now
            self.fenced = True
            return False
        try:
            from jepsen_tpu.utils import atomic_write_json
            atomic_write_json(self._ckpt_path, payload)
        except Exception:  # noqa: BLE001 — snapshots never kill a poll
            logger.exception("live: snapshot write failed for %s",
                             self.label)
            return False
        self._last_snapshot = now
        self._snapshot_ops = self.ops_absorbed
        return True

    def clear_snapshot(self) -> None:
        try:
            self._ckpt_path.unlink(missing_ok=True)
        except OSError:
            logger.exception("couldn't clear %s", self._ckpt_path)

    @property
    def label(self) -> str:
        return f"{self.name}/{self.timestamp}"

    # -- ingestion ------------------------------------------------------

    def _absorb(self, ops: list[dict]) -> None:
        if not ops:
            return
        self.ops_absorbed += len(ops)
        if self.unsupported:
            return
        if self.session is None:
            self._sniff_buf.extend(ops)
            sniffed = sessions_mod.session_for_ops(
                self._sniff_buf, accelerator=self.accelerator)
            if sniffed is sessions_mod.UNSUPPORTED:
                # this workload has no live checker — keep tailing for
                # lag/liveness, never verdicts
                self.unsupported = True
                self._sniff_buf = []
            elif sniffed is not None:
                self.session = sniffed
                self._add_chunk(self._sniff_buf)
                self._sniff_buf = []
            return
        self._add_chunk(ops)

    def _add_chunk(self, ops: list[dict]) -> None:
        # chunked ingest when the session supports it (one native call
        # per poll — doc/performance.md "Host ingest spine")
        add_many = getattr(self.session, "add_many", None)
        if add_many is not None:
            add_many(ops)
            return
        for op in ops:
            self.session.add(op)

    def tail(self) -> int:
        """One tailer poll; returns the number of new ops."""
        ops = self.tailer.poll()
        self._absorb(ops)
        return len(ops)

    def completed(self) -> bool:
        return (self.run_dir / "history.jsonl").exists()

    # -- checking -------------------------------------------------------

    @property
    def pending_ops(self) -> int:
        checked = (self.session.checked_ops if self.session is not None
                   else self.ops_absorbed)
        return max(0, self.ops_absorbed - checked)

    def lag_seconds(self, now: float) -> float:
        return 0.0 if self.pending_ops == 0 else now - self._caught_up_t

    def check(self) -> dict:
        """One verdict dispatch over everything absorbed so far."""
        if self.session is None or self.broken:
            return dict(self.last_verdict)
        try:
            v = self.session.verdict()
            self._consecutive_failures = 0
        except Exception as e:  # noqa: BLE001 — one bad run can't kill the daemon
            self._consecutive_failures += 1
            logger.exception("live check failed for %s", self.label)
            if self._consecutive_failures >= LIVE_BREAKER_THRESHOLD:
                self.broken = f"checker breaker open: {e!r}"
                logger.warning("live breaker open for %s after %d "
                               "consecutive failures", self.label,
                               self._consecutive_failures)
            return dict(self.last_verdict)
        self.last_verdict = v
        if self.pending_ops == 0:
            self._caught_up_t = time.monotonic()
        return dict(v)

    def finalize(self) -> dict | None:
        """End-of-run: absorb any ops the discarded WAL never delivered
        (from the authoritative history.jsonl), settle the exact final
        verdict, and return the final results map (None when the run
        has no live checker)."""
        from jepsen_tpu.journal import read_jsonl_tolerant
        self.tail()
        try:
            ops, _ = read_jsonl_tolerant(self.run_dir / "history.jsonl")
        except OSError:
            ops = []
        if self.tailer.torn_skipped or self.tailer.truncated_tail:
            # a torn WAL line means what we absorbed is NOT a strict
            # prefix of the authoritative history — a count-based
            # back-fill would misalign the session (skip the torn op,
            # double the tail). Rebuild from history.jsonl: slower,
            # exact, and the final verdict stays safe to reuse.
            logger.warning(
                "live: %s WAL had %d torn line(s); rebuilding the "
                "session from history.jsonl for the final verdict",
                self.label, self.tailer.torn_skipped)
            self.session = None
            self._sniff_buf = []
            self.unsupported = False
            self.ops_absorbed = 0
            self._absorb(ops)
        elif len(ops) > self.ops_absorbed:
            self._absorb(ops[self.ops_absorbed:])
        self.final = True
        if self.session is None or self.broken:
            return None
        try:
            results = self.session.finalize()
            self.last_verdict = self.session.last()
            return results
        except Exception:  # noqa: BLE001
            logger.exception("live finalize failed for %s", self.label)
            self.broken = "finalize failed"
            return None

    # -- status ---------------------------------------------------------

    def status(self, lag_budget_ops: float, results: dict | None = None,
               now: float | None = None) -> dict:
        now = time.monotonic() if now is None else now
        state = ("error" if self.broken
                 else "final" if self.final
                 else "untracked" if self.unsupported or self.session is None
                 else "tailing")
        out = {
            "name": self.name,
            "timestamp": self.timestamp,
            "state": state,
            "workload": (self.session.workload
                         if self.session is not None else None),
            "valid_so_far": self.last_verdict.get("valid_so_far"),
            "first_anomaly_op": self.last_verdict.get("first_anomaly_op"),
            "backend": self.last_verdict.get("backend"),
            "ops_absorbed": self.ops_absorbed,
            "checked_ops": (self.session.checked_ops
                            if self.session is not None else 0),
            "lag_ops": self.pending_ops,
            "lag_s": round(self.lag_seconds(now), 3),
            "lag_budget_ops": lag_budget_ops,
            "over_lag_budget": self.pending_ops > lag_budget_ops,
            "torn_skipped": self.tailer.torn_skipped,
            "polls": self.polls,
            "updated": time.time(),
        }
        if self.broken:
            out["error"] = self.broken
        if self.lease is not None:
            out["lease"] = dict(self.lease)
        if results is not None:
            out["results"] = results
        return out

    def write_status(self, status: dict) -> None:
        if self.fence is not None and not self.fence():
            self.fenced = True
            return
        try:
            telemetry._atomic_write(
                self.run_dir / LIVE_STATUS_NAME,
                json.dumps(status, default=repr) + "\n")
        except Exception:  # noqa: BLE001 — status publication never kills polls
            logger.exception("couldn't write %s for %s",
                             LIVE_STATUS_NAME, self.label)


class LiveDaemon:
    """Multiplexes live checking over every active run under a store
    root (and/or explicitly named run directories)."""

    def __init__(self, store_root: str | None = None, run_dirs=(),
                 poll_s=DEFAULT_POLL_S,
                 lag_budget_ops=DEFAULT_LAG_BUDGET_OPS,
                 max_runs=DEFAULT_MAX_RUNS,
                 check_budget_s=DEFAULT_CHECK_BUDGET_S,
                 accelerator: str = "auto",
                 registry: telemetry.Registry | None = None,
                 cost_model=None, on_final=None, lease_store=None):
        self.store_root = Path(store_root) if store_root else None
        self.run_dirs = [Path(d) for d in run_dirs]
        self.poll_s = coerce_knob("live_poll_s", poll_s,
                                  DEFAULT_POLL_S, 0.0)
        self.lag_budget_ops = coerce_knob(
            "live_lag_budget_ops", lag_budget_ops,
            DEFAULT_LAG_BUDGET_OPS, 0.0)
        self.max_runs = int(coerce_knob("live_max_runs", max_runs,
                                        DEFAULT_MAX_RUNS, 1.0))
        self.check_budget_s = coerce_knob(
            "live_check_budget_s", check_budget_s,
            DEFAULT_CHECK_BUDGET_S, 0.0)
        self.accelerator = accelerator
        self.registry = registry if registry is not None \
            else telemetry.Registry()
        if cost_model is None:
            from jepsen_tpu.parallel.pipeline import CostModel
            cost_model = CostModel()
        self.cost_model = cost_model
        # on_final(tracker, results): observed right after a run's
        # finalize, while the tracker (and its session) still exists —
        # final trackers are popped at the end of the poll, so this is
        # the only seam where a batch consumer (the schedule fuzzer's
        # coverage collection) can read per-run session state. A
        # raising hook is logged, never fatal to the poll.
        self.on_final = on_final
        # lease_store (fleet.lease.LeaseStore | None): multi-host pool
        # coordination. When set, a run is only admitted after its lease
        # is claimed, the lease is heartbeat-renewed every poll, and all
        # durable writes are fenced on the claim epoch. None = the
        # single-host live mode, byte-identical to the pre-lease path.
        self.lease_store = lease_store
        self._lease_epochs: dict[str, int] = {}
        self.trackers: dict[str, RunTracker] = {}
        self.polls = 0
        self.run_series_topk = int(coerce_knob(
            "JEPSEN_TPU_LIVE_RUN_SERIES",
            os.environ.get("JEPSEN_TPU_LIVE_RUN_SERIES"),
            DEFAULT_RUN_SERIES_TOPK, 1.0))
        # discovery cache: {name_dir: (mtime_ns, [run_dirs])} — a name
        # dir's run list is reused between polls while its mtime holds
        self._scan_cache: dict | None = None
        # candidates examined and rejected, keyed by run-dir mtime_ns:
        # skipped with ONE stat per poll until something changes inside
        self._settled: dict[str, int] = {}
        # stable {run} label interning for per-run counters (bounded
        # at run_series_topk exact labels; later runs share "other")
        self._run_labels: dict[str, str] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()  # guards trackers vs. stop/inspect

    # -- discovery ------------------------------------------------------

    def _candidate_dirs(self) -> list[Path]:
        """Run-dir candidates under the store root, via a per-name-dir
        cached scan with an mtime fast-path: run dirs are created and
        removed *inside* name dirs, so an unchanged name-dir mtime
        proves its cached run-dir list is still complete. A poll over
        an unchanged tree costs one root listing plus one stat per
        name dir, not an O(runs) listing — a 100+-run store root used
        to pay the full re-scan every tick. (The root's own mtime is
        deliberately not part of the key: the metrics export writes
        files there every poll.)"""
        out = list(self.run_dirs)
        root = self.store_root
        if root is None or not root.is_dir():
            return out
        cache = self._scan_cache
        fresh: dict[Path, tuple[int, list[Path]]] = {}
        all_hit = cache is not None
        for name_dir in root.iterdir():
            if not name_dir.is_dir() or name_dir.name == "current" \
                    or name_dir.is_symlink():
                continue
            try:
                m = name_dir.stat().st_mtime_ns
            except OSError:
                continue
            got = cache.get(name_dir) if cache is not None else None
            if got is not None and got[0] == m:
                fresh[name_dir] = got
                out.extend(got[1])
                continue
            all_hit = False
            runs = [run_dir for run_dir in name_dir.iterdir()
                    if run_dir.is_dir() and not run_dir.is_symlink()
                    and run_dir.name != "latest"]
            fresh[name_dir] = (m, runs)
            out.extend(runs)
        self._scan_cache = fresh
        if all_hit:
            self.registry.counter(
                "live_scan_cache_hits_total",
                "discovery polls answered entirely from the cached "
                "store scan (name-dir mtime fast-path)").inc()
        return out

    def discover(self) -> int:
        """Adds trackers for active runs (WAL present, not yet final),
        newest first, bounded by ``live_max_runs``. Returns the number
        of newly-admitted runs. Candidates rejected once are skipped
        with a single run-dir stat until their mtime changes (a WAL or
        status file appearing bumps it), so settled runs cost O(1) per
        poll instead of a WAL stat + a status-JSON parse each."""
        added = 0
        cands = []
        for d in self._candidate_dirs():
            key = str(d)
            if key in self.trackers:
                continue
            try:
                d_m = d.stat().st_mtime_ns
            except OSError:
                continue
            if self._settled.get(key) == d_m:
                continue  # rejected before; nothing changed inside since
            if not (d / WAL_NAME).exists():
                self._settled[key] = d_m
                continue
            status = load_live_status(d)
            if status is not None and status.get("state") == "final":
                # a previous daemon already settled this run
                self._settled[key] = d_m
                continue
            if (d / "history.jsonl").exists() and status is None \
                    and d not in self.run_dirs:
                # completed before we ever saw it: post-hoc territory
                self._settled[key] = d_m
                continue
            try:
                mtime = (d / WAL_NAME).stat().st_mtime
            except OSError:
                continue
            cands.append((mtime, d))
        cands.sort(reverse=True)
        for _mtime, d in cands:
            with self._lock:
                full = len(self.trackers) >= self.max_runs
            if full:
                self.registry.counter(
                    "live_admission_rejected_total",
                    "runs not admitted because live_max_runs "
                    "trackers are active").inc()
                break
            fence, lease, epoch = None, None, None
            if self.lease_store is not None:
                epoch = self.lease_store.acquire(d)
                if epoch is None:
                    # a live foreign holder: their run, not ours — the
                    # mtime fast-path must NOT settle it (we should
                    # retry once their lease expires)
                    logger.debug("live: %s leased elsewhere; skipping", d)
                    continue
                ls = self.lease_store
                fence = (lambda rd=d, ep=epoch: ls.guard(rd, ep))
                lease = {"host": ls.host_id, "epoch": epoch}
            # construct OUTSIDE the lock: snapshot adoption re-hashes
            # the consumed WAL prefix (seconds on a big run), and
            # stop()/poll must not block behind it
            tracker = RunTracker(d, accelerator=self.accelerator,
                                 fence=fence, lease=lease)
            with self._lock:
                if len(self.trackers) >= self.max_runs:
                    self.registry.counter(
                        "live_admission_rejected_total",
                        "runs not admitted because live_max_runs "
                        "trackers are active").inc()
                    if self.lease_store is not None:
                        self.lease_store.release(d, epoch)
                    break
                self.trackers[str(d)] = tracker
                if epoch is not None:
                    self._lease_epochs[str(d)] = epoch
            if tracker.resumed is True:
                self.registry.counter(
                    "live_session_resumes_total",
                    "trackers resumed from a restart snapshot instead "
                    "of re-ingesting the WAL").inc()
            elif tracker.resumed is False:
                self.registry.counter(
                    "live_session_resume_rejected_total",
                    "restart snapshots discarded (divergence or "
                    "unrestorable payload); the tracker re-ingested"
                ).inc()
            added += 1
            logger.info("live: tracking %s", d)
        return added

    # -- polling --------------------------------------------------------

    def poll_once(self) -> dict:  # owner: scheduler
        """One full poll: discover, tail everything, verdict within the
        admission budget (most-lagged first), publish status + metrics.
        Returns a {label: status} snapshot."""
        t0 = time.perf_counter()
        self.polls += 1
        from jepsen_tpu import trace as trace_mod
        tracer = trace_mod.get_tracer()
        poll_t0 = trace_mod.now_us() if tracer.enabled else 0
        self.discover()
        reg = self.registry
        now = time.monotonic()
        with self._lock:
            trackers = list(self.trackers.values())
        statuses: dict[str, dict] = {}
        rows: list[tuple[RunTracker, dict]] = []
        done: list[str] = []

        # lease heartbeat first: a tracker whose renewal fails is
        # fenced for the whole poll — no tail, no check, no writes; its
        # restart snapshot stays on disk for the adopting host
        fenced: list[RunTracker] = []
        if self.lease_store is not None:
            alive: list[RunTracker] = []
            for tr in trackers:
                ep = self._lease_epochs.get(str(tr.run_dir))
                if ep is not None and self.lease_store.renew(
                        tr.run_dir, ep):
                    alive.append(tr)
                else:
                    tr.fenced = True
                    fenced.append(tr)
            trackers = alive

        for tr in trackers:
            n = tr.tail()
            if n:
                reg.counter("live_ops_tailed_total",
                            "ops read from run WALs", labels=("run",)
                            ).inc(n, run=self._run_label(tr.label))

        # admission: serve the most-lagged runs first; a poll spends at
        # most live_check_budget_s of predicted CPU checking time, so
        # one hot run defers instead of starving its neighbours
        budget_ops = self.cost_model.admission_budget_ops(
            self.check_budget_s)
        spent_ops = 0.0
        order = sorted(trackers, key=lambda t: t.pending_ops,
                       reverse=True)
        for tr in order:
            tr.polls += 1
            results = None
            pending = tr.pending_ops
            if tr.completed() and not tr.final:
                if self.lease_store is not None:
                    # fresh fencing read immediately before the final:
                    # a host un-paused past its TTL must not publish a
                    # second final over its adopter's
                    ep = self._lease_epochs.get(str(tr.run_dir))
                    if ep is None or not self.lease_store.guard(
                            tr.run_dir, ep):
                        tr.fenced = True
                        fenced.append(tr)
                        continue
                t_chk = time.perf_counter()
                chk_t0 = trace_mod.now_us() if tracer.enabled else 0
                results = tr.finalize()
                if tracer.enabled:
                    tracer.complete(trace_mod.TRACK_LIVE, "finalize",
                                    chk_t0, trace_mod.now_us() - chk_t0,
                                    args={"run": tr.label,
                                          "ops": pending})
                self._observe_check(tr, pending,
                                    time.perf_counter() - t_chk)
                if self.on_final is not None:
                    try:
                        self.on_final(tr, results)
                    except Exception:  # noqa: BLE001 — a hook never kills a poll
                        logger.exception("on_final hook failed for %s",
                                         tr.label)
                # the run is over: the restart snapshot has nothing
                # left to resume (live-status.json holds the final)
                tr.clear_snapshot()
                done.append(str(tr.run_dir))
            elif tr.final:
                done.append(str(tr.run_dir))
            elif pending > 0 and tr.session is not None \
                    and not tr.broken:
                if spent_ops > 0 and spent_ops + pending > budget_ops:
                    reg.counter(
                        "live_admission_deferred_total",
                        "verdicts deferred to a later poll by the "
                        "admission budget", labels=("run",)
                        ).inc(run=self._run_label(tr.label))
                else:
                    t_chk = time.perf_counter()
                    chk_t0 = trace_mod.now_us() if tracer.enabled else 0
                    tr.check()
                    dt = time.perf_counter() - t_chk
                    if tracer.enabled:
                        tracer.complete(trace_mod.TRACK_LIVE, "check",
                                        chk_t0,
                                        trace_mod.now_us() - chk_t0,
                                        args={"run": tr.label,
                                              "ops": pending})
                    self._observe_check(tr, pending, dt)
                    spent_ops += pending
            if not tr.final and tr.maybe_snapshot():
                reg.counter("live_session_ckpt_writes_total",
                            "restart-snapshot persists (session carry "
                            "+ WAL offset)").inc()
            status = tr.status(self.lag_budget_ops, results=results,
                               now=now)
            tr.write_status(status)
            if tr.fenced:
                # deposed mid-poll (the write_status fence re-read):
                # nothing was published; drop the tracker
                fenced.append(tr)
                continue
            statuses[tr.label] = status
            rows.append((tr, status))
        self._publish_run_series(rows)

        with self._lock:
            for key in done:
                self.trackers.pop(key, None)
                if self.lease_store is not None:
                    self.lease_store.release(
                        key, self._lease_epochs.pop(key, -1))
            for tr in fenced:
                # fenced trackers leave their lease file alone (the
                # adopter owns it now) and keep their snapshot on disk
                self.trackers.pop(str(tr.run_dir), None)
                self._lease_epochs.pop(str(tr.run_dir), None)
            active = len(self.trackers)
        reg.gauge("live_runs_active",
                  "runs currently tracked by the live checker"
                  ).set(active)
        reg.counter("live_polls_total", "daemon poll loops").inc()
        reg.histogram("live_poll_seconds",
                      "wall time of one full daemon poll"
                      ).observe(time.perf_counter() - t0)
        if tracer.enabled:
            tracer.complete(trace_mod.TRACK_LIVE, "poll", poll_t0,
                            trace_mod.now_us() - poll_t0,
                            args={"runs": len(trackers)})
        self._export()
        return statuses

    def _observe_check(self, tr: RunTracker, n_ops: int,
                       seconds: float) -> None:
        reg = self.registry
        workload = (tr.session.workload if tr.session is not None
                    else "none")
        reg.histogram("live_check_seconds",
                      "incremental verdict dispatch wall time",
                      labels=("workload",)).observe(seconds,
                                                    workload=workload)
        if n_ops > 0 and seconds > 0:
            # feed the shared cost model so admission budgets track the
            # measured host instead of the built-in default
            from jepsen_tpu.parallel.pipeline import observe_cpu_rate
            observe_cpu_rate(n_ops, seconds)

    def _run_label(self, label: str) -> str:
        """Bounded {run} label interning for per-run counters: the first
        ``run_series_topk`` distinct runs keep their exact label; every
        later run shares ``"other"`` so a fleet-scale store can't blow
        up prom series cardinality. Counters can't be re-labeled after
        the fact (their value is cumulative), so the mapping is sticky
        for the daemon's lifetime."""
        got = self._run_labels.get(label)
        if got is not None:
            return got
        if len(self._run_labels) < self.run_series_topk:
            self._run_labels[label] = label
            return label
        return "other"

    def _publish_run_series(self, rows: list) -> None:
        """Rebuilds the {run}-labeled gauges from this poll's statuses:
        exact series for the top-K most-lagged runs, one ``run="other"``
        aggregate for the rest (worst lag / worst verdict / summed open
        breakers), and the unlabeled fleet rollups. Gauges are cleared
        first so runs that finished or fell out of the top K don't
        linger as stale series."""
        reg = self.registry
        lag_g = reg.gauge("live_checker_lag_ops",
                          "ops absorbed but not yet covered by a verdict",
                          labels=("run",))
        lag_s_g = reg.gauge("live_checker_lag_s",
                            "seconds since this run's checker last "
                            "caught up", labels=("run",))
        verdict_g = reg.gauge("live_verdict",
                              "1 valid-so-far, 0 invalid, -1 "
                              "unknown/untracked", labels=("run",))
        first_g = reg.gauge("live_first_anomaly_op",
                            "history index of the first anomaly "
                            "(-1: none found)", labels=("run",))
        breaker_g = reg.gauge("live_run_breaker_open",
                              "1 while a run's checker circuit breaker "
                              "is open (other: open-breaker count)",
                              labels=("run",))
        for g in (lag_g, lag_s_g, verdict_g, first_g, breaker_g):
            g.clear()

        ranked = sorted(rows, key=lambda r: r[1]["lag_ops"],
                        reverse=True)
        exact, other = ranked[:self.run_series_topk], \
            ranked[self.run_series_topk:]
        for tr, st in exact:
            run = tr.label
            lag_g.set(st["lag_ops"], run=run)
            lag_s_g.set(st["lag_s"], run=run)
            valid = st.get("valid_so_far")
            verdict_g.set(
                1.0 if valid is True else
                0.0 if valid is False else -1.0, run=run)
            first = st.get("first_anomaly_op")
            first_g.set(-1.0 if first is None else float(first),
                        run=run)
            if tr.broken:
                breaker_g.set(1.0, run=run)
        if other:
            sts = [st for _, st in other]
            lag_g.set(max(st["lag_ops"] for st in sts), run="other")
            lag_s_g.set(max(st["lag_s"] for st in sts), run="other")
            valids = [st.get("valid_so_far") for st in sts]
            # worst-case ordering: any invalid beats any unknown beats
            # all-valid (a plain min() would rank unknown below invalid)
            verdict_g.set(
                0.0 if any(v is False for v in valids) else
                -1.0 if any(v is None for v in valids) else 1.0,
                run="other")
            broken = sum(1 for tr, _ in other if tr.broken)
            if broken:
                breaker_g.set(float(broken), run="other")

        # unlabeled fleet rollups: always cheap to scrape no matter how
        # many runs the pool holds
        all_sts = [st for _, st in rows]
        reg.gauge("fleet_runs_active",
                  "runs tracked by this pool that are not yet final"
                  ).set(sum(1 for st in all_sts
                            if st.get("state") != "final"))
        reg.gauge("fleet_worst_lag_ops",
                  "largest per-run checker lag across the pool"
                  ).set(max((st["lag_ops"] for st in all_sts),
                            default=0))
        reg.gauge("fleet_invalid_runs",
                  "runs whose live verdict is invalid-so-far"
                  ).set(sum(1 for st in all_sts
                            if st.get("valid_so_far") is False))

    def _export(self) -> None:
        d = self.store_root
        if d is None:
            d = (self.run_dirs[0].parent.parent if self.run_dirs
                 else None)
        if d is None:
            return
        try:
            self.registry.export(d, prefix="live-metrics")
        except Exception:  # noqa: BLE001 — export never stops the poller
            logger.exception("live metrics export failed")

    # -- lifecycle ------------------------------------------------------

    def _loop(self) -> None:  # owner: scheduler
        while not self._stop.is_set():
            t0 = time.monotonic()
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — the poller must survive anything
                logger.exception("live poll failed")
            rest = self.poll_s - (time.monotonic() - t0)
            if rest > 0:
                self._stop.wait(rest)

    def start(self) -> "LiveDaemon":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="jepsen-live-poller")
        self._thread.start()
        return self

    def stop(self) -> None:
        """Wedge-proof shutdown: signal, then join with bounded-wait
        heartbeats (utils.join_noisy); one final metrics export."""
        self._stop.set()
        t = self._thread
        if t is not None:
            join_noisy(t, "live daemon poller", heartbeat_s=5.0)
            self._thread = None
        if self.lease_store is not None:
            # clean shutdown hands runs over immediately instead of
            # making the adopter wait out the TTL
            with self._lock:
                epochs = {key: self._lease_epochs.pop(key, -1)
                          for key in list(self.trackers)}
            for key, epoch in epochs.items():
                self.lease_store.release(key, epoch)
        self._export()

    def run_until_idle(self, timeout_s: float = 60.0) -> dict:
        """Foreground helper (tests, ``--once``): polls until every
        tracked run has finalized (or ``timeout_s`` elapses); returns
        the last status snapshot."""
        deadline = time.monotonic() + timeout_s
        statuses: dict = {}
        while time.monotonic() < deadline:
            statuses = self.poll_once()
            with self._lock:
                active = len(self.trackers)
            if not active:
                break
            # honor the configured cadence (--poll): a foreground --once
            # over long-running tests must not re-scan/re-export at 20 Hz
            time.sleep(min(self.poll_s,
                           max(0.0, deadline - time.monotonic())))
        return statuses


def serve(store_root: str | None, run_dirs=(), **kw) -> None:
    """``jepsen-tpu live``: runs the daemon in the foreground until
    interrupted."""
    daemon = LiveDaemon(store_root=store_root, run_dirs=run_dirs, **kw)
    daemon.start()
    logger.info("live checker daemon polling every %.3gs (ctrl-C stops)",
                daemon.poll_s)
    try:
        while True:
            time.sleep(1.0)
    except KeyboardInterrupt:
        pass
    finally:
        daemon.stop()
