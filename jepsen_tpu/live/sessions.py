"""Per-run incremental checker sessions for the live daemon.

Each session absorbs ops one at a time as a run's WAL streams in and
answers ``verdict()`` — "valid so far" or "first anomaly at op N" —
without re-reading or re-encoding the prefix it has already seen:

* :class:`LinearLiveSession` — single-register linearizability. The
  history IR's incremental register encoder
  (:class:`jepsen_tpu.history_ir.builder.LiveRegisterEncoder`, the
  streaming twin of the ``views.register_stream`` view) feeds a
  resumable
  :class:`~jepsen_tpu.checker.linear_cpu.FrontierSession`; verdict
  dispatches ride a :class:`~jepsen_tpu.checker.ladder.BackendLadder`
  (transfer-matrix device screen over the accumulated stream when in
  regime, exact CPU frontier as the terminal rung) so the live path
  inherits the post-hoc checker's demotion/watchdog/breaker policy.
* :class:`ElleSession` — list-append transactional anomalies. The
  PyObject-heavy build phase (event pairing + micro-op flattening +
  key interning — the ``phase_build_s`` that dominates a post-hoc Elle
  check ~7:1, BENCH_r04) is the history IR's incremental Elle builder
  (:class:`jepsen_tpu.history_ir.builder.LiveElleColumns`), run once
  per op as it arrives; each verdict then only pays the vectorized
  assemble + cycle check (``elle.columnar._assemble`` — the exact
  batch code path, so the final live verdict cannot diverge from
  ``cli analyze``).

Both sessions are thin adapters over
:mod:`jepsen_tpu.history_ir.builder` — the encode state machines live
with the IR, the sessions own only verdict dispatch/ladder policy.

Sessions are single-threaded by contract: the daemon's poller owns
them; nothing here takes locks.
"""
from __future__ import annotations

import logging
from typing import Any

from jepsen_tpu.checker.linear_cpu import (
    FrontierSession, cas_register_step_py,
)
from jepsen_tpu.checker.linear_encode import EV_RETURN
from jepsen_tpu.history import Intern

logger = logging.getLogger("jepsen.live.sessions")

# device-regime threshold for the live matrix screen: same constant the
# post-hoc checker routes on (checker/linearizable.AUTO_TPU_THRESHOLD)
from jepsen_tpu.checker.linearizable import AUTO_TPU_THRESHOLD  # noqa: E402

from jepsen_tpu.elle.columnar import _MAX_KIDS  # noqa: E402


# the incremental encode state machine lives with the history IR
from jepsen_tpu.history_ir.builder import (  # noqa: E402
    LiveRegisterEncoder as _LiveRegisterEncoder,
)

class LinearLiveSession:
    """Streaming single-register linearizability over a WAL tail."""

    workload = "register"

    def __init__(self, accelerator: str = "auto", model_value=None):
        self.accelerator = accelerator
        self.intern = Intern()
        init_id = (0 if model_value is None
                   else self.intern.id(model_value))
        self._spec_init = init_id
        self.encoder = _LiveRegisterEncoder(self.intern)
        self.frontier = FrontierSession(step=cas_register_step_py,
                                        init_state=init_id,
                                        algorithm="jitlin-cpu-live")
        self._ladder = None
        self._last = {"valid_so_far": True, "first_anomaly_op": None,
                      "backend": "frontier-cpu", "checked_ops": 0}
        self._broken: str | None = None
        # latched device localization: an invalid prefix stays invalid
        # with the SAME first anomaly (frontier death is monotone), so
        # later polls answer from the latch instead of re-bisecting
        self._matrix_first: int | None = None

    # -- ingestion ------------------------------------------------------

    def add(self, op: dict) -> None:
        if self._broken:
            return
        try:
            self.encoder.add(op)
        except Exception as e:  # noqa: BLE001 — a bad op poisons, not kills
            self._broken = f"unencodable op: {e!r}"
            logger.exception("live register session poisoned")

    def add_many(self, ops: list) -> None:
        """Chunked ingest: one native call per WAL poll instead of a
        Python frame per op (doc/performance.md "Host ingest spine"),
        with the same poison-not-kill contract as :meth:`add`."""
        if self._broken:
            return
        try:
            self.encoder.add_many(ops)
        except Exception as e:  # noqa: BLE001 — a bad op poisons, not kills
            self._broken = f"unencodable op: {e!r}"
            logger.exception("live register session poisoned")

    @property
    def ops_absorbed(self) -> int:
        return self.encoder.ops_seen

    @property
    def checked_ops(self) -> int:
        return self._last["checked_ops"]

    def last(self) -> dict:
        return dict(self._last)

    # -- ladder ---------------------------------------------------------

    def _get_ladder(self):
        if self._ladder is not None:
            return self._ladder
        from jepsen_tpu.checker.ladder import Backend, BackendLadder

        def matrix_eligible(ctx):
            stream = ctx["session"].encoder.stream
            if self.accelerator == "cpu" or (
                    self.accelerator == "auto"
                    and len(stream) < AUTO_TPU_THRESHOLD):
                return False
            from jepsen_tpu.ops.jitlin import matrix_ok
            n_returns = sum(1 for k in stream.kind if k == EV_RETURN)
            return matrix_ok(stream.n_slots, len(stream.intern),
                             n_returns)

        def matrix_fn(ctx):
            # stateless full-prefix screen: exact True settles this
            # poll's verdict without touching the CPU frontier (which
            # catches up from its own offset on the next demotion).
            # Big prefixes shard over the device mesh when the cost
            # model clears it (doc/performance.md "Multi-device
            # sharding"); a collective failure retries single-device
            # inline — the daemon's poll cadence must not burn a whole
            # ladder demotion on a transient mesh fault.
            from jepsen_tpu import parallel
            from jepsen_tpu.models import cas_register_spec
            from jepsen_tpu.ops.jitlin import matrix_check, matrix_localize
            session = ctx["session"]
            if self._matrix_first is not None:
                # an invalid prefix stays invalid at the same op: the
                # latched localization answers without re-screening
                return {"valid_so_far": False,
                        "first_anomaly_op": self._matrix_first,
                        "checked_ops": session.encoder.ops_encoded}
            es = session.encoder.stream.to_event_stream()
            spec = cas_register_spec(self._spec_init)
            mesh = parallel.sharded_mesh_for(len(es.kind))
            try:
                m = matrix_check(es, step_ids=spec.step_ids,
                                 init_state=spec.init_state,
                                 num_states=len(es.intern), mesh=mesh)
            except Exception:  # noqa: BLE001 — mesh fault: one device
                if mesh is None:
                    raise
                logger.warning("sharded live matrix screen failed; "
                               "retrying single-device", exc_info=True)
                m = matrix_check(es, step_ids=spec.step_ids,
                                 init_state=spec.init_state,
                                 num_states=len(es.intern))
            if m is not None and m[0] and not m[2]:
                return {"valid_so_far": True, "first_anomaly_op": None,
                        "checked_ops": session.encoder.ops_encoded}
            if m is not None and not m[0] and not m[2]:
                # exact INVALID: localize on device (the forensics
                # bisection — doc/observability.md "Anomaly forensics")
                # so the live screen reports the precise first anomaly
                # instead of deferring to the slow CPU frontier rung
                try:
                    loc = matrix_localize(es, step_ids=spec.step_ids,
                                          init_state=spec.init_state,
                                          num_states=len(es.intern))
                except Exception:  # noqa: BLE001 — frontier settles it
                    logger.exception("live matrix localization failed")
                    loc = None
                if loc is not None:
                    self._matrix_first = int(loc.failed_op_index)
                    return {"valid_so_far": False,
                            "first_anomaly_op": self._matrix_first,
                            "checked_ops": session.encoder.ops_encoded}
            return None  # inexact/declined: the exact frontier settles it

        def frontier_fn(ctx):
            session = ctx["session"]
            fs = session.frontier
            res = fs.absorb(session.encoder.stream,
                            start=fs.events_absorbed)
            first = (None if res.valid is True
                     else int(res.failed_op_index))
            return {"valid_so_far": res.valid,
                    "first_anomaly_op": first,
                    "checked_ops": session.encoder.ops_encoded}

        self._ladder = BackendLadder([
            Backend("pallas-matrix", matrix_fn, eligible=matrix_eligible,
                    device=True),
            Backend("frontier-cpu", frontier_fn),
        ])
        return self._ladder

    # -- verdicts -------------------------------------------------------

    def coverage_probe(self) -> dict:
        """Checker-state coverage for the schedule fuzzer
        (doc/robustness.md "Schedule fuzzing"): the frontier's
        cardinality buckets + near-miss margin merged with the ladder's
        rung-regime entries. Sessions are per-run, so the probe is a
        per-trial signal without any reset bookkeeping."""
        probe = self.frontier.coverage_probe()
        edges = list(probe.get("edges") or [])
        if self._ladder is not None:
            edges.extend(self._ladder.coverage_probe().get("edges") or ())
        return {"edges": edges, "margin": probe.get("margin"),
                "died": bool(probe.get("died"))}

    def verdict(self) -> dict:
        """Advances the checkable prefix and returns the live verdict:
        ``{valid_so_far, first_anomaly_op, backend, checked_ops}``."""
        if self._broken:
            return {**self._last, "valid_so_far": "unknown",
                    "error": self._broken}
        self.encoder.encode_resolved()
        out, backend = self._get_ladder().run({"session": self})
        out["backend"] = backend
        self._last = out
        return dict(out)

    # -- durable snapshots (the daemon's restart path) ------------------

    def snapshot(self) -> dict | None:
        """The session's resumable state as a JSON-serializable dict, or
        None when it can't be serialized faithfully (poisoned session,
        exotic values) — the daemon then re-ingests the WAL from zero
        on restart, slower but never wrong."""
        if self._broken:
            return None
        enc = self.encoder.snapshot()
        if enc is None:
            return None
        frontier = self.frontier.snapshot()
        if frontier is None:
            return None
        return {
            "workload": self.workload,
            "spec_init": self._spec_init,
            "encoder": enc,
            "frontier": frontier,
            "matrix_first": self._matrix_first,
            "last": dict(self._last),
        }

    @classmethod
    def restore(cls, snap: dict, accelerator: str = "auto"):
        """A session rebuilt from :meth:`snapshot`, or None on a
        malformed snapshot."""
        try:
            enc = _LiveRegisterEncoder.restore(snap["encoder"])
            if enc is None:
                return None
            init_id = int(snap["spec_init"])
            frontier = FrontierSession.restore(
                snap["frontier"], step=cas_register_step_py,
                init_state=init_id, algorithm="jitlin-cpu-live")
            if frontier is None:
                return None
            sess = cls(accelerator=accelerator)
            sess.intern = enc.intern
            sess._spec_init = init_id
            sess.encoder = enc
            sess.frontier = frontier
            sess._matrix_first = snap.get("matrix_first")
            last = snap.get("last")
            if isinstance(last, dict):
                sess._last = last
            return sess
        except (KeyError, TypeError, ValueError):
            return None

    def finalize(self) -> dict:
        """End-of-run verdict: resolves the still-open tail exactly as
        the batch encoder would, then settles on the exact CPU frontier
        (so ``failed-op-index`` is precise)."""
        if self._broken:
            return {"valid?": "unknown", "error": self._broken,
                    "algorithm": "jitlin-cpu-live"}
        self.encoder.finalize()
        res = self.frontier.absorb(self.encoder.stream,
                                   start=self.frontier.events_absorbed)
        self._last = {
            "valid_so_far": res.valid,
            "first_anomaly_op": (None if res.valid is True
                                 else int(res.failed_op_index)),
            "backend": "frontier-cpu", "checked_ops":
                self.encoder.ops_encoded,
        }
        out: dict[str, Any] = {
            "valid?": res.valid,
            "algorithm": res.algorithm,
            "configs-max": res.configs_max,
        }
        if res.valid is False and res.failed_op_index >= 0:
            out["failed-op-index"] = int(res.failed_op_index)
        return out


class ElleSession:
    """Streaming list-append Elle: incremental graph-build columns.

    ``add`` runs the per-op build work (event pairing, micro-op
    flattening, key interning) exactly once per op; ``verdict`` pays
    only the vectorized assemble + φ-cluster cycle check. A history
    outside the integer columnar regime (exotic keys, non-int payload
    elements) poisons the incremental columns and every later verdict
    falls back to the batch checker over the retained history — slower,
    never wrong."""

    workload = "list-append"

    def __init__(self, accelerator: str = "auto",
                 consistency_models=("strict-serializable",)):
        from jepsen_tpu.history_ir.builder import LiveElleColumns
        self.accelerator = accelerator
        self.consistency_models = tuple(consistency_models)
        self.history: list[dict] = []
        self._cols = LiveElleColumns()
        self._last = {"valid_so_far": True, "first_anomaly_op": None,
                      "backend": "columnar-incremental", "checked_ops": 0}

    @property
    def _fallback(self):
        return self._cols.fallback

    @property
    def ops_absorbed(self) -> int:
        return len(self.history)

    @property
    def checked_ops(self) -> int:
        return self._last["checked_ops"]

    def last(self) -> dict:
        return dict(self._last)

    def add(self, op: dict) -> None:
        i = len(self.history)
        self.history.append(op)
        self._cols.absorb(i, op)

    def add_many(self, ops: list) -> None:
        for op in ops:
            self.add(op)

    def _check_batch(self) -> dict:
        from jepsen_tpu.elle import list_append
        return list_append.check(
            self.history, accelerator=self.accelerator,
            consistency_models=self.consistency_models)

    def _update_last(self, result: dict) -> dict:
        first = None
        if result.get("valid?") is not True:
            first = _first_anomaly_op(result, self.history)
        self._last = {
            "valid_so_far": result.get("valid?"),
            "first_anomaly_op": first,
            "anomaly_types": result.get("anomaly-types") or [],
            "backend": ("batch-fallback" if self._fallback
                        else "columnar-incremental"),
            "checked_ops": len(self.history),
        }
        return dict(self._last)

    def verdict(self) -> dict:
        return self._update_last(self._result())

    def snapshot(self) -> dict | None:
        # an Elle session's state IS the whole retained history (the
        # batch fallback needs every op) — a snapshot would be as large
        # as the WAL it replaces, so restarts re-ingest instead
        # (documented limitation, doc/robustness.md)
        return None

    def finalize(self) -> dict:
        out = self._result()
        self._update_last(out)
        return out

    def _result(self) -> dict:
        """The full checker result map over everything absorbed — the
        same shape ``elle.list_append.check`` returns."""
        import numpy as np

        from jepsen_tpu import elle
        from jepsen_tpu.elle import columnar

        cols = self._cols
        if cols.fallback or len(cols.raw_key) >= _MAX_KIDS:
            return self._check_batch()
        ok, info = cols.ok, cols.info
        n_ok = len(ok.pos)
        txns = ok.txns + info.txns
        if not txns:
            return {"valid?": True, "anomaly-types": [], "not": [],
                    "anomalies": {}, "txn-count": 0, "edge-count": 0,
                    "builder": "columnar-incremental"}
        parts = columnar._assemble(
            txns=txns, n_ok=n_ok, raw_key=cols.raw_key,
            a_txn=ok.a_txn + [n_ok + t for t in info.a_txn],
            a_kid=ok.a_kid + info.a_kid,
            a_val=ok.a_val + info.a_val,
            a_mi=ok.a_mi + info.a_mi,
            r_txn=ok.r_txn + [n_ok + t for t in info.r_txn],
            r_kid=ok.r_kid + info.r_kid,
            r_mi=ok.r_mi + info.r_mi,
            payloads=ok.payloads + info.payloads,
            f_kid=list(cols.f_kid), f_val=list(cols.f_val),
            node_pos=np.asarray(ok.pos + info.pos, np.int64),
            node_inv=np.asarray(ok.inv + info.inv, np.int64),
            node_proc=np.asarray(ok.proc + info.proc, np.int64))
        if parts is None:  # regime miss the per-op checks didn't catch
            cols.fallback = "assemble regime miss"
            return self._check_batch()
        graph, txns, extras, nk = parts
        cyc = elle.check_cycles(graph, accelerator=self.accelerator)
        merged = {k: v for k, v in extras.items()
                  if k != "unobserved-writer"}
        result = elle.result_map(
            cyc, txns, merged, consistency_models=self.consistency_models)
        result["txn-count"] = graph.n
        result["edge-count"] = graph.edge_count()
        result["builder"] = "columnar-incremental"
        return result


def _first_anomaly_op(result: dict, history: list[dict]) -> int | None:
    """Best-effort history index of the first anomalous txn cited by an
    Elle result (cycles cite txn values; extras cite reads/writers) —
    the "first anomaly at op N" surface. None when nothing matched."""
    cited: list = []
    for cycles in (result.get("anomalies") or {}).values():
        for item in cycles if isinstance(cycles, list) else ():
            for hop in item if isinstance(item, list) else ():
                if isinstance(hop, dict):
                    cited.extend([hop.get("from"), hop.get("to"),
                                  hop.get("read"), hop.get("read-txn"),
                                  hop.get("writer")])
    idx = None
    for i, op in enumerate(history):
        if op.get("type") not in ("ok", "info"):
            continue
        v = op.get("value")
        if v is None:
            continue
        if any(c is not None and c == v for c in cited):
            idx = i if idx is None else min(idx, i)
    return idx


class MultiKeyLinearSession:
    """Streaming linearizability over an :mod:`jepsen_tpu.independent`
    key-lifted register history: demuxes ``[k, v]`` tuple values into
    one :class:`LinearLiveSession` per key (the streaming twin of
    ``independent.subhistory`` — ops without a tuple value are outside
    every sub-history there too, so they only count toward lag)."""

    workload = "register-independent"

    def __init__(self, accelerator: str = "auto"):
        self.accelerator = accelerator
        self.sub: dict = {}
        self.ops_absorbed = 0
        self._last = {"valid_so_far": True, "first_anomaly_op": None,
                      "backend": "frontier-cpu", "checked_ops": 0}

    def add(self, op: dict) -> None:
        from jepsen_tpu import independent
        self.ops_absorbed += 1
        v = op.get("value")
        if not independent.is_tuple_value(v):
            return
        k = independent._freeze_key(v[0])
        sess = self.sub.get(k)
        if sess is None:
            sess = self.sub[k] = LinearLiveSession(
                accelerator=self.accelerator)
        sess.add({**op, "value": v[1]})

    def add_many(self, ops: list) -> None:
        for op in ops:
            self.add(op)

    @property
    def checked_ops(self) -> int:
        routed = sum(s.ops_absorbed for s in self.sub.values())
        checked = sum(s.checked_ops for s in self.sub.values())
        # unroutable ops (nemesis, value-less infos) need no checking
        return self.ops_absorbed - routed + checked

    def last(self) -> dict:
        return dict(self._last)

    def _merge(self, per_key: dict) -> dict:
        valids = [r.get("valid_so_far") for r in per_key.values()]
        valid = (False if any(x is False for x in valids)
                 else "unknown" if any(x == "unknown" for x in valids)
                 else True)
        firsts = [r.get("first_anomaly_op") for r in per_key.values()
                  if r.get("first_anomaly_op") is not None]
        self._last = {
            "valid_so_far": valid,
            "first_anomaly_op": min(firsts) if firsts else None,
            "backend": "frontier-cpu",
            "checked_ops": self.checked_ops,
            "keys": len(self.sub),
        }
        return dict(self._last)

    def snapshot(self) -> dict | None:
        """Composes the per-key sessions' snapshots; any unsnapshotable
        key rejects the whole (a partial restore would silently drop a
        key's history)."""
        subs = []
        for k, s in self.sub.items():
            sub = s.snapshot()
            if sub is None:
                return None
            key = list(k) if isinstance(k, tuple) else k
            subs.append([key, sub])
        try:
            import json
            if json.loads(json.dumps(subs)) != subs:
                return None
        except (TypeError, ValueError):
            return None
        return {"workload": self.workload,
                "ops_absorbed": self.ops_absorbed,
                "last": dict(self._last), "sub": subs}

    @classmethod
    def restore(cls, snap: dict, accelerator: str = "auto"):
        try:
            from jepsen_tpu.independent import _freeze_key
            sess = cls(accelerator=accelerator)
            sess.ops_absorbed = int(snap["ops_absorbed"])
            last = snap.get("last")
            if isinstance(last, dict):
                sess._last = last
            for key, sub in snap["sub"]:
                restored = LinearLiveSession.restore(
                    sub, accelerator=accelerator)
                if restored is None:
                    return None
                sess.sub[_freeze_key(key)] = restored
            return sess
        except (KeyError, TypeError, ValueError):
            return None

    def verdict(self) -> dict:
        return self._merge({k: s.verdict() for k, s in self.sub.items()})

    def finalize(self) -> dict:
        results = {str(k): s.finalize() for k, s in self.sub.items()}
        self._merge({k: s.last() for k, s in self.sub.items()})
        valid = self._last["valid_so_far"]
        return {
            "valid?": valid,
            "count": len(results),
            "failures": sorted(k for k, r in results.items()
                               if r.get("valid?") is not True),
            "results": results,
        }


#: session_for_ops sentinel: client ops seen, no live checker matches
UNSUPPORTED = object()


def restore_session(snap, accelerator: str = "auto"):
    """A session rebuilt from a tracker snapshot's ``session`` payload
    (the daemon's restart path), or None when the payload is missing,
    names an unknown workload, or fails to restore — the tracker then
    re-ingests the WAL from zero."""
    if not isinstance(snap, dict):
        return None
    workload = snap.get("workload")
    if workload == "register":
        return LinearLiveSession.restore(snap, accelerator=accelerator)
    if workload == "register-independent":
        return MultiKeyLinearSession.restore(snap, accelerator=accelerator)
    return None


def session_for_ops(ops: list[dict], accelerator: str = "auto"):
    """Sniffs the workload from the first client invocations and builds
    the matching session. Returns None while the evidence is still
    ambiguous (keep buffering), or :data:`UNSUPPORTED` when the
    workload has no live checker (the tracker then reports lag only)."""
    from jepsen_tpu.independent import is_tuple_value
    for op in ops:
        p, f = op.get("process"), op.get("f")
        if not isinstance(p, int) or p < 0 or f is None:
            continue
        v = op.get("value")
        if f in ("read", "write"):
            # plain registers carry None/scalar values; key-lifted ones
            # carry [k, v] tuples (independent.tuple_value)
            if is_tuple_value(v):
                return MultiKeyLinearSession(accelerator=accelerator)
            return LinearLiveSession(accelerator=accelerator)
        if f == "cas":
            # plain cas: [u, v] scalars; lifted cas: [k, [u, v]]
            if is_tuple_value(v) and isinstance(v[1], (list, tuple)):
                return MultiKeyLinearSession(accelerator=accelerator)
            if is_tuple_value(v):
                return LinearLiveSession(accelerator=accelerator)
            continue  # malformed/valueless cas: keep sniffing
        if f == "txn":
            mops = op.get("value") or ()
            fs = {m[0] for m in mops if isinstance(m, (list, tuple)) and m}
            if not fs:
                continue
            if fs <= {"append", "r"}:
                return ElleSession(accelerator=accelerator)
            return UNSUPPORTED  # multi-register txns: no live checker yet
        return UNSUPPORTED
    return None
