"""Per-run incremental checker sessions for the live daemon.

Each session absorbs ops one at a time as a run's WAL streams in and
answers ``verdict()`` — "valid so far" or "first anomaly at op N" —
without re-reading or re-encoding the prefix it has already seen:

* :class:`LinearLiveSession` — single-register linearizability. An
  incremental twin of ``checker.linear_encode.encode_register_ops``
  feeds a resumable
  :class:`~jepsen_tpu.checker.linear_cpu.FrontierSession`; verdict
  dispatches ride a :class:`~jepsen_tpu.checker.ladder.BackendLadder`
  (transfer-matrix device screen over the accumulated stream when in
  regime, exact CPU frontier as the terminal rung) so the live path
  inherits the post-hoc checker's demotion/watchdog/breaker policy.
* :class:`ElleSession` — list-append transactional anomalies. The
  PyObject-heavy build phase (event pairing + micro-op flattening +
  key interning — the ``phase_build_s`` that dominates a post-hoc Elle
  check ~7:1, BENCH_r04) runs once per op as it arrives; each verdict
  then only pays the vectorized assemble + cycle check
  (``elle.columnar._assemble`` — the exact batch code path, so the
  final live verdict cannot diverge from ``cli analyze``).

Sessions are single-threaded by contract: the daemon's poller owns
them; nothing here takes locks.
"""
from __future__ import annotations

import logging
from typing import Any

from jepsen_tpu.checker.linear_cpu import (
    FrontierSession, cas_register_step_py,
)
from jepsen_tpu.checker.linear_encode import EV_INVOKE, EV_RETURN
from jepsen_tpu.history import Intern
from jepsen_tpu.txn import _hk

logger = logging.getLogger("jepsen.live.sessions")

# device-regime threshold for the live matrix screen: same constant the
# post-hoc checker routes on (checker/linearizable.AUTO_TPU_THRESHOLD)
from jepsen_tpu.checker.linearizable import AUTO_TPU_THRESHOLD  # noqa: E402

from jepsen_tpu.elle.columnar import (  # noqa: E402
    _MAX_KIDS, _MAX_MOPS, _MAX_VAL,
)


class _ListStream:
    """A growing, list-backed event stream the FrontierSession can
    absorb from directly (plain-int lists index faster than numpy
    scalars on the Python step loop) and that converts to a real
    EventStream for device dispatch on demand."""

    __slots__ = ("kind", "slot", "f", "a", "b", "op_index", "intern",
                 "n_slots")

    def __init__(self, intern: Intern):
        self.kind: list[int] = []
        self.slot: list[int] = []
        self.f: list[int] = []
        self.a: list[int] = []
        self.b: list[int] = []
        self.op_index: list[int] = []
        self.intern = intern
        self.n_slots = 1

    def __len__(self):
        return len(self.kind)

    def to_event_stream(self):
        import numpy as np

        from jepsen_tpu.checker.linear_encode import EventStream
        return EventStream(
            kind=np.asarray(self.kind, np.int8),
            slot=np.asarray(self.slot, np.int32),
            f=np.asarray(self.f, np.int32),
            a=np.asarray(self.a, np.int32),
            b=np.asarray(self.b, np.int32),
            op_index=np.asarray(self.op_index, np.int32),
            n_slots=self.n_slots,
            n_ops=sum(1 for k in self.kind if k == EV_INVOKE),
            intern=self.intern,
        )


class _LiveRegisterEncoder:
    """Incremental twin of ``encode_register_ops``: absorbs history ops
    in order and emits the identical event sequence (pinned by a
    differential fuzz in tests/test_live.py).

    The batch encoder resolves each invoke by looking ahead at its
    completion (fail pairs drop, crashed reads drop, a read's value
    completes from its :ok). Online, the look-ahead becomes a stall:
    encoding advances through the history strictly in order and pauses
    at the first invoke whose completion hasn't arrived yet — the
    *checkable prefix*. The stall is bounded by the run's concurrency
    (plus the per-op deadline that reaps hung ops to :info), and it is
    exactly the live checker's intrinsic lag."""

    def __init__(self, intern: Intern, encode_args=None):
        self.intern = intern
        self.stream = _ListStream(intern)
        if encode_args is None:
            from jepsen_tpu.models import (
                CAS_F_CAS, CAS_F_READ, CAS_F_WRITE,
            )

            def encode_args(op):
                f, v = op.get("f"), op.get("value")
                if f == "read":
                    return CAS_F_READ, intern.id(v), 0
                if f == "write":
                    return CAS_F_WRITE, intern.id(v), 0
                if f == "cas":
                    u, w = v
                    return CAS_F_CAS, intern.id(u), intern.id(w)
                raise ValueError(f"unknown register op {f!r}")
        self.encode_args = encode_args
        self._ops: list[dict] = []          # raw history, arrival order
        self._next = 0                      # next history index to encode
        self._open_inv: dict = {}           # process -> open invoke index
        self._outcome: dict[int, tuple] = {}  # invoke idx -> resolution
        # second-pass state (slot allocation), advanced in order only
        self._open_by_process: dict = {}
        self._free_slots: list[int] = []
        self._next_slot = 0
        self._finalized = False

    # -- arrival (first-pass resolution) --------------------------------

    def add(self, op: dict) -> None:
        i = len(self._ops)
        self._ops.append(op)
        p, typ = op.get("process"), op.get("type")
        if not isinstance(p, int) or p < 0:
            return
        if typ == "invoke":
            j = self._open_inv.pop(p, None)
            if j is not None:
                # overwritten invoke: never completed, never dropped by
                # the batch encoder either — encode it, return-less
                self._outcome[j] = ("keep",)
            self._open_inv[p] = i
        elif typ == "fail":
            j = self._open_inv.pop(p, None)
            if j is not None:
                self._outcome[j] = ("drop",)
        elif typ == "ok":
            j = self._open_inv.pop(p, None)
            if j is not None:
                v = op.get("value")
                self._outcome[j] = (("ok", v) if v is not None
                                    else ("keep",))
        elif typ == "info":
            j = self._open_inv.pop(p, None)
            if j is not None:
                self._outcome[j] = (
                    ("drop",) if self._ops[j].get("f") == "read"
                    else ("keep",))

    # -- encoding (second pass, in order, stalls at unresolved) ---------

    def encode_resolved(self) -> int:
        """Advances the encoder over every op whose resolution is known;
        returns the new count of encoded history ops (the checkable
        prefix length)."""
        ops = self._ops
        st = self.stream
        # hot loop: bound methods/locals hoisted — this runs once per
        # history op at WAL-ingest rate
        kind_app, slot_app = st.kind.append, st.slot.append
        f_app, a_app, b_app = st.f.append, st.a.append, st.b.append
        idx_app = st.op_index.append
        outcome_get = self._outcome.get
        free_slots = self._free_slots
        open_bp = self._open_by_process
        encode_args = self.encode_args
        n = len(ops)
        i = self._next
        while i < n:
            op = ops[i]
            p = op.get("process")
            typ = op.get("type")
            if not isinstance(p, int) or p < 0:
                i += 1
                continue
            if typ == "invoke":
                outcome = outcome_get(i)
                if outcome is None:
                    if not self._finalized:
                        break  # stall: completion not seen yet
                    # end of run: open reads never happened, open
                    # mutations stay pending forever (batch semantics)
                    outcome = (("drop",) if op.get("f") == "read"
                               else ("keep",))
                if outcome[0] == "drop":
                    i += 1
                    continue
                if free_slots:
                    s = free_slots.pop()
                else:
                    s = self._next_slot
                    self._next_slot += 1
                    st.n_slots = max(st.n_slots, self._next_slot)
                open_bp[p] = s
                inv = op
                if outcome[0] == "ok":
                    inv = dict(op)
                    inv["value"] = outcome[1]
                fcode, a, b = encode_args(inv)
                kind_app(EV_INVOKE)
                slot_app(s)
                f_app(fcode)
                a_app(a)
                b_app(b)
                idx_app(i)
            elif typ == "ok":
                s = open_bp.pop(p, None)
                if s is not None:
                    kind_app(EV_RETURN)
                    slot_app(s)
                    f_app(0)
                    a_app(0)
                    b_app(0)
                    idx_app(i)
                    free_slots.append(s)
            # fail/info: dropped pair / no return event — the crashed
            # op's slot stays occupied forever
            i += 1
        self._next = i
        return i

    def finalize(self) -> int:
        self._finalized = True
        return self.encode_resolved()

    @property
    def ops_seen(self) -> int:
        return len(self._ops)

    @property
    def ops_encoded(self) -> int:
        return self._next


class LinearLiveSession:
    """Streaming single-register linearizability over a WAL tail."""

    workload = "register"

    def __init__(self, accelerator: str = "auto", model_value=None):
        self.accelerator = accelerator
        self.intern = Intern()
        init_id = (0 if model_value is None
                   else self.intern.id(model_value))
        self._spec_init = init_id
        self.encoder = _LiveRegisterEncoder(self.intern)
        self.frontier = FrontierSession(step=cas_register_step_py,
                                        init_state=init_id,
                                        algorithm="jitlin-cpu-live")
        self._ladder = None
        self._last = {"valid_so_far": True, "first_anomaly_op": None,
                      "backend": "frontier-cpu", "checked_ops": 0}
        self._broken: str | None = None
        # latched device localization: an invalid prefix stays invalid
        # with the SAME first anomaly (frontier death is monotone), so
        # later polls answer from the latch instead of re-bisecting
        self._matrix_first: int | None = None

    # -- ingestion ------------------------------------------------------

    def add(self, op: dict) -> None:
        if self._broken:
            return
        try:
            self.encoder.add(op)
        except Exception as e:  # noqa: BLE001 — a bad op poisons, not kills
            self._broken = f"unencodable op: {e!r}"
            logger.exception("live register session poisoned")

    @property
    def ops_absorbed(self) -> int:
        return self.encoder.ops_seen

    @property
    def checked_ops(self) -> int:
        return self._last["checked_ops"]

    def last(self) -> dict:
        return dict(self._last)

    # -- ladder ---------------------------------------------------------

    def _get_ladder(self):
        if self._ladder is not None:
            return self._ladder
        from jepsen_tpu.checker.ladder import Backend, BackendLadder

        def matrix_eligible(ctx):
            stream = ctx["session"].encoder.stream
            if self.accelerator == "cpu" or (
                    self.accelerator == "auto"
                    and len(stream) < AUTO_TPU_THRESHOLD):
                return False
            from jepsen_tpu.ops.jitlin import matrix_ok
            n_returns = sum(1 for k in stream.kind if k == EV_RETURN)
            return matrix_ok(stream.n_slots, len(stream.intern),
                             n_returns)

        def matrix_fn(ctx):
            # stateless full-prefix screen: exact True settles this
            # poll's verdict without touching the CPU frontier (which
            # catches up from its own offset on the next demotion).
            # Big prefixes shard over the device mesh when the cost
            # model clears it (doc/performance.md "Multi-device
            # sharding"); a collective failure retries single-device
            # inline — the daemon's poll cadence must not burn a whole
            # ladder demotion on a transient mesh fault.
            from jepsen_tpu import parallel
            from jepsen_tpu.models import cas_register_spec
            from jepsen_tpu.ops.jitlin import matrix_check, matrix_localize
            session = ctx["session"]
            if self._matrix_first is not None:
                # an invalid prefix stays invalid at the same op: the
                # latched localization answers without re-screening
                return {"valid_so_far": False,
                        "first_anomaly_op": self._matrix_first,
                        "checked_ops": session.encoder.ops_encoded}
            es = session.encoder.stream.to_event_stream()
            spec = cas_register_spec(self._spec_init)
            mesh = parallel.sharded_mesh_for(len(es.kind))
            try:
                m = matrix_check(es, step_ids=spec.step_ids,
                                 init_state=spec.init_state,
                                 num_states=len(es.intern), mesh=mesh)
            except Exception:  # noqa: BLE001 — mesh fault: one device
                if mesh is None:
                    raise
                logger.warning("sharded live matrix screen failed; "
                               "retrying single-device", exc_info=True)
                m = matrix_check(es, step_ids=spec.step_ids,
                                 init_state=spec.init_state,
                                 num_states=len(es.intern))
            if m is not None and m[0] and not m[2]:
                return {"valid_so_far": True, "first_anomaly_op": None,
                        "checked_ops": session.encoder.ops_encoded}
            if m is not None and not m[0] and not m[2]:
                # exact INVALID: localize on device (the forensics
                # bisection — doc/observability.md "Anomaly forensics")
                # so the live screen reports the precise first anomaly
                # instead of deferring to the slow CPU frontier rung
                try:
                    loc = matrix_localize(es, step_ids=spec.step_ids,
                                          init_state=spec.init_state,
                                          num_states=len(es.intern))
                except Exception:  # noqa: BLE001 — frontier settles it
                    logger.exception("live matrix localization failed")
                    loc = None
                if loc is not None:
                    self._matrix_first = int(loc.failed_op_index)
                    return {"valid_so_far": False,
                            "first_anomaly_op": self._matrix_first,
                            "checked_ops": session.encoder.ops_encoded}
            return None  # inexact/declined: the exact frontier settles it

        def frontier_fn(ctx):
            session = ctx["session"]
            fs = session.frontier
            res = fs.absorb(session.encoder.stream,
                            start=fs.events_absorbed)
            first = (None if res.valid is True
                     else int(res.failed_op_index))
            return {"valid_so_far": res.valid,
                    "first_anomaly_op": first,
                    "checked_ops": session.encoder.ops_encoded}

        self._ladder = BackendLadder([
            Backend("pallas-matrix", matrix_fn, eligible=matrix_eligible,
                    device=True),
            Backend("frontier-cpu", frontier_fn),
        ])
        return self._ladder

    # -- verdicts -------------------------------------------------------

    def verdict(self) -> dict:
        """Advances the checkable prefix and returns the live verdict:
        ``{valid_so_far, first_anomaly_op, backend, checked_ops}``."""
        if self._broken:
            return {**self._last, "valid_so_far": "unknown",
                    "error": self._broken}
        self.encoder.encode_resolved()
        out, backend = self._get_ladder().run({"session": self})
        out["backend"] = backend
        self._last = out
        return dict(out)

    def finalize(self) -> dict:
        """End-of-run verdict: resolves the still-open tail exactly as
        the batch encoder would, then settles on the exact CPU frontier
        (so ``failed-op-index`` is precise)."""
        if self._broken:
            return {"valid?": "unknown", "error": self._broken,
                    "algorithm": "jitlin-cpu-live"}
        self.encoder.finalize()
        res = self.frontier.absorb(self.encoder.stream,
                                   start=self.frontier.events_absorbed)
        self._last = {
            "valid_so_far": res.valid,
            "first_anomaly_op": (None if res.valid is True
                                 else int(res.failed_op_index)),
            "backend": "frontier-cpu", "checked_ops":
                self.encoder.ops_encoded,
        }
        out: dict[str, Any] = {
            "valid?": res.valid,
            "algorithm": res.algorithm,
            "configs-max": res.configs_max,
        }
        if res.valid is False and res.failed_op_index >= 0:
            out["failed-op-index"] = int(res.failed_op_index)
        return out


class _TxnCols:
    """Flattened micro-op columns for one node class (ok or info)."""

    __slots__ = ("pos", "inv", "proc", "txns",
                 "a_txn", "a_kid", "a_val", "a_mi",
                 "r_txn", "r_kid", "r_mi", "payloads")

    def __init__(self):
        self.pos: list[int] = []
        self.inv: list[int] = []
        self.proc: list[int] = []
        self.txns: list[dict] = []
        self.a_txn: list[int] = []
        self.a_kid: list[int] = []
        self.a_val: list[int] = []
        self.a_mi: list[int] = []
        self.r_txn: list[int] = []
        self.r_kid: list[int] = []
        self.r_mi: list[int] = []
        self.payloads: list[list] = []


class ElleSession:
    """Streaming list-append Elle: incremental graph-build columns.

    ``add`` runs the per-op build work (event pairing, micro-op
    flattening, key interning) exactly once per op; ``verdict`` pays
    only the vectorized assemble + φ-cluster cycle check. A history
    outside the integer columnar regime (exotic keys, non-int payload
    elements) poisons the incremental columns and every later verdict
    falls back to the batch checker over the retained history — slower,
    never wrong."""

    workload = "list-append"

    def __init__(self, accelerator: str = "auto",
                 consistency_models=("strict-serializable",)):
        self.accelerator = accelerator
        self.consistency_models = tuple(consistency_models)
        self.history: list[dict] = []
        self._last_ev: dict = {}      # process -> (idx, was_invoke)
        self._ok = _TxnCols()
        self._info = _TxnCols()
        self._f_kid: list[int] = []
        self._f_val: list[int] = []
        self._kid_of: dict = {}
        self._raw_key: list = []
        self._fallback: str | None = None
        self._last = {"valid_so_far": True, "first_anomaly_op": None,
                      "backend": "columnar-incremental", "checked_ops": 0}

    @property
    def ops_absorbed(self) -> int:
        return len(self.history)

    @property
    def checked_ops(self) -> int:
        return self._last["checked_ops"]

    def last(self) -> dict:
        return dict(self._last)

    def _kid(self, k) -> int:
        hk = _hk(k)
        i = self._kid_of.get(hk)
        if i is None:
            i = self._kid_of[hk] = len(self._raw_key)
            self._raw_key.append(k)
        return i

    def add(self, op: dict) -> None:
        i = len(self.history)
        self.history.append(op)
        typ = op.get("type")
        if typ not in ("invoke", "ok", "fail", "info"):
            return
        p = op.get("process")
        try:
            prev = self._last_ev.get(p)
        except TypeError:  # unhashable process: outside every regime
            self._fallback = self._fallback or "unhashable process"
            return
        self._last_ev[p] = (i, typ == "invoke")
        if typ == "invoke":
            return
        inv = prev[0] if (prev is not None and prev[1]) else None
        if typ == "fail":
            for m in op.get("value") or ():
                if m[0] == "append":
                    v = m[2]
                    if not isinstance(v, int) or isinstance(v, bool) \
                            or not (0 <= v < _MAX_VAL):
                        self._fallback = "non-int/overflow failed append"
                        return
                    self._f_kid.append(self._kid(m[1]))
                    self._f_val.append(v)
            return
        if not isinstance(p, int):
            return  # not a graph node (batch pint filter)
        cols = self._ok if typ == "ok" else self._info
        t = len(cols.pos)
        cols.pos.append(i)
        cols.inv.append(-1 if inv is None else inv)
        cols.proc.append(p)
        cols.txns.append(op)
        if self._fallback:
            return
        try:
            for mi, m in enumerate(op.get("value") or ()):
                if mi >= _MAX_MOPS:
                    self._fallback = "over-long txn"
                    return
                f = m[0]
                if f == "append":
                    v = m[2]
                    if not isinstance(v, int) or isinstance(v, bool) \
                            or not (0 <= v < _MAX_VAL):
                        self._fallback = "non-int/overflow append value"
                        return
                    cols.a_txn.append(t)
                    cols.a_kid.append(self._kid(m[1]))
                    cols.a_val.append(v)
                    cols.a_mi.append(mi)
                elif f == "r" and m[2] is not None:
                    cols.r_txn.append(t)
                    cols.r_kid.append(self._kid(m[1]))
                    cols.r_mi.append(mi)
                    cols.payloads.append(m[2] if type(m[2]) is list
                                         else list(m[2]))
        except (TypeError, ValueError, IndexError, OverflowError) as e:
            self._fallback = f"unflattenable txn: {e!r}"

    def _check_batch(self) -> dict:
        from jepsen_tpu.elle import list_append
        return list_append.check(
            self.history, accelerator=self.accelerator,
            consistency_models=self.consistency_models)

    def _update_last(self, result: dict) -> dict:
        first = None
        if result.get("valid?") is not True:
            first = _first_anomaly_op(result, self.history)
        self._last = {
            "valid_so_far": result.get("valid?"),
            "first_anomaly_op": first,
            "anomaly_types": result.get("anomaly-types") or [],
            "backend": ("batch-fallback" if self._fallback
                        else "columnar-incremental"),
            "checked_ops": len(self.history),
        }
        return dict(self._last)

    def verdict(self) -> dict:
        return self._update_last(self._result())

    def finalize(self) -> dict:
        out = self._result()
        self._update_last(out)
        return out

    def _result(self) -> dict:
        """The full checker result map over everything absorbed — the
        same shape ``elle.list_append.check`` returns."""
        import numpy as np

        from jepsen_tpu import elle
        from jepsen_tpu.elle import columnar

        if self._fallback or len(self._raw_key) >= _MAX_KIDS:
            return self._check_batch()
        ok, info = self._ok, self._info
        n_ok = len(ok.pos)
        txns = ok.txns + info.txns
        if not txns:
            return {"valid?": True, "anomaly-types": [], "not": [],
                    "anomalies": {}, "txn-count": 0, "edge-count": 0,
                    "builder": "columnar-incremental"}
        parts = columnar._assemble(
            txns=txns, n_ok=n_ok, raw_key=self._raw_key,
            a_txn=ok.a_txn + [n_ok + t for t in info.a_txn],
            a_kid=ok.a_kid + info.a_kid,
            a_val=ok.a_val + info.a_val,
            a_mi=ok.a_mi + info.a_mi,
            r_txn=ok.r_txn + [n_ok + t for t in info.r_txn],
            r_kid=ok.r_kid + info.r_kid,
            r_mi=ok.r_mi + info.r_mi,
            payloads=ok.payloads + info.payloads,
            f_kid=list(self._f_kid), f_val=list(self._f_val),
            node_pos=np.asarray(ok.pos + info.pos, np.int64),
            node_inv=np.asarray(ok.inv + info.inv, np.int64),
            node_proc=np.asarray(ok.proc + info.proc, np.int64))
        if parts is None:  # regime miss the per-op checks didn't catch
            self._fallback = "assemble regime miss"
            return self._check_batch()
        graph, txns, extras, nk = parts
        cyc = elle.check_cycles(graph, accelerator=self.accelerator)
        merged = {k: v for k, v in extras.items()
                  if k != "unobserved-writer"}
        result = elle.result_map(
            cyc, txns, merged, consistency_models=self.consistency_models)
        result["txn-count"] = graph.n
        result["edge-count"] = graph.edge_count()
        result["builder"] = "columnar-incremental"
        return result


def _first_anomaly_op(result: dict, history: list[dict]) -> int | None:
    """Best-effort history index of the first anomalous txn cited by an
    Elle result (cycles cite txn values; extras cite reads/writers) —
    the "first anomaly at op N" surface. None when nothing matched."""
    cited: list = []
    for cycles in (result.get("anomalies") or {}).values():
        for item in cycles if isinstance(cycles, list) else ():
            for hop in item if isinstance(item, list) else ():
                if isinstance(hop, dict):
                    cited.extend([hop.get("from"), hop.get("to"),
                                  hop.get("read"), hop.get("read-txn"),
                                  hop.get("writer")])
    idx = None
    for i, op in enumerate(history):
        if op.get("type") not in ("ok", "info"):
            continue
        v = op.get("value")
        if v is None:
            continue
        if any(c is not None and c == v for c in cited):
            idx = i if idx is None else min(idx, i)
    return idx


class MultiKeyLinearSession:
    """Streaming linearizability over an :mod:`jepsen_tpu.independent`
    key-lifted register history: demuxes ``[k, v]`` tuple values into
    one :class:`LinearLiveSession` per key (the streaming twin of
    ``independent.subhistory`` — ops without a tuple value are outside
    every sub-history there too, so they only count toward lag)."""

    workload = "register-independent"

    def __init__(self, accelerator: str = "auto"):
        self.accelerator = accelerator
        self.sub: dict = {}
        self.ops_absorbed = 0
        self._last = {"valid_so_far": True, "first_anomaly_op": None,
                      "backend": "frontier-cpu", "checked_ops": 0}

    def add(self, op: dict) -> None:
        from jepsen_tpu import independent
        self.ops_absorbed += 1
        v = op.get("value")
        if not independent.is_tuple_value(v):
            return
        k = independent._freeze_key(v[0])
        sess = self.sub.get(k)
        if sess is None:
            sess = self.sub[k] = LinearLiveSession(
                accelerator=self.accelerator)
        sess.add({**op, "value": v[1]})

    @property
    def checked_ops(self) -> int:
        routed = sum(s.ops_absorbed for s in self.sub.values())
        checked = sum(s.checked_ops for s in self.sub.values())
        # unroutable ops (nemesis, value-less infos) need no checking
        return self.ops_absorbed - routed + checked

    def last(self) -> dict:
        return dict(self._last)

    def _merge(self, per_key: dict) -> dict:
        valids = [r.get("valid_so_far") for r in per_key.values()]
        valid = (False if any(x is False for x in valids)
                 else "unknown" if any(x == "unknown" for x in valids)
                 else True)
        firsts = [r.get("first_anomaly_op") for r in per_key.values()
                  if r.get("first_anomaly_op") is not None]
        self._last = {
            "valid_so_far": valid,
            "first_anomaly_op": min(firsts) if firsts else None,
            "backend": "frontier-cpu",
            "checked_ops": self.checked_ops,
            "keys": len(self.sub),
        }
        return dict(self._last)

    def verdict(self) -> dict:
        return self._merge({k: s.verdict() for k, s in self.sub.items()})

    def finalize(self) -> dict:
        results = {str(k): s.finalize() for k, s in self.sub.items()}
        self._merge({k: s.last() for k, s in self.sub.items()})
        valid = self._last["valid_so_far"]
        return {
            "valid?": valid,
            "count": len(results),
            "failures": sorted(k for k, r in results.items()
                               if r.get("valid?") is not True),
            "results": results,
        }


#: session_for_ops sentinel: client ops seen, no live checker matches
UNSUPPORTED = object()


def session_for_ops(ops: list[dict], accelerator: str = "auto"):
    """Sniffs the workload from the first client invocations and builds
    the matching session. Returns None while the evidence is still
    ambiguous (keep buffering), or :data:`UNSUPPORTED` when the
    workload has no live checker (the tracker then reports lag only)."""
    from jepsen_tpu.independent import is_tuple_value
    for op in ops:
        p, f = op.get("process"), op.get("f")
        if not isinstance(p, int) or p < 0 or f is None:
            continue
        v = op.get("value")
        if f in ("read", "write"):
            # plain registers carry None/scalar values; key-lifted ones
            # carry [k, v] tuples (independent.tuple_value)
            if is_tuple_value(v):
                return MultiKeyLinearSession(accelerator=accelerator)
            return LinearLiveSession(accelerator=accelerator)
        if f == "cas":
            # plain cas: [u, v] scalars; lifted cas: [k, [u, v]]
            if is_tuple_value(v) and isinstance(v[1], (list, tuple)):
                return MultiKeyLinearSession(accelerator=accelerator)
            if is_tuple_value(v):
                return LinearLiveSession(accelerator=accelerator)
            continue  # malformed/valueless cas: keep sniffing
        if f == "txn":
            mops = op.get("value") or ()
            fs = {m[0] for m in mops if isinstance(m, (list, tuple)) and m}
            if not fs:
                continue
            if fs <= {"append", "r"}:
                return ElleSession(accelerator=accelerator)
            return UNSUPPORTED  # multi-register txns: no live checker yet
        return UNSUPPORTED
    return None
