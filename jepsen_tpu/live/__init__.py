"""Online consistency checking: tail a run's WAL, verdict as it runs.

The reference checks strictly post-hoc (``analyze!`` runs only after the
run ends — core.clj:221-236); this package turns checking into
*monitoring*. A daemon (:mod:`jepsen_tpu.live.daemon`) discovers active
runs under a store root, tails each run's ``history.wal.jsonl`` with an
incremental offset-tracking reader (:class:`jepsen_tpu.journal.
WalTailer`), and maintains per-run incremental checker state
(:mod:`jepsen_tpu.live.sessions`): a resumable linearizability frontier
and an incrementally-built Elle dependency graph. Each poll publishes
live verdicts ("valid so far" / "first anomaly at op N"), lag, and
backend telemetry into a metrics registry and a per-run
``live-status.json`` the web UI renders (doc/observability.md, "Live
checking").
"""
from jepsen_tpu.live.daemon import (  # noqa: F401
    LIVE_STATUS_NAME, LiveDaemon, RunTracker, load_live_status,
)
from jepsen_tpu.live.sessions import (  # noqa: F401
    ElleSession, LinearLiveSession, MultiKeyLinearSession, UNSUPPORTED,
    session_for_ops,
)
