"""Dummy remote: executes nothing, records everything.

The reference's `:ssh {:dummy? true}` (control.clj:40, cli.clj:230
``--no-ssh``) lets the full run lifecycle execute with in-memory doubles —
the backbone of cluster-free integration tests (SURVEY.md §4 tier 2).
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field

from jepsen_tpu.control.core import Remote, Result


@dataclass
class DummyRemote(Remote):
    host: str | None = None
    log: list = field(default_factory=list)
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def connect(self, conn_spec: dict) -> "DummyRemote":
        return DummyRemote(host=conn_spec.get("host"), log=self.log, _lock=self._lock)

    def execute(self, ctx: dict, cmd: str) -> Result:
        with self._lock:
            self.log.append(("exec", self.host, cmd))
        return Result(cmd=cmd, exit_status=0, out="", err="", host=self.host)

    def upload(self, ctx, local_paths, remote_path) -> None:
        with self._lock:
            self.log.append(("upload", self.host, local_paths, remote_path))

    def download(self, ctx, remote_paths, local_path) -> None:
        with self._lock:
            self.log.append(("download", self.host, remote_paths, local_path))
