"""SSH transport shelling out to the system ssh/scp binaries.

Replaces the reference's JVM SSH stacks (control/clj_ssh.clj, control/sshj.clj).
OpenSSH ControlMaster multiplexing gives us persistent connections (the role
of the reference's cached sessions) and native-speed bulk transfer (the
reference needed an scp-shellout wrapper, control/scp.clj:1-10, because JVM
SSH was "orders of magnitude slower" — shelling out is our default).
"""
from __future__ import annotations

import logging
import os
import subprocess
import tempfile
import time
from dataclasses import dataclass, field
from pathlib import Path

logger = logging.getLogger("jepsen.control.ssh")

from jepsen_tpu import telemetry
from jepsen_tpu.control.core import Remote, RemoteError, Result, wrap_cd, wrap_sudo

DEFAULT_TIMEOUT_S = 120


def _record(op: str, dt: float, status: str) -> None:
    """control_exec latency histogram + outcome counter (no-op when
    telemetry is disabled)."""
    reg = telemetry.get_registry()
    if not reg.enabled:
        return
    reg.histogram("control_exec_seconds", "remote op latency",
                  labels=("op",)).observe(dt, op=op)
    reg.counter("control_exec_total", "remote ops by outcome",
                labels=("op", "status")).inc(op=op, status=status)


@dataclass
class SSHRemote(Remote):
    conn_spec: dict = field(default_factory=dict)
    control_dir: str | None = None

    def connect(self, conn_spec: dict) -> "SSHRemote":
        r = SSHRemote(conn_spec=dict(conn_spec))
        r.control_dir = tempfile.mkdtemp(prefix="jepsen-ssh-")
        # eagerly establish the master connection so connection errors
        # surface at connect time (like Remote.connect in the reference)
        res = r._run_ssh(["true"], check_master=True)
        if res.exit_status != 0:
            raise RemoteError(
                f"can't connect to {conn_spec.get('host')}: {res.err[:500]}",
                host=conn_spec.get("host"), err=res.err,
            )
        return r

    def _base_opts(self, with_port: bool = True) -> list[str]:
        spec = self.conn_spec
        opts = [
            "-o", "StrictHostKeyChecking=no",
            "-o", "UserKnownHostsFile=/dev/null",
            "-o", "LogLevel=ERROR",
            "-o", f"ConnectTimeout={spec.get('connect_timeout', 10)}",
        ]
        if self.control_dir:
            opts += [
                "-o", "ControlMaster=auto",
                "-o", f"ControlPath={self.control_dir}/%r@%h:%p",
                "-o", "ControlPersist=60",
            ]
        if with_port and spec.get("port"):
            opts += ["-p", str(spec["port"])]
        if spec.get("private_key_path"):
            opts += ["-i", spec["private_key_path"]]
        return opts

    def _target(self) -> str:
        spec = self.conn_spec
        user = spec.get("username")
        host = spec.get("host")
        return f"{user}@{host}" if user else str(host)

    # -- ControlMaster liveness -------------------------------------------
    #
    # A master connection can die under us (node reboot, network blip,
    # ControlPersist expiry racing a long pause). Without intervention
    # every subsequent exec fails 255 until the RetryRemote gives up —
    # a dead socket aborted the run. Instead: on a transport-shaped
    # failure, probe the master (``ssh -O check``); if it's dead, clear
    # the stale socket and retry the command once — ControlMaster=auto
    # re-establishes transparently. The retry wrapper above us treats a
    # second failure as the usual flake.

    def _master_alive(self) -> bool:
        if not self.control_dir:
            return True
        try:
            p = subprocess.run(
                ["ssh"] + self._base_opts() + ["-O", "check",
                                               self._target()],
                capture_output=True, text=True, timeout=10)
            return p.returncode == 0
        except Exception:  # noqa: BLE001
            return False

    def _reset_master(self) -> None:
        """Asks any half-dead master to exit, then removes stale socket
        files so the next command's ControlMaster=auto can re-listen."""
        try:
            subprocess.run(
                ["ssh"] + self._base_opts() + ["-O", "exit",
                                               self._target()],
                capture_output=True, text=True, timeout=10)
        except Exception:  # noqa: BLE001
            pass
        try:
            for sock in Path(self.control_dir).iterdir():
                try:
                    sock.unlink()
                except OSError:
                    pass
        except OSError:
            pass

    def _run_ssh(self, cmd_argv: list[str], stdin: str | None = None,
                 check_master: bool = False) -> Result:
        res = self._exec_ssh(cmd_argv, stdin)
        if (res.exit_status in (-1, 255) and self.control_dir
                and not check_master and not self._master_alive()):
            logger.warning("ssh ControlMaster for %s died; reconnecting",
                           self.conn_spec.get("host"))
            self._reset_master()
            reg = telemetry.get_registry()
            if reg.enabled:
                reg.counter("control_master_reconnects_total",
                            "dead ControlMaster sockets revived in-flight"
                            ).inc()
            res = self._exec_ssh(cmd_argv, stdin)
        return res

    def _exec_ssh(self, cmd_argv: list[str],
                  stdin: str | None = None) -> Result:
        argv = ["ssh"] + self._base_opts() + [self._target()] + cmd_argv
        t0 = time.perf_counter()
        try:
            p = subprocess.run(
                argv, capture_output=True, text=True,
                input=stdin,
                timeout=self.conn_spec.get("timeout", DEFAULT_TIMEOUT_S),
            )
            _record("exec", time.perf_counter() - t0,
                    "ok" if p.returncode == 0 else "error")
            return Result(cmd=" ".join(cmd_argv), exit_status=p.returncode,
                          out=p.stdout, err=p.stderr,
                          host=self.conn_spec.get("host"))
        except subprocess.TimeoutExpired as e:
            _record("exec", time.perf_counter() - t0, "timeout")
            return Result(cmd=" ".join(cmd_argv), exit_status=-1,
                          out=e.stdout or "", err=f"timeout: {e}",
                          host=self.conn_spec.get("host"))

    def execute(self, ctx: dict, cmd: str) -> Result:
        full = wrap_sudo(ctx, wrap_cd(ctx, cmd))
        return self._run_ssh([full], stdin=ctx.get("stdin"))

    def _scp(self, sources: list[str], dest: str) -> None:
        argv = (["scp", "-q", "-r"]
                + self._base_opts(with_port=False)  # scp spells it -P
                + (["-P", str(self.conn_spec["port"])] if self.conn_spec.get("port") else [])
                + sources + [dest])
        t0 = time.perf_counter()
        p = subprocess.run(argv, capture_output=True, text=True,
                           timeout=self.conn_spec.get("timeout", 600))
        _record("scp", time.perf_counter() - t0,
                "ok" if p.returncode == 0 else "error")
        if p.returncode != 0:
            raise RemoteError(f"scp failed: {p.stderr[:500]}",
                              cmd=" ".join(argv), exit_status=p.returncode,
                              err=p.stderr, host=self.conn_spec.get("host"))

    def upload(self, ctx: dict, local_paths, remote_path) -> None:
        if isinstance(local_paths, (str, os.PathLike)):
            local_paths = [local_paths]
        self._scp([str(p) for p in local_paths],
                  f"{self._target()}:{remote_path}")

    def download(self, ctx: dict, remote_paths, local_path) -> None:
        if isinstance(remote_paths, (str, os.PathLike)):
            remote_paths = [remote_paths]
        self._scp([f"{self._target()}:{p}" for p in remote_paths],
                  str(local_path))

    def disconnect(self) -> None:
        if self.control_dir:
            subprocess.run(
                ["ssh"] + self._base_opts() + ["-O", "exit", self._target()],
                capture_output=True, text=True, timeout=10,
            )
