"""Remote via `docker exec` / `docker cp` (reference:
jepsen/src/jepsen/control/docker.clj:30-75)."""
from __future__ import annotations

import subprocess
from dataclasses import dataclass

from jepsen_tpu.control.core import Remote, RemoteError, Result, wrap_cd, wrap_sudo


@dataclass
class DockerRemote(Remote):
    container: str | None = None

    def connect(self, conn_spec: dict) -> "DockerRemote":
        return DockerRemote(container=conn_spec.get("host"))

    def execute(self, ctx: dict, cmd: str) -> Result:
        full = wrap_sudo(ctx, wrap_cd(ctx, cmd))
        p = subprocess.run(
            ["docker", "exec", self.container, "sh", "-c", full],
            capture_output=True, text=True, timeout=ctx.get("timeout", 120),
        )
        return Result(cmd=cmd, exit_status=p.returncode, out=p.stdout,
                      err=p.stderr, host=self.container)

    def upload(self, ctx, local_paths, remote_path) -> None:
        paths = [local_paths] if isinstance(local_paths, str) else list(local_paths)
        for p in paths:
            r = subprocess.run(["docker", "cp", str(p),
                                f"{self.container}:{remote_path}"],
                               capture_output=True, text=True)
            if r.returncode != 0:
                raise RemoteError(f"docker cp failed: {r.stderr[:300]}",
                                  host=self.container, err=r.stderr)

    def download(self, ctx, remote_paths, local_path) -> None:
        paths = [remote_paths] if isinstance(remote_paths, str) else list(remote_paths)
        for p in paths:
            r = subprocess.run(["docker", "cp", f"{self.container}:{p}",
                                str(local_path)],
                               capture_output=True, text=True)
            if r.returncode != 0:
                raise RemoteError(f"docker cp failed: {r.stderr[:300]}",
                                  host=self.container, err=r.stderr)
