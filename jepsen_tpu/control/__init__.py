"""Control facade: scoped remote-execution state + the shell DSL.

Reference: jepsen/src/jepsen/control.clj. The reference scopes connection
state in dynamic vars (*host* *session* *dir* *sudo*..., control.clj:39-53);
here a contextvar holds a per-thread/task ``Ctl`` record, so ``exec_``,
``upload``, ``cd``, ``su`` read ambient state exactly like the reference's
facade (:138-189, :203-224). ``on_nodes`` fans out over per-node cached
sessions with real_pmap (:295-311).
"""
from __future__ import annotations

import contextlib
import contextvars
import logging
import threading
from typing import Any, Callable, Iterable

from jepsen_tpu.control.core import (
    Lit, Remote, RemoteError, Result, env, escape, join_cmd, lit,
    throw_on_nonzero_exit,
)
from jepsen_tpu.control.dummy import DummyRemote
from jepsen_tpu.control.retry import RetryRemote
from jepsen_tpu.control.ssh import SSHRemote

logger = logging.getLogger("jepsen.control")

_ctl: contextvars.ContextVar[dict | None] = contextvars.ContextVar("jepsen_ctl", default=None)


def _current() -> dict:
    c = _ctl.get()
    if c is None:
        raise RuntimeError("no control session bound; use with_session/on")
    return c


def conn_spec(test: dict, host: str) -> dict:
    """Builds a connection spec from test['ssh'] options
    (control.clj:55-70)."""
    ssh = dict(test.get("ssh") or {})
    return {
        "host": host,
        "username": ssh.get("username", "root"),
        "password": ssh.get("password"),
        "port": ssh.get("port"),
        "private_key_path": ssh.get("private_key_path"),
        "strict_host_key_checking": ssh.get("strict_host_key_checking", False),
        "dummy": ssh.get("dummy", False),
    }


def default_remote(test: dict) -> Remote:
    """Chooses the transport for a test: dummy, an explicit test['remote'],
    or retry-wrapped subprocess SSH (control.clj:35-37 + sshj composition
    control/sshj.clj:181-187)."""
    ssh = test.get("ssh") or {}
    if ssh.get("dummy"):
        return test.setdefault("_dummy_remote", DummyRemote())
    if test.get("remote") is not None:
        return test["remote"]
    from jepsen_tpu.control.scp import SCPRemote
    return RetryRemote(SCPRemote(SSHRemote()))


@contextlib.contextmanager
def with_session(host: str, session: Remote, test: dict | None = None):
    """Binds a connected session for the dynamic extent of the block
    (control.clj:236-262)."""
    token = _ctl.set({
        "host": host,
        "session": session,
        "dir": "/",
        "sudo": None,
        "trace": False,
        "test": test,
    })
    try:
        yield session
    finally:
        _ctl.reset(token)


def session_for(test: dict, node: str) -> Remote:
    """Connects (or returns a cached) session for node, cached on the test
    map (core.clj with-resources / control.clj:295-311 session caching)."""
    sessions = test.setdefault("_sessions", {})
    lock = test.setdefault("_sessions_lock", threading.Lock())
    with lock:
        s = sessions.get(node)
    if s is not None:
        return s
    remote = default_remote(test)
    s = remote.connect(conn_spec(test, node))
    with lock:
        sessions[node] = s
    return s


def disconnect_all(test: dict) -> None:
    for node, s in list((test.get("_sessions") or {}).items()):
        try:
            s.disconnect()
        except Exception:  # noqa: BLE001
            logger.exception("error disconnecting %s", node)
    test["_sessions"] = {}


# -- the shell DSL ---------------------------------------------------------

def exec_(*args, stdin: str | None = None) -> str:  # blocking: rpc
    """Runs a shell command on the current session, returning trimmed
    stdout; raises RemoteError on nonzero exit (control.clj:138-157).
    Annotated ``# blocking: rpc``: the lock-order rule flags any call
    that reaches this while holding a lock — a remote exec (bounded,
    but up to the transport timeout) must never run under one."""
    c = _current()
    cmd = join_cmd(args)
    ctx = {"dir": c["dir"], "sudo": c["sudo"], "stdin": stdin}
    if c.get("trace"):
        logger.info("[%s] %s", c["host"], cmd)
    res = c["session"].execute(ctx, cmd)
    throw_on_nonzero_exit(res)
    return res.out.strip()


def exec_star(*args, stdin: str | None = None) -> Result:  # blocking: rpc
    """Like exec_ but returns the full Result without raising."""
    c = _current()
    cmd = join_cmd(args)
    ctx = {"dir": c["dir"], "sudo": c["sudo"], "stdin": stdin}
    return c["session"].execute(ctx, cmd)


def upload(local_paths, remote_path) -> None:
    c = _current()
    c["session"].upload({"sudo": c["sudo"]}, local_paths, remote_path)


def download(remote_paths, local_path) -> None:
    c = _current()
    c["session"].download({"sudo": c["sudo"]}, remote_paths, local_path)


def upload_resource(package_relative: str, remote_path: str) -> None:
    """Uploads a file shipped inside jepsen_tpu/resources/
    (control.clj upload-resource!)."""
    import importlib.resources as ir
    ref = ir.files("jepsen_tpu").joinpath("resources", package_relative)
    with ir.as_file(ref) as p:
        upload(str(p), remote_path)


@contextlib.contextmanager
def cd(dir: str):
    c = _current()
    old = c["dir"]
    c["dir"] = dir
    try:
        yield
    finally:
        c["dir"] = old


@contextlib.contextmanager
def su(user: Any = True):
    """Sudo as root (or user) within the block (control.clj:203-218)."""
    c = _current()
    old = c["sudo"]
    c["sudo"] = user
    try:
        yield
    finally:
        c["sudo"] = old


sudo = su


@contextlib.contextmanager
def trace():
    c = _current()
    old = c["trace"]
    c["trace"] = True
    try:
        yield
    finally:
        c["trace"] = old


def current_host():
    return _current()["host"]


def on(node: str, test: dict, fn: Callable[[], Any]) -> Any:
    """Runs fn with a session bound to node (control.clj:272-281)."""
    session = session_for(test, node)
    with with_session(node, session, test):
        return fn()


def on_nodes(test: dict, fn: Callable[[str], Any],
             nodes: Iterable[str] | None = None) -> dict:
    """Runs (fn node) on each node in parallel; returns {node: result}
    (control.clj:295-311)."""
    from jepsen_tpu.utils import real_pmap
    nodes = list(nodes if nodes is not None else (test.get("nodes") or []))

    def run_one(node):
        return node, on(node, test, lambda: fn(node))

    return dict(real_pmap(run_one, nodes))


@contextlib.contextmanager
def with_test_nodes(test: dict):
    """Connects sessions for every node; disconnects after
    (control.clj:313-319 + core.clj with-resources)."""
    try:
        from jepsen_tpu.utils import real_pmap
        real_pmap(lambda n: session_for(test, n), list(test.get("nodes") or []))
        yield
    finally:
        disconnect_all(test)
