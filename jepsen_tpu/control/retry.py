"""Retrying remote wrapper (reference: jepsen/src/jepsen/control/retry.clj).

Wraps any Remote, retrying flaky operations: 5 tries with ~50-150 ms
randomized backoff (retry.clj:15-21 — backoff-time 100 ms ± jitter)."""
from __future__ import annotations

import random
import time

from jepsen_tpu import telemetry
from jepsen_tpu.control.core import Remote, RemoteError, Result

TRIES = 5
BACKOFF_BASE_S = 0.05
BACKOFF_JITTER_S = 0.1


def _count_retry(op: str) -> None:
    reg = telemetry.get_registry()
    if reg.enabled:
        reg.counter("control_retries_total",
                    "transport-flake retries beyond the first attempt",
                    labels=("op",)).inc(op=op)


class RetryRemote(Remote):
    def __init__(self, remote: Remote):
        self.remote = remote

    def connect(self, conn_spec: dict) -> "RetryRemote":
        err = None
        for _ in range(TRIES):
            try:
                return RetryRemote(self.remote.connect(conn_spec))
            except Exception as e:  # noqa: BLE001
                err = e
                time.sleep(BACKOFF_BASE_S + random.random() * BACKOFF_JITTER_S)
        raise err

    # ssh itself exits 255 on transport failure; our SSHRemote reports
    # timeouts as -1. Both are indistinguishable from a remote command
    # exiting 255, so (like the reference, which retries any flaky SSH op)
    # we retry them — remote commands exiting 255 are vanishingly rare.
    TRANSPORT_EXITS = (-1, 255)

    def _retrying(self, fn, op: str = "execute"):
        err = None
        for attempt in range(TRIES):
            try:
                return fn()
            except RemoteError as e:
                raise e  # command failed legitimately; don't retry
            except Exception as e:  # noqa: BLE001  transport flake
                err = e
                if attempt < TRIES - 1:  # a retry follows; give-up doesn't count
                    _count_retry(op)
                time.sleep(BACKOFF_BASE_S + random.random() * BACKOFF_JITTER_S)
        raise err

    def execute(self, ctx, cmd) -> Result:
        res = None
        for attempt in range(TRIES):
            res = self._retrying(lambda: self.remote.execute(ctx, cmd))
            if res.exit_status not in self.TRANSPORT_EXITS:
                return res
            if attempt < TRIES - 1:
                _count_retry("execute")
            time.sleep(BACKOFF_BASE_S + random.random() * BACKOFF_JITTER_S)
        return res

    def upload(self, ctx, local_paths, remote_path):
        return self._retrying(
            lambda: self.remote.upload(ctx, local_paths, remote_path),
            op="upload")

    def download(self, ctx, remote_paths, local_path):
        return self._retrying(
            lambda: self.remote.download(ctx, remote_paths, local_path),
            op="download")

    def disconnect(self):
        self.remote.disconnect()
