"""Retrying remote wrapper (reference: jepsen/src/jepsen/control/retry.clj).

Wraps any Remote, retrying flaky operations: 5 tries with
capped-exponential full-jitter backoff (``uniform(0, min(cap,
base * 2**attempt))``, utils.backoff_delay). The reference uses a fixed
~100 ms ± jitter (retry.clj:15-21); the exponential schedule keeps the
first retry just as fast while spacing later tries out — a cluster-wide
transport brownout (dead ControlMaster, rebooting node) stops being
hammered every 100 ms by every worker at once."""
from __future__ import annotations

import random
import time

from jepsen_tpu import telemetry
from jepsen_tpu.control.core import Remote, RemoteError, Result
from jepsen_tpu.utils import backoff_delay

TRIES = 5
BACKOFF_BASE_S = 0.05
BACKOFF_CAP_S = 5.0


def _count_retry(op: str) -> None:
    reg = telemetry.get_registry()
    if reg.enabled:
        reg.counter("control_retries_total",
                    "transport-flake retries beyond the first attempt",
                    labels=("op",)).inc(op=op)


class RetryRemote(Remote):
    def __init__(self, remote: Remote, rng: random.Random | None = None):
        self.remote = remote
        # injectable RNG so the backoff schedule is deterministic under
        # a seeded random.Random (tests/test_crashsafe.py)
        self.rng = rng

    def _sleep(self, attempt: int) -> None:
        time.sleep(backoff_delay(attempt, BACKOFF_BASE_S, BACKOFF_CAP_S,
                                 self.rng))

    def connect(self, conn_spec: dict) -> "RetryRemote":
        err = None
        for attempt in range(TRIES):
            try:
                return RetryRemote(self.remote.connect(conn_spec),
                                   rng=self.rng)
            except Exception as e:  # noqa: BLE001
                err = e
                if attempt < TRIES - 1:  # no pointless sleep before give-up
                    self._sleep(attempt)
        raise err

    # ssh itself exits 255 on transport failure; our SSHRemote reports
    # timeouts as -1. Both are indistinguishable from a remote command
    # exiting 255, so (like the reference, which retries any flaky SSH op)
    # we retry them — remote commands exiting 255 are vanishingly rare.
    TRANSPORT_EXITS = (-1, 255)

    def _retrying(self, fn, op: str = "execute"):
        err = None
        for attempt in range(TRIES):
            try:
                return fn()
            except RemoteError as e:
                raise e  # command failed legitimately; don't retry
            except Exception as e:  # noqa: BLE001  transport flake
                err = e
                if attempt < TRIES - 1:  # a retry follows; give-up doesn't count
                    _count_retry(op)
                    self._sleep(attempt)
        raise err

    def execute(self, ctx, cmd) -> Result:
        res = None
        for attempt in range(TRIES):
            res = self._retrying(lambda: self.remote.execute(ctx, cmd))
            if res.exit_status not in self.TRANSPORT_EXITS:
                return res
            if attempt < TRIES - 1:
                _count_retry("execute")
                self._sleep(attempt)
        return res

    def upload(self, ctx, local_paths, remote_path):
        return self._retrying(
            lambda: self.remote.upload(ctx, local_paths, remote_path),
            op="upload")

    def download(self, ctx, remote_paths, local_path):
        return self._retrying(
            lambda: self.remote.download(ctx, remote_paths, local_path),
            op="download")

    def disconnect(self):
        self.remote.disconnect()
