"""Remote via `kubectl exec` / `kubectl cp` (reference:
jepsen/src/jepsen/control/k8s.clj:14-73)."""
from __future__ import annotations

import subprocess
from dataclasses import dataclass

from jepsen_tpu.control.core import Remote, RemoteError, Result, wrap_cd, wrap_sudo


@dataclass
class K8sRemote(Remote):
    pod: str | None = None
    namespace: str = "default"

    def connect(self, conn_spec: dict) -> "K8sRemote":
        return K8sRemote(pod=conn_spec.get("host"),
                         namespace=conn_spec.get("namespace", "default"))

    def execute(self, ctx: dict, cmd: str) -> Result:
        full = wrap_sudo(ctx, wrap_cd(ctx, cmd))
        p = subprocess.run(
            ["kubectl", "exec", "-n", self.namespace, self.pod, "--",
             "sh", "-c", full],
            capture_output=True, text=True, timeout=ctx.get("timeout", 120),
        )
        return Result(cmd=cmd, exit_status=p.returncode, out=p.stdout,
                      err=p.stderr, host=self.pod)

    def upload(self, ctx, local_paths, remote_path) -> None:
        paths = [local_paths] if isinstance(local_paths, str) else list(local_paths)
        for p in paths:
            r = subprocess.run(
                ["kubectl", "cp", "-n", self.namespace, str(p),
                 f"{self.pod}:{remote_path}"],
                capture_output=True, text=True)
            if r.returncode != 0:
                raise RemoteError(f"kubectl cp failed: {r.stderr[:300]}",
                                  host=self.pod, err=r.stderr)

    def download(self, ctx, remote_paths, local_path) -> None:
        paths = [remote_paths] if isinstance(remote_paths, str) else list(remote_paths)
        for p in paths:
            r = subprocess.run(
                ["kubectl", "cp", "-n", self.namespace,
                 f"{self.pod}:{p}", str(local_path)],
                capture_output=True, text=True)
            if r.returncode != 0:
                raise RemoteError(f"kubectl cp failed: {r.stderr[:300]}",
                                  host=self.pod, err=r.stderr)
