"""Sudo-aware file-transfer decorator (reference:
jepsen/src/jepsen/control/scp.clj).

The base SSH transport already shells out to ``scp`` for raw speed (the
reference adopted scp because JVM SSH libraries are "orders of magnitude
slower", scp.clj:1-10); what this wrapper adds is scp.clj's *sudo dance*
(:29-56, :94-139): when the control session is running under sudo as a
user other than the login user, uploads land in a world-writable tmp file
and are chown+mv'd into place as root, and downloads of unreadable files
are hardlinked/copied to a readable tmp file first.
"""
from __future__ import annotations

import os.path
import random

from jepsen_tpu.control.core import (Remote, RemoteError, Result, join_cmd,
                                     throw_on_nonzero_exit, wrap_sudo)

TMP_DIR = "/tmp/jepsen/scp"


def _coll(x) -> list:
    return list(x) if isinstance(x, (list, tuple)) else [x]


class SCPRemote(Remote):
    """Wraps any Remote, adding sudo-aware upload/download semantics."""

    def __init__(self, remote: Remote, conn_spec: dict | None = None):
        self.remote = remote
        self.conn_spec = conn_spec or {}

    def connect(self, conn_spec: dict) -> "SCPRemote":
        return SCPRemote(self.remote.connect(conn_spec), conn_spec)

    def disconnect(self) -> None:
        self.remote.disconnect()

    def execute(self, ctx: dict, cmd: str) -> Result:
        return self.remote.execute(ctx, cmd)

    # -- internals ----------------------------------------------------------

    def _exec(self, ctx: dict, args: list) -> Result:
        """Basic exec for our own purposes (scp.clj:17-27)."""
        cmd = wrap_sudo(ctx, join_cmd(args))
        return throw_on_nonzero_exit(self.remote.execute({}, cmd))

    def _ensure_tmp_dir(self) -> None:
        self._exec({"sudo": "root"}, ["mkdir", "-p", TMP_DIR])
        self._exec({"sudo": "root"}, ["chmod", "a+rwx", TMP_DIR])

    def _tmp_file(self) -> str:
        return f"{TMP_DIR}/{random.randint(0, 2**31 - 1)}"

    def _needs_dance(self, ctx: dict) -> bool:
        """True when the transfer must impersonate another user
        (scp.clj:94-97: sudo set, and not the login user)."""
        sudo = ctx.get("sudo")
        if not sudo:
            return False
        owner = "root" if sudo is True else str(sudo)
        return owner != self.conn_spec.get("username")

    # -- transfers ----------------------------------------------------------

    def upload(self, ctx: dict, local_paths, remote_path) -> None:
        if not self._needs_dance(ctx):
            return self.remote.upload(ctx, local_paths, remote_path)
        sudo = ctx.get("sudo")
        owner = "root" if sudo is True else str(sudo)
        srcs = _coll(local_paths)
        # with several sources (or an explicit directory destination) the
        # destination is a directory: keep each source's basename, like
        # the plain-scp passthrough would
        into_dir = len(srcs) > 1 or str(remote_path).endswith("/")
        self._ensure_tmp_dir()
        for src in srcs:
            dest = (f"{str(remote_path).rstrip('/')}/{os.path.basename(str(src))}"
                    if into_dir else remote_path)
            tmp = self._tmp_file()
            try:
                self.remote.upload({}, src, tmp)
                self._exec({"sudo": "root"}, ["chown", owner, tmp])
                self._exec({"sudo": "root"}, ["mv", tmp, dest])
            finally:
                try:
                    self._exec({"sudo": "root"}, ["rm", "-f", tmp])
                except RemoteError:
                    pass

    def download(self, ctx: dict, remote_paths, local_path) -> None:
        if not self._needs_dance(ctx):
            return self.remote.download(ctx, remote_paths, local_path)
        srcs = _coll(remote_paths)
        into_dir = (len(srcs) > 1 or str(local_path).endswith("/")
                    or os.path.isdir(str(local_path)))
        for src in srcs:
            # readable as the login user? then download directly
            try:
                self._exec({}, ["head", "-c", "1", src])
                self.remote.download({}, src, local_path)
                continue
            except RemoteError:
                pass
            self._ensure_tmp_dir()
            tmp = self._tmp_file()
            try:
                # hardlink if possible; fall back to a full copy
                try:
                    self._exec({"sudo": "root"}, ["ln", "-L", src, tmp])
                except RemoteError:
                    self._exec({"sudo": "root"}, ["cp", src, tmp])
                self._exec({"sudo": "root"}, ["chmod", "a+r", tmp])
                # the tmp file's random name must not leak into a
                # directory destination — restore the source basename
                dest = (f"{str(local_path).rstrip('/')}/"
                        f"{os.path.basename(str(src))}"
                        if into_dir else local_path)
                self.remote.download({}, tmp, dest)
            finally:
                try:
                    self._exec({"sudo": "root"}, ["rm", "-f", tmp])
                except RemoteError:
                    pass
