"""Node-side helpers: daemons, process kills, downloads, archives.

Reference: jepsen/src/jepsen/control/util.clj — daemon management
(:310-360), grepkill (:286-308), cached wget / archive install (:167-275),
plus small fs utilities. All run through the ambient control session.
"""
from __future__ import annotations

import logging
from typing import Any, Iterable

from jepsen_tpu import control
from jepsen_tpu.control import RemoteError

logger = logging.getLogger("jepsen.control.util")

WGET_CACHE_DIR = "/tmp/jepsen/wget-cache"


def file_exists(path: str) -> bool:
    try:
        control.exec_("test", "-e", path)
        return True
    except RemoteError:
        return False


def ls(dir: str = ".") -> list[str]:
    out = control.exec_("ls", "-1", dir)
    return [l for l in out.splitlines() if l]


def ls_full(dir: str) -> list[str]:
    d = dir.rstrip("/")
    return [f"{d}/{f}" for f in ls(d)]


def write_file(content: str, path: str) -> None:
    """Writes a string to a remote file via stdin (util.clj write-file!)."""
    control.exec_("tee", path, stdin=content)


def mkdir(path: str) -> None:
    control.exec_("mkdir", "-p", path)


def rm_rf(path: str) -> None:
    control.exec_("rm", "-rf", path)


# ---------------------------------------------------------------------------
# processes
# ---------------------------------------------------------------------------

def signal(process: str, sig: str = "TERM") -> None:
    """killall -s SIG process; ignores 'no process found'."""
    try:
        control.exec_("killall", "-s", sig, "--", process)
    except RemoteError as e:
        if "no process" not in (e.err or "").lower():
            raise


def grepkill(pattern: str, sig: str = "KILL") -> None:
    """Kills processes whose command line matches pattern
    (util.clj:286-308). pkill -f, tolerant of no matches.

    The first literal character is bracketed ([e]tcd) so the pattern
    doesn't match the wrapper shells executing this very command — a
    bare `pkill -f etcd` SIGSTOPs/KILLs its own sh/sudo ancestors, whose
    command lines contain the pattern."""
    i = next((j for j, ch in enumerate(pattern) if ch.isalnum()), None)
    safe = (f"{pattern[:i]}[{pattern[i]}]{pattern[i + 1:]}"
            if i is not None else pattern)
    try:
        control.exec_("pkill", f"-{sig}", "-f", "--", safe)
    except RemoteError as e:
        if e.exit_status != 1:  # 1 = no processes matched
            raise


# ---------------------------------------------------------------------------
# daemons (util.clj:310-360)
# ---------------------------------------------------------------------------

def start_daemon(opts: dict, bin: str, *args) -> bool:
    """Starts bin as a daemon via start-stop-daemon (falling back to
    setsid+nohup), recording a pidfile. opts: {"logfile", "pidfile",
    "chdir", "background"=True, "make-pidfile"=True, "env"={}}.
    Returns False if already running."""
    pidfile = opts.get("pidfile")
    logfile = opts.get("logfile", "/dev/null")
    chdir = opts.get("chdir", "/")
    if pidfile and file_exists(pidfile):
        try:
            pid = control.exec_("cat", pidfile).strip()
            if pid:
                control.exec_("kill", "-0", pid)
                logger.debug("daemon %s already running (pid %s)", bin, pid)
                return False
        except RemoteError:
            pass  # stale pidfile
    envmap = opts.get("env") or {}
    env_prefix = " ".join(f"{k}={control.escape(str(v))}"
                          for k, v in envmap.items())
    cmd = " ".join([control.escape(bin), *[control.escape(str(a)) for a in args]])
    daemon_cmd = (
        f"cd {control.escape(chdir)} && "
        f"{env_prefix + ' ' if env_prefix else ''}"
        f"setsid nohup {cmd} >> {control.escape(logfile)} 2>&1 < /dev/null & "
        + (f"echo $! > {control.escape(pidfile)}" if pidfile else "true"))
    control.exec_(control.lit(daemon_cmd))
    return True


def stop_daemon(bin_or_pidfile: str, pidfile: str | None = None) -> None:
    """Stops a daemon by pidfile (kill -9 pid, remove pidfile) or by
    name via grepkill (util.clj stop-daemon!)."""
    pf = pidfile if pidfile is not None else (
        bin_or_pidfile if bin_or_pidfile.startswith("/") else None)
    if pf is not None:
        if file_exists(pf):
            try:
                pid = control.exec_("cat", pf).strip()
                if pid:
                    try:
                        control.exec_("kill", "-9", pid)
                    except RemoteError:
                        pass
            finally:
                control.exec_("rm", "-f", pf)
        if pidfile is None:
            return
    if pf is None or (pidfile is not None and bin_or_pidfile != pf):
        grepkill(bin_or_pidfile)


def daemon_running(pidfile: str) -> bool:
    try:
        pid = control.exec_("cat", pidfile).strip()
        control.exec_("kill", "-0", pid)
        return True
    except RemoteError:
        return False


# ---------------------------------------------------------------------------
# downloads & archives (util.clj:167-275)
# ---------------------------------------------------------------------------

def cached_wget(url: str, force: bool = False) -> str:
    """Downloads url into a node-local cache dir; returns the cached path."""
    name = url.rstrip("/").rsplit("/", 1)[-1] or "download"
    mkdir(WGET_CACHE_DIR)
    path = f"{WGET_CACHE_DIR}/{name}"
    if force or not file_exists(path):
        control.exec_("wget", "-O", f"{path}.tmp", url)
        control.exec_("mv", f"{path}.tmp", path)
    return path


def install_archive(url: str, dest: str, force: bool = False,
                    user: str | None = None) -> str:
    """Downloads (cached) and unpacks a tar/zip archive into dest,
    stripping a single top-level directory (util.clj install-archive!)."""
    archive = cached_wget(url, force=force)
    rm_rf(dest)
    mkdir(dest)
    if archive.endswith(".zip"):
        tmp = f"{dest}.unzip-tmp"
        rm_rf(tmp)
        mkdir(tmp)
        control.exec_("unzip", "-q", archive, "-d", tmp)
        entries = ls_full(tmp)
        src = entries[0] if len(entries) == 1 else tmp
        control.exec_(control.lit(
            f"mv {control.escape(src)}/* {control.escape(dest)}/"))
        rm_rf(tmp)
    else:
        control.exec_("tar", "-xf", archive, "-C", dest,
                      "--strip-components=1")
    if user:
        control.exec_("chown", "-R", user, dest)
    return dest


def await_tcp_port(port: int, host: str = "localhost",
                   timeout_s: float = 60.0, dt: float = 1.0) -> None:
    """Blocks until the port accepts connections (util.clj await-tcp-port)."""
    from jepsen_tpu.utils import await_fn

    def check():
        control.exec_("bash", "-c",
                      f"exec 3<>/dev/tcp/{host}/{port} && exec 3>&-")
        return True

    await_fn(check, retry_interval=dt, timeout_s=timeout_s,
             log_message=f"waiting for {host}:{port}")


def control_ip(peer: str | None = None) -> str:
    """The control node's IP as routable from the db nodes (reference:
    control/net.clj:19-40 control-ip) — used e.g. by the tcpdump DB's
    clients-only filter. A UDP connect (no packets sent) picks the local
    address the kernel would route toward ``peer``."""
    import socket
    target = peer or "10.255.255.255"
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect((target, 9))
        return s.getsockname()[0]
    except OSError:
        return socket.gethostbyname(socket.gethostname())
    finally:
        s.close()
