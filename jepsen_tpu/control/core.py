"""The pluggable remote-execution transport (reference:
jepsen/src/jepsen/control/core.clj).

``Remote`` is the abstraction every transport implements
(control/core.clj:7-58): connect/disconnect/execute/upload/download.
Shell-escaping helpers mirror lit/escape/env/wrap-sudo (:60-153).
"""
from __future__ import annotations

import shlex
from dataclasses import dataclass, field
from typing import Any, Sequence


class RemoteError(Exception):
    def __init__(self, msg, cmd=None, exit_status=None, out="", err="", host=None):
        super().__init__(msg)
        self.cmd = cmd
        self.exit_status = exit_status
        self.out = out
        self.err = err
        self.host = host

    def __repr__(self):
        return (f"RemoteError(host={self.host!r}, cmd={self.cmd!r}, "
                f"exit={self.exit_status!r}, err={self.err[:200]!r})")


@dataclass
class Result:
    cmd: str
    exit_status: int
    out: str
    err: str
    host: str | None = None


class Lit:
    """An unescaped literal shell fragment (control/core.clj lit)."""

    __slots__ = ("s",)

    def __init__(self, s: str):
        self.s = s

    def __str__(self):
        return self.s


def lit(s: str) -> Lit:
    return Lit(s)


def escape(arg: Any) -> str:
    """Shell-escapes one argument; Lit passes through
    (control/core.clj:67-110)."""
    if isinstance(arg, Lit):
        return arg.s
    if isinstance(arg, (list, tuple)):
        return " ".join(escape(a) for a in arg)
    s = str(arg)
    if s == "":
        return "''"
    return shlex.quote(s)


def join_cmd(args: Sequence[Any]) -> str:
    return " ".join(escape(a) for a in args)


def env(env_map: dict) -> Lit:
    """Renders an env-var prefix: env({'A': 1}) -> A=1
    (control/core.clj:112-140)."""
    return lit(" ".join(f"{k}={escape(v)}" for k, v in env_map.items()))


def wrap_sudo(ctx: dict, cmd: str) -> str:
    """Wraps a command in sudo -u / -S as per context
    (control/core.clj:142-153)."""
    sudo = ctx.get("sudo")
    if not sudo:
        return cmd
    user = "" if sudo is True else f"-u {escape(sudo)} "
    return f"sudo {user}-S -- sh -c {escape(cmd)}"


def wrap_cd(ctx: dict, cmd: str) -> str:
    d = ctx.get("dir")
    if d:
        return f"cd {escape(d)} && {cmd}"
    return cmd


class Remote:
    """Transport protocol (control/core.clj:7-58)."""

    def connect(self, conn_spec: dict) -> "Remote":
        """Returns a connected copy of this remote."""
        return self

    def disconnect(self) -> None:
        pass

    def execute(self, ctx: dict, cmd: str) -> Result:
        """Runs a shell command, returning a Result. ctx carries sudo/dir."""
        raise NotImplementedError

    def upload(self, ctx: dict, local_paths, remote_path) -> None:
        raise NotImplementedError

    def download(self, ctx: dict, remote_paths, local_path) -> None:
        raise NotImplementedError


def throw_on_nonzero_exit(res: Result) -> Result:
    """(control/core.clj:155-171)"""
    if res.exit_status != 0:
        raise RemoteError(
            f"command {res.cmd!r} on {res.host} exited {res.exit_status}: "
            f"{res.err[:500]}",
            cmd=res.cmd, exit_status=res.exit_status, out=res.out,
            err=res.err, host=res.host,
        )
    return res
