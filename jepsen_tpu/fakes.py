"""In-memory test doubles: a CAS register over a locked cell, with a meta-log
of lifecycle calls.

Reference: jepsen/src/jepsen/tests.clj:27-67 (atom-db / atom-client), the
backbone of cluster-free integration tests of the full run lifecycle
(core_test.clj basic-cas-test et al., SURVEY.md §4 tier 2).
"""
from __future__ import annotations

import json
import threading
import time as _time
from pathlib import Path
from typing import Any

from jepsen_tpu import db as db_mod
from jepsen_tpu.client import Client


class MetaLogDB(db_mod.NoopDB, db_mod.Process, db_mod.Pause):
    """Base for in-memory 'clusters': a data lock plus a meta-log of
    lifecycle calls for assertions. Subclasses override ``_wipe`` to clear
    their data under the lock on teardown.

    Implements Process/Pause as meta-logged no-ops so fake-mode tests can
    schedule kill/pause nemesis packages end to end (an in-memory store
    has no process to kill, but the fault plumbing all runs)."""

    def __init__(self):
        self.lock = threading.Lock()
        self.log: list[tuple] = []
        self._log_lock = threading.Lock()

    def _note(self, *event):
        with self._log_lock:
            self.log.append(event)

    def _wipe(self):
        pass

    def setup(self, test, node):
        self._note("db-setup", node)

    def teardown(self, test, node):
        with self.lock:
            self._wipe()
        self._note("db-teardown", node)

    def start(self, test, node):
        self._note("db-start", node)

    def kill(self, test, node):
        self._note("db-kill", node)

    def pause(self, test, node):
        self._note("db-pause", node)

    def resume(self, test, node):
        self._note("db-resume", node)


class AtomDB(MetaLogDB):
    """An in-memory 'cluster': one locked cell shared by all clients
    (tests.clj:27-44 atom-db)."""

    def __init__(self):
        super().__init__()
        self.value: Any = None

    def _wipe(self):
        self.value = None

    # register primitives used by AtomClient
    def read(self):
        with self.lock:
            return self.value

    def write(self, v):
        with self.lock:
            self.value = v

    def cas(self, old, new) -> bool:
        with self.lock:
            if self.value == old:
                self.value = new
                return True
            return False


class MetaLogClient(Client):
    """Base for clients over a MetaLogDB: records open/setup/teardown/close
    in the db's meta-log (tests.clj atom-client lifecycle shape)."""

    def __init__(self, db: MetaLogDB, node: str | None = None):
        self.db = db
        self.node = node

    def open(self, test, node):
        c = type(self)(self.db, node)
        self.db._note("client-open", node)
        return c

    def setup(self, test):
        self.db._note("client-setup", self.node)

    def teardown(self, test):
        self.db._note("client-teardown", self.node)

    def close(self, test):
        self.db._note("client-close", self.node)


class AtomClient(MetaLogClient):
    """CAS-register client over an AtomDB (tests.clj atom-client)."""

    def supported_fs(self, test):
        return {"read", "write", "cas"}

    def invoke(self, test, op):
        f, v = op.get("f"), op.get("value")
        if f == "read":
            return {**op, "type": "ok", "value": self.db.read()}
        if f == "write":
            self.db.write(v)
            return {**op, "type": "ok"}
        if f == "cas":
            old, new = v
            ok = self.db.cas(old, new)
            return {**op, "type": "ok" if ok else "fail"}
        return {**op, "type": "fail", "error": ["unknown-f", f]}


class KVStore(MetaLogDB):
    """In-memory many-key 'cluster': a dict of CAS registers plus a grow-only
    set — enough surface for the register (independent-lifted) and set
    workloads that suites run in --fake mode."""

    def __init__(self):
        super().__init__()
        self.registers: dict = {}
        self.elements: set = set()
        self.lists: dict = {}
        self.accounts: dict = {}   # bank workload balances
        self.rows: dict = {}       # dirty-reads workload rows
        self.mono: list = []       # monotonic workload (val, ts) rows
        self.seq: set = set()      # sequential workload subkeys
        self.adya: dict = {}       # adya G2 pair -> (cell, uid)
        self.holder = None         # mutex workload: current lock holder
        self.counter = 0           # counter workload
        self.ddl_rows: list | None = None  # default-value table (None=absent)
        self.ddl_next = 0
        self.cmt: dict = {}        # comments workload: key -> set of ids
        self.tables: set = set()   # table workload: created table ids
        self.lu: dict = {}         # lost-updates workload: key -> set
        self.mono_keys: dict = {}  # monotonic-key pool (tidb inc-workload)
        self.ledger: dict = {}     # ledger workload: account -> balance
        self.del_records: dict = {}  # delete workload: key -> uid
        self.del_next = 0

    def _wipe(self):
        self.registers.clear()
        self.elements.clear()
        self.lists.clear()
        self.accounts.clear()
        self.rows.clear()
        self.mono.clear()
        self.seq.clear()
        self.adya.clear()
        self.holder = None
        self.counter = 0
        self.ddl_rows = None
        self.ddl_next = 0
        self.cmt.clear()
        self.tables.clear()
        self.lu.clear()
        self.mono_keys.clear()
        self.ledger.clear()
        self.del_records.clear()
        self.del_next = 0

    def read(self, k):
        with self.lock:
            return self.registers.get(k)

    def write(self, k, v):
        with self.lock:
            self.registers[k] = v

    def cas(self, k, old, new) -> bool:
        with self.lock:
            if self.registers.get(k) == old:
                self.registers[k] = new
                return True
            return False

    def add(self, elem):
        with self.lock:
            self.elements.add(elem)

    def set_read(self) -> list:
        with self.lock:
            return sorted(self.elements)

    def set_read_raw(self) -> set:
        with self.lock:
            return set(self.elements)

    def contains(self, elem) -> bool:
        with self.lock:
            return elem in self.elements

    def txn(self, micro_ops, style: str = "append") -> list:
        """Atomically applies a txn of [f, k, v] micro-ops. ``style``
        picks what a read returns: "append" (the per-key list, Elle
        list-append) or "wr" (the register value, Elle rw-register /
        long-fork)."""
        with self.lock:
            out = []
            for f, k, v in micro_ops:
                if f == "r" and style == "wr":
                    out.append(["r", k, self.registers.get(k)])
                elif f == "r":
                    out.append(["r", k, list(self.lists.get(k, []))])
                elif f == "append":
                    self.lists.setdefault(k, []).append(v)
                    out.append(["append", k, v])
                elif f == "w":
                    self.registers[k] = v
                    out.append(["w", k, v])
                else:
                    raise ValueError(f"unknown micro-op {f!r}")
            return out

    def multi_txn(self, group, micro_ops) -> list:
        """Atomic multi-register txn for one independent key group
        (multi-key-acid): registers live at (group, k)."""
        with self.lock:
            out = []
            for f, k, v in micro_ops:
                if f == "r":
                    out.append(["r", k, self.registers.get((group, k))])
                elif f == "w":
                    self.registers[(group, k)] = v
                    out.append(["w", k, v])
                else:
                    raise ValueError(f"unknown micro-op {f!r}")
            return out

    # default-value workload: one DDL-churned table with an int column
    # whose default is 0 (the fake is anomaly-free: inserts always carry
    # the default, so reads never surface a null)
    def ddl_create(self) -> None:
        with self.lock:
            if self.ddl_rows is None:
                self.ddl_rows = []

    def ddl_drop(self) -> None:
        with self.lock:
            self.ddl_rows = None

    def ddl_insert(self) -> bool:
        with self.lock:
            if self.ddl_rows is None:
                return False
            self.ddl_rows.append({"id": self.ddl_next, "v": 0})
            self.ddl_next += 1
            return True

    def ddl_read(self) -> list | None:
        with self.lock:
            return (None if self.ddl_rows is None
                    else [dict(r) for r in self.ddl_rows])

    # table workload: created-table visibility (the fake is anomaly-free)
    def tbl_create(self, tid) -> None:
        with self.lock:
            self.tables.add(tid)

    def tbl_insert(self, tid) -> bool:
        with self.lock:
            return tid in self.tables

    # upsert workload: at most one record per key (the fake is
    # anomaly-free: creates are idempotent under the lock)
    def upsert_create(self, k) -> None:
        with self.lock:
            self.registers.setdefault(("__upsert__", k), f"u{k}")

    def upsert_read(self, k) -> list:
        with self.lock:
            u = self.registers.get(("__upsert__", k))
            return [u] if u is not None else []

    # version-divergence workload: per-key (value, version) rows — the
    # fake's versions advance atomically, so no version ever diverges
    def vd_write(self, k, val) -> None:
        with self.lock:
            _v, ver = self.registers.get(("__vd__", k), (None, 0))
            self.registers[("__vd__", k)] = (val, ver + 1)

    def vd_read(self, k) -> list:
        with self.lock:
            val, ver = self.registers.get(("__vd__", k), (None, None))
            return [val, ver]

    # pages workload: per-key element groups appended atomically
    def pages_add(self, k, group) -> None:
        with self.lock:
            self.lists.setdefault(("__pages__", k), []).extend(group)

    def pages_read(self, k) -> list:
        with self.lock:
            return sorted(self.lists.get(("__pages__", k), []))

    # lost-updates workload: per-key element sets (the fake applies
    # adds atomically, so no update is ever lost)
    def lu_add(self, k, el) -> None:
        with self.lock:
            self.lu.setdefault(k, set()).add(el)

    def lu_read(self, k) -> list:
        with self.lock:
            return sorted(self.lu.get(k, ()))

    # comments workload: per-key visible-id sets
    def cmt_write(self, k, i) -> None:
        with self.lock:
            self.cmt.setdefault(k, set()).add(i)

    def cmt_read(self, k) -> list:
        with self.lock:
            return sorted(self.cmt.get(k, ()))

    def enqueue(self, v):
        with self.lock:
            self.lists.setdefault("__queue__", []).append(v)

    def dequeue(self):
        """The head element, or None when empty."""
        with self.lock:
            q = self.lists.get("__queue__")
            return q.pop(0) if q else None

    def drain(self) -> list:
        with self.lock:
            q = self.lists.get("__queue__", [])
            out = list(q)
            q.clear()
            return out

    # bank (workloads/bank.py): atomic transfers over an accounts dict
    def bank_init(self, accounts, balance: int):
        with self.lock:
            for a in accounts:
                self.accounts.setdefault(a, balance)

    def bank_read(self) -> dict:
        with self.lock:
            return dict(self.accounts)

    def transfer(self, frm, to, amount: int) -> bool:
        """Atomically moves amount; refuses to overdraw (the reference
        bank clients fail transfers that would go negative)."""
        with self.lock:
            if self.accounts.get(frm, 0) < amount:
                return False
            self.accounts[frm] -= amount
            self.accounts[to] = self.accounts.get(to, 0) + amount
            return True

    # monotonic (workloads/monotonic.py): read-max-insert-max+1 rows
    def mono_inc(self) -> int:
        with self.lock:
            val = (self.mono[-1][0] + 1) if self.mono else 0
            self.mono.append((val, len(self.mono)))
            return val

    def mono_read(self) -> list:
        with self.lock:
            return [[v, ts] for v, ts in self.mono]

    # delete workload (workloads/delete_workload.py, dgraph/delete.clj):
    # key -> uid; reads see the whole record
    def del_upsert(self, k):
        """uid when created, None when already present."""
        with self.lock:
            if k in self.del_records:
                return None
            self.del_next += 1
            self.del_records[k] = f"0x{self.del_next:x}"
            return self.del_records[k]

    def del_delete(self, k):
        with self.lock:
            return self.del_records.pop(k, None)

    def del_read(self, k) -> list:
        with self.lock:
            uid = self.del_records.get(k)
            return [{"uid": uid, "key": k}] if uid is not None else []

    # per-process-monotonic register (workloads/dgraph_sequential.py)
    def seq_reg_inc(self, k) -> int:
        with self.lock:
            v = self.mono_keys.get(("seq", k), 0) + 1
            self.mono_keys[("seq", k)] = v
            return v

    def seq_reg_read(self, k) -> int:
        with self.lock:
            return self.mono_keys.get(("seq", k), 0)

    # monotonic-key (workloads/monotonic_key.py, tidb's inc-workload):
    # per-key increment-only pool, -1 = never written
    def mono_key_inc(self, k) -> int:
        with self.lock:
            v = self.mono_keys.get(k, -1) + 1
            self.mono_keys[k] = v
            return v

    def mono_key_read(self, ks) -> dict:
        with self.lock:
            return {k: self.mono_keys.get(k, -1) for k in ks}

    # ledger (workloads/ledger.py): row-per-transfer balances with a
    # non-negative guard, atomic here so the fake never double-spends
    def ledger_transfer(self, account, amount) -> bool:
        with self.lock:
            balance = self.ledger.get(account, 0)
            if amount < 0 and balance + amount < 0:
                return False
            self.ledger[account] = balance + amount
            return True

    # counter (workloads/counter.py)
    def counter_add(self, delta: int) -> None:
        with self.lock:
            self.counter += delta

    def counter_read(self) -> int:
        with self.lock:
            return self.counter

    # mutex (workloads/mutex.py): one lock, owner-checked release
    def acquire(self, p) -> bool:
        with self.lock:
            if self.holder is None:
                self.holder = p
                return True
            return False

    def release(self, p) -> bool:
        with self.lock:
            if self.holder == p:
                self.holder = None
                return True
            return False

    # adya G2 (workloads/adya.py): insert-if-pair-empty, atomically
    def adya_insert(self, pair, uid, cell) -> bool:
        with self.lock:
            if pair in self.adya:
                return False
            self.adya[pair] = (cell, uid)
            return True

    # sequential (workloads/sequential.py): ordered subkey inserts
    def seq_write(self, sks) -> None:
        with self.lock:
            for sk in sks:
                self.seq.add(sk)

    def seq_read(self, sks) -> list:
        with self.lock:
            return [sk if sk in self.seq else None for sk in sks]

    # dirty-reads (workloads/dirty_reads.py): n rows set atomically
    def rows_init(self, n: int):
        with self.lock:
            for i in range(n):
                self.rows.setdefault(i, -1)

    def write_all_rows(self, x):
        with self.lock:
            for i in self.rows:
                self.rows[i] = x

    def read_all_rows(self) -> list:
        with self.lock:
            return [v for _, v in sorted(self.rows.items())]


class KVClient(MetaLogClient):
    """Client over a KVStore, speaking both the independent-lifted register
    protocol ([k, v] tuple values, independent.clj:21-29) and the set
    workload's add/read ops.

    ``whole_read`` disambiguates what a bare ``{"f": "read", "value":
    None}`` means — "set" (whole-set read, the default), "bank" (all
    balances as a dict), or "dirty" (all dirty-reads rows) — since those
    three workloads share the same op shape."""

    def __init__(self, db: MetaLogDB, node: str | None = None,
                 whole_read: str = "set", txn_style: str = "append"):
        super().__init__(db, node)
        self.whole_read = whole_read
        self.txn_style = txn_style

    def open(self, test, node):
        c = type(self)(self.db, node, self.whole_read, self.txn_style)
        self.db._note("client-open", node)
        return c

    def setup(self, test):
        super().setup(test)
        if self.whole_read == "bank":
            self.db.bank_init(test.get("accounts", range(8)), 10)
        elif self.whole_read == "dirty":
            self.db.rows_init(int(test.get("dirty-rows", 4)))

    # the union of every dispatch arm below — preflight checks
    # generator-emitted :f values against this set before a run starts
    SUPPORTED_FS = frozenset({
        "read", "write", "cas", "add", "txn", "enqueue", "dequeue",
        "drain", "transfer", "insert", "acquire", "release", "inc",
        "read-all", "create-table", "drop-table", "upsert", "read-uids",
        "refresh", "strong-read", "delete",
    })

    def supported_fs(self, test):
        return set(self.SUPPORTED_FS)

    def invoke(self, test, op):
        f, v = op.get("f"), op.get("value")
        if test.get("counter") and f == "add":
            self.db.counter_add(int(v))
            return {**op, "type": "ok"}
        if test.get("counter") and f == "read" and v is None:
            return {**op, "type": "ok", "value": self.db.counter_read()}
        if test.get("txn-mode") == "multi" and f == "txn":
            k, mops = v
            return {**op, "type": "ok",
                    "value": [k, self.db.multi_txn(k, mops)]}
        if test.get("ddl-table"):
            if f == "create-table":
                self.db.ddl_create()
                return {**op, "type": "ok"}
            if f == "drop-table":
                self.db.ddl_drop()
                return {**op, "type": "ok"}
            if f == "insert":
                ok = self.db.ddl_insert()
                return {**op, "type": "ok" if ok else "fail"}
            if f == "read":
                rows = self.db.ddl_read()
                if rows is None:
                    return {**op, "type": "fail", "error": ["no-table"]}
                return {**op, "type": "ok", "value": rows}
        if test.get("table-workload"):
            if f == "create-table":
                self.db.tbl_create(v)
                return {**op, "type": "ok"}
            if f == "insert":
                tid, _k = v
                if self.db.tbl_insert(tid):
                    return {**op, "type": "ok"}
                return {**op, "type": "fail", "error": ["doesnt-exist", tid]}
        if test.get("upsert-workload"):
            if f == "upsert":
                k, _uid = v
                self.db.upsert_create(k)
                return {**op, "type": "ok"}
            if f == "read-uids":
                k, _ = v
                return {**op, "type": "ok",
                        "value": [k, self.db.upsert_read(k)]}
        if test.get("pages"):
            if f == "add":
                k, group = v
                self.db.pages_add(k, group)
                return {**op, "type": "ok"}
            if f == "read":
                k, _ = v
                return {**op, "type": "ok",
                        "value": [k, self.db.pages_read(k)]}
        if test.get("dirty-read"):
            if f == "write":
                self.db.add(("__dr__", v))
                return {**op, "type": "ok"}
            if f == "read" and v is not None:
                present = self.db.contains(("__dr__", v))
                return {**op, "type": "ok" if present else "fail"}
            if f == "refresh":
                return {**op, "type": "ok"}
            if f == "strong-read":
                els = [x[1] for x in self.db.set_read_raw()
                       if isinstance(x, tuple) and x[0] == "__dr__"]
                return {**op, "type": "ok", "value": sorted(els)}
        if test.get("version-divergence"):
            if f == "write":
                k, val = v
                self.db.vd_write(k, val)
                return {**op, "type": "ok"}
            if f == "read":
                k, _ = v
                return {**op, "type": "ok",
                        "value": [k, self.db.vd_read(k)]}
        if test.get("lost-updates") or test.get("pause-workload"):
            if f == "add":
                k, el = v
                self.db.lu_add(k, el)
                return {**op, "type": "ok"}
            if f == "read":
                k, _ = v
                return {**op, "type": "ok",
                        "value": [k, self.db.lu_read(k)]}
        if test.get("comments"):
            if f == "write":
                k, i = v
                self.db.cmt_write(k, i)
                return {**op, "type": "ok"}
            if f == "read":
                k, _ = v
                return {**op, "type": "ok",
                        "value": [k, self.db.cmt_read(k)]}
        if test.get("monotonic-key"):
            if f == "inc":
                return {**op, "type": "ok",
                        "value": {v: self.db.mono_key_inc(v)}}
            if f == "read":
                return {**op, "type": "ok",
                        "value": self.db.mono_key_read(
                            list((v or {}).keys()))}
        if test.get("delete-workload"):
            k, _ = v
            if f == "read":
                return {**op, "type": "ok",
                        "value": [k, self.db.del_read(k)]}
            if f == "upsert":
                uid = self.db.del_upsert(k)
                if uid is None:
                    return {**op, "type": "fail", "error": ["present"]}
                return {**op, "type": "ok"}
            if f == "delete":
                uid = self.db.del_delete(k)
                if uid is None:
                    return {**op, "type": "fail", "error": ["not-found"]}
                return {**op, "type": "ok"}
        if test.get("dgraph-sequential"):
            k, _ = v
            if f == "inc":
                return {**op, "type": "ok",
                        "value": [k, self.db.seq_reg_inc(k)]}
            if f == "read":
                return {**op, "type": "ok",
                        "value": [k, self.db.seq_reg_read(k)]}
        if test.get("ledger") and f == "transfer":
            account, amount = v[0], v[1]
            ok = self.db.ledger_transfer(account, int(amount))
            return {**op, "type": "ok" if ok else "fail"}
        if f == "transfer":
            t = v or {}
            ok = self.db.transfer(t.get("from"), t.get("to"),
                                  int(t.get("amount", 0)))
            return {**op, "type": "ok" if ok else "fail"}
        if f == "read" and v is None and self.whole_read == "bank":
            return {**op, "type": "ok", "value": self.db.bank_read()}
        if f == "read" and v is None and self.whole_read == "dirty":
            return {**op, "type": "ok", "value": self.db.read_all_rows()}
        if f == "write" and self.whole_read == "dirty":
            self.db.write_all_rows(v)
            return {**op, "type": "ok"}
        if f == "insert":
            pair, uid, cell = v
            ok = self.db.adya_insert(pair, uid, cell)
            return {**op, "type": "ok" if ok else "fail"}
        if f == "acquire":
            ok = self.db.acquire(op.get("process"))
            return {**op, "type": "ok" if ok else "fail"}
        if f == "release":
            ok = self.db.release(op.get("process"))
            return {**op, "type": "ok" if ok else "fail"}
        if f == "inc":
            return {**op, "type": "ok", "value": self.db.mono_inc()}
        if f == "read-all":
            return {**op, "type": "ok", "value": self.db.mono_read()}
        if test.get("key-count") and f in ("read", "write") \
                and not isinstance(v, (list, tuple)):
            from jepsen_tpu.workloads.sequential import subkeys
            sks = subkeys(int(test.get("key-count", 5)), v)
            if f == "write":
                self.db.seq_write(sks)
                return {**op, "type": "ok"}
            return {**op, "type": "ok",
                    "value": [v, self.db.seq_read(list(reversed(sks)))]}
        if f == "txn":
            return {**op, "type": "ok",
                    "value": self.db.txn(v, style=self.txn_style)}
        if f == "add":
            self.db.add(v)
            return {**op, "type": "ok"}
        if f == "read" and v is None:  # whole-set read
            return {**op, "type": "ok", "value": self.db.set_read()}
        if f == "read":
            k, _ = v
            return {**op, "type": "ok", "value": [k, self.db.read(k)]}
        if f == "write":
            k, val = v
            self.db.write(k, val)
            return {**op, "type": "ok"}
        if f == "cas":
            k, (old, new) = v
            ok = self.db.cas(k, old, new)
            return {**op, "type": "ok" if ok else "fail"}
        if f == "enqueue":
            self.db.enqueue(v)
            return {**op, "type": "ok"}
        if f == "dequeue":
            out = self.db.dequeue()
            if out is None:
                return {**op, "type": "fail"}
            return {**op, "type": "ok", "value": out}
        if f == "drain":
            return {**op, "type": "ok", "value": self.db.drain()}
        return {**op, "type": "fail", "error": ["unknown-f", f]}


class FakeClusterState:  # durability: fsync
    """A membership State (nemesis/membership.py) over a DURABLE fake
    cluster: the member set lives in a JSON file, so reconfigurations
    survive SIGKILL — exactly the crash-safety story the chaos lane
    exercises (a killed run's ``cli heal`` restores the recorded pre-op
    member set by rewriting this file).

    ``settle_s`` keeps a reconfiguration *in flight* (unresolved) for
    that long after its invoke — the SIGKILL window for the chaos test,
    and a stand-in for a real cluster's convergence delay. ``op()``
    alternately shrinks down to ``min_members`` and grows back, one
    node at a time, never with another op in flight.

    ``clock_rate`` is the libfaketime rate factor (faketime.py): the
    fake cluster's convergence clock runs ``clock_rate``× wall speed,
    so a clock-rate nemesis window composed with a membership reconfig
    settles deterministically in *cluster* time — ``settle_s`` used to
    be measured in raw wall seconds, which silently decoupled the two
    nemeses in fake mode (a 2× clock made the settle window look twice
    as long to the cluster). ``set_clock_rate`` flips it mid-run the
    way a clock-rate window begins/ends. ``time_fn`` injects the wall
    clock itself (tests, deterministic fuzz trials).
    """

    def __init__(self, path, nodes=None, settle_s: float = 0.0,
                 min_members: int = 1, clock_rate: float = 1.0,
                 time_fn=None):
        self.path = Path(path)
        self.settle_s = settle_s
        self.min_members = min_members
        self.clock_rate = float(clock_rate) if clock_rate and \
            clock_rate > 0 else 1.0
        self._time_fn = time_fn if time_fn is not None else _time.time
        self._lock = threading.Lock()
        self._inflight = 0
        if self.path.exists():
            self._members = set(json.loads(self.path.read_text()))
        else:
            self._members = set(nodes or [])
            self.path.parent.mkdir(parents=True, exist_ok=True)
            self._persist()
        self._all = sorted(set(nodes or []) | set(self._members))

    def _persist(self) -> None:
        """Atomic durable write: the chaos test inspects this file
        after SIGKILL, so a torn member set is not an option."""
        from jepsen_tpu.utils import atomic_write_json
        atomic_write_json(self.path, sorted(self._members))

    # -- State protocol --------------------------------------------------
    def fs(self):
        return {"grow", "shrink"}

    def node_view(self, test, node):
        with self._lock:
            return sorted(self._members)

    def merge_views(self, test, views):
        return self

    def members(self):
        with self._lock:
            return set(self._members)

    def heal_spec(self, test):
        return {"mechanism": "file", "path": str(self.path)}

    def op(self, test):
        with self._lock:
            if self._inflight:
                return "pending"  # one reconfig at a time
            members = sorted(self._members)
            absent = [n for n in self._all if n not in self._members]
            if len(members) > self.min_members and not absent:
                return {"type": "info", "f": "shrink", "value": members[-1]}
            if absent:
                return {"type": "info", "f": "grow", "value": absent[0]}
            return "pending"

    def invoke(self, test, op):
        f, node = op.get("f"), op.get("value")
        with self._lock:
            if f == "shrink":
                self._members.discard(node)
            elif f == "grow":
                self._members.add(node)
            else:
                return ["unknown-f", f]
            self._persist()
            self._inflight += 1
        return {"action": f, "node": node, "at": self._time_fn()}

    def resolve(self, test):
        return self

    def set_clock_rate(self, factor) -> None:
        """Applies a libfaketime-style rate factor to the convergence
        clock (a clock-rate fault window opening/closing). Garbage or
        non-positive factors read as 1.0 — the nemesis must never wedge
        the cluster it is faulting."""
        try:
            f = float(factor)
        except (TypeError, ValueError):
            f = 1.0
        self.clock_rate = f if f > 0 else 1.0

    def mutate_knobs(self, rng) -> dict:
        """Seeded knob mutation for schedule fuzzing (doc/robustness.md
        "Schedule fuzzing"): jiggles the settle window and the member-
        count floor with the caller's rng and returns the new knob dict
        — the same rng state always produces the same knobs, so a
        fuzzed schedule's seed tuple fully determines the cluster."""
        self.settle_s = round(rng.choice(
            (0.0, 0.01, 0.05, 0.1, 0.25)) * rng.choice((1, 1, 2)), 4)
        upper = max(1, len(self._all) - 1) if self._all else 1
        self.min_members = rng.randint(1, upper)
        return {"settle_s": self.settle_s,
                "min_members": self.min_members}

    def resolve_op(self, test, pending_pair):
        _op, value = pending_pair
        if not isinstance(value, dict):
            return self  # errored invoke: nothing will ever converge it
        # the settle window is measured on the CLUSTER's clock: wall
        # elapsed × the active faketime rate factor (a 2× clock
        # converges in half the wall time, exactly as a real node
        # LD_PRELOADed with "+0 x2" would)
        elapsed = self._time_fn() - value.get("at", 0.0)
        if elapsed * self.clock_rate < self.settle_s:
            return None  # still settling (the SIGKILL window)
        with self._lock:
            self._inflight = max(0, self._inflight - 1)
        return self

    def teardown(self, test):
        pass  # the members file stays — it IS the cluster's state


class CrashingClient(Client):
    """Always raises — exercises the interpreter's indeterminate-op path
    (core_test.clj worker-recovery-test)."""

    def __init__(self):
        self.invocations = 0
        self._lock = threading.Lock()

    def open(self, test, node):
        return self

    def invoke(self, test, op):
        with self._lock:
            self.invocations += 1
        raise RuntimeError("client crashed (as designed)")

    def close(self, test):
        pass


def noop_test(**overrides) -> dict:
    """Default test map (reference: jepsen/src/jepsen/tests.clj:12-25 noop-test).
    A test is plain data; suites merge over these defaults."""
    from jepsen_tpu import checker
    test = {
        "name": "noop",
        "nodes": ["n1", "n2", "n3", "n4", "n5"],
        "concurrency": 5,
        "ssh": {"dummy": True},
        "os": None,
        "db": db_mod.NoopDB(),
        "client": None,
        "nemesis": None,
        "generator": None,
        "checker": checker.unbridled_optimism(),
        "time_limit": 60,
    }
    test.update(overrides)
    return test
