"""In-memory test doubles: a CAS register over a locked cell, with a meta-log
of lifecycle calls.

Reference: jepsen/src/jepsen/tests.clj:27-67 (atom-db / atom-client), the
backbone of cluster-free integration tests of the full run lifecycle
(core_test.clj basic-cas-test et al., SURVEY.md §4 tier 2).
"""
from __future__ import annotations

import threading
from typing import Any

from jepsen_tpu import db as db_mod
from jepsen_tpu.client import Client


class AtomDB(db_mod.NoopDB):
    """An in-memory 'cluster': one locked cell shared by all clients.
    Records setup/teardown calls per node for lifecycle assertions."""

    def __init__(self):
        self.lock = threading.Lock()
        self.value: Any = None
        self.log: list[tuple] = []
        self._log_lock = threading.Lock()

    def _note(self, *event):
        with self._log_lock:
            self.log.append(event)

    def setup(self, test, node):
        self._note("db-setup", node)

    def teardown(self, test, node):
        with self.lock:
            self.value = None
        self._note("db-teardown", node)

    # register primitives used by AtomClient
    def read(self):
        with self.lock:
            return self.value

    def write(self, v):
        with self.lock:
            self.value = v

    def cas(self, old, new) -> bool:
        with self.lock:
            if self.value == old:
                self.value = new
                return True
            return False


class AtomClient(Client):
    """CAS-register client over an AtomDB (tests.clj atom-client)."""

    def __init__(self, db: AtomDB, node: str | None = None):
        self.db = db
        self.node = node

    def open(self, test, node):
        c = AtomClient(self.db, node)
        self.db._note("client-open", node)
        return c

    def setup(self, test):
        self.db._note("client-setup", self.node)

    def invoke(self, test, op):
        f, v = op.get("f"), op.get("value")
        if f == "read":
            return {**op, "type": "ok", "value": self.db.read()}
        if f == "write":
            self.db.write(v)
            return {**op, "type": "ok"}
        if f == "cas":
            old, new = v
            ok = self.db.cas(old, new)
            return {**op, "type": "ok" if ok else "fail"}
        return {**op, "type": "fail", "error": ["unknown-f", f]}

    def teardown(self, test):
        self.db._note("client-teardown", self.node)

    def close(self, test):
        self.db._note("client-close", self.node)


class CrashingClient(Client):
    """Always raises — exercises the interpreter's indeterminate-op path
    (core_test.clj worker-recovery-test)."""

    def __init__(self):
        self.invocations = 0
        self._lock = threading.Lock()

    def open(self, test, node):
        return self

    def invoke(self, test, op):
        with self._lock:
            self.invocations += 1
        raise RuntimeError("client crashed (as designed)")

    def close(self, test):
        pass


def noop_test(**overrides) -> dict:
    """Default test map (reference: jepsen/src/jepsen/tests.clj:12-25 noop-test).
    A test is plain data; suites merge over these defaults."""
    from jepsen_tpu import checker
    test = {
        "name": "noop",
        "nodes": ["n1", "n2", "n3", "n4", "n5"],
        "concurrency": 5,
        "ssh": {"dummy": True},
        "os": None,
        "db": db_mod.NoopDB(),
        "client": None,
        "nemesis": None,
        "generator": None,
        "checker": checker.unbridled_optimism(),
        "time_limit": 60,
    }
    test.update(overrides)
    return test
