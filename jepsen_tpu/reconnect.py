"""Auto-reconnecting client-connection wrapper.

Reference: jepsen/src/jepsen/reconnect.clj — wraps a connection in a
read-write-locked box; any exception inside ``with_conn`` closes and
reopens the connection (under the write lock) before the exception
propagates, so the next op gets a fresh conn.
"""
from __future__ import annotations

import logging
import threading
from typing import Any, Callable

logger = logging.getLogger("jepsen.reconnect")


class _RWLock:
    """Writer-preferring read-write lock (the reference uses a
    ReentrantReadWriteLock, reconnect.clj:93-146)."""

    def __init__(self):
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False
        self._writers_waiting = 0

    def acquire_read(self):
        with self._cond:
            while self._writer or self._writers_waiting:
                self._cond.wait()
            self._readers += 1

    def release_read(self):
        with self._cond:
            self._readers -= 1
            if self._readers == 0:
                self._cond.notify_all()

    def acquire_write(self):
        with self._cond:
            self._writers_waiting += 1
            while self._writer or self._readers:
                self._cond.wait()
            self._writers_waiting -= 1
            self._writer = True

    def release_write(self):
        with self._cond:
            self._writer = False
            self._cond.notify_all()


class Wrapper:
    """(reconnect.clj:16-32). open() -> conn; close(conn); log (name)."""

    def __init__(self, open: Callable[[], Any],
                 close: Callable[[Any], None] = lambda c: None,
                 name: str = "conn"):
        self._open = open
        self._close = close
        self.name = name
        self._lock = _RWLock()
        self._conn: Any = None
        self._opened = False

    def open(self) -> "Wrapper":
        self._lock.acquire_write()
        try:
            if not self._opened:
                self._conn = self._open()
                self._opened = True
        finally:
            self._lock.release_write()
        return self

    def conn(self) -> Any:
        return self._conn

    def reopen(self) -> None:
        """Closes (best-effort) and reopens (reconnect.clj reopen!)."""
        self._lock.acquire_write()
        try:
            if self._opened:
                try:
                    self._close(self._conn)
                except Exception:  # noqa: BLE001
                    logger.debug("error closing %s", self.name, exc_info=True)
            self._conn = self._open()
            self._opened = True
        finally:
            self._lock.release_write()

    def close(self) -> None:
        self._lock.acquire_write()
        try:
            if self._opened:
                try:
                    self._close(self._conn)
                finally:
                    self._conn = None
                    self._opened = False
        finally:
            self._lock.release_write()

    def with_conn(self, fn: Callable[[Any], Any]) -> Any:
        """Runs fn(conn) under the read lock; on ANY exception, reopens
        the conn before rethrowing (reconnect.clj:93-146)."""
        self._lock.acquire_read()
        try:
            return fn(self._conn)
        except Exception:
            self._lock.release_read()
            try:
                self.reopen()
            except Exception:  # noqa: BLE001
                logger.warning("reopen of %s failed", self.name, exc_info=True)
            self._lock.acquire_read()  # re-acquire so finally releases once
            raise
        finally:
            self._lock.release_read()


def wrapper(open: Callable[[], Any], close: Callable[[Any], None] = lambda c: None,
            name: str = "conn") -> Wrapper:
    return Wrapper(open, close, name)
