"""Value <-> bytes codec for histories and wire payloads (reference:
jepsen/src/jepsen/codec.clj — EDN there, canonical JSON here)."""
from __future__ import annotations

import json
from typing import Any


def encode(value: Any) -> bytes:
    """(codec.clj:9-18)"""
    if value is None:
        return b""
    return json.dumps(value, sort_keys=True, separators=(",", ":")).encode()


def decode(data: bytes | None) -> Any:
    """(codec.clj:20-28)"""
    if data is None or len(data) == 0:
        return None
    return json.loads(data.decode())
