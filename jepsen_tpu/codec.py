"""Value <-> bytes codec for wire payloads and the history IR's value
intern table (reference: jepsen/src/jepsen/codec.clj — EDN there,
canonical JSON here).

History *value* encoding is owned by the IR intern table
(:class:`jepsen_tpu.history_ir.ir.DeviceHistory` interns every op value
to a dense int32 id); this codec serializes that table — one canonical
JSON row per interned value — into the ``history.npz`` sidecar
(``val_table``; see :func:`jepsen_tpu.history_ir.sidecar
.intern_to_rows` / ``intern_from_rows``, round-trip pinned in
tests/test_history_ir.py). Wire payloads (suites/_wire.py et al.) use
:func:`encode`/:func:`decode` directly, as before."""
from __future__ import annotations

import json
from typing import Any


def encode(value: Any) -> bytes:
    """(codec.clj:9-18)"""
    if value is None:
        return b""
    return json.dumps(value, sort_keys=True, separators=(",", ":")).encode()


def decode(data: bytes | None) -> Any:
    """(codec.clj:20-28)"""
    if data is None or len(data) == 0:
        return None
    return json.loads(data.decode())
