"""CPU linearizability oracles.

Two independent algorithms, mirroring the reference's knossos surface
(jepsen/src/jepsen/checker.clj:185-216 dispatches :linear -> just-in-time
linearization, :wgl -> Wing & Gong + Lowe bitset/memoization):

* :func:`wgl` — Wing-Gong-Lowe DFS over an entry list with (bitset, model)
  memoization, operating on op dicts + object Models. The ground-truth
  oracle.
* :func:`check_stream` — breadth-first just-in-time linearization over the
  int-encoded :class:`~jepsen_tpu.checker.linear_encode.EventStream`. Shares
  its encoding with the TPU kernel (jepsen_tpu.ops.jitlin), so it's the
  bit-exact CPU twin used for differential testing of the device path.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

from jepsen_tpu.checker.linear_encode import EV_INVOKE, EV_NOOP, EV_RETURN, EventStream
from jepsen_tpu.models import CAS_F_CAS, CAS_F_READ, CAS_F_WRITE, Model, is_inconsistent


def cas_register_step_py(state: int, f: int, a: int, b: int) -> tuple[int, bool]:
    """Pure-python twin of models.cas_register_spec().step_ids."""
    if f == CAS_F_READ:
        return state, (a == 0 or a == state)
    if f == CAS_F_WRITE:
        return a, True
    if f == CAS_F_CAS:
        if state == a:
            return b, True
        return state, False
    return state, False


def multi_register_step_py(n_keys: int, n_values: int):
    """Pure-python twin of models.multi_register_spec().step_ids (same
    base-digit state/txn encodings; see that spec for the layout)."""
    V, K = n_values, n_keys
    SB, AB = V + 1, 2 * V + 2

    def step(state: int, f: int, a: int, b: int) -> tuple[int, bool]:
        acts = a
        for k in range(K):
            act = acts % AB
            acts //= AB
            digit = (state // (SB ** k)) % SB
            if 2 <= act < 2 + V:          # read value act-2
                if digit != act - 1:
                    return state, False
            elif act >= 2 + V:            # write value act-(2+V)
                state += (act - (1 + V) - digit) * (SB ** k)
        return state, True

    return step


# ---------------------------------------------------------------------------
# Just-in-time linearization over an EventStream (the TPU kernel's CPU twin)
# ---------------------------------------------------------------------------

@dataclass
class LinearResult:
    valid: Any                 # True | False | "unknown"
    failed_event: int = -1     # event index where the frontier died
    failed_op_index: int = -1  # history index of that event's op
    configs_max: int = 0       # peak frontier size (for K sizing on TPU)
    algorithm: str = ""
    # on failure: the surviving configurations just before the fatal
    # return killed them (knossos's :configs surface, checker.clj:205-212),
    # truncated to 10 like the reference ("Writing these can take hours").
    # Each is {"state": model-state value-or-id, "linearized": [history
    # op-index...], "pending": [history op-index...]}.
    final_configs: list | None = None


class FrontierSession:
    """Resumable just-in-time linearization: the step loop of the CPU
    twin, factored so a live checker can absorb events in chunks and
    carry the frontier between polls (doc/observability.md "Live
    checking"). ``check_stream`` is now a one-shot absorb over this
    session, so the batch and streaming paths cannot diverge.

    State between absorbs: the surviving configurations (linearized-
    pending bitmask, model state), the open ops per slot, and the
    pending mask. Once the frontier dies the session latches its
    failure LinearResult; further absorbs are no-ops."""

    def __init__(
        self,
        step: Callable[[int, int, int, int],
                       tuple[int, bool]] = cas_register_step_py,
        init_state: int = 0,
        algorithm: str = "jitlin-cpu",
    ):
        self.step = step
        self.algorithm = algorithm
        self.configs: set[tuple[int, int]] = {(0, init_state)}
        self.cur: dict[int, tuple[int, int, int]] = {}
        self.cur_idx: dict[int, int] = {}  # slot -> history index of open op
        self.pending_mask = 0
        self.configs_max = 1
        # near-miss margin for coverage_probe(): the SMALLEST surviving
        # frontier seen after any return — 1 means a single legal
        # linearization kept the history alive (it "almost failed");
        # None until a return has been absorbed
        self.configs_min: int | None = None
        self.events_absorbed = 0
        self.failure: LinearResult | None = None

    def absorb(self, stream, start: int = 0,
               end: int | None = None) -> LinearResult:
        """Consumes events ``[start, end)`` of ``stream`` (any object
        with kind/slot/f/a/b/op_index sequences + an intern table) and
        returns the verdict so far. Event indices are absolute, so a
        failure reports the same ``failed_event`` a one-shot check
        would."""
        if self.failure is not None:
            return self.failure
        if end is None:
            end = len(stream.kind)
        # native frontier (doc/performance.md "Host ingest spine"):
        # the C twin runs the same BFS closure on COPIES and only
        # commits on a fully-alive chunk; a death (or any regime miss)
        # replays the untouched Python state below so the failure
        # forensics are bit-identical to the pure path
        from jepsen_tpu.history_ir import ingest
        if ingest.frontier_absorb(self, stream, start, end):
            # the C twin doesn't track the per-return minimum; fold in
            # the post-chunk frontier so the near-miss margin stays
            # meaningful (coarser granularity, same direction)
            if self.failure is None and self.configs:
                n = len(self.configs)
                if self.configs_min is None or n < self.configs_min:
                    self.configs_min = n
            return self.result()
        step = self.step
        configs = self.configs
        cur = self.cur
        cur_idx = self.cur_idx
        pending_mask = self.pending_mask
        configs_max = self.configs_max
        configs_min = self.configs_min
        kinds, slots = stream.kind, stream.slot
        fcol, acol, bcol, idxcol = stream.f, stream.a, stream.b, \
            stream.op_index
        for e in range(start, end):
            kind = kinds[e]
            if kind == EV_NOOP:
                continue
            s = int(slots[e])
            bit = 1 << s
            if kind == EV_INVOKE:
                cur[s] = (int(fcol[e]), int(acol[e]), int(bcol[e]))
                cur_idx[s] = int(idxcol[e])
                pending_mask |= bit
                continue
            # EV_RETURN: closure, then require this op linearized
            all_seen = set(configs)
            frontier = configs
            while frontier:
                new = set()
                for mask, state in frontier:
                    avail = pending_mask & ~mask
                    m = avail
                    while m:
                        low = m & (-m)
                        m ^= low
                        sl = low.bit_length() - 1
                        f, a, b2 = cur[sl]
                        st2, ok = step(state, f, a, b2)
                        if ok:
                            c2 = (mask | low, st2)
                            if c2 not in all_seen:
                                all_seen.add(c2)
                                new.add(c2)
                frontier = new
            configs_max = max(configs_max, len(all_seen))
            configs = {(mask & ~bit, state)
                       for (mask, state) in all_seen if mask & bit}
            pending_mask &= ~bit
            if configs and (configs_min is None
                            or len(configs) < configs_min):
                configs_min = len(configs)
            if not configs:
                def op_indices(mask):
                    return [cur_idx[t] for t in cur_idx if mask & (1 << t)]

                def state_val(st):
                    try:
                        return stream.intern.value(st)
                    except (IndexError, AttributeError):
                        return st

                # the fatal op WAS pending when these configs died — its
                # bit was cleared from pending_mask just above; restore it
                self.configs_min = configs_min
                fatal_pending = pending_mask | bit
                finals = [{"state": state_val(state),
                           "linearized": sorted(op_indices(mask)),
                           "pending": sorted(
                               op_indices(fatal_pending & ~mask))}
                          for mask, state in sorted(all_seen)[:10]]
                self.configs_max = configs_max
                self.events_absorbed = e + 1
                self.failure = LinearResult(
                    valid=False, failed_event=e,
                    failed_op_index=int(stream.op_index[e]),
                    configs_max=configs_max, algorithm=self.algorithm,
                    final_configs=finals,
                )
                return self.failure
        self.configs = configs
        self.pending_mask = pending_mask
        self.configs_max = configs_max
        self.configs_min = configs_min
        self.events_absorbed = end
        return self.result()

    def coverage_probe(self) -> dict:
        """Checker-state coverage for the schedule fuzzer
        (doc/robustness.md "Schedule fuzzing"): a tiny structural
        summary of where this history drove the frontier —
        ``edges`` are log2 cardinality buckets of the peak frontier
        (new buckets mean the schedule exercised a concurrency regime
        no corpus entry reached before), ``margin`` is the near-miss
        metric (smallest surviving frontier; 1 = one legal
        linearization away from a verdict flip; None = no returns
        absorbed), and ``died`` latches an actual failure."""
        edges = ["frontier:peak:b%d" % self.configs_max.bit_length()]
        if self.configs_min is not None:
            edges.append("frontier:min:b%d"
                         % self.configs_min.bit_length())
        return {"edges": edges, "margin": self.configs_min,
                "died": self.failure is not None}

    def result(self) -> LinearResult:
        """The verdict over everything absorbed so far: valid-so-far, or
        the latched failure."""
        if self.failure is not None:
            return self.failure
        return LinearResult(valid=True, configs_max=self.configs_max,
                            algorithm=self.algorithm)

    # -- durable snapshots (doc/robustness.md "Resumable checks") -------

    def snapshot(self) -> dict | None:
        """The session's resumable state as a JSON-serializable dict, or
        None when it can't be serialized faithfully (exotic open-op
        values). The snapshot is exact: restoring it and absorbing the
        remaining events is bit-identical to one uninterrupted absorb —
        the configuration set IS the algorithm's whole state."""
        try:
            snap = {
                "configs": sorted([int(m), int(s)] for m, s in self.configs),
                "cur": {str(k): [int(x) for x in v]
                        for k, v in self.cur.items()},
                "cur_idx": {str(k): int(v) for k, v in self.cur_idx.items()},
                "pending_mask": int(self.pending_mask),
                "configs_max": int(self.configs_max),
                "configs_min": (None if self.configs_min is None
                                else int(self.configs_min)),
                "events_absorbed": int(self.events_absorbed),
            }
            if self.failure is not None:
                f = self.failure
                snap["failure"] = {
                    "failed_event": int(f.failed_event),
                    "failed_op_index": int(f.failed_op_index),
                    "configs_max": int(f.configs_max),
                    "algorithm": f.algorithm,
                }
            return snap
        except (TypeError, ValueError):
            return None

    @classmethod
    def restore(cls, snap: dict, step=cas_register_step_py,
                init_state: int = 0, algorithm: str = "jitlin-cpu"):
        """A session rebuilt from :meth:`snapshot`'s product, or None on
        a malformed snapshot (the caller restarts from zero — a bad
        snapshot can delay a verdict, never change one)."""
        try:
            fs = cls(step=step, init_state=init_state, algorithm=algorithm)
            fs.configs = {(int(m), int(s)) for m, s in snap["configs"]}
            fs.cur = {int(k): tuple(int(x) for x in v)
                      for k, v in (snap.get("cur") or {}).items()}
            fs.cur_idx = {int(k): int(v)
                          for k, v in (snap.get("cur_idx") or {}).items()}
            fs.pending_mask = int(snap["pending_mask"])
            fs.configs_max = int(snap.get("configs_max", 1))
            cmin = snap.get("configs_min")
            fs.configs_min = None if cmin is None else int(cmin)
            fs.events_absorbed = int(snap["events_absorbed"])
            fail = snap.get("failure")
            if fail is not None:
                fs.failure = LinearResult(
                    valid=False,
                    failed_event=int(fail["failed_event"]),
                    failed_op_index=int(fail["failed_op_index"]),
                    configs_max=int(fail.get("configs_max", 0)),
                    algorithm=fail.get("algorithm") or algorithm,
                )
            return fs
        except (KeyError, TypeError, ValueError):
            return None


def check_stream(
    stream: EventStream,
    step: Callable[[int, int, int, int], tuple[int, bool]] = cas_register_step_py,
    init_state: int = 0,
) -> LinearResult:
    """Breadth-first JIT linearization: configs are (linearized-pending
    bitmask, state) pairs; closure is computed lazily before each return
    event (Lowe's 'just-in-time linearization'). One-shot absorb over a
    :class:`FrontierSession`."""
    return FrontierSession(step=step, init_state=init_state).absorb(stream)


# ---------------------------------------------------------------------------
# Wing-Gong-Lowe DFS over op dicts + object models (ground-truth oracle)
# ---------------------------------------------------------------------------

class _Node:
    __slots__ = ("kind", "op_id", "op", "match", "prev", "next")

    def __init__(self, kind, op_id, op):
        self.kind = kind      # 0 invoke, 1 return
        self.op_id = op_id
        self.op = op
        self.match = None
        self.prev = None
        self.next = None


def _unlink(n: _Node):
    n.prev.next = n.next
    n.next.prev = n.prev


def _relink(n: _Node):
    n.prev.next = n
    n.next.prev = n


def _preprocess(history: list[dict]):
    """Completes invocation values from returns, drops fail pairs and
    crashed reads. Returns [(inv_op, completed?)] per live op in invocation
    order plus their return positions (None = crashed)."""
    open_inv: dict = {}
    drop = set()
    completed_value: dict[int, Any] = {}
    returns: dict[int, int] = {}
    for i, op in enumerate(history):
        p, typ = op.get("process"), op.get("type")
        if not isinstance(p, int) or p < 0:
            drop.add(i)
            continue
        if typ == "invoke":
            open_inv[p] = i
        elif typ == "fail":
            j = open_inv.pop(p, None)
            if j is not None:
                drop.add(j)
            drop.add(i)
        elif typ == "ok":
            j = open_inv.pop(p, None)
            if j is not None:
                returns[j] = i
                if op.get("value") is not None:
                    completed_value[j] = op.get("value")
        elif typ == "info":
            j = open_inv.pop(p, None)
            drop.add(i)
            if j is not None and history[j].get("f") == "read":
                drop.add(j)
    for p, j in open_inv.items():
        if history[j].get("f") == "read":
            drop.add(j)
    live = []
    for i, op in enumerate(history):
        if i in drop or op.get("type") != "invoke":
            continue
        o = dict(op)
        if i in completed_value:
            o["value"] = completed_value[i]
        live.append((i, o, returns.get(i)))
    return live


def wgl(history: list[dict], model: Model, max_steps: int = 50_000_000) -> LinearResult:
    """Wing & Gong DFS with Lowe's (linearized-bitset, state) memoization
    (knossos.wgl equivalent). Crashed mutations may linearize at any later
    point or never."""
    live = _preprocess(history)
    n = len(live)
    if n == 0:
        return LinearResult(valid=True, algorithm="wgl-cpu")

    head = _Node(-1, -1, None)
    tail = _Node(-2, -1, None)
    head.next = tail
    tail.prev = head

    def insert_before(node, ref):
        node.prev = ref.prev
        node.next = ref
        ref.prev.next = node
        ref.prev = node

    # interleave invoke/return nodes in history order; crashed returns at end
    events: list[tuple[int, _Node]] = []
    ok_ops = set()
    for op_id, (hist_i, op, ret_i) in enumerate(live):
        inv = _Node(0, op_id, op)
        events.append((hist_i, inv))
        if ret_i is not None:
            ret = _Node(1, op_id, op)
            inv.match = ret
            ret.match = inv
            events.append((ret_i, ret))
            ok_ops.add(op_id)
    events.sort(key=lambda t: t[0])
    for _, node in events:
        insert_before(node, tail)

    ok_remaining = len(ok_ops)
    linearized_mask = 0
    seen: set[tuple[int, Model]] = set()
    stack: list[tuple[_Node, Model]] = []
    entry = head.next
    steps = 0
    max_lin = 0
    while True:
        steps += 1
        if steps > max_steps:
            return LinearResult(valid="unknown", algorithm="wgl-cpu",
                                configs_max=len(seen))
        if ok_remaining == 0:
            return LinearResult(valid=True, algorithm="wgl-cpu",
                                configs_max=len(seen))
        if entry.kind == 0:  # invoke: candidate for linearization
            m2 = entry.op and model.step(entry.op)
            if not is_inconsistent(m2):
                new_mask = linearized_mask | (1 << entry.op_id)
                key = (new_mask, m2)
                if key not in seen:
                    seen.add(key)
                    stack.append((entry, model))
                    _unlink(entry)
                    if entry.match is not None:
                        _unlink(entry.match)
                        ok_remaining -= 1
                    model = m2
                    linearized_mask = new_mask
                    max_lin = max(max_lin, bin(new_mask).count("1"))
                    entry = head.next
                    continue
            entry = entry.next
        else:
            # return entry of an unlinearized op (kind 1) or tail (kind -2):
            # no way forward; backtrack
            if not stack:
                # report how far we got: first un-linearizable return
                fail_op = entry.op_id if entry.kind == 1 else -1
                hist_i = live[fail_op][0] if fail_op >= 0 else -1
                return LinearResult(valid=False, failed_op_index=hist_i,
                                    algorithm="wgl-cpu", configs_max=len(seen))
            inv, model = stack.pop()
            linearized_mask &= ~(1 << inv.op_id)
            if inv.match is not None:
                _relink(inv.match)
                ok_remaining += 1
            _relink(inv)
            entry = inv.next
