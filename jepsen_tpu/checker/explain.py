"""Anomaly forensics: localization, minimal witnesses, and `explain`.

Jepsen's value is the *evidence* a run persists, not just its verdict
(README's store/ surface; Elle's whole contribution is a human-readable
proof of an anomaly). The fast checker paths outgrew that: the matrix,
segmented, and sharded kernels verify hundreds of millions of events and
answer only ``valid: false``. This module turns that bare INVALID into
something a human can act on:

* **Localization** — the exact first anomaly. In the transfer-matrix
  regime, :func:`jepsen_tpu.ops.jitlin.matrix_localize` bisects the
  composable per-chunk operator products ON DEVICE (log-depth prefix
  combines + one [MV]-vector chunk re-scan) and lands on the same event
  the exact CPU frontier would reject — pinned bit-identical by
  tests/test_explain.py across single-device, segmented, sharded-mesh,
  and live-screen backends. Out of regime, the CPU frontier's own
  rejection (``LinearResult.failed_event``) serves.

* **Witness shrink** — a minimal window that still reproduces the
  failure. A bounded ddmin pass removes candidate op subsets from the
  guilty window and re-checks every candidate of a round in ONE vmapped
  device dispatch (``jitlin.matrix_window_rescan``), accepting only
  candidates that die at the *same* return (a window that fails
  elsewhere explains a different anomaly). Ops already pending at the
  window's entry ride the carried frontier vector and are reported as
  context. Knobs: ``explain_shrink_budget`` (total candidate checks),
  ``explain_max_witness_ops`` (stop shrinking below this), both
  tolerantly coerced (KNB house style; preflight is where garbage
  errors).

* **Artifacts** — a per-run ``anomaly.json`` (first anomaly op, witness
  op indices + per-op process/timing detail, overlapping fault windows
  from the durable ``faults.jsonl`` registry) and a rendered
  ``witness-timeline.html`` (checker/timeline.render_witness) with the
  nemesis/fault overlay. The web run page links both ("Explain" panel);
  ``jepsen-tpu explain <run-dir>`` re-derives them offline.

Telemetry: ``explain_bisect_steps``, ``explain_latency_seconds``,
``witness_ops`` flow through the installed registry.

See doc/observability.md "Anomaly forensics".
"""
from __future__ import annotations

import json
import logging
import time
from pathlib import Path

import numpy as np

from jepsen_tpu import telemetry

logger = logging.getLogger("jepsen.checker.explain")

ANOMALY_NAME = "anomaly.json"
WITNESS_TIMELINE_NAME = "witness-timeline.html"

DEFAULT_SHRINK_BUDGET = 128     # total ddmin candidate evaluations
DEFAULT_MAX_WITNESS_OPS = 16    # stop shrinking at this many ops

# anomaly.json caps detail lists so a pathological witness can't bloat
# the artifact past what a human (or the web panel) would read
MAX_DETAIL_OPS = 200


def enabled(test=None, opts=None) -> bool:
    """The ``explain`` knob (default ON): tolerantly coerced — bools and
    0/1 pass, yes/no strings work, garbage warns and reads as the
    default. Preflight (KNB001) is where strictness lives."""
    from jepsen_tpu import parallel
    v = None
    if isinstance(opts, dict) and "explain" in opts:
        v = opts.get("explain")
    elif isinstance(test, dict):
        v = test.get("explain")
    flag = parallel.coerce_flag(v, knob="explain")
    return True if flag is None else flag


def _coerce_count(value, knob: str, default: int, lo: int = 0) -> int:
    """Tolerant positive-int knob coercion, matching the interpreter's
    knob-layer discipline: numeric strings parse, garbage warns and
    falls back to the default, values below ``lo`` clamp."""
    if value is None or value == "":
        return default
    try:
        if isinstance(value, bool):
            raise ValueError("bool is not a count")
        n = int(float(value))
    except (TypeError, ValueError):
        logger.warning("ignoring malformed %s=%r (want an int); using "
                       "default %r", knob, value, default)
        return default
    return max(lo, n)


def shrink_budget(test=None) -> int:
    return _coerce_count((test or {}).get("explain_shrink_budget"),
                         "explain_shrink_budget", DEFAULT_SHRINK_BUDGET)


def max_witness_ops(test=None) -> int:
    return _coerce_count((test or {}).get("explain_max_witness_ops"),
                         "explain_max_witness_ops",
                         DEFAULT_MAX_WITNESS_OPS, lo=1)


def ddmin(items: list, fails, budget: int = DEFAULT_SHRINK_BUDGET,
          min_items: int = 0) -> tuple[list, dict]:
    """Generic bounded delta-debugging minimization — the exact round
    structure of the device witness shrink in
    :func:`_forensics_from_loc`, lifted over a plain predicate so other
    reproducers (the schedule fuzzer's failing-trial minimization,
    doc/robustness.md "Schedule fuzzing") shrink through the same
    machinery. ``fails(subset)`` returns True when the failure still
    reproduces with only ``subset`` kept; the caller has already
    established ``fails(items)``. Returns ``(kept, info)`` with
    ``info["minimal"]`` a PROOF, not a progress report: True only when
    a full single-item-granularity round removed nothing (or nothing
    removable remains) — a loop cut short by the evaluation budget
    shrank the input but proved nothing about irreducibility."""
    kept = list(items)
    rounds = candidates_used = 0
    n = 2
    converged = not kept
    while kept and len(kept) > min_items and n <= len(kept) \
            and budget > 0:
        chunk = (len(kept) + n - 1) // n
        segs = [kept[i:i + chunk] for i in range(0, len(kept), chunk)]
        cands = [[x for j, seg in enumerate(segs) if j != i for x in seg]
                 for i in range(len(segs))]
        truncated = len(cands) > budget
        cands = cands[:budget]
        rounds += 1
        hit = None
        for i, cand in enumerate(cands):
            budget -= 1
            candidates_used += 1
            if fails(cand):
                hit = i
                break
        if hit is not None:
            kept = cands[hit]
            n = max(2, min(n - 1, max(1, len(kept))))
            if not kept:
                converged = True
                break
        else:
            if n >= len(kept):
                converged = not truncated and budget >= 0
                break
            n = min(len(kept), 2 * n)
    return kept, {"rounds": rounds, "candidates": candidates_used,
                  "minimal": converged}


# ---------------------------------------------------------------------------
# Core: forensics over an encoded stream
# ---------------------------------------------------------------------------

def explain_stream(stream, step_ids=None, step_py=None, init_state: int = 0,
                   num_states: int | None = None, loc=None, failure=None,
                   shrink_budget: int | None = None,
                   max_witness_ops: int | None = None) -> dict | None:
    """Forensics for one encoded history: localize the first anomaly and
    shrink a minimal witness window. ``loc`` reuses a
    :class:`~jepsen_tpu.ops.jitlin.MatrixLocalization` a checker rung
    already computed; ``failure`` reuses an exact CPU
    :class:`~jepsen_tpu.checker.linear_cpu.LinearResult` (no re-check).
    Returns the forensics dict (anomaly.json's core), or None when the
    stream is valid."""
    t0 = time.perf_counter()
    budget = _coerce_count(shrink_budget, "explain_shrink_budget",
                           DEFAULT_SHRINK_BUDGET)
    max_ops = _coerce_count(max_witness_ops, "explain_max_witness_ops",
                            DEFAULT_MAX_WITNESS_OPS, lo=1)
    from jepsen_tpu.ops import jitlin
    if loc is None:
        n_states = (num_states if num_states is not None
                    else len(stream.intern))
        n_returns = int((np.asarray(stream.kind) == jitlin.EV_RETURN).sum())
        if jitlin.matrix_ok(max(1, getattr(stream, "n_slots", 1)),
                            n_states, n_returns):
            try:
                loc = jitlin.matrix_localize(stream, step_ids=step_ids,
                                             init_state=init_state,
                                             num_states=num_states)
            except Exception:  # noqa: BLE001 — forensics must not raise
                logger.exception("matrix localization failed; the CPU "
                                 "frontier settles")
                loc = None
    if loc is not None:
        out = _forensics_from_loc(stream, loc, budget, max_ops)
    else:
        out = _forensics_cpu(stream, step_py, init_state, failure)
    if out is None:
        return None
    out["explain_latency_seconds"] = round(time.perf_counter() - t0, 4)
    _export_metrics(out)
    return out


def first_failure(stream, step_ids=None, step_py=None, init_state: int = 0,
                  num_states: int | None = None):
    """``(failed_event, failed_op_index)`` of a stream's first anomaly —
    device bisection when in regime, exact CPU frontier otherwise — or
    None when the stream is valid. The cheap localization-only surface
    (distributed key batches use it; see
    parallel.distributed.localize_keys_distributed)."""
    from jepsen_tpu.ops import jitlin
    n_states = num_states if num_states is not None else len(stream.intern)
    n_returns = int((np.asarray(stream.kind) == jitlin.EV_RETURN).sum())
    if jitlin.matrix_ok(max(1, getattr(stream, "n_slots", 1)), n_states,
                        n_returns):
        try:
            loc = jitlin.matrix_localize(stream, step_ids=step_ids,
                                         init_state=init_state,
                                         num_states=num_states)
            if loc is not None:
                return loc.failed_event, loc.failed_op_index
        except Exception:  # noqa: BLE001
            logger.exception("matrix localization failed; CPU settles")
    from jepsen_tpu.checker.linear_cpu import (
        cas_register_step_py, check_stream)
    res = check_stream(stream, step=step_py or cas_register_step_py,
                       init_state=init_state)
    if res.valid is not False:
        return None
    return int(res.failed_event), int(res.failed_op_index)


def _forensics_cpu(stream, step_py, init_state, failure=None) -> dict | None:
    """CPU-frontier forensics (out of matrix regime, or localization
    declined): the exact rejection point plus a frontier-derived witness
    (the ops pending when the frontier died — no ddmin; the device
    window machinery is the matrix regime's)."""
    res = failure
    if res is None or res.valid is not False:
        from jepsen_tpu.checker.linear_cpu import (
            cas_register_step_py, check_stream)
        res = check_stream(stream, step=step_py or cas_register_step_py,
                           init_state=init_state)
    if res.valid is not False:
        return None
    pend = sorted({int(i) for c in (res.final_configs or [])
                   for i in (c.get("pending") or [])})
    fatal = int(res.failed_op_index)
    return {
        "first_anomaly": {"event": int(res.failed_event),
                          "op_index": fatal},
        "backend": "frontier-cpu",
        "bisect_steps": 0,
        "witness": {
            "op_indices": sorted(set(pend + [fatal])),
            "context_op_indices": [],
            "window_op_count": len(pend) + 1,
            "shrunk_from": None,
            "rounds": 0,
            "candidates": 0,
            "minimal": False,
        },
    }


def _forensics_from_loc(stream, loc, budget: int, max_ops: int) -> dict:
    """Witness shrink over a settled device localization: bounded ddmin
    on the guilty window's removable ops, every round's candidates
    evaluated in one vmapped ``matrix_window_rescan`` dispatch. A
    candidate counts only when it dies at the SAME return the full
    history died at — a window that fails elsewhere explains a
    different anomaly."""
    from jepsen_tpu.checker.linear_encode import EV_INVOKE
    from jepsen_tpu.ops.jitlin import _bucket, matrix_window_rescan

    kind = np.asarray(stream.kind)
    slot = np.asarray(stream.slot)
    op_index = np.asarray(stream.op_index)
    T, t_star = loc.chunk_returns, loc.step
    ret_idx = loc.ret_idx
    base_r = loc.chunk * T

    # occupant lookup: which op (identified by its invoke EVENT) holds
    # slot s at event e — the last invoke on s at or before e
    inv_pos: dict[int, np.ndarray] = {}
    for s in np.unique(slot[kind == EV_INVOKE]):
        inv_pos[int(s)] = np.nonzero((kind == EV_INVOKE) & (slot == s))[0]

    def occupant(s: int, e: int) -> int:
        pos = inv_pos.get(int(s))
        if pos is None or len(pos) == 0:
            return -1
        j = int(np.searchsorted(pos, e, side="right")) - 1
        return int(pos[j]) if j >= 0 else -1

    window_events = [int(ret_idx[base_r + r]) for r in range(t_star + 1)]
    boundary_event = int(ret_idx[base_r - 1]) if base_r > 0 else -1
    S = loc.window_pend.shape[1]
    occ_grid = np.full((t_star + 1, S), -1, np.int64)
    ret_op = np.full((t_star + 1,), -1, np.int64)
    for r, e in enumerate(window_events):
        ret_op[r] = occupant(int(slot[e]), e)
        for s in np.nonzero(loc.window_pend[r])[0]:
            occ_grid[r, int(s)] = occupant(int(s), e)
    fatal_event = window_events[-1]
    fatal_op = int(ret_op[t_star])
    ops_in_window = sorted(
        {int(o) for o in occ_grid[occ_grid >= 0].ravel()}
        | {int(o) for o in ret_op[ret_op >= 0]})
    # ops invoked before the window boundary are context: their bits
    # already live in the carried frontier vector and cannot be removed
    context = [o for o in ops_in_window if o <= boundary_event]
    removable = [o for o in ops_in_window
                 if o > boundary_event and o != fatal_op]

    base_pend = np.asarray(loc.window_pend).copy()
    base_valid = np.asarray(loc.window_valid).copy()
    base_valid[t_star + 1:] = False  # past the fatal return: irrelevant

    def grids_for(keeps: list[list[int]]):
        K = len(keeps)
        pend = np.broadcast_to(base_pend, (K,) + base_pend.shape).copy()
        valid = np.broadcast_to(base_valid, (K,) + base_valid.shape).copy()
        for k, ks in enumerate(keeps):
            kept_ops = np.asarray(
                sorted(set(ks) | set(context) | {fatal_op}), np.int64)
            keep_grid = (occ_grid < 0) | np.isin(occ_grid, kept_ops)
            pend[k, :t_star + 1] &= keep_grid
            valid[k, :t_star + 1] &= np.isin(ret_op, kept_ops)
        return pend, valid

    def dies_at_fatal(cands: list[list[int]]) -> list[bool]:
        K = len(cands)
        Kb = _bucket(K, floor=4)
        padded = cands + [list(kept)] * (Kb - K)  # keep-all pads, ignored
        pend, valid = grids_for(padded)
        first = matrix_window_rescan(loc, pend, valid)
        return [int(first[i]) == t_star for i in range(K)]

    kept = list(removable)
    rounds = candidates_used = 0
    n = 2
    # "minimal" is a PROOF, not a progress report: True only when ddmin
    # converged — no single op can be removed (a full single-op-granularity
    # round found no candidate), or nothing removable remains. A loop cut
    # short by the candidate budget or the max_ops early-stop shrank the
    # witness but proved nothing about irreducibility.
    converged = not removable
    while kept and len(kept) > max_ops and n <= len(kept) and budget > 0:
        segs = np.array_split(np.asarray(kept, np.int64), n)
        cands = []
        for i in range(len(segs)):
            rest = [int(x) for j, seg in enumerate(segs) if j != i
                    for x in seg]
            cands.append(rest)
        truncated = len(cands) > budget
        cands = cands[:budget]
        budget -= len(cands)
        candidates_used += len(cands)
        rounds += 1
        ok = dies_at_fatal(cands)
        hit = next((i for i, o in enumerate(ok) if o), None)
        if hit is not None:
            kept = cands[hit]
            n = max(2, min(n - 1, max(1, len(kept))))
            if not kept:
                converged = True
                break
        else:
            if n >= len(kept):
                converged = not truncated
                break
            n = min(len(kept), 2 * n)

    witness_events = sorted(set(kept) | {fatal_op})
    out = {
        "first_anomaly": {"event": fatal_event,
                          "op_index": int(op_index[fatal_event])},
        "backend": "matrix-bisect",
        "bisect_steps": int(loc.bisect_steps),
        "witness": {
            "op_indices": sorted({int(op_index[e]) for e in witness_events}),
            "context_op_indices": sorted({int(op_index[e])
                                          for e in context}),
            "window_op_count": len(ops_in_window),
            "shrunk_from": len(removable),
            "rounds": rounds,
            "candidates": candidates_used,
            "minimal": converged,
        },
    }
    if fatal_event != loc.failed_event:  # pragma: no cover — invariant
        logger.warning("witness window disagrees with localization "
                       "(%d != %d); reporting the localization",
                       fatal_event, loc.failed_event)
        out["first_anomaly"] = {"event": int(loc.failed_event),
                                "op_index": int(loc.failed_op_index)}
    return out


def _export_metrics(forensics: dict) -> None:
    reg = telemetry.get_registry()
    if not reg.enabled:
        return
    try:
        backend = forensics.get("backend", "unknown")
        reg.counter("explain_total", "anomaly forensics derived, by "
                    "localization backend", labels=("backend",)
                    ).inc(backend=backend)
        reg.gauge("explain_bisect_steps",
                  "device combine steps of the last first-anomaly "
                  "bisection").set(forensics.get("bisect_steps", 0))
        reg.histogram("explain_latency_seconds",
                      "wall time of localize + witness shrink"
                      ).observe(forensics.get("explain_latency_seconds",
                                              0.0))
        reg.gauge("witness_ops", "ops in the last minimal witness").set(
            len((forensics.get("witness") or {}).get("op_indices") or ()))
    except Exception:  # noqa: BLE001 — telemetry never fails forensics
        logger.exception("explain telemetry recording failed")


# ---------------------------------------------------------------------------
# Artifacts: anomaly.json + witness timeline
# ---------------------------------------------------------------------------

def compose_anomaly(history, forensics: dict, registry_rows=None) -> dict:
    """The full anomaly.json payload: forensics enriched with per-op
    detail (process, f, value, invoke/completion times) and the fault
    windows from the durable registry that overlap the witness."""
    payload = {k: v for k, v in forensics.items()}
    hist = history or []
    completion_of: dict[int, dict] = {}
    invoke_of: dict[int, int] = {}   # completion index -> invoke index
    open_inv: dict = {}
    for i, op in enumerate(hist):
        p, typ = op.get("process"), op.get("type")
        if typ == "invoke":
            open_inv[p] = i
        elif typ in ("ok", "fail", "info"):
            j = open_inv.pop(p, None)
            if j is not None:
                completion_of[j] = op
                invoke_of[i] = j

    def op_detail(i: int) -> dict:
        """Per-op detail for either half of an op: witness indices are
        INVOKE indices, while first_anomaly's op_index is the fatal
        RETURN's (completion's) index — both resolve to the full
        invoke+completion pair."""
        if not (0 <= i < len(hist)):
            return {"index": int(i)}
        op = hist[i]
        inv, comp = op, completion_of.get(i)
        if comp is None and i in invoke_of:
            inv, comp = hist[invoke_of[i]], op
        d = {"index": int(i), "process": op.get("process"),
             "f": op.get("f"), "value": op.get("value"),
             "type": op.get("type"), "time": inv.get("time")}
        if comp is not None:
            d["completion_type"] = comp.get("type")
            d["completion_value"] = comp.get("value")
            if comp.get("time") is not None and inv.get("time") is not None:
                d["latency_ns"] = comp["time"] - inv["time"]
        return d

    fa = dict(payload.get("first_anomaly") or {})
    fa.update(op_detail(fa.get("op_index", -1)))
    payload["first_anomaly"] = fa
    wit = dict(payload.get("witness") or {})
    indices = list(wit.get("op_indices") or [])
    wit["ops"] = [op_detail(i) for i in indices[:MAX_DETAIL_OPS]]
    if len(indices) > MAX_DETAIL_OPS:
        wit["ops_truncated"] = len(indices) - MAX_DETAIL_OPS
    payload["witness"] = wit

    try:
        from jepsen_tpu.nemesis import faults as faults_mod
        windows = faults_mod.history_windows(hist, registry_rows or [])
        times = [hist[i].get("time") for i in indices
                 if 0 <= i < len(hist) and hist[i].get("time") is not None]
        fa_t = fa.get("time")
        if fa_t is not None:
            times.append(fa_t)
        if times:
            lo, hi = min(times), max(times)
            for w in windows:
                w0, w1 = w.get("start_time"), w.get("end_time")
                if w0 is None:
                    w["overlaps_witness"] = False
                else:
                    w["overlaps_witness"] = (w1 is None or w1 >= lo) \
                        and w0 <= hi
        payload["fault_windows"] = windows
    except Exception:  # noqa: BLE001 — the overlay is best-effort
        logger.exception("fault-window overlay failed")
        payload.setdefault("fault_windows", [])
    return payload


def write_artifacts(test: dict, history, forensics: dict,
                    opts: dict | None = None) -> dict:
    """Writes ``anomaly.json`` + ``witness-timeline.html`` into the
    run's store dir (nested under ``subdirectory`` for independent's
    per-key lift). Returns {artifact-name: path}; empty on failure —
    artifact writing never masks a verdict."""
    out: dict = {}
    if not test:
        return out
    try:
        from jepsen_tpu import store
        from jepsen_tpu.nemesis import faults as faults_mod
        sub = (opts or {}).get("subdirectory")
        rows = faults_mod.load_rows(
            store.path(test, faults_mod.FAULTS_NAME))
        payload = compose_anomaly(history, forensics, registry_rows=rows)
        p = store.path_mk(test, *filter(None, [sub, ANOMALY_NAME]))
        p.write_text(json.dumps(payload, indent=2, default=repr) + "\n")
        out[ANOMALY_NAME] = p
        try:
            from jepsen_tpu.checker import timeline
            html = timeline.render_witness(test, history or [], payload)
            tp = store.path_mk(test,
                               *filter(None, [sub, WITNESS_TIMELINE_NAME]))
            tp.write_text(html)
            out[WITNESS_TIMELINE_NAME] = tp
        except Exception:  # noqa: BLE001 — json evidence beats no evidence
            logger.exception("witness timeline rendering failed")
    except Exception:  # noqa: BLE001
        logger.exception("anomaly artifact write failed")
    return out


# ---------------------------------------------------------------------------
# Offline: `jepsen-tpu explain <run-dir>`
# ---------------------------------------------------------------------------

def explain_run(run_dir, shrink_budget: int | None = None,
                max_witness_ops: int | None = None) -> dict | None:
    """Re-derives forensics for a STORED run: loads its history, runs
    localization + witness shrink, writes ``anomaly.json`` +
    ``witness-timeline.html`` into the run dir. Register workloads take
    the full matrix-bisect path; list-append histories re-run the Elle
    checker and get its cycle artifacts + witness timeline. Returns a
    summary dict ({"valid": True} when there is nothing to explain;
    {"unsupported": f} for workloads with no forensics; None when the
    run has no usable history)."""
    # resolve BEFORE splitting: a relative dir ("." from inside a run)
    # must still yield real <store>/<name>/<ts> components
    run_dir = Path(run_dir).resolve()
    name, ts = run_dir.parent.name, run_dir.name
    store_dir = str(run_dir.parent.parent)
    from jepsen_tpu import store
    try:
        history = store.load_history(name, ts, store_dir)
    except (FileNotFoundError, OSError):
        return None
    if not history:
        return None
    test = {"name": name, "start_time": ts, "store_dir": store_dir}
    first_f = store.first_client_f(history)
    if first_f == "txn":
        return _explain_elle_run(test, history)
    if first_f not in ("read", "write", "cas"):
        return {"unsupported": first_f}
    from jepsen_tpu.checker.linear_encode import encode_register_ops
    try:
        stream = encode_register_ops(history)
    except (TypeError, ValueError) as e:
        # key-lifted / exotic value shapes: outside the offline encoder
        logger.warning("history not encodable as a plain register run "
                       "(%r); explain unsupported", e)
        return {"unsupported": first_f}
    forensics = explain_stream(stream, shrink_budget=shrink_budget,
                               max_witness_ops=max_witness_ops)
    if forensics is None:
        return {"valid": True}
    arts = write_artifacts(test, history, forensics)
    return {
        "valid": False,
        "first_anomaly_op": forensics["first_anomaly"]["op_index"],
        "witness_ops": len(forensics["witness"]["op_indices"]),
        "backend": forensics["backend"],
        "artifacts": sorted(str(k) for k in arts),
    }


def _explain_elle_run(test: dict, history) -> dict:
    """Offline forensics for a transactional run: the matching Elle
    checker's result map feeds the same artifact surface (per-anomaly
    cycle explanations + witness timeline with fault overlay). The
    checker is sniffed from the mop shapes the same way the live
    daemon's session_for_ops does — append/r mops are list-append,
    r/w mops are rw-register; anything else has no offline forensics."""
    fs: set = set()
    for op in history:
        if op.get("f") != "txn" or op.get("type") != "invoke":
            continue
        fs |= {m[0] for m in (op.get("value") or ())
               if isinstance(m, (list, tuple)) and m}
        if fs - {"r"}:
            break  # one non-read mop settles the dialect
    from jepsen_tpu.elle import artifacts as elle_artifacts
    if fs and fs <= {"append", "r"}:
        from jepsen_tpu.elle import list_append as elle_checker
    elif fs and fs <= {"r", "w"}:
        from jepsen_tpu.elle import rw_register as elle_checker
    else:
        return {"unsupported": "txn"}
    result = elle_checker.check(history, accelerator="cpu")
    if result.get("valid?") is True:
        return {"valid": True}
    elle_artifacts.write_for_test(test, result, history=history)
    return {
        "valid": result.get("valid?"),
        "anomaly_types": result.get("anomaly-types") or [],
        "artifacts": ["elle/"],
    }
