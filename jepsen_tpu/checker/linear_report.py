"""Linearizability failure rendering.

On an invalid verdict the reference renders ``linear.svg`` via
knossos.linear.report (jepsen/src/jepsen/checker.clj:205-212): the ops
around the failure and the configurations the search was still holding
when the fatal return killed them. This is the matplotlib equivalent,
truncated to 10 configs / a 10-op window exactly like the reference
truncates ``:final-paths``/``:configs`` ("Writing these can take
*hours*", checker.clj:213-216).

The figure has two bands:

* a timeline of the ops overlapping the failing op — one lane per
  process, invoke→completion span bars, the fatal op in red;
* the surviving configurations just before death — one line each,
  ``state=... linearized={...} pending={...}`` referencing ops by their
  timeline labels.
"""
from __future__ import annotations

import logging

logger = logging.getLogger("jepsen.checker.linear")

WINDOW = 10         # ops drawn around the failure (reference's truncation)
MAX_CONFIGS = 10


def _op_label(i: int, op: dict) -> str:
    f, v = op.get("f"), op.get("value")
    return f"{i}:{f} {v!r}" if v is not None else f"{i}:{f}"


def _window_ops(history: list, failed_idx: int) -> list[tuple[int, dict, int]]:
    """The failing invocation plus the WINDOW-1 invocations nearest before
    it, as (history index of invoke, invoke op, completion index|-1)."""
    # pair invokes with completions by process
    completion: dict[int, int] = {}
    open_inv: dict = {}
    for i, op in enumerate(history):
        t = op.get("type")
        p = op.get("process")
        if t == "invoke":
            open_inv[p] = i
        elif t in ("ok", "fail", "info"):
            j = open_inv.pop(p, None)
            if j is not None:
                completion[j] = i
    # the failed index may be a completion: map back to its invocation
    fail_inv = failed_idx
    op = history[failed_idx] if failed_idx < len(history) else {}
    if op.get("type") != "invoke":
        for inv, comp in completion.items():
            if comp == failed_idx:
                fail_inv = inv
                break
    invs = [i for i, o in enumerate(history) if o.get("type") == "invoke"
            and i <= fail_inv]
    picked = invs[-WINDOW:]
    if fail_inv not in picked and fail_inv < len(history):
        picked.append(fail_inv)
    return [(i, history[i], completion.get(i, -1)) for i in picked]


def render_failure(history: list, result, path: str) -> str | None:
    """Writes the failure figure to ``path`` (PNG). Returns the path, or
    None when there is nothing to draw (valid result or empty history)."""
    if getattr(result, "valid", None) is not False or not history:
        return None
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    failed_idx = getattr(result, "failed_op_index", -1)
    if failed_idx < 0 or failed_idx >= len(history):
        return None
    ops = _window_ops(history, failed_idx)
    if not ops:
        return None
    fail_inv = ops[-1][0] if history[failed_idx].get("type") == "invoke" \
        else next((i for i, _, c in ops if c == failed_idx), ops[-1][0])

    procs = sorted({history[i].get("process") for i, _, _ in ops},
                   key=repr)
    lane = {p: k for k, p in enumerate(procs)}
    configs = (getattr(result, "final_configs", None) or [])[:MAX_CONFIGS]

    fig_h = 0.5 * len(procs) + 0.28 * max(1, len(configs)) + 1.6
    fig, (ax, axc) = plt.subplots(
        2, 1, figsize=(10, fig_h),
        gridspec_kw={"height_ratios": [max(1, len(procs)),
                                       max(1, len(configs)) * 0.6]})

    # --- timeline band ---------------------------------------------------
    lo = min(i for i, _, _ in ops)
    hi = max(max(c for _, _, c in ops), failed_idx, fail_inv) + 1
    for i, op, comp in ops:
        p = lane[op.get("process")]
        end = comp if comp >= 0 else hi  # crashed: open to the right edge
        fatal = i == fail_inv
        ax.barh(p, end - i, left=i, height=0.6,
                color="#d62728" if fatal else "#6baed6",
                edgecolor="black", linewidth=0.5, alpha=0.9)
        ax.text(i + 0.1, p, _op_label(i, op), va="center", fontsize=7)
    ax.set_yticks(range(len(procs)))
    ax.set_yticklabels([f"proc {p}" for p in procs], fontsize=8)
    ax.set_xlim(lo - 0.5, hi + 0.5)
    ax.set_xlabel("history index", fontsize=8)
    ax.set_title(
        f"Linearizability failure at op {failed_idx}: "
        f"{history[failed_idx].get('f')} "
        f"{history[failed_idx].get('value')!r} "
        f"(no surviving configuration)", fontsize=9)
    ax.invert_yaxis()

    # --- configuration band ----------------------------------------------
    axc.axis("off")
    if configs:
        lines = [
            f"state={c.get('state')!r}  "
            f"linearized={c.get('linearized')}  pending={c.get('pending')}"
            for c in configs]
        txt = "Configurations before the fatal return "
        txt += f"(showing {len(configs)}):\n" + "\n".join(lines)
    else:
        txt = "No configuration detail available (device verdict; re-run " \
              "with accelerator='cpu' for the exact dying frontier)."
    axc.text(0, 1, txt, va="top", ha="left", fontsize=7, family="monospace")

    fig.tight_layout()
    fig.savefig(path, dpi=120, bbox_inches="tight")
    plt.close(fig)
    return path
