"""HTML timeline: a per-process gantt of operations (reference:
jepsen/src/jepsen/checker/timeline.clj — hiccup there; direct HTML string
assembly here, no dependency).

Each op is a positioned block in its process's column; height spans
invoke→completion, color encodes the completion type. Histories past
``OP_LIMIT`` render *windowed*: evenly sampled across the WHOLE run with
a visible "truncated — N of M ops" banner, instead of the reference's
silent first-N clip (timeline.clj:12-14) — witness windows from huge
histories must render, not vanish.

:func:`render_witness` is the anomaly-forensics view (doc/observability
"Anomaly forensics"): just the witness ops of an ``anomaly.json``, the
fatal op highlighted, with the run's nemesis/fault windows overlaid as
horizontal bands.
"""
from __future__ import annotations

import html as html_mod
from typing import Any

from jepsen_tpu import store
from jepsen_tpu.checker import Checker
from jepsen_tpu.utils import history_to_latencies, nanos_to_ms

OP_LIMIT = 10_000

COLORS = {"ok": "#6DB6FE", "info": "#FFAA26", "fail": "#FEB5DA"}
NS = 1e9
HSCALE = 1e-6 / 10.0  # nanos -> px (1 ms = 0.1 px)
MIN_HEIGHT = 14
COL_WIDTH = 100
GUTTER = 4

STYLE = """
body { font-family: sans-serif; font-size: 11px; }
.ops { position: absolute; }
.op { position: absolute; padding: 2px; border-radius: 2px;
      overflow: hidden; box-sizing: border-box; }
.op:hover { overflow: visible; z-index: 10; min-width: 250px; }
.op.fatal { border: 2px solid #d00; z-index: 5; }
.proc-header { position: absolute; top: 0; font-weight: bold; }
.banner { background: #fff3cd; border: 1px solid #e0c060;
          padding: 0.4em 0.8em; margin-bottom: 0.5em; display: inline-block; }
.fault-band { position: absolute; left: 0; right: 0;
              background: rgba(255, 160, 60, 0.18);
              border-top: 1px dashed #d08030; z-index: 0; }
.fault-band span { color: #a05010; font-size: 10px; }
"""


def pairs(history: list[dict]) -> list[tuple[dict, dict | None]]:
    """(invoke, completion|None) pairs, client ops only
    (timeline.clj:37-57)."""
    out = []
    for op in history_to_latencies(history):
        if op.get("type") != "invoke" or op.get("process") == "nemesis":
            continue
        out.append((op, op.get("completion")))
    return out


def _op_blocks(ps, col, hscale=HSCALE, t_base: float = 0.0,
               fatal_indices=frozenset()):
    """Positioned op divs + the max y they reach."""
    blocks = []
    max_y = 0.0
    for iv, comp in ps:
        t0 = iv.get("time", 0)
        t1 = comp.get("time", t0) if comp else t0 + MIN_HEIGHT / hscale
        y = 20 + (t0 - t_base) * hscale
        h = max(MIN_HEIGHT, (t1 - t0) * hscale)
        max_y = max(max_y, y + h)
        x = col[iv.get("process")] * (COL_WIDTH + GUTTER)
        typ = comp.get("type", "info") if comp else "info"
        color = COLORS.get(typ, "#dddddd")
        label = f"{iv.get('f')} {iv.get('value')!r}"
        if comp is not None and comp.get("value") != iv.get("value"):
            label += f" → {comp.get('value')!r}"
        title = (f"process {iv.get('process')} {typ} "
                 f"t={nanos_to_ms(t0):.1f}ms "
                 f"lat={nanos_to_ms(iv.get('latency', 0)):.1f}ms")
        fatal = iv.get("index") in fatal_indices or \
            (comp is not None and comp.get("index") in fatal_indices)
        cls = "op fatal" if fatal else "op"
        blocks.append(
            f'<div class="{cls}" title="{html_mod.escape(title)}" '
            f'style="left:{x}px;top:{y:.1f}px;width:{COL_WIDTH}px;'
            f'height:{h:.1f}px;background:{color}">'
            f'{html_mod.escape(label)}</div>')
    return blocks, max_y


def _page(title: str, banner: str, blocks: list[str], max_y: float) -> str:
    return (
        "<!DOCTYPE html><html><head><meta charset='utf-8'>"
        f"<title>{html_mod.escape(title)}</title>"
        f"<style>{STYLE}</style></head><body>{banner}"
        f"<div class='ops' style='height:{max_y + 40:.0f}px'>"
        + "".join(blocks) + "</div></body></html>")


def render(test: dict, history: list[dict],
           max_ops: int | None = None) -> str:
    """The run timeline. Histories over the cap render a WINDOWED view —
    every ⌈M/cap⌉-th op across the whole run — with a visible truncation
    banner, so a 1M-op run still shows its full time span instead of
    silently clipping to the first 10k ops."""
    all_ps = pairs(history)
    total = len(all_ps)
    cap = OP_LIMIT if max_ops is None else max_ops
    banner = ""
    if cap and total > cap:
        step = -(-total // cap)
        ps = all_ps[::step]
        banner = (f"<div class='banner'>truncated — showing {len(ps)} of "
                  f"{total} ops (every {step}th, whole run "
                  "windowed)</div>")
    else:
        ps = all_ps
    processes = sorted({iv.get("process") for iv, _ in ps},
                       key=lambda p: (str(type(p)), p))
    col = {p: i for i, p in enumerate(processes)}
    blocks = []
    for p in processes:
        x = col[p] * (COL_WIDTH + GUTTER)
        blocks.append(f'<div class="proc-header" style="left:{x}px">'
                      f'process {html_mod.escape(str(p))}</div>')
    op_blocks, max_y = _op_blocks(ps, col)
    blocks += op_blocks
    return _page(f"{test.get('name', 'test')} timeline", banner, blocks,
                 max_y)


def render_witness(test: dict, history: list[dict], anomaly: dict) -> str:
    """The witness window of an anomaly.json payload as a per-process
    gantt: only the witness (and context) ops, time-zoomed to the
    window, the fatal op outlined, and the run's fault windows overlaid
    as labeled horizontal bands (doc/observability.md "Anomaly
    forensics")."""
    wit = anomaly.get("witness") or {}
    fa = anomaly.get("first_anomaly") or {}
    indices = set(wit.get("op_indices") or [])
    indices |= set(wit.get("context_op_indices") or [])
    if fa.get("op_index") is not None:
        indices.add(fa["op_index"])
    fatal = {i for i in (fa.get("op_index"),) if i is not None}

    # index ops (history_to_latencies preserves dict contents; stored
    # histories already carry "index", fresh ones get one here)
    hist = [op if "index" in op else {**op, "index": i}
            for i, op in enumerate(history)]
    ps = [(iv, comp) for iv, comp in pairs(hist)
          if iv.get("index") in indices
          or (comp is not None and comp.get("index") in indices)]
    times = [iv.get("time", 0) for iv, _ in ps] or [0]
    t_base = min(times)
    t_span = max(max(times) - t_base, 1)
    # zoom the window to ~800px regardless of absolute duration
    hscale = min(800.0 / t_span, 2.0) if t_span else HSCALE

    processes = sorted({iv.get("process") for iv, _ in ps},
                       key=lambda p: (str(type(p)), p))
    col = {p: i for i, p in enumerate(processes)}
    width = max(1, len(processes)) * (COL_WIDTH + GUTTER)
    blocks = []
    for p in processes:
        x = col[p] * (COL_WIDTH + GUTTER)
        blocks.append(f'<div class="proc-header" style="left:{x}px">'
                      f'process {html_mod.escape(str(p))}</div>')
    op_blocks, max_y = _op_blocks(ps, col, hscale=hscale, t_base=t_base,
                                  fatal_indices=fatal)
    blocks += op_blocks

    # fault windows overlapping the witness span, as horizontal bands
    for w in anomaly.get("fault_windows") or ():
        t0 = w.get("start_time")
        if t0 is None:
            continue
        t1 = w.get("end_time")
        # out-of-span windows are omitted — an open (end_time None)
        # window starting past the span must not stretch the page
        if t0 > t_base + t_span or (t1 is not None and t1 < t_base):
            continue
        y0 = 20 + max(0.0, (t0 - t_base)) * hscale
        y1 = (20 + (t1 - t_base) * hscale if t1 is not None
              else max_y + 20)
        label = f"{w.get('kind')} ({w.get('f')})"
        if w.get("healed") and w.get("end_time") is None:
            label += f" — healed via {w.get('via')} (outside history)"
        max_y = max(max_y, y1)
        blocks.append(
            f'<div class="fault-band" style="top:{y0:.1f}px;'
            f'height:{max(2.0, y1 - y0):.1f}px;min-width:{width}px">'
            f'<span>{html_mod.escape(label)}</span></div>')

    summary = (f"first anomaly at op {fa.get('op_index')} "
               f"({fa.get('f')} {fa.get('value')!r}, process "
               f"{fa.get('process')}) — witness of "
               f"{len(wit.get('op_indices') or [])} op(s)")
    banner = f"<div class='banner'>{html_mod.escape(summary)}</div>"
    return _page(f"{test.get('name', 'test')} witness", banner, blocks,
                 max_y)


class Timeline(Checker):
    def name(self):
        return "timeline"

    def check(self, test, history, opts):
        d = opts.get("subdirectory")
        out = store.path_mk(test, *filter(None, [d, "timeline.html"]))
        out.write_text(render(test, history))
        return {"valid?": True}


def html() -> Checker:
    return Timeline()
