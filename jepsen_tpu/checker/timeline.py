"""HTML timeline: a per-process gantt of operations (reference:
jepsen/src/jepsen/checker/timeline.clj — hiccup there; direct HTML string
assembly here, no dependency).

Each op is a positioned block in its process's column; height spans
invoke→completion, color encodes the completion type. Capped at
``OP_LIMIT`` ops like the reference (timeline.clj:12-14).
"""
from __future__ import annotations

import html as html_mod
from typing import Any

from jepsen_tpu import store
from jepsen_tpu.checker import Checker
from jepsen_tpu.utils import history_to_latencies, nanos_to_ms

OP_LIMIT = 10_000

COLORS = {"ok": "#6DB6FE", "info": "#FFAA26", "fail": "#FEB5DA"}
NS = 1e9
HSCALE = 1e-6 / 10.0  # nanos -> px (1 ms = 0.1 px)
MIN_HEIGHT = 14
COL_WIDTH = 100
GUTTER = 4

STYLE = """
body { font-family: sans-serif; font-size: 11px; }
.ops { position: absolute; }
.op { position: absolute; padding: 2px; border-radius: 2px;
      overflow: hidden; box-sizing: border-box; }
.op:hover { overflow: visible; z-index: 10; min-width: 250px; }
.proc-header { position: absolute; top: 0; font-weight: bold; }
"""


def pairs(history: list[dict]) -> list[tuple[dict, dict | None]]:
    """(invoke, completion|None) pairs, client ops only
    (timeline.clj:37-57)."""
    out = []
    for op in history_to_latencies(history):
        if op.get("type") != "invoke" or op.get("process") == "nemesis":
            continue
        out.append((op, op.get("completion")))
    return out


def render(test: dict, history: list[dict]) -> str:
    ps = pairs(history)[:OP_LIMIT]
    processes = sorted({iv.get("process") for iv, _ in ps},
                       key=lambda p: (str(type(p)), p))
    col = {p: i for i, p in enumerate(processes)}
    blocks = []
    for p in processes:
        x = col[p] * (COL_WIDTH + GUTTER)
        blocks.append(f'<div class="proc-header" style="left:{x}px">'
                      f'process {html_mod.escape(str(p))}</div>')
    max_y = 0.0
    for iv, comp in ps:
        t0 = iv.get("time", 0)
        t1 = comp.get("time", t0) if comp else t0 + MIN_HEIGHT / HSCALE
        y = 20 + t0 * HSCALE
        h = max(MIN_HEIGHT, (t1 - t0) * HSCALE)
        max_y = max(max_y, y + h)
        x = col[iv.get("process")] * (COL_WIDTH + GUTTER)
        typ = comp.get("type", "info") if comp else "info"
        color = COLORS.get(typ, "#dddddd")
        label = f"{iv.get('f')} {iv.get('value')!r}"
        if comp is not None and comp.get("value") != iv.get("value"):
            label += f" → {comp.get('value')!r}"
        title = (f"process {iv.get('process')} {typ} "
                 f"t={nanos_to_ms(t0):.1f}ms "
                 f"lat={nanos_to_ms(iv.get('latency', 0)):.1f}ms")
        blocks.append(
            f'<div class="op" title="{html_mod.escape(title)}" '
            f'style="left:{x}px;top:{y:.1f}px;width:{COL_WIDTH}px;'
            f'height:{h:.1f}px;background:{color}">'
            f'{html_mod.escape(label)}</div>')
    return (
        "<!DOCTYPE html><html><head><meta charset='utf-8'>"
        f"<title>{html_mod.escape(str(test.get('name', 'test')))} timeline"
        f"</title><style>{STYLE}</style></head><body>"
        f"<div class='ops' style='height:{max_y + 40:.0f}px'>"
        + "".join(blocks) + "</div></body></html>")


class Timeline(Checker):
    def name(self):
        return "timeline"

    def check(self, test, history, opts):
        d = opts.get("subdirectory")
        out = store.path_mk(test, *filter(None, [d, "timeline.html"]))
        out.write_text(render(test, history))
        return {"valid?": True}


def html() -> Checker:
    return Timeline()
