"""Linearizability checker with an accelerator switch.

Reference surface: jepsen.checker/linearizable (checker.clj:185-216), which
dispatches on :algorithm to knossos's linear/wgl/competition searches. Here
the dispatch axes are:

* ``algorithm``: "wgl" (object-model DFS oracle), "jitlin" (int-encoded
  breadth-first search — the TPU kernel's CPU twin), or "auto".
* ``accelerator``: "cpu", "tpu" (any JAX device), or "auto" — the
  :accelerator option called for by BASELINE.json's north star. "auto" uses
  the device kernel for histories big enough to amortize compilation and
  falls back to CPU when the device frontier overflows (mirroring the
  reference's competition mode, checker.clj:199-203).

Failure output is truncated (the reference truncates :final-paths/:configs
to 10 because "Writing these can take *hours*", checker.clj:213-216).
"""
from __future__ import annotations

import logging
import time
from typing import Any

from jepsen_tpu import telemetry
from jepsen_tpu.checker import Checker
from jepsen_tpu.checker.linear_cpu import (
    LinearResult, cas_register_step_py, check_stream, wgl,
)
from jepsen_tpu.checker.linear_encode import encode_register_ops
from jepsen_tpu.models import CASRegister, Model

logger = logging.getLogger("jepsen.checker.linearizable")

# Histories below this many events run on CPU under accelerator="auto":
# kernel launch + compile isn't worth it.
AUTO_TPU_THRESHOLD = 512

# Failure reports re-run the exact CPU search to recover the dying
# frontier; skip that recovery for histories longer than this.
MAX_REPORT_EVENTS = 200_000

# Backends that have completed at least one dispatch this process: the
# first call's wall time includes JIT compilation, later calls don't —
# exporting both makes the compile/execute split readable from metrics.
_FIRST_CHECK_SEEN: set = set()


class LinearizableChecker(Checker):
    def __init__(
        self,
        model: Model | None = None,
        algorithm: str = "auto",
        accelerator: str = "auto",
        capacity: int = 256,
        multi_shape: tuple = (3, 5),
        watchdog_s: float | None = None,
        breaker_threshold: int | None = None,
    ):
        self.model = model if model is not None else CASRegister()
        self.algorithm = algorithm
        self.accelerator = accelerator
        self.capacity = capacity
        # (n_keys, n_values) for the MultiRegister int encoding — the
        # multi-key-acid workload's shape (multi_key_acid.clj key-range/
        # rand-val)
        self.multi_shape = multi_shape
        # degradation-ladder tunables (doc/robustness.md); None = the
        # ladder module's env-tunable defaults
        self.watchdog_s = watchdog_s
        self.breaker_threshold = breaker_threshold
        self._kernel = None
        self._ladder = None

    def _encoding(self, history, ir=None):
        """(stream, step_py, spec) when the model has an int encoding for
        the device/stream paths, else None (object-model wgl search).
        With an ``ir`` (the run's shared history IR) the stream is the
        memoized view — a second checker over the same history pays
        nothing (bit-identical either way; tests/test_history_ir.py)."""
        from jepsen_tpu.models import MultiRegister, multi_register_spec

        if isinstance(self.model, CASRegister):
            from jepsen_tpu.history import Intern
            from jepsen_tpu.models import cas_register_spec
            if ir is not None:
                from jepsen_tpu.history_ir import views
                stream = views.register_stream(ir,
                                               init_value=self.model.value)
            else:
                intern = Intern()
                # a non-None initial register value interns FIRST so its
                # id is the kernel's init state (single-key-acid at 0)
                if self.model.value is not None:
                    intern.id(self.model.value)
                stream = encode_register_ops(history, intern=intern)
            init_id = (0 if self.model.value is None
                       else stream.intern.id(self.model.value))
            return (stream, cas_register_step_py,
                    cas_register_spec(init_id))
        if isinstance(self.model, MultiRegister):
            from jepsen_tpu.checker.linear_cpu import multi_register_step_py
            from jepsen_tpu.checker.linear_encode import (
                encode_multi_register_ops)
            k, v = self.multi_shape
            if ir is not None:
                from jepsen_tpu.history_ir import views
                stream = views.multi_register_stream(ir, k, v)
                if stream is None:
                    return None  # outside the packed encoding: wgl
            else:
                try:
                    stream = encode_multi_register_ops(history, k, v)
                except ValueError:
                    return None  # outside the packed encoding: wgl
            return (stream, multi_register_step_py(k, v),
                    multi_register_spec(k, v))
        return None

    def _tpu_kernel(self, spec):
        if self._kernel is None:
            from jepsen_tpu.ops.jitlin import JitLinKernel
            self._kernel = JitLinKernel(step_ids=spec.step_ids,
                                        init_state=spec.init_state)
        return self._kernel

    def check(self, test, history, opts):
        algorithm = opts.get("algorithm", self.algorithm)
        accelerator = opts.get("accelerator", self.accelerator)
        # multi-device sharding knobs (doc/performance.md "Multi-device
        # sharding"): checker_sharded force-enables/disables the sharded
        # rung (None = env default + cost model), mesh_devices caps the
        # mesh width
        from jepsen_tpu import parallel as par
        from jepsen_tpu.checker import explain as explain_mod
        sharded, mesh_devices = par.sharding_knobs(test, opts)
        explain_on = explain_mod.enabled(test, opts)
        # matrix-kernel routing knobs (doc/performance.md "Packed
        # boolean kernels"): matrix_variant pins the representation
        # (probe-gated, demotes down the auto order), combine_fused
        # pins the combine path; both tolerantly coerced, opts over test
        from jepsen_tpu.ops import pallas_matrix as pm
        tmap = test if isinstance(test, dict) else {}
        matrix_variant = pm.coerce_variant(
            opts.get("matrix_variant", tmap.get("matrix_variant")))
        combine_fused = par.coerce_flag(
            opts.get("combine_fused", tmap.get("combine_fused")),
            knob="combine_fused")

        t0 = time.perf_counter()
        if algorithm == "wgl":
            res = wgl(history, self.model)
            self._record_metrics(res, time.perf_counter() - t0,
                                 len(history), None)
            return self._finish(res, history, test)

        # jitlin path: encode once — through the run's shared history
        # IR when one is attachable (history_ir.of memoizes on the test
        # map, so composed checkers share a single encode)
        from jepsen_tpu import history_ir
        enc = self._encoding(history, ir=history_ir.of(test, history))
        if enc is None:
            res = wgl(history, self.model)
            self._record_metrics(res, time.perf_counter() - t0,
                                 len(history), None)
            return self._finish(res, history, test)
        stream, step_py, spec = enc
        extras: dict = {}
        # durable checker checkpoints (doc/robustness.md "Resumable
        # checks and the elastic mesh"): a run-dir-backed check persists
        # its tiny carry to check.ckpt and auto-resumes a valid one
        ckpt = self._ckpt_store(test)
        min_devices = par.coerce_devices(
            opts.get("mesh_min_devices", tmap.get("mesh_min_devices")),
            knob="mesh_min_devices")
        res = self._search_stream(stream, step_py, spec, algorithm,
                                  accelerator, history=history,
                                  sharded=sharded,
                                  mesh_devices=mesh_devices,
                                  explain=explain_on, extras=extras,
                                  matrix_variant=matrix_variant,
                                  combine_fused=combine_fused,
                                  ckpt=ckpt, mesh_min_devices=min_devices)
        if ckpt is not None:
            # the check settled: a surviving check.ckpt would mark an
            # interrupted check and mislead the next analyze
            ckpt.clear()
        self._record_metrics(res, time.perf_counter() - t0, len(stream),
                             stream)
        return self._finish(res, history, test, stream, step_py=step_py,
                            init_state=spec.init_state,
                            step_ids=spec.step_ids,
                            explain_on=explain_on,
                            explain_loc=extras.get("loc"), opts=opts)

    def _ckpt_store(self, test):
        """The run's durable check.ckpt store, or None when the test
        map has no store coordinates (bare re-checks, unit tests) or
        checkpointing AND resumption are both off."""
        if not isinstance(test, dict) or not test.get("start_time"):
            return None
        from jepsen_tpu.checker import checkpoint as ckpt_mod
        interval = ckpt_mod.ckpt_interval(test)
        resume = ckpt_mod.resume_enabled(test)
        if interval is None and not resume:
            return None
        try:
            from jepsen_tpu import store
            path = store.path(test, ckpt_mod.CKPT_NAME)
        except Exception:  # noqa: BLE001 — no store dir: no checkpoints
            return None
        return ckpt_mod.CheckpointStore(path, interval_s=interval,
                                        resume=resume)

    def _search_stream(self, stream, step_py, spec, algorithm,
                       accelerator, history=None, sharded=None,
                       mesh_devices=None, explain=True,
                       extras=None, matrix_variant=None,
                       combine_fused=None, ckpt=None,
                       mesh_min_devices=None) -> LinearResult:
        """The full encoded-stream dispatch, shared by check() and the
        stored-column re-check lane (module check_stored), routed
        through the :class:`~jepsen_tpu.checker.ladder.BackendLadder`:
        host rungs (native C++ first, exact Python stream search) below
        the device threshold, device rungs (mesh-sharded matrix,
        transfer-matrix screen, frontier kernel) above it, with the
        exact CPU twin as the terminal rung every demotion lands on."""
        device_regime = not (accelerator == "cpu" or (
            accelerator == "auto" and len(stream) < AUTO_TPU_THRESHOLD))
        ctx = {
            "stream": stream,
            "step_py": step_py,
            "spec": spec,
            "history": history,
            "device_regime": device_regime,
            "capacity": self.capacity,
            # sharded-rung routing (doc/performance.md): True forces,
            # False disables, None = env default + cost-model gate
            "sharded": sharded,
            "mesh_devices": mesh_devices,
            # anomaly forensics (doc/observability.md): invalid matrix
            # verdicts localize on device instead of demoting to a full
            # re-scan just to find the op
            "explain": explain,
            # matrix-kernel routing (doc/performance.md "Packed boolean
            # kernels"): pinned representation / combine path, or None
            # for the probe order
            "matrix_variant": matrix_variant,
            "combine_fused": combine_fused,
            # durable checkpoints + the elastic shrink floor
            # (doc/robustness.md "Resumable checks and the elastic
            # mesh"): the rungs persist/resume their carries through
            # _ckpt, and the sharded rung's shrink ladder bottoms out
            # at mesh_min_devices
            "_ckpt": ckpt,
            "mesh_min_devices": mesh_min_devices,
            # the encoded-stream search applies for jitlin/auto, and for
            # the stored-column lane (no op history to wgl over)
            "stream_path": (algorithm in ("jitlin", "auto")
                            or history is None),
        }
        res, _backend = self._get_ladder().run(ctx)
        if extras is not None and "_explain_loc" in ctx:
            # the rung's device localization rides out so _finish can
            # reuse it for the witness shrink (no second bisection)
            extras["loc"] = ctx["_explain_loc"]
        phases = ctx.pop("_matrix_phase", None)
        if phases:
            # the matrix rung may have run on a watchdog thread; make
            # its phase split visible to this thread's readers
            # (_record_metrics, bench)
            from jepsen_tpu.ops.jitlin import publish_phase_seconds
            publish_phase_seconds(phases)
        return res

    def _get_ladder(self):
        """The degradation ladder, built once per checker: sharded-matrix
        (mesh) -> pallas-matrix -> jitlin device frontier -> native C++
        -> exact CPU. Demotion,
        watchdog, adaptive-shrink retry, and circuit-breaker policy all
        live in checker/ladder.py; the rungs here only encode *what*
        each backend computes and *when* it is in regime."""
        if self._ladder is not None:
            return self._ladder
        from jepsen_tpu.checker.ladder import (
            Backend, BackendLadder, is_device_loss, is_resource_exhausted,
        )

        is_cas = isinstance(self.model, CASRegister)

        def carry_sink(ctx):
            """A gen-guarded carry publisher for the segmented matrix
            chain: carries only land while the publishing attempt still
            owns the ladder (a watchdog-abandoned zombie's late writes
            are dropped — the demoted rung already resumed)."""
            gen = ctx.get("_gen", 0)

            def sink(carry):
                if ctx.get("_gen", 0) == gen:
                    ctx["_carry"] = carry
            return sink

        def matrix_rung_check(ctx, mesh):
            """The matrix screen one rung runs: one-shot for short
            streams, the crash-resumable segmented chain when a
            durable checkpoint store is attached, a demotion carry is
            waiting, or the stream is longer than one segment —
            bit-identical either way (boolean operator products are
            exact under any association)."""
            from jepsen_tpu.ops.jitlin import (
                MATRIX_SEGMENT_EVENTS, matrix_check, matrix_check_segmented,
            )
            stream, spec = ctx["stream"], ctx["spec"]
            kw = dict(step_ids=spec.step_ids, init_state=spec.init_state,
                      num_states=len(stream.intern), mesh=mesh,
                      variant=ctx.get("matrix_variant"),
                      combine_fused=ctx.get("combine_fused"))
            carry = ctx.get("_carry")
            if carry is not None and carry.get("rep") != "matrix":
                carry = None
            ckpt = ctx.get("_ckpt")
            # a stream within one segment can never write a mid-chain
            # checkpoint, so it only takes the chain when a resume is
            # actually pending (a surviving check.ckpt or a demotion
            # carry) — short checks keep the one-shot dispatch
            resume_pending = carry is not None or (
                ckpt is not None and ckpt.resume and ckpt.path.exists())
            if not resume_pending and len(stream) <= MATRIX_SEGMENT_EVENTS:
                return matrix_check(stream, force=False, **kw)
            return matrix_check_segmented(stream, ckpt=ctx.get("_ckpt"),
                                          carry=carry,
                                          carry_sink=carry_sink(ctx),
                                          **kw)

        def matrix_eligible(ctx):
            # long histories over small value domains: the block-composed
            # transfer-matrix kernel settles the verdict with far less
            # sequential depth (MXU boolean matmuls over chunks); the
            # event scan remains the diagnostics path (died-at, peak)
            if not ctx["device_regime"]:
                return False
            import numpy as np
            from jepsen_tpu.ops.jitlin import matrix_ok
            stream = ctx["stream"]
            n_returns = int((np.asarray(stream.kind) == 1).sum())
            return matrix_ok(stream.n_slots, len(stream.intern), n_returns)

        def matrix_settle(ctx, m, algo):
            """A COMPLETED matrix screen verdict -> LinearResult, or
            None to demote. An exact True settles valid. An exact False
            localizes the first anomaly ON DEVICE (the forensics
            bisection over the composable chunk products,
            jitlin.matrix_localize — bit-identical to the CPU
            frontier's rejection) and settles INVALID with the precise
            event, instead of demoting to a full event re-scan just to
            find the op (doc/observability.md "Anomaly forensics").
            Inexact (oob) proves nothing either way and always
            demotes."""
            if m is None:
                return None
            if m[2]:
                return None
            if m[0]:
                return LinearResult(
                    valid=True, failed_event=-1, failed_op_index=-1,
                    configs_max=0, algorithm=algo)
            if not ctx.get("explain", True):
                return None  # explain off: the old demote-to-scan path
            from jepsen_tpu.ops.jitlin import matrix_localize
            stream, spec = ctx["stream"], ctx["spec"]
            try:
                loc = matrix_localize(stream, step_ids=spec.step_ids,
                                      init_state=spec.init_state,
                                      num_states=len(stream.intern))
            except Exception:  # noqa: BLE001 — localization never fails a check
                logger.exception("matrix localization failed; demoting")
                loc = None
            if loc is None:
                return None
            ctx["_explain_loc"] = loc
            return LinearResult(
                valid=False, failed_event=loc.failed_event,
                failed_op_index=loc.failed_op_index, configs_max=0,
                algorithm=algo)

        def matrix_fn(ctx):
            from jepsen_tpu.ops.jitlin import last_phase_seconds
            if ctx.get("_matrix_screened"):
                # the sharded rung already ran the bit-identical screen
                # to completion and it didn't settle; don't pay for it
                # twice (a sharded CRASH leaves the flag unset, so the
                # demotion path still gets its single-device screen —
                # resuming from the sharded rung's threaded carry)
                return None
            m = matrix_rung_check(ctx, mesh=None)
            # capture the phase split on THIS (possibly watchdog) thread;
            # _search_stream re-publishes it on the checker's thread
            ctx["_matrix_phase"] = last_phase_seconds()
            return matrix_settle(ctx, m, "jitlin-tpu-matrix")

        def matrix_shrink(ctx):
            # halve the chunk element budget: _matrix_plan sizes the
            # per-step [G, MV, MV] working set under it, so halving it
            # halves the device-resident intermediates. The halved value
            # sticks (adaptive): the device told us its real capacity.
            from jepsen_tpu.ops import jitlin
            if jitlin.MATRIX_MAX_ELEMS <= (1 << 20):
                return False
            jitlin.MATRIX_MAX_ELEMS //= 2
            return True

        def sharded_eligible(ctx):
            # the mesh-sharded matrix rung: same regime gate as the
            # single-device matrix screen, plus ≥2 devices and the
            # per-device-count cost model (small histories must not pay
            # mesh overhead). checker_sharded=True skips the cost gate
            # (the operator asked); False disables the rung outright.
            if not matrix_eligible(ctx):
                return False
            from jepsen_tpu import parallel
            flag = ctx.get("sharded")
            if flag is False:
                return False
            if flag is not True and not parallel.sharded_enabled():
                return False
            if flag is True:
                mesh = parallel.auto_mesh(ctx.get("mesh_devices"))
            else:
                mesh = parallel.sharded_mesh_for(len(ctx["stream"]),
                                                 ctx.get("mesh_devices"))
            if mesh is None:
                return False
            ctx["_sharded_mesh"] = mesh
            return True

        def sharded_fn(ctx):
            # the multi-device twin of matrix_fn: chunk axis sharded
            # over the mesh, carries tree-combined device-side. A
            # collective error / device loss raises — sharded_shrink
            # below rebuilds the mesh over the survivors and the retry
            # RESUMES from the threaded carry (elastic mesh,
            # doc/robustness.md "Resumable checks and the elastic
            # mesh"); only when the shrink ladder bottoms out does the
            # ladder demote to the single-device rungs — which also
            # resume from the carry, so sharding unavailability
            # degrades, never fails and never restarts.
            from jepsen_tpu.ops.jitlin import last_phase_seconds
            m = matrix_rung_check(ctx, mesh=ctx["_sharded_mesh"])
            ctx["_matrix_phase"] = last_phase_seconds()
            res = matrix_settle(ctx, m, "jitlin-tpu-matrix-sharded")
            if res is not None:
                return res
            # the screen COMPLETED but didn't settle (inexact, or
            # invalid with localization declined/off): the
            # single-device screen is bit-identical, so matrix_fn
            # re-running it would pay a full matrix dispatch to learn
            # the same thing — flag it to decline instead
            ctx["_matrix_screened"] = True
            return None

        def sharded_shrink(ctx):
            # the elastic mesh: rebuild over the surviving device set
            # and let the retry resume from the carry. A genuine OOM
            # (RESOURCE_EXHAUSTED) first gets the classic element-budget
            # halving — shrinking the mesh INCREASES per-device load,
            # and an OOM message that happens to name a device must
            # never poison a healthy device's health record — and only
            # shrinks the mesh (unattributed) once the budget bottoms
            # out. Device-loss/collective failures shrink with casualty
            # attribution from the error text.
            from jepsen_tpu import parallel
            mesh = ctx.get("_sharded_mesh")
            exc = ctx.get("_shrink_error")
            oom = exc is not None and is_resource_exhausted(exc)
            if oom and matrix_shrink(ctx):
                return True
            new = parallel.shrink_mesh(
                mesh, exc=None if oom else exc,
                min_devices=ctx.get("mesh_min_devices")) \
                if mesh is not None else None
            if new is not None:
                ctx["_sharded_mesh"] = new
                return True
            return False

        def frontier_fn(ctx):
            from jepsen_tpu.ops.jitlin import verdict
            stream, spec = ctx["stream"], ctx["spec"]
            alive, died, overflow, peak = self._tpu_kernel(spec).check(
                stream, capacity=ctx["capacity"])
            valid = verdict(alive, overflow)
            if valid == "unknown":
                # frontier overflowed K and died: the exact CPU twin
                # settles it (terminal rung)
                return None
            return LinearResult(
                valid=valid,
                failed_event=died,
                failed_op_index=(int(stream.op_index[died])
                                 if died >= 0 else -1),
                configs_max=peak,
                algorithm="jitlin-tpu",
            )

        def frontier_shrink(ctx):
            # halve the frontier capacity K: less device memory per
            # step. A verdict the smaller frontier can't settle becomes
            # unknown -> CPU demotion — never a wrong answer.
            if ctx["capacity"] <= 16:
                return False
            ctx["capacity"] = max(16, ctx["capacity"] // 2)
            return True

        def native_eligible(ctx):
            # native C++ search (same algorithm, ~100x the Python loop);
            # host regime only, and only the configuration it hardcodes
            # (CAS register, init id 0)
            return (not ctx["device_regime"] and ctx["stream_path"]
                    and is_cas and ctx["spec"].init_state == 0)

        def native_fn(ctx):
            from jepsen_tpu.native import check_stream_native
            res = check_stream_native(ctx["stream"])
            if res is not None and res.valid == "unknown":
                return None  # capacity blown (>63 slots live): Python
            return res  # None when unbuilt -> decline

        def cpu_fn(ctx):
            from_device = any(n in ("sharded-matrix", "pallas-matrix",
                                    "jitlin-device")
                              for n in ctx.get("_attempted", ()))
            if ctx["stream_path"] or from_device:
                step = ctx["step_py"]
                init = ctx["spec"].init_state
                # a demoted matrix rung's threaded carry seeds the exact
                # frontier at its last quiescent cut — a watchdog
                # timeout or mesh collapse keeps its completed segments
                # instead of restarting (doc/robustness.md)
                session = None
                carry = ctx.get("_carry")
                if carry is not None and carry.get("rep") == "matrix" \
                        and carry.get("init_state") == init:
                    from jepsen_tpu.checker import checkpoint as ckpt_mod
                    session = ckpt_mod.frontier_from_matrix_carry(
                        carry, step, init)
                    if session is not None:
                        ckpt_mod.count_resume("carry")
                        logger.info(
                            "exact CPU frontier resuming from the "
                            "demoted matrix rung's carry at event %d",
                            session.events_absorbed)
                ckpt = ctx.get("_ckpt")
                if ckpt is not None:
                    from jepsen_tpu.checker import checkpoint as ckpt_mod
                    res = ckpt_mod.checkpointed_check_stream(
                        ctx["stream"], step, init, ckpt,
                        session=session)
                elif session is not None:
                    res = session.absorb(ctx["stream"],
                                         start=session.events_absorbed)
                else:
                    res = check_stream(ctx["stream"], step=step,
                                       init_state=init)
                if from_device:
                    res.algorithm = "jitlin-cpu(fallback)"
                return res
            return wgl(ctx["history"], self.model)

        kw = {}
        if self.watchdog_s is not None:
            kw["watchdog_s"] = self.watchdog_s
        if self.breaker_threshold is not None:
            kw["breaker_threshold"] = self.breaker_threshold
        self._ladder = BackendLadder([
            # the sharded rung is ELASTIC: device-loss/collective
            # failures shrink the mesh over the survivors (up to
            # max_shrinks steps, e.g. 8→4→2) and resume from the
            # carry, demoting to single-device only when the shrink
            # ladder bottoms out at mesh_min_devices
            Backend("sharded-matrix", sharded_fn, eligible=sharded_eligible,
                    shrink=sharded_shrink, device=True, max_shrinks=6,
                    retryable=is_device_loss),
            Backend("pallas-matrix", matrix_fn, eligible=matrix_eligible,
                    shrink=matrix_shrink, device=True),
            Backend("jitlin-device", frontier_fn,
                    eligible=lambda ctx: ctx["device_regime"],
                    shrink=frontier_shrink, device=True),
            Backend("native-c", native_fn, eligible=native_eligible),
            Backend("cpu", cpu_fn),
        ], **kw)
        return self._ladder

    def _record_metrics(self, res: LinearResult, dt: float, n_events: int,
                        stream) -> None:
        """Runtime telemetry for one check dispatch: which backend won,
        first-call (JIT compile included) vs steady-state latency,
        events/sec, device-memory high-water, and — on the matrix path —
        the achieved-FLOPs/roofline gauges using bench.py's modeled-peak
        accounting (telemetry.matrix_modeled_flops)."""
        reg = telemetry.get_registry()
        if not reg.enabled:
            return
        try:
            backend = res.algorithm or "unknown"
            reg.counter("checker_backend_total",
                        "checks settled, by winning backend",
                        labels=("backend",)).inc(backend=backend)
            reg.histogram("checker_check_seconds",
                          "check dispatch wall time", labels=("backend",)
                          ).observe(dt, backend=backend)
            first = reg.gauge(
                "checker_first_check_seconds",
                "first dispatch per backend (includes JIT compile)",
                labels=("backend",))
            if backend not in _FIRST_CHECK_SEEN:
                _FIRST_CHECK_SEEN.add(backend)
                first.set(dt, backend=backend)
            else:
                reg.gauge("checker_steady_check_seconds",
                          "most recent non-first dispatch (compile "
                          "amortized; first minus steady ~= compile cost)",
                          labels=("backend",)).set(dt, backend=backend)
            if dt > 0:
                reg.gauge("checker_events_per_sec",
                          "events verified per second, last check",
                          labels=("backend",)
                          ).set(n_events / dt, backend=backend)
            if "tpu" in backend:
                peak_bytes = telemetry.device_memory_peak_bytes()
                if peak_bytes is not None:
                    reg.gauge("checker_device_memory_peak_bytes",
                              "device allocator high-water"
                              ).set_max(peak_bytes)
            if backend.startswith("jitlin-tpu-matrix") and stream is not None \
                    and dt > 0:
                import numpy as np
                n_returns = int((np.asarray(stream.kind) == 1).sum())
                achieved = telemetry.matrix_modeled_flops(
                    n_returns, stream.n_slots, len(stream.intern)) / dt
                reg.gauge("checker_achieved_matmul_flops",
                          "modeled matrix-kernel FLOP/s, last check"
                          ).set(achieved)
                peak = telemetry.device_peak_flops()
                if peak:
                    reg.gauge(
                        "checker_roofline_frac",
                        "achieved / measured f32 matmul peak "
                        "(see doc/observability.md)").set(achieved / peak)
                # per-phase attribution (doc/performance.md): where the
                # dispatch wall went — host encode (prepass/grids) vs
                # the async call vs device compute + readback. A small
                # roofline_frac with small host phases is fixed
                # round-trip overhead, not kernel inefficiency.
                from jepsen_tpu.ops.jitlin import last_phase_seconds
                phase_g = reg.gauge(
                    "checker_matrix_phase_seconds",
                    "host/device phase split of the last matrix "
                    "dispatch", labels=("phase",))
                split = last_phase_seconds()
                for ph, secs in split.items():
                    # the split also carries the routing labels
                    # (variant / combine) — strings, counted below
                    if isinstance(secs, (int, float)):
                        phase_g.set(secs, phase=ph)
                if "variant" in split:
                    reg.counter(
                        "checker_matrix_variant_total",
                        "matrix dispatches by kernel representation "
                        "and combine path",
                        labels=("variant", "combine")).inc(
                        variant=str(split["variant"]),
                        combine=str(split.get("combine", "tree")))
        except Exception:  # noqa: BLE001 — telemetry never fails a check
            logger.exception("checker telemetry recording failed")

    def _finish(self, res: LinearResult, history, test=None,
                stream=None, step_py=None, init_state: int = 0,
                step_ids=None, explain_on: bool = True, explain_loc=None,
                opts=None) -> dict:
        out: dict[str, Any] = {
            "valid?": res.valid,
            "algorithm": res.algorithm,
            "configs-max": res.configs_max,
        }
        if res.valid is False and res.failed_op_index >= 0:
            i = res.failed_op_index
            lo = max(0, i - 5)
            out["failed-op"] = history[i] if i < len(history) else None
            out["context"] = history[lo : i + 1][-10:]
            self._trace_anomaly(history, i, res)
            # device verdicts carry no frontier detail: one exact CPU pass
            # recovers the dying configurations for the report (the
            # knossos :configs surface). Gated by length — the history was
            # routed to the device because host search may be slow, and a
            # report must never cost more than the verdict. A device
            # localization (explain_loc) carries the exact event already,
            # so the recovery stays purely report detail.
            if res.final_configs is None and stream is not None \
                    and len(stream) <= MAX_REPORT_EVENTS:
                try:
                    res2 = check_stream(
                        stream, step=step_py or cas_register_step_py,
                        init_state=init_state)
                    if res2.valid is False:
                        res.final_configs = res2.final_configs
                except Exception:  # noqa: BLE001 report detail is optional
                    logger.exception("final-configs recovery failed")
            if res.final_configs is not None:
                out["final-configs"] = res.final_configs
            out["plot"] = self._render(res, history, test)
            self._explain(out, res, history, test, stream, step_py,
                          init_state, step_ids, explain_on, explain_loc,
                          opts)
        return out

    def _trace_anomaly(self, history, op_index: int, res) -> None:
        """The causal-trace half of an INVALID verdict: an ``explain``
        instant on the checker track carrying the first-anomaly op's
        stable trace id — the same id the interpreter's dispatch slice
        carries in its args, so the anomaly links straight back to its
        original dispatch. ``op_index`` may name either half of the op
        (the matrix localizer reports the fatal return); the id is
        always minted from the *invocation*'s time, which is what
        dispatch used. Never fails a check (doc/observability.md
        "Causal trace")."""
        try:
            from jepsen_tpu import trace as trace_mod
            tracer = trace_mod.get_tracer()
            if not tracer.enabled or not (0 <= op_index < len(history)):
                return
            op = history[op_index]
            inv = op
            if op.get("type") != "invoke":
                # walk back to this process's invocation — the most
                # recent earlier invoke by the same process
                for j in range(op_index - 1, -1, -1):
                    cand = history[j]
                    if cand.get("process") == op.get("process") \
                            and cand.get("type") == "invoke":
                        inv = cand
                        break
            tr_id = trace_mod.trace_id_for(inv.get("process"),
                                           inv.get("time"))
            tracer.instant(trace_mod.TRACK_CHECKER, "explain",
                           args={"op_index": op_index,
                                 "f": str(op.get("f")),
                                 "process": op.get("process"),
                                 "algorithm": res.algorithm,
                                 "trace_id": tr_id})
        except Exception:  # noqa: BLE001 — tracing never masks a verdict
            logger.exception("anomaly trace emission failed")

    def _explain(self, out, res, history, test, stream, step_py,
                 init_state, step_ids, explain_on, explain_loc,
                 opts) -> None:
        """Anomaly forensics for an INVALID verdict: localize + shrink a
        minimal witness, write ``anomaly.json`` + the witness timeline
        into the store dir, and surface a summary in the result
        (doc/observability.md "Anomaly forensics"). Never fails the
        check; ``explain: False`` in the test map turns it off."""
        if not explain_on or stream is None:
            return
        try:
            from jepsen_tpu.checker import explain as explain_mod
            tmap = test if isinstance(test, dict) else {}
            forensics = explain_mod.explain_stream(
                stream, step_ids=step_ids, step_py=step_py,
                init_state=init_state, loc=explain_loc, failure=res,
                shrink_budget=explain_mod.shrink_budget(tmap),
                max_witness_ops=explain_mod.max_witness_ops(tmap))
            if forensics is None:
                return
            out["explain"] = {
                "first-anomaly-op": forensics["first_anomaly"]["op_index"],
                "witness-ops": len(forensics["witness"]["op_indices"]),
                "backend": forensics["backend"],
                "bisect-steps": forensics["bisect_steps"],
            }
            if test is not None:
                arts = explain_mod.write_artifacts(test, history,
                                                   forensics, opts=opts)
                if arts:
                    out["explain"]["artifacts"] = sorted(
                        str(k) for k in arts)
        except Exception:  # noqa: BLE001 — forensics never mask a verdict
            logger.exception("anomaly forensics failed")

    def _render(self, res, history, test) -> str | None:
        """linear.png into the test's store dir (checker.clj:205-212)."""
        if test is None:
            return None
        try:
            from jepsen_tpu import store
            from jepsen_tpu.checker.linear_report import render_failure
            path = str(store.path_mk(test, "linear.png"))
            return render_failure(history, res, path)
        except Exception:  # noqa: BLE001  rendering must not mask verdicts
            logger.exception("linear.png rendering failed")
            return None


def linearizable(model=None, **kw) -> Checker:
    return LinearizableChecker(model=model, **kw)


def check_stored(test_name: str, timestamp: str, store_dir: str = "store",
                 model=None, accelerator: str = "auto") -> dict:
    """Re-checks a STORED register run's linearizability, preferring the
    ``lin_*`` EventStream columns in its history.npz sidecar — no jsonl
    load, no re-encoding (the stored-column twin of
    elle.list_append.check_stored). The fast lane settles only VALID
    verdicts (via the transfer-matrix screen or the exact stream
    search); anything else — invalid (needs op context for the
    failure report), out-of-regime, missing sidecar — falls back to
    the jsonl history through the normal checker."""
    from jepsen_tpu import store
    from jepsen_tpu.checker.linear_encode import stream_from_columns
    from jepsen_tpu.models import CASRegister, cas_register_spec

    model = model if model is not None else CASRegister()
    cols = None
    if isinstance(model, CASRegister):
        try:
            cols = store.load_linear_columns(test_name, timestamp,
                                             store_dir)
        except Exception as e:  # noqa: BLE001 - damaged sidecar: use jsonl
            store.note_sidecar_load_failure(
                f"{test_name}/{timestamp} (lin_*)", e)
            cols = None
    if cols is not None:
        try:
            stream = stream_from_columns(cols)
            init_id = (0 if model.value is None
                       else stream.intern.id(model.value))
            spec = cas_register_spec(init_id)
            checker = LinearizableChecker(model=model,
                                          accelerator=accelerator)
            # the one dispatch check() uses — device threshold, matrix
            # screen, frontier kernel, native-first host lanes — so the
            # stored lane can't drift from the live one
            # explain=False: an invalid stored verdict falls back to the
            # jsonl full check below, which runs forensics itself — a
            # localization here would be paid for and discarded
            res = checker._search_stream(stream, cas_register_step_py,
                                         spec, checker.algorithm,
                                         accelerator, explain=False)
            res.algorithm += "(stored)"
            if res.valid is True:
                return checker._finish(res, [], None)
        except Exception:  # noqa: BLE001 - fast lane must never block
            logger.exception("stored-column linear re-check failed; "
                             "falling back to jsonl")
    history = store.load_history(test_name, timestamp, store_dir)
    checker = LinearizableChecker(model=model, accelerator=accelerator)
    return checker.check({"name": test_name, "start_time": timestamp,
                          "store_dir": store_dir}, history, {})
