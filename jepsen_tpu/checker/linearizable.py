"""Linearizability checker with an accelerator switch.

Reference surface: jepsen.checker/linearizable (checker.clj:185-216), which
dispatches on :algorithm to knossos's linear/wgl/competition searches. Here
the dispatch axes are:

* ``algorithm``: "wgl" (object-model DFS oracle), "jitlin" (int-encoded
  breadth-first search — the TPU kernel's CPU twin), or "auto".
* ``accelerator``: "cpu", "tpu" (any JAX device), or "auto" — the
  :accelerator option called for by BASELINE.json's north star. "auto" uses
  the device kernel for histories big enough to amortize compilation and
  falls back to CPU when the device frontier overflows (mirroring the
  reference's competition mode, checker.clj:199-203).

Failure output is truncated (the reference truncates :final-paths/:configs
to 10 because "Writing these can take *hours*", checker.clj:213-216).
"""
from __future__ import annotations

import logging
from typing import Any

from jepsen_tpu.checker import Checker
from jepsen_tpu.checker.linear_cpu import (
    LinearResult, cas_register_step_py, check_stream, wgl,
)
from jepsen_tpu.checker.linear_encode import encode_register_ops
from jepsen_tpu.models import CASRegister, Model

logger = logging.getLogger("jepsen.checker.linearizable")

# Histories below this many events run on CPU under accelerator="auto":
# kernel launch + compile isn't worth it.
AUTO_TPU_THRESHOLD = 512

# Failure reports re-run the exact CPU search to recover the dying
# frontier; skip that recovery for histories longer than this.
MAX_REPORT_EVENTS = 200_000


class LinearizableChecker(Checker):
    def __init__(
        self,
        model: Model | None = None,
        algorithm: str = "auto",
        accelerator: str = "auto",
        capacity: int = 256,
    ):
        self.model = model if model is not None else CASRegister()
        self.algorithm = algorithm
        self.accelerator = accelerator
        self.capacity = capacity
        self._kernel = None

    def _tpu_kernel(self):
        if self._kernel is None:
            from jepsen_tpu.ops.jitlin import JitLinKernel
            self._kernel = JitLinKernel()
        return self._kernel

    def check(self, test, history, opts):
        algorithm = opts.get("algorithm", self.algorithm)
        accelerator = opts.get("accelerator", self.accelerator)

        if algorithm == "wgl":
            return self._finish(wgl(history, self.model), history, test)

        # jitlin path: encode once, run on device or host
        if not isinstance(self.model, CASRegister):
            # only the register family has an int encoding so far
            return self._finish(wgl(history, self.model), history, test)
        stream = encode_register_ops(history)
        if accelerator == "cpu" or (
            accelerator == "auto" and len(stream) < AUTO_TPU_THRESHOLD
        ):
            res = None
            if algorithm in ("jitlin", "auto"):
                # native C++ search first (same algorithm, ~100x the
                # Python loop); falls back when unbuilt or >63 slots
                from jepsen_tpu.native import check_stream_native
                res = check_stream_native(stream)
                if res is not None and res.valid == "unknown":
                    res = None  # capacity blown: retry in Python (bignum)
                if res is None:
                    res = check_stream(stream)
            else:
                res = wgl(history, self.model)
            return self._finish(res, history, test, stream)

        # device path. For long histories over small value domains, the
        # block-composed transfer-matrix kernel settles the verdict with
        # far less sequential depth (MXU boolean matmuls over chunks);
        # the event scan remains the diagnostics path (died-at, peak).
        from jepsen_tpu.ops.jitlin import matrix_check, verdict
        m = matrix_check(stream)
        # accept only an exact matrix True: m[2] (inexact/oob) means a
        # state id escaped the intern range, so the verdict proves nothing
        if m is not None and m[0] and not m[2]:
            return self._finish(LinearResult(
                valid=True, failed_event=-1, failed_op_index=-1,
                configs_max=0, algorithm="jitlin-tpu-matrix"),
                history, test)
        alive, died, overflow, peak = self._tpu_kernel().check(
            stream, capacity=self.capacity
        )
        valid = verdict(alive, overflow)
        if valid == "unknown":
            # frontier overflowed K and died: retry with the exact CPU twin
            res = check_stream(stream)
            res.algorithm = "jitlin-cpu(fallback)"
            return self._finish(res, history, test, stream)
        res = LinearResult(
            valid=valid,
            failed_event=died,
            failed_op_index=int(stream.op_index[died]) if died >= 0 else -1,
            configs_max=peak,
            algorithm="jitlin-tpu",
        )
        return self._finish(res, history, test, stream)

    def _finish(self, res: LinearResult, history, test=None,
                stream=None) -> dict:
        out: dict[str, Any] = {
            "valid?": res.valid,
            "algorithm": res.algorithm,
            "configs-max": res.configs_max,
        }
        if res.valid is False and res.failed_op_index >= 0:
            i = res.failed_op_index
            lo = max(0, i - 5)
            out["failed-op"] = history[i] if i < len(history) else None
            out["context"] = history[lo : i + 1][-10:]
            # device verdicts carry no frontier detail: one exact CPU pass
            # recovers the dying configurations for the report (the
            # knossos :configs surface). Gated by length — the history was
            # routed to the device because host search may be slow, and a
            # report must never cost more than the verdict.
            if res.final_configs is None and stream is not None \
                    and len(stream) <= MAX_REPORT_EVENTS:
                try:
                    res2 = check_stream(stream)
                    if res2.valid is False:
                        res.final_configs = res2.final_configs
                except Exception:  # noqa: BLE001 report detail is optional
                    logger.exception("final-configs recovery failed")
            if res.final_configs is not None:
                out["final-configs"] = res.final_configs
            out["plot"] = self._render(res, history, test)
        return out

    def _render(self, res, history, test) -> str | None:
        """linear.png into the test's store dir (checker.clj:205-212)."""
        if test is None:
            return None
        try:
            from jepsen_tpu import store
            from jepsen_tpu.checker.linear_report import render_failure
            path = str(store.path_mk(test, "linear.png"))
            return render_failure(history, res, path)
        except Exception:  # noqa: BLE001  rendering must not mask verdicts
            logger.exception("linear.png rendering failed")
            return None


def linearizable(model=None, **kw) -> Checker:
    return LinearizableChecker(model=model, **kw)
