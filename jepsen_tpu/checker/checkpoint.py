"""Durable checker checkpoints: the check survives its own faults.

PRs 3/4 made the *run* crash-safe (WAL + ``analyze --recover``); this
module makes the *check* resumable. Long-running checks — the segmented
transfer-matrix chain (``ops/jitlin.matrix_check_segmented`` /
``segmented_check``) and the exact CPU frontier
(``checker/linear_cpu.FrontierSession``) — periodically persist their
tiny carry state to an fsynced ``check.ckpt`` under the run's store
dir, and ``analyze`` auto-resumes from a valid checkpoint instead of
restarting a minutes-long check from zero after a SIGKILL, preemption,
OOM, or device loss. Resumption is bit-identical to an uninterrupted
check: every carry here (a composed 0/1 operator product, a frontier
configuration set) is exact state the uninterrupted check would hold at
the same cut.

Checkpoint schema (one JSON document, atomic tmp+flush+fsync+rename):

* ``version`` — :data:`VERSION`; a reader that doesn't recognize it
  discards.
* ``kind`` — ``matrix`` (segmented transfer-matrix ``tot0`` carry),
  ``frontier`` (segmented event-scan carry: sparse mask/state pair or
  dense table), or ``frontier-session`` (the exact CPU frontier's
  configuration set).
* ``config`` — the knob/shape fingerprint the writer ran under
  (S, V, init state, variant/combine pins, segment size, ...). Any
  drift between writer and reader discards the checkpoint with a
  warning: a carry is only meaningful under the exact same encoding.
* ``events_done`` / ``segment`` — how far the consumed stream prefix
  reaches (an event index at a segment cut) and which segment wrote it.
* ``prefix_hash`` — sha256 over the encoded stream columns up to
  ``events_done``. Analyze re-encodes the history deterministically,
  so a matching hash proves the resumed check is consuming the same
  prefix the checkpoint summarizes; a mismatch (different run, edited
  history, recovered-then-grown WAL) discards rather than trusts.
* ``carry`` — the tiny resume state itself. 0/1 matrices (the matrix
  ``tot0``, the dense frontier table) are bit-packed; everything else
  rides plain JSON.

Validity rules (``load_resume``): version match, kind match, exact
``config`` match, ``events_done`` within the stream, prefix hash match.
Anything else is discarded — with a warning and the file cleared — and
the check restarts from zero; a checkpoint can delay a verdict, never
change one. ``resume_check: False`` (``analyze --no-resume-check``)
opts out of resuming entirely; ``check_ckpt_interval`` (seconds,
``<= 0`` disables, env twin ``JEPSEN_TPU_CHECK_CKPT_INTERVAL``)
throttles writing. Completed checks clear their checkpoint — a
surviving ``check.ckpt`` marks an interrupted check, and the web UI
lists it with the run's forensic artifacts (doc/robustness.md
"Resumable checks and the elastic mesh").
"""
from __future__ import annotations

import base64
import hashlib
import json
import logging
import os
import time
from pathlib import Path

import numpy as np

from jepsen_tpu import telemetry

logger = logging.getLogger("jepsen.checker.checkpoint")

CKPT_NAME = "check.ckpt"
VERSION = 1
DEFAULT_CKPT_INTERVAL_S = 5.0

# chunk size for the checkpointed exact CPU frontier: absorb this many
# events between checkpoint opportunities (the frontier can cut
# anywhere — its state carries the open ops)
FRONTIER_CHUNK_EVENTS = 65_536


# ---------------------------------------------------------------------------
# Knobs
# ---------------------------------------------------------------------------

def ckpt_interval(test) -> float | None:
    """Seconds between checkpoint persists (``check_ckpt_interval`` in
    the test map, env twin ``JEPSEN_TPU_CHECK_CKPT_INTERVAL``), or None
    when checkpointing is disabled (``<= 0``). Tolerantly coerced:
    garbage warns and falls back to the default — preflight (KNB001) is
    where strictness lives."""
    tmap = test if isinstance(test, dict) else {}
    raw = tmap.get("check_ckpt_interval")
    if raw is None:
        raw = os.environ.get("JEPSEN_TPU_CHECK_CKPT_INTERVAL")
    if raw is None or raw == "":
        return DEFAULT_CKPT_INTERVAL_S
    try:
        if isinstance(raw, bool):
            raise ValueError("bool is not an interval")
        v = float(raw)
    except (TypeError, ValueError):
        logger.warning("ignoring malformed check_ckpt_interval=%r; using "
                       "default %r", raw, DEFAULT_CKPT_INTERVAL_S)
        return DEFAULT_CKPT_INTERVAL_S
    return None if v <= 0 else v


def resume_enabled(test) -> bool:
    """Should a valid checkpoint be resumed from? ``resume_check`` in
    the test map (default True; ``analyze --no-resume-check`` sets it
    False), env twin ``JEPSEN_TPU_RESUME_CHECK``."""
    from jepsen_tpu.parallel import coerce_flag
    tmap = test if isinstance(test, dict) else {}
    flag = coerce_flag(tmap.get("resume_check"), knob="resume_check")
    if flag is not None:
        return flag
    env = coerce_flag(os.environ.get("JEPSEN_TPU_RESUME_CHECK"),
                      knob="JEPSEN_TPU_RESUME_CHECK")
    return True if env is None else env


# ---------------------------------------------------------------------------
# Stream prefix hashing
# ---------------------------------------------------------------------------

def step_identity(fn) -> str:
    """A stable identity for the model step function/spec a carry was
    built under — part of the checkpoint's config fingerprint, so a
    carry written under one model can never be resumed under another
    whose encoded columns happen to match (the prefix hash covers the
    columns, which are model-independent)."""
    mod = getattr(fn, "__module__", None) or type(fn).__module__
    qn = getattr(fn, "__qualname__", None) or type(fn).__qualname__
    return f"{mod}.{qn}"


def stream_prefix_hash(stream, end: int) -> str:
    """sha256 over the encoded stream columns up to event ``end``.

    The columns (kind/slot/f/a/b) are derived deterministically from
    the history — value ids assign in first-appearance order — so an
    identical history prefix hashes identically across re-encodes,
    while any divergence (different run, edited history) changes the
    hash. ``op_index`` is excluded: it is diagnostics, not checked
    content."""
    h = hashlib.sha256()
    for name in ("kind", "slot", "f", "a", "b"):
        col = np.ascontiguousarray(np.asarray(getattr(stream, name))[:end])
        h.update(col.tobytes())
        h.update(b"|")
    return h.hexdigest()


# ---------------------------------------------------------------------------
# Array codecs: carries are tiny, but 0/1 matrices pack 8x
# ---------------------------------------------------------------------------

def encode_array(a) -> dict:
    """A numpy (or device) array as a JSON-serializable dict. Arrays
    whose entries are exactly 0/1 — the matrix ``tot0`` product, the
    dense frontier table — pack to one bit per entry."""
    a = np.asarray(a)
    if a.dtype == bool or (a.size and
                           np.isin(a.astype(np.float32), (0.0, 1.0)).all()) \
            or (not a.size):
        bits = np.packbits((a.astype(np.float32) > 0).reshape(-1)
                           if a.dtype != bool else a.reshape(-1))
        return {"enc": "bits", "shape": list(a.shape),
                "b64": base64.b64encode(bits.tobytes()).decode("ascii")}
    a = np.ascontiguousarray(a)
    return {"enc": "raw", "shape": list(a.shape), "dtype": str(a.dtype),
            "b64": base64.b64encode(a.tobytes()).decode("ascii")}


def decode_array(d: dict) -> np.ndarray:
    raw = base64.b64decode(d["b64"])
    shape = tuple(int(x) for x in d["shape"])
    if d["enc"] == "bits":
        n = int(np.prod(shape)) if shape else 1
        bits = np.unpackbits(np.frombuffer(raw, np.uint8), count=n)
        return bits.reshape(shape).astype(np.float32)
    return np.frombuffer(raw, dtype=np.dtype(d["dtype"])).reshape(shape)


# ---------------------------------------------------------------------------
# The store
# ---------------------------------------------------------------------------

class CheckpointStore:  # durability: fsync (via utils.atomic_write_json)
    """One run's ``check.ckpt``: interval-gated atomic persists of a
    resumable check's carry state.

    ``maybe_save`` takes a zero-arg state builder so the (small) cost
    of materializing the carry on host — a device sync for the matrix
    ``tot0`` — is only paid when the interval has actually elapsed.
    The interval clock starts at construction, so a check shorter than
    one interval writes nothing."""

    def __init__(self, path, interval_s: float | None = DEFAULT_CKPT_INTERVAL_S,
                 resume: bool = True, guard=None):
        self.path = Path(path)
        self.interval_s = interval_s
        self.resume = resume
        # guard() -> bool: fencing hook for leased fleet checking
        # (doc/robustness.md "Fleet HA") — re-checked immediately before
        # every persist, so a checker whose run lease went stale cannot
        # overwrite its adopter's checkpoint with an older carry. None
        # (the single-host default) never fences.
        self.guard = guard
        self.fenced = False
        self._last_save = time.monotonic()
        self._last_events = 0
        self.writes = 0

    # -- writing --------------------------------------------------------

    def due(self) -> bool:
        return (self.interval_s is not None
                and time.monotonic() - self._last_save >= self.interval_s)

    def maybe_save(self, make_state, events_done: int) -> bool:
        """Persists ``make_state()`` when the write interval has
        elapsed. Always updates the staleness gauge (ops consumed since
        the last durable checkpoint)."""
        reg = telemetry.get_registry()
        if reg.enabled:
            reg.gauge("checker_ckpt_staleness_ops",
                      "ops consumed since the last durable checker "
                      "checkpoint").set(max(0, events_done
                                           - self._last_events))
        if not self.due():
            return False
        try:
            state = make_state()
        except Exception:  # noqa: BLE001 — checkpointing never fails a check
            logger.exception("checkpoint state build failed; skipping")
            return False
        return self.save(state, events_done=events_done)

    def save(self, state: dict, events_done: int | None = None) -> bool:
        from jepsen_tpu.utils import atomic_write_json
        if self.guard is not None and not self.guard():
            self.fenced = True
            logger.warning("checkpoint write to %s fenced: the run "
                           "lease went stale (a newer epoch owns it)",
                           self.path)
            return False
        doc = dict(state)
        doc.setdefault("version", VERSION)
        doc["wrote_at"] = time.time()
        try:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            atomic_write_json(self.path, doc)
        except Exception:  # noqa: BLE001 — a full disk must not fail the check
            logger.exception("checker checkpoint write failed; continuing "
                             "unresumably")
            return False
        self._last_save = time.monotonic()
        if events_done is not None:
            self._last_events = int(events_done)
        self.writes += 1
        reg = telemetry.get_registry()
        if reg.enabled:
            reg.counter("checker_ckpt_writes_total",
                        "durable checker checkpoint persists").inc()
            reg.gauge("checker_ckpt_staleness_ops",
                      "ops consumed since the last durable checker "
                      "checkpoint").set(0)
        from jepsen_tpu import trace as trace_mod
        trace_mod.get_tracer().instant(
            trace_mod.TRACK_CHECKPOINT, "ckpt-write",
            args={"kind": str(state.get("kind")),
                  "events_done": events_done})
        return True

    # -- reading --------------------------------------------------------

    def load(self) -> dict | None:
        try:
            with open(self.path, encoding="utf-8") as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def clear(self) -> None:
        """Removes the checkpoint — a completed check must not leave a
        stale carry for the next analyze to trust."""
        try:
            self.path.unlink(missing_ok=True)
        except OSError:
            logger.exception("couldn't clear %s", self.path)


def count_resume(source: str) -> None:
    """``checker_resume_total{source}``: a check resumed from a durable
    checkpoint (``ckpt``) or an in-process carry threaded across a
    ladder demotion (``carry``)."""
    reg = telemetry.get_registry()
    if reg.enabled:
        reg.counter("checker_resume_total",
                    "checks resumed instead of restarted, by source",
                    labels=("source",)).inc(source=source)
    from jepsen_tpu import trace as trace_mod
    trace_mod.get_tracer().instant(trace_mod.TRACK_CHECKPOINT,
                                   "ckpt-resume",
                                   args={"source": source})


def load_resume(store: CheckpointStore | None, kind: str, config: dict,
                stream) -> dict | None:
    """The validated resume state for ``stream``, or None.

    Validity: version + kind + exact config match, ``events_done``
    within the stream, and the prefix hash over the re-encoded stream
    matching the writer's. Any mismatch discards the checkpoint (with
    a warning and the file cleared) — knob drift or a different
    history must restart, never compose over a foreign carry."""
    if store is None or not store.resume:
        return None
    state = store.load()
    if state is None:
        return None
    label = store.path
    if state.get("version") != VERSION or state.get("kind") != kind:
        logger.warning("discarding %s: version/kind mismatch (%r/%r vs "
                       "%r/%r)", label, state.get("version"),
                       state.get("kind"), VERSION, kind)
        store.clear()
        return None
    if state.get("config") != config:
        logger.warning("discarding %s: knob/config drift (%r vs %r)",
                       label, state.get("config"), config)
        store.clear()
        return None
    end = state.get("events_done")
    if not isinstance(end, int) or end < 0 or end > len(stream.kind):
        logger.warning("discarding %s: events_done=%r outside the stream",
                       label, end)
        store.clear()
        return None
    if stream_prefix_hash(stream, end) != state.get("prefix_hash"):
        logger.warning("discarding %s: consumed-prefix hash mismatch — "
                       "the stored carry summarizes a different history",
                       label)
        store.clear()
        return None
    return state


# ---------------------------------------------------------------------------
# Matrix-carry -> CPU-frontier handoff
# ---------------------------------------------------------------------------

def frontier_from_matrix_carry(carry: dict, step, init_state: int,
                               algorithm: str = "jitlin-cpu(resumed)"):
    """A :class:`~jepsen_tpu.checker.linear_cpu.FrontierSession` seeded
    from a segmented transfer-matrix carry, or None when the carry
    can't seed one.

    At a quiescent cut every live row of the composed operator product
    has mask 0 (each return's kill cleared its slot bit), so the
    frontier the CPU twin would hold at the same cut is exactly
    ``{(0, state) : tot0[0][0*V + state, init_state] > 0}`` — the
    operators ARE the frontier transition, pinned bit-identical by the
    matrix/CPU differentials. A carry with a live non-zero-mask row is
    not at a quiescent cut and is declined (the caller restarts)."""
    from jepsen_tpu.checker.linear_cpu import FrontierSession
    try:
        tot = np.asarray(carry["tot0"], dtype=np.float32)
        V = int(carry["V"])
        events_done = int(carry["events_done"])
    except (KeyError, TypeError, ValueError):
        return None
    mv = tot.shape[-1]
    vec = tot.reshape(-1, mv, mv)[0][:, init_state]
    live = np.nonzero(vec > 0)[0]
    if live.size == 0:
        return None  # dead carry: the matrix verdict already settled it
    if (live // V != 0).any():
        logger.warning("matrix carry at event %d is not at a quiescent "
                       "cut; declining the frontier handoff", events_done)
        return None
    fs = FrontierSession(step=step, init_state=init_state,
                         algorithm=algorithm)
    fs.configs = {(0, int(r % V)) for r in live}
    fs.events_absorbed = events_done
    return fs


# ---------------------------------------------------------------------------
# Checkpointed exact CPU frontier
# ---------------------------------------------------------------------------

def checkpointed_check_stream(stream, step, init_state: int,
                              store: CheckpointStore,
                              algorithm: str = "jitlin-cpu",
                              session=None):
    """The exact CPU frontier check with periodic durable checkpoints:
    absorbs the stream in :data:`FRONTIER_CHUNK_EVENTS` chunks through
    a resumable :class:`FrontierSession`, persisting the session
    snapshot between chunks when the write interval elapses, and
    resuming a valid ``frontier-session`` checkpoint instead of
    starting over. Bit-identical to a one-shot ``check_stream`` (the
    session IS the one-shot loop; chunk cuts carry the open-op state).
    ``session`` overrides the starting session (a carry handoff)."""
    from jepsen_tpu.checker.linear_cpu import FrontierSession
    config = {"path": "frontier-cpu", "init_state": int(init_state),
              "algorithm": algorithm, "step": step_identity(step)}
    fs = session
    if fs is None:
        state = load_resume(store, "frontier-session", config, stream)
        if state is not None:
            fs = FrontierSession.restore(state.get("carry") or {},
                                         step=step, init_state=init_state,
                                         algorithm=algorithm)
            if fs is not None:
                count_resume("ckpt")
                logger.info("resuming exact CPU frontier from %s at "
                            "event %d/%d", store.path,
                            fs.events_absorbed, len(stream.kind))
    if fs is None:
        fs = FrontierSession(step=step, init_state=init_state,
                             algorithm=algorithm)
    n = len(stream.kind)
    pos = fs.events_absorbed
    while pos < n:
        end = min(n, pos + FRONTIER_CHUNK_EVENTS)
        res = fs.absorb(stream, start=pos, end=end)
        pos = end
        if res.valid is False:
            break
        if pos < n:
            def make_state(fs=fs, pos=pos):
                snap = fs.snapshot()
                if snap is None:
                    raise ValueError("frontier session not snapshotable")
                return {"kind": "frontier-session", "config": config,
                        "events_done": pos, "segment": pos
                        // FRONTIER_CHUNK_EVENTS,
                        "prefix_hash": stream_prefix_hash(stream, pos),
                        "carry": snap}
            store.maybe_save(make_state, pos)
    return fs.result()
