"""Checker protocol + built-in O(n) checkers (reference: jepsen/src/jepsen/checker.clj).

A checker validates a history: ``check(test, history, opts) -> {"valid?": ...}``
where valid? is True, False, or "unknown" (checker.clj:52-67). Exceptions
degrade to unknown rather than crashing the run (check_safe, :74-85).
Checkers compose into named maps evaluated in parallel (:87-99).

The compute-heavy checkers (linearizable, Elle txn anomalies) live in
sibling modules with CPU-oracle and TPU backends; everything here is a
single host-side pass over the history.
"""
from __future__ import annotations

import logging
import re
import threading
from collections import Counter as MultiSet
from collections import defaultdict
from typing import Any

from jepsen_tpu import history as h
from jepsen_tpu.utils import bounded_pmap, fraction, quantile

logger = logging.getLogger("jepsen.checker")

VALID_PRIORITY = {False: 0, "unknown": 1, True: 2}


def merge_valid(valids) -> Any:
    """false > unknown > true (checker.clj:29-50)."""
    result = True
    for v in valids:
        v = "unknown" if v == "unknown" else bool(v) if isinstance(v, bool) else v
        if VALID_PRIORITY.get(v, 1) < VALID_PRIORITY.get(result, 1):
            result = v
    return result


class Checker:
    def check(self, test: dict, history: list[dict], opts: dict) -> dict:
        raise NotImplementedError

    def name(self) -> str:
        return type(self).__name__


def check_safe(checker: Checker, test: dict, history: list[dict], opts: dict | None = None) -> dict:
    """Exceptions become {'valid?': 'unknown'} (checker.clj:74-85)."""
    try:
        return checker.check(test, history, opts or {})
    except Exception as e:  # noqa: BLE001
        logger.exception("checker %s crashed", checker.name())
        return {"valid?": "unknown", "error": repr(e)}


class Compose(Checker):
    """A map of named checkers run in parallel; overall valid? merges
    (checker.clj:87-99)."""

    def __init__(self, checkers: dict[str, Checker]):
        self.checkers = checkers

    def check(self, test, history, opts):
        names = list(self.checkers)
        results = bounded_pmap(
            lambda n: check_safe(self.checkers[n], test, history, opts), names
        )
        by_name = dict(zip(names, results))
        return {
            "valid?": merge_valid(r.get("valid?") for r in results),
            **by_name,
        }


def compose(checkers: dict[str, Checker]) -> Checker:
    return Compose(checkers)


class ConcurrencyLimit(Checker):
    """Limits concurrent executions of a memory-hungry checker via a
    semaphore (checker.clj:101-116)."""

    _sems: dict[int, threading.Semaphore] = {}
    _lock = threading.Lock()

    def __init__(self, limit: int, checker: Checker):
        self.limit = limit
        self.checker = checker
        with ConcurrencyLimit._lock:
            self._sem = ConcurrencyLimit._sems.setdefault(limit, threading.Semaphore(limit))

    def check(self, test, history, opts):
        with self._sem:
            return self.checker.check(test, history, opts)


class Noop(Checker):
    """Always valid (checker.clj:68-72)."""

    def check(self, test, history, opts):
        return {"valid?": True}


class UnbridledOptimism(Checker):
    """It's valid! (checker.clj:118-122)"""

    def check(self, test, history, opts):
        return {"valid?": True}


class UnhandledExceptions(Checker):
    """Aggregates ops with errors/exceptions by frequency
    (checker.clj:124-151). Informational: always valid."""

    def check(self, test, history, opts):
        groups: dict[Any, list] = defaultdict(list)
        for op in history:
            if op.get("exception") is not None or (
                op.get("type") in ("info", "fail") and op.get("error") is not None
            ):
                key = (op.get("f"), _freeze(op.get("error")), _freeze(op.get("exception")))
                groups[key].append(op)
        exceptions = sorted(
            (
                {"f": k[0], "error": ops_[0].get("error"),
                 "exception": ops_[0].get("exception"), "count": len(ops_),
                 "example": ops_[0]}
                for k, ops_ in groups.items()
            ),
            key=lambda m: -m["count"],
        )
        return {"valid?": True, "exceptions": exceptions}


def _freeze(x):
    if isinstance(x, dict):
        return tuple(sorted((k, _freeze(v)) for k, v in x.items()))
    if isinstance(x, (list, tuple)):
        return tuple(_freeze(v) for v in x)
    if isinstance(x, set):
        return frozenset(_freeze(v) for v in x)
    return x


class Stats(Checker):
    """ok/fail/info counts overall and by :f; valid iff every :f saw at
    least one ok (checker.clj:153-183). ``ungated_fs`` exempts specific
    op fs from the validity gate (counts still reported) — for probes
    where ONE op type is expected to fail en masse while the rest must
    still work (e.g. the crate dirty-read generator aims reads at
    in-flight writes; the reference composes only {dirty-read, perf}
    there, crate/dirty_read.clj:245-247 — but a blanket exemption
    would also mask e.g. every write failing)."""

    def __init__(self, ungated_fs=()):
        self.ungated_fs = frozenset(ungated_fs or ())

    def check(self, test, history, opts):
        def summarize(ops):
            c = MultiSet(op.get("type") for op in ops)
            ok, fail, info = c.get("ok", 0), c.get("fail", 0), c.get("info", 0)
            n = ok + fail + info
            return {
                "count": n, "ok-count": ok, "fail-count": fail, "info-count": info,
                "valid?": ok > 0,
            }

        completions = [op for op in history
                       if op.get("type") in ("ok", "fail", "info")
                       and h.is_client_op(op)]
        by_f = defaultdict(list)
        for op in completions:
            by_f[op.get("f")].append(op)
        by_f_stats = {f: summarize(ops_) for f, ops_ in by_f.items()}
        return {
            **summarize(completions),
            "by-f": by_f_stats,
            "valid?": merge_valid(
                [s["valid?"] for f, s in by_f_stats.items()
                 if f not in self.ungated_fs] or [True]),
        }


class SetChecker(Checker):
    """Grow-only set: :add ops then a final :read of the full set
    (checker.clj:240-291)."""

    def check(self, test, history, opts):
        attempts, adds = set(), set()
        final_read = None
        for op in history:
            f, typ, v = op.get("f"), op.get("type"), op.get("value")
            if f == "add":
                if typ == "invoke":
                    attempts.add(v)
                elif typ == "ok":
                    adds.add(v)
            elif f == "read" and typ == "ok":
                final_read = set(v)
        if final_read is None:
            return {"valid?": "unknown", "error": "Set was never read"}
        # The OK set is every read value that we tried to add
        ok = final_read & attempts
        # Unexpected values are those we never tried to add
        unexpected = final_read - attempts
        # Lost records are those we acknowledged but weren't read
        lost = adds - final_read
        # Recovered records are those we weren't sure about and that showed up
        recovered = ok - adds
        return {
            "valid?": not lost and not unexpected,
            "attempt-count": len(attempts),
            "acknowledged-count": len(adds),
            "ok-count": len(ok),
            "lost-count": len(lost),
            "unexpected-count": len(unexpected),
            "recovered-count": len(recovered),
            "ok": sorted(ok, key=repr),
            "lost": sorted(lost, key=repr),
            "unexpected": sorted(unexpected, key=repr),
            "recovered": sorted(recovered, key=repr),
        }


class SetFullChecker(Checker):
    """Full set analysis: every element's visibility lifecycle across *all*
    reads, not just the final one (checker.clj:294-592).

    Each added element ends up :stable (present in the final read and every
    read after it became known), :lost (known, then absent from some later
    read and never seen again), or :never-read. Stale reads (absent after
    known, but present again later) violate linearizability when the
    linearizable option is set. Also reports visibility latency quantiles.

    With accelerator 'auto'/'tpu', the history becomes one dense
    reads x elements membership matrix and every element's verdict is
    computed at once on device (jepsen_tpu.ops.setscan, BASELINE
    config 4); 'cpu' keeps the pure-Python per-element walk as the
    differential oracle.
    """

    def __init__(self, linearizable: bool = False, accelerator: str = "cpu"):
        self.linearizable = linearizable
        self.accelerator = accelerator

    def check(self, test, history, opts):
        accelerator = opts.get("accelerator", self.accelerator)
        fallback = False
        if accelerator in ("auto", "tpu"):
            try:
                return self._check_device(test, history, opts)
            except Exception:  # noqa: BLE001  device path is an optimization
                if accelerator == "tpu":
                    raise
                # visible, counted fallback: a silent one would hide a
                # perf regression behind identical-looking results
                logger.warning("set-full device path failed; falling back "
                               "to CPU", exc_info=True)
                fallback = True
        out = self._check_cpu(test, history, opts)
        if fallback:
            out["device-fallback"] = True
        return out

    def _check_device(self, test, history, opts):
        from jepsen_tpu.ops import setscan

        # the membership-matrix encode is a history-IR view
        # (history_ir.views.set_full_columns — moved there from this
        # method), memoized per run through the shared IR when one is
        # attachable, so composed set checkers encode once
        from jepsen_tpu import history_ir
        from jepsen_tpu.history_ir import views as ir_views
        ir = history_ir.of(test, history)
        enc = (ir_views.set_membership(ir) if ir is not None
               else ir_views.set_full_columns(history))
        if "error" in enc:
            return {"valid?": "unknown", "error": enc["error"]}
        member = enc["member"]
        read_t, invoke_t = enc["read_t"], enc["invoke_t"]
        ok_t, has_ok, els = enc["ok_t"], enc["has_ok"], enc["els"]
        E = len(els)
        code, stale, latency = setscan.classify_elements(
            member, read_t, invoke_t, ok_t, has_ok)

        lost = [els[j] for j in range(E) if code[j] == setscan.LOST]
        never_read = [els[j] for j in range(E)
                      if code[j] == setscan.NEVER_READ]
        stale_els = [els[j] for j in range(E) if stale[j]]
        stable_lat = sorted(float(latency[j]) for j in range(E)
                            if code[j] == setscan.STABLE)
        latencies = ({q: quantile(stable_lat, q)
                      for q in (0.0, 0.5, 0.99, 1.0)} if stable_lat else {})
        valid = not lost
        if self.linearizable and stale_els:
            valid = False
        return {
            "valid?": valid,
            "attempt-count": E,
            "stable-count": sum(1 for j in range(E)
                                if code[j] == setscan.STABLE),
            "lost-count": len(lost),
            "lost": sorted(lost, key=repr)[:100],
            "never-read-count": len(never_read),
            "never-read": sorted(never_read, key=repr)[:100],
            "stale-count": len(stale_els),
            "stale": sorted(stale_els, key=repr)[:100],
            "stable-latencies": latencies,
        }

    def _check_cpu(self, test, history, opts):
        adds: dict[Any, dict] = {}   # element -> {invoke_time, ok_time}
        reads: list[tuple[int, int, set]] = []  # (invoke_time, index, value-set)
        pending_read_invokes: dict[Any, int] = {}
        for i, op in enumerate(history):
            f, typ, v, p = op.get("f"), op.get("type"), op.get("value"), op.get("process")
            t = op.get("time", i)
            if f == "add":
                if typ == "invoke":
                    adds.setdefault(v, {"invoke_time": t, "ok_time": None})
                elif typ == "ok":
                    if v in adds:
                        adds[v]["ok_time"] = t
                    else:
                        adds[v] = {"invoke_time": t, "ok_time": t}
            elif f == "read":
                if typ == "invoke":
                    pending_read_invokes[p] = t
                elif typ == "ok":
                    t0 = pending_read_invokes.pop(p, t)
                    reads.append((t0, i, set(v)))
        if not reads:
            return {"valid?": "unknown", "error": "Set was never read"}
        reads.sort()
        results = {}
        stable_latencies = []
        lost, never_read, stale = [], [], []
        for el, info in adds.items():
            known_time = info["ok_time"]
            present = [(t0, el in vs) for (t0, _, vs) in reads]
            first_seen = next((t0 for (t0, _, vs) in reads if el in vs), None)
            if known_time is None:
                known_time = first_seen
            if known_time is None:
                never_read.append(el)
                results[el] = "never-read"
                continue
            later = [(t0, p) for (t0, p) in present if t0 >= known_time]
            if not later:
                never_read.append(el)
                results[el] = "never-read"
                continue
            # last absence and last presence among later reads
            last_present = max((t0 for (t0, p) in later if p), default=None)
            last_absent = max((t0 for (t0, p) in later if not p), default=None)
            if last_present is None or (last_absent is not None and last_absent > last_present):
                lost.append(el)
                results[el] = "lost"
                continue
            if last_absent is not None:
                # absent after known, but came back: stale read
                stale.append(el)
            results[el] = "stable"
            # stable latency: time from add-ok to start of uninterrupted presence
            stable_from = known_time if last_absent is None else last_absent
            stable_latencies.append(max(0, stable_from - info["invoke_time"]))
        stable_count = sum(1 for v in results.values() if v == "stable")
        sl = sorted(stable_latencies)
        latencies = {q: quantile(sl, q) for q in (0.0, 0.5, 0.99, 1.0)} if sl else {}
        valid = not lost
        if self.linearizable and stale:
            valid = False
        return {
            "valid?": valid,
            "attempt-count": len(adds),
            "stable-count": stable_count,
            "lost-count": len(lost),
            "lost": sorted(lost, key=repr)[:100],
            "never-read-count": len(never_read),
            "never-read": sorted(never_read, key=repr)[:100],
            "stale-count": len(stale),
            "stale": sorted(stale, key=repr)[:100],
            "stable-latencies": latencies,
        }


class QueueChecker(Checker):
    """Model-based queue check: enqueues count from invocation (they may
    have happened even without an ack); every ok dequeue must be consistent
    with the model (checker.clj:218-238)."""

    def __init__(self, model):
        self.model = model

    def check(self, test, history, opts):
        from jepsen_tpu.models import is_inconsistent
        model = self.model
        error = None
        for op in history:
            f, typ = op.get("f"), op.get("type")
            if typ == "invoke" and f == "enqueue":
                m2 = model.step(op)
                if not is_inconsistent(m2):
                    model = m2
            elif typ == "ok" and f == "dequeue":
                m2 = model.step(op)
                if is_inconsistent(m2):
                    error = {"op": op, "error": m2.msg}
                    break
                model = m2
        if error:
            return {"valid?": False, "error": error}
        return {"valid?": True, "final-queue-size": _model_size(model)}


def _model_size(model):
    items = getattr(model, "items", None)
    if items is None:
        return None
    if isinstance(items, frozenset):
        return sum(n for _, n in items)
    return len(items)


def expand_queue_drain_ops(history: list[dict]) -> list[dict]:
    """Expands ``drain`` ops (value = list of drained elements) into
    synthetic dequeue invoke/ok pairs (checker.clj:594-626).

    Beyond the reference: a crashed (``info``) drain that carries a
    partial element list is expanded too — those elements were
    definitely consumed before the crash, and dropping them would
    produce false ``lost`` verdicts. A crashed drain with no element
    list is unsupported, as in the reference."""
    out: list[dict] = []
    for op in history:
        if op.get("f") != "drain":
            out.append(op)
            continue
        typ = op.get("type")
        if typ in ("invoke", "fail"):
            continue
        if typ == "ok" or (typ == "info"
                           and isinstance(op.get("value"), list)):
            for element in op.get("value") or []:
                out.append({**op, "type": "invoke", "f": "dequeue",
                            "value": None})
                out.append({**op, "type": "ok", "f": "dequeue",
                            "value": element})
        else:
            raise ValueError(f"crashed drain operation unsupported: {op!r}")
    return out


class TotalQueueChecker(Checker):
    """Multiset queue algebra: what goes in must come out
    (checker.clj:628-687). Ok ``drain`` ops are expanded into dequeues
    first, per the reference's total-queue."""

    def check(self, test, history, opts):
        history = expand_queue_drain_ops(history)
        attempts: MultiSet = MultiSet()
        enqueues: MultiSet = MultiSet()
        dequeues: MultiSet = MultiSet()
        for op in history:
            f, typ, v = op.get("f"), op.get("type"), op.get("value")
            if f == "enqueue":
                if typ == "invoke":
                    attempts[v] += 1
                elif typ == "ok":
                    enqueues[v] += 1
            elif f == "dequeue" and typ == "ok":
                dequeues[v] += 1
        ok = dequeues & attempts
        # dequeues of values we *never* tried to enqueue — records from
        # nowhere (full multiplicity, not just the excess)
        unexpected = MultiSet({v: n for v, n in dequeues.items()
                               if v not in attempts})
        # dequeues in excess of attempts, for values attempted at least
        # once: redelivery, not invalidity
        duplicated = dequeues - attempts - unexpected
        # acknowledged enqueues that never came out
        lost = enqueues - dequeues
        # dequeues whose enqueue was attempted but never acknowledged
        recovered = ok - enqueues
        return {
            "valid?": not lost and not unexpected,
            "attempt-count": sum(attempts.values()),
            "acknowledged-count": sum(enqueues.values()),
            "ok-count": sum(ok.values()),
            "unexpected-count": sum(unexpected.values()),
            "duplicated-count": sum(duplicated.values()),
            "lost-count": sum(lost.values()),
            "recovered-count": sum(recovered.values()),
            "lost": sorted(lost.elements(), key=repr)[:100],
            "unexpected": sorted(unexpected.elements(), key=repr)[:100],
            "duplicated": sorted(duplicated.elements(), key=repr)[:100],
            "recovered": sorted(recovered.elements(), key=repr)[:100],
        }


class UniqueIdsChecker(Checker):
    """All ok :generate ops must return distinct ids (checker.clj:689-734)."""

    def check(self, test, history, opts):
        attempted = 0
        acknowledged: MultiSet = MultiSet()
        for op in history:
            if op.get("f") == "generate":
                if op.get("type") == "invoke":
                    attempted += 1
                elif op.get("type") == "ok":
                    acknowledged[op.get("value")] += 1
        dups = {v: n for v, n in acknowledged.items() if n > 1}
        return {
            "valid?": not dups,
            "attempted-count": attempted,
            "acknowledged-count": sum(acknowledged.values()),
            "duplicated-count": len(dups),
            "duplicated": dict(sorted(dups.items(), key=lambda kv: -kv[1])[:100]),
            "range": [min(acknowledged, key=repr), max(acknowledged, key=repr)]
            if acknowledged else None,
        }


class CounterChecker(Checker):
    """PN-counter bounds check: each ok read must lie within [lower, upper]
    where indeterminate adds widen the window (checker.clj:737-795)."""

    def check(self, test, history, opts):
        lower = 0
        upper = 0
        reads_checked = 0
        errors = []
        # track pending adds so fails can be rolled back
        pending: dict[Any, float] = {}
        for op in history:
            f, typ, v, p = op.get("f"), op.get("type"), op.get("value"), op.get("process")
            if f == "add":
                if typ == "invoke":
                    pending[p] = v
                    if v >= 0:
                        upper += v
                    else:
                        lower += v
                elif typ == "ok":
                    v = pending.pop(p, v)
                    if v >= 0:
                        lower += v
                    else:
                        upper += v
                elif typ == "fail":
                    v = pending.pop(p, v)
                    if v >= 0:
                        upper -= v
                    else:
                        lower -= v
                # info: leave the window widened forever (indeterminate)
            elif f == "read" and typ == "ok":
                reads_checked += 1
                if not (lower <= v <= upper):
                    errors.append({"op": op, "expected": [lower, upper]})
        return {
            "valid?": not errors,
            "reads-checked": reads_checked,
            "errors": errors[:100],
            "final-bounds": [lower, upper],
        }


class LogFilePattern(Checker):
    """Greps downloaded node logs for a pattern; matches mean invalid
    (checker.clj:839-881)."""

    def __init__(self, pattern: str, filename: str):
        self.pattern = pattern
        self.filename = filename

    def check(self, test, history, opts):
        from jepsen_tpu import store
        matches = []
        for node in test.get("nodes", []):
            path = store.path(test, node, self.filename)
            try:
                with open(path, "r", errors="replace") as f:
                    for line in f:
                        if re.search(self.pattern, line):
                            matches.append({"node": node, "line": line.rstrip()})
            except FileNotFoundError:
                continue
        return {"valid?": not matches, "count": len(matches), "matches": matches[:100]}


# convenience constructors mirroring the reference's fns
def noop() -> Checker:
    return Noop()


def stats(ungated_fs=()) -> Checker:
    return Stats(ungated_fs)


def unhandled_exceptions() -> Checker:
    return UnhandledExceptions()


def set_checker() -> Checker:
    return SetChecker()


def set_full(linearizable: bool = False, accelerator: str = "cpu") -> Checker:
    return SetFullChecker(linearizable=linearizable, accelerator=accelerator)


def queue(model) -> Checker:
    return QueueChecker(model)


def total_queue() -> Checker:
    return TotalQueueChecker()


def unique_ids() -> Checker:
    return UniqueIdsChecker()


def counter() -> Checker:
    return CounterChecker()


def log_file_pattern(pattern: str, filename: str) -> Checker:
    return LogFilePattern(pattern, filename)


def unbridled_optimism() -> Checker:
    return UnbridledOptimism()


def latency_graph() -> Checker:
    from jepsen_tpu.checker.perf_plots import LatencyGraph
    return LatencyGraph()


def rate_graph() -> Checker:
    from jepsen_tpu.checker.perf_plots import RateGraph
    return RateGraph()


def perf() -> Checker:
    from jepsen_tpu.checker.perf_plots import perf as _perf
    return _perf()


def clock_plot() -> Checker:
    from jepsen_tpu.checker.clock import ClockPlot
    return ClockPlot()


def timeline_html() -> Checker:
    from jepsen_tpu.checker.timeline import Timeline
    return Timeline()
