"""Checker backend degradation ladder: fallback as policy, not scatter.

The linearizable checker accumulated three ad-hoc fallbacks (matrix
screen -> frontier kernel, frontier overflow -> exact CPU retry, native
C++ capacity miss -> Python stream search) with no shared accounting,
watchdog, or failure memory. :class:`BackendLadder` owns that chain —
sharded-matrix (multi-device mesh) -> pallas-matrix -> jitlin device
kernel -> native C++ -> CPU — as one policy object:

* **Soft demotion**: a backend may *decline* a dispatch (return ``None``
  or raise :class:`Unavailable`) — out of regime, capacity miss,
  library unbuilt. The ladder falls through and counts the demotion.
* **Resource exhaustion**: an XLA ``RESOURCE_EXHAUSTED`` (device OOM)
  or compile failure gets ONE adaptive retry with halved tile/batch
  sizes (the backend's ``shrink`` hook) before demoting.
* **Watchdog**: device dispatches run under a timeout — a hung TPU
  dispatch (dead tunnel, wedged runtime) demotes to the next backend
  instead of hanging the run. The stuck thread is abandoned (daemon),
  mirroring ``utils.timeout``.
* **Circuit breaker**: ``breaker_threshold`` *consecutive* hard
  failures trip a per-backend breaker; further dispatches skip the
  backend until :meth:`reset`. A flaky accelerator degrades a run to
  CPU once instead of eating the timeout on every check.
* **Telemetry**: ``checker_backend_demotions_total`` (by backend and
  reason), ``checker_watchdog_timeouts_total``,
  ``checker_backend_shrink_retries_total``, and a
  ``checker_circuit_open`` gauge flow through the registry
  (doc/observability.md, doc/robustness.md).

The terminal backend of a well-formed ladder always settles, so
:class:`LadderExhausted` indicates a configuration bug, not a bad
history.
"""
from __future__ import annotations

import logging
import os
import threading
from dataclasses import dataclass, field
from typing import Any, Callable

from jepsen_tpu import telemetry

logger = logging.getLogger("jepsen.checker.ladder")

# Device dispatches hung longer than this demote instead of blocking the
# run. 0 disables the watchdog (dispatch runs inline on the caller's
# thread — zero overhead, the pre-ladder behavior).
DEFAULT_WATCHDOG_S = float(os.environ.get("JEPSEN_TPU_WATCHDOG_S", "600"))
DEFAULT_BREAKER_THRESHOLD = int(
    os.environ.get("JEPSEN_TPU_BREAKER_THRESHOLD", "3"))


class Unavailable(Exception):
    """Raised by a backend to decline a dispatch (capability miss, out of
    regime). A quiet demotion: no failure is counted against the
    backend."""


class LadderExhausted(Exception):
    """Every backend declined or failed — the ladder was configured
    without a terminal always-settles backend."""


# Exception-text markers for device-memory exhaustion and XLA compile
# failures. jaxlib's XlaRuntimeError carries the gRPC-style status name
# in its message; we match text so the ladder needs no jax import (and
# tests can fake the failure with a plain RuntimeError).
_RESOURCE_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory",
                     "OOM ")
_COMPILE_MARKERS = ("XlaRuntimeError", "Compilation failure",
                    "compilation failed", "INTERNAL: Failed to compile")


def is_resource_exhausted(e: BaseException) -> bool:
    s = f"{type(e).__name__}: {e}"
    return any(m in s for m in _RESOURCE_MARKERS)


def is_compile_failure(e: BaseException) -> bool:
    s = f"{type(e).__name__}: {e}"
    return any(m in s for m in _COMPILE_MARKERS)


def _safe_pred(pred, e: BaseException) -> bool:
    try:
        return bool(pred(e))
    except Exception:  # noqa: BLE001 — a broken predicate is a no
        logger.exception("backend retryable predicate failed")
        return False


_TIMED_OUT = object()

# Exception-text markers for losing a device / a collective mid-dispatch
# — the elastic sharded rung's shrink trigger (doc/robustness.md
# "Resumable checks and the elastic mesh"). Text-matched like the
# resource markers so tests can fake the failure with a RuntimeError.
# Capability misses ("collectives are not implemented on this backend")
# are NOT losses: shrinking a mesh the backend can't run at any width
# only delays the demotion, so those demote immediately.
_DEVICE_LOSS_MARKERS = ("UNAVAILABLE", "device lost", "DEVICE_LOST",
                        "collective", "DATA_LOSS", "ABORTED",
                        "failed to connect")
_CAPABILITY_MARKERS = ("not implemented", "not supported", "unimplemented",
                       "UNIMPLEMENTED")


def is_device_loss(e: BaseException) -> bool:
    s = f"{type(e).__name__}: {e}"
    if any(m in s for m in _CAPABILITY_MARKERS):
        return False
    return any(m in s for m in _DEVICE_LOSS_MARKERS)


@dataclass
class Backend:
    """One rung. ``fn(ctx)`` returns a result, or ``None`` /raises
    :class:`Unavailable` to decline. ``eligible(ctx)`` gates routing
    (not counted as demotion — a host-regime dispatch never *attempts*
    the device rungs). ``shrink(ctx)`` halves the backend's tile/batch
    knobs in the shared context before a resource-exhaustion retry
    (the failing exception rides ``ctx["_shrink_error"]`` so an
    elastic rung can attribute a device loss); return False when
    nothing is left to halve. ``max_shrinks`` bounds the retries (1 =
    the classic single adaptive retry; the elastic sharded rung sets
    it to its shrink-ladder depth so an 8-device mesh can step 8→4→2
    before demoting). ``retryable`` extends the shrink-retry trigger
    beyond RESOURCE_EXHAUSTED/compile failures (e.g. device-loss /
    collective errors for the elastic mesh). ``device=True`` opts the
    rung into the watchdog."""

    name: str
    fn: Callable[[dict], Any]
    eligible: Callable[[dict], bool] = field(default=lambda ctx: True)
    shrink: Callable[[dict], bool] | None = None
    device: bool = False
    max_shrinks: int = 1
    retryable: Callable[[BaseException], bool] | None = None


class BackendLadder:
    def __init__(self, backends: list[Backend],
                 watchdog_s: float = DEFAULT_WATCHDOG_S,
                 breaker_threshold: int = DEFAULT_BREAKER_THRESHOLD):
        self.backends = list(backends)
        self.watchdog_s = watchdog_s
        self.breaker_threshold = breaker_threshold
        self._failures: dict[str, int] = {}
        self._broken: set[str] = set()
        # (backend, outcome) attempt regimes this ladder has entered —
        # the rung half of the fuzzer's checker-state coverage signal
        # (doc/robustness.md "Schedule fuzzing")
        self._cov_entries: set[tuple[str, str]] = set()
        self._lock = threading.Lock()

    def coverage_probe(self) -> dict:
        """Rung-regime coverage for the schedule fuzzer: every
        (backend, outcome) pair any attempt has produced on this
        ladder, as stable edge strings. A schedule that first drives a
        rung into shrink-retry or watchdog-timeout is exploring checker
        territory no prior corpus entry reached."""
        with self._lock:
            entries = sorted(self._cov_entries)
        return {"edges": ["rung:%s:%s" % e for e in entries]}

    # -- breaker state ------------------------------------------------------

    def broken(self) -> set[str]:
        with self._lock:
            return set(self._broken)

    def reset(self, backend: str | None = None) -> None:
        """Closes breakers (all, or one backend's) and zeroes failure
        counts — for tests and for operators who fixed the accelerator."""
        with self._lock:
            if backend is None:
                self._broken.clear()
                self._failures.clear()
            else:
                self._broken.discard(backend)
                self._failures.pop(backend, None)
        self._export_breaker()

    def _count_failure(self, name: str) -> None:
        with self._lock:
            n = self._failures.get(name, 0) + 1
            self._failures[name] = n
            tripped = (n >= self.breaker_threshold
                       and name not in self._broken)
            if tripped:
                self._broken.add(name)
        if tripped:
            logger.warning("checker backend %r circuit breaker tripped "
                           "after %d consecutive failures", name, n)
            reg = telemetry.get_registry()
            if reg.enabled:
                reg.event("checker-circuit-open", backend=name, failures=n)
            self._export_breaker()

    def _count_success(self, name: str) -> None:
        with self._lock:
            self._failures[name] = 0

    def _export_breaker(self) -> None:
        reg = telemetry.get_registry()
        if not reg.enabled:
            return
        g = reg.gauge("checker_circuit_open",
                      "1 while a backend's circuit breaker is open",
                      labels=("backend",))
        with self._lock:
            broken = set(self._broken)
        for b in self.backends:
            g.set(1.0 if b.name in broken else 0.0, backend=b.name)

    def _demote(self, name: str, reason: str) -> None:
        reg = telemetry.get_registry()
        if reg.enabled:
            reg.counter("checker_backend_demotions_total",
                        "ladder demotions, by backend and reason",
                        labels=("backend", "reason")
                        ).inc(backend=name, reason=reason)
        from jepsen_tpu import trace as trace_mod
        trace_mod.get_tracer().instant(
            trace_mod.TRACK_LADDER, "demote",
            args={"backend": name, "reason": reason})
        logger.info("checker backend %r demoted (%s)", name, reason)

    # -- dispatch -----------------------------------------------------------

    def _call(self, backend: Backend, ctx: dict) -> Any:
        """One invocation, under the watchdog for device rungs."""
        if not backend.device or not self.watchdog_s:
            return backend.fn(ctx)
        result: list = []
        error: list = []

        def run():
            try:
                result.append(backend.fn(ctx))
            except BaseException as e:  # noqa: BLE001
                error.append(e)

        t = threading.Thread(target=run, daemon=True,
                             name=f"jepsen-checker-{backend.name}")
        t.start()
        t.join(self.watchdog_s)
        if t.is_alive():
            return _TIMED_OUT
        if error:
            raise error[0]
        return result[0]

    def run(self, ctx: dict) -> tuple[Any, str]:
        """Dispatches ``ctx`` down the ladder; returns ``(result,
        backend_name)`` from the first rung that settles. ``ctx``
        accumulates ``_attempted`` — the rung names tried *before* the
        winner — so callers can label results (e.g. the CPU rung tags
        itself ``(fallback)`` only when reached by demotion from a
        device rung). Ineligible rungs are pure routing: neither
        attempted nor counted."""
        attempted: list[str] = ctx.setdefault("_attempted", [])
        last = self.backends[-1] if self.backends else None
        for backend in self.backends:
            try:
                if not backend.eligible(ctx):
                    continue
            except Exception:  # noqa: BLE001 — a broken gate is a decline
                logger.exception("eligibility probe for %r failed",
                                 backend.name)
                continue
            terminal = backend is last
            # the terminal rung is breaker-exempt: it has no fallback,
            # so skipping it would wedge every subsequent dispatch
            if not terminal and backend.name in self.broken():
                self._demote(backend.name, "circuit-open")
                attempted.append(backend.name)
                continue
            res = self._attempt(backend, ctx, terminal=terminal)
            if res is None:
                attempted.append(backend.name)
                continue
            self._count_success(backend.name)
            return res, backend.name
        raise LadderExhausted(
            f"no checker backend settled the dispatch "
            f"(attempted: {attempted})")

    def _attempt(self, backend: Backend, ctx: dict,
                 terminal: bool = False) -> Any:
        """One rung's dispatch: watchdog, single shrink retry, failure
        accounting. Returns the result, or None to demote. A hard
        failure in the ``terminal`` rung re-raises instead of demoting
        — there is nothing below it, and the caller's check_safe wants
        the real traceback (the pre-ladder semantics)."""
        from jepsen_tpu import trace as trace_mod
        tracer = trace_mod.get_tracer()
        reg = telemetry.get_registry()
        shrinks = 0
        t0_us = 0

        def rung_span(outcome: str) -> None:
            with self._lock:
                self._cov_entries.add((backend.name, outcome))
            # one self-contained slice per attempt (ph X, not B/E: a
            # watchdog-abandoned zombie attempt may still be emitting
            # when the next rung starts — X slices can't tear a pairing)
            if tracer.enabled:
                tracer.complete(trace_mod.TRACK_LADDER, "rung", t0_us,
                                trace_mod.now_us() - t0_us,
                                args={"backend": backend.name,
                                      "outcome": outcome})

        while True:
            # carry generation: rungs that thread a resume carry through
            # ctx (the segmented matrix chain) capture this at entry and
            # only publish carries while it is still theirs — a
            # watchdog-abandoned zombie's late writes can't clobber the
            # resumed rung's own progress (doc/robustness.md)
            ctx["_gen"] = ctx.get("_gen", 0) + 1
            t0_us = trace_mod.now_us() if tracer.enabled else 0
            try:
                res = self._call(backend, ctx)
            except Unavailable:
                rung_span("unavailable")
                self._demote(backend.name, "unavailable")
                return None
            except Exception as e:  # noqa: BLE001
                rex = is_resource_exhausted(e) or is_compile_failure(e)
                elastic = (backend.retryable is not None
                           and _safe_pred(backend.retryable, e))
                retryable = rex or elastic
                if retryable and shrinks < backend.max_shrinks \
                        and backend.shrink is not None:
                    ctx["_shrink_error"] = e
                    try:
                        can_shrink = backend.shrink(ctx)
                    except Exception:  # noqa: BLE001
                        can_shrink = False
                    finally:
                        ctx.pop("_shrink_error", None)
                    if can_shrink:
                        shrinks += 1
                        rung_span("shrink-retry")
                        if reg.enabled:
                            reg.counter(
                                "checker_backend_shrink_retries_total",
                                "resource-exhaustion retries with halved "
                                "tile/batch sizes", labels=("backend",)
                            ).inc(backend=backend.name)
                        logger.warning(
                            "backend %r failed retryably (%s); retrying "
                            "with shrunk sizes (%d/%d)", backend.name,
                            type(e).__name__, shrinks,
                            backend.max_shrinks)
                        continue
                rung_span("error")
                if terminal:
                    raise
                self._count_failure(backend.name)
                self._demote(backend.name,
                             "resource-exhausted" if rex
                             else "device-loss" if elastic else "error")
                logger.warning("checker backend %r failed: %r",
                               backend.name, e)
                return None
            if res is _TIMED_OUT:
                rung_span("watchdog-timeout")
                if reg.enabled:
                    reg.counter(
                        "checker_watchdog_timeouts_total",
                        "device dispatches abandoned by the watchdog",
                        labels=("backend",)).inc(backend=backend.name)
                self._count_failure(backend.name)
                self._demote(backend.name, "watchdog-timeout")
                return None
            if res is None:
                rung_span("declined")
                self._demote(backend.name, "declined")
                return None
            rung_span("settled")
            return res
