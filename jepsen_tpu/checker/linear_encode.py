"""History -> event-stream encoding for linearizability checking.

Shared front-end for both the CPU oracle (linear_cpu) and the TPU kernel
(jepsen_tpu.ops.jitlin). Capability-equivalent to the preprocessing knossos
performs before its linear/wgl searches (invoked from the reference at
jepsen/src/jepsen/checker.clj:185-216):

* ``fail`` ops never happened: the invoke/fail pair is dropped.
* ``info`` (crashed) ops may or may not have happened. Crashed *reads* have
  no effect and are dropped; crashed mutations stay open forever (their
  return is at infinity).
* Each live op is assigned a small *slot* (reused after return), so a
  configuration's "linearized pending ops" is a machine-word bitmask.

Values are interned to dense int32 ids (id 0 = None) so the model transition
is pure integer arithmetic on device.
"""
from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from jepsen_tpu.history import Intern
from jepsen_tpu.models import CAS_F_CAS, CAS_F_READ, CAS_F_WRITE

# event kinds
EV_INVOKE, EV_RETURN, EV_NOOP = 0, 1, 2


@dataclass
class EventStream:
    """Columnar event stream for one key's history."""

    kind: np.ndarray   # int8: EV_INVOKE / EV_RETURN / EV_NOOP
    slot: np.ndarray   # int32: pending-slot id
    f: np.ndarray      # int32: model f code
    a: np.ndarray      # int32: first interned arg
    b: np.ndarray      # int32: second interned arg
    op_index: np.ndarray  # int32: source history index (diagnostics)
    n_slots: int
    n_ops: int
    intern: Intern = field(default_factory=Intern)

    def __len__(self):
        return len(self.kind)


def encode_register_ops(history: list[dict], intern: Intern | None = None,
                        encode_args=None) -> EventStream:
    """Encodes a single-register r/w/cas history (the reference tutorial's
    etcd workload; BASELINE configs 1-3) into an EventStream.

    Op encodings (f, a, b):
      read v  -> (CAS_F_READ, id(v), 0); a read of None (id 0) matches any state
      write v -> (CAS_F_WRITE, id(v), 0)
      cas [u,v] -> (CAS_F_CAS, id(u), id(v))

    ``encode_args(op) -> (f, a, b)`` overrides the per-op encoding (the
    invoke/completion pairing, slot assignment, and crashed-read handling
    are model-independent — encode_multi_register_ops reuses them)."""
    intern = intern or Intern()
    kinds, slots, fs, as_, bs, idxs = [], [], [], [], [], []
    open_by_process: dict = {}   # process -> (slot, op)
    free_slots: list[int] = []
    next_slot = 0
    n_ops = 0

    if encode_args is None:
        def encode_args(op):
            f, v = op.get("f"), op.get("value")
            if f == "read":
                return CAS_F_READ, intern.id(v), 0
            if f == "write":
                return CAS_F_WRITE, intern.id(v), 0
            if f == "cas":
                u, w = v
                return CAS_F_CAS, intern.id(u), intern.id(w)
            raise ValueError(f"unknown register op {f!r}")

    # First pass: pair invokes with completions; find fail pairs and crashed
    # reads to drop; *complete* invocation values from their returns
    # (knossos history/complete semantics — a read's definitive value
    # arrives with its :ok, but the search consumes it at the invoke event).
    drop = set()
    open_inv: dict = {}
    completed_value: dict[int, object] = {}  # invoke idx -> definitive value
    for i, op in enumerate(history):
        p, typ = op.get("process"), op.get("type")
        if not isinstance(p, int) or p < 0:
            drop.add(i)
            continue
        if typ == "invoke":
            open_inv[p] = i
        elif typ == "fail":
            j = open_inv.pop(p, None)
            if j is not None:
                drop.add(j)
            drop.add(i)
        elif typ == "ok":
            j = open_inv.pop(p, None)
            if j is not None and op.get("value") is not None:
                completed_value[j] = op.get("value")
        elif typ == "info":
            j = open_inv.pop(p, None)
            drop.add(i)  # info completion itself is not an event
            if j is not None and history[j].get("f") == "read":
                drop.add(j)  # crashed reads have no effect
    # ops still open at the end of history (no completion at all) crash too
    for p, j in open_inv.items():
        if history[j].get("f") == "read":
            drop.add(j)

    for i, op in enumerate(history):
        if i in drop:
            continue
        p, typ = op.get("process"), op.get("type")
        if typ == "invoke":
            if free_slots:
                s = free_slots.pop()
            else:
                s = next_slot
                next_slot += 1
            open_by_process[p] = (s, i)
            inv = dict(op)
            if i in completed_value:
                inv["value"] = completed_value[i]
            fcode, a, b = encode_args(inv)
            kinds.append(EV_INVOKE)
            slots.append(s)
            fs.append(fcode)
            as_.append(a)
            bs.append(b)
            idxs.append(i)
            n_ops += 1
        elif typ == "ok":
            got = open_by_process.pop(p, None)
            if got is None:
                continue
            s, j = got
            kinds.append(EV_RETURN)
            slots.append(s)
            fs.append(0)
            as_.append(0)
            bs.append(0)
            idxs.append(i)
            free_slots.append(s)
        # info: no return event — the crashed op's slot stays occupied
        # forever, so it may be linearized at any later point or never.

    return EventStream(
        kind=np.array(kinds, dtype=np.int8),
        slot=np.array(slots, dtype=np.int32),
        f=np.array(fs, dtype=np.int32),
        a=np.array(as_, dtype=np.int32),
        b=np.array(bs, dtype=np.int32),
        op_index=np.array(idxs, dtype=np.int32),
        n_slots=max(next_slot, 1),
        n_ops=n_ops,
        intern=intern,
    )


def encode_multi_register_ops(history: list[dict], n_keys: int = 3,
                              n_values: int = 5) -> EventStream:
    """Encodes a multi-register txn history (the multi-key-acid workload,
    yugabyte/multi_key_acid.clj) for models.multi_register_spec: one op
    f="txn" whose value is [[f, k, v], ...] packs into base-(2V+2)
    per-key action digits of ``a`` (see the spec for the layout).

    The packed encoding holds one action per key, which covers the
    workload's generators exactly (they draw random nonempty *subsets*
    of the key range, so a txn never touches a key twice); a history
    with repeated keys in one txn raises ValueError and the checker
    falls back to the object-model search."""
    V, K = n_values, n_keys
    AB = 2 * V + 2

    def encode_args(op):
        if op.get("f") != "txn":
            raise ValueError(f"multi-register op must be txn, got "
                             f"{op.get('f')!r}")
        acts = [0] * K
        for f, k, v in op.get("value") or ():
            if not isinstance(k, int) or not (0 <= k < K):
                raise ValueError(f"key {k!r} outside [0, {K})")
            if acts[k] != 0:
                raise ValueError(f"txn touches key {k} twice")
            if f == "r":
                if v is None:
                    acts[k] = 1
                elif isinstance(v, int) and 0 <= v < V:
                    acts[k] = 2 + v
                else:
                    raise ValueError(f"read value {v!r} outside [0, {V})")
            elif f == "w":
                if not (isinstance(v, int) and 0 <= v < V):
                    raise ValueError(f"write value {v!r} outside [0, {V})")
                acts[k] = 2 + V + v
            else:
                raise ValueError(f"unknown micro-op {f!r}")
        a = 0
        for k in reversed(range(K)):
            a = a * AB + acts[k]
        return 0, a, 0

    stream = encode_register_ops(history, encode_args=encode_args)
    # interned-state count for kernel selection: the whole map space
    stream.intern = _DenseIntern((V + 1) ** K)
    return stream


class _DenseIntern:
    """Stands in for Intern when states are arithmetic encodings rather
    than interned values: only the state-count surface is needed."""

    def __init__(self, n: int):
        self._n = n

    def __len__(self):
        return self._n


def pad_streams(streams: list[EventStream], length: int | None = None) -> dict:
    """Stacks several per-key event streams into one padded batch for vmap
    (the jepsen.independent -> vmap mapping, SURVEY.md §2.6). Padding events
    are EV_NOOP."""
    if not streams:
        raise ValueError("no streams")
    E = length or max(len(s) for s in streams)
    S = max(s.n_slots for s in streams)
    B = len(streams)

    def pad(arr, fill, dtype):
        out = np.full((B, E), fill, dtype=dtype)
        for i, s in enumerate(streams):
            a = getattr(s, arr)
            out[i, : len(a)] = a
        return out

    return {
        "kind": pad("kind", EV_NOOP, np.int8),
        "slot": pad("slot", 0, np.int32),
        "f": pad("f", 0, np.int32),
        "a": pad("a", 0, np.int32),
        "b": pad("b", 0, np.int32),
        "n_slots": S,
    }


def stream_to_columns(stream: EventStream) -> dict | None:
    """The stream as plain persistable arrays (the store's ``lin_*``
    sidecar keys), or None when the intern table holds non-int values
    (beyond the id-0 None sentinel) — those can't round-trip through
    an int64 column."""
    vals = stream.intern.table[1:]
    if not all(type(v) is int for v in vals):
        return None
    return {
        "kind": np.asarray(stream.kind, np.int8),
        "slot": np.asarray(stream.slot, np.int32),
        "f": np.asarray(stream.f, np.int32),
        "a": np.asarray(stream.a, np.int32),
        "b": np.asarray(stream.b, np.int32),
        "op_index": np.asarray(stream.op_index, np.int32),
        "n_slots": np.int64(stream.n_slots),
        "n_ops": np.int64(stream.n_ops),
        "intern_table": np.asarray(vals, np.int64),
    }


def stream_from_columns(cols: dict) -> EventStream:
    """Rebuilds an EventStream from stream_to_columns' product."""
    intern = Intern()
    for v in np.asarray(cols["intern_table"]).tolist():
        intern.id(int(v))
    return EventStream(
        kind=np.asarray(cols["kind"], np.int8),
        slot=np.asarray(cols["slot"], np.int32),
        f=np.asarray(cols["f"], np.int32),
        a=np.asarray(cols["a"], np.int32),
        b=np.asarray(cols["b"], np.int32),
        op_index=np.asarray(cols["op_index"], np.int32),
        n_slots=int(cols["n_slots"]),
        n_ops=int(cols["n_ops"]),
        intern=intern,
    )
