"""History -> event-stream encoding for linearizability checking.

Shared front-end for both the CPU oracle (linear_cpu) and the TPU kernel
(jepsen_tpu.ops.jitlin). Capability-equivalent to the preprocessing knossos
performs before its linear/wgl searches (invoked from the reference at
jepsen/src/jepsen/checker.clj:185-216):

* ``fail`` ops never happened: the invoke/fail pair is dropped.
* ``info`` (crashed) ops may or may not have happened. Crashed *reads* have
  no effect and are dropped; crashed mutations stay open forever (their
  return is at infinity).
* Each live op is assigned a small *slot* (reused after return), so a
  configuration's "linearized pending ops" is a machine-word bitmask.

Values are interned to dense int32 ids (id 0 = None) so the model transition
is pure integer arithmetic on device.

The encoder bodies live in :mod:`jepsen_tpu.history_ir.views` (the one
canonical history IR — encode once, every checker a view); this module
keeps the :class:`EventStream` contract, the batching helper, and thin
delegates so existing call sites and the per-key ``independent`` lane
keep working unchanged. Stream <-> column serialization lives with the
rest of the IR sidecar (:mod:`jepsen_tpu.history_ir.sidecar`).
"""
from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from jepsen_tpu.history import Intern

# event kinds
EV_INVOKE, EV_RETURN, EV_NOOP = 0, 1, 2


@dataclass
class EventStream:
    """Columnar event stream for one key's history."""

    kind: np.ndarray   # int8: EV_INVOKE / EV_RETURN / EV_NOOP
    slot: np.ndarray   # int32: pending-slot id
    f: np.ndarray      # int32: model f code
    a: np.ndarray      # int32: first interned arg
    b: np.ndarray      # int32: second interned arg
    op_index: np.ndarray  # int32: source history index (diagnostics)
    n_slots: int
    n_ops: int
    intern: Intern = field(default_factory=Intern)

    def __len__(self):
        return len(self.kind)


def encode_register_ops(history, intern: Intern | None = None,
                        encode_args=None) -> EventStream:
    """Encodes a single-register r/w/cas history into an EventStream —
    see :func:`jepsen_tpu.history_ir.views.encode_register_ops` (the
    implementation; ``views.register_stream`` memoizes it per-run)."""
    from jepsen_tpu.history_ir import views
    return views.encode_register_ops(history, intern=intern,
                                     encode_args=encode_args)


def encode_multi_register_ops(history, n_keys: int = 3,
                              n_values: int = 5) -> EventStream:
    """Encodes a multi-register txn history for
    models.multi_register_spec — see
    :func:`jepsen_tpu.history_ir.views.encode_multi_register_ops`."""
    from jepsen_tpu.history_ir import views
    return views.encode_multi_register_ops(history, n_keys, n_values)


def pad_streams(streams: list[EventStream], length: int | None = None) -> dict:
    """Stacks several per-key event streams into one padded batch for vmap
    (the jepsen.independent -> vmap mapping, SURVEY.md §2.6). Padding events
    are EV_NOOP."""
    if not streams:
        raise ValueError("no streams")
    E = length or max(len(s) for s in streams)
    S = max(s.n_slots for s in streams)
    B = len(streams)

    def pad(arr, fill, dtype):
        out = np.full((B, E), fill, dtype=dtype)
        for i, s in enumerate(streams):
            a = getattr(s, arr)
            out[i, : len(a)] = a
        return out

    return {
        "kind": pad("kind", EV_NOOP, np.int8),
        "slot": pad("slot", 0, np.int32),
        "f": pad("f", 0, np.int32),
        "a": pad("a", 0, np.int32),
        "b": pad("b", 0, np.int32),
        "n_slots": S,
    }


def stream_to_columns(stream: EventStream) -> dict | None:
    """The stream as plain persistable arrays (the store's ``lin_*``
    sidecar keys) — see :mod:`jepsen_tpu.history_ir.sidecar`."""
    from jepsen_tpu.history_ir import sidecar
    return sidecar.stream_to_columns(stream)


def stream_from_columns(cols: dict) -> EventStream:
    """Rebuilds an EventStream from stream_to_columns' product."""
    from jepsen_tpu.history_ir import sidecar
    return sidecar.stream_from_columns(cols)
