"""Performance plots: latency and throughput graphs over the history, with
shaded nemesis-activity regions (reference: jepsen/src/jepsen/checker/perf.clj
— gnuplot there; matplotlib Agg here, no subprocess).

All computation is columnar: the history is reduced once to numpy arrays
(time, latency, f-id, type-id) and every graph is a vectorized
aggregation — the same struct-of-arrays discipline the checker core uses
(SURVEY.md §7 design stance).
"""
from __future__ import annotations

import logging
from collections import defaultdict
from typing import Any

import numpy as np

from jepsen_tpu import store
from jepsen_tpu.checker import Checker
from jepsen_tpu.utils import history_to_latencies, nemesis_intervals

logger = logging.getLogger("jepsen.checker.perf_plots")

DEFAULT_QUANTILES = (0.0, 0.5, 0.95, 0.99, 1.0)
NS = 1e9

TYPE_COLORS = {"ok": "#81BFFC", "info": "#FFA400", "fail": "#FF1E90"}
NEMESIS_SHADE = "#dddddd"


def invokes_with_latency(history: list[dict]) -> list[dict]:
    h = history_to_latencies(history)
    return [op for op in h
            if op.get("type") == "invoke" and op.get("process") != "nemesis"
            and "latency" in op]


def bucket_points(times_s: np.ndarray, dt: float) -> np.ndarray:
    """Bucket index for each time; bucket centers at (i + .5) * dt
    (perf.clj:21-49)."""
    return np.floor(times_s / dt).astype(np.int64)


def latencies_to_quantiles(times_s, lats_ms, dt: float,
                           qs=DEFAULT_QUANTILES) -> dict[float, list[tuple]]:
    """{q: [(bucket-center-time, latency-ms)...]} (perf.clj:63-85)."""
    if len(times_s) == 0:
        return {q: [] for q in qs}
    buckets = bucket_points(np.asarray(times_s), dt)
    out: dict[float, list[tuple]] = {q: [] for q in qs}
    for b in np.unique(buckets):
        sel = np.sort(np.asarray(lats_ms)[buckets == b])
        center = (b + 0.5) * dt
        n = len(sel)
        for q in qs:
            idx = min(n - 1, int(np.ceil(q * n)) - 1) if q > 0 else 0
            out[q].append((center, float(sel[max(0, idx)])))
    return out


def rate(history: list[dict], dt: float) -> dict[tuple, list[tuple]]:
    """{(f, type): [(bucket-center, ops/sec)...]} (perf.clj:127-141)."""
    groups: dict[tuple, list[float]] = defaultdict(list)
    for op in history:
        if op.get("process") == "nemesis":
            continue
        if op.get("type") not in ("ok", "fail", "info"):
            continue
        groups[(op.get("f"), op.get("type"))].append(op.get("time", 0) / NS)
    out = {}
    for k, ts in groups.items():
        arr = np.asarray(ts)
        buckets = bucket_points(arr, dt)
        out[k] = [((b + 0.5) * dt, float((buckets == b).sum()) / dt)
                  for b in np.unique(buckets)]
    return out


def nemesis_activity(history: list[dict]) -> list[tuple[float, float]]:
    """[(start-s, stop-s)] shaded regions (perf.clj:184-270)."""
    end = max((op.get("time", 0) for op in history), default=0) / NS
    out = []
    for start, stop in nemesis_intervals(history):
        t0 = start.get("time", 0) / NS
        t1 = stop.get("time", 0) / NS if stop is not None else end
        out.append((t0, t1))
    return out


def registry_fault_windows(test, history) -> list[dict]:
    """Fault windows from the durable ``faults.jsonl`` registry
    (nemesis/faults.py), in history time. This is what history-derived
    ``nemesis_intervals`` cannot see: fault-specific ``:f`` names
    (partition/heal, kill, bump...) classified by kind, and heals that
    happened OUTSIDE the history — nemesis teardown, the crash-path
    replay, ``cli heal`` — which otherwise read as never healed. []
    when the test can't address a store dir or the run has no
    registry."""
    if not test or not isinstance(test, dict) \
            or test.get("start_time") is None:
        return []
    try:
        from jepsen_tpu import store
        from jepsen_tpu.nemesis import faults as faults_mod
        rows = faults_mod.load_rows(
            store.path(test, faults_mod.FAULTS_NAME))
        if not rows:
            return []
        return faults_mod.history_windows(history, rows)
    except Exception:  # noqa: BLE001 — the overlay is best-effort
        logger.exception("registry fault-window overlay failed")
        return []


FAULT_SHADE = "#f7dcc4"


def _shade_nemesis(ax, history, test=None):
    for t0, t1 in nemesis_activity(history):
        ax.axvspan(t0, t1, color=NEMESIS_SHADE, zorder=0)
    # registry-derived windows layer on top in a warmer shade, labeled
    # by kind — crash-replayed heals appear here even though no history
    # op closes them (the satellite the durable registry buys the plots)
    windows = [w for w in registry_fault_windows(test, history)
               if w.get("start_time") is not None]
    # the open-window end needs a full history max(); a fault-free run
    # (the common case) must not pay that O(n) pass per plot
    end = (max((op.get("time", 0) for op in history), default=0) / NS
           if windows else 0.0)
    for w in windows:
        t0 = w["start_time"] / NS
        t1 = w["end_time"] / NS if w.get("end_time") is not None else end
        ax.axvspan(t0, t1, color=FAULT_SHADE, alpha=0.55, zorder=0)
        label = str(w.get("kind"))
        if w.get("healed") and w.get("end_time") is None:
            label += f" (healed via {w.get('via')})"
        ax.annotate(label, xy=(t0, 1.0), xycoords=("data", "axes fraction"),
                    fontsize=6, color="#a05010", rotation=90,
                    va="top", ha="left")


def _figure():
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    fig, ax = plt.subplots(figsize=(9, 5), dpi=100)
    return plt, fig, ax


POINT_LIMIT = 10_000  # per completion type; matches timeline.py's cap idea


def point_graph(test: dict, history: list[dict], output) -> None:
    """Raw latency scatter, colored by completion type (perf.clj:484-513).
    Downsampled evenly past POINT_LIMIT points per type — a 1M-op run
    must render in seconds, not choke matplotlib (r2 weak #5)."""
    plt, fig, ax = _figure()
    _shade_nemesis(ax, history, test)
    by_type: dict[str, list[tuple]] = defaultdict(list)
    for op in invokes_with_latency(history):
        comp = op.get("completion") or {}
        by_type[comp.get("type", "info")].append(
            (op.get("time", 0) / NS, op["latency"] / 1e6))
    downsampled = False
    for typ, pts in sorted(by_type.items()):
        arr = np.asarray(pts)
        if len(arr) > POINT_LIMIT:
            # stride-sample the bulk but KEEP the slow tail — the
            # outliers are what the scatter exists to reveal
            lat = arr[:, 1]
            tail = lat >= np.quantile(lat, 0.999)
            idx = np.zeros(len(arr), bool)
            idx[np.linspace(0, len(arr) - 1,
                            POINT_LIMIT).astype(np.int64)] = True
            arr = arr[idx | tail]
            downsampled = True
        ax.plot(arr[:, 0], arr[:, 1], ".", ms=3,
                color=TYPE_COLORS.get(typ, "#888888"), label=typ)
    ax.set_yscale("log")
    ax.set_xlabel("time (s)")
    ax.set_ylabel("latency (ms)")
    suffix = (f" (raw, downsampled to {POINT_LIMIT}/type)" if downsampled
              else " (raw)")
    ax.set_title(f"{test.get('name', 'test')} latency{suffix}")
    if by_type:
        ax.legend(loc="upper right", fontsize=8)
    fig.savefig(output, bbox_inches="tight")
    plt.close(fig)


def quantiles_graph(test: dict, history: list[dict], output,
                    dt: float = 10.0, qs=DEFAULT_QUANTILES) -> None:
    """Latency quantiles over time (perf.clj:513-559)."""
    plt, fig, ax = _figure()
    _shade_nemesis(ax, history, test)
    ops = invokes_with_latency(history)
    times = np.asarray([o.get("time", 0) / NS for o in ops])
    lats = np.asarray([o["latency"] / 1e6 for o in ops])
    for q, pts in sorted(latencies_to_quantiles(times, lats, dt, qs).items()):
        if pts:
            arr = np.asarray(pts)
            ax.plot(arr[:, 0], arr[:, 1], "-o", ms=3, label=f"q={q}")
    ax.set_yscale("log")
    ax.set_xlabel("time (s)")
    ax.set_ylabel("latency (ms)")
    ax.set_title(f"{test.get('name', 'test')} latency quantiles")
    if ax.get_legend_handles_labels()[0]:   # empty history: no artists
        ax.legend(loc="upper right", fontsize=8)
    fig.savefig(output, bbox_inches="tight")
    plt.close(fig)


def rate_graph(test: dict, history: list[dict], output,
               dt: float = 10.0) -> None:
    """Throughput per (f, completion-type) (perf.clj:559-599)."""
    plt, fig, ax = _figure()
    _shade_nemesis(ax, history, test)
    for (f, typ), pts in sorted(rate(history, dt).items(), key=str):
        arr = np.asarray(pts)
        ax.plot(arr[:, 0], arr[:, 1], "-",
                color=TYPE_COLORS.get(typ, "#888888"), alpha=0.9,
                label=f"{f} {typ}")
    ax.set_xlabel("time (s)")
    ax.set_ylabel("throughput (ops/s)")
    ax.set_title(f"{test.get('name', 'test')} rate")
    if ax.get_legend_handles_labels()[0]:   # empty history: no artists
        ax.legend(loc="upper right", fontsize=8)
    fig.savefig(output, bbox_inches="tight")
    plt.close(fig)


class LatencyGraph(Checker):
    """(checker.clj:797-811)"""

    def name(self):
        return "latency-graph"

    def check(self, test, history, opts):
        d = opts.get("subdirectory")
        point_graph(test, history,
                    store.path_mk(test, *filter(None, [d, "latency-raw.png"])))
        quantiles_graph(test, history,
                        store.path_mk(test, *filter(None,
                                                    [d, "latency-quantiles.png"])))
        return {"valid?": True}


class RateGraph(Checker):
    """(checker.clj:813-824)"""

    def name(self):
        return "rate-graph"

    def check(self, test, history, opts):
        d = opts.get("subdirectory")
        rate_graph(test, history,
                   store.path_mk(test, *filter(None, [d, "rate.png"])))
        return {"valid?": True}


def latency_graph() -> Checker:
    return LatencyGraph()


def rate_graph_checker() -> Checker:
    return RateGraph()


def perf() -> Checker:
    """latency + rate composed (checker.clj:826-829)."""
    from jepsen_tpu.checker import compose
    return compose({"latency-graph": latency_graph(),
                    "rate-graph": rate_graph_checker()})
