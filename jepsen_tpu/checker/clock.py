"""Clock-offset plot (reference: jepsen/src/jepsen/checker/clock.clj).

Nemesis ops may carry ``{"clock-offsets": {node: ms}}`` values (emitted by
the clock nemesis when it measures per-node wall-clock offsets); this
renders one line per node over test time.
"""
from __future__ import annotations

from collections import defaultdict

from jepsen_tpu import store
from jepsen_tpu.checker import Checker

NS = 1e9


def history_to_datasets(history: list[dict]) -> dict[str, list[tuple]]:
    """{node: [(time-s, offset-ms)...]} (clock.clj:13-34)."""
    out: dict[str, list[tuple]] = defaultdict(list)
    for op in history:
        v = op.get("value")
        offsets = None
        if isinstance(v, dict):
            offsets = v.get("clock-offsets")
        if op.get("f") == "check-offsets" and offsets is None and isinstance(v, dict):
            offsets = v
        if not isinstance(offsets, dict):
            continue
        t = op.get("time", 0) / NS
        for node, ms in offsets.items():
            if isinstance(ms, (int, float)):
                out[str(node)].append((t, float(ms)))
    return dict(out)


def plot(test: dict, history: list[dict], output) -> bool:
    """Renders clock-skew.png; returns False when no data (clock.clj:47-75)."""
    data = history_to_datasets(history)
    if not data:
        return False
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    fig, ax = plt.subplots(figsize=(9, 4), dpi=100)
    for node, pts in sorted(data.items()):
        arr = sorted(pts)
        ax.plot([t for t, _ in arr], [o for _, o in arr], "-o", ms=3,
                label=node)
    ax.set_xlabel("time (s)")
    ax.set_ylabel("clock offset (ms)")
    ax.set_title(f"{test.get('name', 'test')} clock offsets")
    ax.legend(loc="upper right", fontsize=8)
    fig.savefig(output, bbox_inches="tight")
    plt.close(fig)
    return True


class ClockPlot(Checker):
    def name(self):
        return "clock-plot"

    def check(self, test, history, opts):
        d = opts.get("subdirectory")
        plot(test, history,
             store.path_mk(test, *filter(None, [d, "clock-skew.png"])))
        return {"valid?": True}


def clock_plot() -> Checker:
    return ClockPlot()
