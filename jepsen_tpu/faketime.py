"""libfaketime wrappers: divergent per-node clock *rates*.

Reference: jepsen/src/jepsen/faketime.clj — wraps DB binaries in scripts
that LD_PRELOAD libfaketime with a per-node rate factor, so node clocks
drift apart continuously (rather than jumping, like the bump/strobe
nemesis). The reference builds a patched libfaketime from source
(faketime.clj:8-22); in sealed environments we use the distro's
libfaketime when present and raise otherwise (install is gated, not
assumed).
"""
from __future__ import annotations

import random

from jepsen_tpu import control
from jepsen_tpu.control import RemoteError
from jepsen_tpu.control.util import file_exists, write_file

LIB_PATHS = (
    "/usr/lib/x86_64-linux-gnu/faketime/libfaketime.so.1",
    "/usr/lib/faketime/libfaketime.so.1",
    "/usr/lib64/faketime/libfaketime.so.1",
    "/usr/local/lib/faketime/libfaketime.so.1",
)


def find_lib() -> str | None:
    for p in LIB_PATHS:
        if file_exists(p):
            return p
    return None


def local_lib() -> str | None:
    """LIB_PATHS probe on the CONTROL host's local filesystem — no
    remote session needed. Preflight uses it (via the clock-rate
    nemesis' ``preflight_diags``) to surface a missing distro
    libfaketime as a structured NEM006 diagnostic BEFORE a dummy/
    local-mode run starts, instead of a RemoteError mid-run."""
    import os.path
    for p in LIB_PATHS:
        if os.path.exists(p):
            return p
    return None


def install() -> str:
    """Ensures libfaketime is present (distro package), returning the
    library path (faketime.clj:8-22 capability)."""
    lib = find_lib()
    if lib:
        return lib
    try:
        from jepsen_tpu.os_setup import install as pkg_install
        pkg_install(["faketime", "libfaketime"])
    except RemoteError:
        pass
    lib = find_lib()
    if lib is None:
        raise RemoteError("libfaketime unavailable on this node "
                          "(install the faketime package)")
    return lib


def script(lib: str, rate: float) -> str:
    """A wrapper-script body applying a clock-rate factor
    (faketime.clj:24-34)."""
    return (
        "#!/bin/bash\n"
        f"FAKETIME=\"+0 x{rate:.4f}\" "
        f"LD_PRELOAD={lib} "
        "exec \"$(dirname \"$0\")/$(basename \"$0\").real\" \"$@\"\n")


def wrap(binary: str, rate: float, lib: str | None = None) -> None:
    """Moves binary to binary.real and installs a faketime wrapper in its
    place (faketime.clj wrap!/:36-55). Idempotent. ``lib`` pins the
    libfaketime path (skipping the install probe) — the clock-rate
    nemesis passes a preflight-validated path through."""
    lib = lib or install()
    if not file_exists(f"{binary}.real"):
        control.exec_("mv", binary, f"{binary}.real")
    write_file(script(lib, rate), binary)
    control.exec_("chmod", "+x", binary)


def unwrap(binary: str) -> None:
    """Restores the original binary (faketime.clj unwrap!)."""
    if file_exists(f"{binary}.real"):
        control.exec_("mv", f"{binary}.real", binary)


def rand_factor(rng: random.Random | None = None) -> float:
    """A clock-rate factor near 1 (faketime.clj:57-65)."""
    rng = rng or random
    return 1.0 + rng.uniform(-0.02, 0.02)
