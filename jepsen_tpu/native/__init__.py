"""Native (C++) components, built on demand with the system toolchain.

The reference's compute-critical searches are native too (JVM-JIT-compiled
knossos/elle, SURVEY.md §2.5); here the host-side hot kernel is a C++
shared library compiled with g++ at first use and loaded via ctypes —
no pybind11 dependency. The TPU path (ops/jitlin) is independent of this;
the native library is the *CPU* fast path and fallback oracle.
"""
from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import threading
from pathlib import Path

import numpy as np

logger = logging.getLogger("jepsen.native")

_HERE = Path(__file__).parent
_SRC = _HERE / "wgl.cpp"
_lock = threading.Lock()
_lib = None
_lib_failed = False

_SAN_FLAGS = ("-O1", "-g", "-fno-omit-frame-pointer",
              "-fsanitize=address,undefined", "-fno-sanitize-recover=all")


def _build_dir() -> Path:
    d = os.environ.get("JEPSEN_NATIVE_BUILD_DIR")
    return Path(d) if d else _HERE


def _so_path(san: bool = False) -> Path:
    src_hash = hashlib.sha256(_SRC.read_bytes()).hexdigest()[:16]
    stem = "_libwgl_san" if san else "_libwgl"
    return _build_dir() / f"{stem}-{src_hash}.so"


def build(force: bool = False, san: bool = False) -> Path:
    """Compiles wgl.cpp to a hash-stamped .so (cached). ``san`` builds
    the ASan+UBSan variant as a distinct artifact (doc/static-analysis.md
    "Native code")."""
    so = _so_path(san=san)
    if so.exists() and not force:
        return so
    so.parent.mkdir(parents=True, exist_ok=True)
    # per-process tmp name: concurrent builders must not interleave g++
    # output before the atomic publish
    tmp = so.with_suffix(f".so.tmp{os.getpid()}")
    perf = _SAN_FLAGS if san else ("-O3", "-march=native")
    cmd = ["g++", *perf, "-std=c++17", "-shared", "-fPIC",
           "-o", str(tmp), str(_SRC)]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    except subprocess.CalledProcessError as e:
        # -march=native can fail on exotic hosts; retry portable
        cmd = [c for c in cmd if c != "-march=native"]
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    os.replace(tmp, so)
    logger.info("built %s", so)
    return so


def _san_on() -> bool:
    return os.environ.get("JEPSEN_TPU_NATIVE_SAN", "").strip().lower() \
        in ("1", "true", "yes", "on")


def lib():
    """The loaded library, or None when unbuildable (no g++).

    Under ``JEPSEN_TPU_NATIVE_SAN=1`` (the sanitizer lane's child env,
    ``columnar_c.san_env()``) this loads the ASan+UBSan build instead —
    and REFUSES to serve the uninstrumented one when the ASan runtime
    is not preloaded: the lane must fall back to the Python search,
    never masquerade."""
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    with _lock:
        if _lib is not None or _lib_failed:
            return _lib
        try:
            san = _san_on()
            if san:
                from jepsen_tpu.native import columnar_c
                if not columnar_c._asan_mapped():
                    raise RuntimeError(
                        "san wgl requested but libasan is not preloaded")
            so = build(san=san)
            l = ctypes.CDLL(str(so))
            l.wgl_check.restype = ctypes.c_int
            l.wgl_check.argtypes = [
                ctypes.POINTER(ctypes.c_int8),
                ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_int32),
                ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
                ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64),
            ]
            _lib = l
        except Exception:  # noqa: BLE001
            logger.warning("native wgl unavailable; using Python search",
                           exc_info=True)
            _lib_failed = True
    return _lib


def available() -> bool:
    return lib() is not None


def check_stream_native(stream, init_state: int = 0,
                        max_configs: int = 20_000_000):
    """Runs the C++ search over an EventStream. Returns a LinearResult, or
    None when the native path can't handle the input (falls back to
    Python): >63 slots, unbuilt library."""
    from jepsen_tpu.checker.linear_cpu import LinearResult

    l = lib()
    if l is None:
        return None
    kind = np.ascontiguousarray(stream.kind, dtype=np.int8)
    slot = np.ascontiguousarray(stream.slot, dtype=np.int32)
    f = np.ascontiguousarray(stream.f, dtype=np.int32)
    a = np.ascontiguousarray(stream.a, dtype=np.int32)
    b = np.ascontiguousarray(stream.b, dtype=np.int32)
    stats = (ctypes.c_int64 * 3)()
    rc = l.wgl_check(
        kind.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
        slot.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        f.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        b.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        len(kind), init_state, 0, max_configs, stats)
    died, peak, _explored = stats[0], stats[1], stats[2]
    if rc == -2:
        return None
    if rc == -1:
        return LinearResult(valid="unknown", configs_max=int(peak),
                            algorithm="jitlin-native")
    valid = rc == 1
    return LinearResult(
        valid=valid,
        failed_event=int(died),
        failed_op_index=int(stream.op_index[died]) if died >= 0 else -1,
        configs_max=int(peak),
        algorithm="jitlin-native",
    )
