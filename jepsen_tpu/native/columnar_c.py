"""Build + load the C columnar-history parser as an extension module.

Same on-demand g++ pattern as the WGL library (`native/__init__.py`),
but this one needs the CPython C API (it walks PyObject histories), so
it is loaded as a real extension module via importlib rather than
ctypes. Unavailable toolchain degrades silently: callers get ``None``
and use the pure-Python/numpy path.

Sanitizer lane: ``mod(san=True)`` builds an ASan+UBSan variant
(Serebryany et al., USENIX ATC 2012) with its own hash-stamped name so
both variants coexist in the build dir. Loading it requires the ASan
runtime to be FIRST in the process's library list — GCC's libasan
aborts the whole process on a late dlopen otherwise — so the loader
refuses unless libasan is already mapped (``LD_PRELOAD``; see
``san_env()``), and the test/fuzz harnesses re-exec a child with that
environment rather than gambling the parent.
"""
from __future__ import annotations

import hashlib
import importlib.machinery
import importlib.util
import logging
import os
import subprocess
import sysconfig
import threading
from pathlib import Path

logger = logging.getLogger("jepsen.native")

_HERE = Path(__file__).parent
_SRC = _HERE / "columnar_ext.c"
_lock = threading.Lock()
_mod = None
_mod_failed = False
_mod_san = None
_mod_san_failed = False

PLAIN_FLAGS = ("-O3", "-march=native", "-shared", "-fPIC")
SAN_FLAGS = ("-O1", "-g", "-fno-omit-frame-pointer",
             "-fsanitize=address,undefined", "-fno-sanitize-recover=all",
             "-shared", "-fPIC")

# last attempted compile per variant, for the probe-failure log line
_last_cmd: dict[str, list] = {}


def _build_dir() -> Path:
    d = os.environ.get("JEPSEN_NATIVE_BUILD_DIR")
    return Path(d) if d else _HERE


def _so_path(san: bool = False) -> Path:
    src_hash = hashlib.sha256(_SRC.read_bytes()).hexdigest()[:16]
    stem = "_columnar_c_san" if san else "_columnar_c"
    return _build_dir() / f"{stem}-{src_hash}.so"


def build(force: bool = False, san: bool = False) -> Path:
    so = _so_path(san=san)
    if so.exists() and not force:
        return so
    so.parent.mkdir(parents=True, exist_ok=True)
    # per-process tmp name: concurrent builders (pytest workers, parallel
    # sessions) must not interleave g++ output before the atomic publish
    tmp = so.with_suffix(f".so.tmp{os.getpid()}")
    inc = sysconfig.get_paths()["include"]
    flags = SAN_FLAGS if san else PLAIN_FLAGS
    cmd = ["g++", *flags, f"-I{inc}", "-o", str(tmp), str(_SRC)]
    _last_cmd["san" if san else "plain"] = cmd
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    except subprocess.CalledProcessError:
        cmd = [c for c in cmd if c != "-march=native"]
        _last_cmd["san" if san else "plain"] = cmd
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    os.replace(tmp, so)
    logger.info("built %s", so)
    return so


def _asan_mapped() -> bool:
    """True when the ASan runtime is already loaded in THIS process
    (LD_PRELOAD). dlopen'ing a gcc -fsanitize=address .so without it
    doesn't fail politely — libasan calls Die() and takes the whole
    interpreter down, so the check must happen before the attempt."""
    try:
        with open("/proc/self/maps", "rb") as fh:
            return b"libasan" in fh.read()
    except OSError:
        return False


def san_env(base: dict | None = None) -> dict | None:
    """Environment for a child process that can load the sanitizer
    variant: LD_PRELOADs the ASan+UBSan runtimes and sets conservative
    sanitizer options. None when the runtimes can't be resolved.

    detect_leaks is OFF: interpreter-lifetime allocations (interned
    strings, module state) dominate any exit report; the lane exists
    for OOB/UAF/UB, the lint rules cover the leak-on-error-path class.
    """
    libs = []
    for name in ("libasan.so", "libubsan.so"):
        try:
            p = subprocess.run(["g++", f"-print-file-name={name}"],
                               capture_output=True, text=True,
                               check=True).stdout.strip()
        except (OSError, subprocess.CalledProcessError):
            return None
        if not p or "/" not in p:
            return None
        libs.append(p)
    env = dict(base if base is not None else os.environ)
    env["LD_PRELOAD"] = ":".join(
        libs + [x for x in env.get("LD_PRELOAD", "").split(":") if x])
    env["ASAN_OPTIONS"] = env.get(
        "ASAN_OPTIONS", "detect_leaks=0:abort_on_error=1")
    env["UBSAN_OPTIONS"] = env.get(
        "UBSAN_OPTIONS", "halt_on_error=1:print_stacktrace=1")
    env["JEPSEN_TPU_NATIVE_SAN"] = "1"
    return env


def _load(so: Path, name: str):
    loader = importlib.machinery.ExtensionFileLoader(name, str(so))
    spec = importlib.util.spec_from_file_location(name, str(so),
                                                 loader=loader)
    m = importlib.util.module_from_spec(spec)
    loader.exec_module(m)
    return m


def mod(san: bool = False):
    """The extension module, or None when unbuildable (or, for the
    sanitizer variant, unloadable in this process)."""
    global _mod, _mod_failed, _mod_san, _mod_san_failed
    if san:
        if _mod_san is not None or _mod_san_failed:
            return _mod_san
    elif _mod is not None or _mod_failed:
        return _mod
    with _lock:
        if san:
            if _mod_san is not None or _mod_san_failed:
                return _mod_san
        elif _mod is not None or _mod_failed:
            return _mod
        variant = "san" if san else "plain"
        try:
            if san and not _asan_mapped():
                # a late dlopen of libasan Die()s the interpreter —
                # never attempt it; the caller re-execs with san_env()
                raise RuntimeError(
                    "ASan runtime not preloaded in this process "
                    "(LD_PRELOAD libasan first; see san_env())")
            so = build(san=san)
            # both variants load under the module name the C source
            # exports (PyInit__columnar_c); they're distinguished by
            # path, and a process only ever loads one variant
            m = _load(so, "_columnar_c")
            if san:
                _mod_san = m
            else:
                _mod = m
        except Exception:  # noqa: BLE001
            cmd = _last_cmd.get(variant)
            logger.warning(
                "native columnar parser unavailable (variant=%s, "
                "cmd=%s); using Python builder", variant,
                " ".join(cmd) if cmd else "<not compiled>",
                exc_info=True)
            if san:
                _mod_san_failed = True
            else:
                _mod_failed = True
    return _mod_san if san else _mod


def available() -> bool:
    return mod() is not None
