"""Build + load the C columnar-history parser as an extension module.

Same on-demand g++ pattern as the WGL library (`native/__init__.py`),
but this one needs the CPython C API (it walks PyObject histories), so
it is loaded as a real extension module via importlib rather than
ctypes. Unavailable toolchain degrades silently: callers get ``None``
and use the pure-Python/numpy path.
"""
from __future__ import annotations

import hashlib
import importlib.machinery
import importlib.util
import logging
import os
import subprocess
import sysconfig
import threading
from pathlib import Path

logger = logging.getLogger("jepsen.native")

_HERE = Path(__file__).parent
_SRC = _HERE / "columnar_ext.c"
_lock = threading.Lock()
_mod = None
_mod_failed = False


def _build_dir() -> Path:
    d = os.environ.get("JEPSEN_NATIVE_BUILD_DIR")
    return Path(d) if d else _HERE


def _so_path() -> Path:
    src_hash = hashlib.sha256(_SRC.read_bytes()).hexdigest()[:16]
    return _build_dir() / f"_columnar_c-{src_hash}.so"


def build(force: bool = False) -> Path:
    so = _so_path()
    if so.exists() and not force:
        return so
    so.parent.mkdir(parents=True, exist_ok=True)
    # per-process tmp name: concurrent builders (pytest workers, parallel
    # sessions) must not interleave g++ output before the atomic publish
    tmp = so.with_suffix(f".so.tmp{os.getpid()}")
    inc = sysconfig.get_paths()["include"]
    cmd = ["g++", "-O3", "-march=native", "-shared", "-fPIC",
           f"-I{inc}", "-o", str(tmp), str(_SRC)]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    except subprocess.CalledProcessError:
        cmd = [c for c in cmd if c != "-march=native"]
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    os.replace(tmp, so)
    logger.info("built %s", so)
    return so


def mod():
    """The extension module, or None when unbuildable."""
    global _mod, _mod_failed
    if _mod is not None or _mod_failed:
        return _mod
    with _lock:
        if _mod is not None or _mod_failed:
            return _mod
        try:
            so = build()
            loader = importlib.machinery.ExtensionFileLoader(
                "_columnar_c", str(so))
            spec = importlib.util.spec_from_file_location(
                "_columnar_c", str(so), loader=loader)
            m = importlib.util.module_from_spec(spec)
            loader.exec_module(m)
            _mod = m
        except Exception:  # noqa: BLE001
            logger.warning("native columnar parser unavailable; "
                           "using Python builder", exc_info=True)
            _mod_failed = True
    return _mod


def available() -> bool:
    return mod() is not None
