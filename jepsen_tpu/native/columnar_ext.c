/* C-speed columnar history builder for the Elle list-append checker.
 *
 * The reference's Elle runs on the JVM where per-micro-op map walks are
 * JIT-compiled (SURVEY.md §2.5); here the equivalent parse of a Python
 * history — event pairing, micro-op flattening, key interning, spine
 * selection and prefix verification — is one tight C pass over the
 * PyObject graph, feeding the numpy/JAX stages of
 * jepsen_tpu/elle/columnar.py.  Mirrors the semantics of
 * columnar._build's pass A/B + spine/prefix sections bit-for-bit (the
 * differential fuzz in tests/test_elle.py pins it to the Python oracle);
 * any input outside the fast regime returns None and the caller falls
 * back to the Python path.
 *
 * Compiled on demand by jepsen_tpu/native/columnar_c.py (g++, no
 * pybind11 — plain CPython C API), loaded as an extension module.
 */
#include <Python.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>

#define MAX_KIDS (1 << 20)
#define MAX_MOPS (1 << 12)
#define MAX_VAL (4294967296LL) /* 1 << 32 */

typedef struct {
    int64_t *d;
    Py_ssize_t n, cap;
} vec;

static int vpush(vec *v, int64_t x) {
    if (v->n == v->cap) {
        Py_ssize_t nc = v->cap ? v->cap * 2 : 1024;
        int64_t *nd = (int64_t *)realloc(v->d, (size_t)nc * 8);
        if (!nd) return -1;
        v->d = nd;
        v->cap = nc;
    }
    v->d[v->n++] = x;
    return 0;
}

static void vfree(vec *v) {
    free(v->d);
    v->d = NULL;
    v->n = v->cap = 0;
}

static PyObject *vbytes(vec *v) {
    return PyByteArray_FromStringAndSize((char *)v->d, v->n * 8);
}

/* exact int -> int64 with overflow detection; returns -1 on overflow or
 * non-exact-int (bail), 0 ok */
static int as_i64(PyObject *o, int64_t *out) {
    if (!PyLong_CheckExact(o)) return -1;
    int ovf = 0;
    long long x = PyLong_AsLongLongAndOverflow(o, &ovf);
    if (ovf || (x == -1 && PyErr_Occurred())) {
        PyErr_Clear();
        return -1;
    }
    *out = (int64_t)x;
    return 0;
}

/* outcome codes for the parse */
#define OUT_OK 0
#define OUT_BAIL 1 /* regime miss: caller falls back to Python */
#define OUT_ERR 2  /* Python exception set */

typedef struct {
    vec ok_pos, info_pos, fail_pos;
    vec a_txn, a_kid, a_val, a_mi;
    vec r_txn, r_kid, r_mi, r_len, r_last;
    vec f_kid, f_val;
    vec s_concat, s_kid;
    int64_t *inv_pos;  /* [nh] */
    int64_t *best_len; /* [nk] spine */
    int64_t *best_row;
    int64_t *soff, *slen;
    PyObject *payloads, *raw_key, *kid_of, *state, *txns, *scrutiny;
    Py_ssize_t nk;
} ctx;

static void ctx_free(ctx *c) {
    vfree(&c->ok_pos); vfree(&c->info_pos); vfree(&c->fail_pos);
    vfree(&c->a_txn); vfree(&c->a_kid); vfree(&c->a_val); vfree(&c->a_mi);
    vfree(&c->r_txn); vfree(&c->r_kid); vfree(&c->r_mi); vfree(&c->r_len);
    vfree(&c->r_last);
    vfree(&c->f_kid); vfree(&c->f_val);
    vfree(&c->s_concat); vfree(&c->s_kid);
    free(c->inv_pos); free(c->best_len); free(c->best_row);
    free(c->soff); free(c->slen);
    Py_CLEAR(c->payloads); Py_CLEAR(c->raw_key); Py_CLEAR(c->kid_of);
    Py_CLEAR(c->state); Py_CLEAR(c->txns); Py_CLEAR(c->scrutiny);
}

/* interns key (an exact int object) into kid_of/raw_key; returns kid or
 * -1 (bail: too many keys) or -2 (error) */
static int64_t intern_kid(ctx *c, PyObject *key) {
    PyObject *got = PyDict_GetItemWithError(c->kid_of, key);
    if (got) return PyLong_AsLongLong(got);
    if (PyErr_Occurred()) return -2;
    if (c->nk >= MAX_KIDS) return -1;
    PyObject *idx = PyLong_FromSsize_t(c->nk);
    if (!idx) return -2;
    if (PyDict_SetItem(c->kid_of, key, idx) < 0) {
        Py_DECREF(idx);
        return -2;
    }
    Py_DECREF(idx);
    if (PyList_Append(c->raw_key, key) < 0) return -2;
    return (int64_t)c->nk++;
}

/* flatten one committed/info txn's micro-ops (pass B semantics).
 * ni = node index. Returns OUT_*. */
static int flatten_txn(ctx *c, PyObject *op, Py_ssize_t ni) {
    PyObject *value = PyDict_GetItemString(op, "value");
    if (!value) return OUT_OK;
    int truth = PyObject_IsTrue(value);
    if (truth < 0) return OUT_ERR;
    if (!truth) return OUT_OK; /* `op.get("value") or ()` */
    PyObject **items;
    Py_ssize_t nm;
    if (PyList_CheckExact(value)) {
        items = ((PyListObject *)value)->ob_item;
        nm = PyList_GET_SIZE(value);
    } else if (PyTuple_CheckExact(value)) {
        items = ((PyTupleObject *)value)->ob_item;
        nm = PyTuple_GET_SIZE(value);
    } else {
        return OUT_BAIL; /* exotic container: general loop handles it */
    }
    if (nm > MAX_MOPS) return OUT_BAIL;
    for (Py_ssize_t mi = 0; mi < nm; mi++) {
        PyObject *m = items[mi];
        PyObject **mit;
        Py_ssize_t ml;
        if (PyList_CheckExact(m)) {
            mit = ((PyListObject *)m)->ob_item;
            ml = PyList_GET_SIZE(m);
        } else if (PyTuple_CheckExact(m)) {
            mit = ((PyTupleObject *)m)->ob_item;
            ml = PyTuple_GET_SIZE(m);
        } else {
            return OUT_BAIL;
        }
        if (ml < 3) return OUT_BAIL; /* fast path needs [f, k, v] */
        PyObject *f = mit[0];
        if (!PyUnicode_CheckExact(f)) return OUT_BAIL;
        if (PyUnicode_CompareWithASCIIString(f, "append") == 0) {
            int64_t kid, val;
            if (!PyLong_CheckExact(mit[1])) return OUT_BAIL;
            kid = intern_kid(c, mit[1]);
            if (kid == -1) return OUT_BAIL;
            if (kid == -2) return OUT_ERR;
            if (as_i64(mit[2], &val) < 0) return OUT_BAIL;
            if (val < 0 || val >= MAX_VAL) return OUT_BAIL;
            if (vpush(&c->a_txn, ni) || vpush(&c->a_kid, kid) ||
                vpush(&c->a_val, val) || vpush(&c->a_mi, mi))
                return OUT_ERR;
        } else if (PyUnicode_CompareWithASCIIString(f, "r") == 0) {
            PyObject *third = mit[2];
            if (third == Py_None) continue; /* unfulfilled read */
            int64_t kid;
            if (!PyLong_CheckExact(mit[1])) return OUT_BAIL;
            kid = intern_kid(c, mit[1]);
            if (kid == -1) return OUT_BAIL;
            if (kid == -2) return OUT_ERR;
            PyObject *payload;
            if (PyList_CheckExact(third)) {
                payload = third;
                Py_INCREF(payload);
            } else {
                payload = PySequence_List(third);
                if (!payload) return OUT_ERR;
            }
            Py_ssize_t plen = PyList_GET_SIZE(payload);
            int64_t last = -1;
            if (plen > 0 &&
                as_i64(PyList_GET_ITEM(payload, plen - 1), &last) < 0) {
                Py_DECREF(payload);
                return OUT_BAIL; /* non-int tail: Python scrutiny path */
            }
            if (PyList_Append(c->payloads, payload) < 0) {
                Py_DECREF(payload);
                return OUT_ERR;
            }
            Py_DECREF(payload);
            if (vpush(&c->r_txn, ni) || vpush(&c->r_kid, kid) ||
                vpush(&c->r_mi, mi) || vpush(&c->r_len, plen) ||
                vpush(&c->r_last, last))
                return OUT_ERR;
        } /* other mop types: ignored, keys not interned */
    }
    return OUT_OK;
}

static PyObject *parse(PyObject *self, PyObject *args) {
    PyObject *history;
    if (!PyArg_ParseTuple(args, "O", &history)) return NULL;
    if (!PyList_CheckExact(history)) Py_RETURN_NONE;
    Py_ssize_t nh = PyList_GET_SIZE(history);

    ctx c;
    memset(&c, 0, sizeof(c));
    int out = OUT_BAIL;
    Py_ssize_t n_ok = 0, n = 0;
    PyObject *result = NULL;
    vec node_proc_v;
    memset(&node_proc_v, 0, sizeof(node_proc_v));

    c.payloads = PyList_New(0);
    c.raw_key = PyList_New(0);
    c.kid_of = PyDict_New();
    c.state = PyDict_New();
    c.txns = PyList_New(0);
    c.scrutiny = PyList_New(0);
    if (!c.payloads || !c.raw_key || !c.kid_of || !c.state || !c.txns ||
        !c.scrutiny) {
        out = OUT_ERR;
        goto done;
    }
    c.inv_pos = (int64_t *)malloc((size_t)(nh > 0 ? nh : 1) * 8);
    if (!c.inv_pos) {
        PyErr_NoMemory();
        out = OUT_ERR;
        goto done;
    }

    /* ---- pass A: event scan + invocation pairing -------------------- */
    for (Py_ssize_t i = 0; i < nh; i++) {
        c.inv_pos[i] = -1;
        PyObject *op = PyList_GET_ITEM(history, i);
        if (!PyDict_Check(op)) { out = OUT_BAIL; goto done; }
        PyObject *type = PyDict_GetItemString(op, "type");
        int ev = -1, is_ok = 0, is_info = 0, is_fail = 0;
        if (type && PyUnicode_CheckExact(type)) {
            if (PyUnicode_CompareWithASCIIString(type, "invoke") == 0)
                ev = 0;
            else if (PyUnicode_CompareWithASCIIString(type, "ok") == 0) {
                ev = 1; is_ok = 1;
            } else if (PyUnicode_CompareWithASCIIString(type, "info") == 0) {
                ev = 1; is_info = 1;
            } else if (PyUnicode_CompareWithASCIIString(type, "fail") == 0) {
                ev = 1; is_fail = 1;
            }
        }
        PyObject *process = PyDict_GetItemString(op, "process");
        if (!process) process = Py_None;
        if (ev >= 0) {
            /* previous-event-of-same-process rule (columnar pass A) */
            PyObject *prev = PyDict_GetItemWithError(c.state, process);
            if (!prev && PyErr_Occurred()) {
                /* unhashable process: Python path raises too -> bail */
                PyErr_Clear();
                out = OUT_BAIL;
                goto done;
            }
            if (ev == 1 && prev) {
                /* prev is always a PyLong WE stored below (i<<1|bit,
                 * i a list index): in-range, cannot fail.
                 * lint: ignore[jtn-errcheck] */
                long long packed = PyLong_AsLongLong(prev);
                if (packed & 1) c.inv_pos[i] = packed >> 1;
            }
            PyObject *now = PyLong_FromLongLong(((long long)i << 1) |
                                                (ev == 0 ? 1 : 0));
            if (!now) { out = OUT_ERR; goto done; }
            if (PyDict_SetItem(c.state, process, now) < 0) {
                Py_DECREF(now);
                PyErr_Clear();
                out = OUT_BAIL; /* unhashable process */
                goto done;
            }
            Py_DECREF(now);
        }
        int proc_is_int = PyLong_Check(process); /* isinstance(p, int) */
        if (is_ok && proc_is_int) {
            if (vpush(&c.ok_pos, i)) { out = OUT_ERR; goto done; }
        } else if (is_info && proc_is_int) {
            if (vpush(&c.info_pos, i)) { out = OUT_ERR; goto done; }
        } else if (is_fail) {
            if (vpush(&c.fail_pos, i)) { out = OUT_ERR; goto done; }
        }
    }

    n_ok = c.ok_pos.n;
    n = n_ok + c.info_pos.n;
    if (n == 0 || n >= ((Py_ssize_t)1 << 31)) { out = OUT_BAIL; goto done; }

    /* ---- pass B: flatten micro-ops (oks then infos) ----------------- */
    for (Py_ssize_t j = 0; j < n; j++) {
        Py_ssize_t pos = j < n_ok ? c.ok_pos.d[j] : c.info_pos.d[j - n_ok];
        PyObject *op = PyList_GET_ITEM(history, pos);
        if (PyList_Append(c.txns, op) < 0) { out = OUT_ERR; goto done; }
        /* node_proc must fit int64 (Python: np.asarray(..., int64)) */
        PyObject *process = PyDict_GetItemString(op, "process");
        int ovf = 0;
        long long x = process ? PyLong_AsLongLongAndOverflow(process, &ovf)
                              : -1;
        if (!process || ovf || (x == -1 && PyErr_Occurred())) {
            PyErr_Clear();
            out = OUT_BAIL;
            goto done;
        }
        if (vpush(&node_proc_v, x)) { out = OUT_ERR; goto done; }
        int rc = flatten_txn(&c, op, j);
        if (rc != OUT_OK) { out = rc; goto done; }
    }

    /* ---- fail ops' appends (kid() continuation semantics) ----------- */
    for (Py_ssize_t fi = 0; fi < c.fail_pos.n; fi++) {
        PyObject *op = PyList_GET_ITEM(history, c.fail_pos.d[fi]);
        PyObject *value = PyDict_GetItemString(op, "value");
        if (!value) continue;
        int truth = PyObject_IsTrue(value);
        if (truth < 0) { out = OUT_ERR; goto done; }
        if (!truth) continue;
        PyObject **items;
        Py_ssize_t nm;
        if (PyList_CheckExact(value)) {
            items = ((PyListObject *)value)->ob_item;
            nm = PyList_GET_SIZE(value);
        } else if (PyTuple_CheckExact(value)) {
            items = ((PyTupleObject *)value)->ob_item;
            nm = PyTuple_GET_SIZE(value);
        } else { out = OUT_BAIL; goto done; }
        for (Py_ssize_t mi = 0; mi < nm; mi++) {
            PyObject *m = items[mi];
            PyObject **mit;
            Py_ssize_t ml;
            if (PyList_CheckExact(m)) {
                mit = ((PyListObject *)m)->ob_item;
                ml = PyList_GET_SIZE(m);
            } else if (PyTuple_CheckExact(m)) {
                mit = ((PyTupleObject *)m)->ob_item;
                ml = PyTuple_GET_SIZE(m);
            } else { out = OUT_BAIL; goto done; }
            if (ml < 1 || !PyUnicode_CheckExact(mit[0])) {
                out = OUT_BAIL; goto done;
            }
            if (PyUnicode_CompareWithASCIIString(mit[0], "append") != 0)
                continue;
            if (ml < 3 || !PyLong_CheckExact(mit[1])) {
                out = OUT_BAIL; goto done;
            }
            int64_t kid = intern_kid(&c, mit[1]);
            if (kid == -1) { out = OUT_BAIL; goto done; }
            if (kid == -2) { out = OUT_ERR; goto done; }
            int64_t val;
            if (as_i64(mit[2], &val) < 0 || val < 0 || val >= MAX_VAL) {
                out = OUT_BAIL; goto done;
            }
            if (vpush(&c.f_kid, kid) || vpush(&c.f_val, val)) {
                out = OUT_ERR; goto done;
            }
        }
    }

    /* ---- spines: first maximal-length ok read per key ---------------- */
    {
        Py_ssize_t nk = c.nk;
        c.best_len = (int64_t *)malloc((size_t)(nk > 0 ? nk : 1) * 8);
        c.best_row = (int64_t *)malloc((size_t)(nk > 0 ? nk : 1) * 8);
        c.soff = (int64_t *)malloc((size_t)(nk > 0 ? nk : 1) * 8);
        c.slen = (int64_t *)malloc((size_t)(nk > 0 ? nk : 1) * 8);
        if (!c.best_len || !c.best_row || !c.soff || !c.slen) {
            PyErr_NoMemory();
            out = OUT_ERR;
            goto done;
        }
        for (Py_ssize_t k = 0; k < nk; k++) {
            c.best_len[k] = -1;
            c.best_row[k] = -1;
            c.soff[k] = -1;
            c.slen[k] = 0;
        }
        for (Py_ssize_t j = 0; j < c.r_txn.n; j++) {
            if (c.r_txn.d[j] >= (int64_t)n_ok) continue; /* info reads */
            int64_t k = c.r_kid.d[j];
            if (c.r_len.d[j] > c.best_len[k]) {
                c.best_len[k] = c.r_len.d[j];
                c.best_row[k] = j;
            }
        }
        /* S_concat / s_kid / soff / slen in kid order (matches the numpy
         * sort-by-kid layout) */
        for (Py_ssize_t k = 0; k < nk; k++) {
            if (c.best_row[k] < 0) continue;
            PyObject *p = PyList_GET_ITEM(c.payloads, c.best_row[k]);
            Py_ssize_t plen = PyList_GET_SIZE(p);
            c.soff[k] = c.s_concat.n;
            c.slen[k] = plen;
            for (Py_ssize_t e = 0; e < plen; e++) {
                int64_t v;
                if (as_i64(PyList_GET_ITEM(p, e), &v) < 0 || v < 0 ||
                    v >= MAX_VAL) {
                    out = OUT_BAIL; /* non-int/out-of-range spine element */
                    goto done;
                }
                if (vpush(&c.s_concat, v) || vpush(&c.s_kid, k)) {
                    out = OUT_ERR;
                    goto done;
                }
            }
        }
    }

    /* ---- prefix verification against spines -------------------------- */
    for (Py_ssize_t j = 0; j < c.r_txn.n; j++) {
        if (c.r_txn.d[j] >= (int64_t)n_ok) continue;
        int64_t k = c.r_kid.d[j];
        PyObject *p = PyList_GET_ITEM(c.payloads, j);
        PyObject *sp = PyList_GET_ITEM(c.payloads, c.best_row[k]);
        if (p == sp) continue;
        Py_ssize_t plen = PyList_GET_SIZE(p);
        int clean = plen <= PyList_GET_SIZE(sp);
        for (Py_ssize_t e = 0; clean && e < plen; e++) {
            PyObject *a = PyList_GET_ITEM(p, e);
            PyObject *b = PyList_GET_ITEM(sp, e);
            if (a == b) continue;
            int eq = PyObject_RichCompareBool(a, b, Py_EQ);
            if (eq < 0) {
                PyErr_Clear();
                out = OUT_BAIL; /* incomparable payloads: Python path */
                goto done;
            }
            clean = eq;
        }
        if (!clean) {
            PyObject *jj = PyLong_FromSsize_t(j);
            if (!jj || PyList_Append(c.scrutiny, jj) < 0) {
                Py_XDECREF(jj);
                out = OUT_ERR;
                goto done;
            }
            Py_DECREF(jj);
        }
    }

    /* ---- package ----------------------------------------------------- */
    {
        vec np_v, ni_v;
        memset(&np_v, 0, sizeof(np_v));
        memset(&ni_v, 0, sizeof(ni_v));
        int push_fail = 0;
        for (Py_ssize_t j = 0; j < n && !push_fail; j++) {
            Py_ssize_t pos = j < n_ok ? c.ok_pos.d[j]
                                      : c.info_pos.d[j - n_ok];
            push_fail = vpush(&np_v, pos) || vpush(&ni_v, c.inv_pos[pos]);
        }
        if (push_fail) {
            vfree(&np_v);
            vfree(&ni_v);
            PyErr_NoMemory();
            out = OUT_ERR;
            goto done;
        }
        result = PyTuple_New(25);
        if (!result) {
            vfree(&np_v);
            vfree(&ni_v);
            out = OUT_ERR;
            goto done;
        }
        int slot = 0, bad = 0;
        /* SETNEW consumes o; a NULL o marks failure, slot gets None */
#define SETNEW(o)                                                      \
        do {                                                           \
            PyObject *tmp_ = (o);                                      \
            if (!tmp_) { bad = 1; tmp_ = Py_None; Py_INCREF(tmp_); }   \
            PyTuple_SET_ITEM(result, slot++, tmp_);                    \
        } while (0)
        SETNEW(PyLong_FromSsize_t(n_ok));
        SETNEW(PyLong_FromSsize_t(c.nk));
        SETNEW(vbytes(&np_v));
        SETNEW(vbytes(&ni_v));
        SETNEW(vbytes(&node_proc_v));
        SETNEW((Py_INCREF(c.txns), c.txns));
        SETNEW(vbytes(&c.a_txn));
        SETNEW(vbytes(&c.a_kid));
        SETNEW(vbytes(&c.a_val));
        SETNEW(vbytes(&c.a_mi));
        SETNEW(vbytes(&c.r_txn));
        SETNEW(vbytes(&c.r_kid));
        SETNEW(vbytes(&c.r_mi));
        SETNEW(vbytes(&c.r_len));
        SETNEW(vbytes(&c.r_last));
        SETNEW((Py_INCREF(c.payloads), c.payloads));
        SETNEW((Py_INCREF(c.raw_key), c.raw_key));
        SETNEW(vbytes(&c.f_kid));
        SETNEW(vbytes(&c.f_val));
        SETNEW(vbytes(&c.s_concat));
        SETNEW(vbytes(&c.s_kid));
        SETNEW(PyByteArray_FromStringAndSize((char *)c.soff, c.nk * 8));
        SETNEW(PyByteArray_FromStringAndSize((char *)c.slen, c.nk * 8));
        SETNEW(PyByteArray_FromStringAndSize((char *)c.best_row, c.nk * 8));
        SETNEW((Py_INCREF(c.scrutiny), c.scrutiny));
#undef SETNEW
        vfree(&np_v);
        vfree(&ni_v);
        if (bad) {
            if (!PyErr_Occurred()) PyErr_NoMemory();
            out = OUT_ERR;
        } else {
            out = OUT_OK;
        }
    }

done:
    ctx_free(&c);
    vfree(&node_proc_v);
    if (out == OUT_OK) return result;
    Py_XDECREF(result);
    if (out == OUT_BAIL) {
        if (PyErr_Occurred()) PyErr_Clear();
        Py_RETURN_NONE;
    }
    /* OUT_ERR: an exception must be set — the vpush (realloc) failure
     * paths reach here bare, and a NULL return without an exception
     * would surface as a misleading SystemError */
    if (!PyErr_Occurred()) PyErr_NoMemory();
    return NULL;
}

/* ====================================================================
 * Host ingest spine (doc/performance.md "Host ingest spine")
 *
 * Four entry points move the WAL hot loop — newline scan, JSON parse,
 * canonical-column append, live register encode, frontier absorb —
 * off the interpreted path:
 *
 *   ingest_chunk     raw bytes -> ops list (torn-line contract of
 *                    read_jsonl_tolerant / WalTailer.poll, per line)
 *   builder_extend   ops -> IncrementalHistoryBuilder columns
 *   register_add     ops -> LiveRegisterEncoder resolution state
 *   register_encode  resolution state -> ListStream event columns
 *   frontier_absorb  event columns -> FrontierSession config closure
 *
 * Every function mutates (or returns replacements for) the SAME
 * Python-level state its pure-Python twin owns, so the two
 * implementations interleave freely mid-stream and a per-op/per-line
 * regime miss falls back to the Python twin with bit-identical state.
 * The differential suites in tests/test_history_ir.py and
 * tests/test_live.py pin each one to its oracle.
 * ==================================================================== */

/* shared singletons, created once in PyInit */
static PyObject *g_key_cache;  /* str -> str: shared key/short-string pool */
static PyObject *g_s_type, *g_s_process, *g_s_f, *g_s_value, *g_s_time,
    *g_s_index, *g_s_read, *g_s_ok, *g_s_unhash, *g_s_invoke;
static PyObject *g_keep, *g_drop; /* ("keep",) / ("drop",) */
static PyObject *g_int[4];        /* 0..3 */
static PyObject *g_m1;            /* -1 */

static int spine_init(void) {
    if (g_key_cache) return 0;
#define MKSTR(var, lit)                   \
    do {                                  \
        var = PyUnicode_InternFromString(lit); \
        if (!var) return -1;              \
    } while (0)
    g_key_cache = PyDict_New();
    if (!g_key_cache) return -1;
    MKSTR(g_s_type, "type");
    MKSTR(g_s_process, "process");
    MKSTR(g_s_f, "f");
    MKSTR(g_s_value, "value");
    MKSTR(g_s_time, "time");
    MKSTR(g_s_index, "index");
    MKSTR(g_s_read, "read");
    MKSTR(g_s_ok, "ok");
    MKSTR(g_s_unhash, "__unhashable__");
    MKSTR(g_s_invoke, "invoke");
#undef MKSTR
    {
        PyObject *k = PyUnicode_InternFromString("keep");
        PyObject *d = PyUnicode_InternFromString("drop");
        if (!k || !d) {
            Py_XDECREF(k);
            Py_XDECREF(d);
            return -1;
        }
        g_keep = PyTuple_Pack(1, k);
        g_drop = PyTuple_Pack(1, d);
        Py_DECREF(k);
        Py_DECREF(d);
        if (!g_keep || !g_drop) return -1;
    }
    for (int i = 0; i < 4; i++) {
        g_int[i] = PyLong_FromLong(i);
        if (!g_int[i]) return -1;
    }
    g_m1 = PyLong_FromLong(-1);
    if (!g_m1) return -1;
    return 0;
}

/* -------------------- JSON line parser -------------------------------
 * Strict-by-construction: anything this parser is not 100% sure it
 * reproduces exactly as CPython's json.loads would (escapes gone wrong,
 * invalid UTF-8, oversized numbers, depth) flags `bail`, and the caller
 * hands the LINE to the Python fallback. Success must be provably
 * identical to json.loads on the same line. */

typedef struct {
    const unsigned char *p, *end;
    int bail;  /* 1 => caller falls back to Python for this line */
    int depth;
} JP;

#define JP_MAX_DEPTH 64

static void jp_ws(JP *j) {
    while (j->p < j->end) {
        unsigned char c = *j->p;
        if (c == ' ' || c == '\t' || c == '\n' || c == '\r')
            j->p++;
        else
            break;
    }
}

/* strict UTF-8 decode of one codepoint; returns byte length or -1 */
static int u8cp(const unsigned char *p, const unsigned char *end,
                Py_UCS4 *cp) {
    unsigned char c = *p;
    if (c < 0x80) {
        *cp = c;
        return 1;
    }
    if ((c >> 5) == 0x6) {
        if (end - p < 2 || (p[1] & 0xC0) != 0x80) return -1;
        Py_UCS4 v = ((Py_UCS4)(c & 0x1F) << 6) | (p[1] & 0x3F);
        if (v < 0x80) return -1;
        *cp = v;
        return 2;
    }
    if ((c >> 4) == 0xE) {
        if (end - p < 3 || (p[1] & 0xC0) != 0x80 || (p[2] & 0xC0) != 0x80)
            return -1;
        Py_UCS4 v = ((Py_UCS4)(c & 0x0F) << 12) |
                    ((Py_UCS4)(p[1] & 0x3F) << 6) | (p[2] & 0x3F);
        if (v < 0x800 || (v >= 0xD800 && v <= 0xDFFF)) return -1;
        *cp = v;
        return 3;
    }
    if ((c >> 3) == 0x1E) {
        if (end - p < 4 || (p[1] & 0xC0) != 0x80 ||
            (p[2] & 0xC0) != 0x80 || (p[3] & 0xC0) != 0x80)
            return -1;
        Py_UCS4 v = ((Py_UCS4)(c & 0x07) << 18) |
                    ((Py_UCS4)(p[1] & 0x3F) << 12) |
                    ((Py_UCS4)(p[2] & 0x3F) << 6) | (p[3] & 0x3F);
        if (v < 0x10000 || v > 0x10FFFF) return -1;
        *cp = v;
        return 4;
    }
    return -1;
}

static int hex4(const unsigned char *p, unsigned *out) {
    unsigned v = 0;
    for (int i = 0; i < 4; i++) {
        unsigned char c = p[i];
        v <<= 4;
        if (c >= '0' && c <= '9')
            v |= c - '0';
        else if (c >= 'a' && c <= 'f')
            v |= c - 'a' + 10;
        else if (c >= 'A' && c <= 'F')
            v |= c - 'A' + 10;
        else
            return -1;
    }
    *out = v;
    return 0;
}

/* route short strings through the shared pool: repeated keys/values
 * ("type", "invoke", "write", ...) collapse to one object with a
 * cached hash, like json's own per-scan key memo but cross-line.
 * Consumes s; returns a new reference. */
static PyObject *pool_str(PyObject *s) {
    if (!s || PyUnicode_GET_LENGTH(s) > 32) return s;
    PyObject *got = PyDict_GetItemWithError(g_key_cache, s);
    if (got) {
        Py_INCREF(got);
        Py_DECREF(s);
        return got;
    }
    if (PyErr_Occurred()) {
        Py_DECREF(s);
        return NULL;
    }
    if (PyDict_GET_SIZE(g_key_cache) < 4096 &&
        PyDict_SetItem(g_key_cache, s, s) < 0) {
        Py_DECREF(s);
        return NULL;
    }
    return s;
}

/* byte-keyed cache for short escape-free strings: repeated keys and
 * enum-ish values ("type", "invoke", "write", ...) resolve to their
 * pooled PyUnicode without constructing a new object per line. First
 * come, first kept — no eviction, bounded size. */
#define BK_SLOTS 2048 /* power of two */
#define BK_MAXLEN 24
typedef struct {
    unsigned char len;
    unsigned char b[BK_MAXLEN];
    PyObject *s; /* owned; lives as long as the module */
} bkent;
static bkent g_bk[BK_SLOTS];

static PyObject *bk_lookup(const unsigned char *p, Py_ssize_t n) {
    uint64_t h = 1469598103934665603ULL;
    for (Py_ssize_t i = 0; i < n; i++) h = (h ^ p[i]) * 1099511628211ULL;
    size_t idx = (size_t)h & (BK_SLOTS - 1);
    for (int probe = 0; probe < 8; probe++) {
        bkent *e = &g_bk[(idx + probe) & (BK_SLOTS - 1)];
        if (!e->s) {
            /* miss with room: construct, pool, insert */
            PyObject *u = PyUnicode_DecodeUTF8((const char *)p, n, NULL);
            if (!u) return NULL; /* ascii input: shouldn't fail */
            u = pool_str(u);
            if (!u) return NULL;
            e->len = (unsigned char)n;
            memcpy(e->b, p, (size_t)n);
            Py_INCREF(u); /* cache's own reference */
            e->s = u;
            return u;
        }
        if (e->len == n && memcmp(e->b, p, (size_t)n) == 0) {
            Py_INCREF(e->s);
            return e->s;
        }
    }
    /* table neighborhood full: construct without caching */
    PyObject *u = PyUnicode_DecodeUTF8((const char *)p, n, NULL);
    if (!u) return NULL;
    return pool_str(u);
}

/* j->p at the opening quote */
/* lint: ignore[jtn-bounds-guard] — the UCS4 buffer holds cap = q - s
 * codepoints and every loop arm consumes >= 1 input byte per emitted
 * codepoint, so n < cap on every buf[n++] (the fuzz harness hammers
 * exactly this arithmetic under ASan). */
static PyObject *jp_string(JP *j) {
    const unsigned char *s = j->p + 1, *q = s;
    int esc = 0, hi = 0;
    while (q < j->end) {
        unsigned char c = *q;
        if (c == '"') break;
        if (c == '\\') {
            esc = 1;
            q += 2;  /* skip escaped char (never a quote terminator) */
            continue;
        }
        if (c < 0x20) {  /* strict json rejects raw control chars */
            j->bail = 1;
            return NULL;
        }
        if (c >= 0x80) hi = 1;
        q++;
    }
    if (q >= j->end) {  /* unterminated (or escape ran off the end) */
        j->bail = 1;
        return NULL;
    }
    j->p = q + 1;
    if (!esc && !hi && q - s <= BK_MAXLEN)
        return bk_lookup(s, (Py_ssize_t)(q - s));
    if (!esc) {
        PyObject *u = PyUnicode_DecodeUTF8((const char *)s,
                                           (Py_ssize_t)(q - s), NULL);
        if (!u) {
            if (PyErr_ExceptionMatches(PyExc_UnicodeDecodeError)) {
                PyErr_Clear();
                j->bail = 1; /* invalid utf-8: Python 'replace' path */
            }
            return NULL;
        }
        (void)hi;
        return pool_str(u);
    }
    /* escape slow path: decode into a UCS4 buffer */
    Py_ssize_t cap = (Py_ssize_t)(q - s);
    Py_UCS4 *buf = (Py_UCS4 *)malloc(cap ? (size_t)cap * 4 : 4);
    if (!buf) {
        PyErr_NoMemory();
        return NULL;
    }
    Py_ssize_t n = 0;
    const unsigned char *r = s;
    while (r < q) {
        unsigned char c = *r;
        if (c == '\\') {
            r++;
            unsigned char e = *r++;
            Py_UCS4 cp;
            switch (e) {
            case '"': cp = '"'; break;
            case '\\': cp = '\\'; break;
            case '/': cp = '/'; break;
            case 'b': cp = '\b'; break;
            case 'f': cp = '\f'; break;
            case 'n': cp = '\n'; break;
            case 'r': cp = '\r'; break;
            case 't': cp = '\t'; break;
            case 'u': {
                unsigned v;
                if (q - r < 4 || hex4(r, &v) < 0) goto bail;
                r += 4;
                cp = v;
                /* combine surrogate pairs; lone surrogates kept,
                 * exactly like json.decoder.scanstring */
                if (v >= 0xD800 && v <= 0xDBFF && q - r >= 6 &&
                    r[0] == '\\' && r[1] == 'u') {
                    unsigned lo;
                    if (hex4(r + 2, &lo) == 0 && lo >= 0xDC00 &&
                        lo <= 0xDFFF) {
                        cp = 0x10000 + (((v - 0xD800) << 10) |
                                        (lo - 0xDC00));
                        r += 6;
                    }
                }
                break;
            }
            default:
                goto bail;
            }
            buf[n++] = cp;
        } else if (c < 0x80) {
            buf[n++] = c;
            r++;
        } else {
            Py_UCS4 cp;
            int len = u8cp(r, q, &cp);
            if (len < 0) goto bail;
            buf[n++] = cp;
            r += len;
        }
    }
    {
        PyObject *u = PyUnicode_FromKindAndData(PyUnicode_4BYTE_KIND, buf,
                                                n);
        free(buf);
        /* buf already freed; a MemoryError here must propagate as an
         * ERROR, while the bail label means "tolerant re-parse" —
         * routing through it would misfile the failure.
         * lint: ignore[jtn-cleanup-return] */
        if (!u) return NULL;
        return pool_str(u);
    }
bail:
    free(buf);
    j->bail = 1;
    return NULL;
}

static PyObject *jp_number(JP *j) {
    const unsigned char *s = j->p, *q = s;
    int isflt = 0;
    if (q < j->end && *q == '-') q++;
    if (q >= j->end) {
        j->bail = 1;
        return NULL;
    }
    if (*q == '0') {
        q++;
    } else if (*q >= '1' && *q <= '9') {
        while (q < j->end && *q >= '0' && *q <= '9') q++;
    } else {
        j->bail = 1; /* includes -Infinity (handled by caller) */
        return NULL;
    }
    if (q < j->end && *q == '.') {
        isflt = 1;
        q++;
        if (q >= j->end || *q < '0' || *q > '9') {
            j->bail = 1;
            return NULL;
        }
        while (q < j->end && *q >= '0' && *q <= '9') q++;
    }
    if (q < j->end && (*q == 'e' || *q == 'E')) {
        isflt = 1;
        q++;
        if (q < j->end && (*q == '+' || *q == '-')) q++;
        if (q >= j->end || *q < '0' || *q > '9') {
            j->bail = 1;
            return NULL;
        }
        while (q < j->end && *q >= '0' && *q <= '9') q++;
    }
    Py_ssize_t len = (Py_ssize_t)(q - s);
    if (len >= 63) { /* absurd token: let Python decide */
        j->bail = 1;
        return NULL;
    }
    j->p = q;
    char buf[64];
    memcpy(buf, s, (size_t)len);
    buf[len] = 0;
    if (!isflt) {
        if (len <= 18) { /* fits int64 without overflow checks */
            int64_t v = 0;
            const char *t = buf;
            int neg = (*t == '-');
            if (neg) t++;
            while (*t) v = v * 10 + (*t++ - '0');
            return PyLong_FromLongLong(neg ? -v : v);
        }
        return PyLong_FromString(buf, NULL, 10);
    }
    double d = PyOS_string_to_double(buf, NULL, NULL);
    if (d == -1.0 && PyErr_Occurred()) {
        PyErr_Clear();
        j->bail = 1;
        return NULL;
    }
    return PyFloat_FromDouble(d);
}

static int jp_lit(JP *j, const char *lit, size_t n) {
    if ((size_t)(j->end - j->p) < n || memcmp(j->p, lit, n) != 0) return 0;
    j->p += n;
    return 1;
}

static PyObject *jp_value(JP *j) {
    jp_ws(j);
    if (j->p >= j->end) {
        j->bail = 1;
        return NULL;
    }
    unsigned char c = *j->p;
    switch (c) {
    case '{': {
        if (++j->depth > JP_MAX_DEPTH) {
            j->bail = 1;
            return NULL;
        }
        PyObject *d = PyDict_New();
        if (!d) return NULL;
        j->p++;
        jp_ws(j);
        if (j->p < j->end && *j->p == '}') {
            j->p++;
            j->depth--;
            return d;
        }
        for (;;) {
            jp_ws(j);
            if (j->p >= j->end || *j->p != '"') goto obail;
            PyObject *k = jp_string(j);
            if (!k) {
                Py_DECREF(d);
                /* d released inline; obail would double-release.
                 * lint: ignore[jtn-cleanup-return] */
                return NULL;
            }
            jp_ws(j);
            if (j->p >= j->end || *j->p != ':') {
                Py_DECREF(k);
                goto obail;
            }
            j->p++;
            PyObject *v = jp_value(j);
            if (!v) {
                Py_DECREF(k);
                Py_DECREF(d);
                /* k, d released inline (obail releases d alone).
                 * lint: ignore[jtn-cleanup-return] */
                return NULL;
            }
            int rc = PyDict_SetItem(d, k, v); /* dup keys: last wins */
            Py_DECREF(k);
            Py_DECREF(v);
            if (rc < 0) {
                Py_DECREF(d);
                /* d released inline; error already set by SetItem.
                 * lint: ignore[jtn-cleanup-return] */
                return NULL;
            }
            jp_ws(j);
            if (j->p < j->end && *j->p == ',') {
                j->p++;
                continue;
            }
            if (j->p < j->end && *j->p == '}') {
                j->p++;
                j->depth--;
                return d;
            }
            goto obail;
        }
    obail:
        Py_DECREF(d);
        j->bail = 1;
        return NULL;
    }
    case '[': {
        if (++j->depth > JP_MAX_DEPTH) {
            j->bail = 1;
            return NULL;
        }
        PyObject *l = PyList_New(0);
        if (!l) return NULL;
        j->p++;
        jp_ws(j);
        if (j->p < j->end && *j->p == ']') {
            j->p++;
            j->depth--;
            return l;
        }
        for (;;) {
            PyObject *v = jp_value(j);
            if (!v) {
                Py_DECREF(l);
                return NULL;
            }
            int rc = PyList_Append(l, v);
            Py_DECREF(v);
            if (rc < 0) {
                Py_DECREF(l);
                return NULL;
            }
            jp_ws(j);
            if (j->p < j->end && *j->p == ',') {
                j->p++;
                continue;
            }
            if (j->p < j->end && *j->p == ']') {
                j->p++;
                j->depth--;
                return l;
            }
            Py_DECREF(l);
            j->bail = 1;
            return NULL;
        }
    }
    case '"':
        return jp_string(j);
    case 't':
        if (jp_lit(j, "true", 4)) Py_RETURN_TRUE;
        j->bail = 1;
        return NULL;
    case 'f':
        if (jp_lit(j, "false", 5)) Py_RETURN_FALSE;
        j->bail = 1;
        return NULL;
    case 'n':
        if (jp_lit(j, "null", 4)) Py_RETURN_NONE;
        j->bail = 1;
        return NULL;
    case 'N': /* json.loads accepts NaN/Infinity by default */
        if (jp_lit(j, "NaN", 3)) return PyFloat_FromDouble(Py_NAN);
        j->bail = 1;
        return NULL;
    case 'I':
        if (jp_lit(j, "Infinity", 8))
            return PyFloat_FromDouble(Py_HUGE_VAL);
        j->bail = 1;
        return NULL;
    case '-':
        if (j->end - j->p >= 9 && j->p[1] == 'I') {
            if (jp_lit(j, "-Infinity", 9))
                return PyFloat_FromDouble(-Py_HUGE_VAL);
            j->bail = 1;
            return NULL;
        }
        return jp_number(j);
    default:
        if (c >= '0' && c <= '9') return jp_number(j);
        j->bail = 1;
        return NULL;
    }
}

/* ingest_chunk(data: bytes, final: int, fallback, skip, torn)
 *   -> (ops: list, consumed: int, torn: int, truncated: int)
 *
 * Newline scan + per-line parse with WalTailer.poll's tolerant
 * contract: whitespace-only lines skipped uncounted, terminated
 * malformed lines counted torn, the unterminated tail left unconsumed
 * unless `final` (then dropped + counted). Lines this parser can't
 * guarantee go to `fallback(line_bytes)`, which returns the parsed op,
 * `skip` (whitespace-only after decode) or `torn` (JSONDecodeError). */
/* line-template cache: whole-line memo for the op-record steady state.
 * A WAL under load repeats a small set of line shapes (same keys, enum
 * values, small value domains), so a byte-identical line can skip the
 * parser: the result is PyDict_Copy of the cached template (CPython
 * clones the keys table wholesale) plus a fresh one-level copy of any
 * top-level list value — lists are mutable, and handing two ops the
 * SAME list object would be observable aliasing json.loads never
 * produces. Only lines whose parse is a flat dict of immutable scalars
 * (or lists thereof) are cached; everything else misses every time at
 * the cost of one hash+probe. First come, first kept — no eviction. */
#define LT_SLOTS 1024 /* power of two */
#define LT_MAXLEN 96
#define LT_MAXLISTS 4
typedef struct {
    unsigned char len;
    unsigned char nlists;
    unsigned char b[LT_MAXLEN];
    PyObject *tmpl;                  /* owned template dict */
    PyObject *listkeys[LT_MAXLISTS]; /* owned; values needing a copy */
} ltent;
static ltent g_lt[LT_SLOTS];

static uint64_t lt_hash(const unsigned char *p, Py_ssize_t n) {
    uint64_t h = 1469598103934665603ULL;
    for (Py_ssize_t i = 0; i < n; i++) h = (h ^ p[i]) * 1099511628211ULL;
    return h;
}

static int lt_scalar_ok(PyObject *v) {
    return v == Py_None || v == Py_True || v == Py_False ||
           PyLong_CheckExact(v) || PyFloat_CheckExact(v) ||
           PyUnicode_CheckExact(v);
}

/* new ref on hit; NULL on miss (no exception) or on error (exception
 * set — caller must check PyErr_Occurred) */
static PyObject *lt_lookup(const unsigned char *p, Py_ssize_t n) {
    if (n > LT_MAXLEN || n == 0) return NULL;
    size_t idx = (size_t)lt_hash(p, n) & (LT_SLOTS - 1);
    for (int probe = 0; probe < 4; probe++) {
        ltent *e = &g_lt[(idx + probe) & (LT_SLOTS - 1)];
        if (!e->tmpl) return NULL; /* empty slot: definitive miss */
        if (e->len != n || memcmp(e->b, p, (size_t)n) != 0) continue;
        PyObject *d = PyDict_Copy(e->tmpl);
        if (!d) return NULL;
        for (int i = 0; i < e->nlists; i++) {
            PyObject *lv = PyDict_GetItemWithError(d, e->listkeys[i]);
            if (!lv) {
                Py_DECREF(d);
                if (!PyErr_Occurred())
                    PyErr_SetString(PyExc_SystemError, "lt key vanished");
                return NULL;
            }
            PyObject *c = PyList_GetSlice(lv, 0, PyList_GET_SIZE(lv));
            if (!c || PyDict_SetItem(d, e->listkeys[i], c) < 0) {
                Py_XDECREF(c);
                Py_DECREF(d);
                return NULL;
            }
            Py_DECREF(c);
        }
        return d;
    }
    return NULL;
}

/* best-effort: cache `d` (the fresh parse of line p[:n]) when its shape
 * is safely copyable; failures just skip the insert */
static void lt_maybe_insert(const unsigned char *p, Py_ssize_t n,
                            PyObject *d) {
    if (n > LT_MAXLEN || n == 0 || !PyDict_CheckExact(d)) return;
    PyObject *lk[LT_MAXLISTS];
    int nl = 0;
    PyObject *k, *v;
    Py_ssize_t pos = 0;
    while (PyDict_Next(d, &pos, &k, &v)) {
        if (!PyUnicode_CheckExact(k)) return;
        if (lt_scalar_ok(v)) continue;
        if (PyList_CheckExact(v)) {
            if (nl == LT_MAXLISTS) return;
            for (Py_ssize_t i = 0; i < PyList_GET_SIZE(v); i++)
                if (!lt_scalar_ok(PyList_GET_ITEM(v, i))) return;
            lk[nl++] = k;
            continue;
        }
        return; /* nested dict / exotic value: not cacheable */
    }
    size_t idx = (size_t)lt_hash(p, n) & (LT_SLOTS - 1);
    ltent *e = NULL;
    for (int probe = 0; probe < 4; probe++) {
        ltent *cand = &g_lt[(idx + probe) & (LT_SLOTS - 1)];
        if (!cand->tmpl) {
            e = cand;
            break;
        }
        if (cand->len == n && memcmp(cand->b, p, (size_t)n) == 0)
            return; /* already cached (racing inserts can't happen: GIL) */
    }
    if (!e) return; /* neighborhood full */
    /* the template must be isolated from the dict we hand the caller:
     * copy it, and give the copy its own list objects too */
    PyObject *t = PyDict_Copy(d);
    if (!t) {
        PyErr_Clear();
        return;
    }
    for (int i = 0; i < nl; i++) {
        PyObject *lv = PyDict_GetItemWithError(t, lk[i]);
        PyObject *c = lv ? PyList_GetSlice(lv, 0, PyList_GET_SIZE(lv))
                         : NULL;
        if (!c || PyDict_SetItem(t, lk[i], c) < 0) {
            Py_XDECREF(c);
            Py_DECREF(t);
            PyErr_Clear();
            return;
        }
        Py_DECREF(c);
    }
    e->len = (unsigned char)n;
    e->nlists = (unsigned char)nl;
    memcpy(e->b, p, (size_t)n);
    e->tmpl = t;
    for (int i = 0; i < nl; i++) {
        Py_INCREF(lk[i]);
        e->listkeys[i] = lk[i];
    }
}

static PyObject *ingest_chunk(PyObject *self, PyObject *args) {
    (void)self;
    Py_buffer view;
    int final;
    PyObject *fallback, *skip_sent, *torn_sent;
    if (!PyArg_ParseTuple(args, "y*pOOO", &view, &final, &fallback,
                          &skip_sent, &torn_sent))
        return NULL;
    const unsigned char *data = (const unsigned char *)view.buf;
    Py_ssize_t len = view.len;
    PyObject *ops = PyList_New(0);
    if (!ops) {
        PyBuffer_Release(&view);
        return NULL;
    }
    Py_ssize_t pos = 0, consumed = 0;
    long torn = 0;
    int truncated = 0;
    while (pos < len) {
        const unsigned char *nl = (const unsigned char *)memchr(
            data + pos, '\n', (size_t)(len - pos));
        if (!nl) break;
        Py_ssize_t lstart = pos, lend = (Py_ssize_t)(nl - data);
        pos = lend + 1;
        consumed = pos;
        PyObject *hit = lt_lookup(data + lstart, lend - lstart);
        if (hit) {
            if (PyList_Append(ops, hit) < 0) {
                Py_DECREF(hit);
                goto err;
            }
            Py_DECREF(hit);
            continue;
        }
        if (PyErr_Occurred()) goto err;
        JP j;
        j.p = data + lstart;
        j.end = data + lend;
        j.bail = 0;
        j.depth = 0;
        jp_ws(&j);
        if (j.p >= j.end) continue; /* empty / json-ws-only line */
        PyObject *v = jp_value(&j);
        if (v) {
            jp_ws(&j);
            if (j.p >= j.end) { /* clean parse, no trailing garbage */
                lt_maybe_insert(data + lstart, lend - lstart, v);
                if (PyList_Append(ops, v) < 0) {
                    Py_DECREF(v);
                    goto err;
                }
                Py_DECREF(v);
                continue;
            }
            Py_DECREF(v); /* trailing garbage: json.loads would raise */
        } else if (!j.bail) {
            goto err; /* real exception (MemoryError etc.) */
        }
        /* fallback: Python decides parse / skip / torn for this line */
        {
            PyObject *line = PyBytes_FromStringAndSize(
                (const char *)(data + lstart), lend - lstart);
            if (!line) goto err;
            PyObject *r = PyObject_CallFunctionObjArgs(fallback, line,
                                                       NULL);
            Py_DECREF(line);
            if (!r) goto err;
            if (r == torn_sent) {
                torn++;
            } else if (r != skip_sent) {
                if (PyList_Append(ops, r) < 0) {
                    Py_DECREF(r);
                    goto err;
                }
            }
            Py_DECREF(r);
        }
    }
    if (final && consumed < len) {
        truncated = 1;
        torn++;
        consumed = len;
    }
    PyBuffer_Release(&view);
    return Py_BuildValue("(Nnli)", ops, consumed, torn, truncated);
err:
    PyBuffer_Release(&view);
    Py_DECREF(ops);
    return NULL;
}

/* -------------------- canonical-column append ------------------------ */

/* mirrors history.Intern.id (keep_original=0) and
 * history_ir.ir.ValueIntern.id (keep_original=1); returns a NEW ref to
 * the id int, or NULL with an exception set */
static PyObject *intern_id_c(PyObject *ids, PyObject *table, PyObject *v,
                             int keep_original) {
    PyObject *key = v, *keyref = NULL;
    PyObject *got = PyDict_GetItemWithError(ids, v);
    if (!got && PyErr_Occurred()) {
        if (!PyErr_ExceptionMatches(PyExc_TypeError)) return NULL;
        PyErr_Clear(); /* unhashable: freeze by repr, like the twins */
        PyObject *r = PyObject_Repr(v);
        if (!r) return NULL;
        keyref = PyTuple_Pack(2, g_s_unhash, r);
        Py_DECREF(r);
        if (!keyref) return NULL;
        key = keyref;
        got = PyDict_GetItemWithError(ids, key);
        if (!got && PyErr_Occurred()) {
            Py_DECREF(keyref);
            return NULL;
        }
    }
    if (got) {
        Py_INCREF(got);
        Py_XDECREF(keyref);
        return got;
    }
    PyObject *idx = PyLong_FromSsize_t(PyList_GET_SIZE(table));
    if (!idx) {
        Py_XDECREF(keyref);
        return NULL;
    }
    if (PyDict_SetItem(ids, key, idx) < 0 ||
        PyList_Append(table, keep_original ? v : key) < 0) {
        Py_DECREF(idx);
        Py_XDECREF(keyref);
        return NULL;
    }
    Py_XDECREF(keyref);
    return idx;
}

/* builder_extend(ops, start, state) -> count appended
 *
 * state = (ops_out, types, procs, fs, times, indices, value_ids,
 *          values, completion_of, invocation_of, open_invoke,
 *          f_ids, f_table, v_ids, v_table, py_add)
 *
 * Appends ops[start:] into IncrementalHistoryBuilder's own columns,
 * in the exact mutation order of builder.add; any op outside the fast
 * regime goes through py_add (the bound builder.add) instead, so the
 * resulting state is indistinguishable from N sequential add() calls. */
static PyObject *builder_extend(PyObject *self, PyObject *args) {
    (void)self;
    PyObject *ops, *st;
    Py_ssize_t start;
    if (!PyArg_ParseTuple(args, "O!nO!", &PyList_Type, &ops, &start,
                          &PyTuple_Type, &st))
        return NULL;
    if (PyTuple_GET_SIZE(st) != 16) {
        PyErr_SetString(PyExc_ValueError, "builder state tuple != 16");
        return NULL;
    }
    PyObject *ops_out = PyTuple_GET_ITEM(st, 0);
    PyObject *types = PyTuple_GET_ITEM(st, 1);
    PyObject *procs = PyTuple_GET_ITEM(st, 2);
    PyObject *fs = PyTuple_GET_ITEM(st, 3);
    PyObject *times = PyTuple_GET_ITEM(st, 4);
    PyObject *indices = PyTuple_GET_ITEM(st, 5);
    PyObject *value_ids = PyTuple_GET_ITEM(st, 6);
    PyObject *values = PyTuple_GET_ITEM(st, 7);
    PyObject *completion_of = PyTuple_GET_ITEM(st, 8);
    PyObject *invocation_of = PyTuple_GET_ITEM(st, 9);
    PyObject *open_invoke = PyTuple_GET_ITEM(st, 10);
    PyObject *f_ids = PyTuple_GET_ITEM(st, 11);
    PyObject *f_table = PyTuple_GET_ITEM(st, 12);
    PyObject *v_ids = PyTuple_GET_ITEM(st, 13);
    PyObject *v_table = PyTuple_GET_ITEM(st, 14);
    PyObject *py_add = PyTuple_GET_ITEM(st, 15);
    for (int i2 = 0; i2 < 10; i2++) {
        if (!PyList_CheckExact(PyTuple_GET_ITEM(st, i2)) && i2 != 0) {
            PyErr_SetString(PyExc_TypeError, "builder columns not lists");
            return NULL;
        }
    }
    if (!PyList_CheckExact(ops_out) || !PyDict_CheckExact(open_invoke) ||
        !PyDict_CheckExact(f_ids) || !PyList_CheckExact(f_table) ||
        !PyDict_CheckExact(v_ids) || !PyList_CheckExact(v_table)) {
        PyErr_SetString(PyExc_TypeError, "builder state shape");
        return NULL;
    }
    Py_ssize_t n = PyList_GET_SIZE(ops);
    Py_ssize_t added = 0;
    for (Py_ssize_t k = start; k < n; k++) {
        PyObject *op = PyList_GET_ITEM(ops, k);
        long code = 3;
        PyObject *typ = NULL, *f = NULL;
        int slow = 0;
        if (!PyDict_CheckExact(op)) {
            slow = 1;
        } else {
            typ = PyDict_GetItemWithError(op, g_s_type);
            if (!typ && PyErr_Occurred()) return NULL;
            if (typ == NULL || typ == Py_None) {
                code = 3;
            } else if (PyUnicode_CheckExact(typ)) {
                if (PyUnicode_CompareWithASCIIString(typ, "invoke") == 0)
                    code = 0;
                else if (PyUnicode_CompareWithASCIIString(typ, "ok") == 0)
                    code = 1;
                else if (PyUnicode_CompareWithASCIIString(typ, "fail") ==
                         0)
                    code = 2;
                else if (PyUnicode_CompareWithASCIIString(typ, "info") ==
                         0)
                    code = 3;
                else
                    code = 3;
            } else {
                slow = 1; /* exotic type key: TYPE_CODE.get semantics */
            }
            if (!slow) {
                f = PyDict_GetItemWithError(op, g_s_f);
                if (!f && PyErr_Occurred()) return NULL;
                if (f != NULL && f != Py_None &&
                    !PyUnicode_CheckExact(f) && !PyLong_CheckExact(f))
                    slow = 1; /* keep intern semantics provable */
            }
        }
        if (slow) {
            PyObject *r = PyObject_CallFunctionObjArgs(py_add, op, NULL);
            if (!r) return NULL;
            Py_DECREF(r);
            added++;
            continue;
        }
        Py_ssize_t i = PyList_GET_SIZE(ops_out);
        PyObject *i_obj = PyLong_FromSsize_t(i);
        if (!i_obj) return NULL;
        if (PyList_Append(ops_out, op) < 0 ||
            PyList_Append(types, g_int[code]) < 0)
            goto operr;
        {
            PyObject *p = PyDict_GetItemWithError(op, g_s_process);
            if (!p && PyErr_Occurred()) goto operr;
            if (PyList_Append(procs,
                              (p && PyLong_Check(p)) ? p : g_m1) < 0)
                goto operr;
            PyObject *fid = intern_id_c(f_ids, f_table,
                                        f ? f : Py_None, 0);
            if (!fid) goto operr;
            int rc = PyList_Append(fs, fid);
            Py_DECREF(fid);
            if (rc < 0) goto operr;
            PyObject *t = PyDict_GetItemWithError(op, g_s_time);
            if (!t && PyErr_Occurred()) goto operr;
            if (t) {
                int tr = PyObject_IsTrue(t);
                if (tr < 0) goto operr;
                if (PyList_Append(times, tr ? t : g_int[0]) < 0)
                    goto operr;
            } else if (PyList_Append(times, g_int[0]) < 0) {
                goto operr;
            }
            PyObject *idx = PyDict_GetItemWithError(op, g_s_index);
            if (!idx && PyErr_Occurred()) goto operr;
            if (PyList_Append(indices,
                              (idx && idx != Py_None) ? idx : i_obj) < 0)
                goto operr;
            PyObject *v = PyDict_GetItemWithError(op, g_s_value);
            if (!v && PyErr_Occurred()) goto operr;
            if (!v) v = Py_None;
            if (PyList_Append(values, v) < 0) goto operr;
            PyObject *vid = intern_id_c(v_ids, v_table, v, 1);
            if (!vid) goto operr;
            rc = PyList_Append(value_ids, vid);
            Py_DECREF(vid);
            if (rc < 0) goto operr;
            if (PyList_Append(completion_of, g_m1) < 0 ||
                PyList_Append(invocation_of, g_m1) < 0)
                goto operr;
            /* invoke/completion cross-linking, keyed by raw process */
            PyObject *pkey = p ? p : Py_None;
            if (code == 0 &&
                PyUnicode_CompareWithASCIIString(typ, "invoke") == 0) {
                if (PyDict_SetItem(open_invoke, pkey, i_obj) < 0)
                    goto operr;
            } else {
                PyObject *jj = PyDict_GetItemWithError(open_invoke, pkey);
                if (!jj && PyErr_Occurred()) goto operr;
                if (jj) {
                    Py_INCREF(jj);
                    if (PyDict_DelItem(open_invoke, pkey) < 0) {
                        Py_DECREF(jj);
                        goto operr;
                    }
                    Py_ssize_t ji = PyLong_AsSsize_t(jj);
                    if (ji == -1 && PyErr_Occurred()) {
                        Py_DECREF(jj);
                        goto operr;
                    }
                    if (ji < 0 || ji >= PyList_GET_SIZE(completion_of)) {
                        Py_DECREF(jj);
                        PyErr_SetString(PyExc_IndexError,
                                        "open invoke out of range");
                        goto operr;
                    }
                    Py_INCREF(i_obj);
                    if (PyList_SetItem(completion_of, ji, i_obj) < 0) {
                        Py_DECREF(jj);
                        goto operr;
                    }
                    if (PyList_SetItem(invocation_of, i, jj) < 0)
                        goto operr; /* both steal their reference */
                }
            }
        }
        Py_DECREF(i_obj);
        added++;
        continue;
    operr:
        Py_DECREF(i_obj);
        return NULL;
    }
    return PyLong_FromSsize_t(added);
}

/* -------------------- live register encoder --------------------------
 * Twins of LiveRegisterEncoder.add / encode_resolved
 * (history_ir/builder.py) for the default-args single-register session.
 * EV_INVOKE/EV_RETURN = 0/1 and CAS_F_READ/WRITE/CAS = 0/1/2 are
 * hardcoded; the Python wrapper asserts them at import. */

/* pop(d, key) -> new ref or NULL (check PyErr_Occurred) */
static PyObject *dict_pop(PyObject *d, PyObject *k) {
    /* the missing-vs-error split is this helper's documented contract:
     * every caller checks PyErr_Occurred on NULL (see enc_step_ok).
     * lint: ignore[jtn-errcheck] */
    PyObject *v = PyDict_GetItemWithError(d, k);
    if (!v) return NULL;
    Py_INCREF(v);
    if (PyDict_DelItem(d, k) < 0) {
        Py_DECREF(v);
        return NULL;
    }
    return v;
}

/* p is a usable process iff isinstance(p, int) and p >= 0 */
static int proc_ok(PyObject *p) {
    if (!p || !PyLong_Check(p)) return 0;
    int ovf = 0;
    long long v = PyLong_AsLongLongAndOverflow(p, &ovf);
    if (ovf > 0) return 1;  /* huge positive */
    if (ovf < 0) return 0;  /* huge negative */
    return v >= 0;
}

/* register_add(ops, start, state) -> count
 * state = (_ops, open_inv, outcome, py_add) */
static PyObject *register_add(PyObject *self, PyObject *args) {
    (void)self;
    PyObject *ops, *st;
    Py_ssize_t start;
    if (!PyArg_ParseTuple(args, "O!nO!", &PyList_Type, &ops, &start,
                          &PyTuple_Type, &st))
        return NULL;
    if (PyTuple_GET_SIZE(st) != 4) {
        PyErr_SetString(PyExc_ValueError, "register state tuple != 4");
        return NULL;
    }
    PyObject *enc_ops = PyTuple_GET_ITEM(st, 0);
    PyObject *open_inv = PyTuple_GET_ITEM(st, 1);
    PyObject *outcome = PyTuple_GET_ITEM(st, 2);
    PyObject *py_add = PyTuple_GET_ITEM(st, 3);
    if (!PyList_CheckExact(enc_ops) || !PyDict_CheckExact(open_inv) ||
        !PyDict_CheckExact(outcome)) {
        PyErr_SetString(PyExc_TypeError, "register state shape");
        return NULL;
    }
    Py_ssize_t n = PyList_GET_SIZE(ops);
    for (Py_ssize_t k = start; k < n; k++) {
        PyObject *op = PyList_GET_ITEM(ops, k);
        if (!PyDict_CheckExact(op)) {
            PyObject *r = PyObject_CallFunctionObjArgs(py_add, op, NULL);
            if (!r) return NULL;
            Py_DECREF(r);
            continue;
        }
        Py_ssize_t i = PyList_GET_SIZE(enc_ops);
        if (PyList_Append(enc_ops, op) < 0) return NULL;
        PyObject *p = PyDict_GetItemWithError(op, g_s_process);
        if (!p && PyErr_Occurred()) return NULL;
        if (!proc_ok(p)) continue;
        PyObject *typ = PyDict_GetItemWithError(op, g_s_type);
        if (!typ && PyErr_Occurred()) return NULL;
        if (!typ || !PyUnicode_CheckExact(typ)) continue;
        PyObject *j = NULL;
        if (PyUnicode_CompareWithASCIIString(typ, "invoke") == 0) {
            j = dict_pop(open_inv, p);
            if (!j && PyErr_Occurred()) return NULL;
            if (j) { /* same process re-invokes: prior op resolves keep */
                if (PyDict_SetItem(outcome, j, g_keep) < 0) {
                    Py_DECREF(j);
                    return NULL;
                }
                Py_DECREF(j);
            }
            PyObject *i_obj = PyLong_FromSsize_t(i);
            if (!i_obj) return NULL;
            int rc = PyDict_SetItem(open_inv, p, i_obj);
            Py_DECREF(i_obj);
            if (rc < 0) return NULL;
        } else if (PyUnicode_CompareWithASCIIString(typ, "ok") == 0) {
            j = dict_pop(open_inv, p);
            if (!j && PyErr_Occurred()) return NULL;
            if (j) {
                PyObject *v = PyDict_GetItemWithError(op, g_s_value);
                if (!v && PyErr_Occurred()) {
                    Py_DECREF(j);
                    return NULL;
                }
                PyObject *out;
                if (v && v != Py_None) {
                    out = PyTuple_Pack(2, g_s_ok, v);
                    if (!out) {
                        Py_DECREF(j);
                        return NULL;
                    }
                } else {
                    out = g_keep;
                    Py_INCREF(out);
                }
                int rc = PyDict_SetItem(outcome, j, out);
                Py_DECREF(out);
                Py_DECREF(j);
                if (rc < 0) return NULL;
            }
        } else if (PyUnicode_CompareWithASCIIString(typ, "fail") == 0) {
            j = dict_pop(open_inv, p);
            if (!j && PyErr_Occurred()) return NULL;
            if (j) {
                int rc = PyDict_SetItem(outcome, j, g_drop);
                Py_DECREF(j);
                if (rc < 0) return NULL;
            }
        } else if (PyUnicode_CompareWithASCIIString(typ, "info") == 0) {
            j = dict_pop(open_inv, p);
            if (!j && PyErr_Occurred()) return NULL;
            if (j) {
                Py_ssize_t ji = PyLong_AsSsize_t(j);
                if (ji == -1 && PyErr_Occurred()) {
                    Py_DECREF(j);
                    return NULL;
                }
                PyObject *inv = (ji >= 0 &&
                                 ji < PyList_GET_SIZE(enc_ops))
                                    ? PyList_GET_ITEM(enc_ops, ji)
                                    : NULL;
                if (!inv || !PyDict_CheckExact(inv)) {
                    Py_DECREF(j);
                    PyErr_SetString(PyExc_AttributeError,
                                    "invocation is not a dict");
                    return NULL;
                }
                PyObject *fj = PyDict_GetItemWithError(inv, g_s_f);
                if (!fj && PyErr_Occurred()) {
                    Py_DECREF(j);
                    return NULL;
                }
                int rd = PyObject_RichCompareBool(fj ? fj : Py_None,
                                                  g_s_read, Py_EQ);
                if (rd < 0) {
                    Py_DECREF(j);
                    return NULL;
                }
                int rc = PyDict_SetItem(outcome, j, rd ? g_drop : g_keep);
                Py_DECREF(j);
                if (rc < 0) return NULL;
            }
        }
    }
    return PyLong_FromSsize_t(n - start);
}

/* Shared encode-step machinery: ONE copy of the invoke/ok advance,
 * used by register_encode and the fused register_add_encode so the
 * two entries cannot drift. */
typedef struct {
    PyObject *outcome, *open_bp, *free_slots;
    PyObject *kindl, *slotl, *fl, *al, *bl, *oil;
    PyObject *ids, *table;
    Py_ssize_t next_slot, n_slots;
    int finalized;
} encst;

/* Invoke op at enc_ops index i. have=1 means outc is authoritative
 * (possibly NULL = unresolved); have=0 looks outcome[i] up.
 * Returns 0 advance, 1 stall, 2 bail, -1 error. */
static int enc_step_invoke(encst *E, PyObject *op, PyObject *p,
                           Py_ssize_t i, PyObject *outc, int have) {
    PyObject *i_obj = PyLong_FromSsize_t(i);
    if (!i_obj) return -1;
    if (!have) {
        outc = PyDict_GetItemWithError(E->outcome, i_obj);
        if (!outc && PyErr_Occurred()) {
            Py_DECREF(i_obj);
            return -1;
        }
    }
    int is_drop = 0, is_ok = 0;
    if (outc) {
        if (!PyTuple_CheckExact(outc) || PyTuple_GET_SIZE(outc) < 1) {
            Py_DECREF(i_obj);
            return 2;
        }
        PyObject *tag = PyTuple_GET_ITEM(outc, 0);
        if (PyUnicode_CheckExact(tag)) {
            is_drop = PyUnicode_CompareWithASCIIString(tag, "drop") == 0;
            is_ok = PyUnicode_CompareWithASCIIString(tag, "ok") == 0;
        }
    } else {
        if (!E->finalized) { /* stall: unresolved invoke */
            Py_DECREF(i_obj);
            return 1;
        }
        /* finalized: open read drops, open write/cas keeps */
        PyObject *fj = PyDict_GetItemWithError(op, g_s_f);
        if (!fj && PyErr_Occurred()) {
            Py_DECREF(i_obj);
            return -1;
        }
        int rd = PyObject_RichCompareBool(fj ? fj : Py_None, g_s_read,
                                          Py_EQ);
        if (rd < 0) {
            Py_DECREF(i_obj);
            return -1;
        }
        is_drop = rd;
    }
    if (is_drop) {
        Py_DECREF(i_obj);
        return 0;
    }
    /* pre-validate encode_args BEFORE mutating slot state so a bail
     * replays this op through Python from identical state */
    PyObject *fj = PyDict_GetItemWithError(op, g_s_f);
    if (!fj && PyErr_Occurred()) {
        Py_DECREF(i_obj);
        return -1;
    }
    long fcode = -1;
    if (fj && PyUnicode_CheckExact(fj)) {
        if (PyUnicode_CompareWithASCIIString(fj, "read") == 0)
            fcode = 0; /* CAS_F_READ */
        else if (PyUnicode_CompareWithASCIIString(fj, "write") == 0)
            fcode = 1; /* CAS_F_WRITE */
        else if (PyUnicode_CompareWithASCIIString(fj, "cas") == 0)
            fcode = 2; /* CAS_F_CAS */
    }
    PyObject *v = NULL;
    if (is_ok && PyTuple_GET_SIZE(outc) >= 2) {
        v = PyTuple_GET_ITEM(outc, 1);
    } else {
        v = PyDict_GetItemWithError(op, g_s_value);
        if (!v && PyErr_Occurred()) {
            Py_DECREF(i_obj);
            return -1;
        }
        if (!v) v = Py_None;
    }
    if (fcode < 0 ||
        (fcode == 2 &&
         !((PyList_CheckExact(v) && PyList_GET_SIZE(v) == 2) ||
           (PyTuple_CheckExact(v) && PyTuple_GET_SIZE(v) == 2)))) {
        Py_DECREF(i_obj);
        return 2; /* unknown f / non-pair cas: Python raises */
    }
    /* slot allocation */
    PyObject *s_obj;
    Py_ssize_t nfree = PyList_GET_SIZE(E->free_slots);
    if (nfree) {
        s_obj = PyList_GET_ITEM(E->free_slots, nfree - 1);
        Py_INCREF(s_obj);
        if (PyList_SetSlice(E->free_slots, nfree - 1, nfree, NULL) < 0) {
            Py_DECREF(s_obj);
            Py_DECREF(i_obj);
            return -1;
        }
    } else {
        s_obj = PyLong_FromSsize_t(E->next_slot);
        if (!s_obj) {
            Py_DECREF(i_obj);
            return -1;
        }
        E->next_slot++;
        if (E->next_slot > E->n_slots) E->n_slots = E->next_slot;
    }
    if (PyDict_SetItem(E->open_bp, p, s_obj) < 0) goto inverr;
    /* encode args (intern order: u then w, like the twin) */
    {
        PyObject *aobj, *bobj;
        if (fcode == 2) {
            PyObject *u = PySequence_Fast_GET_ITEM(v, 0);
            PyObject *w = PySequence_Fast_GET_ITEM(v, 1);
            aobj = intern_id_c(E->ids, E->table, u, 0);
            if (!aobj) goto inverr;
            bobj = intern_id_c(E->ids, E->table, w, 0);
            if (!bobj) {
                Py_DECREF(aobj);
                goto inverr;
            }
        } else {
            aobj = intern_id_c(E->ids, E->table, v, 0);
            if (!aobj) goto inverr;
            bobj = g_int[0];
            Py_INCREF(bobj);
        }
        int rc = 0;
        if (PyList_Append(E->kindl, g_int[0]) < 0 || /* EV_INVOKE */
            PyList_Append(E->slotl, s_obj) < 0 ||
            PyList_Append(E->fl, g_int[fcode]) < 0 ||
            PyList_Append(E->al, aobj) < 0 ||
            PyList_Append(E->bl, bobj) < 0 ||
            PyList_Append(E->oil, i_obj) < 0)
            rc = -1;
        Py_DECREF(aobj);
        Py_DECREF(bobj);
        if (rc < 0) goto inverr;
    }
    Py_DECREF(s_obj);
    Py_DECREF(i_obj);
    return 0;
inverr:
    Py_DECREF(s_obj);
    Py_DECREF(i_obj);
    return -1;
}

/* Completion ("ok") op at enc_ops index i. 0 advance, -1 error. */
static int enc_step_ok(encst *E, PyObject *p, Py_ssize_t i) {
    PyObject *s_obj = dict_pop(E->open_bp, p);
    if (!s_obj && PyErr_Occurred()) return -1;
    if (s_obj) {
        PyObject *i_obj = PyLong_FromSsize_t(i);
        if (!i_obj) {
            Py_DECREF(s_obj);
            return -1;
        }
        int rc = 0;
        if (PyList_Append(E->kindl, g_int[1]) < 0 || /* EV_RETURN */
            PyList_Append(E->slotl, s_obj) < 0 ||
            PyList_Append(E->fl, g_int[0]) < 0 ||
            PyList_Append(E->al, g_int[0]) < 0 ||
            PyList_Append(E->bl, g_int[0]) < 0 ||
            PyList_Append(E->oil, i_obj) < 0 ||
            PyList_Append(E->free_slots, s_obj) < 0)
            rc = -1;
        Py_DECREF(i_obj);
        Py_DECREF(s_obj);
        if (rc < 0) return -1;
    }
    return 0;
}

/* register_encode(state) -> (next, next_slot, n_slots, bailed)
 * state = (_ops, outcome, open_by_process, free_slots,
 *          kind, slot, f, a, b, op_index,
 *          intern_ids, intern_table, next, next_slot, n_slots,
 *          finalized)
 * On bail the returned cursor points AT the offending op with no
 * mutations for it; the wrapper re-runs the Python twin from there. */
static PyObject *register_encode(PyObject *self, PyObject *args) {
    (void)self;
    PyObject *st;
    if (!PyArg_ParseTuple(args, "O!", &PyTuple_Type, &st)) return NULL;
    if (PyTuple_GET_SIZE(st) != 16) {
        PyErr_SetString(PyExc_ValueError, "encode state tuple != 16");
        return NULL;
    }
    PyObject *enc_ops = PyTuple_GET_ITEM(st, 0);
    PyObject *outcome = PyTuple_GET_ITEM(st, 1);
    PyObject *open_bp = PyTuple_GET_ITEM(st, 2);
    PyObject *free_slots = PyTuple_GET_ITEM(st, 3);
    PyObject *kindl = PyTuple_GET_ITEM(st, 4);
    PyObject *slotl = PyTuple_GET_ITEM(st, 5);
    PyObject *fl = PyTuple_GET_ITEM(st, 6);
    PyObject *al = PyTuple_GET_ITEM(st, 7);
    PyObject *bl = PyTuple_GET_ITEM(st, 8);
    PyObject *oil = PyTuple_GET_ITEM(st, 9);
    PyObject *ids = PyTuple_GET_ITEM(st, 10);
    PyObject *table = PyTuple_GET_ITEM(st, 11);
    Py_ssize_t i = PyLong_AsSsize_t(PyTuple_GET_ITEM(st, 12));
    Py_ssize_t next_slot = PyLong_AsSsize_t(PyTuple_GET_ITEM(st, 13));
    Py_ssize_t n_slots = PyLong_AsSsize_t(PyTuple_GET_ITEM(st, 14));
    int finalized = PyObject_IsTrue(PyTuple_GET_ITEM(st, 15));
    if (PyErr_Occurred()) return NULL;
    if (!PyList_CheckExact(enc_ops) || !PyDict_CheckExact(outcome) ||
        !PyDict_CheckExact(open_bp) || !PyList_CheckExact(free_slots) ||
        !PyList_CheckExact(kindl) || !PyList_CheckExact(slotl) ||
        !PyList_CheckExact(fl) || !PyList_CheckExact(al) ||
        !PyList_CheckExact(bl) || !PyList_CheckExact(oil) ||
        !PyDict_CheckExact(ids) || !PyList_CheckExact(table)) {
        PyErr_SetString(PyExc_TypeError, "encode state shape");
        return NULL;
    }
    Py_ssize_t n = PyList_GET_SIZE(enc_ops);
    int bailed = 0;
    encst E = {outcome, open_bp, free_slots, kindl, slotl, fl, al, bl,
               oil, ids, table, next_slot, n_slots, finalized};
    while (i < n) {
        PyObject *op = PyList_GET_ITEM(enc_ops, i);
        if (!PyDict_CheckExact(op)) {
            bailed = 1;
            break;
        }
        PyObject *p = PyDict_GetItemWithError(op, g_s_process);
        if (!p && PyErr_Occurred()) return NULL;
        if (!proc_ok(p)) {
            i++;
            continue;
        }
        PyObject *typ = PyDict_GetItemWithError(op, g_s_type);
        if (!typ && PyErr_Occurred()) return NULL;
        if (!typ || !PyUnicode_CheckExact(typ)) {
            i++;
            continue;
        }
        if (PyUnicode_CompareWithASCIIString(typ, "invoke") == 0) {
            int rc = enc_step_invoke(&E, op, p, i, NULL, 0);
            if (rc < 0) return NULL;
            if (rc == 1) break; /* stall */
            if (rc == 2) {
                bailed = 1;
                break;
            }
            i++;
            continue;
        }
        if (PyUnicode_CompareWithASCIIString(typ, "ok") == 0) {
            if (enc_step_ok(&E, p, i) < 0) return NULL;
        }
        i++;
    }
    return Py_BuildValue("(nnni)", i, E.next_slot, E.n_slots, bailed);
}

/* Per-op field cache filled by the fused add pass and consumed by its
 * encode pass, so each chunk dict is classified once. typec: 0 invoke,
 * 1 ok, 2 fail, 3 info, 4 no-encode-action. outc mirrors outcome[i]
 * writes made during THIS call (borrowed from the outcome dict, which
 * outlives the call); NULL = unresolved, authoritative for indices
 * appended by this call since older calls could not have resolved
 * ops that did not exist yet. */
typedef struct {
    PyObject *proc; /* borrowed from the op dict */
    PyObject *outc; /* borrowed from the outcome dict */
    int8_t typec;
} opmeta;

/* register_add_encode(ops, start, add_state, enc_state)
 * -> (next, next_slot, n_slots, enc_ran, bailed)
 * One pass over the chunk: LiveRegisterEncoder.add bookkeeping with
 * the per-op classification cached, then encode_resolved consuming
 * the cache — the chunk's dicts are inspected once instead of twice.
 * The encode phase is skipped (enc_ran=0) when the chunk held non-
 * dict ops (py_add may append extra entries, shifting indices); the
 * caller's next encode_resolved covers it from identical state. */
static PyObject *register_add_encode(PyObject *self, PyObject *args) {
    (void)self;
    PyObject *ops, *ast, *est;
    Py_ssize_t start;
    if (!PyArg_ParseTuple(args, "O!nO!O!", &PyList_Type, &ops, &start,
                          &PyTuple_Type, &ast, &PyTuple_Type, &est))
        return NULL;
    if (PyTuple_GET_SIZE(ast) != 4 || PyTuple_GET_SIZE(est) != 16) {
        PyErr_SetString(PyExc_ValueError, "add/encode state tuple size");
        return NULL;
    }
    PyObject *enc_ops = PyTuple_GET_ITEM(ast, 0);
    PyObject *open_inv = PyTuple_GET_ITEM(ast, 1);
    PyObject *outcome = PyTuple_GET_ITEM(ast, 2);
    PyObject *py_add = PyTuple_GET_ITEM(ast, 3);
    PyObject *open_bp = PyTuple_GET_ITEM(est, 2);
    PyObject *free_slots = PyTuple_GET_ITEM(est, 3);
    PyObject *kindl = PyTuple_GET_ITEM(est, 4);
    PyObject *slotl = PyTuple_GET_ITEM(est, 5);
    PyObject *fl = PyTuple_GET_ITEM(est, 6);
    PyObject *al = PyTuple_GET_ITEM(est, 7);
    PyObject *bl = PyTuple_GET_ITEM(est, 8);
    PyObject *oil = PyTuple_GET_ITEM(est, 9);
    PyObject *ids = PyTuple_GET_ITEM(est, 10);
    PyObject *table = PyTuple_GET_ITEM(est, 11);
    Py_ssize_t next = PyLong_AsSsize_t(PyTuple_GET_ITEM(est, 12));
    Py_ssize_t next_slot = PyLong_AsSsize_t(PyTuple_GET_ITEM(est, 13));
    Py_ssize_t n_slots = PyLong_AsSsize_t(PyTuple_GET_ITEM(est, 14));
    int finalized = PyObject_IsTrue(PyTuple_GET_ITEM(est, 15));
    if (PyErr_Occurred()) return NULL;
    if (PyTuple_GET_ITEM(est, 0) != enc_ops ||
        PyTuple_GET_ITEM(est, 1) != outcome ||
        !PyList_CheckExact(enc_ops) || !PyDict_CheckExact(open_inv) ||
        !PyDict_CheckExact(outcome) || !PyDict_CheckExact(open_bp) ||
        !PyList_CheckExact(free_slots) || !PyList_CheckExact(kindl) ||
        !PyList_CheckExact(slotl) || !PyList_CheckExact(fl) ||
        !PyList_CheckExact(al) || !PyList_CheckExact(bl) ||
        !PyList_CheckExact(oil) || !PyDict_CheckExact(ids) ||
        !PyList_CheckExact(table)) {
        PyErr_SetString(PyExc_TypeError, "add/encode state shape");
        return NULL;
    }
    Py_ssize_t n = PyList_GET_SIZE(ops);
    Py_ssize_t base = PyList_GET_SIZE(enc_ops);
    Py_ssize_t ncache = n > start ? n - start : 0;
    opmeta *meta = NULL;
    int enc_ok = 1;
    if (ncache) {
        meta = (opmeta *)calloc((size_t)ncache, sizeof(opmeta));
        if (!meta) return PyErr_NoMemory();
    }
    /* ---- add pass (twin of register_add, plus the cache fill) ---- */
    for (Py_ssize_t k = start; k < n; k++) {
        PyObject *op = PyList_GET_ITEM(ops, k);
        if (!PyDict_CheckExact(op)) {
            enc_ok = 0; /* py_add appends itself; indices shift */
            PyObject *r = PyObject_CallFunctionObjArgs(py_add, op, NULL);
            if (!r) goto adderr;
            Py_DECREF(r);
            continue;
        }
        Py_ssize_t i = PyList_GET_SIZE(enc_ops);
        if (PyList_Append(enc_ops, op) < 0) goto adderr;
        opmeta *mt = NULL;
        if (enc_ok && i >= base && i - base < ncache)
            mt = &meta[i - base];
        else
            enc_ok = 0;
        if (mt) mt->typec = 4;
        PyObject *p = PyDict_GetItemWithError(op, g_s_process);
        if (!p && PyErr_Occurred()) goto adderr;
        if (!proc_ok(p)) continue;
        PyObject *typ = PyDict_GetItemWithError(op, g_s_type);
        if (!typ && PyErr_Occurred()) goto adderr;
        if (!typ || !PyUnicode_CheckExact(typ)) continue;
        PyObject *j = NULL;
        if (PyUnicode_CompareWithASCIIString(typ, "invoke") == 0) {
            if (mt) {
                mt->typec = 0;
                mt->proc = p;
            }
            j = dict_pop(open_inv, p);
            if (!j && PyErr_Occurred()) goto adderr;
            if (j) { /* same process re-invokes: prior op resolves keep */
                if (PyDict_SetItem(outcome, j, g_keep) < 0) {
                    Py_DECREF(j);
                    goto adderr;
                }
                Py_ssize_t jv = PyLong_AsSsize_t(j);
                Py_DECREF(j);
                if (jv == -1 && PyErr_Occurred()) goto adderr;
                if (jv >= base && jv - base < ncache)
                    meta[jv - base].outc = g_keep;
            }
            PyObject *i_obj = PyLong_FromSsize_t(i);
            if (!i_obj) goto adderr;
            int rc = PyDict_SetItem(open_inv, p, i_obj);
            Py_DECREF(i_obj);
            if (rc < 0) goto adderr;
        } else if (PyUnicode_CompareWithASCIIString(typ, "ok") == 0) {
            if (mt) {
                mt->typec = 1;
                mt->proc = p;
            }
            j = dict_pop(open_inv, p);
            if (!j && PyErr_Occurred()) goto adderr;
            if (j) {
                PyObject *v = PyDict_GetItemWithError(op, g_s_value);
                if (!v && PyErr_Occurred()) {
                    Py_DECREF(j);
                    goto adderr;
                }
                PyObject *out;
                if (v && v != Py_None) {
                    out = PyTuple_Pack(2, g_s_ok, v);
                    if (!out) {
                        Py_DECREF(j);
                        goto adderr;
                    }
                } else {
                    out = g_keep;
                    Py_INCREF(out);
                }
                int rc = PyDict_SetItem(outcome, j, out);
                Py_ssize_t jv = PyLong_AsSsize_t(j);
                Py_DECREF(j);
                if (rc < 0 || (jv == -1 && PyErr_Occurred())) {
                    Py_DECREF(out);
                    goto adderr;
                }
                if (jv >= base && jv - base < ncache)
                    meta[jv - base].outc = out; /* dict keeps it alive */
                Py_DECREF(out);
            }
        } else if (PyUnicode_CompareWithASCIIString(typ, "fail") == 0) {
            if (mt) mt->typec = 2;
            j = dict_pop(open_inv, p);
            if (!j && PyErr_Occurred()) goto adderr;
            if (j) {
                int rc = PyDict_SetItem(outcome, j, g_drop);
                Py_ssize_t jv = PyLong_AsSsize_t(j);
                Py_DECREF(j);
                if (rc < 0 || (jv == -1 && PyErr_Occurred()))
                    goto adderr;
                if (jv >= base && jv - base < ncache)
                    meta[jv - base].outc = g_drop;
            }
        } else if (PyUnicode_CompareWithASCIIString(typ, "info") == 0) {
            if (mt) mt->typec = 3;
            j = dict_pop(open_inv, p);
            if (!j && PyErr_Occurred()) goto adderr;
            if (j) {
                Py_ssize_t ji = PyLong_AsSsize_t(j);
                if (ji == -1 && PyErr_Occurred()) {
                    Py_DECREF(j);
                    goto adderr;
                }
                PyObject *inv = (ji >= 0 && ji < PyList_GET_SIZE(enc_ops))
                                    ? PyList_GET_ITEM(enc_ops, ji)
                                    : NULL;
                if (!inv || !PyDict_CheckExact(inv)) {
                    Py_DECREF(j);
                    PyErr_SetString(PyExc_AttributeError,
                                    "invocation is not a dict");
                    goto adderr;
                }
                PyObject *fj = PyDict_GetItemWithError(inv, g_s_f);
                if (!fj && PyErr_Occurred()) {
                    Py_DECREF(j);
                    goto adderr;
                }
                int rd = PyObject_RichCompareBool(fj ? fj : Py_None,
                                                  g_s_read, Py_EQ);
                if (rd < 0) {
                    Py_DECREF(j);
                    goto adderr;
                }
                int rc = PyDict_SetItem(outcome, j, rd ? g_drop : g_keep);
                Py_DECREF(j);
                if (rc < 0) goto adderr;
                if (ji >= base && ji - base < ncache)
                    meta[ji - base].outc = rd ? g_drop : g_keep;
            }
        }
    }
    /* ---- encode pass (twin of register_encode over the cache) ---- */
    {
        int bailed = 0;
        int enc_ran = enc_ok;
        Py_ssize_t i = next;
        if (enc_ok) {
            encst E = {outcome, open_bp, free_slots, kindl, slotl, fl,
                       al, bl, oil, ids, table, next_slot, n_slots,
                       finalized};
            Py_ssize_t ne = PyList_GET_SIZE(enc_ops);
            while (i < ne) {
                PyObject *op = PyList_GET_ITEM(enc_ops, i);
                if (!PyDict_CheckExact(op)) {
                    bailed = 1;
                    break;
                }
                int8_t tc;
                PyObject *p;
                PyObject *outc = NULL;
                int have = 0;
                if (i >= base && i - base < ncache) {
                    opmeta *mt = &meta[i - base];
                    tc = mt->typec;
                    p = mt->proc;
                    outc = mt->outc;
                    have = 1;
                } else { /* stalled op from an earlier chunk */
                    p = PyDict_GetItemWithError(op, g_s_process);
                    if (!p && PyErr_Occurred()) goto adderr;
                    if (!proc_ok(p)) {
                        i++;
                        continue;
                    }
                    PyObject *typ =
                        PyDict_GetItemWithError(op, g_s_type);
                    if (!typ && PyErr_Occurred()) goto adderr;
                    if (!typ || !PyUnicode_CheckExact(typ)) {
                        i++;
                        continue;
                    }
                    if (PyUnicode_CompareWithASCIIString(typ, "invoke") ==
                        0)
                        tc = 0;
                    else if (PyUnicode_CompareWithASCIIString(typ,
                                                              "ok") == 0)
                        tc = 1;
                    else
                        tc = 4;
                }
                if (tc == 0) {
                    int rc = enc_step_invoke(&E, op, p, i, outc, have);
                    if (rc < 0) goto adderr;
                    if (rc == 1) break; /* stall */
                    if (rc == 2) {
                        bailed = 1;
                        break;
                    }
                } else if (tc == 1) {
                    if (enc_step_ok(&E, p, i) < 0) goto adderr;
                }
                i++;
            }
            next_slot = E.next_slot;
            n_slots = E.n_slots;
        }
        free(meta);
        return Py_BuildValue("(nnnii)", i, next_slot, n_slots, enc_ran,
                             bailed);
    }
adderr:
    free(meta);
    return NULL;
}


/* -------------------- frontier absorb --------------------------------
 * Twin of checker/linear_cpu.FrontierSession.absorb for the hardcoded
 * cas-register step. Works entirely on copies: on success returns
 * replacement state, on bail/death returns a signal and the caller
 * replays the Python twin against the UNTOUCHED session (identical
 * result()/failure payloads). */

typedef struct {
    uint64_t *keys;   /* (mask << 1) | 1 sentinel-free packing unused; */
    int64_t *states;  /* parallel value array */
    uint8_t *used;
    size_t cap, n;
} cfgset;

static int cfg_init(cfgset *h, size_t cap) {
    h->cap = cap;
    h->n = 0;
    h->keys = (uint64_t *)calloc(cap, 8);
    h->states = (int64_t *)malloc(cap * 8);
    h->used = (uint8_t *)calloc(cap, 1);
    if (!h->keys || !h->states || !h->used) return -1;
    return 0;
}

static void cfg_free(cfgset *h) {
    free(h->keys);
    free(h->states);
    free(h->used);
}

static int cfg_insert(cfgset **hp, uint64_t mask, int64_t state);

static int cfg_grow(cfgset **hp) {
    cfgset *h = *hp;
    cfgset *nh = (cfgset *)malloc(sizeof(cfgset));
    if (!nh) return -1;
    if (cfg_init(nh, h->cap * 2) < 0) {
        cfg_free(nh);
        free(nh);
        return -1;
    }
    for (size_t i = 0; i < h->cap; i++)
        if (h->used[i])
            if (cfg_insert(&nh, h->keys[i], h->states[i]) < 0) {
                cfg_free(nh);
                free(nh);
                return -1;
            }
    cfg_free(h);
    free(h);
    *hp = nh;
    return 0;
}

/* returns 1 inserted, 0 already present, -1 oom */
static int cfg_insert(cfgset **hp, uint64_t mask, int64_t state) {
    cfgset *h = *hp;
    if ((h->n + 1) * 10 >= h->cap * 7) {
        if (cfg_grow(hp) < 0) return -1;
        h = *hp;
    }
    uint64_t hash = (mask * 0x9E3779B97F4A7C15ULL) ^
                    ((uint64_t)state * 0xC2B2AE3D27D4EB4FULL);
    size_t idx = (size_t)hash & (h->cap - 1);
    for (;;) {
        if (!h->used[idx]) {
            h->used[idx] = 1;
            h->keys[idx] = mask;
            h->states[idx] = state;
            h->n++;
            return 1;
        }
        if (h->keys[idx] == mask && h->states[idx] == state) return 0;
        idx = (idx + 1) & (h->cap - 1);
    }
}

#define FRONTIER_CFG_CAP (1 << 20)

static int list_i64(PyObject *l, Py_ssize_t i, int64_t *out) {
    PyObject *o = PyList_GET_ITEM(l, i);
    if (!PyLong_CheckExact(o)) return -1;
    int ovf = 0;
    long long v = PyLong_AsLongLongAndOverflow(o, &ovf);
    if (ovf || (v == -1 && PyErr_Occurred())) {
        PyErr_Clear();
        return -1;
    }
    *out = v;
    return 0;
}

/* frontier_absorb(configs, cur, cur_idx, pending_mask,
 *                 kind, slot, f, a, b, op_index, start, end,
 *                 configs_max)
 * -> None                              regime miss: Python twin
 *  | ("dead", event_index)            death: Python twin for forensics
 *  | (configs', cur', cur_idx', pending_mask', configs_max', seen_max) */
static PyObject *frontier_absorb(PyObject *self, PyObject *args) {
    (void)self;
    PyObject *configs, *cur, *cur_idx;
    long long pending_in;
    PyObject *kindl, *slotl, *fl, *al, *bl, *oil;
    Py_ssize_t start, end;
    long long configs_max_in;
    if (!PyArg_ParseTuple(args, "O!O!O!LO!O!O!O!O!O!nnL", &PySet_Type,
                          &configs, &PyDict_Type, &cur, &PyDict_Type,
                          &cur_idx, &pending_in, &PyList_Type, &kindl,
                          &PyList_Type, &slotl, &PyList_Type, &fl,
                          &PyList_Type, &al, &PyList_Type, &bl,
                          &PyList_Type, &oil, &start, &end,
                          &configs_max_in))
        return NULL;
    if (pending_in < 0) Py_RETURN_NONE;
    uint64_t pending = (uint64_t)pending_in;
    int64_t configs_max = configs_max_in;

    /* mirror the session's per-slot current-invocation table */
    int64_t curf[63], cura[63], curb[63], curidx[63];
    uint64_t occ = 0;

    /* load cur: {slot: (f, a, b)} */
    {
        PyObject *k, *v;
        Py_ssize_t ppos = 0;
        while (PyDict_Next(cur, &ppos, &k, &v)) {
            if (!PyLong_CheckExact(k) || !PyTuple_CheckExact(v) ||
                PyTuple_GET_SIZE(v) != 3)
                Py_RETURN_NONE;
            long sl = PyLong_AsLong(k);
            if (sl < 0 || sl >= 63) {
                PyErr_Clear();
                Py_RETURN_NONE;
            }
            int64_t fv, av, bv;
            PyObject *t0 = PyTuple_GET_ITEM(v, 0);
            PyObject *t1 = PyTuple_GET_ITEM(v, 1);
            PyObject *t2 = PyTuple_GET_ITEM(v, 2);
            if (as_i64(t0, &fv) || as_i64(t1, &av) || as_i64(t2, &bv))
                Py_RETURN_NONE;
            curf[sl] = fv;
            cura[sl] = av;
            curb[sl] = bv;
            curidx[sl] = -1;
            occ |= 1ULL << sl;
        }
        ppos = 0;
        while (PyDict_Next(cur_idx, &ppos, &k, &v)) {
            if (!PyLong_CheckExact(k)) Py_RETURN_NONE;
            long sl = PyLong_AsLong(k);
            if (sl < 0 || sl >= 63 || !(occ & (1ULL << sl))) {
                PyErr_Clear();
                Py_RETURN_NONE;
            }
            int64_t iv;
            if (as_i64(v, &iv)) Py_RETURN_NONE;
            curidx[sl] = iv;
        }
    }

    /* load configs into a flat frontier array */
    size_t ncfg = (size_t)PySet_GET_SIZE(configs);
    size_t fcap = ncfg ? ncfg : 1;
    uint64_t *fmask = (uint64_t *)malloc(fcap * 8);
    int64_t *fstate = (int64_t *)malloc(fcap * 8);
    size_t fn = 0;
    uint64_t *nmask = NULL;
    int64_t *nstate = NULL;
    size_t ncap = 0;
    cfgset *seen = NULL;
    PyObject *it = NULL;
    int64_t seen_max = 0;
    if (!fmask || !fstate) goto oom;
    it = PyObject_GetIter(configs);
    if (!it) goto err;
    {
        PyObject *item;
        while ((item = PyIter_Next(it)) != NULL) {
            int64_t mv, sv;
            if (!PyTuple_CheckExact(item) || PyTuple_GET_SIZE(item) != 2 ||
                as_i64(PyTuple_GET_ITEM(item, 0), &mv) ||
                as_i64(PyTuple_GET_ITEM(item, 1), &sv) || mv < 0) {
                Py_DECREF(item);
                goto bail;
            }
            fmask[fn] = (uint64_t)mv;
            fstate[fn] = sv;
            fn++;
            Py_DECREF(item);
        }
        if (PyErr_Occurred()) goto err;
    }
    Py_CLEAR(it);

    {
        Py_ssize_t nev = PyList_GET_SIZE(kindl);
        if (end > nev || PyList_GET_SIZE(slotl) < end ||
            PyList_GET_SIZE(fl) < end || PyList_GET_SIZE(al) < end ||
            PyList_GET_SIZE(bl) < end || PyList_GET_SIZE(oil) < end)
            goto bail;
    }

    for (Py_ssize_t e = start; e < end; e++) {
        int64_t kv, sv;
        if (list_i64(kindl, e, &kv) || list_i64(slotl, e, &sv)) goto bail;
        if (kv == 2) continue; /* EV_NOOP */
        if (sv < 0 || sv >= 63) goto bail;
        int sl = (int)sv;
        if (kv == 0) { /* EV_INVOKE */
            int64_t fv, av, bv, iv;
            if (list_i64(fl, e, &fv) || list_i64(al, e, &av) ||
                list_i64(bl, e, &bv) || list_i64(oil, e, &iv))
                goto bail;
            curf[sl] = fv;
            cura[sl] = av;
            curb[sl] = bv;
            curidx[sl] = iv;
            occ |= 1ULL << sl;
            pending |= 1ULL << sl;
            continue;
        }
        if (kv != 1) goto bail; /* EV_RETURN */
        uint64_t bit = 1ULL << sl;
        if (fn == 1 && (pending & ~fmask[0]) == bit) {
            /* singleton frontier with only this return's op available —
             * the steady state of a narrow live stream. The twin's
             * closure is exactly {cfg0, cfg0+op}: survival means the op
             * fires and succeeds, and the sole surviving config keeps
             * mask0 (the op's bit is set by the closure and cleared by
             * the filter) with the stepped state. */
            int64_t fv = curf[sl], av = cura[sl], bv = curb[sl];
            int64_t st = fstate[0], st2 = st;
            int okv;
            if (fv == 0) { /* read */
                okv = (av == 0 || av == st);
            } else if (fv == 1) { /* write */
                st2 = av;
                okv = 1;
            } else if (fv == 2) { /* cas */
                if (st == av) {
                    st2 = bv;
                    okv = 1;
                } else {
                    okv = 0;
                }
            } else {
                okv = 0;
            }
            if (!okv) { /* nothing fired: death, replay in Python */
                PyObject *r = Py_BuildValue("(sn)", "dead", e);
                free(fmask);
                free(fstate);
                free(nmask);
                free(nstate);
                if (seen) {
                    cfg_free(seen);
                    free(seen);
                }
                return r;
            }
            fstate[0] = st2;
            /* all_seen was {cfg0, cfg0+op}: two distinct masks */
            if (configs_max < 2) configs_max = 2;
            if (seen_max < 2) seen_max = 2;
            pending &= ~bit;
            continue;
        }
        /* small frontier: the BFS closure fits in fixed arrays with
         * linear-scan dedup, skipping the hashtable's reset/insert
         * machinery entirely. Narrow live streams (concurrency <= ~5)
         * spend almost every return here. Overflow falls through to
         * the general path with the frontier untouched. */
        if (fn <= 6) {
            uint64_t sm[96];
            int64_t ss[96];
            size_t sn = fn, qh = 0;
            int overflow = 0;
            memcpy(sm, fmask, fn * 8);
            memcpy(ss, fstate, fn * 8);
            while (qh < sn && !overflow) {
                uint64_t mask = sm[qh];
                int64_t state = ss[qh];
                qh++;
                uint64_t avail = pending & ~mask;
                while (avail) {
                    int b2 = __builtin_ctzll(avail);
                    uint64_t abit = 1ULL << b2;
                    avail &= avail - 1;
                    int64_t fv = curf[b2], av = cura[b2], bv = curb[b2];
                    int64_t st2 = state;
                    int okv;
                    if (fv == 0) {
                        okv = (av == 0 || av == state);
                    } else if (fv == 1) {
                        st2 = av;
                        okv = 1;
                    } else if (fv == 2) {
                        if (state == av) {
                            st2 = bv;
                            okv = 1;
                        } else {
                            okv = 0;
                        }
                    } else {
                        okv = 0;
                    }
                    if (!okv) continue;
                    uint64_t nm = mask | abit;
                    size_t si;
                    for (si = 0; si < sn; si++)
                        if (sm[si] == nm && ss[si] == st2) break;
                    if (si < sn) continue;
                    if (sn == 96) {
                        overflow = 1;
                        break;
                    }
                    sm[sn] = nm;
                    ss[sn] = st2;
                    sn++;
                }
            }
            if (!overflow) {
                if (configs_max < (int64_t)sn) configs_max = sn;
                if (seen_max < (int64_t)sn) seen_max = sn;
                /* keep configs where this return fired; clear its bit
                 * and dedup (the twin's set comprehension) */
                uint64_t om[96];
                int64_t os[96];
                size_t nn = 0;
                for (size_t si = 0; si < sn; si++) {
                    if (!(sm[si] & bit)) continue;
                    uint64_t nm = sm[si] & ~bit;
                    size_t di;
                    for (di = 0; di < nn; di++)
                        if (om[di] == nm && os[di] == ss[si]) break;
                    if (di < nn) continue;
                    om[nn] = nm;
                    os[nn] = ss[si];
                    nn++;
                }
                if (nn == 0) { /* death: replay in Python */
                    PyObject *r = Py_BuildValue("(sn)", "dead", e);
                    free(fmask);
                    free(fstate);
                    free(nmask);
                    free(nstate);
                    if (seen) {
                        cfg_free(seen);
                        free(seen);
                    }
                    return r;
                }
                if (nn > fcap) {
                    size_t nc = fcap;
                    while (nc < nn) nc *= 2;
                    uint64_t *m2 = (uint64_t *)realloc(fmask, nc * 8);
                    if (!m2) goto oom;
                    fmask = m2;
                    int64_t *s2 = (int64_t *)realloc(fstate, nc * 8);
                    if (!s2) goto oom;
                    fstate = s2;
                    fcap = nc;
                }
                memcpy(fmask, om, nn * 8);
                memcpy(fstate, os, nn * 8);
                fn = nn;
                pending &= ~bit;
                continue;
            }
        }
        /* BFS closure over pending subsets, then require `bit` fired */
        if (!seen) {
            seen = (cfgset *)malloc(sizeof(cfgset));
            if (!seen) goto oom;
            if (cfg_init(seen, 256) < 0) goto oom;
        } else {
            /* reset in place */
            memset(seen->used, 0, seen->cap);
            seen->n = 0;
        }
        for (size_t ci = 0; ci < fn; ci++)
            if (cfg_insert(&seen, fmask[ci], fstate[ci]) < 0) goto oom;
        /* frontier arrays double as the BFS work queue */
        size_t qhead = 0, qtail = fn, qcap = fcap;
        uint64_t *qmask = fmask;
        int64_t *qstate = fstate;
        while (qhead < qtail) {
            uint64_t mask = qmask[qhead];
            int64_t state = qstate[qhead];
            qhead++;
            uint64_t avail = pending & ~mask;
            while (avail) {
                int b2 = __builtin_ctzll(avail);
                uint64_t abit = 1ULL << b2;
                avail &= avail - 1;
                int64_t fv = curf[b2], av = cura[b2], bv = curb[b2];
                int64_t st2 = state;
                int okv;
                if (fv == 0) { /* read */
                    okv = (av == 0 || av == state);
                } else if (fv == 1) { /* write */
                    st2 = av;
                    okv = 1;
                } else if (fv == 2) { /* cas */
                    if (state == av) {
                        st2 = bv;
                        okv = 1;
                    } else {
                        okv = 0;
                    }
                } else {
                    okv = 0;
                }
                if (!okv) continue;
                int ins = cfg_insert(&seen, mask | abit, st2);
                if (ins < 0) goto oom;
                if (ins) {
                    if ((int64_t)seen->n > FRONTIER_CFG_CAP) goto bail;
                    if (qtail == qcap) {
                        size_t nc = qcap * 2;
                        uint64_t *m2 =
                            (uint64_t *)realloc(qmask, nc * 8);
                        if (!m2) goto oom;
                        qmask = m2;
                        int64_t *s2 =
                            (int64_t *)realloc(qstate, nc * 8);
                        if (!s2) goto oom;
                        qstate = s2;
                        qcap = nc;
                    }
                    qmask[qtail] = mask | abit;
                    qstate[qtail] = st2;
                    qtail++;
                }
            }
        }
        fmask = qmask;
        fstate = qstate;
        fcap = qcap;
        if ((int64_t)seen->n > configs_max) configs_max = seen->n;
        if ((int64_t)seen->n > seen_max) seen_max = seen->n;
        /* keep only configs where this return's op fired; clear its bit */
        if (ncap < seen->n) {
            free(nmask);
            free(nstate);
            ncap = seen->n ? seen->n : 1;
            nmask = (uint64_t *)malloc(ncap * 8);
            nstate = (int64_t *)malloc(ncap * 8);
            if (!nmask || !nstate) goto oom;
        }
        size_t nn = 0;
        for (size_t si = 0; si < seen->cap; si++) {
            if (!seen->used[si] || !(seen->keys[si] & bit)) continue;
            nmask[nn] = seen->keys[si] & ~bit;
            nstate[nn] = seen->states[si];
            nn++;
        }
        /* dedup after clearing the bit (the twin's set comprehension) */
        memset(seen->used, 0, seen->cap);
        seen->n = 0;
        fn = 0;
        for (size_t si = 0; si < nn; si++) {
            int ins = cfg_insert(&seen, nmask[si], nstate[si]);
            if (ins < 0) goto oom;
            if (ins) {
                if (fn == fcap) {
                    size_t nc = fcap * 2;
                    uint64_t *m2 = (uint64_t *)realloc(fmask, nc * 8);
                    if (!m2) goto oom;
                    fmask = m2;
                    int64_t *s2 = (int64_t *)realloc(fstate, nc * 8);
                    if (!s2) goto oom;
                    fstate = s2;
                    fcap = nc;
                }
                fmask[fn] = nmask[si];
                fstate[fn] = nstate[si];
                fn++;
            }
        }
        pending &= ~bit;
        if (fn == 0) { /* death: replay in Python for the forensics */
            PyObject *r = Py_BuildValue("(sn)", "dead", e);
            free(fmask);
            free(fstate);
            free(nmask);
            free(nstate);
            cfg_free(seen);
            free(seen);
            return r;
        }
    }

    /* success: build replacement Python state */
    {
        PyObject *cfg_out = PySet_New(NULL);
        PyObject *cur_out = PyDict_New();
        PyObject *ci_out = PyDict_New();
        PyObject *res = NULL;
        if (!cfg_out || !cur_out || !ci_out) goto werr;
        for (size_t si = 0; si < fn; si++) {
            PyObject *t = Py_BuildValue("(LL)", (long long)fmask[si],
                                        (long long)fstate[si]);
            if (!t || PySet_Add(cfg_out, t) < 0) {
                Py_XDECREF(t);
                goto werr;
            }
            Py_DECREF(t);
        }
        for (int sl = 0; sl < 63; sl++) {
            if (!(occ & (1ULL << sl))) continue;
            PyObject *k = PyLong_FromLong(sl);
            PyObject *v = Py_BuildValue("(LLL)", (long long)curf[sl],
                                        (long long)cura[sl],
                                        (long long)curb[sl]);
            if (!k || !v || PyDict_SetItem(cur_out, k, v) < 0) {
                Py_XDECREF(k);
                Py_XDECREF(v);
                goto werr;
            }
            Py_DECREF(v);
            if (curidx[sl] >= 0) {
                PyObject *iv = PyLong_FromLongLong(curidx[sl]);
                if (!iv || PyDict_SetItem(ci_out, k, iv) < 0) {
                    Py_XDECREF(iv);
                    Py_DECREF(k);
                    goto werr;
                }
                Py_DECREF(iv);
            }
            Py_DECREF(k);
        }
        res = Py_BuildValue("(NNNLLL)", cfg_out, cur_out, ci_out,
                            (long long)pending, (long long)configs_max,
                            (long long)seen_max);
        if (!res) goto werr2;
        free(fmask);
        free(fstate);
        free(nmask);
        free(nstate);
        if (seen) {
            cfg_free(seen);
            free(seen);
        }
        return res;
    werr:
        Py_XDECREF(cfg_out);
        Py_XDECREF(cur_out);
        Py_XDECREF(ci_out);
    werr2:
        goto err;
    }

bail:
    free(fmask);
    free(fstate);
    free(nmask);
    free(nstate);
    if (seen) {
        cfg_free(seen);
        free(seen);
    }
    Py_XDECREF(it);
    if (PyErr_Occurred()) PyErr_Clear();
    Py_RETURN_NONE;
oom:
    if (!PyErr_Occurred()) PyErr_NoMemory();
err:
    free(fmask);
    free(fstate);
    free(nmask);
    free(nstate);
    if (seen) {
        cfg_free(seen);
        free(seen);
    }
    Py_XDECREF(it);
    return NULL;
}

/* ====================================================================
 * sim_lane — the simulated scheduler's hot loop, natively.
 *
 * Twin of generator/simulate.py:simulate() specialized to the stock
 * shape simulate._lane_attempt recognizes before handing off:
 * g = Limit(remaining, Fn(f)) with a zero-arity plain-function f,
 * complete_fn = _completer(typ, latency) with typ ok|fail, a stock
 * random.Random (its MT19937 runs natively from getstate() words and
 * is written back for bit-identical downstream draws), <= 62 threads
 * with unique process ids, no wall-clock deadline, empty pending.
 *
 * Everything observable is produced in the twin's exact order: history
 * dicts (key INSERTION order included — json/repr see it), rng entropy
 * consumption (every _randbelow of every step, including draws for ops
 * a completion then pre-empts and f() calls on steps that go PENDING),
 * step counts, and Limit.remaining. f() returning anything but a plain
 * dict free of process/time/type keys BAILS back to Python with the
 * consumed value in state["bail_x"], so f runs exactly once for that
 * step and the pure twin replays the step's tail from identical state.
 *
 * The pending-completion store is a FIFO ring, equivalent to the
 * twin's (time, seq, op) heap because completion times are pushed in
 * non-decreasing order: dispatch times never move backwards and the
 * latency is constant, so heap order == insertion order with the same
 * seq tie-break.
 *
 * state dict keys (in + written back on EVERY exit, errors included):
 * f, remaining, limit, steps, time, procs, free, history, typ,
 * latency, mt, seq; written back only: pending, bail_x.
 * Returns 0 = the twin's loop would break here (generator exhausted,
 * or PENDING/exhausted with nothing in flight = deadlock),
 * 1 = step-limit hit, 3 = bail (finish the consumed step in Python).
 * ==================================================================== */

#define SIM_MAX_THREADS 62

typedef struct {
    int64_t time;
    int64_t seq;
    int tidx;
    PyObject *comp; /* strong */
} SimPend;

/* CPython Modules/_randommodule.c genrand_uint32, bit for bit: the
   lane's draws must consume the Mersenne Twister stream exactly as
   Random.getrandbits(k<=32) does. */
static uint32_t sim_mt_next(uint32_t *mt, int *idx) {
    uint32_t y;
    if (*idx >= 624) {
        int kk;
        for (kk = 0; kk < 624 - 397; kk++) {
            y = (mt[kk] & 0x80000000U) | (mt[kk + 1] & 0x7fffffffU);
            mt[kk] = mt[kk + 397] ^ (y >> 1) ^ ((y & 1U) ? 0x9908b0dfU : 0U);
        }
        for (; kk < 623; kk++) {
            y = (mt[kk] & 0x80000000U) | (mt[kk + 1] & 0x7fffffffU);
            mt[kk] = mt[kk + 397 - 624] ^ (y >> 1)
                     ^ ((y & 1U) ? 0x9908b0dfU : 0U);
        }
        y = (mt[623] & 0x80000000U) | (mt[0] & 0x7fffffffU);
        mt[623] = mt[396] ^ (y >> 1) ^ ((y & 1U) ? 0x9908b0dfU : 0U);
        *idx = 0;
    }
    y = mt[(*idx)++];
    y ^= (y >> 11);
    y ^= (y << 7) & 0x9d2c5680U;
    y ^= (y << 15) & 0xefc60000U;
    y ^= (y >> 18);
    return y;
}

static int64_t sim_get_ll(PyObject *S, const char *k, int *err) {
    PyObject *v = PyDict_GetItemString(S, k);
    long long r;
    if (!v) {
        PyErr_Format(PyExc_KeyError, "sim_lane state missing %s", k);
        *err = 1;
        return 0;
    }
    r = PyLong_AsLongLong(v);
    if (r == -1 && PyErr_Occurred()) {
        *err = 1;
        return 0;
    }
    return (int64_t)r;
}

/* Folds the lane's state back into S. Runs on every exit path (the
   gate's finally reads the SAME keys it filled, so a lane that never
   got this far folds back as a no-op). Ring comp refs are transferred
   into the pending tuples; leftovers are released on failure. */
static int sim_writeback(PyObject *S, int64_t steps, int64_t time_,
                         uint64_t free_mask, int64_t remaining,
                         int64_t seq, const uint32_t *mt, int mtidx,
                         SimPend *ring, int head, int npend, int cap,
                         PyObject *bail_x) {
    int rc = -1, i;
    PyObject *pend = NULL, *mt_out = NULL, *v = NULL;
#define SIM_WB_LL(key, val)                                   \
    do {                                                      \
        v = PyLong_FromLongLong((long long)(val));            \
        if (!v || PyDict_SetItemString(S, key, v) < 0)        \
            goto done;                                        \
        Py_CLEAR(v);                                          \
    } while (0)
    SIM_WB_LL("steps", steps);
    SIM_WB_LL("time", time_);
    SIM_WB_LL("remaining", remaining);
    SIM_WB_LL("seq", seq);
    v = PyLong_FromUnsignedLongLong((unsigned long long)free_mask);
    if (!v || PyDict_SetItemString(S, "free", v) < 0) goto done;
    Py_CLEAR(v);
    mt_out = PyTuple_New(625);
    if (!mt_out) goto done;
    for (i = 0; i < 624; i++) {
        PyObject *w = PyLong_FromUnsignedLong((unsigned long)mt[i]);
        if (!w) goto done;
        PyTuple_SET_ITEM(mt_out, i, w);
    }
    v = PyLong_FromLong((long)mtidx);
    if (!v) goto done;
    PyTuple_SET_ITEM(mt_out, 624, v);
    v = NULL; /* ref moved into the tuple */
    if (PyDict_SetItemString(S, "mt", mt_out) < 0) goto done;
    Py_CLEAR(mt_out);
    pend = PyList_New(npend);
    if (!pend) goto done;
    for (i = 0; i < npend; i++) {
        SimPend *h = &ring[(head + i) % cap];
        PyObject *t = PyTuple_New(3);
        PyObject *a = PyLong_FromLongLong((long long)h->time);
        PyObject *b = PyLong_FromLongLong((long long)h->seq);
        if (!t || !a || !b) {
            Py_XDECREF(t);
            Py_XDECREF(a);
            Py_XDECREF(b);
            goto done;
        }
        PyTuple_SET_ITEM(t, 0, a);
        PyTuple_SET_ITEM(t, 1, b);
        PyTuple_SET_ITEM(t, 2, h->comp); /* ref transferred */
        h->comp = NULL;
        PyList_SET_ITEM(pend, i, t);
    }
    if (PyDict_SetItemString(S, "pending", pend) < 0) goto done;
    Py_CLEAR(pend);
    if (bail_x && PyDict_SetItemString(S, "bail_x", bail_x) < 0) goto done;
    rc = 0;
done:
    Py_XDECREF(v);
    Py_XDECREF(mt_out);
    Py_XDECREF(pend);
    for (i = 0; i < npend; i++)
        Py_CLEAR(ring[(head + i) % cap].comp);
#undef SIM_WB_LL
    return rc;
}

static PyObject *sim_lane(PyObject *self, PyObject *args) {
    PyObject *S;
    (void)self;
    if (!PyArg_ParseTuple(args, "O!:sim_lane", &PyDict_Type, &S))
        return NULL;
    PyObject *f = PyDict_GetItemString(S, "f");
    PyObject *procs = PyDict_GetItemString(S, "procs");
    PyObject *history = PyDict_GetItemString(S, "history");
    PyObject *typ = PyDict_GetItemString(S, "typ");
    PyObject *mt_in = PyDict_GetItemString(S, "mt");
    PyObject *free_obj = PyDict_GetItemString(S, "free");
    if (!f || !procs || !history || !typ || !mt_in || !free_obj
        || !PyList_CheckExact(procs) || !PyList_CheckExact(history)
        || !PyTuple_CheckExact(mt_in) || PyTuple_GET_SIZE(mt_in) != 625) {
        PyErr_SetString(PyExc_ValueError, "sim_lane: malformed state");
        return NULL;
    }
    int err = 0;
    int64_t remaining = sim_get_ll(S, "remaining", &err);
    int64_t limit = sim_get_ll(S, "limit", &err);
    int64_t steps = sim_get_ll(S, "steps", &err);
    int64_t time_ = sim_get_ll(S, "time", &err);
    int64_t latency = sim_get_ll(S, "latency", &err);
    int64_t seq = sim_get_ll(S, "seq", &err);
    uint64_t free_mask = PyLong_AsUnsignedLongLong(free_obj);
    if (free_mask == (uint64_t)-1 && PyErr_Occurred()) err = 1;
    Py_ssize_t nthreads = PyList_GET_SIZE(procs);
    if (err) return NULL;
    if (nthreads < 1 || nthreads > SIM_MAX_THREADS) {
        PyErr_SetString(PyExc_ValueError, "sim_lane: bad thread count");
        return NULL;
    }
    uint32_t mt[624];
    int mtidx, i;
    for (i = 0; i < 624; i++) {
        unsigned long w = PyLong_AsUnsignedLong(PyTuple_GET_ITEM(mt_in, i));
        if (w == (unsigned long)-1 && PyErr_Occurred()) return NULL;
        mt[i] = (uint32_t)w;
    }
    mtidx = (int)PyLong_AsLong(PyTuple_GET_ITEM(mt_in, 624));
    if ((mtidx == -1 && PyErr_Occurred()) || mtidx < 0 || mtidx > 624) {
        if (!PyErr_Occurred())
            PyErr_SetString(PyExc_ValueError, "sim_lane: bad mt index");
        return NULL;
    }

    SimPend ring[SIM_MAX_THREADS + 2];
    const int cap = (int)nthreads + 1; /* <= 1 in flight per thread */
    int head = 0, tail = 0, npend = 0;
    int status = 0;
    PyObject *bail_x = NULL;

    for (;;) {
        if (steps >= limit) {
            status = 1; /* step_limited */
            break;
        }
        steps++;
        /* g.op(): Limit consults Fn, Fn calls f() — even on steps where
           the op is then pre-empted or PENDING (those calls and their
           rng draws are load-bearing for deterministic enumeration) */
        PyObject *x = NULL;
        if (remaining > 0) {
            x = PyObject_CallNoArgs(f);
            if (!x) goto error;
        }
        if (remaining <= 0 || x == Py_None) {
            /* res is None: apply the soonest completion or break */
            Py_XDECREF(x);
            if (npend == 0) break; /* status 0: twin's loop breaks */
            goto apply_comp;
        }
        /* Fn.op's dict fast path. Anything else — non-dict, an explicit
           process/time/type key — hands the consumed x back to Python.
           A key-compare error here is the same error the twin's
           op.get() would raise: propagate it. */
        if (!PyDict_CheckExact(x)) {
            bail_x = x;
            status = 3;
            break;
        }
        {
            PyObject *hit = PyDict_GetItemWithError(x, g_s_process);
            if (!hit && !PyErr_Occurred())
                hit = PyDict_GetItemWithError(x, g_s_time);
            if (!hit && !PyErr_Occurred())
                hit = PyDict_GetItemWithError(x, g_s_type);
            if (PyErr_Occurred()) {
                Py_DECREF(x);
                goto error;
            }
            if (hit) {
                bail_x = x;
                status = 3;
                break;
            }
        }
        if (free_mask == 0) {
            /* some_free_process -> None -> (PENDING, self): x is
               discarded WITHOUT an rng draw, exactly like the twin */
            Py_DECREF(x);
            if (npend == 0) break; /* status 0: deadlock break */
            goto apply_comp;
        }
        {
            /* ctx.some_free_process(): rng._randbelow(nfree), then the
               r-th thread in sorted order == the r-th set bit (bit i is
               the i-th thread of the gate's sorted thread list) */
            int nfree = __builtin_popcountll((unsigned long long)free_mask);
            int k = 64 - __builtin_clzll((unsigned long long)nfree);
            uint32_t r;
            do {
                r = sim_mt_next(mt, &mtidx) >> (32 - k);
            } while (r >= (uint32_t)nfree);
            uint64_t m = free_mask;
            uint32_t j;
            for (j = 0; j < r; j++) m &= m - 1;
            int tidx = __builtin_ctzll((unsigned long long)m);
            if (npend && ring[head].time <= time_) {
                /* the completion happens first: the op (and its draw,
                   already consumed) is discarded, remaining untouched */
                Py_DECREF(x);
                goto apply_comp;
            }
            /* dispatch */
            remaining--;
            PyObject *op = PyDict_Copy(x);
            Py_DECREF(x);
            if (!op) goto error;
            PyObject *tv = PyLong_FromLongLong((long long)time_);
            if (!tv) {
                Py_DECREF(op);
                goto error;
            }
            /* twin's key order: process, time, type, then setdefault
               f/value — insertion order is observable downstream */
            int bad =
                PyDict_SetItem(op, g_s_process, PyList_GET_ITEM(procs, tidx))
                || PyDict_SetItem(op, g_s_time, tv)
                || PyDict_SetItem(op, g_s_type, g_s_invoke);
            Py_DECREF(tv);
            if (bad) {
                Py_DECREF(op);
                goto error;
            }
            PyObject *hv = PyDict_GetItemWithError(op, g_s_f);
            if (!hv && (PyErr_Occurred()
                        || PyDict_SetItem(op, g_s_f, Py_None) < 0)) {
                Py_DECREF(op);
                goto error;
            }
            hv = PyDict_GetItemWithError(op, g_s_value);
            if (!hv && (PyErr_Occurred()
                        || PyDict_SetItem(op, g_s_value, Py_None) < 0)) {
                Py_DECREF(op);
                goto error;
            }
            free_mask &= ~(1ULL << tidx);
            if (PyList_Append(history, op) < 0) {
                Py_DECREF(op);
                goto error;
            }
            /* complete_fn: comp = dict(op); comp[type]=typ;
               comp[time]=op time + latency (updates in place keep the
               copy's key order, like the twin's) */
            PyObject *comp = PyDict_Copy(op);
            Py_DECREF(op);
            if (!comp) goto error;
            PyObject *ct = PyLong_FromLongLong((long long)(time_ + latency));
            if (!ct) {
                Py_DECREF(comp);
                goto error;
            }
            bad = PyDict_SetItem(comp, g_s_type, typ)
                  || PyDict_SetItem(comp, g_s_time, ct);
            Py_DECREF(ct);
            if (bad) {
                Py_DECREF(comp);
                goto error;
            }
            ring[tail].time = time_ + latency;
            ring[tail].seq = seq++;
            ring[tail].tidx = tidx;
            ring[tail].comp = comp;
            tail = (tail + 1) % cap;
            npend++;
            continue;
        }
    apply_comp:
        {
            /* _apply_completion: advance time, free the thread, append;
               typ is ok|fail so no __free__/renumbering branches */
            SimPend *h = &ring[head];
            if (h->time > time_) time_ = h->time;
            free_mask |= 1ULL << h->tidx;
            if (PyList_Append(history, h->comp) < 0) goto error;
            Py_CLEAR(h->comp);
            head = (head + 1) % cap;
            npend--;
            continue;
        }
    }

    if (sim_writeback(S, steps, time_, free_mask, remaining, seq, mt,
                      mtidx, ring, head, npend, cap, bail_x) < 0) {
        Py_XDECREF(bail_x);
        return NULL;
    }
    Py_XDECREF(bail_x);
    return PyLong_FromLong(status);

error:
    {
        PyObject *et, *ev, *tb;
        PyErr_Fetch(&et, &ev, &tb);
        (void)sim_writeback(S, steps, time_, free_mask, remaining, seq,
                            mt, mtidx, ring, head, npend, cap, NULL);
        PyErr_Restore(et, ev, tb);
        return NULL;
    }
}

/* The spine entry points allocate container objects (op dicts, value
   lists, column ints) at millions per second; CPython's generational
   collector walking gen0 every ~700 allocations costs about HALF the
   parse throughput (measured: 0.9M -> 2.1M lines/s on register-op
   WALs). Collection is deferred, never skipped: each call runs with
   the GC paused and restores the previous state on exit — including
   around the per-line Python fallback, which allocates the same kind
   of short-lived containers. */
#define GC_PAUSED_METH(name)                                          \
    static PyObject *name##_gcp(PyObject *self, PyObject *args) {     \
        int was_enabled = PyGC_Disable();                             \
        PyObject *r = name(self, args);                               \
        if (was_enabled) PyGC_Enable();                               \
        return r;                                                     \
    }
GC_PAUSED_METH(ingest_chunk)
GC_PAUSED_METH(builder_extend)
GC_PAUSED_METH(register_add)
GC_PAUSED_METH(register_encode)
GC_PAUSED_METH(register_add_encode)
GC_PAUSED_METH(frontier_absorb)
GC_PAUSED_METH(sim_lane)

static PyMethodDef methods[] = {
    {"parse", parse, METH_VARARGS,
     "parse(history) -> tuple | None\n"
     "C-speed pass A/B + spine/prefix of the columnar Elle builder."},
    {"ingest_chunk", ingest_chunk_gcp, METH_VARARGS,
     "ingest_chunk(data, final, fallback, skip, torn)\n"
     " -> (ops, consumed, torn, truncated)\n"
     "Newline scan + JSON parse with WalTailer.poll's torn contract."},
    {"builder_extend", builder_extend_gcp, METH_VARARGS,
     "builder_extend(ops, start, state) -> count\n"
     "Canonical-column append twin of IncrementalHistoryBuilder.add."},
    {"register_add", register_add_gcp, METH_VARARGS,
     "register_add(ops, start, state) -> count\n"
     "Resolution twin of LiveRegisterEncoder.add."},
    {"register_encode", register_encode_gcp, METH_VARARGS,
     "register_encode(state) -> (next, next_slot, n_slots, bailed)\n"
     "Event-encode twin of LiveRegisterEncoder.encode_resolved."},
    {"register_add_encode", register_add_encode_gcp, METH_VARARGS,
     "register_add_encode(ops, start, add_state, enc_state)\n"
     " -> (next, next_slot, n_slots, enc_ran, bailed)\n"
     "Fused add_many + encode_resolved: one walk per chunk."},
    {"frontier_absorb", frontier_absorb_gcp, METH_VARARGS,
     "frontier_absorb(...) -> None | ('dead', e) | new state\n"
     "Config-closure twin of FrontierSession.absorb (cas register)."},
    {"sim_lane", sim_lane_gcp, METH_VARARGS,
     "sim_lane(state) -> 0 | 1 | 3\n"
     "Native scheduler loop twin of generator/simulate.simulate for\n"
     "the stock Limit(Fn)/stock-completer/stock-rng shape."},
    {NULL, NULL, 0, NULL}};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_columnar_c",
    "C-speed columnar history builder (see columnar_ext.c)", -1, methods,
    NULL, NULL, NULL, NULL};

#ifdef __cplusplus
extern "C" {
#endif
PyMODINIT_FUNC PyInit__columnar_c(void) {
    if (spine_init() < 0) return NULL;
    return PyModule_Create(&moduledef);
}
#ifdef __cplusplus
}
#endif
