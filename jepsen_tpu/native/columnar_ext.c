/* C-speed columnar history builder for the Elle list-append checker.
 *
 * The reference's Elle runs on the JVM where per-micro-op map walks are
 * JIT-compiled (SURVEY.md §2.5); here the equivalent parse of a Python
 * history — event pairing, micro-op flattening, key interning, spine
 * selection and prefix verification — is one tight C pass over the
 * PyObject graph, feeding the numpy/JAX stages of
 * jepsen_tpu/elle/columnar.py.  Mirrors the semantics of
 * columnar._build's pass A/B + spine/prefix sections bit-for-bit (the
 * differential fuzz in tests/test_elle.py pins it to the Python oracle);
 * any input outside the fast regime returns None and the caller falls
 * back to the Python path.
 *
 * Compiled on demand by jepsen_tpu/native/columnar_c.py (g++, no
 * pybind11 — plain CPython C API), loaded as an extension module.
 */
#include <Python.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>

#define MAX_KIDS (1 << 20)
#define MAX_MOPS (1 << 12)
#define MAX_VAL (4294967296LL) /* 1 << 32 */

typedef struct {
    int64_t *d;
    Py_ssize_t n, cap;
} vec;

static int vpush(vec *v, int64_t x) {
    if (v->n == v->cap) {
        Py_ssize_t nc = v->cap ? v->cap * 2 : 1024;
        int64_t *nd = (int64_t *)realloc(v->d, (size_t)nc * 8);
        if (!nd) return -1;
        v->d = nd;
        v->cap = nc;
    }
    v->d[v->n++] = x;
    return 0;
}

static void vfree(vec *v) {
    free(v->d);
    v->d = NULL;
    v->n = v->cap = 0;
}

static PyObject *vbytes(vec *v) {
    return PyByteArray_FromStringAndSize((char *)v->d, v->n * 8);
}

/* exact int -> int64 with overflow detection; returns -1 on overflow or
 * non-exact-int (bail), 0 ok */
static int as_i64(PyObject *o, int64_t *out) {
    if (!PyLong_CheckExact(o)) return -1;
    int ovf = 0;
    long long x = PyLong_AsLongLongAndOverflow(o, &ovf);
    if (ovf || (x == -1 && PyErr_Occurred())) {
        PyErr_Clear();
        return -1;
    }
    *out = (int64_t)x;
    return 0;
}

/* outcome codes for the parse */
#define OUT_OK 0
#define OUT_BAIL 1 /* regime miss: caller falls back to Python */
#define OUT_ERR 2  /* Python exception set */

typedef struct {
    vec ok_pos, info_pos, fail_pos;
    vec a_txn, a_kid, a_val, a_mi;
    vec r_txn, r_kid, r_mi, r_len, r_last;
    vec f_kid, f_val;
    vec s_concat, s_kid;
    int64_t *inv_pos;  /* [nh] */
    int64_t *best_len; /* [nk] spine */
    int64_t *best_row;
    int64_t *soff, *slen;
    PyObject *payloads, *raw_key, *kid_of, *state, *txns, *scrutiny;
    Py_ssize_t nk;
} ctx;

static void ctx_free(ctx *c) {
    vfree(&c->ok_pos); vfree(&c->info_pos); vfree(&c->fail_pos);
    vfree(&c->a_txn); vfree(&c->a_kid); vfree(&c->a_val); vfree(&c->a_mi);
    vfree(&c->r_txn); vfree(&c->r_kid); vfree(&c->r_mi); vfree(&c->r_len);
    vfree(&c->r_last);
    vfree(&c->f_kid); vfree(&c->f_val);
    vfree(&c->s_concat); vfree(&c->s_kid);
    free(c->inv_pos); free(c->best_len); free(c->best_row);
    free(c->soff); free(c->slen);
    Py_CLEAR(c->payloads); Py_CLEAR(c->raw_key); Py_CLEAR(c->kid_of);
    Py_CLEAR(c->state); Py_CLEAR(c->txns); Py_CLEAR(c->scrutiny);
}

/* interns key (an exact int object) into kid_of/raw_key; returns kid or
 * -1 (bail: too many keys) or -2 (error) */
static int64_t intern_kid(ctx *c, PyObject *key) {
    PyObject *got = PyDict_GetItemWithError(c->kid_of, key);
    if (got) return PyLong_AsLongLong(got);
    if (PyErr_Occurred()) return -2;
    if (c->nk >= MAX_KIDS) return -1;
    PyObject *idx = PyLong_FromSsize_t(c->nk);
    if (!idx) return -2;
    if (PyDict_SetItem(c->kid_of, key, idx) < 0) {
        Py_DECREF(idx);
        return -2;
    }
    Py_DECREF(idx);
    if (PyList_Append(c->raw_key, key) < 0) return -2;
    return (int64_t)c->nk++;
}

/* flatten one committed/info txn's micro-ops (pass B semantics).
 * ni = node index. Returns OUT_*. */
static int flatten_txn(ctx *c, PyObject *op, Py_ssize_t ni) {
    PyObject *value = PyDict_GetItemString(op, "value");
    if (!value) return OUT_OK;
    int truth = PyObject_IsTrue(value);
    if (truth < 0) return OUT_ERR;
    if (!truth) return OUT_OK; /* `op.get("value") or ()` */
    PyObject **items;
    Py_ssize_t nm;
    if (PyList_CheckExact(value)) {
        items = ((PyListObject *)value)->ob_item;
        nm = PyList_GET_SIZE(value);
    } else if (PyTuple_CheckExact(value)) {
        items = ((PyTupleObject *)value)->ob_item;
        nm = PyTuple_GET_SIZE(value);
    } else {
        return OUT_BAIL; /* exotic container: general loop handles it */
    }
    if (nm > MAX_MOPS) return OUT_BAIL;
    for (Py_ssize_t mi = 0; mi < nm; mi++) {
        PyObject *m = items[mi];
        PyObject **mit;
        Py_ssize_t ml;
        if (PyList_CheckExact(m)) {
            mit = ((PyListObject *)m)->ob_item;
            ml = PyList_GET_SIZE(m);
        } else if (PyTuple_CheckExact(m)) {
            mit = ((PyTupleObject *)m)->ob_item;
            ml = PyTuple_GET_SIZE(m);
        } else {
            return OUT_BAIL;
        }
        if (ml < 3) return OUT_BAIL; /* fast path needs [f, k, v] */
        PyObject *f = mit[0];
        if (!PyUnicode_CheckExact(f)) return OUT_BAIL;
        if (PyUnicode_CompareWithASCIIString(f, "append") == 0) {
            int64_t kid, val;
            if (!PyLong_CheckExact(mit[1])) return OUT_BAIL;
            kid = intern_kid(c, mit[1]);
            if (kid == -1) return OUT_BAIL;
            if (kid == -2) return OUT_ERR;
            if (as_i64(mit[2], &val) < 0) return OUT_BAIL;
            if (val < 0 || val >= MAX_VAL) return OUT_BAIL;
            if (vpush(&c->a_txn, ni) || vpush(&c->a_kid, kid) ||
                vpush(&c->a_val, val) || vpush(&c->a_mi, mi))
                return OUT_ERR;
        } else if (PyUnicode_CompareWithASCIIString(f, "r") == 0) {
            PyObject *third = mit[2];
            if (third == Py_None) continue; /* unfulfilled read */
            int64_t kid;
            if (!PyLong_CheckExact(mit[1])) return OUT_BAIL;
            kid = intern_kid(c, mit[1]);
            if (kid == -1) return OUT_BAIL;
            if (kid == -2) return OUT_ERR;
            PyObject *payload;
            if (PyList_CheckExact(third)) {
                payload = third;
                Py_INCREF(payload);
            } else {
                payload = PySequence_List(third);
                if (!payload) return OUT_ERR;
            }
            Py_ssize_t plen = PyList_GET_SIZE(payload);
            int64_t last = -1;
            if (plen > 0 &&
                as_i64(PyList_GET_ITEM(payload, plen - 1), &last) < 0) {
                Py_DECREF(payload);
                return OUT_BAIL; /* non-int tail: Python scrutiny path */
            }
            if (PyList_Append(c->payloads, payload) < 0) {
                Py_DECREF(payload);
                return OUT_ERR;
            }
            Py_DECREF(payload);
            if (vpush(&c->r_txn, ni) || vpush(&c->r_kid, kid) ||
                vpush(&c->r_mi, mi) || vpush(&c->r_len, plen) ||
                vpush(&c->r_last, last))
                return OUT_ERR;
        } /* other mop types: ignored, keys not interned */
    }
    return OUT_OK;
}

static PyObject *parse(PyObject *self, PyObject *args) {
    PyObject *history;
    if (!PyArg_ParseTuple(args, "O", &history)) return NULL;
    if (!PyList_CheckExact(history)) Py_RETURN_NONE;
    Py_ssize_t nh = PyList_GET_SIZE(history);

    ctx c;
    memset(&c, 0, sizeof(c));
    int out = OUT_BAIL;
    Py_ssize_t n_ok = 0, n = 0;
    PyObject *result = NULL;
    vec node_proc_v;
    memset(&node_proc_v, 0, sizeof(node_proc_v));

    c.payloads = PyList_New(0);
    c.raw_key = PyList_New(0);
    c.kid_of = PyDict_New();
    c.state = PyDict_New();
    c.txns = PyList_New(0);
    c.scrutiny = PyList_New(0);
    if (!c.payloads || !c.raw_key || !c.kid_of || !c.state || !c.txns ||
        !c.scrutiny) {
        out = OUT_ERR;
        goto done;
    }
    c.inv_pos = (int64_t *)malloc((size_t)(nh > 0 ? nh : 1) * 8);
    if (!c.inv_pos) {
        PyErr_NoMemory();
        out = OUT_ERR;
        goto done;
    }

    /* ---- pass A: event scan + invocation pairing -------------------- */
    for (Py_ssize_t i = 0; i < nh; i++) {
        c.inv_pos[i] = -1;
        PyObject *op = PyList_GET_ITEM(history, i);
        if (!PyDict_Check(op)) { out = OUT_BAIL; goto done; }
        PyObject *type = PyDict_GetItemString(op, "type");
        int ev = -1, is_ok = 0, is_info = 0, is_fail = 0;
        if (type && PyUnicode_CheckExact(type)) {
            if (PyUnicode_CompareWithASCIIString(type, "invoke") == 0)
                ev = 0;
            else if (PyUnicode_CompareWithASCIIString(type, "ok") == 0) {
                ev = 1; is_ok = 1;
            } else if (PyUnicode_CompareWithASCIIString(type, "info") == 0) {
                ev = 1; is_info = 1;
            } else if (PyUnicode_CompareWithASCIIString(type, "fail") == 0) {
                ev = 1; is_fail = 1;
            }
        }
        PyObject *process = PyDict_GetItemString(op, "process");
        if (!process) process = Py_None;
        if (ev >= 0) {
            /* previous-event-of-same-process rule (columnar pass A) */
            PyObject *prev = PyDict_GetItemWithError(c.state, process);
            if (!prev && PyErr_Occurred()) {
                /* unhashable process: Python path raises too -> bail */
                PyErr_Clear();
                out = OUT_BAIL;
                goto done;
            }
            if (ev == 1 && prev) {
                long long packed = PyLong_AsLongLong(prev);
                if (packed & 1) c.inv_pos[i] = packed >> 1;
            }
            PyObject *now = PyLong_FromLongLong(((long long)i << 1) |
                                                (ev == 0 ? 1 : 0));
            if (!now) { out = OUT_ERR; goto done; }
            if (PyDict_SetItem(c.state, process, now) < 0) {
                Py_DECREF(now);
                PyErr_Clear();
                out = OUT_BAIL; /* unhashable process */
                goto done;
            }
            Py_DECREF(now);
        }
        int proc_is_int = PyLong_Check(process); /* isinstance(p, int) */
        if (is_ok && proc_is_int) {
            if (vpush(&c.ok_pos, i)) { out = OUT_ERR; goto done; }
        } else if (is_info && proc_is_int) {
            if (vpush(&c.info_pos, i)) { out = OUT_ERR; goto done; }
        } else if (is_fail) {
            if (vpush(&c.fail_pos, i)) { out = OUT_ERR; goto done; }
        }
    }

    n_ok = c.ok_pos.n;
    n = n_ok + c.info_pos.n;
    if (n == 0 || n >= ((Py_ssize_t)1 << 31)) { out = OUT_BAIL; goto done; }

    /* ---- pass B: flatten micro-ops (oks then infos) ----------------- */
    for (Py_ssize_t j = 0; j < n; j++) {
        Py_ssize_t pos = j < n_ok ? c.ok_pos.d[j] : c.info_pos.d[j - n_ok];
        PyObject *op = PyList_GET_ITEM(history, pos);
        if (PyList_Append(c.txns, op) < 0) { out = OUT_ERR; goto done; }
        /* node_proc must fit int64 (Python: np.asarray(..., int64)) */
        PyObject *process = PyDict_GetItemString(op, "process");
        int ovf = 0;
        long long x = process ? PyLong_AsLongLongAndOverflow(process, &ovf)
                              : -1;
        if (!process || ovf || (x == -1 && PyErr_Occurred())) {
            PyErr_Clear();
            out = OUT_BAIL;
            goto done;
        }
        if (vpush(&node_proc_v, x)) { out = OUT_ERR; goto done; }
        int rc = flatten_txn(&c, op, j);
        if (rc != OUT_OK) { out = rc; goto done; }
    }

    /* ---- fail ops' appends (kid() continuation semantics) ----------- */
    for (Py_ssize_t fi = 0; fi < c.fail_pos.n; fi++) {
        PyObject *op = PyList_GET_ITEM(history, c.fail_pos.d[fi]);
        PyObject *value = PyDict_GetItemString(op, "value");
        if (!value) continue;
        int truth = PyObject_IsTrue(value);
        if (truth < 0) { out = OUT_ERR; goto done; }
        if (!truth) continue;
        PyObject **items;
        Py_ssize_t nm;
        if (PyList_CheckExact(value)) {
            items = ((PyListObject *)value)->ob_item;
            nm = PyList_GET_SIZE(value);
        } else if (PyTuple_CheckExact(value)) {
            items = ((PyTupleObject *)value)->ob_item;
            nm = PyTuple_GET_SIZE(value);
        } else { out = OUT_BAIL; goto done; }
        for (Py_ssize_t mi = 0; mi < nm; mi++) {
            PyObject *m = items[mi];
            PyObject **mit;
            Py_ssize_t ml;
            if (PyList_CheckExact(m)) {
                mit = ((PyListObject *)m)->ob_item;
                ml = PyList_GET_SIZE(m);
            } else if (PyTuple_CheckExact(m)) {
                mit = ((PyTupleObject *)m)->ob_item;
                ml = PyTuple_GET_SIZE(m);
            } else { out = OUT_BAIL; goto done; }
            if (ml < 1 || !PyUnicode_CheckExact(mit[0])) {
                out = OUT_BAIL; goto done;
            }
            if (PyUnicode_CompareWithASCIIString(mit[0], "append") != 0)
                continue;
            if (ml < 3 || !PyLong_CheckExact(mit[1])) {
                out = OUT_BAIL; goto done;
            }
            int64_t kid = intern_kid(&c, mit[1]);
            if (kid == -1) { out = OUT_BAIL; goto done; }
            if (kid == -2) { out = OUT_ERR; goto done; }
            int64_t val;
            if (as_i64(mit[2], &val) < 0 || val < 0 || val >= MAX_VAL) {
                out = OUT_BAIL; goto done;
            }
            if (vpush(&c.f_kid, kid) || vpush(&c.f_val, val)) {
                out = OUT_ERR; goto done;
            }
        }
    }

    /* ---- spines: first maximal-length ok read per key ---------------- */
    {
        Py_ssize_t nk = c.nk;
        c.best_len = (int64_t *)malloc((size_t)(nk > 0 ? nk : 1) * 8);
        c.best_row = (int64_t *)malloc((size_t)(nk > 0 ? nk : 1) * 8);
        c.soff = (int64_t *)malloc((size_t)(nk > 0 ? nk : 1) * 8);
        c.slen = (int64_t *)malloc((size_t)(nk > 0 ? nk : 1) * 8);
        if (!c.best_len || !c.best_row || !c.soff || !c.slen) {
            PyErr_NoMemory();
            out = OUT_ERR;
            goto done;
        }
        for (Py_ssize_t k = 0; k < nk; k++) {
            c.best_len[k] = -1;
            c.best_row[k] = -1;
            c.soff[k] = -1;
            c.slen[k] = 0;
        }
        for (Py_ssize_t j = 0; j < c.r_txn.n; j++) {
            if (c.r_txn.d[j] >= (int64_t)n_ok) continue; /* info reads */
            int64_t k = c.r_kid.d[j];
            if (c.r_len.d[j] > c.best_len[k]) {
                c.best_len[k] = c.r_len.d[j];
                c.best_row[k] = j;
            }
        }
        /* S_concat / s_kid / soff / slen in kid order (matches the numpy
         * sort-by-kid layout) */
        for (Py_ssize_t k = 0; k < nk; k++) {
            if (c.best_row[k] < 0) continue;
            PyObject *p = PyList_GET_ITEM(c.payloads, c.best_row[k]);
            Py_ssize_t plen = PyList_GET_SIZE(p);
            c.soff[k] = c.s_concat.n;
            c.slen[k] = plen;
            for (Py_ssize_t e = 0; e < plen; e++) {
                int64_t v;
                if (as_i64(PyList_GET_ITEM(p, e), &v) < 0 || v < 0 ||
                    v >= MAX_VAL) {
                    out = OUT_BAIL; /* non-int/out-of-range spine element */
                    goto done;
                }
                if (vpush(&c.s_concat, v) || vpush(&c.s_kid, k)) {
                    out = OUT_ERR;
                    goto done;
                }
            }
        }
    }

    /* ---- prefix verification against spines -------------------------- */
    for (Py_ssize_t j = 0; j < c.r_txn.n; j++) {
        if (c.r_txn.d[j] >= (int64_t)n_ok) continue;
        int64_t k = c.r_kid.d[j];
        PyObject *p = PyList_GET_ITEM(c.payloads, j);
        PyObject *sp = PyList_GET_ITEM(c.payloads, c.best_row[k]);
        if (p == sp) continue;
        Py_ssize_t plen = PyList_GET_SIZE(p);
        int clean = plen <= PyList_GET_SIZE(sp);
        for (Py_ssize_t e = 0; clean && e < plen; e++) {
            PyObject *a = PyList_GET_ITEM(p, e);
            PyObject *b = PyList_GET_ITEM(sp, e);
            if (a == b) continue;
            int eq = PyObject_RichCompareBool(a, b, Py_EQ);
            if (eq < 0) {
                PyErr_Clear();
                out = OUT_BAIL; /* incomparable payloads: Python path */
                goto done;
            }
            clean = eq;
        }
        if (!clean) {
            PyObject *jj = PyLong_FromSsize_t(j);
            if (!jj || PyList_Append(c.scrutiny, jj) < 0) {
                Py_XDECREF(jj);
                out = OUT_ERR;
                goto done;
            }
            Py_DECREF(jj);
        }
    }

    /* ---- package ----------------------------------------------------- */
    {
        vec np_v, ni_v;
        memset(&np_v, 0, sizeof(np_v));
        memset(&ni_v, 0, sizeof(ni_v));
        int push_fail = 0;
        for (Py_ssize_t j = 0; j < n && !push_fail; j++) {
            Py_ssize_t pos = j < n_ok ? c.ok_pos.d[j]
                                      : c.info_pos.d[j - n_ok];
            push_fail = vpush(&np_v, pos) || vpush(&ni_v, c.inv_pos[pos]);
        }
        if (push_fail) {
            vfree(&np_v);
            vfree(&ni_v);
            PyErr_NoMemory();
            out = OUT_ERR;
            goto done;
        }
        result = PyTuple_New(25);
        if (!result) {
            vfree(&np_v);
            vfree(&ni_v);
            out = OUT_ERR;
            goto done;
        }
        int slot = 0, bad = 0;
        /* SETNEW consumes o; a NULL o marks failure, slot gets None */
#define SETNEW(o)                                                      \
        do {                                                           \
            PyObject *tmp_ = (o);                                      \
            if (!tmp_) { bad = 1; tmp_ = Py_None; Py_INCREF(tmp_); }   \
            PyTuple_SET_ITEM(result, slot++, tmp_);                    \
        } while (0)
        SETNEW(PyLong_FromSsize_t(n_ok));
        SETNEW(PyLong_FromSsize_t(c.nk));
        SETNEW(vbytes(&np_v));
        SETNEW(vbytes(&ni_v));
        SETNEW(vbytes(&node_proc_v));
        SETNEW((Py_INCREF(c.txns), c.txns));
        SETNEW(vbytes(&c.a_txn));
        SETNEW(vbytes(&c.a_kid));
        SETNEW(vbytes(&c.a_val));
        SETNEW(vbytes(&c.a_mi));
        SETNEW(vbytes(&c.r_txn));
        SETNEW(vbytes(&c.r_kid));
        SETNEW(vbytes(&c.r_mi));
        SETNEW(vbytes(&c.r_len));
        SETNEW(vbytes(&c.r_last));
        SETNEW((Py_INCREF(c.payloads), c.payloads));
        SETNEW((Py_INCREF(c.raw_key), c.raw_key));
        SETNEW(vbytes(&c.f_kid));
        SETNEW(vbytes(&c.f_val));
        SETNEW(vbytes(&c.s_concat));
        SETNEW(vbytes(&c.s_kid));
        SETNEW(PyByteArray_FromStringAndSize((char *)c.soff, c.nk * 8));
        SETNEW(PyByteArray_FromStringAndSize((char *)c.slen, c.nk * 8));
        SETNEW(PyByteArray_FromStringAndSize((char *)c.best_row, c.nk * 8));
        SETNEW((Py_INCREF(c.scrutiny), c.scrutiny));
#undef SETNEW
        vfree(&np_v);
        vfree(&ni_v);
        if (bad) {
            if (!PyErr_Occurred()) PyErr_NoMemory();
            out = OUT_ERR;
        } else {
            out = OUT_OK;
        }
    }

done:
    ctx_free(&c);
    vfree(&node_proc_v);
    if (out == OUT_OK) return result;
    Py_XDECREF(result);
    if (out == OUT_BAIL) {
        if (PyErr_Occurred()) PyErr_Clear();
        Py_RETURN_NONE;
    }
    /* OUT_ERR: an exception must be set — the vpush (realloc) failure
     * paths reach here bare, and a NULL return without an exception
     * would surface as a misleading SystemError */
    if (!PyErr_Occurred()) PyErr_NoMemory();
    return NULL;
}

static PyMethodDef methods[] = {
    {"parse", parse, METH_VARARGS,
     "parse(history) -> tuple | None\n"
     "C-speed pass A/B + spine/prefix of the columnar Elle builder."},
    {NULL, NULL, 0, NULL}};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_columnar_c",
    "C-speed columnar history builder (see columnar_ext.c)", -1, methods,
    NULL, NULL, NULL, NULL};

#ifdef __cplusplus
extern "C" {
#endif
PyMODINIT_FUNC PyInit__columnar_c(void) { return PyModule_Create(&moduledef); }
#ifdef __cplusplus
}
#endif
