// Native WGL / just-in-time-linearization search over int-encoded event
// streams. The host-side hot kernel of the linearizability checker: the
// exact same algorithm as checker/linear_cpu.py::check_stream (Lowe-style
// lazy closure before each return event), compiled C++ with an open-
// addressing flat hash set instead of Python sets.
//
// The reference keeps its equivalent hot search native too (knossos's
// JVM-JIT-compiled linear/wgl searches, invoked from
// jepsen/src/jepsen/checker.clj:199-203; SURVEY.md §2.5 "JVM-hosted hot
// kernels"). Built with g++ at first use by jepsen_tpu.native.
//
// C ABI:
//   int wgl_check(const int8_t* kind, const int32_t* slot,
//                 const int32_t* f, const int32_t* a, const int32_t* b,
//                 int64_t n_events, int32_t init_state, int32_t model_id,
//                 int64_t max_configs, int64_t out_stats[3]);
// returns 1 valid, 0 invalid, -1 capacity exceeded (unknown),
// -2 unsupported input. out_stats = {died_event, peak_configs, explored}.
// model_id 0 = cas-register family (read/write/cas; read of id 0 matches
// any state — matches models.cas_register_spec).

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

constexpr int8_t EV_INVOKE = 0;
constexpr int8_t EV_RETURN = 1;
// EV_NOOP = 2

constexpr int32_t F_READ = 0;
constexpr int32_t F_WRITE = 1;
constexpr int32_t F_CAS = 2;

// A config packs (mask:64, state:32) into one 128-bit key.
using Key = unsigned __int128;

inline Key make_key(uint64_t mask, int32_t state) {
  return (Key(mask) << 32) | uint32_t(state);
}
inline uint64_t key_mask(Key k) { return uint64_t(k >> 32); }
inline int32_t key_state(Key k) { return int32_t(uint32_t(k)); }

inline uint64_t mix(uint64_t x) {  // splitmix64 finalizer
  x += 0x9e3779b97f4a7c15ULL;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
  return x ^ (x >> 31);
}
inline uint64_t hash_key(Key k) {
  return mix(uint64_t(k)) ^ mix(uint64_t(k >> 64) * 0x100000001b3ULL);
}

// Open-addressing set of Keys. EMPTY sentinel = all-ones (mask of all 64
// slots with state -1 cannot occur: masks are limited to n_slots<=63 bits).
class FlatSet {
 public:
  explicit FlatSet(size_t initial_pow2 = 1 << 12)
      : slots_(initial_pow2, kEmpty), count_(0) {}

  // returns true if inserted (was absent)
  bool insert(Key k) {
    if ((count_ + 1) * 4 >= slots_.size() * 3) grow();
    size_t m = slots_.size() - 1;
    size_t i = hash_key(k) & m;
    while (true) {
      Key cur = slots_[i];
      if (cur == kEmpty) {
        slots_[i] = k;
        ++count_;
        return true;
      }
      if (cur == k) return false;
      i = (i + 1) & m;
    }
  }

  size_t size() const { return count_; }

  template <typename Fn>
  void for_each(Fn fn) const {
    for (Key k : slots_)
      if (k != kEmpty) fn(k);
  }

 private:
  static constexpr Key kEmpty = ~Key(0);

  void grow() {
    std::vector<Key> old;
    old.swap(slots_);
    slots_.assign(old.size() * 2, kEmpty);
    size_t m = slots_.size() - 1;
    for (Key k : old) {
      if (k == kEmpty) continue;
      size_t i = hash_key(k) & m;
      while (slots_[i] != kEmpty) i = (i + 1) & m;
      slots_[i] = k;
    }
  }

  std::vector<Key> slots_;
  size_t count_;
};

// cas-register transition; returns ok, writes new state.
inline bool step_cas(int32_t state, int32_t f, int32_t a, int32_t b,
                     int32_t* out) {
  switch (f) {
    case F_READ:
      *out = state;
      return a == 0 || a == state;
    case F_WRITE:
      *out = a;
      return true;
    case F_CAS:
      *out = b;
      return state == a;
    default:
      return false;
  }
}

}  // namespace

extern "C" int wgl_check(const int8_t* kind, const int32_t* slot,
                         const int32_t* f, const int32_t* a, const int32_t* b,
                         int64_t n_events, int32_t init_state,
                         int32_t model_id, int64_t max_configs,
                         int64_t* out_stats) {
  out_stats[0] = -1;  // died_event
  out_stats[1] = 1;   // peak_configs
  out_stats[2] = 0;   // explored
  if (model_id != 0) return -2;
  if (max_configs <= 0) max_configs = 20'000'000;

  // slot bound check (we pack masks into 63 bits; sentinel uses the rest)
  int32_t max_slot = -1;
  for (int64_t e = 0; e < n_events; ++e)
    if (kind[e] == EV_INVOKE && slot[e] > max_slot) max_slot = slot[e];
  if (max_slot >= 63) return -2;

  struct Op {
    int32_t f, a, b;
  };
  std::vector<Op> cur(size_t(max_slot < 0 ? 1 : max_slot + 1));

  std::vector<Key> configs{make_key(0, init_state)};
  uint64_t pending = 0;
  int64_t explored = 1;
  int64_t peak = 1;

  for (int64_t e = 0; e < n_events; ++e) {
    int8_t k = kind[e];
    if (k == EV_INVOKE) {
      int32_t s = slot[e];
      cur[size_t(s)] = {f[e], a[e], b[e]};
      pending |= 1ULL << s;
      continue;
    }
    if (k != EV_RETURN) continue;
    int32_t s = slot[e];
    uint64_t bit = 1ULL << s;

    // closure under linearizing any pending, unlinearized op
    FlatSet seen;
    for (Key c : configs) seen.insert(c);
    std::vector<Key> frontier = configs;
    std::vector<Key> next;
    while (!frontier.empty()) {
      next.clear();
      for (Key c : frontier) {
        uint64_t mask = key_mask(c);
        int32_t state = key_state(c);
        uint64_t avail = pending & ~mask;
        while (avail) {
          uint64_t low = avail & (~avail + 1);
          avail ^= low;
          int sl = __builtin_ctzll(low);
          const Op& op = cur[size_t(sl)];
          int32_t st2;
          if (step_cas(state, op.f, op.a, op.b, &st2)) {
            Key c2 = make_key(mask | low, st2);
            if (seen.insert(c2)) next.push_back(c2);
          }
        }
      }
      frontier.swap(next);
      if (int64_t(seen.size()) > max_configs) {
        out_stats[1] = peak;
        out_stats[2] = explored + int64_t(seen.size());
        return -1;
      }
    }
    explored += int64_t(seen.size());
    if (int64_t(seen.size()) > peak) peak = int64_t(seen.size());

    // keep configs that linearized op s; free its slot bit
    FlatSet dedup;
    std::vector<Key> survivors;
    seen.for_each([&](Key c) {
      uint64_t mask = key_mask(c);
      if (mask & bit) {
        Key c2 = make_key(mask & ~bit, key_state(c));
        if (dedup.insert(c2)) survivors.push_back(c2);
      }
    });
    pending &= ~bit;
    configs.swap(survivors);
    if (configs.empty()) {
      out_stats[0] = e;
      out_stats[1] = peak;
      out_stats[2] = explored;
      return 0;
    }
  }
  out_stats[1] = peak;
  out_stats[2] = explored;
  return 1;
}
