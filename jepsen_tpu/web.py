"""Web UI over the store (reference: jepsen/src/jepsen/web.clj).

A small stdlib http.server app: a test table colored by validity
(web.clj:104-122), per-run file browser, and zip download of a run
(web.clj:262-300).
"""
from __future__ import annotations

import html
import io
import json
import logging
import time
import urllib.parse
import zipfile
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path

from jepsen_tpu import store

logger = logging.getLogger("jepsen.web")

STYLE = """
body { font-family: sans-serif; margin: 2em; }
table { border-collapse: collapse; }
td, th { padding: 0.3em 0.8em; border: 1px solid #ddd; text-align: left; }
.valid-true { background: #c8f7c5; }
.valid-false { background: #f7c5c5; }
.valid-unknown { background: #f7eec5; }
.badge-incomplete { background: #e0d5f7; border-radius: 0.6em;
  padding: 0.05em 0.5em; font-size: 0.85em; }
.badge-live { background: #c5e3f7; border-radius: 0.6em;
  padding: 0.05em 0.5em; font-size: 0.85em; }
.live-panel { border: 1px solid #9cc; background: #f2fafc;
  padding: 0.6em 1em; margin: 0.5em 0; }
.explain-panel { border: 1px solid #d9a; background: #fdf4f2;
  padding: 0.6em 1em; margin: 0.5em 0; }
a { text-decoration: none; }
"""

# a live-status.json older than this is a dead daemon's leftover, not a
# live run; the home section and the auto-refresh both key off it
LIVE_FRESH_S = 60.0

# run pages with an actively-tailed live panel meta-refresh at this
# cadence; the ETag/304 path keeps the refresh nearly free
LIVE_REFRESH_S = 2


_VALIDITY_CACHE: dict[str, tuple[int, object, bool]] = {}


def _validity(run_dir: Path):
    """Cached ``(valid?, incomplete)`` from results.json (the reference
    memoizes result loading — web.clj:48-69 fast-tests — because
    re-parsing every run per request doesn't scale). Keyed on the
    results file's mtime, so re-analysis invalidates naturally.
    ``incomplete`` is True for results recovered from a crashed run's
    partial journal (cli analyze --recover), and also when the run
    directory holds a WAL with no results at all — a crash nobody has
    recovered yet."""
    f = run_dir / "results.json"
    try:
        mtime = f.stat().st_mtime_ns
    except OSError:
        # run directory deleted: drop its entry so a long-lived server
        # over many runs doesn't grow the cache monotonically
        _VALIDITY_CACHE.pop(str(f), None)
        if len(_VALIDITY_CACHE) > 4096:
            for k in [k for k in _VALIDITY_CACHE
                      if not Path(k).exists()]:
                _VALIDITY_CACHE.pop(k, None)
        # no results: a leftover WAL marks a crashed, unrecovered run
        return None, (run_dir / "history.wal.jsonl").exists()
    hit = _VALIDITY_CACHE.get(str(f))
    if hit is not None and hit[0] == mtime:
        return hit[1], hit[2]
    try:
        with open(f) as fh:
            results = json.load(fh)
        valid = results.get("valid?")
        incomplete = bool(results.get("incomplete"))
    except Exception:  # noqa: BLE001
        valid, incomplete = None, False
    _VALIDITY_CACHE[str(f)] = (mtime, valid, incomplete)
    return valid, incomplete


def _metrics_table(path: Path) -> str:
    """The per-run metrics table: renders an exported metrics.json (one
    JSONL row per metric child, telemetry.Registry.snapshot format) as
    HTML. Empty string when the run has no metrics."""
    try:
        rows = [json.loads(line) for line in
                path.read_text().splitlines() if line.strip()]
    except OSError:
        return ""
    except Exception:  # noqa: BLE001 — a corrupt export shouldn't 500 the page
        logger.exception("unreadable metrics.json at %s", path)
        return ""
    cells = []
    n_events = 0
    for r in rows:
        kind = r.get("type")
        if kind == "event":
            n_events += 1
            continue
        labels = r.get("labels") or {}
        label_s = ",".join(f"{k}={v}" for k, v in labels.items())
        if kind == "histogram":
            mean = (r["sum"] / r["count"]) if r.get("count") else 0.0
            value = (f"count={r.get('count', 0)} mean={mean:.4g}"
                     + (f" p95={r['p95']:.4g}" if r.get("p95") is not None
                        else ""))
        else:
            value = f"{r.get('value', 0):g}"
        cells.append(f"<tr><td>{html.escape(str(r.get('name')))}</td>"
                     f"<td>{html.escape(kind or '')}</td>"
                     f"<td>{html.escape(label_s)}</td>"
                     f"<td>{html.escape(value)}</td></tr>")
    if not cells:
        return ""
    extra = (f"<p>{n_events} telemetry event(s) — see metrics.json</p>"
             if n_events else "")
    return ("<h2>metrics</h2><table class='metrics'>"
            "<tr><th>metric</th><th>type</th><th>labels</th><th>value</th>"
            "</tr>" + "".join(cells) + "</table>" + extra)


def _load_live_status(run_dir: Path) -> dict | None:
    try:
        with open(run_dir / "live-status.json") as f:
            s = json.load(f)
        return s if isinstance(s, dict) else None
    except (OSError, ValueError):
        return None


def _live_is_fresh(status: dict) -> bool:
    try:
        return time.time() - float(status.get("updated", 0)) < LIVE_FRESH_S
    except (TypeError, ValueError):
        return False


def _live_panel(target: Path) -> tuple[str, bool]:
    """(panel html, actively-live?) for a run page: the live checker
    daemon's streaming verdict — valid-so-far / first-anomaly-at-op-N,
    lag, and backend rung (doc/observability.md, "Live checking")."""
    status = _load_live_status(target)
    if status is None:
        return "", False
    live = status.get("state") == "tailing" and _live_is_fresh(status)
    valid = status.get("valid_so_far")
    if valid is True:
        verdict = f"valid so far ({status.get('checked_ops', 0)} ops checked)"
    elif valid is False:
        first = status.get("first_anomaly_op")
        verdict = (f"INVALID — first anomaly at op {first}"
                   if first is not None else "INVALID")
    else:
        verdict = f"unknown ({html.escape(str(status.get('state')))})"
    rows = [
        ("verdict", verdict),
        ("state", status.get("state")),
        ("workload", status.get("workload")),
        ("lag", f"{status.get('lag_ops', 0)} op(s) / "
                f"{status.get('lag_s', 0)} s"
                + (" — OVER BUDGET" if status.get("over_lag_budget")
                   else "")),
        ("backend", status.get("backend")),
        ("ops", f"{status.get('checked_ops', 0)} checked of "
                f"{status.get('ops_absorbed', 0)} absorbed"),
    ]
    if status.get("torn_skipped"):
        rows.append(("torn lines skipped", status.get("torn_skipped")))
    cells = "".join(
        f"<tr><td>{html.escape(str(k))}</td>"
        f"<td>{html.escape(str(v))}</td></tr>" for k, v in rows)
    badge = " <span class='badge-live'>live</span>" if live else ""
    panel = (f"<div class='live-panel'><h2>live checking{badge}</h2>"
             f"<table>{cells}</table>"
             "<p><a href='live-status.json'>live-status.json</a></p>"
             "</div>")
    return panel, live


def _live_home_section(tests: dict) -> str:
    """The home page "Live" section: every actively-tailed run with its
    streaming verdict and lag. Empty string when no daemon is feeding
    fresh statuses. Takes the already-scanned ``store.tests()`` map so a
    meta-refreshing home page walks the store tree once per request."""
    rows = []
    for name, runs in sorted(tests.items()):
        for ts, run_dir in sorted(runs.items(), reverse=True):
            status = _load_live_status(run_dir)
            if status is None or status.get("state") != "tailing" \
                    or not _live_is_fresh(status):
                continue
            valid = status.get("valid_so_far")
            cls = {True: "valid-true", False: "valid-false"}.get(
                valid, "valid-unknown")
            first = status.get("first_anomaly_op")
            verdict = ("valid so far" if valid is True
                       else f"first anomaly at op {first}"
                       if valid is False and first is not None
                       else str(valid))
            rows.append(
                f"<tr class='{cls}'>"
                f"<td><a href='/{name}/{ts}/'>{html.escape(name)}</a></td>"
                f"<td>{html.escape(ts)}</td>"
                f"<td>{html.escape(verdict)}</td>"
                f"<td>{status.get('lag_ops', 0)} /"
                f" {status.get('lag_s', 0)}s</td>"
                f"<td>{html.escape(str(status.get('backend')))}</td></tr>")
    if not rows:
        return ""
    return ("<h2>live <span class='badge-live'>"
            f"{len(rows)} run(s)</span></h2>"
            "<table><tr><th>test</th><th>time</th><th>verdict</th>"
            "<th>lag ops/s</th><th>backend</th></tr>"
            + "".join(rows) + "</table>")


def _hunt_home_section(base: Path) -> str:
    """The home page "hunt" section: anomalies the schedule fuzzer
    landed under ``<store>/hunt/`` (doc/robustness.md "Schedule
    fuzzing"), each linking into its artifact bundle. Empty string
    when no hunt has landed anything."""
    from jepsen_tpu.fuzz.hunt import list_hunts
    rows = []
    for h in list_hunts(base):
        hid = str(h.get("id"))
        rows.append(
            "<tr class='valid-false'>"
            f"<td><a href='/hunt/{hid}/'>{html.escape(hid)}</a></td>"
            f"<td>{h.get('windows')}</td>"
            f"<td>{h.get('n_ops')}</td>"
            f"<td>{h.get('seed')}</td>"
            f"<td><code>jepsen-tpu hunt --replay {html.escape(hid)}"
            "</code></td></tr>")
    if not rows:
        return ""
    return ("<h2>hunt <span class='badge-incomplete'>"
            f"{len(rows)} anomal{'y' if len(rows) == 1 else 'ies'}"
            "</span></h2>"
            "<table><tr><th>id</th><th>windows</th><th>n_ops</th>"
            "<th>gen seed</th><th>reproduce</th></tr>"
            + "".join(rows) + "</table>")


def _explain_section(rel: str, target: Path) -> str:
    """The run page's "Explain" panel: the anomaly-forensics summary
    (first anomaly op, witness size, localization backend) with links to
    ``anomaly.json`` and the rendered witness timeline
    (doc/observability.md "Anomaly forensics"). Empty string when the
    run has no forensics (the valid, healthy case)."""
    f = target / "anomaly.json"
    if not f.is_file():
        return ""
    base = rel.rstrip("/")
    links = [f"<a href='/{base}/anomaly.json'>anomaly.json</a>"]
    if (target / "witness-timeline.html").is_file():
        links.append(f"<a href='/{base}/witness-timeline.html'>"
                     "witness-timeline.html</a>")
    try:
        a = json.loads(f.read_text())
    except Exception:  # noqa: BLE001 — a corrupt artifact still links
        return ("<div class='explain-panel'><h2>explain</h2><p>"
                + " ".join(links) + "</p></div>")
    fa = a.get("first_anomaly") or {}
    wit = a.get("witness") or {}
    overlapping = sum(1 for w in (a.get("fault_windows") or ())
                      if w.get("overlaps_witness"))
    rows = [
        ("first anomaly", f"op {fa.get('op_index')} — "
                          f"{fa.get('f')} {fa.get('value')!r} "
                          f"(process {fa.get('process')})"),
        ("witness", f"{len(wit.get('op_indices') or [])} op(s)"
                    + (" (minimal)" if wit.get("minimal") else "")),
        ("backend", a.get("backend")),
        ("bisect steps", a.get("bisect_steps")),
        ("fault windows overlapping", overlapping),
        ("latency", f"{a.get('explain_latency_seconds')} s"),
    ]
    cells = "".join(
        f"<tr><td>{html.escape(str(k))}</td>"
        f"<td>{html.escape(str(v))}</td></tr>" for k, v in rows)
    return (f"<div class='explain-panel'><h2>explain</h2>"
            f"<table>{cells}</table><p>" + " ".join(links) + "</p></div>")


def _trace_section(rel: str, target: Path) -> str:
    """The run page's "Causal trace" panel: a summary of the Perfetto
    trace (span counts per track, slowest ops, the demotion chain) with
    links to ``trace.json`` and — when a crash/stall left one — the
    flight-recorder dump (doc/observability.md "Causal trace"). Empty
    string when the run has no trace artifacts."""
    names = [n for n in ("trace.json", "trace-derived.json",
                         "flight-recorder.jsonl")
             if (target / n).is_file()]
    if not names:
        return ""
    base = rel.rstrip("/")
    links = " ".join(f"<a href='/{base}/{n}'>{n}</a>" for n in names)
    summary = ""
    trace_file = next((n for n in names if n.endswith(".json")), None)
    if trace_file is not None:
        try:
            from jepsen_tpu.trace.derive import summarize_trace
            s = summarize_trace(target / trace_file)
        except Exception:  # noqa: BLE001 — a corrupt trace still links
            logger.exception("trace summary failed for %s", target)
            s = None
        if s:
            tracks = ", ".join(f"{t}: {n}" for t, n in s["tracks"].items())
            rows = [("events", s["events"]), ("tracks", tracks)]
            if s["slowest_ops"]:
                rows.append(("slowest", "; ".join(
                    f"{o['name']} ({o['track']}) {o['dur_ms']} ms"
                    for o in s["slowest_ops"])))
            if s["demotions"]:
                rows.append(("demotion chain",
                             " → ".join(s["demotions"])))
            if s["open_spans"]:
                rows.append(("unfinished spans", s["open_spans"]))
            summary = "<table>" + "".join(
                f"<tr><td>{html.escape(str(k))}</td>"
                f"<td>{html.escape(str(v))}</td></tr>"
                for k, v in rows) + "</table>"
    return ("<h2>causal trace</h2>" + summary + "<p>" + links +
            " — load trace.json in <a href='https://ui.perfetto.dev'>"
            "Perfetto</a> (doc/observability.md)</p>")


def _forensics_section(rel: str, target: Path) -> str:
    """Links a run's robustness forensics — late.jsonl (completions
    quarantined from reaped zombie workers), stall-threads.txt (the
    stall watchdog's stack dumps), and check.ckpt / live-session.ckpt
    (an interrupted check's durable carry / the live daemon's restart
    snapshot — their presence marks an interrupted check) — from the
    run page. Empty string when the run has none (the common, healthy
    case)."""
    arts = store.forensic_artifacts(target)
    if not arts:
        return ""
    base = rel.rstrip("/")
    links = " ".join(
        f"<a href='/{base}/{html.escape(name)}'>{html.escape(name)}</a>"
        for name in sorted(arts))
    return ("<h2>robustness forensics</h2><p>" + links +
            " — quarantined late completions / stall stack dumps / "
            "interrupted-check checkpoints (doc/robustness.md)</p>")


def _elle_section(rel: str, target: Path) -> str:
    """Links a run's elle/ anomaly artifacts (per-anomaly-type
    explanation files the txn checkers write on invalid results) from
    the run page. Empty string when the run has none."""
    d = target / "elle"
    if not d.is_dir():
        return ""
    files = sorted(p.name for p in d.iterdir()
                   if p.is_file() and p.suffix == ".txt")
    if not files:
        return ""
    base = rel.rstrip("/")
    links = " ".join(
        f"<a href='/{base}/elle/{html.escape(fn)}'>{html.escape(fn)}</a>"
        for fn in files)
    return f"<h2>anomalies (elle)</h2><p>{links}</p>"


class Handler(BaseHTTPRequestHandler):
    store_dir = "store"

    def log_message(self, fmt, *args):  # quiet
        logger.debug(fmt, *args)

    def _send(self, body: bytes, ctype="text/html; charset=utf-8", code=200,
              extra_headers=None):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _page(self, title: str, body: str, head_extra: str = "") -> bytes:
        return (f"<!doctype html><html><head><title>{html.escape(title)}</title>"
                f"{head_extra}<style>{STYLE}</style></head><body>"
                f"<h1>{html.escape(title)}</h1>{body}</body></html>").encode()

    def do_GET(self):  # noqa: N802
        path = urllib.parse.unquote(self.path)
        base = Path(self.store_dir).resolve()
        try:
            if path == "/" or path == "":
                return self._home(base)
            if path == "/fleet":
                return self._fleet(base)
            if path.startswith("/zip/"):
                return self._zip(base, path[len("/zip/"):])
            return self._files(base, path.lstrip("/"))
        except BrokenPipeError:
            pass
        except Exception:  # noqa: BLE001
            logger.exception("web error")
            self._send(self._page("error", "<p>internal error</p>"), code=500)

    def _home(self, base: Path):
        """Test table, most recent first (web.clj:104-122), with links to
        each run's telemetry artifacts (metrics/trace/profile) when the
        run produced them."""
        rows = []
        tests = store.tests(store_dir=str(base))
        for name, runs in sorted(tests.items()):
            for ts, run_dir in sorted(runs.items(), reverse=True):
                valid, incomplete = _validity(run_dir)
                cls = {True: "valid-true", False: "valid-false"}.get(
                    valid, "valid-unknown")
                badge = (" <span class='badge-incomplete'>incomplete"
                         "</span>" if incomplete else "")
                arts = {**store.telemetry_artifacts(run_dir),
                        **store.forensic_artifacts(run_dir),
                        **store.explain_artifacts(run_dir)}
                links = " ".join(
                    f"<a href='/{name}/{ts}/{a}{'/' if a == store.PROFILE_DIR else ''}'>"
                    f"{html.escape(a)}</a>"
                    for a in sorted(arts))
                rows.append(
                    f"<tr class='{cls}'>"
                    f"<td><a href='/{name}/{ts}/'>{html.escape(name)}</a></td>"
                    f"<td><a href='/{name}/{ts}/'>{html.escape(ts)}</a></td>"
                    f"<td>{valid}{badge}</td>"
                    f"<td>{links}</td>"
                    f"<td><a href='/zip/{name}/{ts}'>zip</a></td></tr>")
        live = _live_home_section(tests)
        fleet = ("<p><a href='/fleet'>fleet dashboard</a></p>"
                 if (base / "fleet-status.json").exists() else "")
        hunt = _hunt_home_section(base)
        body = fleet + hunt + (live + "<h2>runs</h2>" if live else "") \
            + ("<table><tr><th>test</th><th>time</th><th>valid?</th>"
               "<th>telemetry</th><th>download</th></tr>"
               + "".join(rows) + "</table>")
        head = (f"<meta http-equiv='refresh' content='{LIVE_REFRESH_S}'>"
                if live else "")
        self._send(self._page("Jepsen-TPU", body, head_extra=head))

    @staticmethod
    def _ha_line(ha: dict) -> str:
        """One HA/degraded card line (doc/robustness.md "Fleet HA"):
        who holds leases, the fencing/shed counters, and whether
        non-verdict surfaces are degraded."""
        if not ha:
            return ""
        lease = (f"leased checking (ttl {ha.get('lease_ttl_s', 0)}s), "
                 f"{ha.get('leases_held', 0)} held"
                 if ha.get("leasing") else "leasing off")
        degraded = int(ha.get("degraded_total", 0))
        badge = (" <span class='badge-incomplete'>degraded</span>"
                 if degraded else "")
        shed = (" <span class='badge-incomplete'>shedding</span>"
                if ha.get("shedding") else "")
        return (f"<p>ha: host <b>{html.escape(str(ha.get('host', '?')))}"
                f"</b> · {lease} · "
                f"{int(ha.get('lease_acquired', 0))} takeovers / "
                f"{int(ha.get('lease_lost', 0))} lost / "
                f"{int(ha.get('fenced_writes', 0))} fenced writes"
                f"{shed}{badge}</p>")

    def _fleet(self, base: Path):
        """The fleet dashboard: renders ``fleet-status.json`` (the pool
        scheduler's atomically-published aggregate — doc/observability.md
        "Fleet plane") with first-anomaly links into each run's explain
        and trace artifacts."""
        try:
            with open(base / "fleet-status.json", encoding="utf-8") as f:
                st = json.load(f)
        except (OSError, ValueError):
            return self._send(self._page(
                "fleet", "<p>no fleet-status.json — is a fleet daemon "
                "writing to this store?</p>"), code=404)
        runs, mesh, ing = st.get("runs", {}), st.get("mesh", {}), \
            st.get("ingest", {})
        stale = time.time() - float(st.get("updated", 0)) > LIVE_FRESH_S
        badge = (" <span class='badge-incomplete'>stale</span>"
                 if stale else "")
        worst = st.get("worst_lag_run")
        cards = (
            f"<p>runs: <b>{runs.get('active', 0)}</b> active / "
            f"{runs.get('tracked', 0)} tracked / "
            f"{runs.get('final', 0)} final / "
            f"<b class='valid-false'>{runs.get('invalid', 0)}"
            f" invalid</b> / {runs.get('breaker_open', 0)} breaker open"
            f" / {int(runs.get('deferred_total', 0))} deferred{badge}"
            f"</p>"
            f"<p>worst lag: <b>{st.get('worst_lag_ops', 0)}</b> ops"
            + (f" ({html.escape(str(worst))})" if worst else "")
            + f" · mesh: <b>{mesh.get('width', 0)}</b> devices wide, "
            f"failed {mesh.get('failed_devices', [])}, "
            f"{int(mesh.get('shrinks', 0))} shrinks / "
            f"{int(mesh.get('regrows', 0))} regrows"
            f" · ingest: {ing.get('bytes_per_s', 0.0):.0f} B/s, "
            f"{int(ing.get('bytes_total', 0))} B total, "
            f"{int(ing.get('rejected_total', 0))} rejected, "
            f"{int(ing.get('shed_total', 0))} shed</p>"
            + self._ha_line(st.get("ha", {})))
        rows = []
        for r in st.get("top_runs", []):
            valid = r.get("valid_so_far")
            cls = {True: "valid-true", False: "valid-false"}.get(
                valid, "valid-unknown")
            rel = f"{r.get('name')}/{r.get('timestamp')}"
            first = r.get("first_anomaly_op")
            links = " ".join(
                f"<a href='/{html.escape(p)}'>{html.escape(a)}</a>"
                for a, p in sorted(r.get("links", {}).items()))
            rows.append(
                f"<tr class='{cls}'>"
                f"<td><a href='/{html.escape(rel)}/'>"
                f"{html.escape(rel)}</a></td>"
                f"<td>{html.escape(str(r.get('state')))}</td>"
                f"<td>{valid}</td>"
                f"<td>{r.get('lag_ops', 0)}</td>"
                f"<td>{'-' if first is None else first}</td>"
                f"<td>{links}</td></tr>")
        table = ("<h2>most lagged runs</h2>"
                 "<table><tr><th>run</th><th>state</th><th>valid?</th>"
                 "<th>lag (ops)</th><th>first anomaly</th>"
                 "<th>artifacts</th></tr>" + "".join(rows) + "</table>"
                 if rows else "<p>no tracked runs this poll</p>")
        head = f"<meta http-equiv='refresh' content='{LIVE_REFRESH_S}'>"
        self._send(self._page("fleet", cards + table, head_extra=head))

    def _files(self, base: Path, rel: str):
        target = (base / rel).resolve()
        if not (target == base or target.is_relative_to(base)):
            return self._send(b"forbidden", code=403)
        if target.is_dir():
            items = "".join(
                f"<li><a href='/{rel.rstrip('/')}/{p.name}{'/' if p.is_dir() else ''}'>"
                f"{html.escape(p.name)}</a></li>"
                for p in sorted(target.iterdir()))
            live_panel, live = _live_panel(target)
            metrics = _metrics_table(target / "metrics.json")
            explain = _explain_section(rel, target)
            elle = _elle_section(rel, target)
            trace = _trace_section(rel, target)
            forensics = _forensics_section(rel, target)
            banner = ""
            if (target / "results.json").exists() or \
                    (target / "history.wal.jsonl").exists():
                _valid, incomplete = _validity(target)
                if incomplete and not live:
                    banner = ("<p><span class='badge-incomplete'>"
                              "incomplete</span> this run crashed; its "
                              "history was (or can be) recovered from "
                              "the write-ahead journal via "
                              "<code>analyze --recover</code></p>")
            head = (f"<meta http-equiv='refresh' "
                    f"content='{LIVE_REFRESH_S}'>" if live else "")
            return self._send(
                self._page(rel, f"{live_panel}{banner}{explain}"
                                f"{trace}{forensics}{elle}"
                                f"{metrics}<ul>{items}</ul>",
                           head_extra=head))
        if target.exists():
            ctype = ("application/json" if target.suffix == ".json"
                     else "image/png" if target.suffix == ".png"
                     else "image/svg+xml" if target.suffix == ".svg"
                     else "text/html; charset=utf-8"
                     if target.suffix in (".html", ".htm")
                     else "text/plain; charset=utf-8")
            # weak-validator ETag from (mtime, size): live pages poll
            # metrics.json / live-status.json every couple of seconds —
            # an unchanged snapshot answers 304 with no body re-read
            try:
                st = target.stat()
                etag = f'"{st.st_mtime_ns:x}-{st.st_size:x}"'
            except OSError:
                etag = None
            if etag is not None and \
                    self.headers.get("If-None-Match") == etag:
                self.send_response(304)
                self.send_header("ETag", etag)
                self.end_headers()
                return None
            return self._send(target.read_bytes(), ctype=ctype,
                              extra_headers=({"ETag": etag} if etag
                                             else None))
        return self._send(self._page("404", "<p>not found</p>"), code=404)

    def _zip(self, base: Path, rel: str):
        """Streams a zip of one run (web.clj:262-300)."""
        target = (base / rel).resolve()
        if not (target.is_relative_to(base) and target != base and target.is_dir()):
            return self._send(b"not found", code=404)
        buf = io.BytesIO()
        with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
            for p in target.rglob("*"):
                if p.is_file():
                    z.write(p, p.relative_to(base))
        self._send(buf.getvalue(), ctype="application/zip",
                   extra_headers={"Content-Disposition":
                                  f"attachment; filename={rel.replace('/', '-')}.zip"})


def serve(store_dir: str = "store", host: str = "0.0.0.0", port: int = 8080):
    """web.clj:361-366"""
    handler = type("BoundHandler", (Handler,), {"store_dir": store_dir})
    server = ThreadingHTTPServer((host, port), handler)
    logger.info("Jepsen-TPU web UI at http://%s:%d", host, port)
    server.serve_forever()


def make_server(store_dir: str = "store", host: str = "127.0.0.1", port: int = 0):
    """Non-blocking variant for tests; returns the server (call
    serve_forever in a thread; .server_address has the bound port)."""
    handler = type("BoundHandler", (Handler,), {"store_dir": store_dir})
    return ThreadingHTTPServer((host, port), handler)
