"""List-append workload checker (capability-equivalent to elle.list-append,
invoked from the reference at jepsen/src/jepsen/tests/cycle/append.clj).

Txns are lists of micro-ops ``["append", k, v]`` / ``["r", k, [v...]]``
(append.clj:29-55). Reads observe the whole list for a key, so version
order per key is directly observable: every read is a prefix of the key's
final order, appends extend it. From that we infer ww/wr/rw edges and feed
jepsen_tpu.elle.check_cycles; non-cyclic anomalies (G1a aborted read, G1b
intermediate read, internal, duplicates, incompatible orders) are
data-parallel scans.
"""
from __future__ import annotations

import logging
import random
from collections import defaultdict
from typing import Any

from jepsen_tpu import elle
from jepsen_tpu.elle import RW, WR, WW, Graph

logger = logging.getLogger("jepsen.elle.append")


from jepsen_tpu.txn import _hk


def check(history: list[dict], accelerator: str = "auto",
          consistency_models=("strict-serializable",)) -> dict:
    # ok txns participate in the graph; failed txns matter for G1a;
    # info (indeterminate) txns' writes may be observed — treated like ok
    # when they are (elle does the same: info writes that appear are real)
    oks = [op for op in history
           if op.get("type") == "ok" and isinstance(op.get("process"), int)]
    fails = [op for op in history if op.get("type") == "fail"]
    infos = [op for op in history if op.get("type") == "info"
             and isinstance(op.get("process"), int)]

    txns = oks + infos  # graph nodes; info txns included if observed
    txn_index = {id(op): i for i, op in enumerate(txns)}
    n = len(txns)

    anomalies_extra: dict[str, list] = defaultdict(list)

    # ---- writer maps ----------------------------------------------------
    writer_of: dict[tuple, tuple[int, int, int]] = {}  # (k,v) -> (txn, mop_i, nth-append-of-key-in-txn)
    appends_per_txn_key: dict[tuple[int, Any], list] = defaultdict(list)
    failed_writes: dict[tuple, dict] = {}
    for op in fails:
        for m in op.get("value") or []:
            if m[0] == "append":
                failed_writes[(_hk(m[1]), m[2])] = op
    for i, op in enumerate(txns):
        for mi, m in enumerate(op.get("value") or []):
            if m[0] == "append":
                key = (_hk(m[1]), m[2])
                if key in writer_of:
                    anomalies_extra["duplicate-appends"].append(
                        {"key": m[1], "value": m[2]})
                    continue
                writer_of[key] = (i, mi, len(appends_per_txn_key[(i, _hk(m[1]))]))
                appends_per_txn_key[(i, _hk(m[1]))].append(m[2])

    # ---- version orders from reads -------------------------------------
    # longest read per key is the spine; every other read must be a prefix
    reads_by_key: dict[Any, list[tuple[int, list]]] = defaultdict(list)
    for i, op in enumerate(txns):
        if op.get("type") != "ok":
            continue  # info txns' reads are unreliable
        for m in op.get("value") or []:
            if m[0] == "r" and m[2] is not None:
                reads_by_key[_hk(m[1])].append((i, list(m[2])))

    version_order: dict[Any, list] = {}
    for k, reads in reads_by_key.items():
        longest = max(reads, key=lambda t: len(t[1]))[1]
        for i, r in reads:
            if r != longest[: len(r)]:
                anomalies_extra["incompatible-order"].append(
                    {"key": k, "read": r, "longest": longest})
            if len(set(r)) != len(r):
                anomalies_extra["duplicate-elements"].append(
                    {"key": k, "read": r})
        version_order[k] = longest

    # ---- non-cyclic anomalies ------------------------------------------
    for k, reads in reads_by_key.items():
        for i, r in reads:
            for v in r:
                if (k, v) in failed_writes:
                    anomalies_extra["G1a"].append(
                        {"key": k, "value": v, "read-txn": txns[i].get("value")})
                elif (k, v) not in writer_of:
                    # no known writer: future/phantom value
                    anomalies_extra["unobserved-writer"].append(
                        {"key": k, "value": v})
            # G1b (intermediate read): txns append atomically, so a read
            # must observe either ALL of a committed txn's appends to k or
            # none of them, in append order. A proper subset (in any
            # position — even when later txns' elements follow it) means
            # the read saw an intermediate state.
            observed: dict[int, list] = defaultdict(list)
            for v in r:
                w = writer_of.get((k, v))
                if w is not None:
                    observed[w[0]].append(v)
            for wi, obs in observed.items():
                if wi == i or txns[wi].get("type") != "ok":
                    continue  # own reads / indeterminate writers: not G1b
                txn_appends = appends_per_txn_key[(wi, k)]
                if obs == txn_appends:
                    continue
                if obs == txn_appends[: len(obs)]:
                    anomalies_extra["G1b"].append(
                        {"key": k, "read": r,
                         "writer": txns[wi].get("value")})
                else:
                    anomalies_extra["incompatible-order"].append(
                        {"key": k, "read": r,
                         "writer-appends": txn_appends})

    # internal: a txn's own read must reflect its earlier appends
    for i, op in enumerate(txns):
        seen_appends: dict[Any, list] = defaultdict(list)
        for m in op.get("value") or []:
            k = _hk(m[1])
            if m[0] == "append":
                seen_appends[k].append(m[2])
            elif m[0] == "r" and m[2] is not None:
                mine = seen_appends[k]
                if mine and list(m[2])[-len(mine):] != mine:
                    anomalies_extra["internal"].append(
                        {"key": m[1], "read": list(m[2]),
                         "expected-suffix": list(mine)})

    # ---- dependency edges ----------------------------------------------
    graph = Graph(n)
    for k, order in version_order.items():
        # ww: consecutive versions; also the unread appends that follow the
        # longest read can't be ordered — elle only orders observed versions
        writers = [writer_of.get((k, v), (None,))[0] for v in order]
        for a, b in zip(writers, writers[1:]):
            if a is not None and b is not None and a != b:
                graph.add(a, b, WW)
        for i, r in reads_by_key[k]:
            if r:
                w = writer_of.get((k, r[-1]))
                if w is not None and w[0] != i:
                    graph.add(w[0], i, WR)  # i read w's final state
            # rw: the version after the one i observed (for an empty read,
            # index 0 — the first version's writer)
            nxt_idx = len(r)
            if nxt_idx < len(order):
                w = writer_of.get((k, order[nxt_idx]))
                if w is not None and w[0] != i:
                    graph.add(i, w[0], RW)

    # realtime (invoke/complete interval order) + per-process succession
    # edges: close the strict-serializable / sequential anomaly surface
    elle.add_timing_edges(graph, history, txns)

    cyc = elle.check_cycles(graph, accelerator=accelerator)
    # drop informational-only extras from validity
    extras = {k: v for k, v in anomalies_extra.items()
              if k != "unobserved-writer"}
    result = elle.result_map(cyc, txns, extras,
                             consistency_models=consistency_models)
    result["txn-count"] = n
    result["edge-count"] = len(graph.edges)
    return result


# ---------------------------------------------------------------------------
# Generator (append.clj gen / elle.list-append/gen)
# ---------------------------------------------------------------------------

def gen(key_count: int = 3, min_txn_length: int = 1, max_txn_length: int = 4,
        max_writes_per_key: int = 256):
    """Generates random list-append txns over a rotating key pool."""
    counters: dict = defaultdict(int)
    active_keys: list = list(range(key_count))
    next_key: list = [key_count]

    def one_txn(test, ctx):
        txn = []
        length = ctx.rng.randint(min_txn_length, max_txn_length)
        for _ in range(length):
            idx = ctx.rng.randrange(len(active_keys))
            k = active_keys[idx]
            if ctx.rng.random() < 0.5:
                txn.append(["r", k, None])
            else:
                counters[k] += 1
                if counters[k] > max_writes_per_key:
                    # retire the key, open a fresh one in its slot
                    k = active_keys[idx] = next_key[0]
                    next_key[0] += 1
                    counters[k] += 1
                txn.append(["append", k, counters[k]])
        return {"f": "txn", "value": txn}

    return one_txn
