"""List-append workload checker (capability-equivalent to elle.list-append,
invoked from the reference at jepsen/src/jepsen/tests/cycle/append.clj).

Txns are lists of micro-ops ``["append", k, v]`` / ``["r", k, [v...]]``
(append.clj:29-55). Reads observe the whole list for a key, so version
order per key is directly observable: every read is a prefix of the key's
final order, appends extend it. From that we infer ww/wr/rw edges and feed
jepsen_tpu.elle.check_cycles; non-cyclic anomalies (G1a aborted read, G1b
intermediate read, internal, duplicates, incompatible orders) are
data-parallel scans.
"""
from __future__ import annotations

import logging
import random
from collections import defaultdict
from typing import Any

import numpy as np

from jepsen_tpu import elle
from jepsen_tpu.elle import RW, WR, WW, Graph

logger = logging.getLogger("jepsen.elle.append")


from jepsen_tpu.txn import _hk


def _scan_reads_fast(k, reads, longest, txns, writer_of, failed_writes,
                     appends_per_txn_key, multi_writers, anomalies_extra,
                     wr_pairs, fail_vals):
    """Columnar per-key read scan (prefix consistency, duplicates, G1a,
    unobserved writers, G1b) for integer value domains — the common
    workload shape, where per-element Python dict walks would dominate
    the whole Elle check at history scale. Returns False when the domain
    isn't integer-typed (caller falls back to the Python twin).

    Anomaly semantics are identical to _scan_reads_py; a differential
    test pins the two together."""
    from itertools import chain

    def int_col(values):
        """Exact signed-int column or None — np.asarray(x, int64) would
        silently TRUNCATE floats (2.7 -> 2), which must fall back to the
        Python twin instead of fabricating membership hits."""
        if not len(values):
            return np.zeros(0, np.int64)  # asarray([]) defaults to float64
        try:
            a = np.asarray(values)
        except (TypeError, ValueError):
            return None
        if a.ndim != 1 or a.dtype.kind != "i":
            return None
        return a.astype(np.int64)

    spine = int_col(longest)
    wvals = int_col([v for v, _ in wr_pairs])
    fvals = int_col(sorted(fail_vals))
    if spine is None or wvals is None or fvals is None:
        return False
    payloads = [r for _, r in reads]
    lens = np.fromiter((len(r) for r in payloads), np.int64,
                       count=len(payloads))
    total = int(lens.sum())
    try:
        # one C pass builds float64; the int view must round-trip exactly
        # (fromiter into int64 would silently truncate 2.7 -> 2). Ints at
        # or beyond 2^53 also fall back: float64 can't represent them
        # exactly, so the round-trip check below couldn't notice a value
        # that float conversion itself already corrupted.
        concat_f = np.fromiter(chain.from_iterable(payloads), np.float64,
                               count=total)
    except (TypeError, ValueError, OverflowError):
        return False
    if total and np.abs(concat_f).max() >= float(1 << 53):
        return False
    concat = concat_f.astype(np.int64)
    if not np.array_equal(concat.astype(np.float64), concat_f):
        return False
    order = np.argsort(wvals) if wvals.size else np.zeros(0, np.int64)
    wvals_sorted = wvals[order]
    wtxn_sorted = (np.asarray([wi for _, wi in wr_pairs], np.int64)[order]
                   if wr_pairs else np.zeros(0, np.int64))
    multi_arr = np.asarray(sorted(multi_writers), dtype=np.int64)

    def member(sorted_arr, vals):
        if sorted_arr.size == 0:
            return np.zeros(vals.shape, bool), np.zeros(vals.shape, np.int64)
        pos = np.clip(np.searchsorted(sorted_arr, vals), 0,
                      sorted_arr.size - 1)
        return sorted_arr[pos] == vals, pos

    ends = np.cumsum(lens)
    starts = ends - lens
    # row id per element; bincount-based segment reductions sidestep
    # reduceat's empty-segment pitfalls (a trailing empty read must not
    # steal elements from its neighbour)
    row_of_elem = np.repeat(np.arange(len(payloads)), lens)

    def read_of(elem_idx):  # global element position -> read row
        return int(np.searchsorted(ends, elem_idx, side="right"))

    def any_per_row(elem_mask):
        return np.bincount(row_of_elem, weights=elem_mask,
                           minlength=len(payloads)) > 0

    # prefix consistency, all reads at once: element p of read j must
    # equal spine[p - starts[j]]
    if total:
        within = np.arange(total) - starts[row_of_elem]
        seg_ok = ~any_per_row(concat != spine[within])
    else:
        seg_ok = np.ones(len(payloads), bool)
    spine_dup_free = np.unique(spine).size == spine.size

    # G1a / unobserved writers, element-level
    failed_hit, _ = member(fvals, concat)
    writer_hit, pos = member(wvals_sorted, concat)
    for idx in np.nonzero(failed_hit)[0].tolist():
        anomalies_extra["G1a"].append(
            {"key": k, "value": int(concat[idx]),
             "read-txn": txns[reads[read_of(idx)][0]].get("value")})
    for idx in np.nonzero(~writer_hit & ~failed_hit)[0].tolist():
        anomalies_extra["unobserved-writer"].append(
            {"key": k, "value": int(concat[idx])})

    # G1b candidates: reads touching a multi-append writer's values need
    # the per-writer grouping check (everything else can't be partial)
    g1b_rows = np.zeros(len(payloads), bool)
    if multi_arr.size and total:
        elem_w = np.where(writer_hit, wtxn_sorted[pos], -1)
        touched, _ = member(multi_arr, elem_w)
        g1b_rows = any_per_row(touched)

    # per-read scrutiny only where something is off: a clean prefix of a
    # duplicate-free spine can contain neither incompatibilities nor
    # duplicates, so the common case never re-enters Python
    for j in np.nonzero(~seg_ok)[0].tolist():
        i, r = reads[j]
        anomalies_extra["incompatible-order"].append(
            {"key": k, "read": r, "longest": longest})
    if spine_dup_free:
        scrutiny = ~seg_ok
    else:
        scrutiny = np.ones(len(payloads), bool)
    for j in np.nonzero(scrutiny)[0].tolist():
        i, r = reads[j]
        if len(set(r)) != len(r):
            anomalies_extra["duplicate-elements"].append(
                {"key": k, "read": r})
            g1b_rows[j] = True  # a doubled single-append value also
            #                     fails the subsequence test
    for j in np.nonzero(g1b_rows)[0].tolist():
        i, r = reads[j]
        _g1b_one_read(k, i, r, txns, writer_of, appends_per_txn_key,
                      anomalies_extra)
    return True


def _g1b_one_read(k, i, r, txns, writer_of, appends_per_txn_key,
                  anomalies_extra):
    """The per-writer observed-subsequence check for one read (G1b /
    incompatible-order): a committed txn's appends to k must be observed
    all-or-nothing, in order (append.clj intermediate-read semantics)."""
    observed: dict[int, list] = defaultdict(list)
    for v in r:
        w = writer_of.get((k, v))
        if w is not None:
            observed[w[0]].append(v)
    for wi, obs in observed.items():
        if wi == i or txns[wi].get("type") != "ok":
            continue  # own reads / indeterminate writers: not G1b
        txn_appends = appends_per_txn_key[(wi, k)]
        if obs == txn_appends:
            continue
        if obs == txn_appends[: len(obs)]:
            anomalies_extra["G1b"].append(
                {"key": k, "read": r, "writer": txns[wi].get("value")})
        else:
            anomalies_extra["incompatible-order"].append(
                {"key": k, "read": r, "writer-appends": txn_appends})


def _scan_reads_py(k, reads, longest, txns, writer_of, failed_writes,
                   appends_per_txn_key, anomalies_extra):
    """Pure-Python per-key read scan: the oracle twin of
    _scan_reads_fast, and the fallback for non-integer domains."""
    for i, r in reads:
        if r != longest[: len(r)]:
            anomalies_extra["incompatible-order"].append(
                {"key": k, "read": r, "longest": longest})
        if len(set(r)) != len(r):
            anomalies_extra["duplicate-elements"].append(
                {"key": k, "read": r})
        for v in r:
            if (k, v) in failed_writes:
                anomalies_extra["G1a"].append(
                    {"key": k, "value": v, "read-txn": txns[i].get("value")})
            elif (k, v) not in writer_of:
                # no known writer: future/phantom value
                anomalies_extra["unobserved-writer"].append(
                    {"key": k, "value": v})
        _g1b_one_read(k, i, r, txns, writer_of, appends_per_txn_key,
                      anomalies_extra)


def check_stored(test_name: str, timestamp: str, store_dir: str = "store",
                 accelerator: str = "auto",
                 consistency_models=("strict-serializable",)) -> dict:
    """Re-checks a STORED run's list-append history, preferring the
    ``elle_*`` columns in its history.npz sidecar — a pure array
    pipeline with no jsonl parse and no PyObject history (the
    struct-of-arrays re-check SURVEY §7 calls for; at 50k txns the
    stored-column path runs ~7x the object parse). Falls back to the
    jsonl history when the sidecar predates the columns or a finding
    needs to cite txn objects (anomalous histories)."""
    from jepsen_tpu import store
    from jepsen_tpu.elle import columnar

    try:
        cols = store.load_elle_columns(test_name, timestamp, store_dir)
    except Exception as e:  # noqa: BLE001 - any sidecar damage (missing,
        #              truncated zip, wrong keys) means: use the jsonl
        store.note_sidecar_load_failure(
            f"{test_name}/{timestamp} (elle_*)", e)
        cols = None
    if cols is not None:
        try:
            return columnar.check_columns(
                cols, consistency_models=consistency_models,
                accelerator=accelerator)
        except columnar.NeedsObjects:
            pass
    history = store.load_history(test_name, timestamp, store_dir)
    return check(history, accelerator=accelerator,
                 consistency_models=consistency_models)


def check(history: list[dict], accelerator: str = "auto",
          consistency_models=("strict-serializable",), ir=None) -> dict:
    # Production path: the vectorized columnar builder (elle.columnar)
    # covers integer-valued histories — the universal workload shape —
    # and feeds the φ-cluster cycle path. The cpu oracle keeps the
    # Python builder below; differential tests pin the two together.
    # With an ``ir`` (the run's shared history IR) the build product is
    # the memoized elle_build view: encode once per run.
    if accelerator != "cpu":
        from jepsen_tpu.elle import columnar
        parts = None
        if ir is not None:
            from jepsen_tpu.history_ir import views
            parts = views.elle_build(ir)
        r = (columnar.check_columnar(history, consistency_models,
                                     accelerator, parts=parts)
             if parts is not None or ir is None else None)
        if r is not None:
            return r
    # ok txns participate in the graph; failed txns matter for G1a;
    # info (indeterminate) txns' writes may be observed — treated like ok
    # when they are (elle does the same: info writes that appear are real)
    if ir is not None:
        from jepsen_tpu.history_ir import views
        oks, fails, infos = views.txn_nodes(ir)
    else:
        oks = [op for op in history if op.get("type") == "ok"
               and isinstance(op.get("process"), int)]
        fails = [op for op in history if op.get("type") == "fail"]
        infos = [op for op in history if op.get("type") == "info"
                 and isinstance(op.get("process"), int)]

    txns = oks + infos  # graph nodes; info txns included if observed
    txn_index = {id(op): i for i, op in enumerate(txns)}
    n = len(txns)

    anomalies_extra: dict[str, list] = defaultdict(list)

    # ---- writer maps ----------------------------------------------------
    writer_of: dict[tuple, tuple[int, int, int]] = {}  # (k,v) -> (txn, mop_i, nth-append-of-key-in-txn)
    appends_per_txn_key: dict[tuple[int, Any], list] = defaultdict(list)
    failed_writes: dict[tuple, dict] = {}
    for op in fails:
        for m in op.get("value") or []:
            if m[0] == "append":
                failed_writes[(_hk(m[1]), m[2])] = op
    for i, op in enumerate(txns):
        for mi, m in enumerate(op.get("value") or []):
            if m[0] == "append":
                key = (_hk(m[1]), m[2])
                if key in writer_of:
                    anomalies_extra["duplicate-appends"].append(
                        {"key": m[1], "value": m[2]})
                    continue
                writer_of[key] = (i, mi, len(appends_per_txn_key[(i, _hk(m[1]))]))
                appends_per_txn_key[(i, _hk(m[1]))].append(m[2])

    # ---- version orders from reads -------------------------------------
    # longest read per key is the spine; every other read must be a prefix
    reads_by_key: dict[Any, list[tuple[int, list]]] = defaultdict(list)
    for i, op in enumerate(txns):
        if op.get("type") != "ok":
            continue  # info txns' reads are unreliable
        for m in op.get("value") or []:
            if m[0] == "r" and m[2] is not None:
                reads_by_key[_hk(m[1])].append((i, list(m[2])))

    # multi-append writers are the only possible G1b sources: a
    # single-append writer is always either fully observed or absent
    multi_by_key: dict[Any, set] = defaultdict(set)
    for (wi, kk), ap in appends_per_txn_key.items():
        if len(ap) > 1:
            multi_by_key[kk].add(wi)

    # per-key writer/failed-value columns, built once (not per key-scan)
    wv_by_key: dict[Any, list] = defaultdict(list)
    for (kk, v), wi in writer_of.items():
        wv_by_key[kk].append((v, wi[0]))
    fails_by_key: dict[Any, list] = defaultdict(list)
    for (kk, v) in failed_writes:
        fails_by_key[kk].append(v)

    version_order: dict[Any, list] = {}
    scan_counts = {"columnar": 0, "python": 0}
    for k, reads in reads_by_key.items():
        longest = max(reads, key=lambda t: len(t[1]))[1]
        version_order[k] = longest
        if _scan_reads_fast(k, reads, longest, txns, writer_of,
                            failed_writes, appends_per_txn_key,
                            multi_by_key.get(k, set()), anomalies_extra,
                            wv_by_key.get(k, []),
                            fails_by_key.get(k, [])):
            scan_counts["columnar"] += 1
        else:  # counted: a silently-falling-back fast path would make a
            #    multi-x perf regression invisible in identical results
            scan_counts["python"] += 1
            _scan_reads_py(k, reads, longest, txns, writer_of, failed_writes,
                           appends_per_txn_key, anomalies_extra)

    # internal: a txn's own read must reflect its earlier appends
    for i, op in enumerate(txns):
        seen_appends: dict[Any, list] = defaultdict(list)
        for m in op.get("value") or []:
            k = _hk(m[1])
            if m[0] == "append":
                seen_appends[k].append(m[2])
            elif m[0] == "r" and m[2] is not None:
                mine = seen_appends[k]
                if mine and list(m[2])[-len(mine):] != mine:
                    anomalies_extra["internal"].append(
                        {"key": m[1], "read": list(m[2]),
                         "expected-suffix": list(mine)})

    # ---- dependency edges ----------------------------------------------
    graph = Graph(n)
    for k, order in version_order.items():
        # ww: consecutive versions; also the unread appends that follow the
        # longest read can't be ordered — elle only orders observed versions
        writers = [writer_of.get((k, v), (None,))[0] for v in order]
        for a, b in zip(writers, writers[1:]):
            if a is not None and b is not None and a != b:
                graph.add(a, b, WW)
        for i, r in reads_by_key[k]:
            if r:
                w = writer_of.get((k, r[-1]))
                if w is not None and w[0] != i:
                    graph.add(w[0], i, WR)  # i read w's final state
            # rw: the version after the one i observed (for an empty read,
            # index 0 — the first version's writer)
            nxt_idx = len(r)
            if nxt_idx < len(order):
                w = writer_of.get((k, order[nxt_idx]))
                if w is not None and w[0] != i:
                    graph.add(i, w[0], RW)

    # realtime (invoke/complete interval order) + per-process succession
    # edges: close the strict-serializable / sequential anomaly surface
    elle.add_timing_edges(graph, history, txns)

    cyc = elle.check_cycles(graph, accelerator=accelerator)
    # drop informational-only extras from validity
    extras = {k: v for k, v in anomalies_extra.items()
              if k != "unobserved-writer"}
    result = elle.result_map(cyc, txns, extras,
                             consistency_models=consistency_models)
    result["txn-count"] = n
    result["edge-count"] = len(graph.edges)
    result["read-scan-keys"] = scan_counts
    return result


# ---------------------------------------------------------------------------
# Generator (append.clj gen / elle.list-append/gen)
# ---------------------------------------------------------------------------

def gen(key_count: int = 3, min_txn_length: int = 1, max_txn_length: int = 4,
        max_writes_per_key: int = 256):
    """Generates random list-append txns over a rotating key pool."""
    counters: dict = defaultdict(int)
    active_keys: list = list(range(key_count))
    next_key: list = [key_count]

    def one_txn(test, ctx):
        txn = []
        length = ctx.rng.randint(min_txn_length, max_txn_length)
        for _ in range(length):
            idx = ctx.rng.randrange(len(active_keys))
            k = active_keys[idx]
            if ctx.rng.random() < 0.5:
                txn.append(["r", k, None])
            else:
                counters[k] += 1
                if counters[k] > max_writes_per_key:
                    # retire the key, open a fresh one in its slot
                    k = active_keys[idx] = next_key[0]
                    next_key[0] += 1
                    counters[k] += 1
                txn.append(["append", k, counters[k]])
        return {"f": "txn", "value": txn}

    return one_txn
