"""Elle anomaly artifacts: human-readable per-anomaly files in the store.

The reference wires elle's output directory into every txn test
(jepsen/src/jepsen/tests/cycle/append.clj:17-22 passes
``:directory (store/path! test ... "elle")``), and elle writes one file
per anomaly type there with cycle explanations a human can read without
parsing the results map. This module is that surface for the repo's
checkers: :func:`write_artifacts` takes a checker result (the
elle.result_map shape — ``anomalies`` holding rendered cycles or extra
findings) and writes ``<type>.txt`` files plus an ``index.txt`` summary
into the run's ``elle/`` directory. The web UI's run page links the
directory when it exists (web.py).

Explanations are in OP terms: each cycle step shows the txn's mops and
spells out what the edge type means (who wrote/read what before whom);
non-cycle findings (G1a, internal, ...) render their structured fields
with the same one-line gloss.
"""
from __future__ import annotations

import json
import logging
from pathlib import Path

logger = logging.getLogger("jepsen.elle.artifacts")

# one-paragraph gloss per anomaly type, written at the top of its file
ANOMALY_DOC = {
    "G0": "Write cycle: a cycle of write-write dependencies alone — two "
          "transactions each overwrote the other's write. Violates "
          "read-uncommitted.",
    "G1a": "Aborted read: a transaction observed a value written by a "
           "transaction that FAILED. Violates read-committed.",
    "G1b": "Intermediate read: a transaction observed a non-final write "
           "of another transaction. Violates read-committed.",
    "G1c": "Cyclic information flow: a cycle of write-write and "
           "write-read dependencies — information flowed in a loop. "
           "Violates read-committed.",
    "G-single": "Read skew: a dependency cycle with exactly one "
                "anti-dependency (read-write) edge. Violates "
                "snapshot isolation.",
    "G2": "Anti-dependency cycle: a dependency cycle with two or more "
          "anti-dependency edges. Violates serializability.",
    "internal": "Internal inconsistency: a transaction's own read "
                "contradicts its earlier operations in the same "
                "transaction.",
    "realtime-cycle": "Realtime cycle: a dependency cycle closed by a "
                      "realtime precedence edge (one transaction "
                      "completed before the other was invoked). "
                      "Violates strict serializability.",
    "process-cycle": "Process cycle: a dependency cycle closed by a "
                     "same-process succession edge. Violates sequential "
                     "consistency.",
    "duplicate-appends": "The same value was appended to a key more "
                         "than once.",
    "cyclic-versions": "The per-key version order derived from reads is "
                       "cyclic.",
    "unobserved-writer": "A read observed a value no known transaction "
                         "wrote (informational).",
}

_EDGE_GLOSS = {
    "ww": "wrote the key before",      # version order
    "wr": "wrote a value read by",     # information flow
    "rw": "read a state overwritten by",  # anti-dependency
    "realtime": "completed before (in real time)",
    "process": "preceded (same process) ",
}


def _fmt_txn(value) -> str:
    """One txn's mops, compactly: [append 5 1, r 5 [1]]."""
    if not isinstance(value, (list, tuple)):
        return json.dumps(value, default=str)
    mops = []
    for m in value:
        if isinstance(m, (list, tuple)):
            mops.append(" ".join(json.dumps(x, default=str) if not
                                 isinstance(x, str) else x for x in m))
        else:
            mops.append(json.dumps(m, default=str))
    return "[" + ", ".join(mops) + "]"


def _render_cycle(cycle: list) -> list[str]:
    """Lines for one rendered cycle ([{from, type, to}] — the
    elle.render_cycle shape)."""
    lines = []
    for step in cycle:
        t = step.get("type")
        gloss = _EDGE_GLOSS.get(t, "depends-on")
        lines.append(f"  {_fmt_txn(step.get('from'))}")
        lines.append(f"    --{t}--> ({gloss})")
    if cycle:
        # close the loop visually: the last edge's target is the first
        # txn again
        lines.append(f"  {_fmt_txn(cycle[-1].get('to'))}")
    return lines


def _render_finding(finding) -> list[str]:
    """Lines for one anomaly instance: a cycle (list of edge dicts) or
    a structured extra finding (plain dict)."""
    if isinstance(finding, list) and finding and \
            isinstance(finding[0], dict) and "type" in finding[0]:
        return _render_cycle(finding)
    return ["  " + json.dumps(finding, default=str)]


def write_artifacts(dirpath, result: dict) -> list[str]:
    """Writes one ``<anomaly-type>.txt`` per anomaly in ``result`` (the
    checker result map) plus an ``index.txt`` summary into ``dirpath``.
    Returns the filenames written (empty when the result has no
    anomalies). Never raises — artifact writing must not mask a
    verdict."""
    anomalies = result.get("anomalies") or {}
    if not anomalies:
        return []
    written: list[str] = []
    try:
        d = Path(dirpath)
        d.mkdir(parents=True, exist_ok=True)
        for name, findings in sorted(anomalies.items()):
            if not findings:
                continue
            lines = [f"{name}", "=" * len(name), ""]
            doc = ANOMALY_DOC.get(name)
            if doc:
                lines += [doc, ""]
            items = findings if isinstance(findings, list) else [findings]
            for i, finding in enumerate(items):
                lines.append(f"#{i + 1}:")
                lines += _render_finding(finding)
                lines.append("")
            fn = f"{name}.txt"
            (d / fn).write_text("\n".join(lines))
            written.append(fn)
        idx = ["Elle anomaly artifacts", "", f"valid?: {result.get('valid?')}",
               f"anomaly types: {', '.join(sorted(anomalies))}", ""]
        idx += [f"- {fn}" for fn in written]
        (d / "index.txt").write_text("\n".join(idx) + "\n")
        written.append("index.txt")
    except Exception:  # noqa: BLE001 — artifacts are best-effort
        logger.exception("elle artifact write failed at %s", dirpath)
    return written


def cited_op_indices(result: dict, history: list[dict]) -> list[int]:
    """History indices of every completion whose txn value a cycle or
    extra finding cites — the op set an anomaly's explanation is *about*
    (the witness the cycle renderings imply). Best-effort value
    matching, like the live first-anomaly surface."""
    cited: list = []
    for findings in (result.get("anomalies") or {}).values():
        for item in findings if isinstance(findings, list) else ():
            for hop in item if isinstance(item, list) else ():
                if isinstance(hop, dict):
                    cited.extend([hop.get("from"), hop.get("to"),
                                  hop.get("read"), hop.get("read-txn"),
                                  hop.get("writer")])
    out = []
    for i, op in enumerate(history or []):
        if op.get("type") not in ("ok", "info"):
            continue
        v = op.get("value")
        if v is None:
            continue
        if any(c is not None and c == v for c in cited):
            out.append(i)
    return out


def _write_witness_timeline(dirpath, test, result: dict,
                            history: list[dict]) -> str | None:
    """The cycle explanations' witness-window timeline + fault overlay
    (doc/observability.md "Anomaly forensics"): the cited txns rendered
    as a per-process gantt with the run's durable fault windows
    overlaid, next to the per-anomaly text files. Returns the filename
    written, or None."""
    indices = cited_op_indices(result, history)
    if not indices:
        return None
    from jepsen_tpu import store
    from jepsen_tpu.checker import explain as explain_mod
    from jepsen_tpu.checker import timeline
    from jepsen_tpu.nemesis import faults as faults_mod
    # cycles cite completions; the timeline draws invoke..completion
    # pairs, so hand the invokes over too (compose_anomaly enriches)
    forensics = {
        "first_anomaly": {"op_index": min(indices)},
        "backend": "elle",
        "bisect_steps": 0,
        "witness": {"op_indices": indices, "context_op_indices": []},
    }
    rows = faults_mod.load_rows(store.path(test, faults_mod.FAULTS_NAME)) \
        if test else []
    payload = explain_mod.compose_anomaly(history, forensics,
                                          registry_rows=rows)
    html = timeline.render_witness(test or {}, history, payload)
    fn = "witness-timeline.html"
    (Path(dirpath) / fn).write_text(html)
    return fn


def write_for_test(test, result: dict, opts: dict | None = None,
                   history: list[dict] | None = None) -> None:
    """Writes the artifacts into ``store/<test>/<ts>/[subdir/]elle/``
    when the result is invalid and the test map can address a store
    directory. The ``subdirectory`` opt (independent's per-key lift)
    nests the artifacts the same way other per-key artifacts nest.
    With ``history``, the cycle explanations additionally get a
    witness-window timeline with the durable fault-window overlay."""
    if not test or result.get("valid?") is True:
        return
    if not result.get("anomalies"):
        return
    try:
        from jepsen_tpu import store
        parts = [p for p in [(opts or {}).get("subdirectory"), "elle"] if p]
        d = store.path_mk(test, *parts)
        written = write_artifacts(d, result)
        if history:
            try:
                fn = _write_witness_timeline(d, test, result, history)
                if fn and written:
                    with open(Path(d) / "index.txt", "a") as f:
                        f.write(f"- {fn}\n")
            except Exception:  # noqa: BLE001 — the timeline is additive
                logger.exception("elle witness timeline failed")
    except Exception:  # noqa: BLE001
        logger.exception("elle artifact store write failed")
