"""Read-write-register workload checker (capability-equivalent to
elle.rw-register, invoked from the reference at
jepsen/src/jepsen/tests/cycle/wr.clj).

Txns are lists of ``["w", k, v]`` / ``["r", k, v]`` micro-ops with writes
unique per key (wr.clj:31-45 documents the anomaly surface: G0/G1a/G1b/
G1c/G-single/G2/internal). Unlike list-append, version order is not
directly observable; we infer it from:

* wr edges: reader of v depends on the (unique) writer of v.
* ww edges within a txn's own trace: if a txn reads v then writes v', then
  writer(v) ww-precedes this txn for that key.
* rw anti-dependencies through the trace-derived version-succession map
  (reader of v precedes writers of v's known successors) — longer
  write-follows-read chains compose through the txn graph, since each
  version-graph edge contributes its own ww/rw edges.
* initial-state ordering: a read of the initial None (on a key where no
  txn ever wrote a literal None) proves the reader serialized before
  EVERY writer of that key — a register never returns to its initial
  state — yielding rw edges to all writers, across processes.
* the per-key version graph itself is checked for cycles: a succession
  loop (v overwritten by v', v' overwritten by ... v) is impossible for
  uniquely-written registers and reported as ``cyclic-versions``.

All of these under-approximate elle's full inference soundly (never
false positives — fuzz-verified against a brute-force serializability
oracle).
"""
from __future__ import annotations

from collections import defaultdict

from jepsen_tpu import elle
from jepsen_tpu.elle import RW, WR, WW, Graph
from jepsen_tpu.txn import _hk, int_write_mops


def check(history: list[dict], accelerator: str = "auto",
          consistency_models=("strict-serializable",), ir=None) -> dict:
    # the ok/fail/info node split comes from the run's shared history
    # IR when one is attached (memoized txn_nodes view — the same split
    # the list-append checker starts from), else inline
    if ir is not None:
        from jepsen_tpu.history_ir import views
        oks, fails, infos = views.txn_nodes(ir)
    else:
        oks = [op for op in history if op.get("type") == "ok"
               and isinstance(op.get("process"), int)]
        fails = [op for op in history if op.get("type") == "fail"]
        infos = [op for op in history if op.get("type") == "info"
                 and isinstance(op.get("process"), int)]
    txns = oks + infos
    n = len(txns)

    anomalies_extra: dict[str, list] = defaultdict(list)

    writer_of: dict[tuple, int] = {}
    failed_writes: dict[tuple, dict] = {}
    intermediate_writes: dict[tuple, int] = {}
    for op in fails:
        for m in op.get("value") or []:
            if m[0] == "w":
                failed_writes[(_hk(m[1]), m[2])] = op
    for i, op in enumerate(txns):
        for m in op.get("value") or []:
            if m[0] == "w":
                key = (_hk(m[1]), m[2])
                if key in writer_of:
                    anomalies_extra["duplicate-writes"].append(
                        {"key": m[1], "value": m[2]})
                writer_of[key] = i
        if op.get("type") == "ok":
            for f, k, v in int_write_mops(op.get("value") or []):
                intermediate_writes[(_hk(k), v)] = i

    # writers per key, for the single-write init-read inference below
    key_writers: dict = defaultdict(set)
    for (k, _v), w in writer_of.items():
        key_writers[k].add(w)

    graph = Graph(n)
    # One pass per txn builds: wr edges (reads of known writes), trace ww
    # edges and value-level succession (txn read v then wrote v' for the
    # same key => writer(v) precedes this txn), G1a, and internal checks.
    # The initial state None is a first-class version: a None-read then
    # write traces the succession init -> first value.
    _MISSING = object()
    succ: dict[tuple, set[int]] = defaultdict(set)
    vedges: dict = defaultdict(set)   # hk -> {(prev_value, new_value)}
    key_repr: dict = {}               # hk -> a representative original key
    for i, op in enumerate(txns):
        if op.get("type") != "ok":
            continue
        last_read: dict = {}
        written: dict = {}
        for m in op.get("value") or []:
            k = _hk(m[1])
            if m[0] == "r":
                v = m[2]
                if k in written and v != written[k]:
                    # internal: read contradicts own earlier write
                    anomalies_extra["internal"].append(
                        {"key": m[1], "expected": written[k], "got": v})
                if v is not None:
                    if (k, v) in failed_writes:
                        anomalies_extra["G1a"].append(
                            {"key": m[1], "value": v,
                             "read-txn": op.get("value")})
                    iw = intermediate_writes.get((k, v))
                    if iw is not None and iw != i:
                        # G1b: v was overwritten within its own txn — only
                        # an intermediate state could have exposed it
                        anomalies_extra["G1b"].append(
                            {"key": m[1], "value": v,
                             "writer": txns[iw].get("value")})
                    w = writer_of.get((k, v))
                    if w is not None and w != i:
                        graph.add(w, i, WR)
                last_read[k] = v
            elif m[0] == "w":
                prev = last_read.get(k, _MISSING)
                if prev is not _MISSING:
                    # prev None traces init -> m[2], but only when None
                    # is really the init state (never a written value)
                    if prev is not None or (k, None) not in writer_of:
                        succ[(k, prev)].add(i)
                        key_repr.setdefault(k, m[1])
                        vedges[k].add((prev, m[2]))
                    if prev is not None:
                        w = writer_of.get((k, prev))
                        if w is not None and w != i:
                            graph.add(w, i, WW)
                last_read[k] = m[2]
                written[k] = m[2]

    # the trace-derived version graph must be acyclic: versions of a
    # uniquely-written register install in one linear order, so a
    # succession loop can't come from any real execution (elle's
    # cyclic-version-order anomaly)
    for k, edges in vedges.items():
        cyc = _version_cycle(edges)
        if cyc is not None:
            anomalies_extra["cyclic-versions"].append(
                {"key": key_repr.get(k, k), "versions": cyc})

    # rw anti-dependencies: i read version v of k; known successor writers
    # (from the succession map) anti-depend on i. A read of the initial
    # state (None) additionally anti-depends on the key's writer when the
    # key has exactly ONE writing txn — init's immediate successor is then
    # unambiguous (elle's nil-version inference).
    for i, op in enumerate(txns):
        if op.get("type") != "ok":
            continue
        for m in op.get("value") or []:
            if m[0] != "r":
                continue
            k, v = _hk(m[1]), m[2]
            for w in succ.get((k, v), ()):
                if w != i:
                    graph.add(i, w, RW)
            if v is None and (k, None) not in writer_of:
                # a None read is the INITIAL state only if no txn ever
                # wrote a literal None to this key — and the register
                # never returns to it, so the reader serialized before
                # EVERY writer of the key, whichever installed first
                for w in key_writers.get(k, ()):
                    if w != i:
                        graph.add(i, w, RW)

    # realtime (invoke/complete interval order) + per-process succession
    # edges: close the strict-serializable / sequential anomaly surface
    elle.add_timing_edges(graph, history, txns)

    cyc = elle.check_cycles(graph, accelerator=accelerator)
    result = elle.result_map(cyc, txns, anomalies_extra,
                             consistency_models=consistency_models)
    result["txn-count"] = n
    result["edge-count"] = len(graph.edges)
    return result


def _version_cycle(edges: set) -> list | None:
    """A cyclic strongly-connected component of one key's
    version-succession graph (its member values), or None. Reuses the
    exact host Tarjan from ops.scc over interned values; self-loops
    (read v, rewrote v) are duplicate-writes, already reported
    separately — skipped here."""
    from jepsen_tpu.ops.scc import tarjan_scc

    ids: dict = {}
    for a, b in edges:
        for v in (a, b):
            if v not in ids:
                ids[v] = len(ids)
    int_edges = [(ids[a], ids[b]) for a, b in edges if a != b]
    sccs = tarjan_scc(len(ids), int_edges)
    if not sccs:
        return None
    values = list(ids)
    return [values[i] for i in sccs[0]]


def gen(key_count: int = 5, min_txn_length: int = 1, max_txn_length: int = 4):
    """Random rw-register txns; writes unique per key."""
    from collections import defaultdict as dd
    counters: dict = dd(int)

    def one_txn(test, ctx):
        txn = []
        for _ in range(ctx.rng.randint(min_txn_length, max_txn_length)):
            k = ctx.rng.randrange(key_count)
            if ctx.rng.random() < 0.5:
                txn.append(["r", k, None])
            else:
                counters[k] += 1
                txn.append(["w", k, counters[k]])
        return {"f": "txn", "value": txn}

    return one_txn
