"""Transactional-anomaly detection (capability-equivalent to Elle, the
reference's txn checker — invoked from jepsen/src/jepsen/tests/cycle*.clj).

Builds ww/wr/rw dependency graphs from txn histories, detects cycles with
the device trimming kernel (jepsen_tpu.ops.scc), and classifies anomalies
with Adya's taxonomy:

* G0 (write cycle): cycle of only ww edges
* G1a (aborted read): observed a failed txn's write
* G1b (intermediate read): observed a non-final write of a txn
* G1c (cyclic information flow): cycle of ww+wr edges
* G-single (read skew): cycle with exactly one rw anti-dependency
* G2 (anti-dependency cycle): cycle with >= 2 rw edges
* internal: a txn's reads contradict its own earlier ops
* realtime-cycle: dependency cycle closed by a realtime precedence edge
  (txn A completed before txn B was invoked) — strict-serializability only
* process-cycle: dependency cycle closed by a same-process succession
  edge — sequential consistency and stronger
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

import numpy as np

WW, WR, RW = "ww", "wr", "rw"
REALTIME, PROCESS = "realtime", "process"

# anomaly -> weakest consistency model it violates (loosely following
# elle's anomaly/model mapping)
ANOMALY_SEVERITY = {
    "G0": "read-uncommitted",
    "cyclic-versions": "read-uncommitted",
    "G1a": "read-committed",
    "G1b": "read-committed",
    "G1c": "read-committed",
    "internal": "read-atomic",
    "duplicate-elements": "read-atomic",
    "incompatible-order": "read-atomic",
    "G-single": "snapshot-isolation",
    "G2": "serializable",
    "process-cycle": "sequential",
    "realtime-cycle": "strict-serializable",
}

SERIALIZABLE_BLOCKERS = {"G0", "G1a", "G1b", "G1c", "G-single", "G2",
                         "internal", "duplicate-elements",
                         "incompatible-order"}

# anomalies proscribed by each consistency model (Adya's hierarchy, the
# shape of elle's consistency-model option)
_RU = {"G0", "duplicate-elements", "incompatible-order", "duplicate-appends",
       "duplicate-writes", "cyclic-versions"}
_RC = _RU | {"G1a", "G1b", "G1c", "internal"}
MODEL_ANOMALIES = {
    "read-uncommitted": _RU,
    "read-committed": _RC,
    "read-atomic": _RC,
    "repeatable-read": _RC | {"G-single"},
    "snapshot-isolation": _RC | {"G-single"},
    "serializable": _RC | {"G-single", "G2"},
    "sequential": _RC | {"G-single", "G2", "process-cycle"},
    "strict-serializable": _RC | {"G-single", "G2", "realtime-cycle",
                                  "process-cycle"},
}


def blocked_anomalies(consistency_models) -> set:
    out: set = set()
    for m in consistency_models or ("strict-serializable",):
        out |= MODEL_ANOMALIES.get(m, SERIALIZABLE_BLOCKERS)
    return out


@dataclass
class Graph:
    """Typed edge-list dependency graph over txn indices.

    Two storage forms: ``edges`` (list of (src, dst, type) tuples — the
    incremental builder API) or ``cols`` (columnar int64 arrays
    (type-codes, src, dst) — what the vectorized builder in
    elle.columnar produces). ``edge_list()`` materializes tuples from
    columns on demand so every consumer works with either form."""

    n: int
    edges: list = field(default_factory=list)  # (src, dst, type)
    # per-node history position (invocation when known), filled by
    # add_timing_edges; None when unavailable or per-process
    # sequentiality was violated
    time_order: np.ndarray | None = None
    cols: tuple | None = None  # (codes, src, dst) int64 arrays

    def add(self, src: int, dst: int, typ: str):
        if src != dst or typ == RW:
            self.edges.append((src, dst, typ))

    def edge_list(self) -> list:
        if self.cols is not None and not self.edges:
            codes, src, dst = self.cols
            self.edges = [(int(s), int(d), _CODE_TYPE[int(c)])
                          for c, s, d in zip(codes.tolist(), src.tolist(),
                                             dst.tolist())]
        return self.edges

    def arrays(self, types: set | None = None):
        if self.cols is not None and not self.edges:
            codes, src, dst = self.cols
            if types is None:
                keep = np.ones(len(codes), bool)
            else:
                tcodes = [_TYPE_CODE[t] for t in types]
                keep = np.isin(codes, tcodes)
            return src[keep].astype(np.int32), dst[keep].astype(np.int32)
        es = [(s, d) for s, d, t in self.edges
              if types is None or t in types]
        if not es:
            return np.zeros(0, np.int32), np.zeros(0, np.int32)
        a = np.asarray(es, dtype=np.int32)
        return a[:, 0], a[:, 1]

    def edge_count(self) -> int:
        return len(self.cols[0]) if (self.cols is not None
                                     and not self.edges) else len(self.edges)


def add_timing_edges(graph: Graph, history: list, txns: list,
                     realtime: bool = True, process: bool = True) -> None:
    """Adds realtime and process precedence edges to a dependency graph
    (the reference's strict-serializability surface: elle's realtime /
    process graphs behind jepsen/src/jepsen/tests/cycle/wr.clj:31-45).

    *Realtime*: txn A precedes txn B when A's completion appears before
    B's invocation in history order. Rather than the O(n^2) full order we
    add its transitive reduction with the frontier construction: a
    completed txn stays in the frontier until some later txn both invoked
    after it completed and has itself completed (dominating it), so every
    invocation links only from the O(concurrency) non-dominated txns and
    the closure of the added edges equals the full realtime order.
    Requires invocation events in the history; completion-only histories
    get no realtime edges (their intervals are unknown).

    *Process*: consecutive committed txns of one process, in history
    order — sound even for completion-only histories because a process is
    sequential by construction (the interpreter renumbers crashed
    processes rather than reusing them).

    ``info`` (indeterminate) txns never complete, so they may *receive*
    timing edges from their invocation point but never enter the frontier.
    """
    node_of = {id(op): i for i, op in enumerate(txns)}
    pending: dict = {}          # process -> history position of open invoke
    last_by_process: dict = {}  # process -> (last completed node, its pos)
    events: list = []           # (pos, 0=invoke|1=complete, node, invoke_pos)
    # Per-node event position (invocation when known, else completion):
    # every timing edge strictly increases it, so check_cycles can screen
    # the timing stages with a potential argument (see there). A history
    # that violates per-process sequentiality voids the screen.
    order = np.full(graph.n, -1, np.int64)
    sequential_ok = True
    for pos, op in enumerate(history):
        t = op.get("type")
        p = op.get("process")
        if t == "invoke":
            pending[p] = pos
            continue
        if t not in ("ok", "fail", "info"):
            continue
        inv = pending.pop(p, None)
        node = node_of.get(id(op))
        if node is None:
            continue
        order[node] = pos if inv is None else inv
        if process and isinstance(p, int):
            prev = last_by_process.get(p)
            if prev is not None:
                graph.add(prev[0], node, PROCESS)
                if inv is not None and inv < prev[1]:
                    sequential_ok = False  # overlapping ops in one process
            last_by_process[p] = (node, pos)
        if realtime and inv is not None:
            events.append((inv, 0, node, inv))
            if t != "info":
                events.append((pos, 1, node, inv))
    events.sort()
    frontier: list = []  # (complete_pos, node), none dominated
    for pos, kind, node, inv in events:
        if kind == 0:
            for _c, a in frontier:
                graph.add(a, node, REALTIME)
        else:
            frontier = [(c, a) for c, a in frontier if c >= inv]
            frontier.append((pos, node))
    graph.time_order = order if sequential_ok else None


# below this many edges, "auto" trims on host (see residue() in
# _check_cycles_global); measured crossover on one chip with
# tunnel-attached dispatch — the device trim amortizes only on big graphs
TRIM_DEVICE_MIN_EDGES = 500_000

# φ-interval clusters larger than this fall back to the trim + global
# Tarjan pipeline for that cluster: a [V, V] dense closure beyond it
# stops paying for itself on one chip (and 1024² bf16 is still <3 MB)
MATRIX_CLUSTER_MAX = 1024

# under "auto" with no explicit device request, clusters are settled by
# host Tarjan directly unless the batched matrix work is at least this
# many elements (B·V²) — tunnel dispatch costs ~10 ms either way
SCREEN_DEVICE_MIN_ELEMS = 1 << 16


def check_cycles(graph: Graph, accelerator: str = "auto") -> dict:
    """Finds and classifies cycles (the structure of elle.core/check with
    typed searches, jepsen/src/jepsen/tests/cycle.clj).

    Production path (``auto``/``tpu``) is φ-interval localization:
    add_timing_edges records each node's event position φ, and all timing
    edges strictly increase φ by construction, so **every cycle must
    traverse a φ-decreasing dependency edge** ("back edge"). Forward
    paths visit φ-monotone node intervals, so every cycle — and therefore
    every SCC — lies entirely inside the merged φ-interval hull of its
    back edges (proof in _phi_clusters). Back-edge detection and interval
    merging are O(E) vectorized; each cluster is then settled EXACTLY by
    the batched [B, V, V] matrix-closure screen on device
    (ops.scc.batch_cluster_screen — one dispatch for all clusters) and
    flagged clusters get the exact typed classification on their few
    nodes. No trim, no full-graph Tarjan, and the two timing stages ride
    the same clusters.

    ``cpu`` keeps the trim + global-Tarjan pipeline unchanged — it is the
    auditable oracle twin the differential tests pin the fast path to.
    Histories without a usable φ (no invocations recorded, or per-process
    sequentiality violated) fall back to that pipeline too."""
    if accelerator == "cpu":
        return _check_cycles_global(graph, accelerator)

    codes, src, dst, order = _edge_columns(graph)
    if order is None:
        return _check_cycles_global(graph, accelerator)

    dep_mask = codes <= 2
    o_s, o_d = order[src], order[dst]
    if ((o_s < 0) | (o_d < 0)).any():
        # a node never matched to a history event: φ is unusable
        return _check_cycles_global(graph, accelerator)
    back = dep_mask & (o_d <= o_s)
    if not back.any():
        return {}  # all dependency edges increase φ: acyclic in every stage

    clusters = _phi_clusters(order[src[back]], order[dst[back]])
    return _check_cycles_clusters(codes, src, dst, order, clusters,
                                  accelerator)


_TYPE_CODE = {WW: 0, WR: 1, RW: 2, REALTIME: 3, PROCESS: 4}
_CODE_TYPE = {v: k for k, v in _TYPE_CODE.items()}


def _edge_columns(graph: Graph):
    """Columnar (type-code, src, dst, φ) view of the graph, built once
    (free when the columnar builder already produced ``cols``)."""
    if graph.time_order is None:
        return None, None, None, None
    if graph.cols is not None and not graph.edges:
        codes, src, dst = graph.cols
        return codes, src, dst, graph.time_order
    if not graph.edges:
        return None, None, None, None
    arr = np.asarray([(_TYPE_CODE[t], s, d) for s, d, t in graph.edges],
                     np.int64)
    return arr[:, 0], arr[:, 1], arr[:, 2], graph.time_order


def _phi_clusters(back_src_phi: np.ndarray, back_dst_phi: np.ndarray):
    """Merges back-edge φ-intervals into disjoint clusters [(lo, hi), ...].

    Soundness: a cycle alternates back edges with (possibly empty)
    forward paths. A forward path from a to b climbs φ monotonically, so
    its nodes lie in [φ(a), φ(b)]; hence every node of the cycle lies in
    the union of its back edges' intervals [φ(dst), φ(src)]. Consecutive
    intervals around the cycle overlap (the forward path from one back
    edge's dst ends at the next one's src, so φ(dst_i) <= φ(src_{i+1})
    and disjointness would contradict it), so the whole cycle sits inside
    ONE merged cluster. Clusters are therefore an exact localization: all
    cycles (and all nontrivial SCCs) of every stage's edge set live
    inside them, and none spans two."""
    lo = np.minimum(back_dst_phi, back_src_phi)
    hi = np.maximum(back_dst_phi, back_src_phi)
    order = np.argsort(lo, kind="stable")
    lo, hi = lo[order], hi[order]
    out = []
    cur_lo, cur_hi = int(lo[0]), int(hi[0])
    for l, h in zip(lo[1:].tolist(), hi[1:].tolist()):
        if l <= cur_hi:
            cur_hi = max(cur_hi, h)
        else:
            out.append((cur_lo, cur_hi))
            cur_lo, cur_hi = l, h
    out.append((cur_lo, cur_hi))
    return out


def _check_cycles_clusters(codes, src, dst, order, clusters,
                           accelerator: str) -> dict:
    """Classifies anomalies cluster by cluster. Every edge (any type) with
    both endpoint φs inside a cluster's interval joins that cluster's
    subgraph; the device screen proves most clusters acyclic in a few
    batched dispatches and the exact typed searches run only on the rest.

    Clusters are remapped to dense local ids ONCE, then grouped into
    size buckets for the screen — so a thousand 4-node clusters never
    pay a single big cluster's [V, V] matrix footprint."""
    from jepsen_tpu.ops import scc as scc_mod
    from jepsen_tpu.ops.jitlin import _bucket

    los = np.asarray([c[0] for c in clusters], np.int64)
    his = np.asarray([c[1] for c in clusters], np.int64)
    o_s, o_d = order[src], order[dst]
    # cluster id per edge (-1 = none): both endpoints inside one interval
    cid_s = np.searchsorted(los, o_s, side="right") - 1
    in_s = (cid_s >= 0) & (o_s <= his[np.clip(cid_s, 0, len(his) - 1)])
    cid_d = np.searchsorted(los, o_d, side="right") - 1
    in_d = (cid_d >= 0) & (o_d <= his[np.clip(cid_d, 0, len(his) - 1)])
    member = in_s & in_d & (cid_s == cid_d)
    e_cid = np.where(member, cid_s, -1)

    # pack per-cluster edge lists (global node ids), remap once apiece
    sel = np.nonzero(member)[0]
    sel = sel[np.argsort(e_cid[sel], kind="stable")]
    bounds = np.searchsorted(e_cid[sel], np.arange(len(clusters) + 1))
    remapped: list = []  # (n_local, local_edges, to_global) per cluster
    for c in range(len(clusters)):
        idx = sel[bounds[c]:bounds[c + 1]]
        edges = [(int(src[i]), int(dst[i]), _CODE_TYPE[int(codes[i])])
                 for i in idx.tolist()]
        remapped.append(_remap_full(edges) if edges else None)

    # group screenable clusters into size buckets so each screen call's
    # [B, V, V] footprint matches its clusters
    big: list = []
    buckets: dict[int, list] = {}
    for c, rm in enumerate(remapped):
        if rm is None:
            continue
        if rm[0] > MATRIX_CLUSTER_MAX:
            big.append(c)
            continue
        buckets.setdefault(_bucket(rm[0], floor=8), []).append(c)

    live: list = []
    for vb, members in sorted(buckets.items()):
        use_device = accelerator == "tpu" or (
            accelerator == "auto"
            and len(members) * vb * vb >= SCREEN_DEVICE_MIN_ELEMS)
        if use_device:
            packed_cid: list = []
            packed_src: list = []
            packed_dst: list = []
            for b, c in enumerate(members):
                for s, d, _ in remapped[c][1]:
                    packed_cid.append(b)
                    packed_src.append(s)
                    packed_dst.append(d)
            flags = scc_mod.batch_cluster_screen(
                np.asarray(packed_cid, np.int32),
                np.asarray(packed_src, np.int32),
                np.asarray(packed_dst, np.int32), len(members), vb)
            live += [c for c, f in zip(members, flags.tolist()) if f]
        else:
            # host screen: no nontrivial SCC means no cycles
            live += [c for c in members
                     if scc_mod.tarjan_scc(
                         remapped[c][0],
                         [(s, d) for s, d, _ in remapped[c][1]])]
    live += big  # oversized clusters go straight to the exact pass

    anomalies: dict[str, list] = {}
    for c in sorted(live):
        n_local, local_edges, to_global = remapped[c]
        _classify_stages(n_local, local_edges, to_global, anomalies)
    return anomalies


def _remap_full(edges):
    nodes = sorted({v for s, d, _ in edges for v in (s, d)})
    local = {g: i for i, g in enumerate(nodes)}
    return (len(nodes),
            [(local[s], local[d], t) for s, d, t in edges],
            nodes)


def _run_stages(n: int, dep_edges: list, all_edges: list, emit) -> None:
    """The typed anomaly stages, shared verbatim by the global pipeline
    and the per-cluster classifier (one copy so the two cannot
    desynchronize — the differential tests pin them together).

    * G0: ww-only cycles.
    * G1c: ww+wr cycles through at least one wr edge. When G0 exists the
      same SCC may hold both a pure-ww and a mixed cycle, so the search
      goes through each wr edge specifically to avoid shadowing.
    * G-single / G2: per-SCC fewest-rw cycle over the dependency edges
      (n_rw == 0 cycles were already reported as G0/G1c).
    * realtime / process: cycles forced through a timing edge. A strict
      serialization must respect realtime AND process order, so the
      realtime search walks paths through process edges too; the process
      search stays dep+process only — exactly the sequential-consistency
      question.

    ``dep_edges`` may be a trimmed superset (global path) or a cluster's
    dependency subset; ``all_edges`` additionally carries the timing
    edges for the timing stages."""
    from jepsen_tpu.ops import scc as scc_mod

    # G0: ww-only cycles
    ww_edges = [e for e in dep_edges if e[2] == WW]
    g0 = _exemplars(n, ww_edges) if ww_edges else []
    emit("G0", g0)

    # G1c: ww+wr cycles through at least one wr edge
    g1_edges = [e for e in dep_edges if e[2] in (WW, WR)]
    if g1_edges:
        if not g0:
            emit("G1c", _exemplars(n, g1_edges))
        else:
            emit("G1c", _cycles_through_type(n, g1_edges, WR))

    # dependency stage: G-single / G2 via per-SCC fewest-rw cycles
    if dep_edges:
        sccs = scc_mod.tarjan_scc(n, [(s, d) for s, d, _ in dep_edges])
        singles, g2s = [], []
        for scc in sccs:
            cycle = scc_mod.find_cycle_in_scc(scc, dep_edges,
                                              prefer_fewest=RW)
            if cycle is None:
                continue
            n_rw = sum(1 for _, _, t in cycle if t == RW)
            if n_rw == 1:
                singles.append(cycle)
            elif n_rw >= 2:
                g2s.append(cycle)
        emit("G-single", singles)
        emit("G2", g2s)

    # timing stages: cycles through a realtime/process edge
    for typ, path_types, name in (
            (REALTIME, (WW, WR, RW, REALTIME, PROCESS), "realtime-cycle"),
            (PROCESS, (WW, WR, RW, PROCESS), "process-cycle")):
        if not any(t == typ for _, _, t in all_edges):
            continue
        timed = [e for e in all_edges if e[2] in path_types]
        sccs = scc_mod.tarjan_scc(n, [(s, d) for s, d, _ in timed])
        if not sccs:
            continue
        keep = {v for scc in sccs for v in scc}
        scc_edges = [(s, d, t) for s, d, t in timed
                     if s in keep and d in keep]
        if any(t == typ for _, _, t in scc_edges):
            emit(name, _cycles_through_type(n, scc_edges, typ))


def _classify_stages(n: int, edges: list, to_global: list,
                     anomalies: dict, limit: int = 10) -> None:
    """Runs the typed anomaly stages on one cluster subgraph and merges
    renders (in GLOBAL node ids) into ``anomalies``. Restricting each
    search to the cluster loses nothing: closed walks, like cycles, sit
    φ-inside one cluster (_phi_clusters), so every path the global BFS
    could use is cluster-internal."""
    def emit(name, cycles):
        if cycles:
            room = limit - len(anomalies.get(name, []))
            if room > 0:
                anomalies.setdefault(name, []).extend(
                    [[(to_global[s], to_global[d], t) for s, d, t in cyc]
                     for cyc in cycles[:room]])

    dep_edges = [e for e in edges if e[2] in (WW, WR, RW)]
    _run_stages(n, dep_edges, edges, emit)


def _check_cycles_global(graph: Graph, accelerator: str = "auto") -> dict:
    """Trim + global Tarjan pipeline: the oracle twin of the φ-cluster
    path, and the fallback when no usable φ exists. Device trim narrows
    the graph; exact host Tarjan + typed cycle search classify the
    residue."""
    from jepsen_tpu.ops import scc as scc_mod

    anomalies: dict[str, list] = {}
    graph.edge_list()  # materialize tuples if the builder was columnar

    # Potential-function screen shared by every stage: add_timing_edges
    # records each node's event position φ, and all timing edges strictly
    # increase φ by construction. If every dependency edge also strictly
    # increases φ, no cycle can exist in ANY stage's edge set (a cycle
    # would strictly increase φ around a loop) — the common
    # valid-history case settles with two vectorized comparisons, no trim.
    order = graph.time_order
    dep_screen = False
    dep = np.asarray([(s, d) for s, d, t in graph.edges
                      if t in (WW, WR, RW)], np.int64)
    if order is not None:
        if dep.size == 0:
            dep_screen = True  # timing edges alone are acyclic
        else:
            o_s, o_d = order[dep[:, 0]], order[dep[:, 1]]
            dep_screen = bool((o_s >= 0).all() and (o_d >= 0).all()
                              and (o_d > o_s).all())
    if dep_screen:
        return anomalies

    def residue(types: set | None):
        src, dst = graph.arrays(types)
        if len(src) == 0:
            return []
        # "auto" takes the device trim only at scale: below this edge
        # count the vectorized host peel wins on measured shapes (the
        # trim is O(diameter) sequential sweeps either way, and the
        # device pays per-iteration dispatch for tiny arrays)
        if accelerator == "cpu" or (
                accelerator == "auto"
                and len(src) < TRIM_DEVICE_MIN_EDGES):
            mask = _trim_cpu(graph.n, src, dst)
        else:
            mask = scc_mod.trim_to_cycles(graph.n, src, dst)
        if not mask.any():
            return []
        keep = set(np.nonzero(mask)[0].tolist())
        return [(s, d, t) for s, d, t in graph.edges
                if (types is None or t in types) and s in keep and d in keep]

    # The trim residue is a *superset* of the cycle nodes (and may be
    # loose when the peel hits its iteration cap on long-diameter graphs),
    # so only the exact host search's findings count as anomalies.
    #
    # One device trim serves every dependency stage: a cycle in any typed
    # subset (ww-only, ww+wr) is a cycle of the full dependency graph, so
    # its nodes are inside the full residue — the typed stages search the
    # residue-restricted subsets exactly instead of paying a trim each.
    #
    # The timing stages get the UNtrimmed edge set: the peel trim is
    # wrong for them (timing edges chain nearly the whole history, so
    # peeling needs O(diameter) ~ O(n) sweeps; linear-time Tarjan inside
    # _run_stages goes straight to the nontrivial SCCs instead).
    full_edges = residue({WW, WR, RW})

    def emit(name, cycles):
        if cycles:
            anomalies[name] = cycles

    _run_stages(graph.n, full_edges, graph.edges, emit)
    return anomalies


def _trim_cpu(n, src, dst, max_iters: int = 512):
    """Pure-numpy twin of the device trim kernel (oracle). Same iteration
    cap: the residue is a superset of the cycle nodes either way."""
    active = np.ones(n, dtype=bool)
    for _ in range(max_iters):
        ea = active[src] & active[dst]
        indeg = np.bincount(dst[ea], minlength=n) > 0
        outdeg = np.bincount(src[ea], minlength=n) > 0
        new = active & indeg & outdeg
        if (new == active).all():
            break
        active = new
    return active


def _exemplars(n, edges, limit: int = 10):
    from jepsen_tpu.ops import scc as scc_mod
    sccs = scc_mod.tarjan_scc(n, [(s, d) for s, d, _ in edges])
    out = []
    for scc in sccs[:limit]:
        c = scc_mod.find_cycle_in_scc(scc, edges)
        if c is not None:
            out.append(c)
    return out


def _cycles_through_type(n, edges, typ, limit: int = 10):
    """Cycles guaranteed to traverse at least one edge of `typ`: for each
    such edge (s, d), a path d -> s through any edges closes the cycle."""
    from jepsen_tpu.ops import scc as scc_mod
    adj: dict[int, list] = {}
    types = {t for _, _, t in edges}
    for s, d, t in edges:
        adj.setdefault(s, []).append((d, t))
    out = []
    for s, d, t in edges:
        if t != typ or len(out) >= limit:
            continue
        path = scc_mod._bfs_path(adj, d, s, types)
        if path is not None:
            out.append([(s, d, t)] + path)
    return out


def render_cycle(cycle, txns) -> list:
    """Makes a cycle human-readable: the txns along it."""
    out = []
    for s, d, t in cycle:
        out.append({"from": txns[s].get("value"), "type": t,
                    "to": txns[d].get("value")})
    return out


def result_map(anomalies: dict, txns, extra_anomalies: dict | None = None,
               consistency_models=("strict-serializable",)) -> dict:
    """Builds the checker result (elle.core/check shape: {:valid?
    :anomaly-types :anomalies}). Validity is judged against the anomalies
    proscribed by the requested consistency models."""
    merged: dict[str, Any] = {}
    for k, cycles in anomalies.items():
        if cycles:
            merged[k] = [render_cycle(c, txns) for c in cycles[:10]]
    for k, v in (extra_anomalies or {}).items():
        if v:
            merged[k] = v[:10] if isinstance(v, list) else v
    types = sorted(merged.keys())
    blocked = blocked_anomalies(consistency_models)
    invalid = [t for t in types if t in blocked]
    return {
        "valid?": not invalid,
        "anomaly-types": types,
        "not": sorted(invalid),
        "anomalies": merged,
    }
