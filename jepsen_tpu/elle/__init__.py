"""Transactional-anomaly detection (capability-equivalent to Elle, the
reference's txn checker — invoked from jepsen/src/jepsen/tests/cycle*.clj).

Builds ww/wr/rw dependency graphs from txn histories, detects cycles with
the device trimming kernel (jepsen_tpu.ops.scc), and classifies anomalies
with Adya's taxonomy:

* G0 (write cycle): cycle of only ww edges
* G1a (aborted read): observed a failed txn's write
* G1b (intermediate read): observed a non-final write of a txn
* G1c (cyclic information flow): cycle of ww+wr edges
* G-single (read skew): cycle with exactly one rw anti-dependency
* G2 (anti-dependency cycle): cycle with >= 2 rw edges
* internal: a txn's reads contradict its own earlier ops
* realtime-cycle: dependency cycle closed by a realtime precedence edge
  (txn A completed before txn B was invoked) — strict-serializability only
* process-cycle: dependency cycle closed by a same-process succession
  edge — sequential consistency and stronger
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

import numpy as np

WW, WR, RW = "ww", "wr", "rw"
REALTIME, PROCESS = "realtime", "process"

# anomaly -> weakest consistency model it violates (loosely following
# elle's anomaly/model mapping)
ANOMALY_SEVERITY = {
    "G0": "read-uncommitted",
    "cyclic-versions": "read-uncommitted",
    "G1a": "read-committed",
    "G1b": "read-committed",
    "G1c": "read-committed",
    "internal": "read-atomic",
    "duplicate-elements": "read-atomic",
    "incompatible-order": "read-atomic",
    "G-single": "snapshot-isolation",
    "G2": "serializable",
    "process-cycle": "sequential",
    "realtime-cycle": "strict-serializable",
}

SERIALIZABLE_BLOCKERS = {"G0", "G1a", "G1b", "G1c", "G-single", "G2",
                         "internal", "duplicate-elements",
                         "incompatible-order"}

# anomalies proscribed by each consistency model (Adya's hierarchy, the
# shape of elle's consistency-model option)
_RU = {"G0", "duplicate-elements", "incompatible-order", "duplicate-appends",
       "duplicate-writes", "cyclic-versions"}
_RC = _RU | {"G1a", "G1b", "G1c", "internal"}
MODEL_ANOMALIES = {
    "read-uncommitted": _RU,
    "read-committed": _RC,
    "read-atomic": _RC,
    "repeatable-read": _RC | {"G-single"},
    "snapshot-isolation": _RC | {"G-single"},
    "serializable": _RC | {"G-single", "G2"},
    "sequential": _RC | {"G-single", "G2", "process-cycle"},
    "strict-serializable": _RC | {"G-single", "G2", "realtime-cycle",
                                  "process-cycle"},
}


def blocked_anomalies(consistency_models) -> set:
    out: set = set()
    for m in consistency_models or ("strict-serializable",):
        out |= MODEL_ANOMALIES.get(m, SERIALIZABLE_BLOCKERS)
    return out


@dataclass
class Graph:
    """Typed edge-list dependency graph over txn indices."""

    n: int
    edges: list = field(default_factory=list)  # (src, dst, type)
    # per-node history position (invocation when known), filled by
    # add_timing_edges; None when unavailable or per-process
    # sequentiality was violated
    time_order: np.ndarray | None = None

    def add(self, src: int, dst: int, typ: str):
        if src != dst or typ == RW:
            self.edges.append((src, dst, typ))

    def arrays(self, types: set | None = None):
        es = [(s, d) for s, d, t in self.edges
              if types is None or t in types]
        if not es:
            return np.zeros(0, np.int32), np.zeros(0, np.int32)
        a = np.asarray(es, dtype=np.int32)
        return a[:, 0], a[:, 1]


def add_timing_edges(graph: Graph, history: list, txns: list,
                     realtime: bool = True, process: bool = True) -> None:
    """Adds realtime and process precedence edges to a dependency graph
    (the reference's strict-serializability surface: elle's realtime /
    process graphs behind jepsen/src/jepsen/tests/cycle/wr.clj:31-45).

    *Realtime*: txn A precedes txn B when A's completion appears before
    B's invocation in history order. Rather than the O(n^2) full order we
    add its transitive reduction with the frontier construction: a
    completed txn stays in the frontier until some later txn both invoked
    after it completed and has itself completed (dominating it), so every
    invocation links only from the O(concurrency) non-dominated txns and
    the closure of the added edges equals the full realtime order.
    Requires invocation events in the history; completion-only histories
    get no realtime edges (their intervals are unknown).

    *Process*: consecutive committed txns of one process, in history
    order — sound even for completion-only histories because a process is
    sequential by construction (the interpreter renumbers crashed
    processes rather than reusing them).

    ``info`` (indeterminate) txns never complete, so they may *receive*
    timing edges from their invocation point but never enter the frontier.
    """
    node_of = {id(op): i for i, op in enumerate(txns)}
    pending: dict = {}          # process -> history position of open invoke
    last_by_process: dict = {}  # process -> (last completed node, its pos)
    events: list = []           # (pos, 0=invoke|1=complete, node, invoke_pos)
    # Per-node event position (invocation when known, else completion):
    # every timing edge strictly increases it, so check_cycles can screen
    # the timing stages with a potential argument (see there). A history
    # that violates per-process sequentiality voids the screen.
    order = np.full(graph.n, -1, np.int64)
    sequential_ok = True
    for pos, op in enumerate(history):
        t = op.get("type")
        p = op.get("process")
        if t == "invoke":
            pending[p] = pos
            continue
        if t not in ("ok", "fail", "info"):
            continue
        inv = pending.pop(p, None)
        node = node_of.get(id(op))
        if node is None:
            continue
        order[node] = pos if inv is None else inv
        if process and isinstance(p, int):
            prev = last_by_process.get(p)
            if prev is not None:
                graph.add(prev[0], node, PROCESS)
                if inv is not None and inv < prev[1]:
                    sequential_ok = False  # overlapping ops in one process
            last_by_process[p] = (node, pos)
        if realtime and inv is not None:
            events.append((inv, 0, node, inv))
            if t != "info":
                events.append((pos, 1, node, inv))
    events.sort()
    frontier: list = []  # (complete_pos, node), none dominated
    for pos, kind, node, inv in events:
        if kind == 0:
            for _c, a in frontier:
                graph.add(a, node, REALTIME)
        else:
            frontier = [(c, a) for c, a in frontier if c >= inv]
            frontier.append((pos, node))
    graph.time_order = order if sequential_ok else None


# below this many edges, "auto" trims on host (see residue() in
# check_cycles); measured crossover on one chip with tunnel-attached
# dispatch — the device trim amortizes only on big graphs
TRIM_DEVICE_MIN_EDGES = 500_000


def check_cycles(graph: Graph, accelerator: str = "auto") -> dict:
    """Finds and classifies cycles. Device trim narrows the graph; exact
    host Tarjan + typed cycle search classify the residue (the structure of
    elle.core/check with typed searches)."""
    from jepsen_tpu.ops import scc as scc_mod

    anomalies: dict[str, list] = {}

    # Potential-function screen shared by every stage: add_timing_edges
    # records each node's event position φ, and all timing edges strictly
    # increase φ by construction. If every dependency edge also strictly
    # increases φ, no cycle can exist in ANY stage's edge set (a cycle
    # would strictly increase φ around a loop) — the common
    # valid-history case settles with two vectorized comparisons, no trim.
    order = graph.time_order
    dep_screen = False
    dep = np.asarray([(s, d) for s, d, t in graph.edges
                      if t in (WW, WR, RW)], np.int64)
    if order is not None:
        if dep.size == 0:
            dep_screen = True  # timing edges alone are acyclic
        else:
            o_s, o_d = order[dep[:, 0]], order[dep[:, 1]]
            dep_screen = bool((o_s >= 0).all() and (o_d >= 0).all()
                              and (o_d > o_s).all())
    if dep_screen:
        return anomalies

    def residue(types: set | None):
        src, dst = graph.arrays(types)
        if len(src) == 0:
            return []
        # "auto" takes the device trim only at scale: below this edge
        # count the vectorized host peel wins on measured shapes (the
        # trim is O(diameter) sequential sweeps either way, and the
        # device pays per-iteration dispatch for tiny arrays)
        if accelerator == "cpu" or (
                accelerator == "auto"
                and len(src) < TRIM_DEVICE_MIN_EDGES):
            mask = _trim_cpu(graph.n, src, dst)
        else:
            mask = scc_mod.trim_to_cycles(graph.n, src, dst)
        if not mask.any():
            return []
        keep = set(np.nonzero(mask)[0].tolist())
        return [(s, d, t) for s, d, t in graph.edges
                if (types is None or t in types) and s in keep and d in keep]

    # The trim residue is a *superset* of the cycle nodes (and may be
    # loose when the peel hits its iteration cap on long-diameter graphs),
    # so only the exact host search's findings count as anomalies.
    #
    # One device trim serves every dependency stage: a cycle in any typed
    # subset (ww-only, ww+wr) is a cycle of the full dependency graph, so
    # its nodes are inside the full residue — the typed stages search the
    # residue-restricted subsets exactly instead of paying a trim each.
    full_edges = residue({WW, WR, RW})

    # G0: ww-only cycles
    ww_edges = [e for e in full_edges if e[2] == WW]
    g0 = _exemplars(graph.n, ww_edges) if ww_edges else []
    if g0:
        anomalies["G0"] = g0

    # G1c: ww+wr cycles involving at least one wr edge
    g1_edges = [e for e in full_edges if e[2] in (WW, WR)]
    if g1_edges:
        if not g0:
            g1c = _exemplars(graph.n, g1_edges)
        else:
            # an SCC may contain both a pure-ww cycle (already reported as
            # G0) and a mixed cycle; search specifically for a cycle
            # through each wr edge so G1c isn't shadowed
            g1c = _cycles_through_type(graph.n, g1_edges, WR)
        if g1c:
            anomalies["G1c"] = g1c

    # dependency graph: G-single / G2. Timing edges are excluded here so
    # the serializable verdict is exactly the dependency-cycle question;
    # they get their own stages below.
    if full_edges:
        sccs = scc_mod.tarjan_scc(graph.n, [(s, d) for s, d, _ in full_edges])
        singles, g2s = [], []
        for scc in sccs:
            cycle = scc_mod.find_cycle_in_scc(scc, full_edges,
                                              prefer_fewest=RW)
            if cycle is None:
                continue
            n_rw = sum(1 for _, _, t in cycle if t == RW)
            if n_rw == 0:
                continue  # already reported as G0/G1c
            elif n_rw == 1:
                singles.append(cycle)
            else:
                g2s.append(cycle)
        if singles:
            anomalies["G-single"] = singles
        if g2s:
            anomalies["G2"] = g2s

    # strict-serializable / sequential: cycles forced through a timing
    # edge. Timing edges alone are acyclic (both follow history event
    # order), so any such cycle genuinely mixes in dependency edges.
    # The peel trim is wrong here — timing edges chain nearly the whole
    # history, so peeling needs O(diameter) ~ O(n) sweeps; linear-time
    # Tarjan goes straight to the nontrivial SCCs instead.
    # A strict serialization must respect realtime AND process order, so
    # the realtime search walks paths through process edges too (a cycle
    # needing both kinds is still a strict-serializability violation);
    # the process search stays dep+process only — that is exactly the
    # sequential-consistency question.
    for typ, path_types, name in (
            (REALTIME, (WW, WR, RW, REALTIME, PROCESS), "realtime-cycle"),
            (PROCESS, (WW, WR, RW, PROCESS), "process-cycle")):
        if not any(t == typ for _, _, t in graph.edges):
            continue
        timed = [(s, d, t) for s, d, t in graph.edges if t in path_types]
        sccs = scc_mod.tarjan_scc(graph.n, [(s, d) for s, d, _ in timed])
        if not sccs:
            continue
        keep = {v for scc in sccs for v in scc}
        scc_edges = [(s, d, t) for s, d, t in timed
                     if s in keep and d in keep]
        if any(t == typ for _, _, t in scc_edges):
            cycles = _cycles_through_type(graph.n, scc_edges, typ)
            if cycles:
                anomalies[name] = cycles
    return anomalies


def _trim_cpu(n, src, dst, max_iters: int = 512):
    """Pure-numpy twin of the device trim kernel (oracle). Same iteration
    cap: the residue is a superset of the cycle nodes either way."""
    active = np.ones(n, dtype=bool)
    for _ in range(max_iters):
        ea = active[src] & active[dst]
        indeg = np.bincount(dst[ea], minlength=n) > 0
        outdeg = np.bincount(src[ea], minlength=n) > 0
        new = active & indeg & outdeg
        if (new == active).all():
            break
        active = new
    return active


def _exemplars(n, edges, limit: int = 10):
    from jepsen_tpu.ops import scc as scc_mod
    sccs = scc_mod.tarjan_scc(n, [(s, d) for s, d, _ in edges])
    out = []
    for scc in sccs[:limit]:
        c = scc_mod.find_cycle_in_scc(scc, edges)
        if c is not None:
            out.append(c)
    return out


def _cycles_through_type(n, edges, typ, limit: int = 10):
    """Cycles guaranteed to traverse at least one edge of `typ`: for each
    such edge (s, d), a path d -> s through any edges closes the cycle."""
    from jepsen_tpu.ops import scc as scc_mod
    adj: dict[int, list] = {}
    types = {t for _, _, t in edges}
    for s, d, t in edges:
        adj.setdefault(s, []).append((d, t))
    out = []
    for s, d, t in edges:
        if t != typ or len(out) >= limit:
            continue
        path = scc_mod._bfs_path(adj, d, s, types)
        if path is not None:
            out.append([(s, d, t)] + path)
    return out


def render_cycle(cycle, txns) -> list:
    """Makes a cycle human-readable: the txns along it."""
    out = []
    for s, d, t in cycle:
        out.append({"from": txns[s].get("value"), "type": t,
                    "to": txns[d].get("value")})
    return out


def result_map(anomalies: dict, txns, extra_anomalies: dict | None = None,
               consistency_models=("strict-serializable",)) -> dict:
    """Builds the checker result (elle.core/check shape: {:valid?
    :anomaly-types :anomalies}). Validity is judged against the anomalies
    proscribed by the requested consistency models."""
    merged: dict[str, Any] = {}
    for k, cycles in anomalies.items():
        if cycles:
            merged[k] = [render_cycle(c, txns) for c in cycles[:10]]
    for k, v in (extra_anomalies or {}).items():
        if v:
            merged[k] = v[:10] if isinstance(v, list) else v
    types = sorted(merged.keys())
    blocked = blocked_anomalies(consistency_models)
    invalid = [t for t in types if t in blocked]
    return {
        "valid?": not invalid,
        "anomaly-types": types,
        "not": sorted(invalid),
        "anomalies": merged,
    }
