"""Columnar list-append checker: the production-path twin of
jepsen_tpu.elle.list_append's Python builder, vectorized end to end.

The reference's Elle (jepsen/src/jepsen/tests/cycle/append.clj via the
elle library) walks per-txn micro-ops with JVM map operations; at 50k+
txns the equivalent Python walk dominates the whole check. This module
derives the same dependency graph with a few C-speed passes instead:

* history parsing — event pairing, micro-op flattening, key interning,
  spine selection, prefix verification — runs in a native C extension
  (`native/columnar_ext.c`, built on demand) when available, else in
  the vectorized numpy front below,
* writer maps, element-level scans (aborted reads, unobserved writers,
  intermediate reads), the internal (own-writes) check, ww/wr/rw edge
  derivation and the realtime/process timing edges are array joins over
  the ~n_appends spine/last-element columns: sorts, searchsorted,
  gathers (the shared tail, identical for both fronts).

The key economy: a read that verifies as a clean prefix of its key's
spine contains only spine elements, so element-level scans run over the
spine columns instead of the O(sum of read lengths) raw payloads. Rows
that fail verification (rare, and exactly the anomalous ones) fall back
to per-row Python scrutiny with the oracle's semantics.

Applies when append/fail values are ints in [0, 2^32) (the universal
workload shape — elle's own generator emits dense int appends); anything
else returns None and the caller falls back to the Python builder. The
cpu-oracle path never comes here: differential tests pin this builder
to it.
"""
from __future__ import annotations

from collections import defaultdict

import numpy as np

from jepsen_tpu import elle
from jepsen_tpu.elle import Graph, PROCESS, REALTIME, RW, WR, WW, _TYPE_CODE
from jepsen_tpu.txn import _hk

# composite-key bit budget: (txn << 32) | (kid << 12) | mi must be exact
# in int64, and (kid << 32) | value needs value in [0, 2^32)
_MAX_KIDS = 1 << 20
_I64 = 1 << 63
_MAX_MOPS = 1 << 12
_MAX_VAL = 1 << 32


# phase timings of the most recent check_columnar call (seconds); a
# diagnosis surface for benchmark trial spread — build is host-side
# C/numpy, cycles is the (possibly device) screen + search
LAST_PHASE_SECONDS: dict = {}


def _cmod():
    """The native C parser module, or None (pure-Python fallback)."""
    from jepsen_tpu.native import columnar_c
    return columnar_c.mod()


def check_columnar(history: list, consistency_models, accelerator: str,
                   parts=None):
    """Full list-append check on the columnar fast path, or None when the
    history falls outside the integer regime (caller falls back).
    ``parts`` short-circuits the build phase with a precomputed
    ``_build`` product — the history-IR view
    (jepsen_tpu.history_ir.views.elle_build) passes it so a run that
    already encoded pays ~zero build here (``phase_build_s`` then
    measures just the handoff)."""
    import time as _time
    t0 = _time.perf_counter()
    if parts is None:
        try:
            parts = _build(history)
        except (TypeError, ValueError, OverflowError):
            return None
    if parts is None:
        return None
    graph, txns, extras, n_keys = parts
    t1 = _time.perf_counter()

    cyc = elle.check_cycles(graph, accelerator=accelerator)
    LAST_PHASE_SECONDS.update(build=round(t1 - t0, 3),
                              cycles=round(_time.perf_counter() - t1, 3))
    merged_extras = {k: v for k, v in extras.items()
                     if k != "unobserved-writer"}
    result = elle.result_map(cyc, txns, merged_extras,
                             consistency_models=consistency_models)
    result["txn-count"] = graph.n
    result["edge-count"] = graph.edge_count()
    result["read-scan-keys"] = {"columnar": n_keys, "python": 0}
    result["builder"] = "columnar"
    return result


def _build(history: list):
    """Dependency-graph build: C parser when available, numpy otherwise.
    Returns (graph, txns, extras, n_keys) or None (regime miss)."""
    m = _cmod()
    if m is not None:
        try:
            out = m.parse(history)
        except Exception:  # noqa: BLE001 - never fail the check over C
            out = None
        if out is not None:
            return _build_from_c(out)
    return _build_py(history)


def _build_from_c(out):
    """Adapts the C parser's 25-tuple into the shared tail's inputs."""
    (n_ok, nk, node_pos_b, node_inv_b, node_proc_b, txns,
     a_txn_b, a_kid_b, a_val_b, a_mi_b,
     r_txn_b, r_kid_b, r_mi_b, r_len_b, r_last_b,
     payloads, raw_key, f_kid_b, f_val_b,
     s_concat_b, s_kid_b, soff_b, slen_b, brow_b, scrutiny_l) = out
    b = lambda x: np.frombuffer(x, np.int64)  # noqa: E731
    R_txn = b(r_txn_b)
    R_isok = R_txn < n_ok
    F_comp = np.sort((b(f_kid_b) << 32) | b(f_val_b)) \
        if len(f_val_b) else np.asarray([], np.int64)
    brow = b(brow_b)

    def spine_of(k):
        r = int(brow[k])
        return payloads[r] if r >= 0 else None

    return _tail(
        txns=txns, n=len(txns), n_ok=n_ok, nk=nk, raw_key=raw_key,
        A_txn=b(a_txn_b), A_kid=b(a_kid_b), A_val=b(a_val_b),
        A_mi=b(a_mi_b), F_comp=F_comp,
        R_txn=R_txn, R_kid=b(r_kid_b), R_mi=b(r_mi_b),
        lens=b(r_len_b), last_arr=b(r_last_b), R_isok=R_isok,
        payloads=payloads,
        S_concat=b(s_concat_b), s_kid=b(s_kid_b),
        soff_of_kid=b(soff_b), slen_of_kid=b(slen_b),
        spine_of=spine_of,
        scrutiny=set(scrutiny_l), rows_by_kid=None,
        node_pos=b(node_pos_b), node_inv=b(node_inv_b),
        node_proc=b(node_proc_b))


class NeedsObjects(Exception):
    """A finding requires op-object context (txn values) that stored
    columns don't carry — re-run the check from the jsonl history."""


class _ObjectsNeeded:
    """Stand-in for the txn-object list in stored-column checks: sized,
    but any element access means a finding wants to cite a txn — the
    caller must fall back to the object history."""

    def __init__(self, n: int):
        self._n = n

    def __len__(self):
        return self._n

    def __getitem__(self, i):
        raise NeedsObjects("finding cites txn objects; re-check from "
                           "the jsonl history")


class _PayloadView:
    """Read payloads as lists on demand from the stored concat+offsets
    (only anomaly-scrutiny paths ever materialize one)."""

    def __init__(self, concat, off):
        self.concat = concat
        self.off = off

    def __len__(self):
        return len(self.off) - 1

    def __getitem__(self, j):
        return self.concat[self.off[j]:self.off[j + 1]].tolist()


#: keys of the storable column set (parse_columns product); scalars
#: n_ok/nk ride as 0-d arrays
ELLE_COLUMN_KEYS = (
    "n_ok", "nk", "node_pos", "node_inv", "node_proc",
    "a_txn", "a_kid", "a_val", "a_mi",
    "r_txn", "r_kid", "r_mi", "r_len", "r_last",
    "f_kid", "f_val", "s_concat", "s_kid", "soff", "slen", "brow",
    "scrutiny", "raw_key", "payload_concat", "payload_off")


def parse_columns(history: list):
    """The C parser's product as plain int64 numpy columns — the
    struct-of-arrays form the store persists so later re-checks skip
    the PyObject parse entirely (SURVEY §7's history-as-columns
    stance). None when the history is outside the storable regime
    (no C parser, exotic keys, non-int payload elements)."""
    m = _cmod()
    if m is None:
        return None
    try:
        out = m.parse(history)
    except Exception:  # noqa: BLE001
        return None
    if out is None:
        return None
    (n_ok, nk, node_pos_b, node_inv_b, node_proc_b, _txns,
     a_txn_b, a_kid_b, a_val_b, a_mi_b,
     r_txn_b, r_kid_b, r_mi_b, r_len_b, r_last_b,
     payloads, raw_key, f_kid_b, f_val_b,
     s_concat_b, s_kid_b, soff_b, slen_b, brow_b, scrutiny_l) = out
    b = lambda x: np.frombuffer(x, np.int64)  # noqa: E731
    try:
        raw_key_arr = np.asarray(raw_key, np.int64)
        # natural-dtype conversion + integer-kind check: a forced
        # int64 cast would silently TRUNCATE float payload elements
        # (e.g. a corrupt read of 1.5) and the stored re-check would
        # miss anomalies the object path reports
        pay_arrays = []
        for p in payloads:
            a = np.asarray(p)
            if a.size == 0:
                a = np.zeros(0, np.int64)
            elif a.ndim != 1 or a.dtype.kind not in "iu":
                return None  # non-int elements: not storable
            pay_arrays.append(a.astype(np.int64))
    except (TypeError, ValueError, OverflowError):
        return None  # exotic keys/payload elements: not storable
    lens = b(r_len_b)
    off = np.zeros(len(pay_arrays) + 1, np.int64)
    np.cumsum(lens, out=off[1:])
    concat = (np.concatenate(pay_arrays) if pay_arrays
              else np.zeros(0, np.int64))
    return {
        "n_ok": np.int64(n_ok), "nk": np.int64(nk),
        "node_pos": b(node_pos_b), "node_inv": b(node_inv_b),
        "node_proc": b(node_proc_b),
        "a_txn": b(a_txn_b), "a_kid": b(a_kid_b), "a_val": b(a_val_b),
        "a_mi": b(a_mi_b),
        "r_txn": b(r_txn_b), "r_kid": b(r_kid_b), "r_mi": b(r_mi_b),
        "r_len": lens, "r_last": b(r_last_b),
        "f_kid": b(f_kid_b), "f_val": b(f_val_b),
        "s_concat": b(s_concat_b), "s_kid": b(s_kid_b),
        "soff": b(soff_b), "slen": b(slen_b), "brow": b(brow_b),
        "scrutiny": np.asarray(scrutiny_l, np.int64),
        "raw_key": raw_key_arr,
        "payload_concat": concat, "payload_off": off,
    }


def check_columns(cols: dict, consistency_models=("strict-serializable",),
                  accelerator: str = "auto") -> dict:
    """Full list-append check from stored columns — no op objects, no
    parse. Raises :class:`NeedsObjects` when a finding needs to cite
    txn values (anomalous histories); the clean path completes
    entirely from the arrays."""
    import time as _time
    t0 = _time.perf_counter()
    a = {k: np.asarray(cols[k]) for k in ELLE_COLUMN_KEYS}
    n_ok, nk = int(a["n_ok"]), int(a["nk"])
    payloads = _PayloadView(a["payload_concat"], a["payload_off"])
    brow = a["brow"]

    def spine_of(k):
        r = int(brow[k])
        return payloads[r] if r >= 0 else None

    F_comp = np.sort((a["f_kid"] << 32) | a["f_val"]) \
        if a["f_val"].size else np.asarray([], np.int64)
    txns = _ObjectsNeeded(int(a["node_pos"].size))
    graph, _txns, extras, nk = _tail(
        txns=txns, n=len(txns), n_ok=n_ok, nk=nk,
        raw_key=a["raw_key"].tolist(),
        A_txn=a["a_txn"], A_kid=a["a_kid"], A_val=a["a_val"],
        A_mi=a["a_mi"], F_comp=F_comp,
        R_txn=a["r_txn"], R_kid=a["r_kid"], R_mi=a["r_mi"],
        lens=a["r_len"], last_arr=a["r_last"],
        R_isok=a["r_txn"] < n_ok, payloads=payloads,
        S_concat=a["s_concat"], s_kid=a["s_kid"],
        soff_of_kid=a["soff"], slen_of_kid=a["slen"], spine_of=spine_of,
        scrutiny=set(a["scrutiny"].tolist()), rows_by_kid=None,
        node_pos=a["node_pos"], node_inv=a["node_inv"],
        node_proc=a["node_proc"])
    t1 = _time.perf_counter()
    cyc = elle.check_cycles(graph, accelerator=accelerator)
    LAST_PHASE_SECONDS.update(build=round(t1 - t0, 3),
                              cycles=round(_time.perf_counter() - t1, 3))
    merged_extras = {k: v for k, v in extras.items()
                     if k != "unobserved-writer"}
    result = elle.result_map(cyc, txns, merged_extras,
                             consistency_models=consistency_models)
    result["txn-count"] = graph.n
    result["edge-count"] = graph.edge_count()
    result["read-scan-keys"] = {"columnar": nk, "python": 0}
    result["builder"] = "columnar-store"
    return result


def _flatten_mops_fast(txns):
    """Vectorized pass B for the all-int regime (every mop key a plain
    int, every append value a plain int): C-speed comprehensions +
    numpy replace the per-mop Python loop, which dominates the whole
    check on large histories. Returns the exact pass-B product —
    including kid ids in FIRST-ENCOUNTER order over appended/read mops,
    matching the general loop's interning bit-for-bit — or None to fall
    back to the general loop (exotic keys/values, over-long txns).
    Differentially pinned to the loop by the columnar-vs-python fuzz in
    tests/test_elle.py."""
    vals = [op.get("value") or () for op in txns]
    # only sized, re-iterable containers take the fast path — a one-shot
    # or unsized iterable (no len, or consumed by the flatten) must flow
    # to the general loop, which iterates each value exactly once
    if any(type(v) not in (list, tuple) for v in vals):
        return None
    counts = np.fromiter((len(v) for v in vals), np.int64, len(vals))
    total = int(counts.sum())
    if counts.size and int(counts.max()) > _MAX_MOPS:
        return None
    mops = [m for v in vals for m in v]
    if not mops:
        return None
    try:
        fs = [m[0] for m in mops]
        keys = [m[1] for m in mops]
        third = [m[2] for m in mops]
    except (ValueError, IndexError, TypeError):
        return None
    if any(type(k) is not int or k < -_I64 or k >= _I64 for k in keys):
        return None  # exotic/huge keys: the general loop interns anything

    flat_txn = np.repeat(np.arange(len(vals), dtype=np.int64), counts)
    flat_mi = (np.arange(total, dtype=np.int64)
               - np.repeat(np.cumsum(counts) - counts, counts))
    is_append = np.fromiter((f == "append" for f in fs), bool, total)
    is_read = np.fromiter(
        (f == "r" and t is not None for f, t in zip(fs, third)),
        bool, total)
    ai = np.nonzero(is_append)[0]
    ri = np.nonzero(is_read)[0]

    a_val = [third[j] for j in ai.tolist()]
    if any(type(v) is not int for v in a_val):
        return None
    payloads = [third[j] if type(third[j]) is list else list(third[j])
                for j in ri.tolist()]

    # interning: ids in first-encounter order over appended/read mops
    # (ignored mops' keys never intern — same as kid())
    karr = np.asarray(keys, np.int64)
    sel = np.sort(np.concatenate([ai, ri]))
    ksel = karr[sel]
    uniq, first_idx, inverse = np.unique(ksel, return_index=True,
                                         return_inverse=True)
    order = np.argsort(first_idx)
    rank = np.empty(order.size, np.int64)
    rank[order] = np.arange(order.size)
    kid_of_flat = np.full(total, -1, np.int64)
    kid_of_flat[sel] = rank[inverse]
    raw_key = uniq[order].tolist()
    kid_of = {k: i for i, k in enumerate(raw_key)}

    # a_* go straight back into np.asarray downstream: return arrays
    # (no copy on re-asarray); r_kid stays a python list — the prefix
    # loop indexes it per row and np scalar boxing would cost more
    return (flat_txn[ai], kid_of_flat[ai], a_val, flat_mi[ai],
            flat_txn[ri], kid_of_flat[ri].tolist(), flat_mi[ri],
            payloads, raw_key, kid_of)


def _build_py(history: list):
    # ---- pass A: event extraction + invocation pairing -----------------
    # Closed form of the pending-dict walk: a completion's invocation is
    # the previous event of the same process iff that event is an invoke
    # (a newer invoke overwrites, a completion consumes — both exactly
    # the "previous event" rule). Verified equivalent by differential
    # test against the dict semantics.
    nh = len(history)
    types = [op.get("type") for op in history]
    procs = [op.get("process") for op in history]
    _EV = {"invoke": 0, "ok": 1, "fail": 1, "info": 1}
    ev = [_EV.get(t, -1) for t in types]
    pid_of: dict = {}
    pid = [pid_of.setdefault(p, len(pid_of)) for p in procs]
    ev_a = np.asarray(ev, np.int64)
    pid_a = np.asarray(pid, np.int64)
    sel = np.nonzero(ev_a >= 0)[0]
    o = sel[np.argsort(pid_a[sel], kind="stable")]
    link = ((pid_a[o][1:] == pid_a[o][:-1]) & (ev_a[o][:-1] == 0)
            & (ev_a[o][1:] == 1)) if o.size > 1 else np.zeros(0, bool)
    inv_pos_of = np.full(nh, -1, np.int64)
    if o.size > 1:
        inv_pos_of[o[1:][link]] = o[:-1][link]

    # mask-select ok/info/fail positions at C speed (the per-event
    # conditional comprehensions dominated the whole build at 50k txns)
    pint = np.fromiter((isinstance(p, int) for p in procs), bool, nh)
    ok_m = np.fromiter((t == "ok" for t in types), bool, nh)
    info_m = np.fromiter((t == "info" for t in types), bool, nh)
    fail_m = np.fromiter((t == "fail" for t in types), bool, nh)
    ok_pos = np.nonzero(ok_m & pint)[0]
    info_pos = np.nonzero(info_m & pint)[0]
    fail_ops = [history[i] for i in np.nonzero(fail_m)[0].tolist()]

    n_ok = int(ok_pos.size)
    node_pos = np.concatenate([ok_pos, info_pos])
    txns = [history[i] for i in node_pos.tolist()]
    n = len(txns)
    if n == 0 or n >= (1 << 31):
        return None
    node_inv = inv_pos_of[node_pos]
    node_proc = np.asarray([procs[i] for i in node_pos.tolist()], np.int64)

    # ---- pass B: flatten micro-ops into columns ------------------------
    def kid(k):
        # interns into kid_of/raw_key (bound at call time): fresh on the
        # general loop, continuing the fast map for fail ops after it
        hk = _hk(k)
        i = kid_of.get(hk)
        if i is None:
            i = kid_of[hk] = len(raw_key)
            raw_key.append(k)
        return i

    fast = _flatten_mops_fast(txns)
    if fast is not None:
        (a_txn, a_kid, a_val, a_mi, r_txn, r_kid, r_mi, payloads,
         raw_key, kid_of) = fast
    else:
        kid_of = {}
        raw_key = []
        a_txn, a_kid, a_val, a_mi = [], [], [], []
        r_txn, r_kid, r_mi = [], [], []
        payloads = []
        for i, op in enumerate(txns):
            for mi, m in enumerate(op.get("value") or ()):
                if mi >= _MAX_MOPS:
                    return None
                f = m[0]
                if f == "append":
                    v = m[2]
                    if not isinstance(v, int) or isinstance(v, bool):
                        return None
                    a_txn.append(i)
                    a_kid.append(kid(m[1]))
                    a_val.append(v)
                    a_mi.append(mi)
                elif f == "r" and m[2] is not None:
                    r_txn.append(i)
                    r_kid.append(kid(m[1]))
                    r_mi.append(mi)
                    payloads.append(m[2] if type(m[2]) is list
                                    else list(m[2]))

    f_kid: list = []
    f_val: list = []
    for op in fail_ops:
        for m in op.get("value") or ():
            if m[0] == "append":
                v = m[2]
                if not isinstance(v, int) or isinstance(v, bool):
                    return None
                f_kid.append(kid(m[1]))
                f_val.append(v)

    return _assemble(txns=txns, n_ok=n_ok, raw_key=raw_key,
                     a_txn=a_txn, a_kid=a_kid, a_val=a_val, a_mi=a_mi,
                     r_txn=r_txn, r_kid=r_kid, r_mi=r_mi,
                     payloads=payloads, f_kid=f_kid, f_val=f_val,
                     node_pos=node_pos, node_inv=node_inv,
                     node_proc=node_proc)


def _assemble(*, txns, n_ok, raw_key, a_txn, a_kid, a_val, a_mi,
              r_txn, r_kid, r_mi, payloads, f_kid, f_val,
              node_pos, node_inv, node_proc):
    """Array build + spine selection + prefix verification over flattened
    micro-op columns, ending in the shared :func:`_tail`. Factored out of
    ``_build_py`` so the live checker's incremental builder
    (jepsen_tpu.live.sessions.ElleSession) — which maintains the
    flattened columns op by op as a run's WAL streams in — reuses the
    exact batch semantics for every verdict. Returns the ``_build``
    4-tuple or None on a regime miss (caller falls back to the Python
    builder)."""
    n = len(txns)
    nk = len(raw_key)
    if nk >= _MAX_KIDS:
        return None

    A_txn = np.asarray(a_txn, np.int64)
    A_kid = np.asarray(a_kid, np.int64)
    A_val = np.asarray(a_val, np.int64)
    A_mi = np.asarray(a_mi, np.int64)
    if A_val.size and (A_val.min() < 0 or A_val.max() >= _MAX_VAL):
        return None
    F_comp = np.asarray([], np.int64)
    if f_val:
        fv = np.asarray(f_val, np.int64)
        if fv.min() < 0 or fv.max() >= _MAX_VAL:
            return None
        F_comp = np.sort((np.asarray(f_kid, np.int64) << 32) | fv)

    n_reads = len(payloads)
    R_txn = np.asarray(r_txn, np.int64)
    R_kid = np.asarray(r_kid, np.int64)
    R_mi = np.asarray(r_mi, np.int64)
    lens = np.asarray([len(p) for p in payloads], np.int64)
    R_isok = R_txn < n_ok  # info txns' reads are unreliable (no spine use)

    # last element per read feeds composite joins (wr edges, internal):
    # must be exact ints; anything else punts to the Python builder
    last_list = [p[-1] if p else -1 for p in payloads]
    last_arr = np.asarray(last_list) if n_reads else np.zeros(0, np.int64)
    if last_arr.size and last_arr.dtype.kind != "i":
        return None
    last_arr = last_arr.astype(np.int64, copy=False)

    # ---- spines: longest ok read per key -------------------------------
    okr = np.nonzero(R_isok)[0]
    soff_of_kid = np.full(nk, -1, np.int64)
    slen_of_kid = np.zeros(nk, np.int64)
    spine_list_of_kid: list = [None] * nk
    if okr.size:
        # first maximal-length read per key (the oracle's max(reads,
        # key=len) picks the FIRST on length ties — order must match, a
        # different spine is a different version order)
        osort = okr[np.lexsort((okr, -lens[okr], R_kid[okr]))]
        kid_sorted = R_kid[osort]
        firstm = np.nonzero(np.r_[True, kid_sorted[1:] != kid_sorted[:-1]])[0]
        spine_rows = osort[firstm]
        spine_kids = R_kid[spine_rows]
        spine_lens = lens[spine_rows]
        spine_arrays = []
        for r, k in zip(spine_rows.tolist(), spine_kids.tolist()):
            spine_list_of_kid[k] = payloads[r]
            a = np.asarray(payloads[r])
            if a.dtype.kind != "i":
                if a.size == 0:
                    a = np.zeros(0, np.int64)
                else:
                    return None  # non-int observed values: python builder
            spine_arrays.append(a.astype(np.int64, copy=False))
        S_concat = (np.concatenate(spine_arrays) if spine_arrays
                    else np.zeros(0, np.int64))
        slen_of_kid[spine_kids] = spine_lens
        soff_of_kid[spine_kids] = np.cumsum(spine_lens) - spine_lens
        s_kid = np.repeat(spine_kids, spine_lens)
    else:
        S_concat = np.zeros(0, np.int64)
        s_kid = np.zeros(0, np.int64)
    if S_concat.size and (S_concat.min() < 0 or S_concat.max() >= _MAX_VAL):
        return None

    # ---- prefix verification: C-speed list compares --------------------
    rows_by_kid: dict = defaultdict(list)
    scrutiny: set = set()
    r_kid_l = r_kid if type(r_kid) is list else \
        R_kid.tolist()  # python list view, avoids 50k np scalar boxing
    for j in okr.tolist():
        k = r_kid_l[j]
        rows_by_kid[k].append(j)
        p = payloads[j]
        sp = spine_list_of_kid[k]
        if p is sp:
            continue  # the spine trivially prefixes itself
        if p != sp[: len(p)]:
            scrutiny.add(j)

    return _tail(
        txns=txns, n=n, n_ok=n_ok, nk=nk, raw_key=raw_key,
        A_txn=A_txn, A_kid=A_kid, A_val=A_val, A_mi=A_mi, F_comp=F_comp,
        R_txn=R_txn, R_kid=R_kid, R_mi=R_mi, lens=lens,
        last_arr=last_arr, R_isok=R_isok, payloads=payloads,
        S_concat=S_concat, s_kid=s_kid, soff_of_kid=soff_of_kid,
        slen_of_kid=slen_of_kid, spine_of=spine_list_of_kid.__getitem__,
        scrutiny=scrutiny, rows_by_kid=rows_by_kid,
        node_pos=node_pos, node_inv=node_inv, node_proc=node_proc)


def _tail(*, txns, n, n_ok, nk, raw_key,
          A_txn, A_kid, A_val, A_mi, F_comp,
          R_txn, R_kid, R_mi, lens, last_arr, R_isok, payloads,
          S_concat, s_kid, soff_of_kid, slen_of_kid, spine_of,
          scrutiny, rows_by_kid, node_pos, node_inv, node_proc):
    """Shared analysis tail over the columnar product (either front):
    writer maps, anomaly scans, edge derivation, timing edges."""
    extras: dict[str, list] = defaultdict(list)
    n_reads = len(payloads)

    # lazy rows_by_kid: the C front doesn't build it (only anomaly
    # attribution needs it, which clean histories never reach)
    _rbk = [rows_by_kid]

    def get_rows_by_kid():
        if _rbk[0] is None:
            d: dict = defaultdict(list)
            okr = np.nonzero(R_isok)[0]
            for j, k in zip(okr.tolist(), R_kid[okr].tolist()):
                d[k].append(j)
            _rbk[0] = d
        return _rbk[0]

    # ---- writer map: first append of (key, value) wins -----------------
    A_comp = (A_kid << 32) | A_val
    a_order = np.argsort(A_comp, kind="stable")
    ac_sorted = A_comp[a_order]
    first = np.r_[True, ac_sorted[1:] != ac_sorted[:-1]] \
        if ac_sorted.size else np.zeros(0, bool)
    for j in a_order[~first].tolist():
        extras["duplicate-appends"].append(
            {"key": raw_key[int(A_kid[j])], "value": int(A_val[j])})
    W_comp = ac_sorted[first]
    W_txn = A_txn[a_order][first]

    def writer_lookup(comps):
        if W_comp.size == 0:
            return np.full(comps.shape, -1, np.int64)
        pos = np.clip(np.searchsorted(W_comp, comps), 0, W_comp.size - 1)
        return np.where(W_comp[pos] == comps, W_txn[pos], -1)

    def failed_lookup(comps):
        if F_comp.size == 0:
            return np.zeros(comps.shape, bool)
        pos = np.clip(np.searchsorted(F_comp, comps), 0, F_comp.size - 1)
        return F_comp[pos] == comps

    # keys whose spine repeats a value need per-row duplicate scrutiny
    if S_concat.size:
        comp_spine = (s_kid << 32) | S_concat
        sc = np.sort(comp_spine)
        dup_kids = set((sc[1:][sc[1:] == sc[:-1]] >> 32).tolist())
        if dup_kids:
            for k in dup_kids:
                scrutiny.update(get_rows_by_kid().get(int(k), ()))
    else:
        comp_spine = np.zeros(0, np.int64)

    # ---- spine-element membership: G1a / unobserved / G1b sources ------
    w_of_spine = writer_lookup(comp_spine)
    f_hit_spine = failed_lookup(comp_spine)
    # multi-append writers per (txn, key): the only possible G1b sources
    TK = (A_txn << 32) | A_kid
    tks = np.sort(TK)
    multi_tk = np.unique(tks[1:][tks[1:] == tks[:-1]]) if tks.size else \
        np.asarray([], np.int64)

    def spine_elem_hits(mask):
        """(kid, local position, global elem) for flagged spine elems."""
        idx = np.nonzero(mask)[0]
        return [(int(s_kid[e]), int(e - soff_of_kid[s_kid[e]]), int(e))
                for e in idx.tolist()]

    # lazy Python maps for the rare scrutiny / G1b paths. Keys are
    # (kid, value) tuples — same hash semantics as the oracle's dicts
    # (so a float read of an int append still matches, like the oracle)
    _maps: dict = {}

    def lazy_maps():
        if not _maps:
            writer_txn: dict = {}
            appends_ptk: dict = defaultdict(list)
            srt = np.argsort((A_txn << 32) | (A_kid << 12) | A_mi,
                             kind="stable")
            for j in srt.tolist():
                appends_ptk[(int(A_txn[j]), int(A_kid[j]))].append(
                    int(A_val[j]))
            for comp, w in zip(W_comp.tolist(), W_txn.tolist()):
                writer_txn[(comp >> 32, comp & 0xFFFFFFFF)] = w
            failed = {(c >> 32, c & 0xFFFFFFFF) for c in F_comp.tolist()}
            _maps.update(writer=writer_txn, aptk=appends_ptk, failed=failed)
        return _maps

    def g1b_row(j):
        """Per-writer observed-subsequence check for one read (oracle
        _g1b_one_read semantics: committed multi-append writers must be
        observed all-or-nothing, in order)."""
        m = lazy_maps()
        r = payloads[j]
        k = int(R_kid[j])
        observed: dict = defaultdict(list)
        for v in r:
            w = m["writer"].get((k, v))
            if w is not None:
                observed[w].append(v)
        for wi, obs in observed.items():
            if wi == int(R_txn[j]) or wi >= n_ok:
                continue  # own reads / indeterminate writers: not G1b
            txn_appends = m["aptk"].get((wi, k), [])
            if obs == txn_appends:
                continue
            if obs == txn_appends[: len(obs)]:
                extras["G1b"].append(
                    {"key": raw_key[k], "read": list(r),
                     "writer": txns[wi].get("value")})
            else:
                extras["incompatible-order"].append(
                    {"key": raw_key[k], "read": list(r),
                     "writer-appends": txn_appends})

    def scan_row(j):
        """Full per-row scrutiny (oracle _scan_reads_py semantics)."""
        m = lazy_maps()
        r = payloads[j]
        k = int(R_kid[j])
        sp = spine_of(k) or []
        if r != sp[: len(r)]:
            extras["incompatible-order"].append(
                {"key": raw_key[k], "read": list(r), "longest": list(sp)})
        if len(set(r)) != len(r):
            extras["duplicate-elements"].append(
                {"key": raw_key[k], "read": list(r)})
        for v in r:
            kv = (k, v)
            if kv in m["failed"]:
                extras["G1a"].append(
                    {"key": raw_key[k], "value": v,
                     "read-txn": txns[int(R_txn[j])].get("value")})
            elif kv not in m["writer"]:
                extras["unobserved-writer"].append(
                    {"key": raw_key[k], "value": v})
        g1b_row(j)

    for j in sorted(scrutiny):
        scan_row(j)

    # clean rows: element-level anomalies can only involve spine elements
    def clean_rows_of(k, q):
        return [j for j in get_rows_by_kid().get(k, ())
                if j not in scrutiny and lens[j] > q]

    for k, q, e in spine_elem_hits(f_hit_spine):
        for j in clean_rows_of(k, q):
            extras["G1a"].append(
                {"key": raw_key[k], "value": int(S_concat[e]),
                 "read-txn": txns[int(R_txn[j])].get("value")})
    unobserved = (w_of_spine < 0) & ~f_hit_spine
    for k, q, e in spine_elem_hits(unobserved):
        for j in clean_rows_of(k, q):
            extras["unobserved-writer"].append(
                {"key": raw_key[k], "value": int(S_concat[e])})
    if multi_tk.size and S_concat.size:
        elem_tk = (w_of_spine << 32) | s_kid
        pos = np.clip(np.searchsorted(multi_tk, elem_tk), 0,
                      multi_tk.size - 1)
        m_hit = (multi_tk[pos] == elem_tk) & (w_of_spine >= 0)
        g1b_rows: set = set()
        for k, q, _ in spine_elem_hits(m_hit):
            g1b_rows.update(clean_rows_of(k, q))
        for j in sorted(g1b_rows):
            g1b_row(j)

    # ---- internal: own reads must reflect own earlier appends ----------
    if A_mi.size and n_reads:
        a3_order = np.argsort((A_txn << 32) | (A_kid << 12) | A_mi,
                              kind="stable")
        a3 = ((A_txn << 32) | (A_kid << 12) | A_mi)[a3_order]
        a3_val = A_val[a3_order]
        base = (R_txn << 32) | (R_kid << 12)
        lo = np.searchsorted(a3, base)
        hi = np.searchsorted(a3, base | R_mi)
        cb = hi - lo
        one = np.nonzero(cb == 1)[0]
        if one.size:
            v1 = a3_val[lo[one]]
            bad = np.where(lens[one] > 0, last_arr[one], -1) != v1
            for j, v in zip(one[bad].tolist(), v1[bad].tolist()):
                extras["internal"].append(
                    {"key": raw_key[int(R_kid[j])],
                     "read": list(payloads[j]),
                     "expected-suffix": [int(v)]})
        for j in np.nonzero(cb >= 2)[0].tolist():
            mine = a3_val[lo[j]:hi[j]].tolist()
            r = payloads[j]
            if list(r[-len(mine):]) != mine:
                extras["internal"].append(
                    {"key": raw_key[int(R_kid[j])], "read": list(r),
                     "expected-suffix": mine})

    # ---- dependency edges ----------------------------------------------
    edge_codes: list = []
    edge_src: list = []
    edge_dst: list = []

    def add_edges(code, src, dst):
        if len(src):
            edge_codes.append(np.full(len(src), code, np.int64))
            edge_src.append(np.asarray(src, np.int64))
            edge_dst.append(np.asarray(dst, np.int64))

    if S_concat.size:
        same = s_kid[1:] == s_kid[:-1]
        a, b = w_of_spine[:-1], w_of_spine[1:]
        keep = same & (a >= 0) & (b >= 0) & (a != b)
        add_edges(_TYPE_CODE[WW], a[keep], b[keep])
    if n_reads:
        nz = np.nonzero(R_isok & (lens > 0))[0]
        if nz.size:
            # out-of-range last elements (possible in corrupt off-spine
            # reads) cannot have a writer — and would collide across
            # keys in the 32-bit composite if not masked out
            in_range = (last_arr[nz] >= 0) & (last_arr[nz] < _MAX_VAL)
            nz = nz[in_range]
        if nz.size:
            w = writer_lookup((R_kid[nz] << 32) | last_arr[nz])
            keep = (w >= 0) & (w != R_txn[nz])
            add_edges(_TYPE_CODE[WR], w[keep], R_txn[nz][keep])
        has_next = R_isok & (lens < slen_of_kid[R_kid]) & \
            (soff_of_kid[R_kid] >= 0)
        nz = np.nonzero(has_next)[0]
        if nz.size:
            w = w_of_spine[soff_of_kid[R_kid[nz]] + lens[nz]]
            keep = (w >= 0) & (w != R_txn[nz])
            add_edges(_TYPE_CODE[RW], R_txn[nz][keep], w[keep])

    # ---- timing edges (vectorized add_timing_edges twin) ---------------
    order = np.where(node_inv >= 0, node_inv, node_pos)

    sequential_ok = True
    if n > 1:
        po = np.lexsort((node_pos, node_proc))
        same_p = node_proc[po][1:] == node_proc[po][:-1]
        prev_n, next_n = po[:-1][same_p], po[1:][same_p]
        add_edges(_TYPE_CODE[PROCESS], prev_n, next_n)
        viol = (node_inv[next_n] >= 0) & \
            (node_inv[next_n] < node_pos[prev_n])
        if viol.any():
            sequential_ok = False

    # realtime: a completion a links to every invocation i with
    # pos(a) < t_i < killer(a), where killer(a) is the first completion
    # that both invoked after a completed and has itself completed — the
    # same frontier-domination rule as add_timing_edges, closed-form
    comp_mask = (np.arange(n) < n_ok) & (node_inv >= 0)
    inv_mask = node_inv >= 0
    c_nodes = np.nonzero(comp_mask)[0]
    i_nodes = np.nonzero(inv_mask)[0]
    if c_nodes.size and i_nodes.size:
        c_pos = node_pos[c_nodes]
        by_inv = np.argsort(node_inv[c_nodes])
        inv_sorted = node_inv[c_nodes][by_inv]
        pos_by_inv = c_pos[by_inv]
        suffix_min = np.minimum.accumulate(pos_by_inv[::-1])[::-1]
        j = np.searchsorted(inv_sorted, c_pos, side="right")
        killer = np.r_[suffix_min, np.iinfo(np.int64).max][j]
        ti_order = np.argsort(node_inv[i_nodes])
        ts = node_inv[i_nodes][ti_order]
        i_sorted = i_nodes[ti_order]
        lo_i = np.searchsorted(ts, c_pos, side="right")
        hi_i = np.searchsorted(ts, killer, side="left")
        counts = np.maximum(hi_i - lo_i, 0)
        total = int(counts.sum())
        if total:
            src = np.repeat(c_nodes, counts)
            offs = np.arange(total) - np.repeat(
                np.cumsum(counts) - counts, counts)
            dst = i_sorted[np.repeat(lo_i, counts) + offs]
            add_edges(_TYPE_CODE[REALTIME], src, dst)

    cols = (np.concatenate(edge_codes) if edge_codes else np.zeros(0, np.int64),
            np.concatenate(edge_src) if edge_src else np.zeros(0, np.int64),
            np.concatenate(edge_dst) if edge_dst else np.zeros(0, np.int64))
    graph = Graph(n, edges=[], time_order=order if sequential_ok else None,
                  cols=cols)
    return graph, txns, extras, nk
