"""Kitchen-sink utilities (reference: jepsen/src/jepsen/util.clj).

Time here follows the reference's convention: every history ``time`` is a
*relative* monotonic nanosecond count from the start of the test
(util.clj:333-347), so histories are comparable and serializable.
"""
from __future__ import annotations

import contextlib
import contextvars
import json
import logging
import math
import os
import random
import threading
import time as _time
from concurrent.futures import ThreadPoolExecutor
from pathlib import Path
from typing import Any, Callable, Iterable, Sequence

logger = logging.getLogger("jepsen")

NANOS_PER_SECOND = 1_000_000_000
NANOS_PER_MILLI = 1_000_000
MICROS_PER_SECOND = 1_000_000


def majority(n: int) -> int:
    """Smallest integer strictly greater than half of n (util.clj:84)."""
    return n // 2 + 1


def atomic_write_json(path, value) -> None:
    """Durable atomic JSON write: tmp file + flush + fsync + rename, so
    readers never see a torn document and the content survives a crash.
    Shared by the durable fake cluster's members file and the
    membership heal's pre-op-set restore (doc/robustness.md)."""
    path = Path(path)
    tmp = path.with_name(path.name + ".tmp")
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(value, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def minority(n: int) -> int:
    """Largest number of nodes that is still a minority."""
    return (n - 1) // 2


def minority_third(n: int) -> int:
    """Largest m such that 3m < n, min 1 (util.clj:89). Useful for Raft-style
    systems where a third of nodes can fail without losing two quorums."""
    return max(1, (n - 1) // 3)


def parse_concurrency(s, n_nodes: int) -> int:
    """'30' -> 30; '3n' -> 3 * n_nodes; 'n' -> n_nodes (cli.clj:150-165).
    Single source of truth for the CLI and core.prepare_test."""
    if isinstance(s, int):
        return s
    s = str(s).strip()
    if s.endswith("n"):
        return int(s[:-1] or 1) * n_nodes
    return int(s)


def secs_to_nanos(s: float) -> int:
    return int(s * NANOS_PER_SECOND)


def nanos_to_secs(n: int) -> float:
    return n / NANOS_PER_SECOND

def ms_to_nanos(ms: float) -> int:
    return int(ms * NANOS_PER_MILLI)


def nanos_to_ms(n: int) -> float:
    return n / NANOS_PER_MILLI


def linear_time_nanos() -> int:
    """A monotonic clock in nanoseconds (util.clj:328)."""
    return _time.monotonic_ns()


# Relative test clock (util.clj:333-347). All history :time values are nanos
# since the enclosing with_relative_time block began.
_relative_time_origin: contextvars.ContextVar[int | None] = contextvars.ContextVar(
    "relative_time_origin", default=None
)


@contextlib.contextmanager
def with_relative_time():
    """Zeroes the test clock for the dynamic extent of this block."""
    token = _relative_time_origin.set(linear_time_nanos())
    try:
        yield
    finally:
        _relative_time_origin.reset(token)


def relative_time_nanos() -> int:
    origin = _relative_time_origin.get()
    if origin is None:
        raise RuntimeError("relative_time_nanos called outside with_relative_time")
    return linear_time_nanos() - origin


def relative_time_origin() -> int | None:
    return _relative_time_origin.get()


def sleep_nanos(n: int) -> None:
    if n > 0:
        _time.sleep(n / NANOS_PER_SECOND)


class ExceptionHolder:
    __slots__ = ("exc",)

    def __init__(self):
        self.exc: BaseException | None = None


# Heartbeat interval for bounded-wait joins: long enough to never spam
# a healthy run, short enough that a wedged thread is visible in the
# log well before anyone reaches for SIGKILL.
JOIN_HEARTBEAT_S = 30.0


def join_noisy(thread: threading.Thread, what: str,
               heartbeat_s: float = JOIN_HEARTBEAT_S,
               max_wait_s: float | None = None) -> bool:
    """Joins ``thread`` with the same wait-forever semantics as a bare
    ``join()``, but bounded per wait with a heartbeat log — the caller
    (often the orchestrator/scheduler thread) is never wedged SILENTLY,
    and a stuck thread is diagnosable from the log
    (no-unbounded-block, doc/static-analysis.md).

    ``max_wait_s`` bounds the TOTAL wait: once exhausted the thread is
    left running and False is returned — the wedge-proof-teardown mode
    (a poll thread stuck in remote I/O must not hold the run's teardown
    hostage). Returns True when the thread finished."""
    waited = 0.0
    while thread.is_alive():
        if max_wait_s is not None and waited >= max_wait_s:
            logger.warning("%s still running after %.0fs; abandoning "
                           "the wait", what, waited)
            return False
        step = heartbeat_s if max_wait_s is None \
            else min(heartbeat_s, max_wait_s - waited)
        thread.join(timeout=step)
        if thread.is_alive():
            waited += step
            logger.warning("%s still running after %.0fs", what, waited)
    return True


# thread-helper: sync-spawn(arg=0)
def real_pmap(fn: Callable, coll: Sequence) -> list:
    """Maps fn over coll in one thread per element; re-raises the first
    non-interrupt exception raised by any element (util.clj:65-78, dom-top's
    real-pmap). Unlike a pooled map, every element genuinely runs concurrently
    — required for barrier-synchronized DB setup across nodes."""
    coll = list(coll)
    if not coll:
        return []
    if len(coll) == 1:
        return [fn(coll[0])]
    results: list = [None] * len(coll)
    errors: list[BaseException | None] = [None] * len(coll)

    def run(i, x):
        try:
            results[i] = fn(x)
        except BaseException as e:  # noqa: BLE001 - mirrored to caller
            errors[i] = e

    threads = [threading.Thread(target=run, args=(i, x), daemon=True) for i, x in enumerate(coll)]
    for t in threads:
        t.start()
    for t in threads:
        join_noisy(t, f"real_pmap element {t.name}")
    for e in errors:
        if e is not None:
            raise e
    return results


# thread-helper: sync-spawn(arg=0)
def bounded_pmap(fn: Callable, coll: Iterable, bound: int | None = None) -> list:
    """Parallel map with a bounded worker pool (dom-top bounded-pmap)."""
    coll = list(coll)
    if not coll:
        return []
    bound = bound or min(32, len(coll))
    with ThreadPoolExecutor(max_workers=bound) as pool:
        return list(pool.map(fn, coll))


class JepsenTimeout(Exception):
    pass


# thread-helper: spawn(arg=2) — the child is abandoned at the deadline,
# so its block can't wedge the caller; ownership still transfers
def timeout(ms: float, dflt: Any, fn: Callable[[], Any]) -> Any:
    """Runs fn in a thread; if it doesn't complete within ms, returns dflt
    (util.clj:370-381). The straggler thread is abandoned (daemon).

    The caller's interpreter-worker identity rides along: code under a
    nemesis ``Timeout`` wrapper (or any thread-hopping helper) must
    still see ``interpreter.current_op_reaped()`` — the membership
    nemesis keys its leave-the-registry-entry-unhealed rule on it."""
    from jepsen_tpu.generator.interpreter import (
        adopt_worker_zombie, current_worker_zombie,
    )
    result: list = []
    error: list = []
    zombie = current_worker_zombie()

    def run():
        adopt_worker_zombie(zombie)
        try:
            result.append(fn())
        except BaseException as e:  # noqa: BLE001
            error.append(e)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(ms / 1000.0)
    if t.is_alive():
        return dflt
    if error:
        raise error[0]
    return result[0]


def backoff_delay(attempt: int, base_s: float = 0.05, cap_s: float = 5.0,
                  rng: random.Random | None = None) -> float:
    """Capped exponential backoff with FULL jitter (the AWS
    architecture-blog schedule): ``uniform(0, min(cap, base * 2**n))``.
    Full jitter decorrelates retry storms — N clients that failed
    together spread over the whole window instead of thundering back in
    lockstep. ``rng`` makes the schedule deterministic under a seeded
    ``random.Random`` for tests."""
    ceiling = min(cap_s, base_s * (2.0 ** attempt))
    return (rng or random).uniform(0.0, ceiling)


def retry_with_backoff(fn: Callable[[], Any], tries: int = 5,
                       base_s: float = 0.05, cap_s: float = 5.0,
                       rng: random.Random | None = None,
                       desc: str = "operation",
                       no_retry: tuple = ()) -> Any:
    """Runs fn up to ``tries`` times with :func:`backoff_delay` sleeps
    between attempts; raises the last exception when every try fails.
    Exception types in ``no_retry`` are terminal verdicts, re-raised
    immediately without burning the remaining attempts. The workhorse
    behind idempotent nemesis teardowns and fault-registry heal replay
    (doc/robustness.md)."""
    err: Exception | None = None
    for attempt in range(tries):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001
            if no_retry and isinstance(e, no_retry):
                raise
            err = e
            if attempt < tries - 1:
                delay = backoff_delay(attempt, base_s, cap_s, rng)
                logger.debug("%s failed (try %d/%d), backing off %.3fs: %r",
                             desc, attempt + 1, tries, delay, e)
                _time.sleep(delay)
    raise err


def retry(dt_seconds: float, fn: Callable[[], Any], retries: int | None = None) -> Any:
    """Retries fn every dt seconds until it returns non-exceptionally
    (util.clj:425-440)."""
    attempt = 0
    while True:
        try:
            return fn()
        except Exception as e:  # noqa: BLE001
            attempt += 1
            if retries is not None and attempt > retries:
                raise
            logger.debug("retrying after %r", e)
            _time.sleep(dt_seconds)


def await_fn(
    fn: Callable[[], Any],
    retry_interval: float = 1.0,
    log_interval: float = 10.0,
    log_message: str | None = None,
    timeout_s: float = 60.0,
) -> Any:
    """Invokes fn until it returns non-exceptionally (util.clj:383-424)."""
    t0 = _time.monotonic()
    last_log = t0
    while True:
        try:
            return fn()
        except Exception as e:  # noqa: BLE001
            now = _time.monotonic()
            if now - t0 > timeout_s:
                raise JepsenTimeout(f"await_fn timed out after {timeout_s}s") from e
            if now - last_log > log_interval:
                logger.info(log_message or f"still waiting: {e!r}")
                last_log = now
            _time.sleep(retry_interval)


def meh(fn: Callable[[], Any]) -> Any:
    """Runs fn, returning the exception instead of raising (util.clj:656)."""
    try:
        return fn()
    except Exception as e:  # noqa: BLE001
        return e


@contextlib.contextmanager
def with_thread_name(name: str):
    t = threading.current_thread()
    old = t.name
    t.name = name
    try:
        yield
    finally:
        t.name = old


def map_vals(fn: Callable, m: dict) -> dict:
    return {k: fn(v) for k, v in m.items()}


def map_keys(fn: Callable, m: dict) -> dict:
    return {fn(k): v for k, v in m.items()}


def rand_nth_empty(seq: Sequence, rng: random.Random | None = None):
    """Random element or None if empty."""
    if not seq:
        return None
    r = rng or random
    return seq[r.randrange(len(seq))]


def integer_interval_set_str(xs: Iterable[int]) -> str:
    """Compact string for a set of integers: '#{1-3 5 7-9}'
    (util.clj:629-654)."""
    xs = sorted(set(xs))
    if not xs:
        return "#{}"
    parts = []
    lo = prev = xs[0]
    for x in xs[1:]:
        if x == prev + 1:
            prev = x
            continue
        parts.append(f"{lo}" if lo == prev else f"{lo}-{prev}")
        lo = prev = x
    parts.append(f"{lo}" if lo == prev else f"{lo}-{prev}")
    return "#{" + " ".join(parts) + "}"


def op2str(op: dict) -> str:
    """Render an op like the reference log format (util.clj:205-243)."""
    proc = op.get("process")
    typ = op.get("type")
    f = op.get("f")
    value = op.get("value")
    err = op.get("error")
    s = f"{proc}\t{typ}\t{f}\t{value}"
    if err is not None:
        s += f"\t{err}"
    return s


def log_op(op: dict) -> None:
    logger.info(op2str(op))


def history_to_latencies(history: list[dict]) -> list[dict]:
    """Pairs invocations with completions, attaching :latency (nanos) to both,
    and :completion to the invocation (util.clj:700-735). Unmatched invokes
    get latency = max time seen."""
    history = [dict(op) for op in history]
    pending: dict[Any, int] = {}
    max_time = 0
    for i, op in enumerate(history):
        t = op.get("time", 0)
        max_time = max(max_time, t)
        if op.get("type") == "invoke":
            pending[op.get("process")] = i
        elif op.get("type") in ("ok", "fail", "info"):
            j = pending.pop(op.get("process"), None)
            if j is not None:
                latency = t - history[j].get("time", 0)
                history[j]["latency"] = latency
                op["latency"] = latency
                history[j]["completion"] = op
    for i in pending.values():
        history[i]["latency"] = max_time - history[i].get("time", 0)
    return history


def nemesis_intervals(history: list[dict], start_fs=("start",), stop_fs=("stop",)) -> list[tuple]:
    """Pairs up intervals of nemesis activity: [(start-op, stop-op-or-None)]
    (util.clj:736-783)."""
    intervals = []
    starts: list[dict] = []
    for op in history:
        if op.get("process") != "nemesis":
            continue
        if op.get("type") != "info":
            continue
        f = op.get("f")
        if f in start_fs:
            starts.append(op)
        elif f in stop_fs:
            if starts:
                intervals.append((starts.pop(0), op))
    for s in starts:
        intervals.append((s, None))
    return intervals


def quantile(sorted_xs: Sequence[float], q: float) -> float:
    """Nearest-rank quantile over a pre-sorted sequence."""
    if not sorted_xs:
        return math.nan
    i = min(len(sorted_xs) - 1, max(0, int(math.ceil(q * len(sorted_xs))) - 1))
    return sorted_xs[i]


def fraction(a: float, b: float) -> float:
    """a/b, but 1 when b is zero (checker.clj stats convention)."""
    return a / b if b else 1.0


class NamedLocks:
    """A map of named reentrant locks (util.clj:860-900)."""

    def __init__(self):
        self._locks: dict[Any, threading.RLock] = {}
        self._guard = threading.Lock()

    @contextlib.contextmanager
    def hold(self, name):
        with self._guard:
            lock = self._locks.setdefault(name, threading.RLock())
        with lock:
            yield


def int_key(k):
    """Digit-string → int, anything else unchanged. JSON round-trips
    (store history.jsonl → analyze re-check) stringify dict keys, so
    checkers comparing read maps against int-keyed config (bank
    accounts, transfer's Accounts model) normalize through this before
    judging — a stored history must reach the live verdict."""
    if isinstance(k, str):
        try:
            return int(k)
        except ValueError:
            return k
    return k


def int_keyed(d: dict) -> dict:
    return {int_key(k): v for k, v in d.items()}
