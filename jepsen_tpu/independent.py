"""Key-lifting: turn single-key workloads into many-key workloads.

Reference: jepsen/src/jepsen/independent.clj. Values become [k, v] tuples
(:21-29); generators run per-key either sequentially (:31-47) or with
groups of n threads working concurrently through a key rotation
(ConcurrentGenerator, :101-209); the checker splits the history per key and
checks each sub-history independently (:264-315).

This is the cleanest TPU win (SURVEY.md §2.6): per-key sub-histories are
embarrassingly parallel, so the lifted linearizability checker batches all
keys into one padded event tensor and runs the jitlin kernel under vmap —
sharded across devices by jepsen_tpu.parallel when a mesh is available
(BASELINE config 3).
"""
from __future__ import annotations

import logging
from dataclasses import dataclass, field, replace
from typing import Any, Callable, Iterable

from jepsen_tpu import generator as gen_mod
from jepsen_tpu.checker import Checker, check_safe, merge_valid
from jepsen_tpu.generator import Generator, PENDING, as_gen
from jepsen_tpu.utils import bounded_pmap

logger = logging.getLogger("jepsen.independent")


def tuple_value(k, v) -> list:
    """An independent [key, value] pair (independent.clj:21-29). Plain
    lists so histories stay JSON-serializable."""
    return [k, v]


def is_tuple_value(v) -> bool:
    return isinstance(v, (list, tuple)) and len(v) == 2


def tuple_gen(k, gen) -> Generator:
    """Lifts a generator's values into [k, v] tuples."""
    def lift(op):
        op = dict(op)
        op["value"] = tuple_value(k, op.get("value"))
        return op
    return gen_mod.Map(lift, gen)


@dataclass(frozen=True)
class SequentialGenerator(Generator):
    """One key at a time: exhaust gen_fn(k) for each k in order
    (independent.clj:31-47). ``keys`` may be infinite."""

    keys: "KeyStream" = field(compare=False)
    gen_fn: Callable = field(compare=False)
    idx: int = 0
    current: Any = None
    started: bool = False

    def _advance(self):
        k, ok = self.keys.get(self.idx)
        if not ok:
            return None
        return replace(self, idx=self.idx + 1,
                       current=tuple_gen(k, self.gen_fn(k)), started=True)

    def op(self, test, ctx):
        state = self if self.started else self._advance()
        while state is not None:
            g = as_gen(state.current)
            res = g.op(test, ctx) if g is not None else None
            if res is None:
                state = state._advance()
                continue
            op, g2 = res
            return (op, replace(state, current=g2))
        return None

    def update(self, test, ctx, event):
        if not self.started:
            return self
        g = as_gen(self.current)
        if g is None:
            return self
        return replace(self, current=g.update(test, ctx, event))


def sequential_generator(keys: Iterable, gen_fn: Callable[[Any], Any]) -> Generator:
    """(independent.clj:31-47)."""
    return SequentialGenerator(keys=KeyStream(keys), gen_fn=gen_fn)


class KeyStream:
    """Memoizing immutable view over a possibly-infinite key sequence, so
    ``concurrent_generator`` accepts ``itertools.count()`` the way the
    reference accepts infinite lazy seqs (independent.clj:211-236).
    Functional generator copies share one stream; the memo only grows, so
    ``get(i)`` is referentially transparent."""

    def __init__(self, iterable):
        self._it = iter(iterable)
        self._memo: list = []
        self._done = False

    def get(self, i: int):
        """(key, True) for index i, or (None, False) past the end."""
        while not self._done and len(self._memo) <= i:
            try:
                self._memo.append(next(self._it))
            except StopIteration:
                self._done = True
        if i < len(self._memo):
            return self._memo[i], True
        return None, False


@dataclass(frozen=True)
class ConcurrentGenerator(Generator):
    """Groups of n threads each work through their own sequence of keys
    concurrently (independent.clj:101-209). When a group's generator for
    its current key is exhausted, the group rotates to the next unclaimed
    key; the whole generator is exhausted when no keys remain and every
    group's generator is spent.
    """

    n: int                       # threads per group
    keys: KeyStream = field(compare=False)  # shared lazy key source
    gen_fn: Callable = field(compare=False)
    groups: tuple = ()           # ((threads-frozenset, key, gen) ...)
    next_idx: int = 0            # next unclaimed index into the stream

    def _claim(self, state, idx):
        """Next key from the stream, or (None, state) when exhausted."""
        k, ok = state.keys.get(idx)
        if not ok:
            return None, False
        return k, True

    def _init_groups(self, ctx):
        """Carve client threads into groups of n."""
        client_threads = sorted(t for t in ctx.workers if t != gen_mod.NEMESIS)
        groups = []
        idx = self.next_idx
        for i in range(0, len(client_threads) - self.n + 1, self.n):
            threads = frozenset(client_threads[i:i + self.n])
            k, ok = self.keys.get(idx)
            if ok:
                idx += 1
                groups.append((threads, k, tuple_gen(k, self.gen_fn(k))))
            else:
                groups.append((threads, None, None))
        return replace(self, next_idx=idx, groups=tuple(groups))

    def op(self, test, ctx):
        if not self.groups:
            inited = self._init_groups(ctx)
            if not inited.groups:
                return None
            return inited.op(test, ctx)
        candidates = []
        state = self
        for i, (threads, k, g) in enumerate(state.groups):
            # rotate exhausted groups to fresh keys
            while True:
                gg = as_gen(g)
                res = gg.op(test, ctx.restrict(threads)) if gg is not None else None
                if res is not None:
                    break
                k2, ok = state.keys.get(state.next_idx)
                if ok:
                    k = k2
                    g = tuple_gen(k, state.gen_fn(k))
                    groups = list(state.groups)
                    groups[i] = (threads, k, g)
                    state = replace(state, next_idx=state.next_idx + 1,
                                    groups=tuple(groups))
                else:
                    g = None
                    groups = list(state.groups)
                    groups[i] = (threads, None, None)
                    state = replace(state, groups=tuple(groups))
                    break
            if g is None:
                continue
            op, g2 = res
            candidates.append((op, g2, i))
        if not candidates:
            return None
        best = gen_mod.soonest_op_map(candidates)
        op, g2, i = best
        if op is PENDING:
            return (PENDING, state)
        groups = list(state.groups)
        threads, k, _ = groups[i]
        groups[i] = (threads, k, g2)
        return (op, replace(state, groups=tuple(groups)))

    def update(self, test, ctx, event):
        if not self.groups:
            return self
        p = event.get("process")
        t = gen_mod.NEMESIS if p == gen_mod.NEMESIS else ctx.thread_of(p)
        for i, (threads, k, g) in enumerate(self.groups):
            if t in threads and g is not None:
                gg = as_gen(g)
                if gg is None:
                    return self
                groups = list(self.groups)
                groups[i] = (threads, k,
                             gg.update(test, ctx.restrict(threads), event))
                return replace(self, groups=tuple(groups))
        return self


def concurrent_generator(n: int, keys: Iterable, gen_fn: Callable) -> Generator:
    """(independent.clj:211-236). n threads per key-group; len(client
    threads) should be a multiple of n. ``keys`` may be infinite
    (e.g. itertools.count())."""
    return ConcurrentGenerator(n=n, keys=KeyStream(keys), gen_fn=gen_fn)


def history_keys(history: list[dict]) -> list:
    """All keys in a lifted history (independent.clj:238-248)."""
    seen = {}
    for op in history:
        v = op.get("value")
        if is_tuple_value(v):
            seen.setdefault(_freeze_key(v[0]), v[0])
    return list(seen.values())


def _freeze_key(k):
    return tuple(k) if isinstance(k, list) else k


def subhistory(k, history: list[dict]) -> list[dict]:
    """The sub-history for key k, with inner values unwrapped
    (independent.clj:250-262)."""
    fk = _freeze_key(k)
    out = []
    for op in history:
        v = op.get("value")
        if is_tuple_value(v) and _freeze_key(v[0]) == fk:
            out.append({**op, "value": v[1]})
    return out


class IndependentChecker(Checker):
    """Lifts a checker over keys (independent.clj:264-315): splits the
    history, checks each key, merges validity and reports failures by key.

    Fast path: when the inner checker is a register LinearizableChecker and
    a device is wanted, all keys are encoded and batched through one
    vmapped jitlin kernel call (optionally sharded over a mesh); keys whose
    device verdict is unsound (frontier overflow + death) fall back to the
    exact CPU search.
    """

    def __init__(self, checker: Checker):
        self.checker = checker

    def name(self):
        return f"independent({self.checker.name()})"

    @staticmethod
    def _explain_key(test, sub_history, stream, step_py, spec, failure,
                     result: dict, key_opts: dict) -> None:
        """Anomaly forensics for one invalid key of the batched device
        lane: localize + shrink over the key's own stream, artifacts
        under independent/<k> (doc/observability.md "Anomaly
        forensics"). Never fails the batch."""
        try:
            from jepsen_tpu.checker import explain as explain_mod
            tmap = test if isinstance(test, dict) else {}
            forensics = explain_mod.explain_stream(
                stream, step_ids=spec.step_ids, step_py=step_py,
                init_state=spec.init_state, failure=failure,
                shrink_budget=explain_mod.shrink_budget(tmap),
                max_witness_ops=explain_mod.max_witness_ops(tmap))
            if forensics is None:
                return
            result["explain"] = {
                "first-anomaly-op": forensics["first_anomaly"]["op_index"],
                "witness-ops": len(forensics["witness"]["op_indices"]),
                "backend": forensics["backend"],
            }
            if test is not None and isinstance(test, dict) \
                    and test.get("name"):
                arts = explain_mod.write_artifacts(
                    test, sub_history, forensics, opts=key_opts)
                if arts:
                    result["explain"]["artifacts"] = sorted(
                        str(k) for k in arts)
        except Exception:  # noqa: BLE001 — forensics never mask a verdict
            logger.exception("per-key anomaly forensics failed")

    @staticmethod
    def _key_opts(opts, k):
        """Per-key opts: sub-checkers write under independent/<k> like the
        reference (independent.clj:287-292), so concurrent keys' artifacts
        (timeline.html, plots) can't overwrite each other."""
        d = opts.get("subdirectory")
        return {**opts,
                "subdirectory": "/".join(
                    filter(None, [d, "independent", str(k)])),
                "history-key": k}

    def check(self, test, history, opts):
        # the per-key split rides the run's shared history IR when one
        # is attachable (memoized subhistories view): composed lifted
        # checkers split the history once, not once per checker
        from jepsen_tpu import history_ir
        ir = history_ir.of(test, history)
        if ir is not None:
            from jepsen_tpu.history_ir import views
            keys, subs = views.subhistories(ir)
        else:
            keys = history_keys(history)
            subs = {_freeze_key(k): subhistory(k, history) for k in keys}
        if not keys:
            return {"valid?": True, "results": {}, "count": 0}

        batched = self._try_batched(test, keys, subs, opts)
        if batched is not None:
            results = batched
        else:
            # per-key sub-checks get ir_enabled: False — a sub-history
            # is not the run's history, so attaching it would evict the
            # run-level `_history_ir` (and serialize bounded_pmap on
            # the attach lock); the legacy per-key encode is exactly
            # what these small sub-checks should pay
            sub_test = ({**test, "ir_enabled": False}
                        if isinstance(test, dict) else test)
            pairs = list(subs.items())
            rs = bounded_pmap(
                lambda kv: check_safe(self.checker, sub_test, kv[1],
                                      self._key_opts(opts, kv[0])), pairs)
            results = {k: r for (k, _), r in zip(pairs, rs)}

        valid = merge_valid(r.get("valid?") for r in results.values())
        failures = sorted((str(k) for k, r in results.items()
                           if r.get("valid?") is not True), key=str)
        return {
            "valid?": valid,
            "count": len(results),
            "failures": failures,
            "results": {str(k): r for k, r in results.items()},
        }

    def _try_batched(self, test, keys, subs, opts):
        from jepsen_tpu.checker import Compose
        from jepsen_tpu.checker.linearizable import LinearizableChecker
        from jepsen_tpu.models import CASRegister

        # see through a Compose holding exactly one LinearizableChecker
        # (the register workload's linear+timeline composition): the
        # linear sub-checker takes the one batched kernel call, the rest
        # run per key, and per-key results merge like Compose would
        chk = self.checker
        lin_name, others = None, {}
        if isinstance(chk, Compose):
            lins = [(nm, c) for nm, c in chk.checkers.items()
                    if isinstance(c, LinearizableChecker)]
            if len(lins) != 1:
                return None
            lin_name, chk = lins[0]
            others = {nm: c for nm, c in self.checker.checkers.items()
                      if nm != lin_name}
        if not isinstance(chk, LinearizableChecker):
            return None
        if not isinstance(chk.model, CASRegister):
            return None
        accelerator = opts.get("accelerator", chk.accelerator)
        if accelerator == "cpu":
            return None
        # honor an explicit request for the exact WGL search: the batched
        # kernel is jitlin-only
        if opts.get("algorithm", chk.algorithm) == "wgl":
            return None
        try:
            from jepsen_tpu.checker import merge_valid
            from jepsen_tpu.checker.linear_cpu import check_stream
            from jepsen_tpu.ops.jitlin import verdict
            from jepsen_tpu.parallel import batch_check
            fkeys = list(subs.keys())
            # per-key encode via the checker's own _encoding so the
            # initial register value interns to the kernel's init state
            # (CASRegister(0) — single-key-acid — needs init id 1)
            encs = [chk._encoding(subs[fk]) for fk in fkeys]
            if any(e is None for e in encs):
                return None
            streams = [e[0] for e in encs]
            step_py, spec = encs[0][1], encs[0][2]
            # accelerator=auto lets batch_check's round-trip cost model
            # route small batches to the C++/CPU lane instead of eating
            # the device dispatch latency (parallel.pipeline.CostModel);
            # the mesh knobs shard the key axis over the devices
            # (doc/performance.md "Multi-device sharding")
            from jepsen_tpu import parallel as par
            sharded, mesh_devices = par.sharding_knobs(test, opts)
            # checker_sharded: False forces single-device, True skips
            # the cost gate (explicit mesh), None = auto (cost-gated)
            mesh = False if sharded is False else None
            if sharded is True:
                mesh = par.auto_mesh(mesh_devices)
            outcomes = batch_check(
                streams, capacity=chk.capacity,
                kernel=chk._tpu_kernel(spec),
                accelerator="auto" if accelerator == "auto" else "device",
                mesh=mesh, mesh_devices=mesh_devices)
            route = par.last_route()
            backend = {"cpu": "jitlin-cpu(routed)",
                       "mesh": "jitlin-tpu-sharded"}.get(route,
                                                         "jitlin-tpu")
            from jepsen_tpu.checker import explain as explain_mod
            explain_on = explain_mod.enabled(test, opts)
            results = {}
            invalid: list[tuple] = []
            for fk, stream, (alive, died, ovf, peak) in zip(fkeys, streams, outcomes):
                v = verdict(alive, ovf)
                if v == "unknown":
                    res = check_stream(stream, step=step_py,
                                       init_state=spec.init_state)
                    results[fk] = {"valid?": res.valid,
                                   "algorithm": "jitlin-cpu(fallback)"}
                    v, failure = res.valid, res
                else:
                    results[fk] = {"valid?": v, "algorithm": backend,
                                   "configs-max": peak}
                    failure = None
                if v is False and explain_on:
                    invalid.append((fk, stream, failure))
            if invalid:
                # per-key anomaly forensics — an invalid key is rare, so
                # the localization dispatches stay off the happy path
                import jax
                if jax.process_count() > 1:
                    # multi-host: split the localizations across
                    # processes, allgather only the per-key positions
                    # (no witness/artifacts — every host would race on
                    # the shared store dir)
                    from jepsen_tpu.parallel.distributed import (
                        localize_keys_distributed)
                    idx = {fk: i for i, fk in enumerate(fkeys)}
                    found = localize_keys_distributed(
                        streams, [idx[fk] for fk, _, _ in invalid],
                        step_ids=spec.step_ids, step_py=step_py,
                        init_state=spec.init_state)
                    for fk, _, _ in invalid:
                        hit = found.get(idx[fk])
                        if hit is not None:
                            results[fk]["explain"] = {
                                "first-anomaly-op": hit[1],
                                "backend": "matrix-bisect-distributed"}
                else:
                    for fk, stream, failure in invalid:
                        # full forensics + artifacts under the same
                        # independent/<k> lift the per-key lane uses
                        self._explain_key(test, subs[fk], stream,
                                          step_py, spec, failure,
                                          results[fk],
                                          self._key_opts(opts, fk))
            if lin_name is None:
                return results
            pairs = list(subs.items())
            other_rs = bounded_pmap(
                lambda kv: {nm: check_safe(c, test, kv[1],
                                           self._key_opts(opts, kv[0]))
                            for nm, c in others.items()}, pairs)
            merged = {}
            for (fk, _), extra in zip(pairs, other_rs):
                sub = {lin_name: results[fk], **extra}
                merged[fk] = {
                    "valid?": merge_valid(r.get("valid?")
                                          for r in sub.values()),
                    **sub,
                }
            return merged
        except Exception:  # noqa: BLE001
            logger.exception("batched independent check failed; "
                             "falling back to per-key")
            return None


def checker(inner: Checker) -> Checker:
    return IndependentChecker(inner)
