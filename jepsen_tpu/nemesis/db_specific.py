"""DB-specific fault vocabularies: the named nemesis families the deep
reference suites ship beyond the generic kill/pause/partition/clock
packages.

* Cockroach's clock-skew family — ``strobe-skews``, ``small-skews``
  (100 ms), ``subcritical-skews`` (200 ms), ``critical-skews`` (250 ms,
  the commit-wait boundary), ``big-skews``/``huge-skews`` (0.5 s / 5 s,
  network-slowed so the cluster survives the jump) — plus the
  ``restarting`` and ``slowing`` combinators and ``startkill``
  (reference: cockroachdb/src/jepsen/cockroach/nemesis.clj:110-141,
  152-267).
* Yugabyte's role-targeted process nemesis: master-vs-tserver
  start/stop/kill/pause/resume on random node subsets (reference:
  yugabyte/src/yugabyte/nemesis.clj:12-44).

Everything is packaged in the combined.clj package shape so suites wire
them through ``--fault`` exactly like the generic families: a suite
passes ``fault_packages`` (name → builder) and the combined assembler
picks them up (see jepsen_tpu.nemesis.combined.nemesis_package).
"""
from __future__ import annotations

import random

from jepsen_tpu import generator as gen
from jepsen_tpu.nemesis import Nemesis
from jepsen_tpu.nemesis.combined import DEFAULT_INTERVAL
from jepsen_tpu.utils import real_pmap


def _start_stop_gen(interval, start_f="start", stop_f="stop"):
    return gen.stagger(interval, gen.cycle(gen.Seq([
        {"type": "info", "f": start_f, "value": None},
        {"type": "info", "f": stop_f, "value": None},
    ])))


def _on_nodes(test, nodes, fn):
    """{node: result-or-error-string} via per-node control sessions (the
    c/on-nodes shape: failures become values, not raised exceptions)."""
    from jepsen_tpu import control

    def one(node):
        try:
            return node, control.on(node, test, lambda: fn(node))
        except Exception as e:  # noqa: BLE001 — mirrored on-nodes contract
            return node, f"{type(e).__name__}: {e}"

    return dict(real_pmap(one, list(nodes)))


class Restarting(Nemesis):
    """Wraps a nemesis so that every ``stop`` op additionally restarts
    the DB on all nodes — skewed clocks crash strict stores, and the
    family's contract is "on stop, nodes come back"
    (cockroach/nemesis.clj:175-200 ``restarting``)."""

    def __init__(self, inner: Nemesis, db):
        self.inner = inner
        self.db = db

    def fs(self):
        return self.inner.fs()

    def setup(self, test):
        self.inner.setup(test)
        return self

    def invoke(self, test, op):
        out = self.inner.invoke(test, op)
        if op.get("f") == "stop":
            started = _on_nodes(
                test, test.get("nodes") or [],
                lambda node: (self.db.start(test, node), "started")[1])
            out = {**out, "value": [out.get("value"), started]}
        return out

    def teardown(self, test):
        self.inner.teardown(test)


class BumpTime(Nemesis):
    """On ``start``, bumps the clock by dt seconds on a random half of
    the nodes (coin flip per node, millisecond precision); on ``stop``,
    resets all clocks (cockroach/nemesis.clj:232-252 ``bump-time``)."""

    def __init__(self, dt_s: float, rng: random.Random | None = None):
        self.dt_s = dt_s
        self.rng = rng or random.Random()

    def fs(self):
        return {"start", "stop"}

    def setup(self, test):
        from jepsen_tpu.nemesis import time as nt
        _on_nodes(test, test.get("nodes") or [],
                  lambda node: (nt.install(), nt.reset_time()))
        return self

    def invoke(self, test, op):
        from jepsen_tpu.nemesis import time as nt
        if op.get("f") == "start":
            ms = int(self.dt_s * 1000)
            picks = {n: (self.rng.random() < 0.5)
                     for n in (test.get("nodes") or [])}
            res = _on_nodes(
                test, picks,
                lambda node: (nt.bump_time(ms), self.dt_s)[1]
                if picks[node] else 0)
        else:
            res = _on_nodes(test, test.get("nodes") or [],
                            lambda node: (nt.reset_time(), "reset")[1])
        return {**op, "type": "info", "value": res}

    def teardown(self, test):
        from jepsen_tpu.nemesis import time as nt
        _on_nodes(test, test.get("nodes") or [],
                  lambda node: nt.reset_time())


class StrobeTime(Nemesis):
    """On ``start``, strobes the clock between now and delta ms ahead,
    flipping every period ms for duration seconds, on every node
    (cockroach/nemesis.clj:202-230 ``strobe-time``/``strobe-skews``)."""

    def __init__(self, delta_ms: int = 200, period_ms: int = 10,
                 duration_s: int = 10):
        self.delta_ms = delta_ms
        self.period_ms = period_ms
        self.duration_s = duration_s

    def fs(self):
        return {"start", "stop"}

    def setup(self, test):
        from jepsen_tpu.nemesis import time as nt
        _on_nodes(test, test.get("nodes") or [],
                  lambda node: (nt.install(), nt.reset_time()))
        return self

    def invoke(self, test, op):
        from jepsen_tpu.nemesis import time as nt
        if op.get("f") == "start":
            res = _on_nodes(
                test, test.get("nodes") or [],
                lambda node: (nt.strobe_time(self.delta_ms, self.period_ms,
                                             self.duration_s), "strobed")[1])
        else:
            res = None
        return {**op, "type": "info", "value": res}

    def teardown(self, test):
        from jepsen_tpu.nemesis import time as nt
        _on_nodes(test, test.get("nodes") or [],
                  lambda node: nt.reset_time())


class Slowing(Nemesis):
    """Wraps a nemesis: before its ``start`` the network slows by dt
    seconds of added latency; after its ``stop`` speeds are restored
    (cockroach/nemesis.clj:152-173 ``slowing`` — big/huge skews only
    survive because the cluster is slowed around them)."""

    def __init__(self, inner: Nemesis, dt_s: float):
        self.inner = inner
        self.dt_s = dt_s

    def fs(self):
        return self.inner.fs()

    def setup(self, test):
        net = test.get("net")
        if net is not None:
            net.fast(test)
        self.inner.setup(test)
        return self

    def invoke(self, test, op):
        net = test.get("net")
        if op.get("f") == "start" and net is not None:
            net.slow(test, mean_ms=self.dt_s * 1000)
        out = self.inner.invoke(test, op)
        if op.get("f") == "stop" and net is not None:
            net.fast(test)
        return out

    def teardown(self, test):
        net = test.get("net")
        if net is not None:
            net.fast(test)
        self.inner.teardown(test)


class StartKill(Nemesis):
    """``start`` kills the DB on n shuffled nodes; ``stop`` restarts it
    there (cockroach/nemesis.clj:135-141 ``startkill`` via
    node-start-stopper)."""

    def __init__(self, db, n: int = 1, rng: random.Random | None = None):
        self.db = db
        self.n = n
        self.rng = rng or random.Random()
        self.targets: list = []

    def fs(self):
        return {"start", "stop"}

    def invoke(self, test, op):
        if op.get("f") == "start":
            nodes = list(test.get("nodes") or [])
            self.rng.shuffle(nodes)
            self.targets = nodes[: self.n]
            res = _on_nodes(test, self.targets,
                            lambda node: (self.db.kill(test, node),
                                          "killed")[1])
        else:
            res = _on_nodes(test, self.targets or test.get("nodes") or [],
                            lambda node: (self.db.start(test, node),
                                          "started")[1])
        return {**op, "type": "info", "value": res}


def _skew_package(opts: dict, name: str, client: Nemesis,
                  slow_s: float | None = None) -> dict:
    db = opts.get("db")
    interval = opts.get("interval", DEFAULT_INTERVAL)
    nem: Nemesis = Restarting(client, db) if db is not None else client
    if slow_s is not None:
        nem = Slowing(nem, slow_s)
    return {
        "nemesis": nem,
        "generator": _start_stop_gen(interval),
        "final_generator": gen.Seq([{"type": "info", "f": "stop",
                                     "value": None}]),
        "perf": {"name": name, "fs": {"start", "stop"},
                 "start": {"start"}, "stop": {"stop"}},
    }


def cockroach_fault_packages() -> dict:
    """--fault name → package builder, the cockroach skew/kill family
    (cockroach/nemesis.clj:110-141, 201-271)."""
    def skew(name, offset_s, slow_s=None):
        return lambda opts: _skew_package(
            opts, name, BumpTime(offset_s), slow_s)

    return {
        "skew-small": skew("small-skews", 0.100),
        "skew-subcritical": skew("subcritical-skews", 0.200),
        "skew-critical": skew("critical-skews", 0.250),
        "skew-big": skew("big-skews", 0.5, slow_s=0.5),
        "skew-huge": skew("huge-skews", 5.0, slow_s=5.0),
        "skew-strobe": lambda opts: _skew_package(
            opts, "strobe-skews", StrobeTime(200, 10, 10)),
        "startkill": lambda opts: {
            "nemesis": StartKill(opts.get("db"), 1),
            "generator": _start_stop_gen(
                opts.get("interval", DEFAULT_INTERVAL)),
            "final_generator": gen.Seq([{"type": "info", "f": "stop",
                                         "value": None}]),
            "perf": {"name": "startkill", "fs": {"start", "stop"},
                     "start": {"start"}, "stop": {"stop"}},
        },
    }


# ---------------------------------------------------------------------------
# yugabyte: master / tserver role-targeted process faults
# ---------------------------------------------------------------------------

class RoleProcess(Nemesis):
    """start/stop/kill/pause/resume one DB *role* (yugabyte master vs
    tserver) on random node subsets (yugabyte/nemesis.clj:12-44).

    Destructive verbs target a random nonempty subset of the role's
    nodes; ``start``/``resume`` go to all of them. The DB supplies
    ``role_nodes(test, role)`` and per-role methods
    (``kill_master(test, node)``, ...); absent a per-role method the
    generic Process/Pause verb runs with the role recorded in the value.
    """

    VERBS = ("start", "stop", "kill", "pause", "resume")

    def __init__(self, db, roles=("master", "tserver"),
                 rng: random.Random | None = None):
        self.db = db
        self.roles = tuple(roles)
        self.rng = rng or random.Random()

    def fs(self):
        return {f"{v}-{r}" for v in self.VERBS for r in self.roles}

    def _role_nodes(self, test, role):
        fn = getattr(self.db, "role_nodes", None)
        if fn is not None:
            return list(fn(test, role))
        return list(test.get("nodes") or [])

    def invoke(self, test, op):
        verb, _, role = op.get("f", "").partition("-")
        nodes = self._role_nodes(test, role)
        if verb in ("stop", "kill", "pause") and nodes:
            k = self.rng.randint(1, len(nodes))
            nodes = self.rng.sample(nodes, k)
        method = getattr(self.db, f"{verb}_{role}", None)

        def one(node):
            if method is not None:
                return method(test, node)
            return getattr(self.db, verb)(test, node)

        res = _on_nodes(test, nodes, one)
        return {**op, "type": "info", "value": {"role": role, verb: res}}


def role_fault_package(opts: dict, role: str, verb: str) -> dict:
    """One --fault entry, e.g. kill-master: cycles destroy/heal on the
    role with the package interval; final op heals the role."""
    heal = "resume" if verb == "pause" else "start"
    interval = opts.get("interval", DEFAULT_INTERVAL)
    return {
        "nemesis": RoleProcess(opts.get("db"), roles=(role,)),
        "generator": _start_stop_gen(interval, f"{verb}-{role}",
                                     f"{heal}-{role}"),
        "final_generator": gen.Seq([{"type": "info", "f": f"{heal}-{role}",
                                     "value": None}]),
        "perf": {"name": f"{verb}-{role}",
                 "fs": {f"{verb}-{role}", f"{heal}-{role}"},
                 "start": {f"{verb}-{role}"}, "stop": {f"{heal}-{role}"}},
    }


def yugabyte_fault_packages() -> dict:
    """--fault name → package builder for the master/tserver process
    faults (yugabyte/nemesis.clj:12-44, core.clj nemeses map)."""
    out = {}
    for role in ("master", "tserver"):
        for verb in ("kill", "stop", "pause"):
            out[f"{verb}-{role}"] = (
                lambda opts, r=role, v=verb: role_fault_package(opts, r, v))
    return out
