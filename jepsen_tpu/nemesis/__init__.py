"""Fault injection (reference: jepsen/src/jepsen/nemesis.clj).

A Nemesis is a special client for failure modes: setup/invoke/teardown
(nemesis.clj:11-16), plus Reflection (``fs``) so composition can route ops
by :f (:18-21). The *grudge* functions here are pure set math over node
lists (nemesis.clj:108-281) — fully unit-testable without a cluster; the
partitioner applies grudges via the net layer over SSH.
"""
from __future__ import annotations

import logging
import random
from typing import Any, Callable, Iterable

from jepsen_tpu.utils import majority

logger = logging.getLogger("jepsen.nemesis")


class Nemesis:
    def setup(self, test: dict) -> "Nemesis":
        return self

    def invoke(self, test: dict, op: dict) -> dict:
        raise NotImplementedError

    def teardown(self, test: dict) -> None:
        pass

    def fs(self) -> set:
        """The set of :f values this nemesis handles (Reflection,
        nemesis.clj:18-21)."""
        return set()

    def self_recorded_kinds(self) -> set:
        """Fault kinds (faults.KINDS) this nemesis books into the
        durable registry ITSELF — richer records than the interpreter's
        generic pre-fire snapshot (e.g. the membership nemesis records
        the pre-op member set and marks entries healed at resolution).
        The interpreter's NemesisWorker skips its own record/heal-mark
        for these kinds so every fault lands exactly once."""
        return set()


def self_recorded_kinds(nemesis) -> set:
    """``nemesis.self_recorded_kinds()`` with tolerance for bare duck-
    typed nemeses (tests wire plain objects) — absent or broken means
    "none": the generic registry path stays on."""
    fn = getattr(nemesis, "self_recorded_kinds", None)
    if not callable(fn):
        return set()
    try:
        return set(fn() or ())
    except Exception:  # noqa: BLE001 — reflection must never block an op
        logger.exception("self_recorded_kinds() raised; assuming none")
        return set()


class Noop(Nemesis):
    """Does nothing (jepsen.nemesis/noop)."""

    def invoke(self, test, op):
        return {**op, "type": "info"}


class ValidateNemesis(Nemesis):
    """Checks op shapes around an inner nemesis (nemesis.clj:49-90)."""

    def __init__(self, nemesis: Nemesis):
        self.nemesis = nemesis

    def setup(self, test):
        inner = self.nemesis.setup(test)
        if inner is None:
            raise ValueError(f"{self.nemesis!r}.setup returned None")
        return ValidateNemesis(inner)

    def invoke(self, test, op):
        if op.get("type") != "invoke" and op.get("type") != "info":
            raise ValueError(f"nemesis op has type {op.get('type')!r}")
        completion = self.nemesis.invoke(test, op)
        if not isinstance(completion, dict):
            raise ValueError(f"nemesis completion {completion!r} is not an op")
        return completion

    def teardown(self, test):
        self.nemesis.teardown(test)

    def fs(self):
        return self.nemesis.fs()

    def self_recorded_kinds(self):
        return self_recorded_kinds(self.nemesis)


def validate(nemesis: Nemesis) -> Nemesis:
    return ValidateNemesis(nemesis)


class Timeout(Nemesis):
    """Gives up on ops that take longer than dt seconds
    (nemesis.clj:92-106)."""

    def __init__(self, dt_seconds: float, nemesis: Nemesis):
        self.dt = dt_seconds
        self.nemesis = nemesis

    def setup(self, test):
        return Timeout(self.dt, self.nemesis.setup(test))

    def invoke(self, test, op):
        from jepsen_tpu.utils import timeout as timeout_fn
        res = timeout_fn(self.dt * 1000, None, lambda: self.nemesis.invoke(test, op))
        if res is None:
            return {**op, "type": "info", "value": "timeout"}
        return res

    def teardown(self, test):
        self.nemesis.teardown(test)

    def fs(self):
        return self.nemesis.fs()

    def self_recorded_kinds(self):
        return self_recorded_kinds(self.nemesis)


# ---------------------------------------------------------------------------
# Grudge math: pure functions from node lists to partition maps
# (a *grudge* maps each node -> collection of nodes it should snub)
# ---------------------------------------------------------------------------

def bisect(coll: list) -> tuple[list, list]:
    """Splits a collection in half; first half smaller (nemesis.clj:108-112)."""
    coll = list(coll)
    mid = len(coll) // 2
    return coll[:mid], coll[mid:]


def split_one(coll: list, rng: random.Random | None = None) -> tuple[list, list]:
    """Splits off one random node: ([n], rest) (nemesis.clj:114-118)."""
    coll = list(coll)
    r = rng or random
    i = r.randrange(len(coll))
    return [coll[i]], coll[:i] + coll[i + 1:]


def complete_grudge(components: Iterable[list]) -> dict:
    """Given components, every node snubs every node outside its component
    (nemesis.clj:120-132)."""
    components = [list(c) for c in components]
    all_nodes = [n for c in components for n in c]
    grudge = {}
    for c in components:
        others = [n for n in all_nodes if n not in c]
        for n in c:
            grudge[n] = set(others)
    return grudge


def invert_grudge(grudge: dict) -> dict:
    """Takes a grudge of what to cut, returns what to *keep* cut if you
    invert connectivity (nemesis.clj:134-142)."""
    nodes = set(grudge)
    return {n: nodes - set(snubbed) - {n} for n, snubbed in grudge.items()}


def bridge(nodes: list) -> dict:
    """Two halves connected only through a single bridge node
    (nemesis.clj:144-155)."""
    nodes = list(nodes)
    mid = len(nodes) // 2
    bridge_node = nodes[mid]
    halves = (nodes[:mid], nodes[mid + 1:])
    grudge = {}
    for i, half in enumerate(halves):
        other = halves[1 - i]
        for n in half:
            grudge[n] = set(other)
    grudge[bridge_node] = set()
    return grudge


def majorities_ring_perfect(nodes: list) -> dict:
    """Every node sees a majority, but no node sees the *same* majority:
    node i sees the (majority-sized) window centered on i in a ring
    (nemesis.clj:202-216)."""
    nodes = list(nodes)
    n = len(nodes)
    m = majority(n)
    half = (m - 1) // 2
    grudge = {}
    for i, node in enumerate(nodes):
        visible = {nodes[(i + d) % n] for d in range(-half, half + 1)}
        # if majority is even-sized, extend forward
        d = half + 1
        while len(visible) < m:
            visible.add(nodes[(i + d) % n])
            d += 1
        grudge[node] = set(nodes) - visible
    return grudge


def majorities_ring_stochastic(nodes: list, rng: random.Random | None = None) -> dict:
    """Random variant: each node sees a random majority including itself
    (nemesis.clj:218-258). Unlike the perfect ring this may isolate some
    links asymmetrically; grudges are symmetrized by union."""
    nodes = list(nodes)
    r = rng or random
    n = len(nodes)
    m = majority(n)
    visible: dict[Any, set] = {}
    for node in nodes:
        others = [x for x in nodes if x != node]
        r.shuffle(others)
        visible[node] = {node} | set(others[: m - 1])
    grudge = {node: set(nodes) - visible[node] for node in nodes}
    # symmetrize: if a snubs b, b snubs a
    for a in nodes:
        for b in grudge[a]:
            grudge[b].add(a)
    return grudge


def partition_halves_grudge(nodes: list) -> dict:
    return complete_grudge(bisect(nodes))


def partition_random_halves_grudge(nodes: list, rng=None) -> dict:
    nodes = list(nodes)
    (rng or random).shuffle(nodes)
    return complete_grudge(bisect(nodes))


def partition_random_node_grudge(nodes: list, rng=None) -> dict:
    return complete_grudge(split_one(nodes, rng))


# ---------------------------------------------------------------------------
# Partitioner nemesis (applies grudges via the net layer)
# ---------------------------------------------------------------------------

class Partitioner(Nemesis):
    """start-partition / stop-partition via a grudge function
    (nemesis.clj:157-183). grudge_fn(test, nodes, op_value) -> grudge."""

    def __init__(self, grudge_fn: Callable | None = None):
        self.grudge_fn = grudge_fn

    def setup(self, test):
        net = test.get("net")
        if net is not None:
            net.heal(test)
        return self

    def fs(self):
        return {"start-partition", "stop-partition", "start", "stop"}

    def _grudge(self, test, op):
        v = op.get("value")
        if isinstance(v, dict):
            return v  # explicit grudge
        nodes = list(test.get("nodes") or [])
        if self.grudge_fn is not None:
            return self.grudge_fn(test, nodes, v)
        if v == "majority":
            return partition_random_halves_grudge(nodes)
        if v == "one":
            return partition_random_node_grudge(nodes)
        if v == "majorities-ring":
            return majorities_ring_perfect(nodes)
        return partition_halves_grudge(nodes)

    def invoke(self, test, op):
        f = op.get("f")
        net = test.get("net")
        if f in ("start", "start-partition"):
            grudge = self._grudge(test, op)
            if net is not None:
                net.drop_all(test, grudge)
            return {**op, "type": "info",
                    "value": ["isolated", {k: sorted(v) for k, v in grudge.items()}]}
        if f in ("stop", "stop-partition"):
            if net is not None:
                net.heal(test)
            return {**op, "type": "info", "value": "network-healed"}
        return {**op, "type": "info", "value": ["unknown-f", f]}

    def teardown(self, test):
        net = test.get("net")
        if net is not None:
            net.heal(test)


def partitioner(grudge_fn=None) -> Nemesis:
    return Partitioner(grudge_fn)


def partition_halves() -> Nemesis:
    return Partitioner(lambda test, nodes, v: partition_halves_grudge(nodes))


def partition_random_halves() -> Nemesis:
    return Partitioner(lambda test, nodes, v: partition_random_halves_grudge(nodes))


def partition_random_node() -> Nemesis:
    return Partitioner(lambda test, nodes, v: partition_random_node_grudge(nodes))


def partition_majorities_ring() -> Nemesis:
    return Partitioner(lambda test, nodes, v: majorities_ring_perfect(nodes))


# ---------------------------------------------------------------------------
# Composition (nemesis.clj:285-428)
# ---------------------------------------------------------------------------

class FMap(Nemesis):
    """Rewrites op :f values through a mapping before the inner nemesis sees
    them (nemesis.clj:285-327)."""

    def __init__(self, f_mapping: dict, nemesis: Nemesis):
        self.f_mapping = f_mapping
        self.inverse = {v: k for k, v in f_mapping.items()}
        self.nemesis = nemesis

    def setup(self, test):
        return FMap(self.f_mapping, self.nemesis.setup(test))

    def invoke(self, test, op):
        f = op.get("f")
        inner_f = self.inverse.get(f, f)
        completion = self.nemesis.invoke(test, {**op, "f": inner_f})
        return {**completion, "f": f}

    def teardown(self, test):
        self.nemesis.teardown(test)

    def fs(self):
        return {self.f_mapping.get(f, f) for f in self.nemesis.fs()}

    def self_recorded_kinds(self):
        # kinds are classify() groups, not :f names — no renaming
        return self_recorded_kinds(self.nemesis)


def f_map(f_mapping: dict, nemesis: Nemesis) -> Nemesis:
    return FMap(f_mapping, nemesis)


class Compose(Nemesis):
    """Routes ops to the sub-nemesis whose fs() claims the op's :f
    (Reflection-based compose, nemesis.clj:329-428)."""

    def __init__(self, nemeses: list[Nemesis]):
        self.nemeses = list(nemeses)

    def setup(self, test):
        return Compose([n.setup(test) for n in self.nemeses])

    def _route(self, f):
        for n in self.nemeses:
            if f in n.fs():
                return n
        return None

    def invoke(self, test, op):
        n = self._route(op.get("f"))
        if n is None:
            raise ValueError(
                f"no nemesis handles f={op.get('f')!r} "
                f"(available: {[sorted(map(str, x.fs())) for x in self.nemeses]})"
            )
        return n.invoke(test, op)

    def teardown(self, test):
        for n in self.nemeses:
            n.teardown(test)

    def fs(self):
        out = set()
        for n in self.nemeses:
            out |= n.fs()
        return out

    def self_recorded_kinds(self):
        out = set()
        for n in self.nemeses:
            out |= self_recorded_kinds(n)
        return out


def compose(nemeses: Iterable[Nemesis]) -> Nemesis:
    return Compose(list(nemeses))


class NodeStartStopper(Nemesis):
    """Runs start/stop functions on targeted nodes (node-start-stopper,
    nemesis.clj:452-495). targeter(test, nodes) -> nodes to affect."""

    def __init__(self, targeter: Callable, start_fn: Callable, stop_fn: Callable,
                 start_f: str = "start", stop_f: str = "stop"):
        self.targeter = targeter
        self.start_fn = start_fn
        self.stop_fn = stop_fn
        self.start_f = start_f
        self.stop_f = stop_f
        self.affected: list = []

    def fs(self):
        return {self.start_f, self.stop_f}

    def invoke(self, test, op):
        from jepsen_tpu.utils import real_pmap
        f = op.get("f")
        if f == self.start_f:
            targets = list(self.targeter(test, list(test.get("nodes") or [])))
            real_pmap(lambda n: self.start_fn(test, n), targets)
            self.affected = targets
            return {**op, "type": "info", "value": [f, targets]}
        if f == self.stop_f:
            targets = self.affected or list(test.get("nodes") or [])
            real_pmap(lambda n: self.stop_fn(test, n), targets)
            self.affected = []
            return {**op, "type": "info", "value": [f, targets]}
        return {**op, "type": "info", "value": ["unknown-f", f]}


def hammer_time(targeter=None, process: str = "") -> Nemesis:
    """SIGSTOP/SIGCONT a process on targeted nodes (nemesis.clj:497-511)."""
    from jepsen_tpu import control

    targeter = targeter or (lambda test, nodes: [random.choice(nodes)])

    def start(test, node):
        control.on(node, test, lambda: control.exec_("killall", "-s", "STOP", process))

    def stop(test, node):
        control.on(node, test, lambda: control.exec_("killall", "-s", "CONT", process))

    return NodeStartStopper(targeter, start, stop, "start-pause", "stop-pause")


def set_time(test, node, unix_seconds: float) -> None:
    """Sets the wall clock on a node via ``date`` (nemesis.clj:430-433
    set-time!) — the coarse sibling of the compiled bump-time utility
    (nemesis/time.py)."""
    from jepsen_tpu import control
    control.on(node, test,
               lambda: control.exec_("date", "-s", f"@{int(unix_seconds)}"))


class ClockScrambler(Nemesis):
    """Randomizes node clocks within ±limit seconds of now
    (nemesis.clj:435-450); teardown restores approximately-correct
    time. The C-utility ClockNemesis (nemesis/time.py) is the precise
    replacement; this is the reference's original coarse scrambler."""

    def __init__(self, limit_s: int):
        self.limit_s = limit_s

    def fs(self):
        return {"scramble-clock"}

    def invoke(self, test, op):
        import time as _time
        from jepsen_tpu.utils import real_pmap
        nodes = op.get("value") or list(test.get("nodes") or [])

        def scramble(node):
            offset = random.randint(-self.limit_s, self.limit_s)
            set_time(test, node, _time.time() + offset)
            return offset
        offsets = real_pmap(scramble, nodes)
        return {**op, "type": "info",
                "value": dict(zip(nodes, offsets))}

    def teardown(self, test):
        import time as _time
        from jepsen_tpu.utils import real_pmap
        real_pmap(lambda n: set_time(test, n, _time.time()),
                  list(test.get("nodes") or []))


def clock_scrambler(limit_s: int) -> Nemesis:
    return ClockScrambler(limit_s)


class TruncateFile(Nemesis):
    """Truncates a file on targeted nodes by a random number of bytes
    (nemesis.clj:513-539)."""

    def __init__(self, path: str, max_bytes: int = 1024):
        self.path = path
        self.max_bytes = max_bytes

    def fs(self):
        return {"truncate-file"}

    def invoke(self, test, op):
        from jepsen_tpu import control
        from jepsen_tpu.utils import real_pmap
        nodes = op.get("value") or list(test.get("nodes") or [])
        n_bytes = random.randrange(1, self.max_bytes)

        def trunc(node):
            control.on(node, test,
                       lambda: control.exec_("truncate", "-c", "-s",
                                             f"-{n_bytes}", self.path))
        real_pmap(trunc, nodes)
        return {**op, "type": "info", "value": ["truncated", self.path, n_bytes, nodes]}
