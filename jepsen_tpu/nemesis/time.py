"""Clock nemesis: skew, bump, and strobe the wall clocks of DB nodes.

Reference: jepsen/src/jepsen/nemesis/time.clj — uploads C sources and
compiles them with gcc ON EACH NODE at setup (:20-39,52-61), then drives
them per op; NTP is stopped so it can't fight back; offsets are measured
and embedded in completion values for the clock-plot checker.

The C sources are ours (jepsen_tpu/resources/bump-time.c, strobe-time.c —
fresh implementations of the same capability).
"""
from __future__ import annotations

import logging
import random
from typing import Iterable

from jepsen_tpu import control
from jepsen_tpu import faketime as faketime_mod
from jepsen_tpu.control import RemoteError
from jepsen_tpu.control.util import file_exists, mkdir
from jepsen_tpu.nemesis import Nemesis
from jepsen_tpu.utils import real_pmap

logger = logging.getLogger("jepsen.nemesis.time")

BIN_DIR = "/opt/jepsen"
SOURCES = ("bump-time", "strobe-time")


def compile_resource(name: str, force: bool = False) -> None:
    """Uploads resources/<name>.c and compiles it with the node's gcc
    (time.clj compile! :20-39)."""
    binpath = f"{BIN_DIR}/{name}"
    if not force and file_exists(binpath):
        return
    mkdir(BIN_DIR)
    control.upload_resource(f"{name}.c", f"{BIN_DIR}/{name}.c")
    control.exec_("gcc", "-O2", "-o", binpath, f"{BIN_DIR}/{name}.c")


def install() -> None:
    """Installs both clock binaries on the current node (time.clj:52-61)."""
    for name in SOURCES:
        compile_resource(name)


def stop_ntp() -> None:
    """Keeps NTP from correcting our skew (time.clj clock-nemesis setup)."""
    for svc in ("ntp", "ntpd", "chrony", "chronyd",
                "systemd-timesyncd"):
        try:
            control.exec_("systemctl", "stop", svc)
        except RemoteError:
            pass


def reset_time() -> None:
    """Resyncs this node's clock (ntpdate or systemd restart,
    time.clj:80-84)."""
    for cmd in (("ntpdate", "-p", "1", "-b", "pool.ntp.org"),
                ("chronyc", "-a", "makestep"),
                ("systemctl", "restart", "systemd-timesyncd")):
        try:
            control.exec_(*cmd)
            return
        except RemoteError:
            continue
    logger.warning("no working clock-resync mechanism on %s",
                   control.current_host())


def bump_time(delta_ms: int) -> None:
    """(time.clj:86-90)"""
    control.exec_(f"{BIN_DIR}/bump-time", str(int(delta_ms)))


def strobe_time(delta_ms: int, period_ms: int, duration_s: int) -> None:
    """(time.clj:92-96)"""
    control.exec_(f"{BIN_DIR}/strobe-time", str(int(delta_ms)),
                  str(int(period_ms)), str(int(duration_s)))


def current_offset_ms(reference_ms: float) -> float:
    """Node wall-clock minus control-node reference, in ms."""
    node_ms = float(control.exec_("date", "+%s%3N").strip())
    return node_ms - reference_ms


def clock_offsets(test: dict, nodes: Iterable[str] | None = None) -> dict:
    """{node: offset-ms} measured against the control node's clock."""
    import time as _time
    nodes = list(nodes or test.get("nodes") or [])

    def one(node):
        ref = _time.time() * 1000.0
        try:
            return node, control.on(node, test, lambda: current_offset_ms(ref))
        except Exception as e:  # noqa: BLE001 — unmeasurable node (e.g. dummy)
            logger.debug("clock offset unavailable on %s: %r", node, e)
            return node, None

    return {n: off for n, off in real_pmap(one, nodes) if off is not None}


class ClockNemesis(Nemesis):
    """Ops (time.clj:98-146):
      {f: "reset",  value: [nodes...]}
      {f: "bump",   value: {node: delta-ms}}
      {f: "strobe", value: {node: {"delta": ms, "period": ms, "duration": s}}}
      {f: "check-offsets"}
    Completions embed {"clock-offsets": {...}} for the clock-plot checker.
    """

    def fs(self):
        return {"reset", "bump", "strobe", "check-offsets"}

    def setup(self, test):
        def prep(node):
            control.on(node, test, lambda: (install(), stop_ntp()))
        real_pmap(prep, list(test.get("nodes") or []))
        return self

    def invoke(self, test, op):
        f, v = op.get("f"), op.get("value")
        if f == "reset":
            nodes = v or list(test.get("nodes") or [])
            real_pmap(lambda n: control.on(n, test, reset_time), nodes)
        elif f == "bump":
            real_pmap(lambda kv: control.on(
                kv[0], test, lambda: bump_time(kv[1])), list((v or {}).items()))
        elif f == "strobe":
            real_pmap(lambda kv: control.on(
                kv[0], test, lambda: strobe_time(
                    kv[1]["delta"], kv[1]["period"], kv[1]["duration"])),
                list((v or {}).items()))
        elif f == "check-offsets":
            pass
        else:
            return {**op, "type": "info", "value": ["unknown-f", f]}
        offsets = clock_offsets(test)
        return {**op, "type": "info",
                "value": {"f": f, "arg": v, "clock-offsets": offsets}}


def clock_nemesis() -> Nemesis:
    return ClockNemesis()


# ---------------------------------------------------------------------------
# generators (time.clj:148-205)
# ---------------------------------------------------------------------------

def reset_gen(test, ctx):
    nodes = list(test.get("nodes") or [])
    return {"f": "reset",
            "value": ctx.rng.sample(nodes, ctx.rng.randint(1, len(nodes)))
            if nodes else []}


def bump_gen(test, ctx):
    """±2^2..2^18 ms exponential deltas on a random node subset
    (time.clj bump-gen)."""
    nodes = list(test.get("nodes") or [])
    subset = ctx.rng.sample(nodes, ctx.rng.randint(1, len(nodes))) if nodes else []
    return {"f": "bump",
            "value": {n: ctx.rng.choice([-1, 1]) * (2 ** ctx.rng.randint(2, 18))
                      for n in subset}}


def strobe_gen(test, ctx):
    """Strobe a node subset: delta up to 2^8 ms, period up to ~1s, a few
    seconds long (time.clj strobe-gen)."""
    nodes = list(test.get("nodes") or [])
    subset = ctx.rng.sample(nodes, ctx.rng.randint(1, len(nodes))) if nodes else []
    return {"f": "strobe",
            "value": {n: {"delta": 2 ** ctx.rng.randint(2, 8),
                          "period": 2 ** ctx.rng.randint(0, 10),
                          "duration": ctx.rng.randint(1, 16)}
                      for n in subset}}


def clock_gen():
    """Mixed reset/bump/strobe stream (time.clj clock-gen)."""
    from jepsen_tpu import generator as gen
    return gen.mix([gen.Fn(reset_gen), gen.Fn(bump_gen), gen.Fn(strobe_gen)])


# ---------------------------------------------------------------------------
# Clock-RATE nemesis: divergent per-node clock rates via libfaketime
# (faketime.py; the faketime.clj capability). Unlike bump/strobe —
# which JUMP clocks — a rate factor makes node clocks drift apart
# continuously for the whole window.
# ---------------------------------------------------------------------------

class ClockRateNemesis(Nemesis):
    """Ops:
      {f: "start-clock-rate", value: {"binary": path, "rates": {node: r}}}
      {f: "stop-clock-rate",  value: {"binary": path}}

    ``start`` wraps the DB binary on each named node with a libfaketime
    rate factor (faketime.wrap) and — when the test's db implements
    Process — restarts the process so the wrapper takes effect; ``stop``
    unwraps and restarts everywhere. The binary path rides in the OP
    VALUE so the durable ``clock-rate`` registry record carries it: an
    offline ``cli heal`` must know which binary to unwrap
    (faults._heal_clock_rate)."""

    def __init__(self, binary: str, lib: str | None = None,
                 restart: bool = True):
        self.binary = binary
        self.lib = lib
        self.restart = restart

    def fs(self):
        return {"start-clock-rate", "stop-clock-rate"}

    def _restart(self, test, node) -> None:
        from jepsen_tpu import db as db_mod
        db = test.get("db")
        if self.restart and isinstance(db, db_mod.Process):
            db.kill(test, node)
            db.start(test, node)

    def invoke(self, test, op):
        f = op.get("f")
        v = op.get("value") if isinstance(op.get("value"), dict) else {}
        binary = v.get("binary") or self.binary
        if f == "start-clock-rate":
            rates = v.get("rates") or {}

            def start(node):
                rate = float(rates.get(node, 1.0))
                control.on(node, test,
                           lambda: faketime_mod.wrap(binary, rate,
                                                     lib=self.lib))
                self._restart(test, node)

            real_pmap(start, sorted(rates))
            return {**op, "type": "info",
                    "value": {"binary": binary, "rates": rates}}
        if f == "stop-clock-rate":
            def stop(node):
                control.on(node, test,
                           lambda: faketime_mod.unwrap(binary))
                self._restart(test, node)

            nodes = sorted(v.get("rates") or ()) \
                or list(test.get("nodes") or [])
            real_pmap(stop, nodes)
            return {**op, "type": "info",
                    "value": {"binary": binary, "rates": {}}}
        return {**op, "type": "info", "value": ["unknown-f", f]}

    def teardown(self, test):
        def stop(node):
            control.on(node, test, lambda: faketime_mod.unwrap(self.binary))
            self._restart(test, node)
        real_pmap(stop, list(test.get("nodes") or []))

    def preflight_diags(self, test) -> list:
        """Missing-lib check (doc/static-analysis.md NEM006): with the
        dummy/local transport the control host IS every node, so a
        local LIB_PATHS probe is authoritative — a run that would die
        in ``faketime.install`` mid-nemesis dies here instead, as a
        structured (``preflight_allow``-downgradeable) diagnostic. Over
        real SSH the library is per-node and install() is probed at
        fault time; preflight stays silent rather than guessing."""
        from jepsen_tpu.analysis.diagnostics import ERROR, Diagnostic
        out: list = []
        if not self.binary or not isinstance(self.binary, str):
            out.append(Diagnostic(
                "NEM004", ERROR, "nemesis",
                f"clock-rate nemesis has no binary path ({self.binary!r})"
                " to wrap"))
        if self.lib:
            return out
        if (test.get("ssh") or {}).get("dummy") \
                and faketime_mod.local_lib() is None:
            out.append(Diagnostic(
                "NEM006", ERROR, "nemesis",
                "clock-rate faults need libfaketime, and no distro "
                "library exists at any known path "
                "(jepsen_tpu.faketime.LIB_PATHS)",
                hint="install the faketime package, pass an explicit "
                     "lib= path, or add 'NEM006' to preflight_allow to "
                     "let the run try an on-node install"))
        return out


def clock_rate_nemesis(binary: str, lib: str | None = None,
                       restart: bool = True) -> Nemesis:
    return ClockRateNemesis(binary, lib=lib, restart=restart)


def clock_rate_gen(binary: str, spread: float = 0.02):
    """Start-op generator: a random node subset gets random rate factors
    near 1 (faketime.clj:57-65 rand-factor). Pure over ctx.rng, so
    preflight can enumerate it."""

    def gen_fn(test, ctx):
        nodes = list(test.get("nodes") or [])
        subset = ctx.rng.sample(nodes, ctx.rng.randint(1, len(nodes))) \
            if nodes else []
        rates = {n: round(1.0 + ctx.rng.uniform(-spread, spread), 4)
                 for n in subset}
        return {"type": "info", "f": "start-clock-rate",
                "value": {"binary": binary, "rates": rates}}

    return gen_fn
