"""Composable nemesis packages (reference:
jepsen/src/jepsen/nemesis/combined.clj).

A *package* bundles a nemesis with the generators that drive it:

    {"nemesis": Nemesis, "generator": gen, "final_generator": gen | None,
     "perf": {"name", "start", "stop", "fs"}}

``nemesis_package(opts)`` assembles kill/pause/partition/clock packages
from ``opts["faults"]`` and composes them into one (combined.clj:328-374).
Node targeting uses the db-nodes spec DSL (combined.clj:38-61): None/
"one"/"minority"/"majority"/"minority-third"/"primaries"/"all".
"""
from __future__ import annotations

import random
from typing import Any, Callable

from jepsen_tpu import generator as gen
from jepsen_tpu import nemesis as nem
from jepsen_tpu.db import Pause, Primary, Process
from jepsen_tpu.utils import majority, minority_third, real_pmap

DEFAULT_INTERVAL = 10.0  # seconds between faults (combined.clj:27-29)


# ---------------------------------------------------------------------------
# node specs (combined.clj:38-61)
# ---------------------------------------------------------------------------

def db_nodes(test: dict, db, node_spec, rng: random.Random | None = None) -> list:
    """Nodes targeted by a spec: None (random choice among specs), "one",
    "minority", "majority", "minority-third", "primaries", "all"."""
    rng = rng or random
    nodes = list(test.get("nodes") or [])
    if node_spec is None:
        specs = ["one", "minority-third", "majority", "all"]
        if isinstance(db, Primary):
            specs.append("primaries")
        node_spec = rng.choice(specs)
    if node_spec == "one":
        return [rng.choice(nodes)]
    if node_spec == "minority":
        n = max(1, (len(nodes) - 1) // 2)
        return rng.sample(nodes, n)
    if node_spec == "majority":
        return rng.sample(nodes, majority(len(nodes)))
    if node_spec == "minority-third":
        return rng.sample(nodes, max(1, minority_third(len(nodes))))
    if node_spec == "primaries":
        return list(db.primaries(test)) if isinstance(db, Primary) else []
    if node_spec == "all":
        return nodes
    if isinstance(node_spec, (list, tuple)):
        return list(node_spec)
    raise ValueError(f"unknown node spec {node_spec!r}")


# ---------------------------------------------------------------------------
# db package: kill / pause via the DB's Process/Pause protocols
# (combined.clj:70-160)
# ---------------------------------------------------------------------------

class DBNemesis(nem.Nemesis):
    """start/kill and pause/resume DB processes on targeted nodes."""

    def __init__(self, db):
        self.db = db

    def fs(self):
        out = set()
        if isinstance(self.db, Process):
            out |= {"start", "kill"}
        if isinstance(self.db, Pause):
            out |= {"pause", "resume"}
        return out

    def invoke(self, test, op):
        from jepsen_tpu import control
        f = op.get("f")
        spec = op.get("value")
        if f in ("start", "resume"):
            targets = list(test.get("nodes") or [])
        else:
            targets = db_nodes(test, self.db, spec)
        method = {"start": "start", "kill": "kill",
                  "pause": "pause", "resume": "resume"}[f]

        def one(node):
            return node, control.on(
                node, test, lambda: getattr(self.db, method)(test, node))

        res = dict(real_pmap(one, targets))
        return {**op, "type": "info", "value": {f: res}}


def db_package(opts: dict) -> dict | None:
    """Kill/pause package when those faults are requested
    (combined.clj:141-160)."""
    faults = set(opts.get("faults") or [])
    db = opts.get("db")
    if db is None:
        return None
    interval = opts.get("interval", DEFAULT_INTERVAL)
    wants_kill = "kill" in faults and isinstance(db, Process)
    wants_pause = "pause" in faults and isinstance(db, Pause)
    if not (wants_kill or wants_pause):
        return None

    streams = []
    fs = set()
    if wants_kill:
        fs |= {"start", "kill"}
        streams.append(gen.Seq([{"type": "info", "f": "kill", "value": None},
                                {"type": "info", "f": "start", "value": None}]))
    if wants_pause:
        fs |= {"pause", "resume"}
        streams.append(gen.Seq([{"type": "info", "f": "pause", "value": None},
                                {"type": "info", "f": "resume", "value": None}]))
    g = gen.stagger(interval, gen.mix([gen.cycle(s) for s in streams]))
    final = gen.Seq([{"type": "info", "f": "start", "value": None}]
                    if wants_kill else []) if wants_kill else None
    return {
        "nemesis": DBNemesis(db),
        "generator": g,
        "final_generator": final,
        "perf": {"name": "kill/pause", "fs": fs,
                 "start": {"kill", "pause"}, "stop": {"start", "resume"}},
    }


# ---------------------------------------------------------------------------
# partition package (combined.clj:162-246)
# ---------------------------------------------------------------------------

def grudge_for(test: dict, db, part_spec, rng: random.Random | None = None) -> dict:
    """A grudge map for a partition spec (combined.clj:162-188): None,
    "one", "majority", "majorities-ring", "primaries", "minority-third"."""
    rng = rng or random
    nodes = list(test.get("nodes") or [])
    if part_spec is None:
        specs = ["one", "majority", "majorities-ring", "minority-third"]
        if isinstance(db, Primary):
            specs.append("primaries")
        part_spec = rng.choice(specs)
    if part_spec == "one":
        iso = [rng.choice(nodes)]
        rest = [n for n in nodes if n not in iso]
        return nem.complete_grudge([iso, rest])
    if part_spec == "majority":
        shuffled = rng.sample(nodes, len(nodes))
        m = majority(len(nodes))
        return nem.complete_grudge([shuffled[:m], shuffled[m:]])
    if part_spec == "minority-third":
        shuffled = rng.sample(nodes, len(nodes))
        m = max(1, minority_third(len(nodes)))
        return nem.complete_grudge([shuffled[:m], shuffled[m:]])
    if part_spec == "majorities-ring":
        return nem.majorities_ring_stochastic(nodes, rng=random.Random(rng.random()))
    if part_spec == "primaries":
        prim = list(db.primaries(test)) if isinstance(db, Primary) else []
        if not prim:
            return {}
        iso = [rng.choice(prim)]
        rest = [n for n in nodes if n not in iso]
        return nem.complete_grudge([iso, rest])
    raise ValueError(f"unknown partition spec {part_spec!r}")


class PartitionNemesis(nem.Nemesis):
    """start-partition/stop-partition over the test's Net
    (combined.clj:196-224)."""

    def __init__(self, db):
        self.db = db

    def fs(self):
        return {"start-partition", "stop-partition"}

    def setup(self, test):
        net = test.get("net")
        if net is not None:
            net.heal(test)
        return self

    def invoke(self, test, op):
        f = op.get("f")
        net = test.get("net")
        if f == "start-partition":
            grudge = grudge_for(test, self.db, op.get("value"))
            if net is not None:
                net.drop_all(test, grudge)
            return {**op, "type": "info", "value": ["isolated", grudge]}
        if f == "stop-partition":
            if net is not None:
                net.heal(test)
            return {**op, "type": "info", "value": ["network-healed"]}
        return {**op, "type": "info", "value": ["unknown-f", f]}

    def teardown(self, test):
        net = test.get("net")
        if net is not None:
            net.heal(test)


def partition_package(opts: dict) -> dict | None:
    """(combined.clj:226-246)"""
    if "partition" not in set(opts.get("faults") or []):
        return None
    interval = opts.get("interval", DEFAULT_INTERVAL)
    g = gen.stagger(interval, gen.cycle(gen.Seq([
        {"type": "info", "f": "start-partition", "value": None},
        {"type": "info", "f": "stop-partition", "value": None},
    ])))
    return {
        "nemesis": PartitionNemesis(opts.get("db")),
        "generator": g,
        "final_generator": gen.Seq([
            {"type": "info", "f": "stop-partition", "value": None}]),
        "perf": {"name": "partition", "fs": {"start-partition", "stop-partition"},
                 "start": {"start-partition"}, "stop": {"stop-partition"}},
    }


# ---------------------------------------------------------------------------
# clock package (combined.clj:248-280)
# ---------------------------------------------------------------------------

def clock_package(opts: dict) -> dict | None:
    if "clock" not in set(opts.get("faults") or []):
        return None
    from jepsen_tpu.nemesis.time import clock_gen, clock_nemesis
    interval = opts.get("interval", DEFAULT_INTERVAL)
    return {
        "nemesis": clock_nemesis(),
        "generator": gen.stagger(interval, clock_gen()),
        "final_generator": gen.Seq([{"type": "info", "f": "reset",
                                     "value": None}]),
        "perf": {"name": "clock", "fs": {"reset", "bump", "strobe"},
                 "start": {"bump", "strobe"}, "stop": {"reset"}},
    }


# ---------------------------------------------------------------------------
# membership + clock-rate packages (membership.clj:224-250, faketime.clj)
# ---------------------------------------------------------------------------

def membership_package(opts: dict) -> dict | None:
    """A membership-reconfiguration package when the test supplies a
    State model: ``opts["membership_state"]`` (a built State) or
    ``opts["membership_state_fn"]`` (a ``fn(opts) -> State`` factory —
    suites use this so fake and real modes build different models).
    Wired through :func:`jepsen_tpu.nemesis.membership.package`, so ops
    land in the durable fault registry with their pre-op member sets."""
    if "membership" not in set(opts.get("faults") or []):
        return None
    state = opts.get("membership_state")
    if state is None and callable(opts.get("membership_state_fn")):
        state = opts["membership_state_fn"](opts)
    if state is None:
        return None
    from jepsen_tpu.nemesis import membership
    return membership.package(
        state, interval=opts.get("interval", DEFAULT_INTERVAL),
        poll_interval=opts.get("membership_poll_interval",
                               membership.NODE_VIEW_INTERVAL))


def clock_rate_package(opts: dict) -> dict | None:
    """Begin/end ``clock-rate`` windows: libfaketime rate factors on a
    random node subset (nemesis/time.ClockRateNemesis). Needs
    ``opts["clock_rate_binary"]`` — the DB binary to wrap."""
    if "clock-rate" not in set(opts.get("faults") or []):
        return None
    binary = opts.get("clock_rate_binary")
    if not binary:
        return None
    from jepsen_tpu.nemesis.time import ClockRateNemesis, clock_rate_gen
    interval = opts.get("interval", DEFAULT_INTERVAL)
    stop_op = {"type": "info", "f": "stop-clock-rate",
               "value": {"binary": binary}}
    # limit(1, Fn): a bare Fn never exhausts (it is its own
    # continuation), which would pin the Seq on start ops forever
    g = gen.stagger(interval, gen.cycle(gen.Seq([
        gen.limit(1, gen.Fn(clock_rate_gen(binary))), dict(stop_op)])))
    return {
        "nemesis": ClockRateNemesis(binary,
                                    lib=opts.get("clock_rate_lib")),
        "generator": g,
        "final_generator": gen.Seq([dict(stop_op)]),
        "perf": {"name": "clock-rate",
                 "fs": {"start-clock-rate", "stop-clock-rate"},
                 "start": {"start-clock-rate"},
                 "stop": {"stop-clock-rate"}},
    }


def _during_reconfig_package(opts: dict, open_fn: Callable,
                             close_fn: Callable, inner_pkg: dict | None,
                             name: str) -> dict | None:
    """Model-aware combo scaffolding: compose a membership package with
    a second fault whose window OPENS while a reconfiguration is in
    flight and CLOSES once it resolves — the schedule jepsen uses to
    catch consensus bugs that only bite mid-reconfig. The window
    generator consults the live MembershipNemesis (``pending_count``),
    so preflight skips it as stateful (GEN005) rather than enumerating
    through run state. ``open_fn``/``close_fn`` are
    ``(test, ctx) -> op`` edge builders."""
    mpkg = membership_package(
        {**opts, "faults": set(opts.get("faults") or ()) | {"membership"}})
    if mpkg is None or inner_pkg is None:
        return None
    from jepsen_tpu.nemesis.membership import PollingGen
    mn = mpkg["nemesis"]
    perf = inner_pkg.get("perf") or {}
    open_fs = set(perf.get("start") or ())
    close_fs = set(perf.get("stop") or ())
    window = {"open": False}

    def window_gen(test, ctx):
        # PURE over observable state: the window flag flips only in
        # on_update, when an edge actually DISPATCHED — an offered edge
        # can sit through many re-polls (busy nemesis thread, lost
        # scheduling tie) or never dispatch at all, and must keep being
        # offered rather than silently dropped
        pending = mn.pending_count()
        if pending and not window["open"]:
            return open_fn(test, ctx)
        if window["open"] and not pending:
            return close_fn(test, ctx)
        return None

    def on_update(event):
        f = event.get("f")
        if f in open_fs:
            window["open"] = True
        elif f in close_fs:
            window["open"] = False

    pkg = compose_packages([mpkg, {
        **inner_pkg,
        "generator": PollingGen(window_gen, on_update=on_update),
    }])
    pkg["perf"] = [mpkg.get("perf"), {**perf, "name": name}]
    return pkg


def partition_during_reconfig_package(opts: dict) -> dict | None:
    """Partition windows synchronized to reconfigurations: the network
    splits while a membership op is unresolved and heals when the
    cluster converges."""
    return _during_reconfig_package(
        opts,
        lambda test, ctx: {"type": "info", "f": "start-partition",
                           "value": None},
        lambda test, ctx: {"type": "info", "f": "stop-partition",
                           "value": None},
        partition_package({**opts, "faults": {"partition"}}),
        "partition-during-reconfig")


def clock_rate_during_reconfig_package(opts: dict) -> dict | None:
    """Clock-rate skew synchronized to reconfigurations: node clocks
    drift apart exactly while membership is in flux."""
    binary = opts.get("clock_rate_binary")
    if not binary:
        return None
    from jepsen_tpu.nemesis.time import clock_rate_gen
    rate_fn = clock_rate_gen(binary)
    return _during_reconfig_package(
        opts, rate_fn,
        lambda test, ctx: {"type": "info", "f": "stop-clock-rate",
                           "value": {"binary": binary}},
        clock_rate_package({**opts, "faults": {"clock-rate"}}),
        "clock-rate-during-reconfig")


# ---------------------------------------------------------------------------
# composition (combined.clj:283-374)
# ---------------------------------------------------------------------------

def f_map_package(f_mapping: dict, pkg: dict) -> dict:
    """Lifts a package's fs through a renaming map (combined.clj:283-303)."""
    inv = {v: k for k, v in f_mapping.items()}
    return {
        **pkg,
        "nemesis": nem.f_map(f_mapping, pkg["nemesis"]),
        "generator": gen.f_map(f_mapping, pkg["generator"]),
        "final_generator": (gen.f_map(f_mapping, pkg["final_generator"])
                            if pkg.get("final_generator") is not None else None),
        "perf": {**pkg.get("perf", {}),
                 "fs": {f_mapping.get(f, f)
                        for f in pkg.get("perf", {}).get("fs", set())},
                 "start": {f_mapping.get(f, f)
                           for f in pkg.get("perf", {}).get("start", set())},
                 "stop": {f_mapping.get(f, f)
                          for f in pkg.get("perf", {}).get("stop", set())}},
    }


def compose_packages(packages: list[dict]) -> dict:
    """(combined.clj:305-316)"""
    packages = [p for p in packages if p]
    finals = [p["final_generator"] for p in packages
              if p.get("final_generator") is not None]
    return {
        "nemesis": nem.compose([p["nemesis"] for p in packages]),
        "generator": gen.any_gen(*[p["generator"] for p in packages])
        if len(packages) > 1 else (packages[0]["generator"] if packages else None),
        "final_generator": (gen.Seq(finals) if finals else None),
        "perf": [p.get("perf") for p in packages],
    }


def nemesis_package(opts: dict) -> dict:
    """The top-level entry (combined.clj:328-374). opts keys: db, faults
    (set of "kill"/"pause"/"partition"/"clock"/"membership"/"clock-rate"
    plus any name registered in ``fault_packages``), interval,
    extra_packages, fault_packages (name → builder(opts), the
    DB-specific vocabularies — see jepsen_tpu.nemesis.db_specific),
    membership_state / membership_state_fn (the reconfiguration model),
    clock_rate_binary / clock_rate_lib (the libfaketime wrap target).
    The combo faults "partition-during-reconfig" and
    "clock-rate-during-reconfig" subsume their component packages.
    """
    faults = set(opts.get("faults") or [])
    pkgs = [db_package(opts), clock_package(opts)]
    combos_wanted = faults & {"partition-during-reconfig",
                              "clock-rate-during-reconfig"}
    if len(combos_wanted) > 1:
        # each combo owns the (single) membership State; two combos
        # would double-drive it — and silently building only one would
        # drop a fault the user named
        raise ValueError(
            "partition-during-reconfig and clock-rate-during-reconfig "
            "cannot be combined in one run: both own the membership "
            "State; pick one (the other fault class can ride along "
            "standalone)")
    combo = combo_name = None
    if "partition-during-reconfig" in faults:
        combo_name = "partition-during-reconfig"
        combo = partition_during_reconfig_package(
            {**opts, "faults": faults | {"membership"}})
    elif "clock-rate-during-reconfig" in faults:
        combo_name = "clock-rate-during-reconfig"
        combo = clock_rate_during_reconfig_package(
            {**opts, "faults": faults | {"membership"}})
    if combo_name and combo is None:
        # a fault the user NAMED must never silently no-op (the same
        # contract NEM005/NEM006 enforce for misconfigured packages)
        raise ValueError(
            f"fault {combo_name!r} requested but its wiring is missing: "
            "it needs membership_state/membership_state_fn"
            + ("" if combo_name.startswith("partition")
               else " and clock_rate_binary"))
    if combo is not None:
        # the combo already owns the membership nemesis (and its inner
        # fault); a standalone membership package would double-drive
        # the same State
        pkgs.append(combo)
    else:
        mpkg = membership_package(opts)
        if "membership" in faults and mpkg is None:
            raise ValueError(
                "fault 'membership' requested but no membership_state/"
                "membership_state_fn is wired (this suite may not "
                "support the membership fault class)")
        pkgs.append(mpkg)
    if combo_name != "partition-during-reconfig":
        # the partition combo subsumes the standalone partition
        # package: a second PartitionNemesis' staggered stop-partition
        # would heal mid-reconfig, and its start events would flip the
        # combo's on_update window state
        pkgs.append(partition_package(opts))
    if combo_name != "clock-rate-during-reconfig":
        crpkg = clock_rate_package(opts)
        if "clock-rate" in faults and crpkg is None:
            raise ValueError(
                "fault 'clock-rate' requested but no clock_rate_binary "
                "is wired (this suite may not support the clock-rate "
                "fault class)")
        pkgs.append(crpkg)
    registry = opts.get("fault_packages") or {}
    for name in sorted(set(opts.get("faults") or []) & set(registry)):
        pkgs.append(registry[name](opts))
    pkgs += list(opts.get("extra_packages") or [])
    pkgs = [p for p in pkgs if p]
    if not pkgs:
        return {"nemesis": nem.Noop(), "generator": None,
                "final_generator": None, "perf": []}
    return compose_packages(pkgs)
