"""Cluster-membership nemesis: grow/shrink the SUT's member set mid-test.

Reference: jepsen/src/jepsen/nemesis/membership.clj + membership/state.clj.
A user-supplied State object models the cluster's membership view; per-node
view threads poll every ``NODE_VIEW_INTERVAL`` seconds and merge into a
resolved view; ops are generated from the current view, applied via the
State, and completed once the State considers them resolved (fixed-point
resolve loop, membership.clj:95-107,159-210).
"""
from __future__ import annotations

import logging
import threading
import time as _time
from typing import Any

from jepsen_tpu.nemesis import Nemesis

logger = logging.getLogger("jepsen.nemesis.membership")

NODE_VIEW_INTERVAL = 5.0  # seconds (membership.clj:59-61)


class State:
    """Membership model protocol (membership/state.clj). Implementations
    are free-form records over {"view": ..., "pending": [...]}-style
    state; all methods return a new State (pure) except invoke/teardown.
    """

    def node_view(self, test: dict, node: str):
        """This node's current view of the cluster (polled, may raise)."""
        raise NotImplementedError

    def merge_views(self, test: dict, views: dict):
        """Collapses {node: view} into one authoritative view; returns
        new State."""
        raise NotImplementedError

    def fs(self) -> set:
        """Op :f values this membership State can perform."""
        return set()

    def op(self, test: dict):
        """Next membership op to try: an op dict or "pending"."""
        return "pending"

    def invoke(self, test: dict, op: dict):
        """Actually performs the op against the cluster. Returns the
        completion value."""
        raise NotImplementedError

    def resolve(self, test: dict):
        """A chance to update internal state; returns new State."""
        return self

    def resolve_op(self, test: dict, pending_pair):
        """(op, completion-value) -> None if still pending, else new
        State with the op resolved."""
        return None

    def teardown(self, test: dict) -> None:
        pass


class MembershipNemesis(Nemesis):
    """(membership.clj:159-210)"""

    def __init__(self, state: State, poll_interval: float = NODE_VIEW_INTERVAL):
        self.state = state
        self.poll_interval = poll_interval
        self._lock = threading.Lock()
        self._views: dict = {}
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._pending: list = []

    def fs(self):
        return self.state.fs()

    # -- node view polling (membership.clj:143-157) ---------------------
    def _poll_node(self, test, node):
        while not self._stop.is_set():
            try:
                view = self.state.node_view(test, node)
                with self._lock:
                    self._views[node] = view
            except Exception as e:  # noqa: BLE001
                logger.debug("node view %s failed: %r", node, e)
            self._stop.wait(self.poll_interval)

    def setup(self, test):
        for node in test.get("nodes") or []:
            t = threading.Thread(target=self._poll_node, args=(test, node),
                                 daemon=True,
                                 name=f"membership-view-{node}")
            t.start()
            self._threads.append(t)
        return self

    # -- resolution fixed point (membership.clj:95-107) ------------------
    def _resolve(self, test):
        with self._lock:
            views = dict(self._views)
        state = self.state
        try:
            state = state.merge_views(test, views) or state
        except Exception as e:  # noqa: BLE001
            logger.debug("merge_views failed: %r", e)
        changed = True
        while changed:
            changed = False
            state = state.resolve(test) or state
            still = []
            for pair in self._pending:
                nxt = state.resolve_op(test, pair)
                if nxt is None:
                    still.append(pair)
                else:
                    state = nxt
                    changed = True
            self._pending = still
        self.state = state

    def invoke(self, test, op):
        self._resolve(test)
        try:
            value = self.state.invoke(test, op)
        except Exception as e:  # noqa: BLE001
            return {**op, "type": "info", "value": ["error", repr(e)]}
        self._pending.append((op, value))
        self._resolve(test)
        return {**op, "type": "info", "value": value}

    def teardown(self, test):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=1.0)
        self.state.teardown(test)


def membership_gen(nemesis: MembershipNemesis):
    """Generator polling the State for its next op (membership.clj:212-222)."""

    def next_op(test, ctx):
        nemesis._resolve(test)
        op = nemesis.state.op(test)
        if op == "pending" or op is None:
            return None
        return op

    return next_op


def package(state: State, interval: float = 10.0,
            poll_interval: float = NODE_VIEW_INTERVAL) -> dict:
    """A combined-style package (membership.clj:224-250)."""
    from jepsen_tpu import generator as gen
    n = MembershipNemesis(state, poll_interval=poll_interval)
    return {
        "nemesis": n,
        "generator": gen.stagger(interval, gen.Fn(membership_gen(n))),
        "final_generator": None,
        "perf": {"name": "membership", "fs": state.fs(),
                 "start": set(), "stop": set()},
    }
